"""MCMC strategy search: simulated annealing over per-op sharding
assignments.

Analog of the reference's legacy search (``FFModel::mcmc_optimize``,
``src/runtime/model.cc:3286-3357``): start from the canonical data-parallel
assignment, randomly rewrite one op's parallel config, score with the
simulator, accept with probability exp(-alpha * delta). The Unity
substitution-DP search (search/unity.py) supersedes this but the MCMC
remains the cheap robust fallback, exactly as in the reference.
"""
from __future__ import annotations

import dataclasses
import math
import random
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core.layer import Layer
from ..dtypes import itemsize
from ..ffconst import OperatorType
from ..obs import events as obs_events
from ..parallel.machine import DeviceMesh
from ..parallel.strategy import ShardingStrategy
from .costmodel import CostMetrics, OpCostModel
from .opshard import ShardOption, assignment_to_sharding, options_for


@dataclasses.dataclass
class GraphCost:
    total: float
    compute: float
    xfer: float
    sync: float
    peak_memory: int
    # overlap-aware scoring only (OpCostModel.overlap_mode): gradient-
    # sync seconds predicted HIDDEN behind backward compute; `sync`
    # then carries the exposed remainder and `total` counts exposed
    # only. 0.0 under the serial model (bit-identical legacy scores).
    sync_hidden: float = 0.0


class StrategySimulator:
    """Scores a full per-op assignment (reference ``simulate_runtime`` in
    its additive DP-search approximation)."""

    def __init__(self, layers: Sequence[Layer], dmesh: DeviceMesh,
                 cost_model: OpCostModel):
        self.layers = list(layers)
        self.dmesh = dmesh
        self.cost = cost_model
        self.options: Dict[str, List[ShardOption]] = {
            l.name: options_for(l) for l in self.layers}

    def _degrees_of(self, layer: Layer,
                    assign: Dict[str, Tuple[int, ...]]) -> Dict[int, int]:
        degs: Dict[int, int] = {}
        for opt, d in zip(self.options[layer.name],
                          assign.get(layer.name, ())):
            if d > 1 and opt.out_dim >= 0:
                degs[opt.out_dim] = d
        return degs

    def evaluate(self, assign: Dict[str, Tuple[int, ...]]) -> GraphCost:
        gc, _ = self._evaluate(assign, breakdown=False)
        return gc

    def evaluate_breakdown(self, assign: Dict[str, Tuple[int, ...]]
                           ) -> Tuple[GraphCost, List[Dict]]:
        """(GraphCost, per-op entries) — the strategy-audit breakdown;
        entry component sums equal the GraphCost components (before the
        infeasibility penalty, flagged per entry set by the caller)."""
        try:
            return self._evaluate(assign, breakdown=True)
        finally:
            # the provenance tap (installed below for breakdowns only)
            # must not survive onto the MCMC walk's hot loop
            self.cost.provenance = None

    def _evaluate(self, assign: Dict[str, Tuple[int, ...]],
                  breakdown: bool) -> Tuple[GraphCost, List[Dict]]:
        compute = xfer = sync = 0.0
        mem = 0
        entries: List[Dict] = []
        # overlap-aware sync pricing — same contract as unity's
        # GraphCostEvaluator: sites collected in program order, the
        # hidden/exposed split resolved by the shared _overlap_split
        # queue model after the walk. Serial mode is bit-identical.
        overlap_on = bool(getattr(self.cost, "overlap_mode", False))
        sync_sites: List[Dict] = []
        if breakdown:
            # calibration-row provenance for obs/drift.py — same
            # contract as GraphCostEvaluator.graph_cost_breakdown: each
            # entry records which table rows priced it, so drift on an
            # mcmc-searched plan marks the right rows stale instead of
            # reporting calibrated predictions as "analytic"
            self.cost.provenance = []
        out_degrees: Dict[int, Dict[int, int]] = {}  # tensor guid -> degrees
        for layer in self.layers:
            opts = self.options[layer.name]
            degs = self._degrees_of(layer, assign)
            wdeg = 1
            for opt, d in zip(opts, assign.get(layer.name, ())):
                if d > 1 and opt.weight_dims:
                    wdeg *= d
            # kernel tier attached: attention prices at its cheapest
            # available implementation (the impl is a search dimension)
            cm = self.cost.op_cost_with_impl(layer, degs, wdeg)
            compute += cm.forward_time + cm.backward_time
            l_mem = cm.weights_memory + cm.outputs_memory
            mem += l_mem
            # input resharding: producer layout vs this op's batch layout
            l_xfer = 0.0
            for t in layer.inputs:
                src = out_degrees.get(t.guid, {})
                dst = {d: v for d, v in degs.items()
                       if d < len(t.shape) and t.shape[d] % v == 0} \
                    if t.shape else {}
                tb = int(np.prod(t.shape)) * itemsize(t.dtype) \
                    if t.shape else 0
                l_xfer += self.cost.resharding_cost(tb, src, dst)
                # backward: cotangent moves the other way
                l_xfer += self.cost.resharding_cost(tb, dst, src)
            xfer += l_xfer
            for o in layer.outputs:
                out_degrees[o.guid] = degs
            # gradient sync: weights replicated across the dp degree
            dp_deg = self.dmesh.num_devices
            for opt, d in zip(opts, assign.get(layer.name, ())):
                if opt.weight_dims and d > 1:
                    dp_deg //= d
            l_sync = 0.0
            if layer.weights:
                wbytes = sum(int(np.prod(w.shape)) * itemsize(w.dtype)
                             for w in layer.weights) // max(wdeg, 1)
                l_sync = self.cost.weight_sync_cost(wbytes, dp_deg)
            sync += l_sync
            if breakdown:
                e = {
                    "name": layer.name,
                    "op_type": getattr(layer.op_type, "name",
                                       str(layer.op_type)),
                    "fwd_s": cm.forward_time, "bwd_s": cm.backward_time,
                    "xfer_s": l_xfer, "sync_s": l_sync,
                    "mem_bytes": l_mem,
                    "total_s": cm.forward_time + cm.backward_time
                    + l_xfer + l_sync}
                if l_sync > 0:
                    # wire dtype the sync was priced at (same contract
                    # as unity's entries — "float32" unless quantized)
                    e["sync_wire"] = getattr(self.cost,
                                             "last_sync_wire",
                                             "float32")
                if getattr(self.cost, "last_kernel_impl", None):
                    # kernel implementation this op was priced at
                    # (searchable kernel tier; same contract as unity)
                    e["kernel_impl"] = self.cost.last_kernel_impl
                prov = self.cost.provenance
                if prov:
                    e["calib"] = list(prov)
                if prov is not None:
                    del prov[:]
                entries.append(e)
            if overlap_on:
                sync_sites.append({
                    "bwd": cm.backward_time, "sync": l_sync,
                    "entry": entries[-1] if breakdown else None})
        sync_hidden = 0.0
        if overlap_on and sync > 0:
            from .unity import _overlap_split
            sync, sync_hidden = _overlap_split(sync_sites)
        total = compute + xfer + sync
        # memory feasibility: ~4x weights (param + grad + 2 Adam moments)
        if mem * 4 > self.cost.spec.hbm_bytes:
            total *= 100.0  # infeasible penalty (memory-aware search refines)
        return GraphCost(total, compute, xfer, sync, mem,
                         sync_hidden=sync_hidden), entries


def data_parallel_assignment(layers: Sequence[Layer], dmesh: DeviceMesh,
                             options: Dict[str, List[ShardOption]]
                             ) -> Dict[str, Tuple[int, ...]]:
    n = dmesh.num_devices
    assign = {}
    for l in layers:
        degs = []
        for opt in options[l.name]:
            if opt.kind == "sample" and l.outputs and l.outputs[0].shape \
                    and l.outputs[0].shape[opt.out_dim] % n == 0:
                degs.append(n)
            else:
                degs.append(1)
        assign[l.name] = tuple(degs)
    return assign


def _option_signature(opts: Sequence[ShardOption]) -> Tuple:
    return tuple((o.kind, o.out_dim) for o in opts)


def _propagate_neighbors(layer: Layer, cand: Tuple[int, ...],
                         sim: StrategySimulator,
                         consumers: Dict[int, List[Layer]],
                         dmesh: DeviceMesh, rng,
                         p_cont: float = 0.7) -> Dict[str, Tuple[int, ...]]:
    """Flood the mutated config to same-shape neighbors.

    Reference ``FFModel::propagate`` (``model.cc:3181-3261``,
    ``FF_USE_PROPAGATE``): after rewriting one op's parallel config, the
    proposal copies it to graph neighbors with matching output shape and
    option structure, continuing each hop with probability ``p_cont`` —
    so chain-structured graphs (transformer blocks) change whole
    segments per step instead of one op, removing the resharding seams
    single-op moves leave behind."""
    sig = _option_signature(sim.options[layer.name])
    oshape = tuple(layer.outputs[0].shape) if layer.outputs else None
    changed: Dict[str, Tuple[int, ...]] = {layer.name: cand}
    frontier = [layer]
    while frontier:
        cur = frontier.pop()
        nbrs: List[Layer] = []
        for t in cur.inputs:
            if t.owner_layer is not None:
                nbrs.append(t.owner_layer)
        for t in cur.outputs:
            nbrs.extend(consumers.get(t.guid, ()))
        for nb in nbrs:
            if nb.name in changed or nb.name not in sim.options:
                continue
            if not nb.outputs \
                    or tuple(nb.outputs[0].shape) != oshape:
                continue
            if _option_signature(sim.options[nb.name]) != sig:
                continue
            if rng.random() > p_cont:
                continue
            if assignment_to_sharding(nb, sim.options[nb.name], cand,
                                      dmesh) is None:
                continue
            changed[nb.name] = cand
            frontier.append(nb)
    return changed


def mcmc_search(layers: Sequence[Layer], dmesh: DeviceMesh,
                cost_model: OpCostModel, budget: int = 1000,
                alpha: float = 0.05, seed: int = 0,
                verbose: bool = False, propagate: bool = True):
    """Returns (best_assignment, best_cost, simulator).

    ``propagate`` enables the reference's ``FF_USE_PROPAGATE`` proposal
    (``model.cc:3181-3261``): each accepted rewrite may carry its config
    to same-shape neighbors, accepted/rejected atomically."""
    rng = random.Random(seed)
    sim = StrategySimulator(layers, dmesh, cost_model)
    valid_degrees = dmesh.valid_degrees()
    current = data_parallel_assignment(layers, dmesh, sim.options)
    cur_cost = sim.evaluate(current).total
    best, best_cost = dict(current), cur_cost
    shardable = [l for l in layers if sim.options[l.name]]
    if not shardable or budget <= 0:
        return best, best_cost, sim
    consumers: Dict[int, List[Layer]] = {}
    for l in layers:
        for t in l.inputs:
            consumers.setdefault(t.guid, []).append(l)
    with obs_events.span("mcmc.search", budget=budget):
        for it in range(budget):
            layer = rng.choice(shardable)
            opts = sim.options[layer.name]
            oi = rng.randrange(len(opts))
            old = current[layer.name]
            # propose a new degree for this option; keep product ≤
            # num devices
            choices = [d for d in valid_degrees
                       if d * math.prod(old[:oi] + old[oi + 1:])
                       <= dmesh.num_devices]
            if not choices:
                continue
            new_deg = rng.choice(choices)
            cand = old[:oi] + (new_deg,) + old[oi + 1:]
            # realizability check (divisibility + axis allocation)
            if assignment_to_sharding(layer, opts, cand, dmesh) is None:
                continue
            if propagate:
                moves = _propagate_neighbors(layer, cand, sim, consumers,
                                             dmesh, rng)
            else:
                moves = {layer.name: cand}
            olds = {n: current[n] for n in moves}
            current.update(moves)
            obs_events.counter("mcmc.proposals")
            new_cost = sim.evaluate(current).total
            delta = new_cost - cur_cost
            if delta < 0 or rng.random() < math.exp(-delta / max(
                    alpha * cur_cost, 1e-12)):
                obs_events.counter("mcmc.accepts")
                cur_cost = new_cost
                if new_cost < best_cost:
                    best, best_cost = dict(current), new_cost
                    if verbose:
                        print(f"  mcmc iter {it}: best "
                              f"{best_cost * 1e3:.3f} ms")
            else:
                current.update(olds)
    return best, best_cost, sim


def assignment_to_strategy(layers: Sequence[Layer], input_tensors,
                           assign: Dict[str, Tuple[int, ...]],
                           dmesh: DeviceMesh,
                           sim: StrategySimulator) -> ShardingStrategy:
    """Materialize an assignment as a ShardingStrategy (the searched
    artifact — reference (PCG, MachineView map) analog)."""
    from jax.sharding import PartitionSpec as P
    st = ShardingStrategy(dmesh)
    batch_sharding_axes = None
    for layer in layers:
        opts = sim.options[layer.name]
        degs = assign.get(layer.name, ())
        res = assignment_to_sharding(layer, opts, degs, dmesh)
        if res is None:
            continue
        out_specs, wspecs = res
        st.set_op(layer.name, out_specs, wspecs)
        if batch_sharding_axes is None and out_specs and out_specs[0]:
            first = out_specs[0][0] if len(out_specs[0]) > 0 else None
            if first is not None:
                batch_sharding_axes = first
    for t in input_tensors:
        if batch_sharding_axes is not None and t.shape and \
                t.shape[0] % dmesh.num_devices == 0:
            st.inputs[t.name] = P(batch_sharding_axes)
    return st
