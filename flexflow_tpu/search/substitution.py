"""Graph substitution engine: TASO-style rewrite rules over the PCG.

Reference analog: ``GraphXfer`` (``src/runtime/substitution.cc:596``),
``OpX``/``TensorX``/``PMConstraint`` (``include/flexflow/substitution.h:39-122``).
A rule is a source pattern (``src_ops``) matched against the graph with
backtracking, a destination pattern (``dst_ops``) instantiated in its place,
and a mapping of boundary outputs. Parallelization rules
(``create_partition_linear_combine`` etc., ``substitution.cc:61-110,1726``)
are generated programmatically per parallel degree; algebraic rule
collections load from JSON (``substitution_loader.py``).

TPU semantics: a dst op may *re-annotate* a matched compute op (new
``ParAnn`` — the analog of giving it a different machine view) and insert
parallel ops (Repartition/Combine/Replicate/Reduction) that execute as
sharding transitions (XLA collectives), not explicit copies.
"""
from __future__ import annotations

import dataclasses
import itertools
from typing import (Any, Callable, Dict, Iterable, List, Optional, Sequence,
                    Set, Tuple, Union)

from ..core.layer import Layer
from ..core.tensor import Tensor
from ..ffconst import OperatorType, PARALLEL_OPS
from ..pcg.graph import Edge, Graph, ParAnn, PNode

# A binding of a pattern-input TensorX to reality: either an internal
# producer ("node", PNode, out_idx) or a graph-external tensor ("ext", Tensor,
# consumer_guid_hint)
SrcBinding = Tuple


class TensorX:
    """Symbolic tensor in a pattern: output `idx` of pattern op `op`, or a
    free input (op is None) bound during matching."""
    __slots__ = ("op", "idx", "uid")
    _uid = itertools.count()

    def __init__(self, op: Optional["OpX"] = None, idx: int = 0):
        self.op = op
        self.idx = idx
        self.uid = next(TensorX._uid)

    def __repr__(self):
        return f"TX({self.op.name if self.op else 'in'}:{self.idx})"


@dataclasses.dataclass(frozen=True)
class PMConstraint:
    """Compare a layer param against a constant (reference ``PMConstraint``)."""
    key: str
    value: Any
    compare: str = "eq"   # eq | ne | ge | le

    def check(self, layer: Layer) -> bool:
        v = layer.params.get(self.key)
        if self.compare == "eq":
            return v == self.value
        if self.compare == "ne":
            return v != self.value
        if v is None:
            return False
        return v >= self.value if self.compare == "ge" else v <= self.value


class OpX:
    """Pattern op. In a src pattern: matches a graph node by op type,
    param constraints, annotation predicate, and input-wiring consistency.
    In a dst pattern: instantiates either a re-annotated copy of a matched
    src op (``share``) or a brand-new op (parallel ops, fused ops)."""

    def __init__(self, op_type: Optional[OperatorType],
                 inputs: Sequence[TensorX] = (), num_outputs: int = 1,
                 name: str = "", constraints: Sequence[PMConstraint] = (),
                 cond: Optional[Callable[[PNode, Graph], bool]] = None,
                 share: Optional["OpX"] = None,
                 ann: Union[None, ParAnn,
                            Callable[[Dict["OpX", PNode]], ParAnn]] = None,
                 params: Union[None, Dict[str, Any],
                               Callable[[Dict["OpX", PNode]],
                                        Dict[str, Any]]] = None):
        self.op_type = op_type
        self.inputs = list(inputs)
        self.outputs = [TensorX(self, i) for i in range(num_outputs)]
        self.name = name or (op_type.name.lower() if op_type else "any")
        self.constraints = list(constraints)
        self.cond = cond
        self.share = share        # dst-only: reuse matched layer of this OpX
        self.ann = ann            # dst-only: parallel annotation
        self.params = params      # dst-only: params for a new op
        # arity of a params callable, computed once (hot path avoids
        # per-application inspect.signature)
        if callable(params):
            import inspect
            self._params_nargs = len(inspect.signature(params).parameters)
        else:
            self._params_nargs = 0

    def out(self, idx: int = 0) -> TensorX:
        return self.outputs[idx]

    # -- src matching ------------------------------------------------------
    def can_match(self, node: PNode, graph: Graph) -> bool:
        if self.op_type is not None and node.op_type != self.op_type:
            return False
        if len(self.inputs) > (len(graph.in_edges[node])
                               + len(graph.external_inputs.get(node.guid, ()))):
            return False
        for c in self.constraints:
            if not c.check(node.layer):
                return False
        if self.cond is not None and not self.cond(node, graph):
            return False
        return True

    def __repr__(self):
        return f"OpX({self.name})"


class SkipRewrite(Exception):
    """Raised by dst-pattern param callables to veto one concrete rewrite
    (e.g. a loaded rule whose dim translation is invalid for the matched
    tensor ranks)."""


class GraphXfer:
    """One rewrite rule. ``run(graph)`` yields every rewritten graph."""

    def __init__(self, name: str, src_ops: Sequence[OpX],
                 dst_ops: Sequence[OpX],
                 mapped_outputs: Sequence[Tuple[TensorX, TensorX]]):
        self.name = name
        self.src_ops = list(src_ops)
        self.dst_ops = list(dst_ops)
        self.mapped_outputs = list(mapped_outputs)
        # layer cache for instantiated dst ops, keyed by
        # (op_type, params, input tensor guids) — the analog of the
        # reference's get_or_create_node caching (model.h:678)
        self._layer_cache: Dict[Tuple, Layer] = {}

    # ------------------------------------------------------------------
    def run(self, graph: Graph, max_num_ops: int = 10_000
            ) -> Iterable[Graph]:
        """Backtracking match over src_ops (reference ``GraphXfer::run``),
        yielding one rewritten graph per complete, safe match."""
        mapping: Dict[OpX, PNode] = {}
        bindings: Dict[int, SrcBinding] = {}   # TensorX.uid -> binding
        yield from self._match(0, graph, mapping, bindings, max_num_ops)

    # ------------------------------------------------------------------
    def _input_binding_of(self, graph: Graph, node: PNode, slot: int
                          ) -> Optional[SrcBinding]:
        e = graph.producer(node, slot)
        if e is not None:
            return ("node", e.src, e.src_idx)
        for s, t in graph.external_inputs.get(node.guid, ()):
            if s == slot:
                return ("ext", t)
        return None

    def _try_bind(self, tx: TensorX, actual: SrcBinding,
                  mapping: Dict[OpX, PNode],
                  bindings: Dict[int, SrcBinding]) -> Optional[bool]:
        """Returns True if newly bound (caller must unbind), False if
        consistent with an existing binding, None on conflict."""
        if tx.op is not None:
            # must be the output of the matched node for tx.op
            m = mapping.get(tx.op)
            if m is None:
                # pattern op not yet matched: defer — record as binding
                if tx.uid in bindings:
                    return False if bindings[tx.uid] == actual else None
                bindings[tx.uid] = actual
                return True
            want = ("node", m, tx.idx)
            return False if actual == want else None
        if tx.uid in bindings:
            return False if bindings[tx.uid] == actual else None
        bindings[tx.uid] = actual
        return True

    def _match(self, depth: int, graph: Graph, mapping: Dict[OpX, PNode],
               bindings: Dict[int, SrcBinding], max_num_ops: int
               ) -> Iterable[Graph]:
        if depth == len(self.src_ops):
            if self._check_match_safe(graph, mapping, bindings):
                try:
                    g2 = self._apply(graph, mapping, bindings)
                except SkipRewrite:
                    g2 = None
                if g2 is not None and g2.num_nodes() <= max_num_ops:
                    yield g2
            return
        opx = self.src_ops[depth]
        matched = set(mapping.values())
        for node in list(graph.in_edges.keys()):
            if node in matched or not opx.can_match(node, graph):
                continue
            # check + record input wiring
            newly: List[int] = []
            ok = True
            for slot, tx in enumerate(opx.inputs):
                actual = self._input_binding_of(graph, node, slot)
                if actual is None:
                    ok = False
                    break
                r = self._try_bind(tx, actual, mapping, bindings)
                if r is None:
                    ok = False
                    break
                if r:
                    newly.append(tx.uid)
            if ok:
                # deferred check: outputs of this node that earlier pattern
                # ops consumed must line up
                mapping[opx] = node
                if self._outputs_consistent(opx, node, bindings):
                    yield from self._match(depth + 1, graph, mapping,
                                           bindings, max_num_ops)
                del mapping[opx]
            for uid in newly:
                del bindings[uid]

    def _outputs_consistent(self, opx: OpX, node: PNode,
                            bindings: Dict[int, SrcBinding]) -> bool:
        for tx in opx.outputs:
            b = bindings.get(tx.uid)
            if b is not None and b != ("node", node, tx.idx):
                return False
        return True

    # ------------------------------------------------------------------
    def _check_match_safe(self, graph: Graph, mapping: Dict[OpX, PNode],
                          bindings: Dict[int, SrcBinding]) -> bool:
        """Every edge from a matched node to the outside must leave through
        a mapped output (reference: srcOp output use check)."""
        matched = set(mapping.values())
        mapped_src: Set[Tuple[int, int]] = set()
        for stx, _ in self.mapped_outputs:
            m = mapping.get(stx.op)
            if m is None:
                return False
            mapped_src.add((m.guid, stx.idx))
        for opx, node in mapping.items():
            for e in graph.out_edges[node]:
                if e.dst not in matched and \
                        (node.guid, e.src_idx) not in mapped_src:
                    return False
            # graph outputs count as external consumers
            for (n, i) in graph.outputs:
                if n is node and (node.guid, i) not in mapped_src:
                    return False
        return True

    # ------------------------------------------------------------------
    def _resolve_ann(self, opx: OpX, mapping) -> ParAnn:
        if opx.ann is None:
            return ParAnn.trivial()
        return opx.ann(mapping) if callable(opx.ann) else opx.ann

    def _resolve_params(self, opx: OpX, mapping,
                        in_tensors: Optional[List[Tensor]] = None
                        ) -> Dict[str, Any]:
        if opx.params is None:
            return {}
        if callable(opx.params):
            # loader-generated params also need the concrete input tensors
            # (rank/shape-dependent dim translation); programmatic xfers
            # take mapping only
            return (opx.params(mapping, in_tensors)
                    if opx._params_nargs >= 2 else opx.params(mapping))
        return dict(opx.params)

    def _dst_layer(self, opx: OpX, in_tensors: List[Tensor],
                   mapping) -> Layer:
        """Create (or fetch cached) the concrete Layer for a new dst op."""
        params = self._resolve_params(opx, mapping, in_tensors)
        key = (opx.op_type,
               tuple(sorted((k, tuple(v) if isinstance(v, list) else v)
                            for k, v in params.items())),
               tuple(t.guid for t in in_tensors))
        hit = self._layer_cache.get(key)
        if hit is not None:
            return hit
        layer = Layer(opx.op_type, None, in_tensors, params)
        # real shape inference via the op registry (loaded rules introduce
        # shape-changing dst ops like Concat/Split); identity fallback ONLY
        # for unregistered ops — a registered op whose infer rejects these
        # inputs vetoes the rewrite instead of fabricating a wrong shape
        from ..ops import get_op_def
        try:
            op = get_op_def(opx.op_type)
        except KeyError:
            op = None
        if op is None:
            for t in in_tensors[:1]:
                layer.outputs.append(
                    Tensor(t.shape, t.dtype, owner_layer=layer))
        else:
            try:
                outs = op.infer(params, [t.shape for t in in_tensors],
                                [t.dtype for t in in_tensors])
            except Exception as e:
                raise SkipRewrite(f"{opx.name}: infer failed: {e}")
            for shape, dt in outs:
                layer.outputs.append(Tensor(shape, dt, owner_layer=layer))
        self._layer_cache[key] = layer
        return layer

    def _apply(self, graph: Graph, mapping: Dict[OpX, PNode],
               bindings: Dict[int, SrcBinding]) -> Optional[Graph]:
        g = graph.copy()
        matched = set(mapping.values())

        # tx.uid -> concrete ("node", PNode, idx) or ("ext", Tensor) in g
        def src_loc(tx: TensorX) -> SrcBinding:
            if tx.op is not None and tx.op in mapping:
                return ("node", mapping[tx.op], tx.idx)
            b = bindings.get(tx.uid)
            if b is None:
                raise RuntimeError(f"unbound pattern input {tx}")
            return b

        # Instantiate dst ops in dependency order.
        dst_nodes: Dict[OpX, PNode] = {}
        produced: Dict[int, Tuple[PNode, int]] = {}  # tx.uid -> (node, idx)

        def resolve(tx: TensorX) -> SrcBinding:
            if tx.uid in produced:
                n, i = produced[tx.uid]
                return ("node", n, i)
            if tx.op is not None and tx.op in dst_nodes:
                return ("node", dst_nodes[tx.op], tx.idx)
            return src_loc(tx)

        pending = list(self.dst_ops)
        while pending:
            progressed = False
            for opx in list(pending):
                locs = []
                ready = True
                for tx in opx.inputs:
                    if tx.op is not None and tx.op in [p for p in pending
                                                       if p is not opx]:
                        ready = False
                        break
                    locs.append(resolve(tx))
                if not ready:
                    continue
                pending.remove(opx)
                progressed = True
                ann = self._resolve_ann(opx, mapping)
                if opx.share is not None:
                    layer = mapping[opx.share].layer
                    node = PNode(layer, ann)
                else:
                    in_ts: List[Tensor] = []
                    for loc in locs:
                        if loc[0] == "node":
                            in_ts.append(loc[1].layer.outputs[loc[2]])
                        else:
                            in_ts.append(loc[1])
                    layer = self._dst_layer(opx, in_ts, mapping)
                    node = PNode(layer, ann)
                dst_nodes[opx] = node
                g.add_node(node)
                for slot, loc in enumerate(locs):
                    if loc[0] == "node":
                        g.add_edge(loc[1], node, loc[2], slot)
                    else:
                        g.external_inputs.setdefault(node.guid, []).append(
                            (slot, loc[1]))
                for tx in opx.outputs:
                    produced[tx.uid] = (node, tx.idx)
            if not progressed:
                return None  # cyclic dst pattern

        # Rewire external consumers of mapped outputs.
        for stx, dtx in self.mapped_outputs:
            src_node = mapping[stx.op]
            d = resolve(dtx)
            if d[0] != "node":
                raise RuntimeError(
                    f"substitution output resolved to {d[0]}, expected "
                    f"a node binding")
            d_node, d_idx = d[1], d[2]
            for e in list(g.out_edges.get(src_node, ())):
                if e.src_idx == stx.idx and e.dst not in matched:
                    g.remove_edge(e)
                    g.add_edge(d_node, e.dst, d_idx, e.dst_idx)
            g.outputs = [(d_node, d_idx)
                         if (n is src_node and i == stx.idx) else (n, i)
                         for n, i in g.outputs]
        # Remove matched nodes.
        for node in matched:
            g.remove_node(node)
        return g

    def __repr__(self):
        return f"GraphXfer({self.name})"


# ===========================================================================
# Programmatic parallelization xfers (reference substitution.cc:61-110,1726)
# ===========================================================================
def _unannotated(node: PNode, graph: Graph) -> bool:
    return node.ann.is_trivial()


def _rank_of(node: PNode) -> int:
    return len(node.layer.outputs[0].shape)


def _divisible(dim: int, d: int) -> Callable[[PNode, Graph], bool]:
    def cond(node: PNode, graph: Graph) -> bool:
        if not node.ann.is_trivial():
            return False
        shape = node.layer.outputs[0].shape
        dd = dim if dim >= 0 else len(shape) + dim
        return 0 <= dd < len(shape) and shape[dd] % d == 0 \
            and shape[dd] >= d
    return cond


def _partition(x: TensorX, dim: int, degree: int, group: str) -> OpX:
    return OpX(OperatorType.OP_REPARTITION, [x],
               params={"dim": dim, "degree": degree, "group": group},
               ann=ParAnn(groups=((group, degree),),
                          out=((0, dim, group),)))


def _combine(x: TensorX, dim: int, degree: int, group: str) -> OpX:
    return OpX(OperatorType.OP_COMBINE, [x],
               params={"dim": dim, "degree": degree, "group": group})


def _replicate(x: TensorX, degree: int, group: str) -> OpX:
    return OpX(OperatorType.OP_REPLICATE, [x],
               params={"degree": degree, "group": group},
               ann=ParAnn(groups=((group, degree),), replicate=group))


def _reduction(x: TensorX, degree: int, group: str) -> OpX:
    return OpX(OperatorType.OP_REDUCTION, [x],
               params={"degree": degree, "group": group})


def create_partition_op_combine(op_type: OperatorType, n_inputs: int,
                                dim: int, degree: int,
                                weight_dims: Sequence[Tuple[str, int]] = (),
                                name: Optional[str] = None) -> GraphXfer:
    """Generic data/attribute-partition rule: partition every input along
    ``dim`` by ``degree``, run the op sharded, combine the output.
    Reference: ``create_partition_add_combine``/``relu``/``softmax``/
    ``concat`` family."""
    g = f"p{dim}d{degree}"
    src_ins = [TensorX() for _ in range(n_inputs)]
    src = OpX(op_type, src_ins, cond=_divisible(dim, degree))
    parts = [_partition(t, dim, degree, g) for t in src_ins]
    dst = OpX(op_type, [p.out() for p in parts], share=src,
              ann=ParAnn(groups=((g, degree),), out=((0, dim, g),),
                         weights=tuple((w, wd, g) for w, wd in weight_dims)))
    comb = _combine(dst.out(), dim, degree, g)
    nm = name or f"partition_{op_type.name[3:].lower()}_dim{dim}_deg{degree}"
    return GraphXfer(nm, [src], parts + [dst, comb],
                     [(src.out(), comb.out())])


def create_partition_linear_combine(degree: int, out_dim: int = 0
                                    ) -> GraphXfer:
    """Batch-partition a Linear (reference
    ``create_partition_linear_combine``, ``substitution.cc:61``)."""
    return create_partition_op_combine(OperatorType.OP_LINEAR, 1, out_dim,
                                       degree)


def create_replicate_linear_combine(degree: int) -> GraphXfer:
    """Column-parallel (tensor-parallel) Linear: replicate the input, shard
    the kernel's output dim, combine the sharded last output dim.
    Reference: ``create_replicate_linear_combine``."""
    g = f"tp{degree}"
    x = TensorX()
    src = OpX(OperatorType.OP_LINEAR, [x],
              cond=lambda n, gr: (_unannotated(n, gr)
                                  and n.layer.outputs[0].shape[-1] % degree
                                  == 0
                                  and n.layer.outputs[0].shape[-1] >= degree))
    rep = _replicate(x, degree, g)

    def ann(mapping):
        r = _rank_of(mapping[src])
        return ParAnn(groups=((g, degree),), out=((0, r - 1, g),),
                      weights=(("kernel", 1, g), ("bias", 0, g)))

    dst = OpX(OperatorType.OP_LINEAR, [rep.out()], share=src, ann=ann)

    def comb_params(mapping):
        return {"dim": _rank_of(mapping[src]) - 1, "degree": degree,
                "group": g}

    comb = OpX(OperatorType.OP_COMBINE, [dst.out()], params=comb_params)
    return GraphXfer(f"replicate_linear_combine_deg{degree}", [src],
                     [rep, dst, comb], [(src.out(), comb.out())])


def create_partition_linear_reduce(degree: int) -> GraphXfer:
    """Row-parallel Linear: partition the contraction dim of input + kernel;
    outputs are partial sums resolved by a Reduction (all-reduce).
    Reference: partition_linear w/ Reduction dst."""
    g = f"rp{degree}"
    x = TensorX()

    def cond(n: PNode, gr: Graph) -> bool:
        if not _unannotated(n, gr):
            return False
        ishape = n.layer.inputs[0].shape
        return bool(ishape) and ishape[-1] % degree == 0 \
            and ishape[-1] >= degree

    src = OpX(OperatorType.OP_LINEAR, [x], cond=cond)

    def part_params(mapping):
        r = len(mapping[src].layer.inputs[0].shape)
        return {"dim": r - 1, "degree": degree, "group": g}

    part = OpX(OperatorType.OP_REPARTITION, [x], params=part_params,
               ann=ParAnn(groups=((g, degree),)))
    dst = OpX(OperatorType.OP_LINEAR, [part.out()], share=src,
              ann=ParAnn(groups=((g, degree),),
                         weights=(("kernel", 0, g),), reduce=g))
    red = _reduction(dst.out(), degree, g)
    return GraphXfer(f"partition_linear_reduce_deg{degree}", [src],
                     [part, dst, red], [(src.out(), red.out())])


def create_partition_attention_combine(degree: int) -> GraphXfer:
    """Head-parallel MultiHeadAttention: replicate inputs, shard all
    projection weights on the head dim, all-reduce after the output
    projection. Reference: ``create_partition_attention_combine``
    (``substitution.cc:1756-1769``)."""
    g = f"hp{degree}"
    q, k, v = TensorX(), TensorX(), TensorX()

    def cond(n: PNode, gr: Graph) -> bool:
        return _unannotated(n, gr) and \
            n.layer.params.get("num_heads", 1) % degree == 0 and \
            n.layer.params.get("num_heads", 1) >= degree

    src = OpX(OperatorType.OP_MULTIHEAD_ATTENTION, [q, k, v], cond=cond)
    reps = [_replicate(t, degree, g) for t in (q, k, v)]
    dst = OpX(OperatorType.OP_MULTIHEAD_ATTENTION,
              [r.out() for r in reps], share=src,
              ann=ParAnn(groups=((g, degree),),
                         weights=(("wq", 1, g), ("wk", 1, g), ("wv", 1, g),
                                  ("wo", 0, g), ("bq", 0, g), ("bk", 0, g),
                                  ("bv", 0, g)),
                         reduce=g))
    red = _reduction(dst.out(), degree, g)
    return GraphXfer(f"partition_attention_combine_deg{degree}", [src],
                     reps + [dst, red], [(src.out(), red.out())])


def create_partition_conv2d_combine(degree: int) -> GraphXfer:
    return create_partition_op_combine(OperatorType.OP_CONV2D, 1, 0, degree)


def create_partition_embedding_combine(degree: int) -> GraphXfer:
    """Parameter-parallel embedding: shard the table's output-feature dim."""
    g = f"ep{degree}"
    x = TensorX()

    def cond(n: PNode, gr: Graph) -> bool:
        return _unannotated(n, gr) and \
            n.layer.outputs[0].shape[-1] % degree == 0

    src = OpX(OperatorType.OP_EMBEDDING, [x], cond=cond)

    def ann(mapping):
        r = _rank_of(mapping[src])
        return ParAnn(groups=((g, degree),), out=((0, r - 1, g),),
                      weights=(("kernel", 1, g),))

    dst = OpX(OperatorType.OP_EMBEDDING, [x], share=src, ann=ann)

    def comb_params(mapping):
        return {"dim": _rank_of(mapping[src]) - 1, "degree": degree,
                "group": g}

    comb = OpX(OperatorType.OP_COMBINE, [dst.out()], params=comb_params)
    return GraphXfer(f"partition_embedding_combine_deg{degree}", [src],
                     [dst, comb], [(src.out(), comb.out())])


# ---------------------------------------------------------------------------
# Composed 2D machine views. The reference enumerates per-op MachineViews
# with multiple parallel degrees at once (``graph.h:205``: a view can
# partition batch AND an attribute dim). Single-group xfers cannot compose
# — every ``cond`` requires an unannotated source — so the composed view
# must be reachable in ONE rewrite. These rules take a serial op directly
# to a batch(dp) x feature/head(tp) hybrid, the strategy family Megatron/
# Unity find for transformer blocks.
# ---------------------------------------------------------------------------
def _col_linear_cond(dp: int, tp: int):
    """Shared eligibility for batch(dp) x column(tp) linear rewrites."""
    def cond(n: PNode, gr: Graph) -> bool:
        if not _unannotated(n, gr):
            return False
        o = n.layer.outputs[0].shape
        return len(o) >= 2 and o[0] % dp == 0 and o[0] >= dp \
            and o[-1] % tp == 0 and o[-1] >= tp
    return cond


def _col_linear_ann(src: OpX, dp: int, tp: int, g1: str, g2: str):
    """Shared annotation: batch on g1, kernel output-dim on g2."""
    def ann(mapping):
        r = _rank_of(mapping[src])
        return ParAnn(groups=((g1, dp), (g2, tp)),
                      out=((0, 0, g1), (0, r - 1, g2)),
                      weights=(("kernel", 1, g2), ("bias", 0, g2)))
    return ann


def create_partition_linear_combine_2d(dp: int, tp: int) -> GraphXfer:
    """Batch-partition by ``dp`` AND column-parallel the kernel by ``tp``
    in one rewrite (composed analog of ``create_partition_linear_combine``
    + ``create_replicate_linear_combine``)."""
    g1, g2 = f"dp{dp}", f"tp{tp}"
    x = TensorX()
    src = OpX(OperatorType.OP_LINEAR, [x], cond=_col_linear_cond(dp, tp))
    part = _partition(x, 0, dp, g1)
    rep = _replicate(part.out(), tp, g2)
    dst = OpX(OperatorType.OP_LINEAR, [rep.out()], share=src,
              ann=_col_linear_ann(src, dp, tp, g1, g2))

    def comb_params(mapping):
        return {"dim": _rank_of(mapping[src]) - 1, "degree": tp,
                "group": g2}

    comb_tp = OpX(OperatorType.OP_COMBINE, [dst.out()], params=comb_params)
    comb_dp = _combine(comb_tp.out(), 0, dp, g1)
    return GraphXfer(f"partition_linear_combine_2d_dp{dp}xtp{tp}", [src],
                     [part, rep, dst, comb_tp, comb_dp],
                     [(src.out(), comb_dp.out())])


def create_partition_linear_reduce_2d(dp: int, tp: int) -> GraphXfer:
    """Batch-partition by ``dp`` AND row-parallel the kernel's contraction
    dim by ``tp``: outputs are partial sums resolved by a Reduction within
    each batch shard."""
    g1, g2 = f"dp{dp}", f"rp{tp}"
    x = TensorX()

    def cond(n: PNode, gr: Graph) -> bool:
        if not _unannotated(n, gr):
            return False
        o = n.layer.outputs[0].shape
        ish = n.layer.inputs[0].shape
        return bool(o) and o[0] % dp == 0 and o[0] >= dp and bool(ish) \
            and ish[-1] % tp == 0 and ish[-1] >= tp

    src = OpX(OperatorType.OP_LINEAR, [x], cond=cond)
    part_b = _partition(x, 0, dp, g1)

    def part_params(mapping):
        r = len(mapping[src].layer.inputs[0].shape)
        return {"dim": r - 1, "degree": tp, "group": g2}

    part_k = OpX(OperatorType.OP_REPARTITION, [part_b.out()],
                 params=part_params, ann=ParAnn(groups=((g2, tp),)))
    dst = OpX(OperatorType.OP_LINEAR, [part_k.out()], share=src,
              ann=ParAnn(groups=((g1, dp), (g2, tp)), out=((0, 0, g1),),
                         weights=(("kernel", 0, g2),), reduce=g2))
    red = _reduction(dst.out(), tp, g2)
    comb = _combine(red.out(), 0, dp, g1)
    return GraphXfer(f"partition_linear_reduce_2d_dp{dp}xrp{tp}", [src],
                     [part_b, part_k, dst, red, comb],
                     [(src.out(), comb.out())])


def create_partition_attention_combine_2d(dp: int, tp: int) -> GraphXfer:
    """Batch-partition by ``dp`` AND head-parallel MultiHeadAttention by
    ``tp`` (composed analog of ``create_partition_attention_combine``,
    ``substitution.cc:1756``)."""
    g1, g2 = f"dp{dp}", f"hp{tp}"
    q, k, v = TensorX(), TensorX(), TensorX()

    def cond(n: PNode, gr: Graph) -> bool:
        if not _unannotated(n, gr):
            return False
        o = n.layer.outputs[0].shape
        h = n.layer.params.get("num_heads", 1)
        return bool(o) and o[0] % dp == 0 and o[0] >= dp \
            and h % tp == 0 and h >= tp

    src = OpX(OperatorType.OP_MULTIHEAD_ATTENTION, [q, k, v], cond=cond)
    parts = [_partition(t, 0, dp, g1) for t in (q, k, v)]
    reps = [_replicate(p.out(), tp, g2) for p in parts]
    dst = OpX(OperatorType.OP_MULTIHEAD_ATTENTION,
              [r.out() for r in reps], share=src,
              ann=ParAnn(groups=((g1, dp), (g2, tp)),
                         out=((0, 0, g1),),
                         weights=(("wq", 1, g2), ("wk", 1, g2),
                                  ("wv", 1, g2), ("wo", 0, g2),
                                  ("bq", 0, g2), ("bk", 0, g2),
                                  ("bv", 0, g2)),
                         reduce=g2))
    red = _reduction(dst.out(), tp, g2)
    comb = _combine(red.out(), 0, dp, g1)
    return GraphXfer(f"partition_attention_combine_2d_dp{dp}xhp{tp}", [src],
                     parts + reps + [dst, red, comb],
                     [(src.out(), comb.out())])


def create_partition_ffn_2d(dp: int, tp: int) -> GraphXfer:
    """Megatron-paired FFN in one rewrite: Linear -> Linear becomes
    batch-partition(dp) x [column-parallel d1 -> row-parallel d2] with a
    SINGLE tp all-reduce after d2 — the intermediate (the wide dim)
    never leaves the shard, unlike rewriting the two linears
    independently (which gathers the wide activation). The canonical
    transformer-FFN machine view (Megatron-LM); the reference's rule set
    reaches it only through multi-step substitution chains."""
    g1, g2 = f"dp{dp}", f"mp{tp}"
    x = TensorX()
    l1 = OpX(OperatorType.OP_LINEAR, [x], cond=_col_linear_cond(dp, tp))
    # l2's input IS l1's output, so cond1's last-dim % tp check already
    # guarantees l2's contraction-dim divisibility
    l2 = OpX(OperatorType.OP_LINEAR, [l1.out()], cond=_unannotated)

    part = _partition(x, 0, dp, g1)
    rep = _replicate(part.out(), tp, g2)
    d1 = OpX(OperatorType.OP_LINEAR, [rep.out()], share=l1,
             ann=_col_linear_ann(l1, dp, tp, g1, g2))
    d2 = OpX(OperatorType.OP_LINEAR, [d1.out()], share=l2,
             ann=ParAnn(groups=((g1, dp), (g2, tp)), out=((0, 0, g1),),
                        weights=(("kernel", 0, g2),), reduce=g2))
    red = _reduction(d2.out(), tp, g2)
    comb = _combine(red.out(), 0, dp, g1)
    return GraphXfer(f"partition_ffn_2d_dp{dp}xmp{tp}", [l1, l2],
                     [part, rep, d1, d2, red, comb],
                     [(l2.out(), comb.out())])


def degree_pairs(degrees: Sequence[int]) -> List[Tuple[int, int]]:
    """(dp, tp) pairs whose product is itself a realizable degree —
    the composed-2D rule instantiation set."""
    ds = sorted({d for d in degrees if d > 1})
    dset = set(ds)
    return [(a, b) for a in ds for b in ds if a * b in dset]


def create_partition_combine_elimination(dim: int, degree: int) -> GraphXfer:
    """Repartition(dim,d) then Combine(dim,d) → identity."""
    x = TensorX()
    c1 = PMConstraint("dim", dim)
    c2 = PMConstraint("degree", degree)
    part = OpX(OperatorType.OP_REPARTITION, [x], constraints=[c1, c2])
    comb = OpX(OperatorType.OP_COMBINE, [part.out()], constraints=[c1, c2])
    noop = OpX(OperatorType.OP_NOOP, [x])
    return GraphXfer(f"partition_combine_elim_dim{dim}_deg{degree}",
                     [part, comb], [noop], [(comb.out(), noop.out())])


def create_combine_partition_elimination(dim: int, degree: int) -> GraphXfer:
    """Combine(dim,d) then Repartition(dim,d) → identity — the propagation
    enabler that merges adjacent partitioned regions
    (reference leaf/fuse patterns, ``substitution.cc:1726``)."""
    x = TensorX()
    c1 = PMConstraint("dim", dim)
    c2 = PMConstraint("degree", degree)
    comb = OpX(OperatorType.OP_COMBINE, [x], constraints=[c1, c2])
    part = OpX(OperatorType.OP_REPARTITION, [comb.out()],
               constraints=[c1, c2])
    noop = OpX(OperatorType.OP_NOOP, [x])
    return GraphXfer(f"combine_partition_elim_dim{dim}_deg{degree}",
                     [comb, part], [noop], [(part.out(), noop.out())])


def create_reduction_replicate_elimination(degree: int) -> GraphXfer:
    """Replicate(d) ∘ Reduction(d) -> Reduction (replication after a full
    all-reduce is free under GSPMD)."""
    x = TensorX()
    c = PMConstraint("degree", degree)
    red = OpX(OperatorType.OP_REDUCTION, [x], constraints=[c])
    rep = OpX(OperatorType.OP_REPLICATE, [red.out()], constraints=[c])
    red2 = OpX(OperatorType.OP_REDUCTION, [x],
               params={"degree": degree, "group": f"r{degree}"})
    return GraphXfer(f"reduction_replicate_elim_deg{degree}",
                     [red, rep], [red2], [(rep.out(), red2.out())])


_ELEMENTWISE_PARTITIONABLE = (
    (OperatorType.OP_RELU, 1), (OperatorType.OP_GELU, 1),
    (OperatorType.OP_SIGMOID, 1), (OperatorType.OP_TANH, 1),
    (OperatorType.OP_EW_ADD, 2), (OperatorType.OP_EW_MUL, 2),
    (OperatorType.OP_SOFTMAX, 1), (OperatorType.OP_DROPOUT, 1),
    (OperatorType.OP_POOL2D, 1), (OperatorType.OP_FLAT, 1),
    (OperatorType.OP_CAST, 1),
)

# Norm ops: batch-partition the activations; the (replicated) scale/bias
# weights carry no placement, so they need no weight_dims entries.
_NORM_PARTITIONABLE = (
    (OperatorType.OP_LAYERNORM, 1),
    (OperatorType.OP_RMSNORM, 1),
    (OperatorType.OP_BATCHNORM, 1),
)


def generate_all_pcg_xfers(degrees: Sequence[int],
                           include_eliminations: bool = True,
                           max_dims: int = 4) -> List[GraphXfer]:
    """All parallelization + elimination rules for the given degrees —
    the analog of ``generate_all_pcg_xfers`` (``substitution.cc:1726``)."""
    xfers: List[GraphXfer] = []
    for d in degrees:
        if d <= 1:
            continue
        xfers.append(create_partition_linear_combine(d))
        xfers.append(create_replicate_linear_combine(d))
        xfers.append(create_partition_linear_reduce(d))
        xfers.append(create_partition_attention_combine(d))
        xfers.append(create_partition_conv2d_combine(d))
        xfers.append(create_partition_embedding_combine(d))
        for op_type, n_in in (_ELEMENTWISE_PARTITIONABLE
                              + _NORM_PARTITIONABLE):
            xfers.append(create_partition_op_combine(op_type, n_in, 0, d))
        if include_eliminations:
            for dim in range(max_dims):
                xfers.append(create_combine_partition_elimination(dim, d))
                xfers.append(create_partition_combine_elimination(dim, d))
            xfers.append(create_reduction_replicate_elimination(d))
    for dp, tp in degree_pairs(degrees):
        xfers.append(create_partition_linear_combine_2d(dp, tp))
        xfers.append(create_partition_linear_reduce_2d(dp, tp))
        xfers.append(create_partition_attention_combine_2d(dp, tp))
        xfers.append(create_partition_ffn_2d(dp, tp))
    return xfers
