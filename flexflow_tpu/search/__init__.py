from .costmodel import CostMetrics, OpCostModel  # noqa: F401
from .mcmc import (StrategySimulator, assignment_to_strategy,  # noqa: F401
                   data_parallel_assignment, mcmc_search)
from .optimizer import optimize_strategy  # noqa: F401
from .serialization import load_strategy, save_strategy  # noqa: F401
