"""Search pass proposing per-op device-subset placement (op banks).

Reference analog: the DLRM strategies that assign each embedding table
its own MachineView over a disjoint GPU subset
(``examples/cpp/DLRM/strategies/dlrm_strategy_16embs_16gpus.pb``,
``include/flexflow/machine_view.h:14-62``). There the search enumerates
machine views per op; here banking is a structural proposal — find
groups of independent same-signature heavy ops, predict the cost of
placing them on disjoint subsets, adopt on a modeled win (and the
measured DP-floor guard in ``search/optimizer.py`` still arbitrates the
final adoption with real timed steps).

Cost model of one group (K members, weight bytes W each, output bytes O
each, mesh of n devices, bank degree Bk):

whole-mesh (weights replicated, batch-sharded over n):
  - dense weight-grad all-reduce across the n replicas: ring cost of
    K*W bytes (the dominant term for embedding tables — the reference
    avoids it the same way, by not replicating tables);
  - optimizer update touches all K tables on EVERY device: 3*K*W bytes
    of HBM traffic per device.

banked (bank degree Bk, batch-sharded n/Bk inside each subset):
  - grad all-reduce only inside each subset over its own members:
    ring cost of (K/Bk)*W bytes over n/Bk replicas;
  - per-device update traffic: 3*(K/Bk)*W;
  - rejoin all-gather of member outputs over the bank axes:
    K*O*(Bk-1)/Bk bytes.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

from ..parallel.banks import (BankSpec, choose_bank_axes, find_bank_groups,
                              group_is_padded)
from ..parallel.machine import DeviceMesh
from .costmodel import OpCostModel


def _weight_specs(layer):
    from ..ops import get_op_def
    op = get_op_def(layer.op_type)
    return layer.weights or op.weights(
        layer.params, [t.shape for t in layer.inputs],
        [t.dtype for t in layer.inputs])


def _weight_bytes(layer) -> int:
    from ..dtypes import itemsize
    total = 0
    for s in _weight_specs(layer):
        n = 1
        for d in s.shape:
            n *= d
        total += n * itemsize(s.dtype)
    return total


def _padded_weight_bytes(group) -> float:
    """Mean per-member weight bytes AFTER pad-stacking (heterogeneous
    groups pay for the max shape per weight name on every member)."""
    from ..dtypes import itemsize
    shapes = {}
    dt = {}
    for l in group:
        for s in _weight_specs(l):
            cur = shapes.get(s.name)
            shapes[s.name] = tuple(max(a, b)
                                   for a, b in zip(cur, s.shape)) \
                if cur is not None else tuple(s.shape)
            dt[s.name] = s.dtype
    total = 0
    for nm, sh in shapes.items():
        n = 1
        for d in sh:
            n *= d
        total += n * itemsize(dt[nm])
    return float(total)


def _output_bytes(layer) -> int:
    from ..dtypes import itemsize
    t = layer.outputs[0]
    n = 1
    for d in t.shape:
        n *= d
    return n * itemsize(t.dtype)


def bank_group_cost(k: int, w_bytes: float, o_bytes: float, n: int,
                    bank_deg: int, cm: OpCostModel) -> float:
    """Per-step cost attributable to a K-member group at the given bank
    degree (1 = whole-mesh). Compute (the lookups/matmuls themselves) is
    identical on both sides and omitted; only the terms that differ are
    charged."""
    hbm = cm.spec.hbm_bandwidth
    local_k = k / bank_deg
    replicas = max(1, n // bank_deg)
    # collectives priced by the SAME calibrated/hierarchical model the
    # rest of the search uses (costmodel.xfer_cost handles multi-slice
    # ICI+DCN decomposition and measured-coll constants)
    grad_ar = cm.xfer_cost(local_k * w_bytes, "all_reduce", replicas) \
        if replicas > 1 else 0.0
    update = 3.0 * local_k * w_bytes / hbm
    rejoin = cm.xfer_cost(k * o_bytes, "all_gather", bank_deg) \
        if bank_deg > 1 else 0.0
    return grad_ar + update + rejoin


def propose_banks(layers: Sequence, dmesh: DeviceMesh,
                  cost_model: OpCostModel,
                  reserved_axes: Sequence[str] = (),
                  mode: str = "auto",
                  ) -> List[Tuple[BankSpec, float, float]]:
    """Returns ``[(spec, cost_whole_mesh, cost_banked), ...]`` for every
    group predicted to win (or all eligible groups under ``force``)."""
    if mode == "off" or dmesh.num_devices <= 1:
        return []
    out: List[Tuple[BankSpec, float, float]] = []
    n = dmesh.num_devices
    for gi, group in enumerate(find_bank_groups(layers)):
        k = len(group)
        axes = choose_bank_axes(dmesh, k, reserved=reserved_axes)
        if axes is None:
            continue
        bank_axes, batch_axes = axes
        padded = group_is_padded(group)
        spec = BankSpec([l.name for l in group], bank_axes,
                        batch_axes=batch_axes,
                        param_name=f"__bank{gi}__{group[0].op_type.name}",
                        padded=padded)
        bdeg = spec.bank_degree(dmesh)
        # heterogeneous groups are charged their pad-stacked weight
        # bytes: every member pays for the per-name max shape
        w_b = _padded_weight_bytes(group) if padded \
            else float(sum(_weight_bytes(l) for l in group)) / k
        o_b = float(sum(_output_bytes(l) for l in group)) / k
        c_whole = bank_group_cost(k, w_b, o_b, n, 1, cost_model)
        c_bank = bank_group_cost(k, w_b, o_b, n, bdeg, cost_model)
        # auto mode banks only when the win is material: a relative
        # AND absolute margin, on a table-scale group. Without the
        # floor, tiny embedding pairs (e.g. a transformer's wte/wpe,
        # ~16 KB) bank for microsecond-level predicted savings, moving
        # their params under the stacked bank leaf for nothing — the
        # placement exists for DLRM-scale tables.
        material = (c_whole - c_bank > 5e-5
                    and w_b * k >= (1 << 20))
        if mode == "force" or (c_bank < 0.95 * c_whole and material):
            out.append((spec, c_whole, c_bank))
    return out


def attach_banks(strategy, layers, cost_model,
                 mode: str = "auto",
                 reserved_axes: Sequence[str] = ()) -> List[BankSpec]:
    """Attach winning banks to a ShardingStrategy in place.

    Composes with a pipeline region: the prologue and epilogue are
    emitted through the same bank-aware ``emit_layers`` path
    (executor.py ``_forward``), so groups whose members sit entirely
    before the region (e.g. DLRM-style embedding tables feeding a
    pipelined MLP) or entirely after it bank normally; only groups
    touching the region — whose members are stacked/scanned by the
    pipeline engine itself — are skipped. The pp mesh axis is reserved
    so the bank dim never claims it."""
    pipe = getattr(strategy, "pipeline", None)
    reserved = list(reserved_axes)
    pre = post = None
    if pipe is not None:
        # layers absorbed into the edge stages are emitted inside the
        # pipeline's shard_map (not the bank-aware emit_layers path):
        # treat them as in-region
        absorbed = {l.name
                    for ls in (getattr(pipe, "prologue", None) or (),
                               getattr(pipe, "epilogue", None) or ())
                    for l in ls}
        pre = {l.name for l in layers[:pipe.start]} - absorbed
        post = {l.name for l in layers[pipe.end:]} - absorbed
        for ax in (getattr(pipe, "pp_axis", None),
                   getattr(pipe, "tp_axis", None)):
            if ax and ax not in reserved:
                reserved.append(ax)
    props = propose_banks(layers, strategy.dmesh, cost_model,
                          reserved_axes=tuple(reserved), mode=mode)
    specs = [p[0] for p in props]
    if pipe is not None:
        specs = [s for s in specs
                 if set(s.members) <= pre or set(s.members) <= post]
    strategy.banks = list(getattr(strategy, "banks", [])) + specs
    return specs
