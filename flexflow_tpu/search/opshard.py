"""Per-operator sharding options: which tensor dims may be partitioned and
what weight shardings each choice implies.

Analog of the reference's ParallelDimMappingRecords (``operator.h:127-130``)
plus the programmatic parallelization xfers (``substitution.cc:61-110``):
each op type declares its shardable output dims (SOAP: Sample / Operator /
Attribute / Parameter) and how weights co-shard. The search assigns a
degree to each option; axes come from the factorized mesh.
"""
from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from jax.sharding import PartitionSpec as P

from ..ffconst import (ELEMENTWISE_BINARY_OPS, ELEMENTWISE_UNARY_OPS,
                       OperatorType)
from ..core.layer import Layer


@dataclasses.dataclass(frozen=True)
class ShardOption:
    """One shardable dimension of an op's output."""
    kind: str          # "sample" | "parameter" | "attribute"
    out_dim: int       # which output dim gets the degree
    # weight name -> weight dim that co-shards (same axes)
    weight_dims: Tuple[Tuple[str, int], ...] = ()


def _rank(layer: Layer) -> int:
    return len(layer.outputs[0].shape)


def options_for(layer: Layer) -> List[ShardOption]:
    """Enumerate shardable dims for this layer (batch dim is option 0
    when available)."""
    t = layer.op_type
    r = _rank(layer)
    opts: List[ShardOption] = []
    if r == 0:
        return opts

    def sample(dim=0):
        opts.append(ShardOption("sample", dim))

    if t == OperatorType.OP_LINEAR:
        sample()
        opts.append(ShardOption("parameter", r - 1,
                                (("kernel", 1), ("bias", 0))))
    elif t == OperatorType.OP_CONV2D:
        sample()
        opts.append(ShardOption("parameter", 1,
                                (("kernel", 0), ("bias", 0))))
        if r == 4:
            opts.append(ShardOption("attribute", 2))  # image H
    elif t == OperatorType.OP_POOL2D or t == OperatorType.OP_BATCHNORM:
        sample()
        opts.append(ShardOption("attribute", 1, (("scale", 0), ("bias", 0))
                                if t == OperatorType.OP_BATCHNORM else ()))
    elif t == OperatorType.OP_EMBEDDING:
        sample()
        opts.append(ShardOption("parameter", r - 1, (("kernel", 1),)))
    elif t == OperatorType.OP_MULTIHEAD_ATTENTION:
        sample()
        # head-parallel: wq/wk/wv head dim, wo input-head dim; output stays
        # unsharded on hidden (all-reduce after wo) — reference
        # create_partition_attention_combine
        opts.append(ShardOption("parameter", -1,
                                (("wq", 1), ("wk", 1), ("wv", 1), ("wo", 0),
                                 ("bq", 0), ("bk", 0), ("bv", 0))))
    elif t == OperatorType.OP_LAYERNORM or t == OperatorType.OP_RMSNORM:
        sample()
        if r >= 3:
            opts.append(ShardOption("attribute", 1))  # sequence dim
    elif t in ELEMENTWISE_UNARY_OPS or t in ELEMENTWISE_BINARY_OPS \
            or t in (OperatorType.OP_DROPOUT, OperatorType.OP_SOFTMAX,
                     OperatorType.OP_MUL):
        sample()
        if r >= 3:
            opts.append(ShardOption("attribute", 1))
    elif t in (OperatorType.OP_FLAT, OperatorType.OP_RESHAPE,
               OperatorType.OP_CONCAT, OperatorType.OP_SPLIT,
               OperatorType.OP_TRANSPOSE, OperatorType.OP_BATCHMATMUL,
               OperatorType.OP_MATMUL, OperatorType.OP_TOPK,
               OperatorType.OP_CAST, OperatorType.OP_GATHER):
        sample()
    elif t in (OperatorType.OP_AGGREGATE, OperatorType.OP_AGG_SPEC):
        sample()
    # GROUP_BY and expert-side ops stay unsharded here (EP handled by
    # presets/placement); reductions/means: batch only if dim 0 survives
    elif layer.outputs[0].shape and layer.inputs and \
            layer.inputs[0].shape[:1] == layer.outputs[0].shape[:1]:
        sample()
    return opts


@dataclasses.dataclass
class OpAssignment:
    """Chosen degrees per option for one op. degree 1 = not partitioned."""
    degrees: Tuple[int, ...]  # parallel to options_for(layer)


def assignment_to_sharding(layer: Layer, options: Sequence[ShardOption],
                           degrees: Sequence[int], dmesh
                           ) -> Optional[Tuple[List[Optional[P]],
                                               Dict[str, P]]]:
    """Convert (options, degrees) to (output specs, weight specs) over the
    mesh, allocating disjoint atomic axes per option. Returns None when the
    mesh can't realize the degree product or a dim isn't divisible."""
    r = _rank(layer)
    used: List[str] = []
    out_axes: Dict[int, Tuple[str, ...]] = {}
    weight_axes: Dict[str, Dict[int, Tuple[str, ...]]] = {}
    for opt, deg in zip(options, degrees):
        if deg <= 1:
            continue
        axes = dmesh.allocate_axes(deg, used)
        if axes is None:
            return None
        used.extend(axes)
        if opt.out_dim >= 0:
            dim = opt.out_dim
            size = layer.outputs[0].shape[dim]
            if size % deg != 0:
                return None
            out_axes[dim] = axes
        for wname, wdim in opt.weight_dims:
            weight_axes.setdefault(wname, {})[wdim] = axes

    def to_spec(axes_map: Dict[int, Tuple[str, ...]], rank: int) -> P:
        entries = []
        for d in range(rank):
            ax = axes_map.get(d)
            if ax is None:
                entries.append(None)
            else:
                entries.append(ax[0] if len(ax) == 1 else tuple(ax))
        return P(*entries)

    out_spec = to_spec(out_axes, r) if out_axes else None
    out_specs: List[Optional[P]] = []
    for o in layer.outputs:
        if out_spec is not None and len(o.shape) == r:
            ok = all(o.shape[d] % _deg(dmesh, ax) == 0
                     for d, ax in out_axes.items())
            out_specs.append(out_spec if ok else None)
        else:
            out_specs.append(None)
    wspecs: Dict[str, P] = {}
    for wname, amap in weight_axes.items():
        rank_w = max(amap.keys()) + 1
        wspecs[wname] = to_spec(amap, rank_w)
    return out_specs, wspecs


def _deg(dmesh, axes: Tuple[str, ...]) -> int:
    d = 1
    for a in axes:
        d *= dmesh.axis_sizes[a]
    return d
