"""Loader for reference-format substitution rule collections (JSON).

Reads the reference's ``substitutions/graph_subst_3_v2.json`` schema
(``include/flexflow/substitution_loader.h:131``, 640 TASO-generated rules;
``substitution_loader::RuleCollection``) and compiles each rule into a
:class:`~.substitution.GraphXfer` over our PCG.

Schema: a rule has ``srcOp``/``dstOp`` operator lists, each operator with
``type`` (reference OperatorType name), ``input`` tensors referencing
``(opId, tsId)`` — ``opId == -1`` meaning external pattern input ``tsId`` —
and ``para`` key/value constraints (PM_*). ``mappedOutput`` wires boundary
outputs from src to dst.

Dim-numbering translation: the reference orders tensor dims innermost-
first (``ParallelDim`` index 0 = fastest-varying; numpy's last axis), so a
rule dim ``d`` on a rank-r tensor is numpy axis ``r - 1 - d``. The rank is
only known once a concrete match is found, so dim checks compile to match-
time conditions and dst dim params to apply-time callables; a translation
that lands outside the tensor's rank (the reference's replica dim) vetoes
that rewrite (``SkipRewrite``) — conservative, never wrong.

Enum value translation is identity: our ``ffconst`` mirrors the reference's
integer enum values (e.g. ``AC_MODE_RELU == 11``).
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..ffconst import ActiMode, OperatorType
from .substitution import (GraphXfer, OpX, PMConstraint, SkipRewrite,
                           TensorX)


def default_collection_path() -> str:
    """The vendored 640-rule collection shipped with the package
    (``flexflow_tpu/data/graph_subst_v3.json``, decoded once from the
    TASO-era ``.pb`` wire format by ``tools/pb_rules.py``) — what
    ``--substitution-json`` points at in a standalone install."""
    return os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "data", "graph_subst_v3.json")


# reference OperatorType name -> our op type
_OP_TYPE_MAP = {
    "OP_PARTITION": OperatorType.OP_REPARTITION,
    "OP_COMBINE": OperatorType.OP_COMBINE,
    "OP_REPLICATE": OperatorType.OP_REPLICATE,
    "OP_REDUCE": OperatorType.OP_REDUCTION,
    "OP_LINEAR": OperatorType.OP_LINEAR,
    "OP_RELU": OperatorType.OP_RELU,
    "OP_EW_ADD": OperatorType.OP_EW_ADD,
    "OP_EW_MUL": OperatorType.OP_EW_MUL,
    "OP_CONCAT": OperatorType.OP_CONCAT,
    "OP_SPLIT": OperatorType.OP_SPLIT,
    "OP_SOFTMAX": OperatorType.OP_SOFTMAX,
    "OP_MATMUL": OperatorType.OP_BATCHMATMUL,
    "OP_EW_SUB": OperatorType.OP_EW_SUB,
    "OP_SIGMOID": OperatorType.OP_SIGMOID,
    "OP_TANH": OperatorType.OP_TANH,
}

_PARALLEL_TYPES = {OperatorType.OP_REPARTITION, OperatorType.OP_COMBINE,
                   OperatorType.OP_REPLICATE, OperatorType.OP_REDUCTION}

# PM_ACTI uses the TASO ActiMode numbering (NONE=0, SIGMOID=1, RELU=2,
# TANH=3), not the reference's AC_MODE_* (10..14)
_TASO_ACTI = {0: ActiMode.AC_MODE_NONE, 1: ActiMode.AC_MODE_SIGMOID,
              2: ActiMode.AC_MODE_RELU, 3: ActiMode.AC_MODE_TANH}


def _para(op_doc: Dict) -> Dict[str, int]:
    return {p["key"]: p["value"] for p in op_doc.get("para", [])}


class _ActiConstraint:
    """PM_ACTI check: absent activation param means AC_MODE_NONE."""

    def __init__(self, acti: ActiMode):
        self.acti = acti

    def check(self, layer) -> bool:
        v = layer.params.get("activation", ActiMode.AC_MODE_NONE)
        try:
            return ActiMode(v) == self.acti
        except ValueError:
            return False


def _rank_of_output(node) -> int:
    return len(node.layer.outputs[0].shape)


def _np_dim(ff_dim: int, rank: int) -> Optional[int]:
    """Reference dim index -> numpy axis; None if it names the replica dim
    (or beyond) for this rank."""
    if 0 <= ff_dim < rank:
        return rank - 1 - ff_dim
    return None


def _src_cond(op_type: OperatorType, para: Dict[str, int]):
    """Match-time predicate translating PM_* constraints for one src op."""
    ff_dim = para.get("PM_PARALLEL_DIM")
    degree = para.get("PM_PARALLEL_DEGREE")
    axis = para.get("PM_AXIS")
    numdim = para.get("PM_NUMDIM")
    num_inputs = para.get("PM_NUM_INPUTS")
    num_outputs = para.get("PM_NUM_OUTPUTS")

    def cond(node, graph) -> bool:
        rank = _rank_of_output(node)
        p = node.layer.params
        if numdim is not None and rank != numdim:
            return False
        if degree is not None and p.get("degree") != degree:
            return False
        if ff_dim is not None and op_type in (OperatorType.OP_REPARTITION,
                                              OperatorType.OP_COMBINE):
            nd = _np_dim(ff_dim, rank)
            if nd is None or p.get("dim") != nd:
                return False
        if axis is not None:
            nd = _np_dim(axis, rank)
            if nd is None or p.get("axis", -1) % rank != nd:
                return False
        if num_inputs is not None and len(node.layer.inputs) != num_inputs:
            return False
        if num_outputs is not None \
                and len(node.layer.outputs) != num_outputs:
            return False
        return True

    return cond


def _dst_params(op_type: OperatorType, para: Dict[str, int],
                rule_name: str):
    """Apply-time params for a new dst op; receives the concrete input
    tensors so reference dims translate against the real ranks."""
    ff_dim = para.get("PM_PARALLEL_DIM")
    degree = para.get("PM_PARALLEL_DEGREE", 1)
    axis = para.get("PM_AXIS")
    n_out = para.get("PM_NUM_OUTPUTS", 2)

    def params(mapping, in_tensors):
        if not in_tensors:
            raise SkipRewrite(rule_name)
        shape = in_tensors[0].shape
        rank = len(shape)

        def need_dim(d: Optional[int]) -> int:
            nd = _np_dim(d if d is not None else 0, rank)
            if nd is None:
                raise SkipRewrite(rule_name)  # replica-dim placement
            return nd

        if op_type in (OperatorType.OP_REPARTITION, OperatorType.OP_COMBINE):
            return {"dim": need_dim(ff_dim), "degree": degree,
                    "group": f"j{degree}"}
        if op_type in (OperatorType.OP_REPLICATE, OperatorType.OP_REDUCTION):
            return {"degree": degree, "group": f"j{degree}"}
        if op_type == OperatorType.OP_CONCAT:
            return {"axis": need_dim(axis)}
        if op_type == OperatorType.OP_SPLIT:
            nd = need_dim(axis)
            size = shape[nd]
            if size % n_out != 0:
                raise SkipRewrite(rule_name)
            return {"axis": nd, "sizes": [size // n_out] * n_out}
        return {}

    return params


def compile_rule(rule: Dict) -> Optional[GraphXfer]:
    """Compile one reference Rule doc into a GraphXfer; None if the rule
    uses an operator we can't map."""
    name = rule.get("name", "loaded_rule")
    ext: Dict[int, TensorX] = {}

    def ext_tx(ts_id: int) -> TensorX:
        if ts_id not in ext:
            ext[ts_id] = TensorX()
        return ext[ts_id]

    # ---- src ops ----
    src_ops: List[OpX] = []
    for doc in rule["srcOp"]:
        ot = _OP_TYPE_MAP.get(doc["type"])
        if ot is None:
            return None
        para = _para(doc)
        ins: List[TensorX] = []
        for t in doc.get("input", []):
            if t["opId"] < 0:
                ins.append(ext_tx(t["tsId"]))
            else:
                ins.append(src_ops[t["opId"]].out(t["tsId"]))
        n_out = para.get("PM_NUM_OUTPUTS", 1)
        constraints = []
        if "PM_ACTI" in para:
            acti = _TASO_ACTI.get(para["PM_ACTI"],
                                  ActiMode.AC_MODE_NONE)
            constraints.append(_ActiConstraint(acti))
        src_ops.append(OpX(ot, ins, num_outputs=n_out,
                           name=f"{name}:src{len(src_ops)}",
                           constraints=constraints,
                           cond=_src_cond(ot, para)))

    # ---- dst ops ----
    # compute ops re-use the matched src layer of the same type, in order
    # of appearance (TASO parallelization rules re-wire the same compute
    # around moved parallel ops)
    src_by_type: Dict[OperatorType, List[OpX]] = {}
    for s in src_ops:
        src_by_type.setdefault(s.op_type, []).append(s)
    used_by_type: Dict[OperatorType, int] = {}

    dst_ops: List[OpX] = []
    for doc in rule["dstOp"]:
        ot = _OP_TYPE_MAP.get(doc["type"])
        if ot is None:
            return None
        para = _para(doc)
        ins = []
        for t in doc.get("input", []):
            if t["opId"] < 0:
                ins.append(ext_tx(t["tsId"]))
            else:
                ins.append(dst_ops[t["opId"]].out(t["tsId"]))
        n_out = para.get("PM_NUM_OUTPUTS", 1)
        pool = src_by_type.get(ot, [])
        k = used_by_type.get(ot, 0)
        if ot not in _PARALLEL_TYPES and k < len(pool):
            # re-use the matched src compute op of the same type, in order
            used_by_type[ot] = k + 1
            dst_ops.append(OpX(ot, ins, num_outputs=n_out,
                               name=f"{name}:dst{len(dst_ops)}",
                               share=pool[k]))
        elif ot not in _PARALLEL_TYPES and ot in (
                OperatorType.OP_LINEAR, OperatorType.OP_BATCHMATMUL):
            # a brand-new weighted op (e.g. fused wider linear) would need
            # weight concatenation semantics we don't synthesize — skip rule
            return None
        else:
            # new parallel op, or new unweighted compute op (concat/split/
            # elementwise introduced by fusion rules)
            dst_ops.append(OpX(ot, ins, num_outputs=n_out,
                               name=f"{name}:dst{len(dst_ops)}",
                               params=_dst_params(ot, para, name)))

    mapped = []
    for mo in rule.get("mappedOutput", []):
        mapped.append((src_ops[mo["srcOpId"]].out(mo["srcTsId"]),
                       dst_ops[mo["dstOpId"]].out(mo["dstTsId"])))
    return GraphXfer(name, src_ops, dst_ops, mapped)


def load_rule_collection(path: str) -> List[GraphXfer]:
    """Load a reference-format JSON rule collection into GraphXfers.
    Unmappable rules are skipped (reported via the returned list length)."""
    with open(path) as f:
        doc = json.load(f)
    rules = doc["rule"] if isinstance(doc, dict) else doc
    out: List[GraphXfer] = []
    for r in rules:
        xf = compile_rule(r)
        if xf is not None:
            out.append(xf)
    return out
