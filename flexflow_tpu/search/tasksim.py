"""Task-graph execution simulator for candidate strategies.

Analog of the reference's full-graph simulation path
(``Simulator::simulate_runtime``, ``src/runtime/simulator.cc:822-1200``,
``TaskManager``/``SimTask``): expand a PCG + annotations into a DAG of
per-shard forward/backward compute tasks and per-device communication tasks
(links modeled as extra processors, exactly like the reference models
inter-device connections as schedulable devices), then play the DAG through
the native event-driven simulator (``flexflow_tpu/native/src/ffruntime.cc``). This
captures queueing and compute/comm overlap that the additive
``GraphCostEvaluator`` cannot; it is selected with
``machine_model_version >= 1`` (the reference's ``--machine-model-version``).

Hierarchical placement (``parallel/placement.py``): every collective's
seconds come from ``OpCostModel.xfer_cost`` / ``weight_sync_cost``, so
when a placement is attached the durations already reflect the chosen
reduction-tree shape over the (tier, degree) path; link-level DCN
contention is additionally modeled by the ``GraphTopology`` fabric's
per-link factors (a DCN hop serializes ``link_factor``x longer).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..ffconst import OperatorType
from ..pcg.graph import Graph, PNode
from .. import native
from .costmodel import OpCostModel
from .unity import (GraphCost, GraphCostEvaluator, _bytes_of,
                    _bytes_of_spec, _coll_bytes, propagate_layouts)


def _compute_and_place_degree(ann) -> Tuple[int, int]:
    """(compute-division degree, placement degree) for one annotation.

    Compute shrinks only with output-sharding (+partial-sum) groups;
    replicate/weight-only groups add devices without dividing work."""
    scale_groups = {g for (_, _, g) in ann.out}
    if ann.reduce:
        scale_groups.add(ann.reduce)
    scale = 1
    for g in scale_groups:
        scale *= ann.degree_of(g)
    return max(1, scale), max(1, ann.total_degree())


class TaskGraphBuilder:
    """Expands one PCG into (proc, duration, edges) arrays.

    Processors: [0, n_dev) = compute cores. Communication:
      - no physical topology known: [n_dev, 2*n_dev) = each device's ICI
        injection port (one comm processor per device);
      - ``MachineSpec.ici_shape`` set (e.g. via --machine-model-file):
        one processor PER PHYSICAL TORUS LINK (parallel/topology.py), and
        ring collectives charge every link on each participant's
        dimension-ordered route — so strategies whose collectives share
        links (a flat ring snaking a 4x8 torus; concurrent groups
        aliasing onto one dim) serialize there, exactly the congestion
        the reference models with per-connection CommDevices
        (``simulator.h:142``, ``network.cc``)."""

    def __init__(self, cost: OpCostModel, n_dev: int,
                 comm_scale: float = 1.0):
        self.cost = cost
        self.n_dev = n_dev
        # overlap-estimate support: comm_scale=0.0 builds the same task
        # DAG with zero-duration communication (the compute-only
        # makespan baseline of TaskGraphEvaluator.overlap_estimate);
        # comm_seconds accumulates the UNSCALED logical collective
        # seconds charged by build() — the serial comm total the
        # exposed/hidden decomposition is taken against.
        self.comm_scale = comm_scale
        self.comm_seconds = 0.0
        # proc/duration/edge arrays live in the native TaskBuffer (C++
        # when libffruntime.so is available): ring expansion of one
        # search is ~20M dependency edges — the round-4 profile's
        # hottest Python loop at ~60 s, now one call per collective
        self.buf = native.TaskBuffer()
        topo = cost.spec.topology
        self.topo = topo if topo is not None \
            and topo.num_devices == n_dev else None
        self.link_idx = self.topo.link_index() if self.topo else None
        self.segment_size = getattr(cost, "segment_size", 16777216)
        self.max_segments = getattr(cost, "max_segments", 1)
        # per-BUILDER processor-id arrays for ring routes; the raw link
        # tuples underneath are cached on the (shared) topology object
        # — see _flat_routes
        self._route_procs: Dict[Tuple[int, ...], Tuple] = {}

    @property
    def num_procs(self) -> int:
        return self.n_dev + (len(self.link_idx) if self.link_idx
                             else self.n_dev)

    # array views (full copies out of the native buffer on EVERY access
    # — introspection only; to simulate, call buf.simulate directly)
    @property
    def proc(self):
        return self.buf.arrays()[0]

    @property
    def dur(self):
        return self.buf.arrays()[1]

    @property
    def edges(self):
        return self.buf.arrays()[2]

    def add_task(self, proc: int, dur: float) -> int:
        return self.buf.add_tasks([proc], [dur])

    def dep(self, a: int, b: int):
        self.buf.cross_deps([a], [b])

    def shard_devices(self, degree: int) -> List[int]:
        """Block-distribute `degree` shards over the devices."""
        degree = max(1, min(degree, self.n_dev))
        stride = self.n_dev // degree
        return [i * stride for i in range(degree)]

    # ring-algorithm round counts (reference LogicalTaskgraphBasedSimulator
    # expands a logical allreduce into physical p2p rounds at sim time,
    # simulator.h:785; same algebra as the calibrated cost model)
    _ROUNDS = {"all_reduce": (lambda d: 2 * (d - 1)),
               "all_gather": (lambda d: d - 1),
               "reduce_scatter": (lambda d: d - 1),
               "all_to_all": (lambda d: d - 1)}

    def _flat_routes(self, devices: Tuple[int, ...]):
        """Flattened ring routes for one participant tuple: (offsets,
        hop link-processor ids, per-hop duration factors or None,
        any_hops).

        Two-level cache: the topology caches only builder-INDEPENDENT
        data — raw link tuples + bandwidth factors, bounded
        (``parallel/topology.py:flat_ring_links``) — and each builder
        maps links to ITS processor ids here. The old single-level
        scheme stored ``self.n_dev + self.link_idx[link]`` on the shared
        topology object, so the first builder to touch a device tuple
        poisoned every later builder with its own processor numbering
        (and the cache grew without bound across searches)."""
        hit = self._route_procs.get(devices)
        if hit is None:
            import numpy as np

            from ..parallel.topology import flat_ring_links
            off, links, fac = flat_ring_links(self.topo, devices)
            procs = [self.n_dev + self.link_idx[l] for l in links]
            hit = (np.asarray(off, np.int32),
                   np.asarray(procs, np.int32),
                   np.asarray(fac, np.float64) if fac is not None
                   else None,
                   len(procs) > 0)
            self._route_procs[devices] = hit
        return hit

    def collective_tasks(self, devices: List[int], coll: str,
                         seconds: float, after: List[int],
                         nbytes: int = 0) -> List[int]:
        """Expand one logical collective into physical ring rounds.

        Round r of participant i transfers its chunk to the ring
        successor and cannot start before round r-1 of the PREDECESSOR
        delivered (the chunk being forwarded) — the actual ring
        dataflow, so concurrent collectives interleave with other
        traffic at round granularity instead of whole-collective lumps.
        The calibrated total is preserved: rounds x per-round = the
        cost model's collective seconds. Falls back to the lump-sum
        :meth:`comm_tasks` without a physical topology or for
        degenerate/oversized expansions."""
        deg = len(devices)
        rounds = self._ROUNDS.get(coll, lambda d: 1)(deg) \
            if deg > 1 else 1
        if (self.topo is None or rounds <= 1 or rounds > 128):
            return self.comm_tasks(devices, seconds, after, nbytes)
        off, procs, fac, any_hops = self._flat_routes(tuple(devices))
        if not any_hops:
            return self.comm_tasks(devices, seconds, after, nbytes)
        n_seg = 1
        # segment sizing uses the ring CHUNK (nbytes / deg) — what each
        # round actually moves per participant — not nbytes / rounds,
        # which under-counts all_reduce chunks ~2x (ADVICE r4)
        round_bytes = nbytes // max(deg, 1) if nbytes else 0
        if round_bytes > 0 and self.max_segments > 1:
            n_seg = min(self.max_segments,
                        max(1, -(-round_bytes // self.segment_size)))
        out = self.buf.collective(off, procs, fac, rounds,
                                  seconds / rounds, n_seg, list(after))
        return out or self.comm_tasks(devices, seconds, after, nbytes)

    def comm_tasks(self, devices: List[int], seconds: float,
                   after: List[int], nbytes: int = 0) -> List[int]:
        """Communication tasks for one ring collective.

        Without a topology: one task on each participant's injection
        port. With a torus: one task per physical link on each
        participant's route to its ring successor — multi-hop routes and
        link sharing between concurrent collectives then cost real time
        on the shared link processors.

        ``nbytes`` > 0 with ``--simulator-max-num-segments`` > 1 splits
        each transfer into segments that pipeline across the route
        (segment s can occupy hop k while segment s+1 is on hop k-1),
        the reference EnhancedMachineModel's segmented transfers
        (machine_model.cc, --simulator-segment-size): a multi-hop
        transfer then costs ~(n_seg + hops - 1)/n_seg of its
        store-and-forward time, and congestion on shared links is
        resolved at segment granularity instead of whole messages.
        Returns each participant's last-segment final-hop task (segment
        chains are symmetric, so it is the last to finish)."""
        n_seg = 1
        if nbytes > 0 and self.max_segments > 1:
            n_seg = min(self.max_segments,
                        max(1, -(-nbytes // self.segment_size)))
        if self.topo is not None and len(devices) > 1:
            # heterogeneous fabrics (GraphTopology): a DCN or degraded
            # link serializes the same bytes for link_factor x longer
            off, procs, fac, any_hops = self._flat_routes(tuple(devices))
            if any_hops:
                out = self.buf.collective(off, procs, fac, 1, seconds,
                                          n_seg, list(after))
                if out:
                    return out
            # fully-local ring (all routes empty): charge the first
            # participant's first outgoing link so time is accounted
            first = next((l for l in self.link_idx
                          if l[0] == devices[0]), None)
            if first is None:
                procs2 = [self.n_dev + d for d in devices]
            else:
                procs2 = [self.n_dev + self.link_idx[first]] \
                    * len(devices)
        else:
            procs2 = [self.n_dev + d for d in devices]
        first_id = self.buf.add_tasks(procs2, [seconds] * len(procs2))
        out = list(range(first_id, first_id + len(procs2)))
        self.buf.cross_deps(list(after), out)
        return out

    # ------------------------------------------------------------------
    def build(self, graph: Graph) -> Tuple[float, int]:
        """Returns (makespan_seconds, peak_weight+act bytes per device).

        Task expansion:
          fwd shard tasks (per device of the op's group)
          -> parallel-op comm tasks on link processors
          -> bwd shard tasks in reverse order (dep on all fwd done)
          -> gradient all-reduce comm + optimizer update per weighted op.
        """
        topo = graph.topo_order()
        lay = propagate_layouts(graph)
        # per (node, phase): list of task ids; phase 0 fwd, 1 bwd
        fwd_tasks: Dict[int, List[int]] = {}
        bwd_tasks: Dict[int, List[int]] = {}
        mem = 0

        def in_region(n: PNode, in_bytes: int, own: int = 1) -> int:
            """Collective-group region bytes given the producer layout
            (same composed-view correction as GraphCostEvaluator)."""
            e0 = graph.producer(n, 0)
            in_lay = lay.get((e0.src.guid, e0.src_idx), ()) \
                if e0 is not None else ()
            return _coll_bytes(in_bytes, in_lay, own)

        def producer_tasks(n: PNode, table) -> List[int]:
            out = []
            for e in graph.in_edges[n]:
                out.extend(table.get(e.src.guid, []))
            return out

        # ---- forward ----
        for n in topo:
            t = n.op_type
            preds = producer_tasks(n, fwd_tasks)
            if t in (OperatorType.OP_INPUT, OperatorType.OP_NOOP,
                     OperatorType.OP_WEIGHT):
                fwd_tasks[n.guid] = preds
                continue
            in_bytes = 0
            e0 = graph.producer(n, 0)
            if e0 is not None:
                in_bytes = _bytes_of(e0.src.layer.outputs[e0.src_idx])
            elif n.layer.inputs:
                in_bytes = _bytes_of(n.layer.inputs[0])
            if t in (OperatorType.OP_REPARTITION, OperatorType.OP_COMBINE,
                     OperatorType.OP_REPLICATE, OperatorType.OP_REDUCTION):
                # forward collective per parallel op; REPLICATE fwd is free
                # under SPMD (input already replicated) — same semantics as
                # GraphCostEvaluator
                # REPARTITION fwd: slicing owned/replicated data is
                # (near-)local under SPMD — its cost is charged on the
                # backward cotangent gather (mirrors GraphCostEvaluator)
                deg = n.layer.params.get("degree", 1)
                coll = {OperatorType.OP_REPARTITION: None,
                        OperatorType.OP_COMBINE: "all_gather",
                        OperatorType.OP_REPLICATE: None,
                        OperatorType.OP_REDUCTION: "all_reduce"}[t]
                if coll is None:
                    fwd_tasks[n.guid] = preds
                    continue
                own = deg if t == OperatorType.OP_COMBINE else 1
                region = in_region(n, in_bytes, own)
                secs = self.cost.xfer_cost(region, coll, deg)
                self.comm_seconds += secs
                devs = self.shard_devices(deg)
                fwd_tasks[n.guid] = self.collective_tasks(
                    devs, coll, secs * self.comm_scale, preds,
                    nbytes=region)
                continue
            if t in (OperatorType.OP_PIPELINE,
                     OperatorType.OP_FUSED_PARALLEL):
                fwd_tasks[n.guid] = preds
                continue
            ann = n.ann
            # compute divides only over output-sharding (+reduce) groups;
            # replicate / weight-only groups duplicate work across devices
            # (same rule as GraphCostEvaluator.graph_cost)
            scale_deg, place_deg = _compute_and_place_degree(ann)
            degs = {0: scale_deg} if scale_deg > 1 else {}
            cm = self.cost.op_cost(n.layer, degs, ann.weight_degree())
            mem += cm.weights_memory * 4 + cm.outputs_memory
            shards = self.shard_devices(place_deg)
            first = self.buf.add_tasks(shards,
                                       [cm.forward_time] * len(shards))
            ids = list(range(first, first + len(shards)))
            self.buf.cross_deps(preds, ids)
            fwd_tasks[n.guid] = ids

        # ---- backward (reverse topo; bwd(n) after fwd(n) and after bwd of
        # all consumers) ----
        for n in reversed(topo):
            t = n.op_type
            succs: List[int] = []
            for e in graph.out_edges[n]:
                succs.extend(bwd_tasks.get(e.dst.guid, []))
            if not succs:
                succs = fwd_tasks.get(n.guid, [])
            if t in (OperatorType.OP_INPUT, OperatorType.OP_NOOP,
                     OperatorType.OP_WEIGHT, OperatorType.OP_PIPELINE,
                     OperatorType.OP_FUSED_PARALLEL):
                bwd_tasks[n.guid] = succs
                continue
            in_bytes = 0
            e0 = graph.producer(n, 0)
            if e0 is not None:
                in_bytes = _bytes_of(e0.src.layer.outputs[e0.src_idx])
            elif n.layer.inputs:
                in_bytes = _bytes_of(n.layer.inputs[0])
            if t in (OperatorType.OP_REPARTITION, OperatorType.OP_COMBINE,
                     OperatorType.OP_REPLICATE, OperatorType.OP_REDUCTION):
                # backward cotangent collective: REPARTITION/COMBINE move
                # the cotangent the other way; REPLICATE bwd all-reduces
                # the replica cotangents; REDUCTION bwd is free (cotangent
                # broadcast is the producing op's replication) — mirrors
                # GraphCostEvaluator's per-op charges
                deg = n.layer.params.get("degree", 1)
                coll = {OperatorType.OP_REPARTITION: "all_to_all",
                        OperatorType.OP_COMBINE: "all_to_all",
                        OperatorType.OP_REPLICATE: "all_reduce",
                        OperatorType.OP_REDUCTION: None}[t]
                if coll is None:
                    bwd_tasks[n.guid] = succs
                    continue
                own = deg if t == OperatorType.OP_COMBINE else 1
                region = in_region(n, in_bytes, own)
                secs = self.cost.xfer_cost(region, coll, deg)
                self.comm_seconds += secs
                devs = self.shard_devices(deg)
                bwd_tasks[n.guid] = self.collective_tasks(
                    devs, coll, secs * self.comm_scale, succs,
                    nbytes=region)
                continue
            ann = n.ann
            scale_deg, place_deg = _compute_and_place_degree(ann)
            degs = {0: scale_deg} if scale_deg > 1 else {}
            cm = self.cost.op_cost(n.layer, degs, ann.weight_degree())
            shards = self.shard_devices(place_deg)
            first = self.buf.add_tasks(shards,
                                       [cm.backward_time] * len(shards))
            ids = list(range(first, first + len(shards)))
            self.buf.cross_deps(succs, ids)
            self.buf.cross_deps(fwd_tasks.get(n.guid, []), ids)
            bwd_tasks[n.guid] = ids
            # gradient sync + update riding the link processor, overlapping
            # with earlier ops' backward compute (reference NCCL path)
            wbytes = sum(_bytes_of_spec(w) for w in n.layer.weights)
            if wbytes:
                wdeg = max(1, ann.weight_degree())
                dp_deg = max(1, self.n_dev // wdeg)
                secs = self.cost.weight_sync_cost(wbytes // wdeg, dp_deg)
                if secs > 0:
                    self.comm_seconds += secs
                    # participants = the dp replica group the cost was
                    # priced for (a dp_deg-way ring), NOT all placement
                    # devices — the round count derives from len(devices)
                    self.collective_tasks(self.shard_devices(dp_deg),
                                          "all_reduce",
                                          secs * self.comm_scale, ids,
                                          nbytes=wbytes // wdeg)

        makespan = self.buf.simulate(self.num_procs)
        return makespan, mem


class TaskGraphEvaluator(GraphCostEvaluator):
    """GraphCostEvaluator variant whose total is the simulated makespan.

    Keeps the analytic components (xfer/sync breakdown, memory) from the
    base class for reporting and pin penalties, but scores graphs by
    playing the expanded task DAG through the native simulator."""

    def overlap_estimate(self, graph: Graph) -> Dict[str, float]:
        """Event-driven compute/comm concurrency decomposition of one
        graph — THE authoritative overlap estimate the additive
        evaluator's closed-form hidden/exposed split
        (``unity._overlap_split``) is checked against (bench
        ``comm_overlap`` leg: agreement within 2x).

        Two simulations of the same task DAG: the real one (comm tasks
        at their calibrated durations, riding the link processors
        concurrently with compute — overlap is what the event engine
        natively models) and a comm-free one (identical structure,
        zero-duration communication). The makespan delta is the comm
        time the schedule could NOT hide::

            exposed = max(0, makespan − compute_makespan)
            hidden  = max(0, serial_comm_total − exposed)

        The real-side build shares this evaluator's simulation cache
        with :meth:`graph_cost` (expansion is the expensive half — see
        the TaskBuffer note above), so scoring then estimating the
        same graph expands it once, not twice.
        """
        n = self.dmesh.num_devices
        h = graph.hash()
        cached = self._cache.get(("tg-overlap", h))
        if cached is not None:
            makespan, comm_total = cached
        else:
            real = TaskGraphBuilder(self.cost, n)
            makespan, mem = real.build(graph)
            comm_total = real.comm_seconds
            self._cache[("tg-overlap", h)] = (makespan, comm_total)
            # seed graph_cost's sim cache too: a later score of the
            # same graph reuses this expansion
            self._cache.setdefault(("tg-sim", h), (makespan, mem))
        free = TaskGraphBuilder(self.cost, n, comm_scale=0.0)
        compute_ms, _ = free.build(graph)
        exposed = max(0.0, makespan - compute_ms)
        # a queueing artifact can push `exposed` past the serial comm
        # total on contended links; clamp so hidden stays >= 0
        exposed = min(exposed, comm_total) if comm_total > 0 else exposed
        hidden = max(0.0, comm_total - exposed)
        return {"makespan_s": float(makespan),
                "compute_makespan_s": float(compute_ms),
                "comm_total_s": float(comm_total),
                "exposed_comm_s": float(exposed),
                "hidden_comm_s": float(hidden)}

    def graph_cost(self, graph: Graph,
                   in_pins=None, out_pin=None) -> GraphCost:
        key = ("tg", graph.hash(),
               tuple(sorted((in_pins or {}).items())), out_pin,
               self.mem_lambda)
        hit = self._cache.get(key)
        if hit is not None:
            return hit
        # makespan/mem are pin-independent: simulate once per graph
        sim_key = ("tg-sim", graph.hash())
        sim = self._cache.get(sim_key)
        if sim is None:
            builder = TaskGraphBuilder(self.cost, self.dmesh.num_devices)
            sim = builder.build(graph)
            self._cache[sim_key] = sim
            # the expansion also produced the serial comm total —
            # cache it so overlap_estimate skips the real-side rebuild
            self._cache.setdefault(
                ("tg-overlap", graph.hash()),
                (sim[0], builder.comm_seconds))
        makespan, _ = sim
        # isolate the pin-dependent analytic terms (boundary resharding):
        # collectives internal to the graph are already in the makespan
        base_pinned = super().graph_cost(graph, in_pins, out_pin)
        base_free = super().graph_cost(graph)
        pin_penalty = max(0.0, base_pinned.total - base_free.total)
        total = makespan + pin_penalty \
            + self.mem_lambda * base_pinned.peak_memory
        gc = GraphCost(total, makespan, base_pinned.xfer, base_pinned.sync,
                       base_pinned.peak_memory)
        self._cache[key] = gc
        return gc
