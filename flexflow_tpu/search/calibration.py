"""Measurement-grounded cost-model calibration (v2).

The analytic cost model in ``search/costmodel.py`` prices compute from
datasheet FLOP/s and collectives from machine-model link constants. Both
are host-blind: on the CPU simulation substrate (and on any
oversubscribed host) they miss three effects the r05 fidelity study
showed to dominate the prediction error (VERDICT r5 "What's weak" #1):

  - **host dispatch overhead** — every jitted call pays a fixed host
    cost that dwarfs tiny per-shard kernels (the bert 2.06x-vs-5.85x
    under-prediction at per-device batch 1);
  - **memory bandwidth** — the dlrm/xdl ~3x over-prediction traces to a
    shared host-memory ceiling the per-device HBM constant cannot see;
  - **parallel efficiency** — N "devices" of a virtual CPU mesh share a
    few physical cores, so N concurrent shard tasks do NOT run N-way
    parallel; the simulator's makespan must know the real speedup.

This module microbenchmarks all three on the live backend, plus the real
XLA collectives (all-reduce / all-gather / reduce-scatter / all-to-all
over mesh axes) at import-time shapes, and persists every measurement in
an on-disk table keyed by ``(backend, kind, dtype, shape-class,
axis-size)`` — the same cross-process amortization pattern as
``utils/compilation_cache.py``: a fresh process reuses the table with
zero re-measurements. Hierarchical per-link + per-collective calibration
follows the cost-model decomposition of arXiv:2110.10548 /
arXiv:2112.01075 (separate collective and redistribution terms per
fabric level).

Opt-in: ``FFConfig.calibration_v2 = "true"`` or ``FF_CALIBRATION_V2=1``
in the environment ("auto" honors the env var only, so default search
behavior — and every recorded benchmark — is unchanged unless asked).
Force re-calibration by deleting ``<repo>/.ffcache/calibration_v2.json``
(see docs/calibration.md).
"""
from __future__ import annotations

import dataclasses
import json
import math
import os
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ..obs import events as obs_events

_DEFAULT_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), ".ffcache")

#: collective payload sizes measured per (kind, axis-size): the small
#: class pins the fixed dispatch/rendezvous floor that dominates small
#: transfers (the r05 mlp searched-cost was under-priced ~85x for lack
#: of it), the larger classes the per-byte regime
COLLECTIVE_SIZES = (1 << 16, 1 << 20, 1 << 23)

COLLECTIVES = ("all_reduce", "all_gather", "reduce_scatter", "all_to_all")

#: ring-attention hop payloads measured over the dedicated seq axis
#: (coll_ppermute rows): one neighbor-exchange of the local K/V block
PPERMUTE_SIZES = (1 << 16, 1 << 20, 1 << 23)

#: attention-core payload sizes for the kernel-impl rows
#: (op_attention@<impl>): q bytes at (b=1, h=8, d=64) — the two classes
#: span s=128..512; larger contexts extrapolate on the measured pair.
#: Kept small on purpose: the flash row times the Pallas kernel in
#: interpret mode on CPU hosts, which is minutes-slow at long s.
ATTN_IMPL_SIZES = (1 << 16, 1 << 20)


def shape_class(nbytes: int) -> int:
    """Power-of-two size bucket: measurements and lookups for payloads
    within the same factor-of-2 band share one table entry."""
    if nbytes <= 1:
        return 1
    return 1 << int(round(math.log2(nbytes)))


#: process-wide staleness generation: bumped whenever any table
#: instance rewrites a stale sidecar (mark_stale / put superseding a
#: mark). Every ``CalibrationTable`` revalidates its in-memory sidecar
#: set against this counter (and the sidecar file's mtime, for marks
#: written by ANOTHER process), and every ``MeshCalibration`` drops its
#: lookup memos — so an in-process stale mark written by the drift
#: detector through a fresh table object is a miss IMMEDIATELY, not
#: after the next process restart.
_stale_gen = 0


def stale_generation() -> int:
    return _stale_gen


class CalibrationTable:
    """Persistent microbenchmark results, one JSON file per cache dir.

    Every entry is keyed ``backend|kind|dtype|shape_class|axis_size`` so
    a value measured on one backend (or for one dtype) can never be
    served for another. ``measured`` counts live microbenchmarks run by
    THIS process — a second process loading a warm table must report 0.
    """

    def __init__(self, cache_dir: Optional[str] = None):
        self._cache_dir = cache_dir or _DEFAULT_DIR
        self._data: Optional[Dict[str, float]] = None
        self._stale: Optional[set] = None
        self._stale_seen_gen = -1      # _stale_gen at last sidecar read
        self._stale_mtime = None       # sidecar mtime_ns at last read
        self.measured = 0          # live measurements this process

    @property
    def path(self) -> str:
        return os.path.join(self._cache_dir, "calibration_v2.json")

    @property
    def stale_path(self) -> str:
        """Sidecar naming rows the drift detector voted out: a stale
        key answers like a miss (so exactly IT is re-measured on the
        next calibration load) while every healthy row keeps serving
        warm — the surgical alternative to deleting the whole table."""
        return os.path.join(self._cache_dir, "calibration_v2_stale.json")

    @staticmethod
    def key(backend: str, kind: str, dtype: str = "-",
            sclass: int = 0, axis_size: int = 0) -> str:
        return f"{backend}|{kind}|{dtype}|{sclass}|{axis_size}"

    def _load(self) -> Dict[str, float]:
        if self._data is None:
            try:
                with open(self.path) as f:
                    self._data = {k: float(v)
                                  for k, v in json.load(f).items()}
            except Exception:
                self._data = {}
        return self._data

    def _stale_sidecar_mtime(self):
        try:
            return os.stat(self.stale_path).st_mtime_ns
        except OSError:
            return None

    def _load_stale(self) -> set:
        # revalidate against the process-wide staleness generation (a
        # mark written through ANY table object this process created)
        # and the sidecar mtime (a mark written by another process) —
        # a live table must treat fresh stale marks as misses without
        # waiting for a restart
        mt = self._stale_mtime
        if self._stale is not None and self._stale_seen_gen != _stale_gen:
            mt = self._stale_sidecar_mtime()
        if self._stale is None or mt != self._stale_mtime:
            try:
                with open(self.stale_path) as f:
                    self._stale = {str(k) for k in json.load(f)}
            except Exception:  # noqa: BLE001 — no sidecar = none stale
                self._stale = set()
            self._stale_mtime = self._stale_sidecar_mtime()
        self._stale_seen_gen = _stale_gen
        return self._stale

    def _write_stale(self) -> None:
        global _stale_gen
        _stale_gen += 1
        self._stale_seen_gen = _stale_gen
        try:
            os.makedirs(self._cache_dir, exist_ok=True)
            tmp = self.stale_path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(sorted(self._stale or set()), f)
            os.replace(tmp, self.stale_path)
            self._stale_mtime = self._stale_sidecar_mtime()
        except Exception:  # noqa: BLE001 — persistence is best-effort
            pass

    def mark_stale(self, keys) -> int:
        """Mark full table keys (``backend|kind|dtype|sclass|axis``) as
        stale: they stop answering (get/entries skip them) until a fresh
        measurement re-files them via :meth:`put`. Returns how many of
        the keys actually exist in the table (unknown keys are ignored —
        a drift report from another machine's table must not poison
        this one)."""
        data = self._load()
        stale = self._load_stale()
        hit = 0
        for k in keys:
            if k in data:
                stale.add(k)
                hit += 1
        if hit:
            self._write_stale()
        return hit

    def stale_keys(self) -> List[str]:
        return sorted(self._load_stale())

    def get(self, backend: str, kind: str, dtype: str = "-",
            sclass: int = 0, axis_size: int = 0) -> Optional[float]:
        key = self.key(backend, kind, dtype, sclass, axis_size)
        if key in self._load_stale():
            return None
        return self._load().get(key)

    def put(self, backend: str, kind: str, dtype: str, sclass: int,
            axis_size: int, value: float) -> None:
        data = self._load()
        key = self.key(backend, kind, dtype, sclass, axis_size)
        data[key] = value
        stale = self._load_stale()
        if key in stale:
            # a fresh measurement supersedes the drift verdict
            stale.discard(key)
            self._write_stale()
        try:
            os.makedirs(self._cache_dir, exist_ok=True)
            tmp = self.path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(data, f)
            os.replace(tmp, self.path)
        except Exception:  # noqa: BLE001 — persistence is best-effort
            pass

    def get_or_measure(self, backend: str, kind: str, dtype: str,
                       sclass: int, axis_size: int,
                       fn: Callable[[], float]) -> Optional[float]:
        """Serve from the table; run ``fn`` (a microbenchmark) only on a
        genuine miss, recording the result for future processes."""
        hit = self.get(backend, kind, dtype, sclass, axis_size)
        if hit is not None:
            obs_events.counter("calibration.cache_hits")
            return hit
        obs_events.counter("calibration.cache_misses")
        try:
            with obs_events.span("calibration.measure", kind=kind,
                                 axis_size=axis_size, sclass=sclass):
                v = float(fn())
        except Exception:  # noqa: BLE001 — calibration is best-effort
            return None
        self.measured += 1
        self.put(backend, kind, dtype, sclass, axis_size, v)
        return v

    def entries(self, backend: str, kind: str, dtype: str = "-",
                axis_size: int = 0) -> List[Tuple[int, float]]:
        """(shape_class, value) pairs for one (backend, kind, dtype,
        axis-size), sorted by shape class — interpolation input."""
        prefix = f"{backend}|{kind}|{dtype}|"
        suffix = f"|{axis_size}"
        stale = self._load_stale()
        out = []
        for k, v in self._load().items():
            if k.startswith(prefix) and k.endswith(suffix) \
                    and k not in stale:
                out.append((int(k[len(prefix):-len(suffix)]), v))
        return sorted(out)

    # ------------------------------------------------------------------
    # targeted in-process re-measurement (the drift detector's heal)
    # ------------------------------------------------------------------
    def remeasure_stale(self, dmesh=None, keys=None) -> Dict[str, float]:
        """Re-measure exactly the stale-marked rows on the live backend,
        in-process — no table delete, no restart. Each re-measured value
        is re-filed via :meth:`put` (which clears its stale mark), so
        attached ``MeshCalibration`` objects answer from the fresh row
        on their next lookup. Rows this process cannot realize — another
        backend's keys, collective degrees with no matching mesh-axis
        prefix, ring rows without a seq axis — are left stale for a
        process that can. Returns ``{key: seconds}`` for the rows
        actually re-measured; ``keys`` narrows the work to a subset
        (default: every stale key)."""
        import jax
        backend = jax.default_backend()
        todo = [str(k) for k in (keys if keys is not None
                                 else self.stale_keys())]
        stale = self._load_stale()
        mesh = dmesh.mesh if dmesh is not None else None
        axis_names = list(mesh.shape.keys()) if mesh is not None else []
        try:
            axis_tiers = dict(dmesh.axis_tiers) \
                if dmesh is not None else {}
        except Exception:  # noqa: BLE001 — tiers are best-effort
            axis_tiers = {}
        out: Dict[str, float] = {}
        with obs_events.span("calibration.remeasure_stale",
                             n_stale=len(todo)):
            for key in todo:
                if key not in stale:
                    continue
                parts = key.split("|")
                if len(parts) != 5 or parts[0] != backend:
                    continue
                _, kind, dtype, sc_s, ax_s = parts
                try:
                    sclass, axis_size = int(sc_s), int(ax_s)
                except ValueError:
                    continue
                try:
                    with obs_events.span("calibration.measure",
                                         kind=kind, axis_size=axis_size,
                                         sclass=sclass):
                        v = self._remeasure_one(
                            kind, dtype, sclass, axis_size, dmesh,
                            mesh, axis_names, axis_tiers)
                except Exception:  # noqa: BLE001 — best-effort per row
                    v = None
                if v is None:
                    continue
                self.measured += 1
                # filed under the PARSED key (not the re-derived shape
                # class): the stale row itself must be superseded
                self.put(backend, kind, dtype, sclass, axis_size,
                         float(v))
                out[key] = float(v)
        if out:
            try:
                from ..obs.metrics_registry import REGISTRY
                REGISTRY.counter(
                    "ff_calibration_rows_remeasured_total",
                    "Stale calibration rows re-measured in-process by "
                    "remeasure_stale").inc(len(out))
            except Exception:  # noqa: BLE001 — metering is best-effort
                pass
        return out

    def _remeasure_one(self, kind: str, dtype: str, sclass: int,
                       axis_size: int, dmesh, mesh, axis_names,
                       axis_tiers) -> Optional[float]:
        """One stale row's fresh measurement (seconds / bytes-per-s /
        efficiency), or None when this process cannot realize it."""
        if kind == "host_dispatch":
            return _bench_dispatch()
        if kind == "host_membw":
            return _bench_membw()
        if kind == "parallel_eff":
            if mesh is None or dmesh.num_devices != axis_size:
                return None
            return _bench_parallel_eff(mesh, axis_size)
        if kind.startswith("op_attention@"):
            impl = kind.split("@", 1)[1]
            seq_axis = getattr(dmesh, "seq_axis", None) \
                if dmesh is not None else None
            if impl == "ring":
                if mesh is None or seq_axis is None \
                        or int(mesh.shape[seq_axis]) != axis_size:
                    return None
                s = _attn_seq_len(sclass, axis_size)
            else:
                s = _attn_seq_len(sclass)
            return _bench_attention_impl(impl, s, mesh=mesh,
                                         seq_axis=seq_axis)
        if kind.startswith("coll_"):
            if mesh is None:
                return None
            coll, _, tier = kind[len("coll_"):].partition("@")
            tier = tier or None
            if coll == "ppermute":
                # single-axis ring: the dedicated seq axis when its
                # size matches, else the innermost axis of that size
                ring_ax = getattr(dmesh, "seq_axis", None)
                if ring_ax is None \
                        or int(mesh.shape[ring_ax]) != axis_size:
                    ring_ax = next(
                        (a for a in reversed(axis_names)
                         if int(mesh.shape[a]) == axis_size), None)
                if ring_ax is None:
                    return None
                tiers = {axis_tiers.get(ring_ax, "ici")}
                if tier is not None and tiers != {tier}:
                    return None
                v = _bench_collective(mesh, "ppermute", sclass,
                                      axes=(ring_ax,), dtype=dtype)
            else:
                if coll not in COLLECTIVES:
                    return None
                # realize the degree as a mesh-axis prefix product —
                # the same grid _calibrate_mesh measured
                p, n_axes = 1, None
                for k, a in enumerate(axis_names, start=1):
                    p *= int(mesh.shape[a])
                    if p == axis_size:
                        n_axes = k
                        break
                    if p > axis_size:
                        break
                if n_axes is None:
                    return None
                tiers = {axis_tiers.get(a, "ici")
                         for a in axis_names[:n_axes]}
                if tier is not None and tiers != {tier}:
                    return None
                v = _bench_collective(mesh, coll, sclass,
                                      n_axes=n_axes, dtype=dtype)
            return v * _link_degradation_factor(tiers)
        return None


def _link_degradation_factor(tiers) -> float:
    """Max registered chaos-drill bandwidth degradation across
    ``tiers`` (resilience/faults.py ``degrade_link@N:tier:factor``).
    The CPU-sim substrate cannot physically slow a modeled link, so the
    timing path scales measured collective seconds by this factor
    instead — a measurement taken while a drill is active reflects the
    degraded fabric exactly as a real slow link would."""
    try:
        from ..resilience.faults import link_degradation
        return max([float(link_degradation(t)) for t in tiers]
                   or [1.0])
    except Exception:  # noqa: BLE001 — no drill machinery = healthy
        return 1.0


# ----------------------------------------------------------------------
# microbenchmarks (each returns seconds; device->host fetch = sync
# barrier, since block_until_ready does not block on tunneled backends)
# ----------------------------------------------------------------------

def _timed(f, args, warmup: int = 2, repeats: int = 5) -> float:
    """MIN over repeats: host-load noise is one-sided (contention only
    adds time), and a polluted measurement persisted to the table is
    served forever — the minimum is the stable estimator here."""
    for _ in range(warmup):
        float(np.asarray(f(*args)).ravel()[0])
    ts = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        float(np.asarray(f(*args)).ravel()[0])
        ts.append(time.perf_counter() - t0)
    return float(min(ts))


def _bench_dispatch() -> float:
    """Fixed per-call host cost of one trivial jitted op (trace/dispatch/
    fetch) — the floor under every per-shard kernel."""
    import jax
    import jax.numpy as jnp
    f = jax.jit(lambda x: x + 1.0)
    return _timed(f, (jnp.zeros((8,), jnp.float32),), repeats=9)


def _bench_membw(nbytes: int = 64 << 20) -> float:
    """Effective memory bandwidth (bytes/s) of a streaming read at
    ``nbytes`` working set — the shared ceiling concurrent shards hit.
    The jitted body REDUCES to a scalar so the sync fetch moves 4
    bytes: fetching the full output would time the device-to-host link
    (PCIe/tunnel), not memory, on accelerator backends."""
    import jax
    import jax.numpy as jnp
    n = nbytes // 4
    x = jnp.arange(n, dtype=jnp.float32)
    f = jax.jit(lambda x: jnp.sum(x * 1.0001 + 1.0))
    dt = _timed(f, (x,), repeats=5)
    if dt < 1e-3:
        # a 64 MiB stream cannot finish in under a millisecond on any
        # current part — the work was eliminated or the clock lied;
        # failing here makes the caller fall back to the spec constant
        # instead of persisting a physically impossible bandwidth
        raise RuntimeError(f"membw bench eliminated (dt={dt:.2e}s)")
    return nbytes / dt


def _bench_parallel_eff(mesh, n_dev: int) -> float:
    """Measured efficiency of ``n_dev`` concurrent shard tasks: time one
    matmul on a single device, then the SAME per-shard matmul replicated
    across every mesh device via shard_map. On real hardware the wall
    time is flat (eff ~ 1); on an oversubscribed virtual CPU mesh the
    shards serialize onto the physical cores (eff ~ cores / n_dev)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..utils.jax_compat import shard_map
    m = 384
    a = jnp.ones((m, m), jnp.float32)

    def chain(x):
        for _ in range(4):
            x = x @ x * 1e-3
        return jnp.sum(x)[None]      # (1,): concatenable per-shard value

    t1 = _timed(jax.jit(chain), (a,), repeats=3)
    axes = tuple(mesh.axis_names)
    big = jnp.ones((m * n_dev, m), jnp.float32)
    big = jax.device_put(big, NamedSharding(mesh, P(axes)))

    def sharded(x):
        return shard_map(chain, mesh=mesh,
                         in_specs=P(axes), out_specs=P(axes))(x)

    tn = _timed(jax.jit(sharded), (big,), repeats=3)
    return float(min(1.0, max(1.0 / n_dev, t1 / max(tn, 1e-9))))


def _bench_collective(mesh, coll: str, nbytes: int,
                      n_axes: Optional[int] = None,
                      dtype: str = "float32",
                      axes: Optional[Tuple[str, ...]] = None) -> float:
    """One logical collective over the first ``n_axes`` mesh axes (all
    by default) at ``nbytes`` payload per group, on the live backend.
    With a subset, the remaining axes run the same collective
    concurrently in independent groups — exactly how a sub-degree
    collective executes inside a larger mesh, contention included.
    ``dtype`` sets the wire payload type — the quantized-collective
    rows (int8/fp8) time the same logical collectives at narrow
    payloads; a backend that cannot lower them raises and the caller
    records nothing (itemsize-scaled float32 rows stand in)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ..utils.jax_compat import shard_map
    jdt = {"float32": jnp.float32, "int8": jnp.int8,
           "float8_e4m3": jnp.float8_e4m3fn,
           "float8_e5m2": jnp.float8_e5m2}[dtype]
    isz = np.dtype(jdt).itemsize
    all_axes = tuple(mesh.axis_names)
    coll_axes = axes if axes is not None \
        else (all_axes[:n_axes] if n_axes else all_axes)
    axes = all_axes
    n_dev = int(np.prod([mesh.shape[a] for a in axes]))
    deg = int(np.prod([mesh.shape[a] for a in coll_axes]))
    if coll == "ppermute":
        # ring-hop exchange: every device sends its WHOLE local block
        # to its +1 neighbor on the ring axis — ``nbytes`` is the
        # per-device (= per-hop per-link) payload
        m = max(nbytes // isz * n_dev, n_dev * n_dev)
    else:
        # ``nbytes`` is the PER-GROUP payload (what xfer_cost queries);
        # a subset collective has n_dev/deg concurrent groups, so the
        # global array scales up to keep each group's volume at nbytes
        m = max(nbytes // isz * (n_dev // deg), n_dev * n_dev)
    m -= m % (n_dev * n_dev)       # shardable + all_to_all reshapable
    x = jnp.ones((m,), jdt)

    def acc(y):
        # per-shard (1,) value; integer/fp8 payloads fold in fp32 so
        # the sync-fetch scalar is well-defined on every backend
        return jnp.sum(y.astype(jnp.float32))[None]

    # every body returns a (1,) per-shard value gathered with
    # out_specs=P(axes): no replication claim, works for all kinds
    if coll == "all_reduce":
        def body(xl):
            return acc(jax.lax.psum(xl, coll_axes))
    elif coll == "all_gather":
        def body(xl):
            return acc(jax.lax.all_gather(xl, coll_axes, tiled=True))
    elif coll == "reduce_scatter":
        def body(xl):
            return acc(jax.lax.psum_scatter(
                xl, coll_axes, scatter_dimension=0, tiled=True))
    elif coll == "all_to_all":
        def body(xl):
            return acc(jax.lax.all_to_all(
                xl.reshape(deg, -1), coll_axes, 0, 0))
    elif coll == "ppermute":
        # one ring hop (the unit step of ring attention's K/V
        # rotation): a single named axis only — a ring over a
        # flattened multi-axis prefix is not a neighbor exchange
        if len(coll_axes) != 1:
            raise ValueError("ppermute benches a single mesh axis")
        ax = coll_axes[0]
        perm = [(i, (i + 1) % deg) for i in range(deg)]

        def body(xl):
            return acc(jax.lax.ppermute(xl, ax, perm))
    else:
        raise ValueError(coll)

    f = jax.jit(shard_map(body, mesh=mesh, in_specs=P(axes),
                          out_specs=P(axes)))
    return _timed(f, (x,), repeats=3)


def _attn_seq_len(nbytes: int, deg: int = 1) -> int:
    """Sequence length whose q payload is ``nbytes`` at the canonical
    bench geometry (b=1, h=8, d=64, f32), rounded so flash blocks and
    ring chunks both divide."""
    s = max(nbytes // (4 * 8 * 64), 128)
    step = 128 * max(deg, 1)
    return max(s - s % step, step)


def _bench_attention_impl(impl: str, s: int, mesh=None,
                          seq_axis: Optional[str] = None) -> float:
    """Forward time of one attention core at sequence length ``s`` and
    the canonical bench geometry (b=1, h=8, d=64, f32) — the measured
    anchor for the searchable kernel tier (``op_attention@<impl>``
    rows). ``xla`` is the materialized-scores reference, ``flash`` the
    Pallas kernel (interpret mode off-TPU), ``ring`` one shard_map over
    the mesh's seq axis with ppermute hops (requires
    ``mesh``/``seq_axis``)."""
    import jax
    import jax.numpy as jnp

    b, h, d = 1, 8, 64
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((b, h, s, d)) * 0.02, jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, h, s, d)) * 0.02, jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, h, s, d)) * 0.02, jnp.float32)
    sc = 1.0 / math.sqrt(d)

    if impl == "xla":
        def f(q_, k_, v_):
            sm = jnp.einsum("bhqd,bhkd->bhqk", q_, k_) * sc
            i = jax.lax.broadcasted_iota(jnp.int32, (s, s), 0)
            j = jax.lax.broadcasted_iota(jnp.int32, (s, s), 1)
            sm = jnp.where(j <= i, sm, -1e9)
            p = jax.nn.softmax(sm, axis=-1)
            return jnp.sum(jnp.einsum("bhqk,bhkd->bhqd", p, v_))[None]
    elif impl == "flash":
        from ..kernels import flash_attention

        def f(q_, k_, v_):
            o = flash_attention(
                q_, k_, v_, causal=True,
                interpret=None if jax.default_backend() == "tpu"
                else True)
            return jnp.sum(o.astype(jnp.float32))[None]
    elif impl == "ring":
        if mesh is None or seq_axis is None:
            raise ValueError("ring bench needs a mesh with a seq axis")
        from jax.sharding import PartitionSpec as P

        from ..kernels import ring_attention
        from ..utils.jax_compat import shard_map
        spec = P(None, None, seq_axis, None)

        def body(q_, k_, v_):
            o = ring_attention(q_, k_, v_, seq_axis, causal=True)
            return jnp.sum(o.astype(jnp.float32))[None]

        inner = shard_map(body, mesh=mesh, in_specs=(spec,) * 3,
                          out_specs=P(seq_axis), check_vma=False)

        def f(q_, k_, v_):
            return jnp.sum(inner(q_, k_, v_))[None]
    else:
        raise ValueError(impl)

    return _timed(jax.jit(f), (q, k, v), repeats=3)


def calibrate_kernel_impls(dmesh=None,
                           table: Optional[CalibrationTable] = None,
                           cache_dir: Optional[str] = None,
                           impls: Tuple[str, ...] = ("xla", "flash",
                                                     "ring"),
                           sizes: Tuple[int, ...] = ATTN_IMPL_SIZES
                           ) -> CalibrationTable:
    """Measure (or warm-load) the kernel-impl rows the searchable
    kernel tier prices from: ``op_attention@<impl>`` keyed by the q
    payload's shape class (``ring`` additionally by the seq degree).
    Persisted like every other calibration row — a warm table makes
    this call measurement-free. Called by ``FFModel._plan_kernels``
    (not the base ``calibrate_mesh``) so searches without the kernel
    tier pay nothing new."""
    import jax
    tab = table if table is not None else CalibrationTable(cache_dir)
    backend = jax.default_backend()
    mesh = dmesh.mesh if dmesh is not None else None
    seq_axis = getattr(dmesh, "seq_axis", None) if dmesh is not None \
        else None
    for impl in impls:
        deg = 0
        if impl == "ring":
            if mesh is None or seq_axis is None:
                continue               # no seq axis: no ring row
            deg = int(mesh.shape[seq_axis])
            # ring's chunking floor (128*deg) collapses the small size
            # classes onto one sequence length — bench two DISTINCT
            # lengths so the row interpolates instead of degenerating
            # to a single point
            seqs = (128 * deg, 256 * deg)
        else:
            seqs = tuple(_attn_seq_len(nb) for nb in sizes)
        for s in sorted(set(seqs)):
            # keyed by the ACTUAL q payload of the benched shape, not
            # the requested class — ring's rounding must not file an
            # s=512 measurement under the s=128 class
            qbytes = 4 * 8 * 64 * s
            tab.get_or_measure(
                backend, f"op_attention@{impl}", "float32",
                shape_class(qbytes), deg,
                lambda i=impl, n=s: _bench_attention_impl(
                    i, n, mesh=mesh, seq_axis=seq_axis))
    return tab


# ----------------------------------------------------------------------
# the attachable calibration object
# ----------------------------------------------------------------------

@dataclasses.dataclass
class MeshCalibration:
    """Measured host + collective terms the cost model consults.

    ``collective_time`` answers from the persisted table by log-log
    interpolation between the measured shape classes of the matching
    (backend, collective, dtype, axis-size) row; a query for a degree
    that was never measured returns None and the cost model falls back
    to its fitted/analytic path.
    """
    backend: str
    dispatch_s: Optional[float] = None
    mem_bw: Optional[float] = None
    parallel_eff: Dict[int, float] = dataclasses.field(default_factory=dict)
    table: Optional[CalibrationTable] = None
    dtype: str = "float32"
    # lookup memos — collective_time sits inside xfer_cost, the
    # search's hottest evaluator loop (1e4-1e6 calls per search), and
    # the table only changes when a drift verdict lands, so the
    # full-table key scans are done once per (coll, degree) per
    # staleness generation (stale marks / re-measurements drop them)
    _pts: Dict = dataclasses.field(default_factory=dict, repr=False)
    _degs: Dict = dataclasses.field(default_factory=dict, repr=False)
    _seen_gen: int = dataclasses.field(default=-1, repr=False)

    def _sync_gen(self) -> None:
        if self._seen_gen != _stale_gen:
            self._pts.clear()
            self._degs.clear()
            self._seen_gen = _stale_gen

    def _points(self, coll: str, degree: int,
                tier: Optional[str] = None,
                dtype: Optional[str] = None) -> List[Tuple[int, float]]:
        """Measured (shape_class, seconds) points for one collective at
        one degree. ``tier`` selects the tier-keyed rows
        (``coll_<kind>@<tier>``, written by :func:`calibrate_mesh` on
        multi-tier meshes); flat rows remain the fallback so warm
        pre-tier tables keep answering without re-measurement.
        ``dtype`` selects wire-dtype rows (``int8``/``float8_*``,
        measured by :func:`calibrate_mesh` when quantized collectives
        are enabled) instead of the default element dtype."""
        self._sync_gen()
        kind = f"{coll}@{tier}" if tier else coll
        dt = dtype or self.dtype
        key = (kind, degree, dt)
        hit = self._pts.get(key)
        if hit is None:
            hit = self.table.entries(self.backend, f"coll_{kind}",
                                     dt, axis_size=degree)
            self._pts[key] = hit
        return hit

    def efficiency(self, n_shards: int) -> float:
        """Measured parallel efficiency for ``n_shards`` concurrent shard
        tasks (1.0 = ideal). Unmeasured widths interpolate between the
        measured ones (ideal at 1)."""
        if n_shards <= 1 or not self.parallel_eff:
            return 1.0
        if n_shards in self.parallel_eff:
            return self.parallel_eff[n_shards]
        pts = sorted(self.parallel_eff.items())
        lo_n, lo_e = 1, 1.0
        for n, e in pts:
            if n >= n_shards:
                # linear in log(n): eff falls off as oversubscription grows
                t = ((math.log(n_shards) - math.log(lo_n))
                     / max(math.log(n) - math.log(lo_n), 1e-9))
                return lo_e + t * (e - lo_e)
            lo_n, lo_e = n, e
        return pts[-1][1]          # wider than measured: worst measured

    def _degrees_measured(self, coll: str) -> List[int]:
        if self.table is None:
            return []
        self._sync_gen()
        hit = self._degs.get(coll)
        if hit is None:
            prefix = f"{self.backend}|coll_{coll}|{self.dtype}|"
            stale = self.table._load_stale()
            out = set()
            for k in self.table._load():
                if k.startswith(prefix) and k not in stale:
                    out.add(int(k.rsplit("|", 1)[1]))
            hit = sorted(out)
            self._degs[coll] = hit
        return hit

    def collective_time(self, coll: str, degree: int, nbytes: float,
                        tier: Optional[str] = None,
                        dtype: Optional[str] = None) -> Optional[float]:
        if self.table is None or degree <= 1 or nbytes <= 0:
            return None
        if dtype is not None:
            # wire-dtype rows are measured opportunistically (quantized
            # collectives enabled): STRICT like tier rows — a miss
            # returns None and the caller falls back to the
            # itemsize-scaled float32 query, never a wrong row
            pts = self._points(coll, degree, tier, dtype=dtype)
            if not pts:
                return None
            return self._interp(pts, nbytes)
        if tier is not None:
            # STRICT: a tier-scoped query answers only from rows
            # measured for that tier. Falling back to the flat rows
            # here would price a DCN leg at the innermost fabric's
            # measured speed (~20x under on the virtual 2-slice config)
            # — the caller's fallback is the tier's machine-model
            # constants, not a wrong measurement. Flat (tier=None)
            # queries keep the whole warm table, so pre-tier caches
            # still answer with zero re-measurement.
            pts = self._points(coll, degree, tier)
            if not pts:
                return None
        else:
            pts = self._points(coll, degree)
        if not pts:
            # nearest measured degree (log distance): a degree-3 query
            # on a mesh measured at {2, 4, 8} answers from the closest
            # curve rather than falling to the host-blind analytic model
            degs = self._degrees_measured(coll)
            if not degs:
                return None
            near = min(degs, key=lambda d: abs(math.log(d)
                                               - math.log(degree)))
            if not (0.5 <= near / degree <= 2.0):
                return None          # too far to stand in
            pts = self._points(coll, near)
        return self._interp(pts, nbytes)

    def op_time(self, kind: str, nbytes: float,
                degree: int = 0) -> Optional[float]:
        """Measured time of one kernel-impl row (``op_<kind>`` —
        e.g. ``attention@ring``), interpolated across the measured
        shape classes. ``degree`` keys the rows that depend on a mesh
        axis size (ring's seq degree); 0 for degree-free impls. None =
        never measured — the cost model falls back to its analytic
        curve for that impl."""
        if self.table is None or nbytes <= 0:
            return None
        self._sync_gen()
        key = (f"op:{kind}", degree, self.dtype)
        pts = self._pts.get(key)
        if pts is None:
            pts = self.table.entries(self.backend, f"op_{kind}",
                                     self.dtype, axis_size=degree)
            self._pts[key] = pts
        if not pts:
            return None
        return self._interp(pts, nbytes)

    @staticmethod
    def _interp(pts: List[Tuple[int, float]], nbytes: float) -> float:
        # at/below the smallest measured class the fixed dispatch/
        # rendezvous floor dominates: CLAMP, never extrapolate downward
        # (a 16 KiB collective does not cost 16/64 of the 64 KiB one)
        if nbytes <= pts[0][0]:
            return pts[0][1]
        if len(pts) == 1:
            sc, t = pts[0]
            return t * nbytes / sc   # single point: linear in volume
        # log-log interpolation (upward extrapolation on the top pair)
        xs = [math.log(sc) for sc, _ in pts]
        ys = [math.log(max(t, 1e-12)) for _, t in pts]
        x = math.log(max(nbytes, 1.0))
        i = 1
        while i < len(xs) - 1 and xs[i] < x:
            i += 1
        slope = (ys[i] - ys[i - 1]) / max(xs[i] - xs[i - 1], 1e-9)
        y = ys[i - 1] + slope * (x - xs[i - 1])
        return math.exp(y)

    def row_key(self, coll: str, degree: int, nbytes: float,
                tier: Optional[str] = None) -> Optional[str]:
        """Full table key (``backend|kind|dtype|shape_class|axis_size``)
        of the measured row anchoring a :meth:`collective_time` answer —
        the nearest measured shape class at the answering degree. The
        drift detector (obs/drift.py) attributes an out-of-band
        predicted-vs-measured ratio to exactly this row and marks it
        stale. None = the query would not answer from the table (the
        prediction came from the analytic model instead)."""
        if self.table is None or degree <= 1 or nbytes <= 0:
            return None
        kind = f"{coll}@{tier}" if tier else coll
        pts = self._points(coll, degree, tier)
        deg = degree
        if not pts and tier is None:
            degs = self._degrees_measured(coll)
            if degs:
                near = min(degs, key=lambda d: abs(math.log(d)
                                                   - math.log(degree)))
                if 0.5 <= near / degree <= 2.0:
                    deg = near
                    pts = self._points(coll, near)
        if not pts:
            return None
        sc = min(pts, key=lambda p: abs(
            math.log(max(p[0], 1)) - math.log(max(nbytes, 1.0))))[0]
        return CalibrationTable.key(self.backend, f"coll_{kind}",
                                    self.dtype, sc, deg)

    def collective_marginal(self, coll: str, degree: int,
                            nbytes: float,
                            dtype: Optional[str] = None
                            ) -> Optional[float]:
        """Per-byte MARGINAL cost of a collective — the measured curve's
        top-range slope times the volume, with the fixed dispatch/
        rendezvous floor amortized away. This prices per-op gradient
        all-reduces: XLA's all-reduce combiner coalesces the per-layer
        reductions of a training step into a few large collectives, so
        the executed program pays the floor once, not once per layer —
        charging it per op made every many-layer DP baseline look
        ~per-layer-floor too expensive and inverted the searched-vs-DP
        ranking on dense tower models (candle/mlp)."""
        if self.table is None or degree <= 1 or nbytes <= 0:
            return None
        full = self.collective_time(coll, degree, nbytes, dtype=dtype)
        if full is None:
            return None
        pts = self._points(coll, degree, dtype=dtype)
        if dtype is not None and len(pts) < 2:
            # wire-dtype rows: no nearest-degree stand-in (strict, like
            # tier rows) — fall back to the top point's average
            return full
        if not pts:
            degs = self._degrees_measured(coll)
            if not degs:
                return full
            near = min(degs, key=lambda d: abs(math.log(d)
                                               - math.log(degree)))
            pts = self._points(coll, near)
        if len(pts) < 2:
            return full
        (s1, t1), (s2, t2) = pts[-2], pts[-1]
        slope = (t2 - t1) / max(s2 - s1, 1.0)
        if slope <= 0.0:
            # non-monotone measured pair (transient load during the
            # smaller bench, persisted forever): fall back to the top
            # point's average per-byte cost rather than pricing every
            # gradient all-reduce at zero
            slope = t2 / max(s2, 1.0)
        return min(full, slope * nbytes)


def calibrate_mesh(dmesh=None, cache_dir: Optional[str] = None,
                   collectives: Tuple[str, ...] = COLLECTIVES,
                   sizes: Tuple[int, ...] = COLLECTIVE_SIZES,
                   table: Optional[CalibrationTable] = None,
                   wire_dtypes: Tuple[str, ...] = ()
                   ) -> MeshCalibration:
    """Measure (or load) every calibration term for the live backend and
    the given mesh. Persisted measurements are reused across processes;
    a warm table makes this call measurement-free. ``wire_dtypes``
    additionally measures the quantized-collective payload rows
    (int8/fp8) for the same (collective, degree, size) grid — passed by
    the search when ``FFConfig.quantized_collectives`` is on; a backend
    that cannot lower a narrow collective records nothing and lookups
    fall back to itemsize-scaled float32 rows (docs/calibration.md)."""
    import jax
    with obs_events.span("search.calibrate_mesh"):
        return _calibrate_mesh(jax.default_backend(), dmesh, cache_dir,
                               collectives, sizes, table, wire_dtypes)


def _calibrate_mesh(backend, dmesh, cache_dir, collectives, sizes,
                    table, wire_dtypes=()) -> MeshCalibration:
    tab = table if table is not None else CalibrationTable(cache_dir)
    calib = MeshCalibration(backend=backend, table=tab)
    calib.dispatch_s = tab.get_or_measure(
        backend, "host_dispatch", "-", 0, 0, _bench_dispatch)
    calib.mem_bw = tab.get_or_measure(
        backend, "host_membw", "-", 0, 0, _bench_membw)
    if dmesh is not None and dmesh.num_devices > 1:
        n = dmesh.num_devices
        mesh = dmesh.mesh
        eff = tab.get_or_measure(backend, "parallel_eff", "-", 0, n,
                                 lambda: _bench_parallel_eff(mesh, n))
        if eff is not None:
            calib.parallel_eff[n] = eff
        # collective degrees: every prefix product of the mesh axes
        # (e.g. 2, 4, 8 on a 2x2x2 virtual mesh) — a sub-degree
        # collective runs concurrently in groups across the remaining
        # axes, exactly as the search would place it; capped at 4
        # degree points to bound the one-time measurement cost
        sizes_list = list(mesh.shape.values())
        degrees = []
        p = 1
        for k, s in enumerate(sizes_list, start=1):
            p *= s
            degrees.append((p, k))
        if len(degrees) > 4:
            keep = {0, len(degrees) - 1,
                    len(degrees) // 3, 2 * len(degrees) // 3}
            degrees = [d for i, d in enumerate(degrees) if i in keep]
        # tier annotation of each measured degree prefix: the outermost
        # tier the prefix axes touch (dmesh.axis_tiers; None when the
        # machine is single-tier — flat keys only, as before)
        axis_names = list(mesh.shape.keys())
        try:
            axis_tiers = dict(dmesh.axis_tiers)
            multi_tier = len(set(axis_tiers.values())) > 1
        except Exception:  # noqa: BLE001 — tiers are best-effort
            axis_tiers, multi_tier = {}, False
        for coll in collectives:
            for deg, n_axes in degrees:
                if deg <= 1:
                    continue
                prefix_tiers = {axis_tiers.get(a, "ici")
                                for a in axis_names[:n_axes]}
                # mirror ONLY pure single-tier prefixes: a mixed-tier
                # prefix's measurement filed under the outermost tier
                # would later answer a pure-tier query of a differently
                # shaped mesh sharing this table (the entries carry no
                # mesh identity) — the exact mispricing the strict tier
                # lookup exists to prevent
                tier = next(iter(prefix_tiers)) \
                    if multi_tier and len(prefix_tiers) == 1 else None
                for nbytes in sizes:
                    v = tab.get_or_measure(
                        backend, f"coll_{coll}", "float32",
                        shape_class(nbytes), deg,
                        lambda c=coll, s=nbytes, k=n_axes,
                        pt=frozenset(prefix_tiers):
                            _bench_collective(mesh, c, s, n_axes=k)
                            * _link_degradation_factor(pt))
                    # mirror the measurement under the tier key (no
                    # re-measurement): tier-aware lookups answer from
                    # coll_<kind>@<tier> first, flat stays the fallback
                    if v is not None and tier is not None and tab.get(
                            backend, f"coll_{coll}@{tier}", "float32",
                            shape_class(nbytes), deg) is None:
                        tab.put(backend, f"coll_{coll}@{tier}",
                                "float32", shape_class(nbytes), deg, v)
                    # quantized wire rows (same grid, narrow payload):
                    # keyed by the wire dtype so a float32 query can
                    # never answer from them; failures record nothing
                    # (get_or_measure swallows the raise) and the
                    # itemsize-scaled float32 rows stand in
                    for wdt in wire_dtypes:
                        vw = tab.get_or_measure(
                            backend, f"coll_{coll}", wdt,
                            shape_class(nbytes), deg,
                            lambda c=coll, s=nbytes, k=n_axes, w=wdt,
                            pt=frozenset(prefix_tiers):
                                _bench_collective(mesh, c, s, n_axes=k,
                                                  dtype=w)
                                * _link_degradation_factor(pt))
                        if vw is not None and tier is not None \
                                and tab.get(backend,
                                            f"coll_{coll}@{tier}", wdt,
                                            shape_class(nbytes),
                                            deg) is None:
                            tab.put(backend, f"coll_{coll}@{tier}",
                                    wdt, shape_class(nbytes), deg, vw)
        # ring-hop rows (coll_ppermute): ONE neighbor exchange over a
        # single mesh axis — the unit step ring attention's K/V
        # rotation pays (degree-1) times. Measured over the dedicated
        # seq axis when the mesh has one (that IS the ring), else the
        # innermost axis; tier-mirrored like the grouped collectives so
        # placement-path pricing stays strict per tier.
        ring_ax = getattr(dmesh, "seq_axis", None) or axis_names[-1]
        ring_deg = int(mesh.shape[ring_ax])
        if ring_deg > 1:
            ring_tier = axis_tiers.get(ring_ax, "ici") \
                if multi_tier else None
            for nbytes in PPERMUTE_SIZES:
                v = tab.get_or_measure(
                    backend, "coll_ppermute", "float32",
                    shape_class(nbytes), ring_deg,
                    lambda s=nbytes, a=ring_ax:
                        _bench_collective(mesh, "ppermute", s,
                                          axes=(a,))
                        * _link_degradation_factor(
                            {axis_tiers.get(a, "ici")}))
                if v is not None and ring_tier is not None and tab.get(
                        backend, f"coll_ppermute@{ring_tier}",
                        "float32", shape_class(nbytes),
                        ring_deg) is None:
                    tab.put(backend, f"coll_ppermute@{ring_tier}",
                            "float32", shape_class(nbytes), ring_deg, v)
    return calib


def calibration_enabled(cfg=None) -> bool:
    """Resolve the opt-in: config "true"/"false" wins; "auto" (and no
    config at all) honors the FF_CALIBRATION_V2 env var."""
    mode = str(getattr(cfg, "calibration_v2", "auto") or "auto").lower()
    if mode in ("true", "on", "1", "yes"):
        return True
    if mode in ("false", "off", "0", "no"):
        return False
    return os.environ.get("FF_CALIBRATION_V2", "").lower() \
        in ("1", "true", "yes", "on")
