"""Strategy optimization entry point: dispatches to the configured search.

Analog of the reference's ``Graph::graph_optimize_task``
(``src/runtime/graph.cc:2046``): builds the machine model + cost model,
runs the search (Unity DP when available, MCMC otherwise — mirroring the
reference's new/legacy pair), and returns the best strategy. Honors
``--budget``, ``--only-data-parallel``, ``--import``/``--export``.
"""
from __future__ import annotations

import json
import time
from typing import Optional

from ..parallel.machine import DeviceMesh, MachineSpec
from ..parallel.strategy import ShardingStrategy
from .costmodel import OpCostModel
from .mcmc import (StrategySimulator, assignment_to_strategy,
                   data_parallel_assignment, mcmc_search)
from .serialization import load_strategy, save_strategy


def optimize_strategy(ff) -> ShardingStrategy:
    """ff: FFModel (post graph construction, pre executor build)."""
    cfg = ff.config
    dmesh = ff.dmesh
    if cfg.import_strategy_file:
        return load_strategy(cfg.import_strategy_file, ff.layers, dmesh)
    spec = dmesh.spec
    cost_model = OpCostModel(spec)
    import jax
    if jax.devices()[0].platform != "cpu":
        # refine MXU efficiency with a real on-chip microbenchmark
        # (analog of inner_measure_operator_cost; skipped on CPU sim
        # where analytic constants already match cpu-sim MachineSpec)
        cost_model.calibrate()
    budget = cfg.search_budget if cfg.search_budget > 0 else 500
    t0 = time.perf_counter()
    best, best_cost, sim = mcmc_search(
        ff.layers, dmesh, cost_model, budget=budget,
        alpha=max(cfg.search_alpha - 1.0, 0.01), seed=cfg.seed,
        verbose=cfg.profiling)
    dp = data_parallel_assignment(ff.layers, dmesh, sim.options)
    dp_cost = sim.evaluate(dp).total
    strategy = assignment_to_strategy(ff.layers, ff.graph_inputs, best,
                                      dmesh, sim)
    if cfg.profiling:
        print(f"search: {time.perf_counter() - t0:.2f}s, "
              f"best {best_cost * 1e3:.3f} ms vs DP {dp_cost * 1e3:.3f} ms "
              f"({dp_cost / max(best_cost, 1e-12):.2f}x)")
    errs = strategy.validate()
    assert not errs, errs
    if cfg.export_strategy_file:
        save_strategy(cfg.export_strategy_file, strategy, best,
                      {"best_cost": best_cost, "dp_cost": dp_cost})
    return strategy
