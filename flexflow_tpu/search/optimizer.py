"""Strategy optimization entry point: dispatches to the configured search.

Analog of the reference's ``Graph::graph_optimize_task``
(``src/runtime/graph.cc:2046``): builds the machine model + cost model,
runs the search (Unity DP when available, MCMC otherwise — mirroring the
reference's new/legacy pair), and returns the best strategy. Honors
``--budget``, ``--only-data-parallel``, ``--import``/``--export``.
"""
from __future__ import annotations

import json
import time
from typing import Optional

from ..parallel.machine import DeviceMesh, MachineSpec
from ..parallel.strategy import ShardingStrategy
from .costmodel import OpCostModel
from .mcmc import (StrategySimulator, assignment_to_strategy,
                   data_parallel_assignment, mcmc_search)
from .serialization import load_strategy, save_strategy


def optimize_strategy(ff):
    """ff: FFModel (post graph construction, pre executor build).

    Returns ``(strategy, program_info_or_None)``: Unity search may rewrite
    the graph (inserting parallel ops), in which case ``program_info``
    carries the new executable layer list — the analog of the reference's
    ``convert_graph_to_operators`` output replacing the original operators.
    """
    cfg = ff.config
    dmesh = ff.dmesh
    if cfg.import_strategy_file:
        return _import_strategy(ff, cfg.import_strategy_file, dmesh)
    spec = dmesh.spec
    cost_model = OpCostModel(spec)
    import jax
    if jax.devices()[0].platform != "cpu":
        # real chip: refine MXU efficiency with a matmul microbenchmark
        # AND enable per-op on-device measurement (the analog of
        # measure_operator_cost, simulator.cc:537 — every heavy op is
        # timed at shard-local shape and disk-cached). On the CPU sim
        # the analytic constants already match the cpu-sim MachineSpec.
        cost_model.calibrate()
        cost_model.measure_on_device = True
    t0 = time.perf_counter()
    if cfg.search_algo == "unity":
        return _unity(ff, cost_model, t0)
    budget = cfg.search_budget if cfg.search_budget > 0 else 500
    best, best_cost, sim = mcmc_search(
        ff.layers, dmesh, cost_model, budget=budget,
        alpha=max(cfg.search_alpha - 1.0, 0.01), seed=cfg.seed,
        verbose=cfg.profiling)
    dp = data_parallel_assignment(ff.layers, dmesh, sim.options)
    dp_cost = sim.evaluate(dp).total
    strategy = assignment_to_strategy(ff.layers, ff.graph_inputs, best,
                                      dmesh, sim)
    if cfg.profiling:
        print(f"search: {time.perf_counter() - t0:.2f}s, "
              f"best {best_cost * 1e3:.3f} ms vs DP {dp_cost * 1e3:.3f} ms "
              f"({dp_cost / max(best_cost, 1e-12):.2f}x)")
    errs = strategy.validate()
    assert not errs, errs
    if cfg.export_strategy_file:
        save_strategy(cfg.export_strategy_file, strategy, best,
                      {"best_cost": best_cost, "dp_cost": dp_cost})
    return _maybe_pipeline(ff, cost_model, best_cost, (strategy, None))


def _maybe_pipeline(ff, cost_model, searched_cost, searched_result):
    """--enable-pipeline-search: score GPipe candidates (bubble model,
    search/pipeline_score.py) against the searched sharding strategy and
    take the winner. The chosen strategy carries its own (dp, S) mesh —
    FFModel.compile adopts strategy.dmesh."""
    cfg = ff.config
    if not cfg.enable_pipeline_search:
        return searched_result
    from .pipeline_score import best_pipeline
    cand = best_pipeline(ff.layers, ff.dmesh, cost_model,
                         cfg.pipeline_microbatches)
    if cand is None or (searched_cost is not None
                        and cand.cost >= searched_cost):
        if cfg.profiling and cand is not None:
            print(f"pipeline candidate S={cand.n_stages} "
                  f"cost {cand.cost * 1e3:.3f} ms >= searched "
                  f"{searched_cost * 1e3:.3f} ms — keeping searched")
        return searched_result
    from ..parallel.machine import DeviceMesh
    from ..parallel.presets import pipeline_strategy
    n = ff.dmesh.num_devices
    shape = (n // cand.n_stages, cand.n_stages) if n > cand.n_stages \
        else (cand.n_stages,)
    dmesh2 = DeviceMesh(ff.dmesh.spec, mesh_shape=shape)
    st = pipeline_strategy(ff.layers, ff.graph_inputs, dmesh2,
                           n_stages=cand.n_stages,
                           n_microbatches=cand.n_microbatches,
                           n_chunks=cand.n_chunks)
    if cfg.profiling:
        print(f"pipeline candidate S={cand.n_stages} wins: "
              f"{cand.cost * 1e3:.3f} ms < {searched_cost * 1e3:.3f} ms")
    return st, None


def _unity(ff, cost_model: OpCostModel, t0: float):
    """Unity substitution-DP search path (default)."""
    from .unity import unity_search
    cfg = ff.config
    dmesh = ff.dmesh
    budget = cfg.search_budget if cfg.search_budget > 0 else 32
    mem_budget = None
    if cfg.enable_memory_search:
        mem_budget = (cfg.device_mem_mb * (1 << 20)
                      if cfg.device_mem_mb > 0 else dmesh.spec.hbm_bytes)
    xfers = None
    if cfg.substitution_json_path:
        # reference-format rule collection (graph_subst_3_v2.json schema)
        # appended to the programmatic parallelization xfers
        from .substitution import generate_all_pcg_xfers
        from .substitution_loader import load_rule_collection
        degrees = [d for d in dmesh.valid_degrees() if d > 1]
        xfers = list(generate_all_pcg_xfers(degrees))
        xfers += load_rule_collection(cfg.substitution_json_path)
    evaluator_cls = None
    if cfg.machine_model_version >= 1:
        # machine model v1: native event-driven task-graph simulator
        # (reference --machine-model-version / EnhancedMachineModel)
        from .tasksim import TaskGraphEvaluator
        evaluator_cls = TaskGraphEvaluator
    info, strategy, gc, graph = unity_search(
        ff.layers, ff.graph_inputs + getattr(ff, "const_inputs", []),
        [ff._output_tensor], dmesh, cost_model,
        budget=budget, alpha=max(cfg.search_alpha, 1.0 + 1e-6),
        mem_budget_bytes=mem_budget,
        base_optimize_threshold=max(cfg.base_optimize_threshold, 2),
        xfers=xfers, evaluator_cls=evaluator_cls)
    if cfg.profiling:
        print(f"unity search: {time.perf_counter() - t0:.2f}s, "
              f"cost {gc.total * 1e3:.3f} ms "
              f"(compute {gc.compute * 1e3:.3f} xfer {gc.xfer * 1e3:.3f} "
              f"sync {gc.sync * 1e3:.3f})")
    if cfg.export_strategy_task_graph_file:
        with open(cfg.export_strategy_task_graph_file, "w") as f:
            f.write(graph.to_dot())
    if cfg.export_strategy_file:
        from .serialization import program_to_json
        prog_doc = program_to_json(
            info.layers,
            ff.graph_inputs + getattr(ff, "const_inputs", []),
            info.output_tensors[0])
        save_strategy(cfg.export_strategy_file, strategy, None,
                      {"best_cost": gc.total}, program=prog_doc)
    return _maybe_pipeline(ff, cost_model, gc.total, (strategy, info))


def _import_strategy(ff, path: str, dmesh):
    """--import: load a saved strategy; when it carries a serialized
    rewritten program (Unity export), rebuild that program too so parallel
    ops and layer names line up with the saved shardings."""
    import json as _json
    from ..pcg.graph import GraphProgramInfo
    from .serialization import program_from_json
    strategy = load_strategy(path, ff.layers, dmesh)
    with open(path) as f:
        doc = _json.load(f)
    prog_doc = doc.get("program")
    if not prog_doc:
        return strategy, None
    layers, out_t = program_from_json(
        prog_doc, ff.graph_inputs + getattr(ff, "const_inputs", []))
    return strategy, GraphProgramInfo(layers, {}, [out_t])
