"""Strategy optimization entry point: dispatches to the configured search.

Analog of the reference's ``Graph::graph_optimize_task``
(``src/runtime/graph.cc:2046``): builds the machine model + cost model,
runs the search (Unity DP when available, MCMC otherwise — mirroring the
reference's new/legacy pair), and returns the best strategy. Honors
``--budget``, ``--only-data-parallel``, ``--import``/``--export``.
"""
from __future__ import annotations

import json
import time
from typing import Optional

from ..obs import audit as obs_audit
from ..obs import events as obs_events
from ..parallel.machine import DeviceMesh, MachineSpec
from ..parallel.strategy import ShardingStrategy
from .costmodel import OpCostModel
from .mcmc import (StrategySimulator, assignment_to_strategy,
                   data_parallel_assignment, mcmc_search)
from .serialization import load_strategy, save_strategy


def optimize_strategy(ff, mode: str = "train"):
    """ff: FFModel (post graph construction, pre executor build).

    Returns ``(strategy, program_info_or_None)``: Unity search may rewrite
    the graph (inserting parallel ops), in which case ``program_info``
    carries the new executable layer list — the analog of the reference's
    ``convert_graph_to_operators`` output replacing the original operators.

    ``mode="serving"`` dispatches to the inference-native search
    (search/serving_plan.py): one plan per batch bucket ranked by
    prefill + per-token decode-step LATENCY with the KV cache resident
    in the envelope. It requires a compiled model (the search scores
    against the live mesh) and returns the largest bucket's strategy —
    the full per-bucket plan lands on ``ff._serving_plan`` and in the
    ``--export`` artifact's ``serving`` block.
    """
    cfg = ff.config
    if mode == "serving":
        from .serving_plan import optimize_serving_strategy
        plan = optimize_serving_strategy(ff)
        return plan.largest.strategy, None
    if mode != "train":
        raise ValueError(f"unknown strategy-search mode {mode!r} "
                         f"(expected 'train' or 'serving')")
    dmesh = ff.dmesh
    # stale-path guard: if THIS search's audit write is skipped (tracing
    # off) or fails, the floor guard below must not annotate a previous
    # compile's record with this compile's measured timings
    ff._strategy_audit_path = None
    if cfg.import_strategy_file:
        return _import_strategy(ff, cfg.import_strategy_file, dmesh)
    spec = dmesh.spec
    cost_model = OpCostModel(spec)
    cost_model.segment_size = max(1, cfg.simulator_segment_size)
    cost_model.max_segments = max(1, cfg.simulator_max_num_segments)
    _attach_placement(cfg, cost_model, dmesh)
    # quantized gradient collectives (ops/quantized_collectives.py,
    # arXiv 2506.17615): with the policy attached the search scores
    # every grad-sync site with its slow legs optionally narrowed to
    # the wire dtype, so precision is a dimension of the ranking —
    # per-tensor on flat syncs, per-phase on the reduction trees. Off
    # (the default) keeps every prediction bit-identical.
    from ..ops.quantized_collectives import (resolve_qsync_mode,
                                             resolve_qsync_wire)
    _qsync_mode = resolve_qsync_mode(cfg)
    if _qsync_mode != "off":
        cost_model.attach_quantization(_qsync_mode,
                                       resolve_qsync_wire(cfg))
    # overlap-aware scoring (FFConfig.overlap / FF_OVERLAP): gradient
    # sync is priced at its EXPOSED cost — what the executor's bucketed
    # schedule (runtime/overlap.py) cannot hide behind backward compute
    # — so the search ranks collective-heavy plans the way the overlap
    # runtime will execute them. Off (default) is bit-identical serial
    # pricing.
    from ..runtime.overlap import overlap_enabled
    cost_model.overlap_mode = overlap_enabled(cfg)
    # the ZeRO planner (FFModel._plan_zero) re-prices per-parameter
    # update paths against the SAME calibrated, placement-aware model
    # the search scored the strategy with
    ff._search_cost_model = cost_model
    import jax
    with obs_events.span("search.calibrate"):
        if jax.devices()[0].platform != "cpu":
            # real chip: refine MXU efficiency with a matmul
            # microbenchmark AND enable per-op on-device measurement
            # (the analog of measure_operator_cost, simulator.cc:537 —
            # every heavy op is timed at shard-local shape and
            # disk-cached). On the CPU sim the analytic constants
            # already match the cpu-sim MachineSpec.
            cost_model.calibrate()
            cost_model.measure_on_device = True
        # fit the collective constants from a real ring all-reduce on
        # the live mesh (disk-cached; the round-2 A/B showed machine-
        # model ICI constants mispredicting CPU-sim collectives by
        # orders of magnitude, adopting strategies that lost to DP).
        # ONLY when the search targets the live platform: under
        # --machine-model-file the described machine's constants are the
        # ground truth, and measuring the host fabric would corrupt the
        # simulation.
        if not cfg.machine_model_file:
            cost_model.calibrate_collectives(dmesh)
            # calibration v2 (opt-in): measured host dispatch/memory-
            # bandwidth/parallel-efficiency terms + persisted per-
            # collective tables, reused across processes
            # (search/calibration.py). Same exclusion as above: a
            # described machine's constants are ground truth, so never
            # overwrite them with live-host measurements.
            from .calibration import calibrate_mesh, calibration_enabled
            if calibration_enabled(cfg):
                try:
                    # quantized collectives on: additionally measure
                    # the wire-dtype rows (int8/fp8) so the precision
                    # choice is grounded in measured narrow-payload
                    # collectives, not just itemsize scaling
                    wires = ()
                    if _qsync_mode != "off":
                        wires = (resolve_qsync_wire(cfg),)
                    cost_model.attach_calibration(
                        calibrate_mesh(dmesh, wire_dtypes=wires))
                except Exception:  # noqa: BLE001 — best-effort
                    pass
    # searchable kernel tier (kernels/registry.py): grow the impl-keyed
    # calibration rows (warm table: zero re-measurement) and price every
    # attention op at its cheapest AVAILABLE implementation during the
    # search. Gated on an attached calibration: without measured machine
    # evidence the analytic curves would flip CPU runs onto
    # interpret-mode kernels the host executes orders of magnitude
    # slower than its own XLA path. Forced specs resolve unconditionally
    # (a typo'd --kernel-impl must fail loudly, so no try around it).
    from ..kernels.registry import resolve_forced as _kernel_forced
    _kpolicy = str(getattr(cfg, "kernel_impls", "auto") or "auto").lower()
    if _kpolicy not in ("off", "none") and cost_model.calib is not None:
        _forced = _kernel_forced(cfg)
        try:
            from .calibration import calibrate_kernel_impls
            calibrate_kernel_impls(dmesh, cost_model.calib.table)
        except Exception:  # noqa: BLE001 — priced analytically instead
            pass
        cost_model.attach_kernel_tier(dmesh, forced=_forced)
    t0 = time.perf_counter()
    if cfg.search_algo == "unity":
        return _apply_floor_guard(
            ff, _maybe_banks(ff, cost_model, _unity(ff, cost_model, t0)))
    budget = cfg.search_budget if cfg.search_budget > 0 else 500
    best, best_cost, sim = mcmc_search(
        ff.layers, dmesh, cost_model, budget=budget,
        alpha=max(cfg.search_alpha - 1.0, 0.01), seed=cfg.seed,
        verbose=cfg.profiling)
    dp = data_parallel_assignment(ff.layers, dmesh, sim.options)
    dp_cost = sim.evaluate(dp).total
    _write_mcmc_audit(ff, sim, best, dp)
    strategy = assignment_to_strategy(ff.layers, ff.graph_inputs, best,
                                      dmesh, sim)
    if cost_model.placement is not None:
        # re-price ONLY the adopted assignment with cleared memos so the
        # recorded tree choices are its collective sites (the MCMC walk
        # recorded every candidate's); axis_tiers travels with the
        # trees — the verifier's latency-bound check keys on it
        cost_model.attach_placement(cost_model.placement, "hier")
        sim.evaluate(best)
        strategy.collective_trees = list(
            cost_model.algo_choices.values())
        strategy.axis_tiers = cost_model.placement.to_json()
    if cfg.profiling:
        print(f"search: {time.perf_counter() - t0:.2f}s, "
              f"best {best_cost * 1e3:.3f} ms vs DP {dp_cost * 1e3:.3f} ms "
              f"({dp_cost / max(best_cost, 1e-12):.2f}x)")
    errs = strategy.validate()
    if errs:
        raise RuntimeError(f"search produced an unsound strategy: "
                           f"{errs}")
    if cfg.export_strategy_file:
        save_strategy(cfg.export_strategy_file, strategy, best,
                      {"best_cost": best_cost, "dp_cost": dp_cost})
    return _apply_floor_guard(
        ff, _maybe_banks(ff, cost_model, _maybe_pipeline(
            ff, cost_model, best_cost, (strategy, None))))


def _placement_enabled(cfg) -> bool:
    """Resolve the hierarchical-placement opt-out: config "true"/"false"
    wins; "auto" (the default) honors FF_HIER_PLACEMENT, defaulting ON
    — single-tier machines degenerate to flat behavior anyway."""
    import os
    mode = str(getattr(cfg, "hier_placement", "auto") or "auto").lower()
    if mode in ("true", "on", "1", "yes"):
        return True
    if mode in ("false", "off", "0", "no"):
        return False
    return os.environ.get("FF_HIER_PLACEMENT", "1").lower() \
        not in ("0", "false", "no", "off")


def _attach_placement(cfg, cost_model, dmesh) -> None:
    """Attach the axis→tier placement to the cost model when the
    machine has more than one hardware tier (multi-slice/multi-host).
    Single-tier machines skip it entirely — every prediction stays
    bit-identical to the flat model."""
    if not _placement_enabled(cfg):
        return
    from ..obs.metrics_registry import REGISTRY
    from ..parallel.placement import AxisPlacement
    placement = AxisPlacement.from_dmesh(dmesh)
    if placement is None or not placement.multi_tier:
        return
    cost_model.attach_placement(placement, "hier")
    REGISTRY.counter(
        "ff_placement_searches_total",
        "Searches run with hierarchical placement attached").inc()


def _placement_audit(ff, cost_model, graph, dmesh, evaluator_cls=None):
    """Searched-vs-flat placement comparison for the strategy audit
    record: re-price the ADOPTED graph under the hierarchical policy
    (recording each collective site's chosen tree) and under the
    flat-ring baseline policy, so a placement regression is diagnosable
    from artifacts alone. Returns (trees, record) — ``trees`` is what
    the adopted strategy serializes as ``collective_trees``."""
    if cost_model.placement is None:
        return [], None
    from ..obs.metrics_registry import REGISTRY
    from .unity import GraphCostEvaluator
    ev_cls = evaluator_cls or GraphCostEvaluator
    t0 = time.perf_counter()
    try:
        try:
            with obs_events.span("placement.search"):
                # fresh evaluator + cleared memos: the recorded choices
                # are exactly the adopted graph's collective sites
                cost_model.attach_placement(cost_model.placement, "hier")
                hier_total = ev_cls(cost_model,
                                    dmesh).graph_cost(graph).total
                trees = list(cost_model.algo_choices.values())
                cost_model.attach_placement(cost_model.placement, "flat")
                flat_total = ev_cls(cost_model,
                                    dmesh).graph_cost(graph).total
        finally:
            # the flat policy must NEVER leak past the audit: later
            # evaluations (dp-prediction fallback, pipeline scoring)
            # share this cost model
            cost_model.attach_placement(cost_model.placement, "hier")
        multi = [t for t in trees if len(t.get("phases", ())) > 1]
        record = {
            "policy": "hier",
            "axis_tiers": cost_model.placement.to_json(),
            "searched_total_s": hier_total,
            "flat_total_s": flat_total,
            "flat_over_searched": flat_total / max(hier_total, 1e-12),
            "n_collective_sites": len(trees),
            "n_multi_phase_trees": len(multi),
            "collectives": trees,
            "duration_s": time.perf_counter() - t0,
        }
        REGISTRY.counter(
            "ff_placement_adopted_total",
            "Adopted strategies by placement policy").inc(policy="hier")
        REGISTRY.gauge(
            "ff_placement_flat_over_searched",
            "Predicted flat-placement / searched-placement step-time "
            "ratio of the last search").set(
                record["flat_over_searched"])
        return trees, record
    except Exception:  # noqa: BLE001 — audit must never kill compile
        return [], None


def _write_unity_audit(ff, cost_model, graph, gc, info):
    """Strategy audit record (obs/audit.py): per-op predicted cost
    breakdown of the adopted PCG vs the canonical DP baseline, both
    priced by the additive evaluator so the per-op entries sum exactly
    to each side's recorded total. Written only when tracing is on
    (``FF_TRACE`` / ``FFConfig.trace``); best-effort."""
    if not obs_events.enabled():
        return
    try:
        from .unity import GraphCostEvaluator, data_parallel_graph
        dmesh = ff.dmesh
        inputs = ff.graph_inputs + getattr(ff, "const_inputs", [])
        ev = GraphCostEvaluator(cost_model, dmesh)
        with obs_events.span("search.audit"):
            a_gc, a_entries = ev.graph_cost_breakdown(graph)
            dp_g = data_parallel_graph(ff.layers, inputs,
                                       [ff._output_tensor], dmesh)
            d_gc, d_entries = ev.graph_cost_breakdown(dp_g)
        key = obs_audit.workload_key(ff.layers, dmesh.num_devices)
        record = {
            "search_algo": "unity",
            "ranker": getattr(info, "final_ranker", "additive"),
            "ranker_total_s": gc.total,
            "n_devices": dmesh.num_devices,
            "adopted": obs_audit.side_record(a_entries, a_gc.total),
            "dp_baseline": obs_audit.side_record(d_entries, d_gc.total),
            "predicted_dp_over_searched":
                d_gc.total / max(a_gc.total, 1e-12),
        }
        ov = _overlap_audit_block(cost_model, graph, dmesh, a_gc)
        if ov is not None:
            record["overlap"] = ov
        path = obs_audit.write_strategy_audit(record, key)
        if path:
            ff._strategy_audit_path = path
            obs_events.counter("search.audit_records")
    except Exception:  # noqa: BLE001 — audit must never kill compile
        pass


def _overlap_audit_block(cost_model, graph, dmesh, a_gc):
    """The strategy audit's ``overlap`` section (written only when the
    overlap-aware scoring mode is on): the adopted plan's predicted
    hidden-vs-exposed gradient-sync split (per-site entries already
    carry ``sync_hidden_s``/``sync_s`` in the adopted side) plus the
    event-driven simulator's authoritative estimate, so the bench's 2x
    agreement gate and obs/drift's predicted-vs-measured exposed-comm
    diff both work from artifacts alone. Bumps the
    ``ff_comm_overlap_hidden_s_total`` / ``ff_comm_exposed_s_total``
    counters with the predicted split."""
    if not getattr(cost_model, "overlap_mode", False):
        return None
    try:
        from ..obs.metrics_registry import REGISTRY
        # exposed comm = EVERYTHING communication the additive model
        # leaves on the critical path: the grad-sync exposure from the
        # window split PLUS the per-op xfer collectives (never hidden
        # by the additive model — they sit on data dependencies). Same
        # quantity the tasksim estimate and the measured estimator
        # report, so the bench's 2x agreement gate and obs/drift
        # compare like against like.
        block = {
            "enabled": True,
            "predicted_exposed_s": float(a_gc.sync + a_gc.xfer),
            "predicted_hidden_s": float(
                getattr(a_gc, "sync_hidden", 0.0)),
        }
        REGISTRY.counter(
            "ff_comm_overlap_hidden_s_total",
            "Communication seconds hidden behind backward compute "
            "(overlap-aware scoring)").inc(
                block["predicted_hidden_s"], side="predicted")
        REGISTRY.counter(
            "ff_comm_exposed_s_total",
            "Communication seconds exposed on the step critical path"
        ).inc(block["predicted_exposed_s"], side="predicted")
        try:
            from .tasksim import TaskGraphEvaluator
            tev = TaskGraphEvaluator(cost_model, dmesh)
            block["tasksim"] = tev.overlap_estimate(graph)
        except Exception as e:  # noqa: BLE001 — sim side best-effort
            # the bench's agreement gate reads this block: a swallowed
            # failure must at least leave its cause in the artifact
            block["tasksim_error"] = repr(e)
            import logging
            logging.getLogger("flexflow_tpu").warning(
                "overlap audit: tasksim estimate failed: %r", e)
        return block
    except Exception:  # noqa: BLE001 — audit must never kill compile
        return None


def _write_mcmc_audit(ff, sim, best, dp):
    """MCMC-path strategy audit record: per-op breakdown of the best
    assignment vs the DP assignment from the same simulator."""
    if not obs_events.enabled():
        return
    try:
        with obs_events.span("search.audit"):
            b_gc, b_entries = sim.evaluate_breakdown(best)
            d_gc, d_entries = sim.evaluate_breakdown(dp)
        key = obs_audit.workload_key(ff.layers, ff.dmesh.num_devices)
        # side totals are the pre-penalty component sums, so per_op
        # entries always sum to them; ranker_total_s keeps the
        # simulator's (possibly memory-penalized) objective
        b_tot = b_gc.compute + b_gc.xfer + b_gc.sync
        d_tot = d_gc.compute + d_gc.xfer + d_gc.sync
        record = {
            "search_algo": "mcmc",
            "ranker": "additive",
            "ranker_total_s": b_gc.total,
            "n_devices": ff.dmesh.num_devices,
            "adopted": obs_audit.side_record(b_entries, b_tot),
            "dp_baseline": obs_audit.side_record(d_entries, d_tot),
            "predicted_dp_over_searched": d_tot / max(b_tot, 1e-12),
        }
        if getattr(sim.cost, "overlap_mode", False):
            # same exposed/hidden definitions as the unity block; the
            # event-driven estimate needs a PCG the mcmc path doesn't
            # build, so the sim side is absent here by construction
            record["overlap"] = {
                "enabled": True,
                "predicted_exposed_s": float(b_gc.sync + b_gc.xfer),
                "predicted_hidden_s": float(
                    getattr(b_gc, "sync_hidden", 0.0)),
            }
        path = obs_audit.write_strategy_audit(record, key)
        if path:
            ff._strategy_audit_path = path
            obs_events.counter("search.audit_records")
    except Exception:  # noqa: BLE001 — audit must never kill compile
        pass


def _synth_batch(ff):
    """Random batch matching the graph inputs + label. Int tensors get
    tiny non-negative ids (valid for any embedding), labels get class 0
    (valid for any loss); values only need to execute, not converge."""
    import numpy as np
    from ..ffconst import DataType
    rng = np.random.default_rng(ff.config.seed)
    batch = {}
    for t in ff.graph_inputs:
        if t.dtype in (DataType.DT_INT32, DataType.DT_INT64):
            batch[t.name] = rng.integers(0, 2, size=t.shape).astype(np.int32)
        elif t.dtype == DataType.DT_BOOLEAN:
            batch[t.name] = np.ones(t.shape, dtype=bool)
        else:
            batch[t.name] = rng.normal(size=t.shape).astype(np.float32)
    lt = getattr(ff, "label_tensor", None)
    if lt is not None:
        if lt.dtype in (DataType.DT_INT32, DataType.DT_INT64):
            batch["label"] = np.zeros(lt.shape, dtype=np.int32)
        else:
            batch["label"] = np.zeros(lt.shape, dtype=np.float32)
    else:
        # no explicit label tensor: derive from the output + loss type
        # (same contract the loss fn applies at step time)
        from ..ffconst import LossType
        oshape = ff._output_tensor.shape
        if ff.loss_type == LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY:
            batch["label"] = np.zeros(oshape[:-1] + (1,), dtype=np.int32)
        else:
            batch["label"] = np.zeros(oshape, dtype=np.float32)
    return batch


def _time_strategy(ff, strategy, info):
    """Compile + time `floor_guard_steps` train steps of one strategy.
    Returns (mean seconds/step, executor, per_step_times, carry): the
    executor carries the compiled jitted step, so FFModel.compile can
    adopt it instead of re-jitting the winning program from scratch;
    per_step_times + carry let the guard extend the measurement via
    :func:`_extend_timing` when the decision is within timing noise.
    The device->host fetch is the sync point (block_until_ready does
    not synchronize on tunneled backends)."""
    import jax.numpy as jnp
    import numpy as np
    from ..executor import Executor, GraphProgram
    cfg = ff.config
    steps = max(1, cfg.floor_guard_steps)
    layers, outputs = ff.layers, [ff._output_tensor]
    if info is not None:
        layers, outputs = info.layers, info.output_tensors
    dmesh = strategy.dmesh if strategy.dmesh is not None else ff.dmesh
    program = GraphProgram(
        layers, ff.graph_inputs + getattr(ff, "const_inputs", []), outputs)
    ex = Executor(program, cfg, dmesh, strategy, ff.optimizer,
                  ff.loss_type, getattr(ff, "metrics", []), seed=cfg.seed)
    params, state = ex.init_params_and_state()
    opt_state = ff.optimizer.init_state(params)
    batch = _synth_batch(ff)
    step = ex.make_train_step()
    p, o, s, bm = step(params, opt_state, state, jnp.int32(0), batch)
    float(np.asarray(bm["loss"]))  # compile + sync
    # per-step wall times (synced each step) so the guard can judge
    # whether its decision margin exceeds the timing noise
    times = []
    for i in range(steps):
        t0 = time.perf_counter()
        p, o, s, bm = step(p, o, s, jnp.int32(i + 1), batch)
        float(np.asarray(bm["loss"]))
        times.append(time.perf_counter() - t0)
    return sum(times) / len(times), ex, times, [step, p, o, s, batch]


def _extend_timing(carry, times, extra):
    """Run `extra` more synced steps on an already-compiled guard
    executor, appending to its per-step time list. `carry` is mutated in
    place: the step donates its inputs, so the post-step arrays must
    replace the donated ones before any later extension round."""
    import jax.numpy as jnp
    import numpy as np
    step, p, o, s, batch = carry
    base = len(times)
    for i in range(extra):
        t0 = time.perf_counter()
        p, o, s, bm = step(p, o, s, jnp.int32(base + i + 1), batch)
        float(np.asarray(bm["loss"]))
        times.append(time.perf_counter() - t0)
    carry[1:4] = [p, o, s]
    return times


def _mean_std(times):
    n = len(times)
    m = sum(times) / n
    var = sum((t - m) ** 2 for t in times) / (n - 1) if n > 1 else 0.0
    return m, var ** 0.5


def _apply_floor_guard(ff, result):
    """Measured DP-floor on search adoption: time a few real steps of the
    searched program AND plain data parallel; keep DP when the searched
    program measures slower. The reference adopts searched strategies on
    the strength of its per-op-calibrated simulator
    (src/runtime/simulator.cc:537); here the floor is enforced by direct
    measurement so a mispredicting cost model can never ship a strategy
    that loses to the DP baseline. Records both numbers in
    ``ff._floor_guard_record`` and in the strategy export."""
    cfg = ff.config
    mode = str(cfg.search_floor_guard or "auto").lower()
    if mode in ("false", "off", "0", "no"):
        return result
    import jax
    if mode == "auto" and jax.devices()[0].platform == "cpu":
        return result  # CPU sim: double-compile too costly by default
    if jax.process_count() > 1:
        return result  # multi-controller feeding needs per-process arrays
    strategy, info = result
    dp = ShardingStrategy.data_parallel(ff.layers, ff.graph_inputs,
                                        ff.dmesh)
    _guard_t0 = time.perf_counter()
    try:
        t_s, ex_s, times_s, carry_s = _time_strategy(ff, strategy, info)
        t_dp, ex_dp, times_dp, carry_dp = _time_strategy(ff, dp, None)
        # when the margin between the two means is inside the combined
        # timing noise (2 x standard error), keep measuring — up to 4x
        # the base step count — instead of deciding from ~3 noisy steps
        max_steps = max(2, len(times_s), 4 * max(1, cfg.floor_guard_steps))
        while len(times_s) < max_steps:
            m_s, sd_s = _mean_std(times_s)
            m_dp, sd_dp = _mean_std(times_dp)
            sem = 2.0 * (sd_s ** 2 / len(times_s)
                         + sd_dp ** 2 / len(times_dp)) ** 0.5
            # with a single sample the std is vacuously 0 and any margin
            # would "exceed the noise" — force a second step first so a
            # real variance estimate exists; past that, identical-to-the-
            # bit times (only monkeypatched fakes) cannot shrink the sem
            # by measuring more, so stop
            if len(times_s) >= 2 and (abs(m_s - m_dp) > sem
                                      or (sd_s == 0.0 and sd_dp == 0.0)):
                break
            extra = min(len(times_s), max_steps - len(times_s))
            _extend_timing(carry_s, times_s, extra)
            _extend_timing(carry_dp, times_dp, extra)
        t_s, sd_s = _mean_std(times_s)
        t_dp, sd_dp = _mean_std(times_dp)
    except Exception as e:  # noqa: BLE001 — guard must never kill compile
        if cfg.profiling:
            print(f"floor guard skipped ({e!r})")
        return result
    adopted = "searched" if t_s <= t_dp else "dp"
    record = {"searched_s_per_step": t_s, "dp_s_per_step": t_dp,
              "searched_std": sd_s, "dp_std": sd_dp,
              "n_steps": len(times_s), "adopted": adopted}
    ff._floor_guard_record = record
    obs_events.record_span("search.floor_guard", _guard_t0,
                           time.perf_counter() - _guard_t0,
                           adopted=adopted)
    # measured timings join the predicted per-op breakdown in the audit
    # record — both sides of one adoption decision in one file
    _audit_path = getattr(ff, "_strategy_audit_path", None)
    if _audit_path:
        obs_audit.annotate_strategy_audit(_audit_path,
                                          {"floor_guard": record})
    # hand the winning side's compiled executor to FFModel.compile so
    # the adopted program is not re-jitted a third time (params are
    # re-initialized there — the guard's few synthetic steps must not
    # leak into training)
    ff._prebuilt_executor = (strategy, ex_s) if adopted == "searched" \
        else (dp, ex_dp)
    if adopted == "dp":
        print(f"[flexflow_tpu] searched strategy measured "
              f"{t_s * 1e3:.2f} ms/step vs data-parallel "
              f"{t_dp * 1e3:.2f} ms/step — keeping data parallel "
              f"(measured DP floor)")
        if cfg.export_strategy_file:
            # the export must describe the ADOPTED strategy: a later
            # --import of this file bypasses search AND guard entirely,
            # so leaving the rejected searched strategy in it would
            # deploy exactly what the guard measured as losing
            save_strategy(cfg.export_strategy_file, dp, None,
                          {"floor_guard": record})
        result = (dp, None)
    else:
        if cfg.export_strategy_file:
            _annotate_export(cfg.export_strategy_file, record)
        if cfg.profiling:
            print(f"floor guard: searched {t_s * 1e3:.2f} ms/step <= DP "
                  f"{t_dp * 1e3:.2f} ms/step — adopting searched")
    return result


def _annotate_export(path: str, record) -> None:
    try:
        with open(path) as f:
            doc = json.load(f)
        doc["floor_guard"] = record
        with open(path, "w") as f:
            json.dump(doc, f, indent=1)
    except Exception:  # noqa: BLE001 — export annotation is best-effort
        pass


def _maybe_banks(ff, cost_model, result):
    """--banked-placement: attach per-op device-subset placements
    (search/banking.py) to the searched strategy when the cost model
    predicts a win; the measured DP-floor guard downstream still
    arbitrates with real timed steps. Reference: MachineView
    per-op placement (machine_view.h:14-62, DLRM strategies)."""
    cfg = ff.config
    mode = str(getattr(cfg, "banked_placement", "auto")).lower()
    if mode == "off":
        return result
    strategy, info = result
    layers = info.layers if info is not None else ff.layers
    try:
        from .banking import attach_banks
        specs = attach_banks(strategy, layers, cost_model, mode=mode)
        if specs and cfg.profiling:
            for s in specs:
                print(f"banked placement: {len(s.members)} x "
                      f"{s.members[0].split('_')[0]} over axes {s.axes}")
        if specs and cfg.export_strategy_file:
            # the search path exported before banks attached; rewrite
            # the banks field so --import round-trips the placement
            try:
                from .serialization import banks_to_json
                with open(cfg.export_strategy_file) as f:
                    doc = json.load(f)
                doc["banks"] = banks_to_json(strategy)
                with open(cfg.export_strategy_file, "w") as f:
                    json.dump(doc, f, indent=1)
            except Exception:  # noqa: BLE001 — export is best-effort
                pass
    except Exception as e:  # noqa: BLE001 — proposal must not kill compile
        import logging
        logging.getLogger("flexflow_tpu").warning(
            "banked-placement proposal failed: %r", e)
    return result


def _maybe_pipeline(ff, cost_model, searched_cost, searched_result):
    """--enable-pipeline-search: score GPipe candidates (bubble model,
    search/pipeline_score.py) against the searched sharding strategy and
    take the winner. The chosen strategy carries its own (dp, S) mesh —
    FFModel.compile adopts strategy.dmesh."""
    cfg = ff.config
    if not cfg.enable_pipeline_search:
        return searched_result
    from .pipeline_score import best_pipeline
    cand = best_pipeline(ff.layers, ff.dmesh, cost_model,
                         cfg.pipeline_microbatches)
    if cand is None or (searched_cost is not None
                        and cand.cost >= searched_cost):
        if cfg.profiling and cand is not None:
            print(f"pipeline candidate S={cand.n_stages} "
                  f"cost {cand.cost * 1e3:.3f} ms >= searched "
                  f"{searched_cost * 1e3:.3f} ms — keeping searched")
        return searched_result
    from ..parallel.machine import DeviceMesh
    from ..parallel.presets import pipeline_strategy
    n = ff.dmesh.num_devices
    tp = max(cand.tp, 1)
    sizes = (n // (cand.n_stages * tp), cand.n_stages, tp)
    roles = [r for r, d in zip(("dp", "pp", "tp"), sizes) if d > 1]
    dmesh2 = DeviceMesh(ff.dmesh.spec,
                        mesh_shape=tuple(d for d in sizes if d > 1))
    by_role = dict(zip(roles, dmesh2.axis_names))
    st = pipeline_strategy(ff.layers, ff.graph_inputs, dmesh2,
                           n_stages=cand.n_stages,
                           n_microbatches=cand.n_microbatches,
                           n_chunks=cand.n_chunks, tp=tp,
                           pp_axis=by_role["pp"],
                           tp_axis=by_role.get("tp"),
                           dp_axes=(by_role["dp"],) if "dp" in by_role
                           else ())
    if cfg.profiling:
        print(f"pipeline candidate S={cand.n_stages} tp={tp} wins: "
              f"{cand.cost * 1e3:.3f} ms < {searched_cost * 1e3:.3f} ms")
    ff._pipeline_choice = cand    # winner record (northstar/bench JSON)
    pred = getattr(ff, "_search_predicted", None)
    if pred is not None:
        # the prediction must describe the strategy actually adopted,
        # or the predicted-vs-measured fidelity metric correlates a
        # discarded program
        pred["searched_cost_s"] = cand.cost
    return st, None


def _unity(ff, cost_model: OpCostModel, t0: float):
    """Unity substitution-DP search path (default)."""
    from .unity import unity_search
    cfg = ff.config
    dmesh = ff.dmesh
    budget = cfg.search_budget if cfg.search_budget > 0 else 32
    mem_budget = None
    if cfg.enable_memory_search:
        mem_budget = (cfg.device_mem_mb * (1 << 20)
                      if cfg.device_mem_mb > 0 else dmesh.spec.hbm_bytes)
    xfers = None
    if cfg.substitution_json_path:
        # reference-format rule collection (graph_subst_3_v2.json schema)
        # appended to the programmatic parallelization xfers
        from .substitution import generate_all_pcg_xfers
        from .substitution_loader import load_rule_collection
        degrees = [d for d in dmesh.valid_degrees() if d > 1]
        xfers = list(generate_all_pcg_xfers(degrees))
        xfers += load_rule_collection(cfg.substitution_json_path)
    evaluator_cls = None
    if cfg.machine_model_version >= 1:
        # machine model v1: native event-driven task-graph simulator
        # (reference --machine-model-version / EnhancedMachineModel)
        from .tasksim import TaskGraphEvaluator
        evaluator_cls = TaskGraphEvaluator
    with obs_events.span("search.unity", budget=budget):
        info, strategy, gc, graph = unity_search(
            ff.layers, ff.graph_inputs + getattr(ff, "const_inputs", []),
            [ff._output_tensor], dmesh, cost_model,
            budget=budget, alpha=max(cfg.search_alpha, 1.0 + 1e-6),
            mem_budget_bytes=mem_budget,
            base_optimize_threshold=max(cfg.base_optimize_threshold, 2),
            xfers=xfers, evaluator_cls=evaluator_cls)
    _write_unity_audit(ff, cost_model, graph, gc, info)
    # the adopted PCG, retained for post-compile analysis (the bench's
    # comm_overlap leg re-derives the model-vs-sim exposed-comm
    # agreement from it when the audit record is unavailable)
    ff._adopted_pcg = graph
    trees, placement_rec = _placement_audit(ff, cost_model, graph, dmesh,
                                            evaluator_cls=evaluator_cls)
    if trees:
        strategy.collective_trees = trees
    if placement_rec is not None:
        _audit_path = getattr(ff, "_strategy_audit_path", None)
        if _audit_path:
            obs_audit.annotate_strategy_audit(
                _audit_path, {"placement": placement_rec})
        ff._placement_record = placement_rec
        if cfg.profiling:
            print(f"placement: flat/searched predicted "
                  f"{placement_rec['flat_over_searched']:.2f}x, "
                  f"{placement_rec['n_multi_phase_trees']} multi-phase "
                  f"tree(s) over "
                  f"{placement_rec['n_collective_sites']} site(s)")
    try:
        # predicted searched-vs-DP ratio, recorded so A/B harnesses can
        # correlate the cost model's prediction with measurement; the
        # DP-floor evaluation inside unity_search already produced the
        # baseline cost — only the memory-search branch recomputes
        dp_pred = getattr(info, "dp_predicted_total", None)
        if dp_pred is None:
            from .unity import GraphCostEvaluator, data_parallel_graph
            ev = (evaluator_cls or GraphCostEvaluator)(cost_model, dmesh)
            dp_pred = ev.graph_cost(data_parallel_graph(
                ff.layers,
                ff.graph_inputs + getattr(ff, "const_inputs", []),
                [ff._output_tensor], dmesh)).total
        ff._search_predicted = {"searched_cost_s": gc.total,
                                "dp_cost_s": dp_pred,
                                "peak_mem_per_dev_bytes": gc.peak_memory
                                / max(dmesh.num_devices, 1)}
    except Exception:  # noqa: BLE001 — reporting only
        pass
    if cfg.profiling:
        print(f"unity search: {time.perf_counter() - t0:.2f}s, "
              f"cost {gc.total * 1e3:.3f} ms "
              f"(compute {gc.compute * 1e3:.3f} xfer {gc.xfer * 1e3:.3f} "
              f"sync {gc.sync * 1e3:.3f})")
    if cfg.export_strategy_task_graph_file:
        with open(cfg.export_strategy_task_graph_file, "w") as f:
            f.write(graph.to_dot())
    if cfg.export_strategy_file:
        from .serialization import program_to_json
        prog_doc = program_to_json(
            info.layers,
            ff.graph_inputs + getattr(ff, "const_inputs", []),
            info.output_tensors[0])
        save_strategy(cfg.export_strategy_file, strategy, None,
                      {"best_cost": gc.total}, program=prog_doc)
    return _maybe_pipeline(ff, cost_model, gc.total, (strategy, info))


def _import_strategy(ff, path: str, dmesh):
    """--import: load a saved strategy; when it carries a serialized
    rewritten program (Unity export), rebuild that program too so parallel
    ops and layer names line up with the saved shardings."""
    import json as _json
    from ..pcg.graph import GraphProgramInfo
    from .serialization import program_from_json
    strategy = load_strategy(path, ff.layers, dmesh)
    with open(path) as f:
        doc = _json.load(f)
    prog_doc = doc.get("program")
    if not prog_doc:
        return strategy, None
    layers, out_t = program_from_json(
        prog_doc, ff.graph_inputs + getattr(ff, "const_inputs", []))
    return strategy, GraphProgramInfo(layers, {}, [out_t])
