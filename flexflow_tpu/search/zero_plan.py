"""Per-parameter ZeRO planning: optimizer-state sharding as a searched,
cost-model-scored decision (PAPERS.md, arXiv 2004.13336 "Automatic
Cross-Replica Sharding of Weight Update in Data-Parallel Training").

The uniform ``--zero`` flag shards every moment or none — a global
choice made after the search already committed a strategy. This module
makes it a **per-parameter** trade the stack scores and honors:

  - **memory side**: sharding one parameter's optimizer slots over a
    degree-``d`` group saves ``slots x param_bytes x (1 - 1/d)`` bytes
    per device (Adam: 2 slots, momentum-SGD: 1);
  - **time side**: the update path changes from
    ``all-reduce(grad) + replicated update`` to ``reduce-scatter(grad)
    + sharded update + all-gather(param)``. Ring algebra makes the two
    nearly bandwidth-neutral (2(d-1)/d vs (d-1)/d + (d-1)/d), so the
    marginal cost is mostly latency rounds and tier effects — priced
    here through :meth:`OpCostModel.xfer_cost` with the assignment's
    actual mesh axes, so PR 9's tier-aware tables and reduction-tree
    selection apply (a DCN-crossing all-gather prices as a DCN
    all-gather, not an ICI one).

Policies (``FFConfig.zero_policy``):

  - ``"off"``  — never plan (default; the uniform flag is untouched);
  - ``"auto"`` — shard every parameter whose predicted overhead is
    within ``zero_overhead_frac`` of its replicated update cost (the
    "free wins"), then shard further — cheapest overhead per byte
    saved first — only while the static memory envelope exceeds the
    device budget;
  - ``"memory"`` — shard nothing unless the replicated envelope
    exceeds the budget, then the cheapest set that fits;
  - ``"all"`` — shard everything shardable (the uniform assignment,
    scored).

The adopted :class:`~flexflow_tpu.runtime.zero.ZeroAssignment`
serializes with the strategy, is statically verified (a moment sharded
over its weight's own axis is a compile-time error), annotates the
strategy audit record under ``"zero"``, and drives the executor's
in-jit state pins and the checkpoint meta's per-leaf shardings.
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..dtypes import itemsize
from ..obs import events as obs_events
from ..obs.metrics_registry import REGISTRY
from ..runtime.zero import (ZeroAssignment, opt_slots, spec_axes,
                            spec_degree, zero_spec)


def score_param(cost_model, wbytes_local: float, zero_degree: int,
                dp_degree: int, slots: int,
                zero_axes: Optional[Tuple[str, ...]] = None
                ) -> Tuple[float, float, float]:
    """Score one parameter's update paths.

    Returns ``(bytes_saved, overhead_s, replicated_s)``:

      - ``bytes_saved`` — per-device optimizer-state bytes the sharding
        frees: ``slots x wbytes_local x (1 - 1/zero_degree)``;
      - ``replicated_s`` — the replicated path: one gradient
        all-reduce over the data-parallel group (the cost the strategy
        already pays today);
      - ``overhead_s`` — sharded-path cost minus ``replicated_s``. The
        sharded path is reduce-scatter(grad) over the assignment's
        axes, an all-reduce of the scattered gradient over whatever
        data-parallel degree remains (``dp_degree / zero_degree``, when
        the free axes don't absorb the whole group), and the parameter
        all-gather. Near zero on flat fabrics; tier-aware with a
        placement attached (PR 9).
    """
    d = max(int(zero_degree), 1)
    dp = max(int(dp_degree), 1)
    saved = float(slots) * float(wbytes_local) * (1.0 - 1.0 / d)
    base = cost_model.weight_sync_cost(wbytes_local, dp) if dp > 1 else 0.0
    if d <= 1:
        return 0.0, 0.0, float(base)
    rs = cost_model.xfer_cost(wbytes_local, "reduce_scatter", d,
                              axes=zero_axes)
    ag = cost_model.xfer_cost(wbytes_local, "all_gather", d,
                              axes=zero_axes)
    rest = dp // d
    mid = cost_model.weight_sync_cost(wbytes_local / d, rest) \
        if rest > 1 else 0.0
    return saved, float(rs + mid + ag - base), float(base)


def plan_zero_assignment(strategy, layers: Sequence, dmesh, cost_model,
                         optimizer, *, policy: str = "auto",
                         overhead_frac: float = 0.05,
                         hbm_bytes: Optional[float] = None
                         ) -> Optional[ZeroAssignment]:
    """Plan the per-parameter assignment for an adopted strategy.

    Scores every trainable parameter, then applies ``policy`` under the
    static per-device memory envelope (the same conservative envelope
    the plan verifier enforces — a plan adopted here because it fits
    *with* ZeRO also verifies). Returns None when nothing is worth (or
    able to be) sharded — the caller keeps the replicated path.
    """
    t0 = time.perf_counter()
    axis_sizes = dict(dmesh.axis_sizes)
    n_dev = 1
    for s in axis_sizes.values():
        n_dev *= s
    slots = opt_slots(optimizer)
    if n_dev <= 1 or slots <= 0:
        return None
    ops = getattr(strategy, "ops", {})
    # bank / place-group members execute on device SUBSETS with their
    # parameters stacked under a group key — the per-layer assignment
    # cannot address that state, so they stay replicated
    subset_members: set = set()
    for bk in getattr(strategy, "banks", None) or ():
        subset_members.update(bk.members)
    for pg in getattr(strategy, "place_groups", None) or ():
        subset_members.update(pg.members)
    assignment = ZeroAssignment({}, policy=policy)
    candidates: List[Tuple[str, str, Dict]] = []
    for layer in layers:
        if layer.name in subset_members:
            continue
        for w in layer.weights or ():
            if not getattr(layer, "trainable", True):
                continue
            total = float(int(np.prod(w.shape)) or 1) * itemsize(w.dtype)
            os_ = ops.get(layer.name)
            wspec = os_.weights.get(w.name) if os_ is not None else None
            wdeg = spec_degree(wspec, axis_sizes)
            dp_deg = max(1, n_dev // max(wdeg, 1))
            sp = zero_spec(w.shape, wspec, axis_sizes)
            zaxes = tuple(a for a in spec_axes(sp)
                          if a not in spec_axes(wspec)) if sp else ()
            zdeg = 1
            for a in zaxes:
                zdeg *= axis_sizes.get(a, 1)
            local = total / max(wdeg, 1)
            saved, overhead, base = score_param(
                cost_model, local, zdeg, dp_deg, slots, zaxes or None)
            rec = {
                "spec": None,
                "candidate_spec": None if sp is None else
                [list(e) if isinstance(e, tuple) else e for e in sp],
                "degree": 1, "candidate_degree": zdeg,
                "bytes_saved": 0.0, "candidate_bytes_saved": saved,
                "overhead_s": overhead, "replicated_s": base,
            }
            assignment.decisions.setdefault(layer.name, {})[w.name] = rec
            if sp is not None and zdeg > 1:
                candidates.append((layer.name, w.name, rec))
    if not candidates:
        return None

    def adopt(rec) -> None:
        rec["spec"] = rec["candidate_spec"]
        rec["degree"] = rec["candidate_degree"]
        rec["bytes_saved"] = rec["candidate_bytes_saved"]

    if policy == "all":
        for _, _, rec in candidates:
            adopt(rec)
    else:
        if policy == "auto":
            for _, _, rec in candidates:
                slack = overhead_frac * max(rec["replicated_s"], 0.0)
                if rec["overhead_s"] <= slack:
                    adopt(rec)
        # memory pressure: shard further (cheapest overhead per byte
        # saved first) while the static envelope exceeds the device
        # budget. Each adoption shrinks the envelope by exactly the
        # candidate's bytes_saved (the same per-leaf formula
        # memory_envelope applies), so the envelope is computed ONCE
        # and a running deficit decremented — not O(params^2)
        if hbm_bytes:
            from ..analysis.plan_verifier import memory_envelope
            env = memory_envelope(strategy, layers, axis_sizes,
                                  optimizer, zero=assignment)
            deficit = env["envelope_bytes"] - hbm_bytes
            remaining = sorted(
                (c for c in candidates if c[2]["spec"] is None),
                key=lambda c: (max(c[2]["overhead_s"], 0.0)
                               / max(c[2]["candidate_bytes_saved"], 1.0),
                               c[0], c[1]))
            for lname, wname, rec in remaining:
                if deficit <= 0:
                    break
                adopt(rec)
                deficit -= rec["bytes_saved"]
    if not assignment:
        return None
    summary = assignment.summary()
    REGISTRY.counter(
        "ff_zero_plans_total",
        "Per-parameter ZeRO assignments adopted by policy"
        ).inc(policy=policy)
    REGISTRY.gauge(
        "ff_zero_bytes_saved",
        "Per-device optimizer-state bytes saved by the last adopted "
        "ZeRO assignment").set(summary["bytes_saved_total"])
    obs_events.record_span(
        "zero.plan", t0, time.perf_counter() - t0,
        policy=policy, n_params=summary["n_params"],
        n_sharded=summary["n_sharded"])
    return assignment


def audit_record(assignment: ZeroAssignment) -> Dict[str, Any]:
    """The strategy-audit ``"zero"`` section: the summary plus every
    parameter's choice with its bytes-saved / predicted-overhead score —
    a regressed assignment is diagnosable from artifacts alone."""
    per_param = []
    for lname, ws in assignment.decisions.items():
        for wname, rec in ws.items():
            per_param.append({
                "param": f"{lname}/{wname}",
                "sharded": rec.get("spec") is not None,
                "spec": rec.get("spec"),
                "degree": rec.get("degree", 1),
                "bytes_saved": rec.get("bytes_saved", 0.0),
                "overhead_s": rec.get("overhead_s", 0.0),
                "replicated_s": rec.get("replicated_s", 0.0),
            })
    return {**assignment.summary(), "per_param": per_param}
