"""Inference-native strategy search: per-batch-class serving plans.

The training search (mcmc.py / unity.py) optimizes one objective —
training step time — but serving is latency-bound, batch shapes churn
across the buckets ``InferenceSession`` pads to, and autoregressive
decode carries a resident per-layer KV cache the training cost model
knows nothing about. This module makes serving a first-class search
target:

* **Objective** (``ServingCostEvaluator``): prefill cost + per-token
  decode-step LATENCY (not throughput), one evaluation per batch
  bucket. Decode-step collectives are priced latency-side through
  ``OpCostModel.xfer_cost`` — the path that includes per-hop latency,
  the calibrated small-message table rows, and the placement/tree
  selector (arXiv 2110.10548) — never through the bandwidth-marginal
  ``weight_sync_cost`` path (XLA does not coalesce decode-step
  collectives across tokens, so the per-dispatch floor is real).
* **KV cache as a first-class resident tensor**: sized
  ``2 (K+V) × max_seq × bucket × num_kv_heads × head_dim`` (respecting
  GQA), sharded along the attention head-parallel degree, counted in
  the serving memory envelope (``analysis/plan_verifier``) and read
  once per decode step on the HBM side of the roofline.
* **Seq-sharded KV as a scored option** (long-prompt buckets): when
  the mesh carries a sequence axis (``DeviceMesh.seq_degree >= 2``),
  each cache-carrying layer may additionally shard its KV cache over
  the CONTEXT dimension — per-device residency (and the decode-step
  cache-read floor) drops by the seq degree, paid for by a per-step
  flash-decoding-style combine of partial attention outputs rotated
  over the seq axis (priced from the calibrated per-tier
  ``coll_ppermute`` rows when present). Adopted when the cache-read
  saving beats the combine, or when the head-sharded cache alone
  cannot fit HBM; recorded as ``seq_shard_degree`` in the KV plan and
  re-checked by the verifier.
* **Per-(model, batch-class) plans** (``optimize_serving_strategy``):
  one searched assignment per bucket — small buckets lean tensor-
  parallel (batch can't shard), large buckets lean data-parallel —
  serialized as a ``serving`` block in the strategy artifact
  (``search/serialization.py``), audited (``serving`` block in the
  strategy audit record) and verified like training strategies.
"""
from __future__ import annotations

import dataclasses
import math
import random
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core.layer import Layer
from ..dtypes import itemsize
from ..ffconst import OperatorType
from ..obs import audit as obs_audit
from ..obs import events as obs_events
from ..parallel.machine import DeviceMesh
from ..parallel.strategy import ShardingStrategy
from .costmodel import OpCostModel
from .opshard import ShardOption, assignment_to_sharding, options_for

#: default batch classes — the buckets InferenceSession pads to
DEFAULT_BUCKETS = (1, 4, 16, 64)

KV_DTYPE_BYTES = 4  # float32 cache entries (executor kv_prefill dtype)


# ---------------------------------------------------------------------------
# KV cache geometry
# ---------------------------------------------------------------------------

def kv_cache_spec(layer: Layer) -> Optional[Dict[str, int]]:
    """KV-cache head geometry for a causal attention layer, or None for
    ops that carry no cache. GQA (``num_kv_heads < num_heads``) shrinks
    the cache — and caps how far it can shard."""
    if layer.op_type != OperatorType.OP_MULTIHEAD_ATTENTION:
        return None
    p = layer.params
    if not p.get("causal", False):
        return None
    embed = int(p["embed_dim"])
    num_heads = int(p["num_heads"])
    kv_heads = int(p.get("num_kv_heads", 0) or num_heads)
    return {"num_kv_heads": kv_heads,
            "head_dim": embed // max(num_heads, 1),
            "embed_dim": embed}


def kv_cache_bytes(layer: Layer, bucket: int, max_seq: int,
                   shard_degree: int = 1) -> int:
    """Resident K+V bytes for one attention layer at one batch bucket,
    per device when ``shard_degree`` shards the kv heads."""
    spec = kv_cache_spec(layer)
    if spec is None:
        return 0
    total = (2 * bucket * max_seq * spec["num_kv_heads"]
             * spec["head_dim"] * KV_DTYPE_BYTES)
    return total // max(int(shard_degree), 1)


def kv_shard_degree(layer: Layer, options: Sequence[ShardOption],
                    degrees: Sequence[int]) -> int:
    """KV-cache shard degree implied by an assignment: the cache co-
    shards with the attention head-parallel weights (the ``parameter``
    option), clamped to what GQA allows — a degree that does not divide
    ``num_kv_heads`` cannot split the kv heads, so the cache stays
    replicated (degree 1) and the envelope must budget for it."""
    spec = kv_cache_spec(layer)
    if spec is None:
        return 1
    for opt, d in zip(options, degrees):
        if opt.kind == "parameter" and d > 1:
            if d <= spec["num_kv_heads"] \
                    and spec["num_kv_heads"] % d == 0:
                return int(d)
            return 1
    return 1


# ---------------------------------------------------------------------------
# serving-objective evaluator
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class ServingCost:
    """One bucket's predicted serving profile. ``total`` is the search
    objective: prefill + decode_tokens × decode-step latency (with the
    infeasible-memory penalty folded in, like GraphCost.total)."""
    total: float
    prefill: float
    decode_step: float
    kv_bytes: int
    peak_memory: int
    decode_compute: float = 0.0
    decode_comm: float = 0.0


class ServingCostEvaluator:
    """Scores a per-op assignment under the serving objective for ONE
    batch bucket (the per-batch-class analog of
    ``mcmc.StrategySimulator``; shares its options/assignment
    vocabulary so proposals and strategy materialization reuse the
    training machinery)."""

    def __init__(self, layers: Sequence[Layer], dmesh: DeviceMesh,
                 cost_model: OpCostModel, bucket: int, max_seq: int,
                 decode_tokens: Optional[int] = None):
        self.layers = list(layers)
        self.dmesh = dmesh
        self.cost = cost_model
        self.bucket = int(bucket)
        self.max_seq = int(max_seq)
        self.decode_tokens = int(decode_tokens or max_seq)
        self.options: Dict[str, List[ShardOption]] = {
            l.name: options_for(l) for l in self.layers}
        # compile-time (batch, seq) the graph was built at — cost
        # scaling maps compile-shape op costs to serving shapes
        self.compile_batch, self.compile_seq = self._graph_shape()
        self._n_cache = sum(1 for l in self.layers
                            if kv_cache_spec(l) is not None)

    def _graph_shape(self) -> Tuple[int, int]:
        for l in self.layers:
            for t in list(l.inputs) + list(l.outputs):
                if t.shape and len(t.shape) >= 2:
                    return int(t.shape[0]), int(t.shape[1])
        return 1, 1

    def _carries_seq(self, shape) -> bool:
        return bool(shape) and len(shape) >= 2 \
            and int(shape[1]) == self.compile_seq

    def _degrees_of(self, layer: Layer,
                    assign: Dict[str, Tuple[int, ...]]) -> Dict[int, int]:
        degs: Dict[int, int] = {}
        for opt, d in zip(self.options[layer.name],
                          assign.get(layer.name, ())):
            if d > 1 and opt.out_dim >= 0:
                degs[opt.out_dim] = d
        return degs

    def bucket_feasible(self, layer: Layer,
                        degrees: Sequence[int]) -> bool:
        """Serving adds one constraint the training search lacks: a
        batch-dim (sample) degree must divide the BUCKET — the runtime
        batch the session pads to — not just the compile-time batch."""
        for opt, d in zip(self.options[layer.name], degrees):
            if d > 1 and opt.kind == "sample" and opt.out_dim == 0 \
                    and self.bucket % d != 0:
                return False
        return True

    def kv_plan(self, assign: Dict[str, Tuple[int, ...]]
                ) -> Dict[str, Dict[str, int]]:
        """layer name -> {shard_degree, seq_shard_degree, bytes (per
        device, this bucket), num_kv_heads, head_dim} for every
        cache-carrying op."""
        plan: Dict[str, Dict[str, int]] = {}
        for l in self.layers:
            spec = kv_cache_spec(l)
            if spec is None:
                continue
            deg = kv_shard_degree(l, self.options[l.name],
                                  assign.get(l.name, ()))
            sdeg = self.kv_seq_degree(l, assign)
            plan[l.name] = {
                "shard_degree": deg,
                "seq_shard_degree": sdeg,
                "bytes": kv_cache_bytes(l, self.bucket, self.max_seq,
                                        deg * sdeg),
                "num_kv_heads": spec["num_kv_heads"],
                "head_dim": spec["head_dim"]}
        return plan

    def _seq_combine_cost(self, act_bytes: int, sdeg: int) -> float:
        """Per-decode-step price of combining seq-sharded partial
        attention outputs: a (sdeg-1)-hop ppermute rotation of the
        (bucket × embed) partial output + running softmax statistics
        (flash-decoding style) over the sequence axis. Priced from the
        calibrated per-tier ``coll_ppermute`` rows when the table has
        them; otherwise through the decode-latency collective path
        (per-dispatch floor included — these fire once per token)."""
        if sdeg <= 1 or act_bytes <= 0:
            return 0.0
        cm = self.cost
        tier = getattr(self.dmesh, "axis_tiers", {}).get(
            getattr(self.dmesh, "seq_axis", None))
        hop = None
        if cm.calib is not None:
            hop = cm.calib.collective_time("ppermute", sdeg, act_bytes,
                                           tier=tier)
            if hop is None and tier is not None:
                hop = cm.calib.collective_time("ppermute", sdeg,
                                               act_bytes)
        if hop is not None:
            floor = cm.calib.dispatch_s or 0.0
            return max((sdeg - 1) * float(hop), floor)
        return cm.decode_collective_cost(act_bytes, "all_gather", sdeg)

    def kv_seq_degree(self, layer: Layer,
                      assign: Dict[str, Tuple[int, ...]]) -> int:
        """Sequence-dim KV shard degree scored for this layer: the
        mesh's seq degree when context sharding WINS — the per-step
        cache-read saving beats the per-step partial-output combine —
        or when the head-sharded cache alone cannot fit this model's
        HBM share (the long-prompt bucket a flat cache would reject);
        1 otherwise. Deterministic in (layer, assign) so ``evaluate``,
        ``kv_plan`` and the audit all agree."""
        sdeg = int(getattr(self.dmesh, "seq_degree", 0) or 0)
        if sdeg < 2:
            return 1
        spec = kv_cache_spec(layer)
        if spec is None or self.max_seq % sdeg != 0:
            return 1
        kv_deg = kv_shard_degree(layer, self.options[layer.name],
                                 assign.get(layer.name, ()))
        flat = kv_cache_bytes(layer, self.bucket, self.max_seq, kv_deg)
        saved = self.cost.kv_read_time(flat) \
            - self.cost.kv_read_time(flat // sdeg)
        act = self.bucket * spec["embed_dim"] * KV_DTYPE_BYTES
        if saved > self._seq_combine_cost(act, sdeg):
            return sdeg
        # memory-bound adoption: head-sharded residency across all
        # cache layers busts HBM — seq sharding is what makes the
        # bucket feasible at all
        if flat * max(self._n_cache, 1) > self.cost.spec.hbm_bytes:
            return sdeg
        return 1

    def evaluate(self, assign: Dict[str, Tuple[int, ...]]) -> ServingCost:
        prefill = dec_compute = dec_comm = 0.0
        mem = kv_total = 0
        sb = self.bucket / max(self.compile_batch, 1)
        seq = max(self.compile_seq, 1)
        out_degrees: Dict[int, Dict[int, int]] = {}
        for layer in self.layers:
            opts = self.options[layer.name]
            degs = self._degrees_of(layer, assign)
            if not self.bucket_feasible(layer,
                                        assign.get(layer.name, ())):
                # unrealizable at this bucket: make the walk reject it
                return ServingCost(float("inf"), float("inf"),
                                   float("inf"), 0, 0)
            wdeg = 1
            head_deg = 1
            for opt, d in zip(opts, assign.get(layer.name, ())):
                if d > 1 and opt.weight_dims:
                    wdeg *= d
                if d > 1 and opt.kind == "parameter" \
                        and opt.out_dim == -1:
                    head_deg = d
            cm = self.cost.op_cost(layer, degs, wdeg)
            # ---- prefill: one full-sequence forward at the bucket ----
            l_prefill = cm.forward_time * sb
            # ---- decode step: one token through the same weights ----
            # compute shrinks ~1/seq for sequence-carrying ops (the
            # fused attention's per-step cost is O(S) cache reads, which
            # fwd/seq captures); the floor is the HBM side — every
            # decode step re-reads the full local weights and KV cache
            kv_deg = kv_shard_degree(layer, opts,
                                     assign.get(layer.name, ()))
            kv_sdeg = self.kv_seq_degree(layer, assign)
            kv_local = kv_cache_bytes(layer, self.bucket, self.max_seq,
                                      kv_deg * kv_sdeg)
            kv_total += kv_local
            if kv_sdeg > 1:
                # seq-sharded KV: each step combines partial outputs
                # over the sequence axis (flash-decoding rotation)
                spec_l = kv_cache_spec(layer) or {}
                dec_comm += self._seq_combine_cost(
                    self.bucket * int(spec_l.get("embed_dim") or 0)
                    * KV_DTYPE_BYTES, kv_sdeg)
            seq_scale = 1.0 / seq \
                if self._carries_seq(layer.outputs[0].shape
                                     if layer.outputs else None) else 1.0
            l_dec = max(cm.forward_time * sb * seq_scale,
                        self.cost.kv_read_time(cm.weights_memory
                                               + kv_local))
            # ---- communication -------------------------------------
            # producer/consumer resharding, forward-only, at serving
            # shapes; decode moves one-token activations (the small-
            # message rows of the calibration tables)
            for t in layer.inputs:
                src = out_degrees.get(t.guid, {})
                dst = {d: v for d, v in degs.items()
                       if d < len(t.shape) and t.shape[d] % v == 0} \
                    if t.shape else {}
                tb = int(np.prod(t.shape)) * itemsize(t.dtype) \
                    if t.shape else 0
                t_seq = 1.0 / seq if self._carries_seq(t.shape) else 1.0
                l_prefill += self.cost.resharding_cost(tb * sb, src, dst)
                dec_comm += self.cost.resharding_cost(
                    tb * sb * t_seq, src, dst)
            # head-parallel attention ends in an all-reduce after wo
            # (opshard: out_dim == -1, output unsharded on hidden);
            # per decode step that is a (bucket × embed) payload —
            # priced latency-side (xfer_cost: calibrated small-message
            # rows + placement tree + dispatch floor)
            if head_deg > 1:
                spec = kv_cache_spec(layer) or {}
                embed = spec.get("embed_dim") or (
                    int(layer.outputs[0].shape[-1])
                    if layer.outputs and layer.outputs[0].shape else 0)
                act = self.bucket * embed * KV_DTYPE_BYTES
                l_prefill += self.cost.xfer_cost(act * seq, "all_reduce",
                                                 head_deg)
                dec_comm += self.cost.decode_collective_cost(
                    act, "all_reduce", head_deg)
            prefill += l_prefill
            dec_compute += l_dec
            for o in layer.outputs:
                out_degrees[o.guid] = degs
            # resident memory: weights (no grads/optimizer states in
            # serving) + KV cache + double-buffered activations at the
            # serving batch
            mem += cm.weights_memory + kv_local \
                + 2 * int(cm.outputs_memory * sb)
        decode_step = dec_compute + dec_comm
        total = prefill + self.decode_tokens * decode_step
        if mem > self.cost.spec.hbm_bytes:
            total *= 100.0  # infeasible: KV + weights exceed HBM
        return ServingCost(total, prefill, decode_step, kv_total, mem,
                           decode_compute=dec_compute,
                           decode_comm=dec_comm)


# ---------------------------------------------------------------------------
# per-bucket search
# ---------------------------------------------------------------------------

def serving_baseline_assignment(layers: Sequence[Layer],
                                dmesh: DeviceMesh,
                                evaluator: ServingCostEvaluator
                                ) -> Dict[str, Tuple[int, ...]]:
    """The reused-training-plan analog: batch-parallel wherever the
    compile shape AND the bucket allow it, degree clamped to the
    largest mesh-realizable divisor (bucket 1 yields the all-replicated
    plan — exactly what reusing a DP training plan degrades to)."""
    n = dmesh.num_devices
    valid = sorted(dmesh.valid_degrees(), reverse=True)
    assign: Dict[str, Tuple[int, ...]] = {}
    for l in layers:
        degs = []
        for opt in evaluator.options[l.name]:
            d = 1
            if opt.kind == "sample" and opt.out_dim == 0 and l.outputs \
                    and l.outputs[0].shape:
                for cand in valid:
                    if cand <= n \
                            and l.outputs[0].shape[0] % cand == 0 \
                            and evaluator.bucket % cand == 0:
                        d = cand
                        break
            degs.append(d)
        cand = tuple(degs)
        if assignment_to_sharding(l, evaluator.options[l.name], cand,
                                  dmesh) is None:
            cand = tuple(1 for _ in degs)
        assign[l.name] = cand
    return assign


def search_serving_assignment(layers: Sequence[Layer],
                              dmesh: DeviceMesh,
                              cost_model: OpCostModel,
                              bucket: int, max_seq: int,
                              budget: int = 200,
                              decode_tokens: Optional[int] = None,
                              seed: int = 0, alpha: float = 0.05
                              ) -> Tuple[Dict[str, Tuple[int, ...]],
                                         ServingCost,
                                         ServingCostEvaluator]:
    """MCMC walk over per-op assignments under the serving objective
    for one bucket (same proposal scheme as ``mcmc.mcmc_search``, plus
    the bucket-divisibility constraint on batch-dim degrees)."""
    rng = random.Random(seed ^ (bucket << 16))
    ev = ServingCostEvaluator(layers, dmesh, cost_model, bucket,
                              max_seq, decode_tokens)
    valid_degrees = dmesh.valid_degrees()
    current = serving_baseline_assignment(layers, dmesh, ev)
    cur = ev.evaluate(current)
    best, best_cost = dict(current), cur
    shardable = [l for l in layers if ev.options[l.name]]
    if not shardable or budget <= 0:
        return best, best_cost, ev
    from .mcmc import _propagate_neighbors
    consumers: Dict[int, List[Layer]] = {}
    for l in layers:
        for t in l.inputs:
            consumers.setdefault(t.guid, []).append(l)
    with obs_events.span("serving.search", bucket=bucket,
                         budget=budget):
        for it in range(budget):
            layer = rng.choice(shardable)
            opts = ev.options[layer.name]
            oi = rng.randrange(len(opts))
            old = current[layer.name]
            choices = [d for d in valid_degrees
                       if d * math.prod(old[:oi] + old[oi + 1:])
                       <= dmesh.num_devices]
            if not choices:
                continue
            cand = old[:oi] + (rng.choice(choices),) + old[oi + 1:]
            if not ev.bucket_feasible(layer, cand):
                continue
            if assignment_to_sharding(layer, opts, cand, dmesh) is None:
                continue
            moves = _propagate_neighbors(layer, cand, ev, consumers,
                                         dmesh, rng)
            moves = {n: c for n, c in moves.items()
                     if ev.bucket_feasible(
                         next(l for l in layers if l.name == n), c)}
            if layer.name not in moves:
                continue
            olds = {n: current[n] for n in moves}
            current.update(moves)
            nxt = ev.evaluate(current)
            delta = nxt.total - cur.total
            if delta < 0 or (math.isfinite(delta) and rng.random()
                             < math.exp(-delta / max(alpha * cur.total,
                                                     1e-12))):
                cur = nxt
                if nxt.total < best_cost.total:
                    best, best_cost = dict(current), nxt
            else:
                current.update(olds)
    return best, best_cost, ev


def serving_assignment_to_strategy(layers: Sequence[Layer],
                                   input_tensors,
                                   assign: Dict[str, Tuple[int, ...]],
                                   dmesh: DeviceMesh,
                                   evaluator: ServingCostEvaluator
                                   ) -> ShardingStrategy:
    """Materialize one bucket's assignment. Unlike the training path,
    input batch specs are only emitted when the batch degree divides
    the BUCKET (the runtime batch), not the full device count."""
    from jax.sharding import PartitionSpec as P
    st = ShardingStrategy(dmesh)
    batch_axes = None
    batch_deg = 1
    for layer in layers:
        opts = evaluator.options[layer.name]
        degs = assign.get(layer.name, ())
        res = assignment_to_sharding(layer, opts, degs, dmesh)
        if res is None:
            continue
        out_specs, wspecs = res
        st.set_op(layer.name, out_specs, wspecs)
        if batch_axes is None and out_specs and out_specs[0] \
                and len(out_specs[0]) > 0 and out_specs[0][0] is not None:
            for opt, d in zip(opts, degs):
                if opt.kind == "sample" and opt.out_dim == 0 and d > 1:
                    batch_axes = out_specs[0][0]
                    batch_deg = d
    for t in input_tensors:
        if batch_axes is not None and t.shape \
                and t.shape[0] % batch_deg == 0 \
                and evaluator.bucket % batch_deg == 0:
            st.inputs[t.name] = P(batch_axes)
    return st


# ---------------------------------------------------------------------------
# plan container + entry point
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class BucketPlan:
    bucket: int
    assignment: Dict[str, Tuple[int, ...]]
    strategy: ShardingStrategy
    cost: ServingCost
    kv: Dict[str, Dict[str, int]]
    # calibration provenance of the adopted assignment's predicted cost
    # (deduped {term, table, key} rows from the cost model's provenance
    # tap) — what serving drift detection attributes out-of-band
    # prefill/decode ratios to
    calib: List[Dict] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class ServingPlan:
    """Per-(model, batch-class) searched plans + shared geometry."""
    buckets: Dict[int, BucketPlan]
    max_seq: int
    decode_tokens: int
    baseline: Dict[int, ServingCost]

    @property
    def largest(self) -> BucketPlan:
        return self.buckets[max(self.buckets)]

    def to_block(self) -> Dict:
        """The ``serving`` block of the strategy artifact: one complete
        sub-strategy (ops + inputs + assignment) per bucket, so load
        paths can adopt any bucket's plan standalone."""
        from .serialization import _spec_to_json
        block: Dict = {"version": 1, "max_seq": self.max_seq,
                       "decode_tokens": self.decode_tokens,
                       "buckets": {}}
        for b, plan in sorted(self.buckets.items()):
            st = plan.strategy
            ops = {}
            for name, op in st.ops.items():
                ops[name] = {
                    "outputs": [_spec_to_json(s) for s in op.outputs],
                    "weights": {w: _spec_to_json(s)
                                for w, s in op.weights.items()}}
            block["buckets"][str(b)] = {
                "ops": ops,
                "inputs": {n: _spec_to_json(s)
                           for n, s in st.inputs.items()},
                "assignment": {n: list(d)
                               for n, d in plan.assignment.items()},
                "kv": plan.kv,
                "predicted": {
                    "prefill_s": plan.cost.prefill,
                    "decode_step_s": plan.cost.decode_step,
                    "decode_comm_s": plan.cost.decode_comm,
                    "kv_bytes": plan.cost.kv_bytes,
                    "peak_memory_bytes": plan.cost.peak_memory,
                    "total_s": plan.cost.total},
                "calib": plan.calib}
        return block


def bucket_strategy_doc(doc: Dict, bucket: int) -> Dict:
    """Extract one bucket's sub-strategy from a serving artifact as a
    standalone strategy document (loadable by
    ``serialization.load_strategy`` / importable via
    ``FFConfig.import_strategy_file``)."""
    serving = doc.get("serving")
    if not serving:
        raise ValueError("strategy document carries no serving block")
    bkey = str(int(bucket))
    if bkey not in serving.get("buckets", {}):
        raise KeyError(
            f"serving block has no bucket {bucket} "
            f"(have {sorted(serving.get('buckets', {}))})")
    sub = serving["buckets"][bkey]
    return {"version": doc.get("version", 1),
            "mesh_axes": doc["mesh_axes"],
            "inputs": sub.get("inputs", {}),
            "ops": sub["ops"],
            "assignment": sub.get("assignment", {}),
            "meta": {"serving_bucket": int(bucket)},
            # single-bucket serving block: load_strategy attaches it to
            # the strategy, so compile's plan verification runs the
            # serving KV/envelope checks at THIS bucket — an unsharded
            # KV cache that does not fit fails typed at compile
            "serving": {"version": serving.get("version", 1),
                        "max_seq": serving.get("max_seq"),
                        "decode_tokens": serving.get("decode_tokens"),
                        "buckets": {bkey: sub}}}


def _assignment_provenance(ev: ServingCostEvaluator, assign) -> List[Dict]:
    """Calibration provenance of one bucket's adopted assignment:
    re-score it with the cost model's provenance tap installed (the
    attribution/drift machinery's tap, here installed by the serving
    evaluator too) and dedup the recorded rows to ``{term, table,
    key}``.  READ-ONLY by construction — the tap changes what
    ``op_cost`` RECORDS, never what it returns, so pricing (and the
    fidelity number keyed on it) is untouched."""
    cm = ev.cost
    prev = cm.provenance
    cm.provenance = []
    try:
        ev.evaluate(assign)
        rows = cm.provenance
    finally:
        cm.provenance = prev
    seen = set()
    out: List[Dict] = []
    for r in rows:
        k = (r.get("term"), r.get("table"), r.get("key"))
        if k in seen:
            continue
        seen.add(k)
        out.append({"term": r.get("term"),
                    "table": r.get("table") or "analytic",
                    "key": r.get("key")})
    return out


def _serving_cost_model(ff, dmesh) -> OpCostModel:
    """The cost model serving scoring prices with. Reuses the training
    search's calibrated model when compile built one (the common path);
    otherwise builds one the same way — placement attached, collective
    constants fitted on the live mesh unless a machine file is the
    ground truth. Calibration tables are READ here, never refit: the
    fidelity number (`virtual_fidelity_spearman`) keys on them."""
    cm = getattr(ff, "_search_cost_model", None)
    if cm is not None:
        return cm
    cfg = ff.config
    cm = OpCostModel(dmesh.spec)
    cm.segment_size = max(1, cfg.simulator_segment_size)
    cm.max_segments = max(1, cfg.simulator_max_num_segments)
    from .optimizer import _attach_placement
    _attach_placement(cfg, cm, dmesh)
    if not cfg.machine_model_file:
        try:
            cm.calibrate_collectives(dmesh)
        except Exception:  # noqa: BLE001 — analytic constants suffice
            pass
    return cm


def optimize_serving_strategy(ff, buckets: Optional[Sequence[int]] = None,
                              max_seq: Optional[int] = None,
                              budget: Optional[int] = None,
                              decode_tokens: Optional[int] = None,
                              verify: bool = True) -> ServingPlan:
    """Search one serving plan per batch bucket (``optimize_strategy``'s
    ``mode="serving"``). ``ff`` must be compiled (or at least carry a
    ``dmesh``): the mesh the plans target is the mesh serving runs on.

    Verifies the per-bucket plans (KV sharding sound, serving memory
    envelope fits at the largest bucket — typed
    ``PlanVerificationError`` otherwise), writes a ``serving`` audit
    block, and exports the artifact when
    ``FFConfig.export_strategy_file`` is set."""
    if getattr(ff, "dmesh", None) is None:
        raise ValueError("compile() the model first: serving plans "
                         "target the compiled mesh")
    cfg = ff.config
    dmesh = ff.dmesh
    if buckets is None:
        buckets = cfg.serving_buckets_list() or DEFAULT_BUCKETS
    buckets = sorted(set(int(b) for b in buckets))
    cost_model = _serving_cost_model(ff, dmesh)
    probe = ServingCostEvaluator(ff.layers, dmesh, cost_model, 1, 1)
    if max_seq is None:
        max_seq = cfg.serving_max_seq or probe.compile_seq
    if decode_tokens is None:
        decode_tokens = cfg.serving_decode_tokens or 0
    budget = budget if budget is not None else (
        cfg.search_budget if cfg.search_budget > 0 else 200)
    t0 = time.perf_counter()
    plans: Dict[int, BucketPlan] = {}
    baseline: Dict[int, ServingCost] = {}
    for b in buckets:
        best, best_cost, ev = search_serving_assignment(
            ff.layers, dmesh, cost_model, b, max_seq, budget=budget,
            decode_tokens=decode_tokens or None, seed=cfg.seed)
        baseline[b] = ev.evaluate(
            serving_baseline_assignment(ff.layers, dmesh, ev))
        strategy = serving_assignment_to_strategy(
            ff.layers, ff.graph_inputs, best, dmesh, ev)
        errs = strategy.validate()
        if errs:
            raise RuntimeError(f"serving search produced an unsound "
                               f"strategy at bucket {b}: {errs}")
        plans[b] = BucketPlan(b, best, strategy, best_cost,
                              ev.kv_plan(best),
                              calib=_assignment_provenance(ev, best))
    plan = ServingPlan(plans, int(max_seq),
                       int(decode_tokens or max_seq), baseline)
    # the per-bucket strategies carry their serving block so any later
    # verify_plan/verify_model pass runs the serving checks on them
    block = plan.to_block()
    for b, p in plan.buckets.items():
        p.strategy.serving = {
            "version": block["version"], "max_seq": block["max_seq"],
            "decode_tokens": block["decode_tokens"],
            "buckets": {str(b): block["buckets"][str(b)]}}
    if verify:
        from ..analysis.plan_verifier import verify_serving_plan
        hbm = None
        if getattr(cfg, "device_mem_mb", 0):
            hbm = cfg.device_mem_mb * (1 << 20)
        verify_serving_plan(plan, ff.layers, dmesh,
                            hbm_bytes=hbm, context="serving-search")
    _write_serving_audit(ff, plan, time.perf_counter() - t0)
    if cfg.export_strategy_file:
        save_serving_plan(cfg.export_strategy_file, plan)
    ff._serving_plan = plan
    return plan


def save_serving_plan(path: str, plan: ServingPlan) -> None:
    """Write the serving artifact: the largest bucket's strategy as the
    base document + the per-bucket ``serving`` block."""
    from .serialization import save_strategy
    big = plan.largest
    save_strategy(path, big.strategy, big.assignment,
                  meta={"mode": "serving",
                        "buckets": sorted(plan.buckets),
                        "max_seq": plan.max_seq},
                  serving=plan.to_block())


def _write_serving_audit(ff, plan: ServingPlan, search_s: float) -> None:
    """Strategy audit record with a ``serving`` block: per-bucket
    predicted prefill/decode-step/kv profile of the adopted plan vs the
    reused-training-plan baseline."""
    if not obs_events.enabled():
        return
    try:
        key = obs_audit.workload_key(ff.layers, ff.dmesh.num_devices)
        buckets = {}
        for b, p in sorted(plan.buckets.items()):
            base = plan.baseline.get(b)
            buckets[str(b)] = {
                "prefill_s": p.cost.prefill,
                "decode_step_s": p.cost.decode_step,
                "decode_comm_s": p.cost.decode_comm,
                "kv_bytes": p.cost.kv_bytes,
                "peak_memory_bytes": p.cost.peak_memory,
                "baseline_decode_step_s":
                    base.decode_step if base else None,
                "predicted_baseline_over_searched":
                    (base.decode_step / max(p.cost.decode_step, 1e-12))
                    if base else None,
                "kv": p.kv,
                "assignment": {n: list(d)
                               for n, d in p.assignment.items()},
                "calib": p.calib}
        record = {
            "search_algo": "serving",
            "ranker": "serving-latency",
            "n_devices": ff.dmesh.num_devices,
            "search_s": round(search_s, 4),
            "serving": {"max_seq": plan.max_seq,
                        "decode_tokens": plan.decode_tokens,
                        "buckets": buckets}}
        path = obs_audit.write_strategy_audit(record, key + "-serving")
        if path:
            ff._strategy_audit_path = path
            obs_events.counter("search.serving_audit_records")
    except Exception:  # noqa: BLE001 — audit must never kill the search
        pass
