"""Unity auto-parallelization search: best-first substitution search with
alpha pruning, recursive sequence-split DP with memoization, and
memory-aware multi-objective search.

Reference analogs:
  - ``base_optimize`` ≙ ``GraphSearchHelper::base_optimize``
    (``substitution.cc:2229``): cost-ordered priority queue of candidate
    graphs, pop best, apply every xfer, keep candidates within
    ``alpha``× best, stop at ``budget`` expansions.
  - ``sequence_optimize`` ≙ ``generic_sequence_optimize``
    (``substitution.cc:2572``): split at a bottleneck (post-dominator of
    all sources), DP over the cut tensor's layout (the analog of the
    (source view, sink view) machine-view pairs), memoized by
    ``dp_state_hash`` (``graph.cc:1863``).
  - ``graph_optimize_with_memory`` ≙ ``substitution.cc:1960`` +
    ``try_one_lambda`` (``graph.cc:1883``): binary search on lambda
    weighting per-device memory against the HBM budget.

The evaluator's execution model is TPU-SPMD: every op runs on the whole
mesh (sharded by its annotation), so graph run time is additive over nodes
(unlike the reference's per-view concurrent placement — that role is played
by pipeline parallelism, handled separately).
"""
from __future__ import annotations

import dataclasses
import heapq
import itertools
import os
import time
from typing import Dict, FrozenSet, List, Optional, Sequence, Tuple

import numpy as np

from ..core.layer import Layer
from ..core.tensor import Tensor
from ..dtypes import itemsize
from ..ffconst import OperatorType, PARALLEL_OPS
from ..obs import events as obs_events
from ..parallel.machine import DeviceMesh
from ..parallel.strategy import ShardingStrategy
from ..pcg.graph import Graph, GraphProgramInfo, ParAnn, PNode
from .costmodel import OpCostModel
from .mcmc import GraphCost
from .substitution import GraphXfer, generate_all_pcg_xfers

Layout = Tuple[Tuple[int, int], ...]       # sorted ((dim, degree), ...)


def _layout(d: Dict[int, int]) -> Layout:
    return tuple(sorted((k, v) for k, v in d.items() if v > 1))


def _coll_bytes(full_bytes: int, in_lay: Layout, own_degree: int = 1) -> int:
    """Logical bytes moved by ONE parallel-op collective group when the
    tensor is co-partitioned by other groups/dims.

    A Combine(dim, d) on a tensor also batch-partitioned by b gathers a
    region of ``full/b`` bytes within each batch shard — charging the
    full tensor would overprice composed (2D) machine views by the
    co-partition factor. ``own_degree`` is the collective's own degree
    when it already appears in the producer layout (Combine)."""
    prod = 1
    for _, d in in_lay:
        prod *= d
    prod = max(1, prod // max(own_degree, 1))
    return max(full_bytes // prod, 1) if full_bytes else 0


def _bytes_of(t: Tensor) -> int:
    return int(np.prod(t.shape)) * itemsize(t.dtype) if t.shape else 0


# ---------------------------------------------------------------------------
# Graph cost evaluation
# ---------------------------------------------------------------------------
def propagate_layouts(graph: Graph,
                      in_pins: Optional[Dict[int, Layout]] = None
                      ) -> Dict[Tuple[int, int], Layout]:
    """(node guid, out_idx) -> layout. Parallel ops transform their
    input layout; compute ops emit their annotation's layout."""
    lay: Dict[Tuple[int, int], Layout] = {}
    in_pins = in_pins or {}
    for n in graph.topo_order():
        t = n.op_type
        in_lay: Layout = ()
        e = graph.producer(n, 0)
        if e is not None:
            in_lay = lay[(e.src.guid, e.src_idx)]
        else:
            for s, tens in graph.external_inputs.get(n.guid, ()):
                if s == 0 and tens.guid in in_pins:
                    in_lay = in_pins[tens.guid]
        if t == OperatorType.OP_REPARTITION:
            d = dict(in_lay)
            dim = n.layer.params["dim"]
            d[dim] = d.get(dim, 1) * n.layer.params["degree"]
            out = _layout(d)
        elif t == OperatorType.OP_COMBINE:
            d = dict(in_lay)
            d.pop(n.layer.params["dim"], None)
            out = _layout(d)
        elif t in (OperatorType.OP_REPLICATE, OperatorType.OP_REDUCTION,
                   OperatorType.OP_NOOP, OperatorType.OP_PIPELINE,
                   OperatorType.OP_FUSED_PARALLEL, OperatorType.OP_INPUT):
            out = in_lay
        else:
            out = _layout(n.ann.out_degrees(0))
        for i in range(max(len(n.layer.outputs), 1)):
            lay[(n.guid, i)] = out if i == 0 else _layout(
                n.ann.out_degrees(i))
    return lay


class GraphCostEvaluator:
    """Scores a PCG: additive node costs + reified communication costs +
    gradient-sync costs + per-device peak memory."""

    def __init__(self, cost_model: OpCostModel, dmesh: DeviceMesh,
                 mem_lambda: float = 0.0):
        self.cost = cost_model
        self.dmesh = dmesh
        self.mem_lambda = mem_lambda  # $/byte weighting for memory-aware DP
        self._cache: Dict[Tuple, GraphCost] = {}

    # -- expected input layout of a compute node ----------------------------
    def _expected_input(self, node: PNode, in_idx: int,
                        in_shape: Tuple[int, ...]) -> Layout:
        ann = node.ann
        if ann.is_trivial():
            return ()
        if ann.replicate is not None:
            return ()
        if ann.reduce is not None and in_idx == 0 and in_shape:
            # contraction dim partitioned by the reduce group, PLUS any
            # co-partitioned output dims (e.g. the dp batch dim of the
            # composed row-parallel 2D rule) that pass through the input
            degs = {len(in_shape) - 1: ann.degree_of(ann.reduce)}
            for d, v in ann.out_degrees(0).items():
                if d < len(in_shape) - 1 and in_shape[d] % v == 0:
                    degs[d] = v
            return _layout(degs)
        degs = {d: v for d, v in ann.out_degrees(0).items()
                if in_shape and d < len(in_shape)
                and in_shape[d] % v == 0}
        # parameter-dim placements don't constrain the input
        out_shape = node.layer.outputs[0].shape
        if in_shape and out_shape and in_shape[-1] != out_shape[-1] \
                and len(in_shape) - 1 in degs:
            degs.pop(len(in_shape) - 1, None)
        return _layout(degs)

    # -- cost ---------------------------------------------------------------
    def graph_cost(self, graph: Graph,
                   in_pins: Optional[Dict[int, Layout]] = None,
                   out_pin: Optional[Layout] = None) -> GraphCost:
        key = (graph.hash(),
               tuple(sorted((in_pins or {}).items())),
               out_pin, self.mem_lambda)
        hit = self._cache.get(key)
        if hit is not None:
            obs_events.counter("unity.graph_cost_cache_hits")
            return hit
        obs_events.counter("unity.graph_cost_evals")
        gc, _ = self._evaluate(graph, in_pins, out_pin, breakdown=False)
        self._cache[key] = gc
        return gc

    def graph_cost_breakdown(self, graph: Graph,
                             in_pins: Optional[Dict[int, Layout]] = None,
                             out_pin: Optional[Layout] = None
                             ) -> Tuple[GraphCost, List[Dict]]:
        """(GraphCost, per-op entries) — uncached; the entries' component
        sums equal the GraphCost components BY CONSTRUCTION (the
        aggregate is accumulated from the same per-node terms), which is
        what makes the strategy audit record diffable against the
        search's reported cost."""
        try:
            return self._evaluate(graph, in_pins, out_pin,
                                  breakdown=True)
        finally:
            # the tap must not survive onto the search's hot loop (the
            # cost model is shared across evaluators)
            self.cost.provenance = None

    def _evaluate(self, graph: Graph, in_pins: Optional[Dict[int, Layout]],
                  out_pin: Optional[Layout], breakdown: bool
                  ) -> Tuple[GraphCost, List[Dict]]:
        lay = propagate_layouts(graph, in_pins)
        compute = xfer = sync = 0.0
        mem = 0
        entries: List[Dict] = []
        n_dev = self.dmesh.num_devices
        # overlap-aware sync pricing (OpCostModel.overlap_mode): collect
        # every compute node's (backward compute, grad-sync cost) in
        # topo order; the hidden/exposed split is resolved after the
        # walk by _overlap_split. Serial mode (default) keeps the exact
        # historical accumulation.
        overlap_on = bool(getattr(self.cost, "overlap_mode", False))
        sync_sites: List[Dict] = []
        if breakdown:
            # calibration-row provenance tap (obs/drift.py): the cost
            # model appends which table row answered each pricing call;
            # note() folds the rows accumulated since the previous
            # entry into that entry's "calib" list. Breakdowns are
            # uncached audit-only evaluations, so the tap never rides
            # along on the search's hot loop.
            self.cost.provenance = []

        def note(node, fwd=0.0, bwd=0.0, nx=0.0, ns=0.0, nmem=0):
            if breakdown:
                e = {
                    "name": node.layer.name,
                    "op_type": getattr(node.op_type, "name",
                                       str(node.op_type)),
                    "fwd_s": fwd, "bwd_s": bwd, "xfer_s": nx,
                    "sync_s": ns, "mem_bytes": nmem,
                    "total_s": fwd + bwd + nx + ns
                    + self.mem_lambda * nmem}
                if ns > 0:
                    # the wire dtype this site's gradient collective was
                    # priced at ("float32" unless a quantized-
                    # collectives policy narrowed it) — drift detection
                    # attributes quantized rows by it
                    e["sync_wire"] = getattr(self.cost,
                                             "last_sync_wire",
                                             "float32")
                if (fwd or bwd) and getattr(self.cost,
                                            "last_kernel_impl", None):
                    # the kernel implementation this compute node was
                    # priced at (searchable kernel tier) — fresh only
                    # right after op_cost_with_impl, hence the fwd/bwd
                    # guard keeps it off reshard-only entries
                    e["kernel_impl"] = self.cost.last_kernel_impl
                prov = self.cost.provenance
                if prov:
                    e["calib"] = list(prov)
                if prov is not None:
                    del prov[:]
                entries.append(e)

        for n in graph.topo_order():
            t = n.op_type
            in_bytes = 0
            in_lay: Layout = ()
            e0 = graph.producer(n, 0)
            if e0 is not None:
                src_t = e0.src.layer.outputs[e0.src_idx]
                in_bytes = _bytes_of(src_t)
                in_lay = lay[(e0.src.guid, e0.src_idx)]
            elif n.layer.inputs:
                in_bytes = _bytes_of(n.layer.inputs[0])
                for s, tens in graph.external_inputs.get(n.guid, ()):
                    if s == 0 and in_pins and tens.guid in in_pins:
                        in_lay = in_pins[tens.guid]
            if t in (OperatorType.OP_INPUT, OperatorType.OP_NOOP,
                     OperatorType.OP_WEIGHT):
                continue
            if t == OperatorType.OP_REPARTITION:
                deg = n.layer.params["degree"]
                # fwd: slicing replicated/owned data is (near-)local under
                # SPMD; bwd: the cotangent re-gathers within the group.
                # Charged on the per-existing-shard region so composed
                # (2D) views aren't overpriced by the co-partition factor.
                nx = self.cost.xfer_cost(_coll_bytes(in_bytes, in_lay),
                                         "all_to_all", deg)
                xfer += nx
                note(n, nx=nx)
                continue
            if t == OperatorType.OP_COMBINE:
                deg = n.layer.params["degree"]
                eff = _coll_bytes(in_bytes, in_lay, deg)
                nx = self.cost.xfer_cost(eff, "all_gather", deg) \
                    + self.cost.xfer_cost(eff, "all_to_all", deg)
                xfer += nx
                note(n, nx=nx)
                continue
            if t == OperatorType.OP_REPLICATE:
                deg = n.layer.params["degree"]
                # fwd free under SPMD when input already replicated;
                # bwd: all-reduce of input cotangent across the group
                nx = self.cost.xfer_cost(_coll_bytes(in_bytes, in_lay),
                                         "all_reduce", deg)
                xfer += nx
                note(n, nx=nx)
                continue
            if t == OperatorType.OP_REDUCTION:
                deg = n.layer.params["degree"]
                nx = self.cost.xfer_cost(_coll_bytes(in_bytes, in_lay),
                                         "all_reduce", deg)
                xfer += nx
                note(n, nx=nx)
                continue
            if t in (OperatorType.OP_PIPELINE,
                     OperatorType.OP_FUSED_PARALLEL):
                continue
            # ---- compute node ----
            ann = n.ann
            scale_groups = {g for (_, _, g) in ann.out}
            if ann.reduce:
                scale_groups.add(ann.reduce)
            scale = 1
            for g in scale_groups:
                scale *= ann.degree_of(g)
            degs = {0: scale} if scale > 1 else {}
            # kernel tier attached: attention prices at its cheapest
            # available implementation (the impl is a search dimension)
            cm = self.cost.op_cost_with_impl(n.layer, degs,
                                             ann.weight_degree())
            compute += cm.forward_time + cm.backward_time
            n_mem = cm.weights_memory * 4 + cm.outputs_memory
            mem += n_mem
            # input mismatch safety net
            n_xfer = 0.0
            for e in graph.in_edges[n]:
                src_lay = lay[(e.src.guid, e.src_idx)]
                src_t = e.src.layer.outputs[e.src_idx]
                want = self._expected_input(n, e.dst_idx, src_t.shape)
                if src_lay != want:
                    n_xfer += self.cost.resharding_cost(
                        _bytes_of(src_t), dict(src_lay), dict(want))
            xfer += n_xfer
            # gradient sync for weights: all-reduce over the mesh part not
            # sharding the weight
            n_sync = 0.0
            wdeg = ann.weight_degree()
            wbytes = sum(_bytes_of_spec(w) for w in n.layer.weights)
            if wbytes:
                dp_deg = max(1, n_dev // max(wdeg, 1))
                n_sync = self.cost.weight_sync_cost(
                    wbytes // max(wdeg, 1), dp_deg)
            sync += n_sync
            note(n, fwd=cm.forward_time, bwd=cm.backward_time,
                 nx=n_xfer, ns=n_sync, nmem=n_mem)
            if overlap_on:
                sync_sites.append({
                    "bwd": cm.backward_time, "sync": n_sync,
                    "entry": entries[-1] if breakdown else None})
        # output pin: resharding from final layout to the pinned layout
        if out_pin is not None and graph.outputs:
            n0, i0 = graph.outputs[0]
            fin = lay.get((n0.guid, i0), ())
            if fin != out_pin:
                nx = self.cost.resharding_cost(
                    _bytes_of(n0.layer.outputs[i0]), dict(fin),
                    dict(out_pin))
                xfer += nx
                if breakdown:
                    e = {
                        "name": "__out_pin__", "op_type": "RESHARD",
                        "fwd_s": 0.0, "bwd_s": 0.0, "xfer_s": nx,
                        "sync_s": 0.0, "mem_bytes": 0, "total_s": nx}
                    prov = self.cost.provenance
                    if prov:
                        e["calib"] = list(prov)
                        del prov[:]
                    entries.append(e)
        sync_hidden = 0.0
        if overlap_on and sync > 0:
            sync, sync_hidden = _overlap_split(sync_sites)
        total = compute + xfer + sync + self.mem_lambda * mem
        return GraphCost(total, compute, xfer, sync, mem,
                         sync_hidden=sync_hidden), entries


def _overlap_split(sync_sites: Sequence[Dict]) -> Tuple[float, float]:
    """Resolve per-site hidden vs exposed gradient-sync cost under the
    overlap schedule's execution model (``runtime/overlap.py``): the
    backward pass runs nodes in REVERSE topo order, each weighted
    node's sync launches when its backward slice completes, and syncs
    drain FIFO through one comm channel concurrent with the remaining
    backward compute. A sync's exposed cost is the part of its channel
    occupancy that extends past the end of backward — per-site
    ``max(0, comm − hideable backward compute)``, with the channel
    queue keeping two syncs from hiding behind the same compute.

    Mutates each site's breakdown entry (when present): ``sync_s``
    becomes the exposed cost, ``sync_hidden_s``/``sync_full_s`` record
    the split — so audit entries still sum exactly to the GraphCost
    components. Returns (exposed_total, hidden_total).

    The event-driven task simulator (``tasksim.TaskGraphEvaluator.
    overlap_estimate``) is the authoritative overlap model this
    closed-form split is checked against (bench ``comm_overlap`` leg
    gates agreement within 2x)."""
    t_bwd = 0.0   # backward clock at each launch point
    chan = 0.0    # comm-channel free time
    launches: List[Tuple[float, float, Optional[Dict]]] = []
    for site in reversed(list(sync_sites)):
        t_bwd += site["bwd"]
        s = site["sync"]
        if s <= 0:
            continue
        start = max(t_bwd, chan)
        chan = start + s
        launches.append((start, s, site.get("entry")))
    exposed_total = hidden_total = 0.0
    for start, s, entry in launches:
        exposed = min(s, max(0.0, (start + s) - t_bwd))
        hidden = s - exposed
        exposed_total += exposed
        hidden_total += hidden
        if entry is not None:
            entry["sync_full_s"] = entry["sync_s"]
            entry["sync_hidden_s"] = hidden
            entry["sync_s"] = exposed
            entry["total_s"] -= hidden
    return exposed_total, hidden_total


def _bytes_of_spec(w) -> int:
    return int(np.prod(w.shape)) * itemsize(w.dtype)


# ---------------------------------------------------------------------------
# Best-first substitution search (base_optimize)
# ---------------------------------------------------------------------------
class SearchPool:
    """Global work budget shared by every ``base_optimize`` call of one
    search. The DP recursion fans out over split positions x cut
    layouts; without a GLOBAL cap the per-call budget multiplies into
    hours on deep graphs (the reference's budget is likewise a whole-
    search iteration count, ``substitution.cc`` ``budget--``)."""

    __slots__ = ("remaining", "deadline")

    def __init__(self, expansions: int, seconds: float):
        self.remaining = expansions
        self.deadline = time.monotonic() + seconds

    def take(self, want: int) -> int:
        if time.monotonic() >= self.deadline:
            return 0
        got = max(0, min(want, self.remaining))
        return got

    def spend(self, used: int):
        self.remaining -= used


def base_optimize(graph: Graph, xfers: Sequence[GraphXfer],
                  evaluator: GraphCostEvaluator, budget: int = 32,
                  alpha: float = 1.05, max_num_ops: int = 512,
                  in_pins: Optional[Dict[int, Layout]] = None,
                  out_pin: Optional[Layout] = None,
                  pool: Optional[SearchPool] = None
                  ) -> Tuple[Graph, float]:
    """Cost-ordered best-first search over rewrites
    (reference ``base_optimize``, ``substitution.cc:2229``)."""
    counter = itertools.count()
    start_cost = evaluator.graph_cost(graph, in_pins, out_pin).total
    best, best_cost = graph, start_cost
    if pool is not None:
        budget = pool.take(budget)
        if budget == 0:
            return best, best_cost
    heap: List[Tuple[float, int, Graph]] = [(start_cost, next(counter),
                                            graph)]
    seen = {graph.hash()}
    expansions = 0
    while heap and expansions < budget \
            and (pool is None or time.monotonic() < pool.deadline):
        cost, _, g = heapq.heappop(heap)
        if cost > alpha * best_cost:
            continue  # alpha-pruned
        expansions += 1
        for xfer in xfers:
            for g2 in xfer.run(g, max_num_ops):
                h = g2.hash()
                if h in seen:
                    continue
                seen.add(h)
                c2 = evaluator.graph_cost(g2, in_pins, out_pin).total
                if c2 < best_cost:
                    best, best_cost = g2, c2
                if c2 <= alpha * best_cost:
                    heapq.heappush(heap, (c2, next(counter), g2))
    if pool is not None:
        pool.spend(expansions)
    return best, best_cost


# ---------------------------------------------------------------------------
# Unity sequence-split DP
# ---------------------------------------------------------------------------
class UnitySearch:
    def __init__(self, evaluator: GraphCostEvaluator,
                 xfers: Sequence[GraphXfer], budget: int = 32,
                 alpha: float = 1.05, base_optimize_threshold: int = 12,
                 max_num_ops: int = 512,
                 pool: Optional[SearchPool] = None):
        self.ev = evaluator
        self.xfers = list(xfers)
        self.budget = budget
        self.alpha = alpha
        self.threshold = base_optimize_threshold
        self.max_num_ops = max_num_ops
        # whole-search budget: the DP visits many (subgraph, pins) leaves;
        # give the search `budget` expansions per leaf locally but at most
        # 16x `budget` expansions / 15+4*budget seconds GLOBALLY
        self.pool = pool or SearchPool(budget * 16, 15.0 + 4.0 * budget)
        self._memo: Dict[Tuple, Tuple[Graph, float]] = {}
        # structural (guid-independent) memo: identical transformer
        # blocks are isomorphic subproblems — solve one, replay the
        # rewrite onto the others (the reference memoizes by
        # dp_state_hash over op guids, graph.cc:1863, so it re-solves
        # every block; repeated-block models dominate the workload here)
        self._smemo: Dict[Tuple, Tuple[List[PNode], List, Graph,
                                       float]] = {}
        self.smemo_hits = 0
        self._run_cache: Dict[Tuple, Optional[Tuple]] = {}

    def _cut_layout_candidates(self, t: Tensor,
                               depth: int = 0) -> List[Layout]:
        """Candidate layouts of the cut tensor — the analog of enumerating
        the bottleneck node's machine views (reference ``graph.h:205``):
        replicated, every divisible dim at every realizable degree, and
        batch×feature 2-dim combinations. Ordered best-guess-first
        (replicated, batch shardings, feature, interior, combos) and
        capped at deeper DP levels to bound the layout×position
        combinatorics."""
        if not t.shape:
            return [()]
        rank = len(t.shape)
        degrees = sorted((d for d in self.ev.dmesh.valid_degrees()
                          if d > 1), reverse=True)
        batch: List[Layout] = []
        feature: List[Layout] = []
        interior_dims: List[Layout] = []
        combos: List[Layout] = []
        for d in degrees:
            if t.shape[0] % d == 0:
                batch.append(_layout({0: d}))
            if rank > 1 and t.shape[-1] % d == 0:
                feature.append(_layout({rank - 1: d}))
            for dim in range(1, rank - 1):
                if t.shape[dim] % d == 0:
                    interior_dims.append(_layout({dim: d}))
        if rank > 1:
            valid = set(self.ev.dmesh.valid_degrees())
            for d0 in degrees:
                if t.shape[0] % d0:
                    continue
                for d1 in degrees:
                    if t.shape[rank - 1] % d1 == 0 and d0 * d1 in valid:
                        combos.append(_layout({0: d0, rank - 1: d1}))
        cands = list(dict.fromkeys(
            [()] + batch + feature + interior_dims + combos))
        cap = 12 if depth < 2 else 6
        return cands[:cap]

    def _split_positions(self, interior: List[PNode], depth: int,
                         order: Optional[List[PNode]] = None
                         ) -> List[PNode]:
        """Split positions to try. Repeated-block boundaries (transformer
        blocks, residual stacks) are preferred: cutting there aligns the
        sub-chains on whole blocks, so offset-shifted chains become
        isomorphic subproblems and the structural memo replays one
        block-run's solution across the others. Otherwise: at shallow
        depth several bottlenecks compete (the reference's
        per-bottleneck recursion, substitution.cc:2572); deeper, the
        midpoint alone."""
        bounds: List[PNode] = []
        if order is not None and len(order) >= 6:
            from ..parallel.pipeline_lowering import find_repeated_run
            layers = [n.layer for n in order]
            # run detection is O(n^2)-ish; identical subgraphs recur
            # across the DP (pre/post splits rebuild the same node sets)
            rkey = tuple(l.guid for l in layers)
            if rkey in self._run_cache:
                run = self._run_cache[rkey]
            else:
                run = self._run_cache[rkey] = find_repeated_run(layers, 1)
            if run is not None:
                total, start, unit = run
                reps = total // unit
                by_layer = {n.layer.guid: n for n in order}
                ok = {n.guid for n in interior}
                for k in range(1, reps):
                    n = by_layer.get(layers[start + k * unit - 1].guid)
                    if n is not None and n.guid in ok:
                        bounds.append(n)
        if bounds:
            if depth >= 2 or len(bounds) == 1:
                return [bounds[len(bounds) // 2]]
            q = len(bounds) // 4
            picks = [bounds[len(bounds) // 2], bounds[q], bounds[-1 - q]]
            return list(dict.fromkeys(picks))
        if depth >= 2 or len(interior) == 1:
            return [interior[len(interior) // 2]]
        if len(interior) <= 3:
            return list(interior)
        q = len(interior) // 4
        picks = [interior[q], interior[len(interior) // 2],
                 interior[-1 - q]]
        return list(dict.fromkeys(picks))

    # ------------------------------------------------------------------
    # structural memoization (guid-independent; isomorphic-subproblem
    # replay across repeated blocks)
    # ------------------------------------------------------------------
    def _canonical(self, graph: Graph, in_pins: Dict[int, Layout],
                   out_pin) -> Tuple[Optional[Tuple],
                                     Optional[List[PNode]]]:
        """Fully-structural key of (subgraph, pins): node signatures in
        canonical (topo) order, positional edges/externals/outputs. Two
        isomorphic subproblems produce equal keys with position-aligned
        node lists; equality of the full key (not a hash) rules out
        collisions. Returns (None, None) when a pin references a tensor
        outside the subgraph's externals (no safe structural identity)."""
        from ..core.layer import _hashable
        order = graph.topo_order()
        pos = {n.guid: i for i, n in enumerate(order)}
        sigs = tuple(
            (n.layer.op_type, _hashable(n.layer.params),
             tuple((t.shape, t.dtype) for t in n.layer.inputs),
             tuple((t.shape, t.dtype) for t in n.layer.outputs),
             n.ann)
            for n in order)
        edges = tuple(sorted(
            (pos[e.src.guid], pos[e.dst.guid], e.src_idx, e.dst_idx)
            for es in graph.in_edges.values() for e in es))
        covered = set()
        ext = []
        for n in order:
            for slot, t in graph.external_inputs.get(n.guid, ()):
                covered.add(t.guid)
                ext.append((pos[n.guid], slot, tuple(t.shape), t.dtype,
                            in_pins.get(t.guid)))
        # pins on tensors the subgraph never consumes are inert (the
        # evaluator only consults pins for node-input tensors present in
        # the graph) and are EXCLUDED from the key; a pin on an internal
        # (non-external) consumed tensor cannot be keyed structurally
        consumed = {t.guid for n in order for t in n.layer.inputs}
        if any(g in consumed and g not in covered for g in in_pins):
            return None, order
        outs = tuple((pos[n.guid], i) for n, i in graph.outputs)
        return (sigs, edges, tuple(sorted(ext)), outs, out_pin), order

    def _replay(self, result: Graph, memo_order: List[PNode],
                memo_ext: List, query: Graph,
                query_order: List[PNode]) -> Optional[Graph]:
        """Re-instantiate a memoized optimized subgraph onto an
        isomorphic query subgraph: query layers substitute for memo
        layers position-by-position; layers the rewrite introduced
        (parallel ops, fused replacements) are cloned with their inputs
        re-plumbed to query tensors — exactly what re-running the same
        rewrite on the query block would create. Returns None when any
        tensor fails to map (caller re-searches)."""
        try:
            tmap: Dict[int, Tensor] = {}
            lmap: Dict[int, Layer] = {}
            for mn, qn in zip(memo_order, query_order):
                lmap[mn.layer.guid] = qn.layer
                for mt, qt in zip(mn.layer.outputs, qn.layer.outputs):
                    tmap[mt.guid] = qt
            qpos = {n.guid: i for i, n in enumerate(query_order)}
            qext = {}
            for n in query_order:
                for slot, t in query.external_inputs.get(n.guid, ()):
                    qext[(qpos[n.guid], slot)] = t
            for p, slot, t in memo_ext:
                tmap[t.guid] = qext[(p, slot)]
            g = Graph()
            new_nodes: Dict[int, PNode] = {}
            for n in result.topo_order():
                ql = lmap.get(n.layer.guid)
                if ql is None:
                    ins = [tmap[t.guid] for t in n.layer.inputs]
                    ql = Layer(n.layer.op_type, None, ins,
                               dict(n.layer.params))
                    for t in n.layer.outputs:
                        ql.outputs.append(Tensor(t.shape, t.dtype,
                                                 owner_layer=ql))
                    for mt, qt in zip(n.layer.outputs, ql.outputs):
                        tmap[mt.guid] = qt
                    lmap[n.layer.guid] = ql
                nn = PNode(ql, n.ann)
                new_nodes[n.guid] = nn
                g.add_node(nn)
            for es in result.in_edges.values():
                for e in es:
                    g.add_edge(new_nodes[e.src.guid], new_nodes[e.dst.guid],
                               e.src_idx, e.dst_idx)
            for guid, slots in result.external_inputs.items():
                if guid not in new_nodes:
                    continue
                g.external_inputs[new_nodes[guid].guid] = [
                    (slot, tmap[t.guid]) for slot, t in slots]
            g.input_tensors = [tmap[t.guid] for t in result.input_tensors]
            g.outputs = [(new_nodes[n.guid], i) for n, i in result.outputs]
            return g
        except KeyError:
            return None

    @staticmethod
    def _ext_list(graph: Graph, order: List[PNode]) -> List:
        pos = {n.guid: i for i, n in enumerate(order)}
        out = []
        for n in order:
            for slot, t in graph.external_inputs.get(n.guid, ()):
                out.append((pos[n.guid], slot, t))
        return out

    def _store(self, skey, graph, order, res) -> None:
        if skey is not None and skey not in self._smemo:
            self._smemo[skey] = (order, self._ext_list(graph, order),
                                 res[0], res[1])

    def optimize(self, graph: Graph,
                 in_pins: Optional[Dict[int, Layout]] = None,
                 out_pin: Optional[Layout] = None, depth: int = 0
                 ) -> Tuple[Graph, float]:
        """``generic_sequence_optimize``: recursively split at a bottleneck
        with DP over cut layouts; base case: best-first rewrite search."""
        in_pins = in_pins or {}
        key = (graph.hash(), tuple(sorted(in_pins.items())), out_pin)
        hit = self._memo.get(key)
        if hit is not None:
            return hit
        skey, order = self._canonical(graph, in_pins, out_pin)
        if skey is not None:
            sh = self._smemo.get(skey)
            if sh is not None:
                memo_order, memo_ext, res_g, res_c = sh
                replayed = self._replay(res_g, memo_order, memo_ext,
                                        graph, order)
                if replayed is not None:
                    self.smemo_hits += 1
                    res = (replayed, res_c)
                    self._memo[key] = res
                    return res
        interior = [n for n in graph.bottlenecks()
                    if graph.in_edges[n] and graph.out_edges[n]
                    and n.op_type not in PARALLEL_OPS
                    and n is not order[-1]]
        if graph.num_nodes() <= self.threshold or not interior \
                or depth > 6 or self.pool.take(1) == 0:
            res = base_optimize(graph, self.xfers, self.ev, self.budget,
                                self.alpha, self.max_num_ops, in_pins,
                                out_pin, pool=self.pool)
            self._memo[key] = res
            self._store(skey, graph, order, res)
            return res
        # DP over split positions × cut layouts (reference recurses at
        # each bottleneck over machine-view sets, substitution.cc:2572;
        # memoization by (subgraph hash, pins) keeps this polynomial)
        best_merged: Optional[Graph] = None
        best_cost = float("inf")
        for b in self._split_positions(interior, depth, order):
            pre, post = graph.split_at(b)
            # crossing tensors, positionally aligned with pre.outputs —
            # substitutions may replace the producing node (fresh output
            # Tensors), but graph.outputs positions are rewired in place,
            # so index k of the optimized pre's outputs still corresponds
            # to original cut tensor k
            cut_tensors = [n.layer.outputs[i] for n, i in pre.outputs]
            cut_t = b.layer.outputs[0]
            best_pair: Optional[Tuple[Graph, Graph]] = None
            split_cost = float("inf")
            for L in self._cut_layout_candidates(cut_t, depth):
                g1, c1 = self.optimize(pre, in_pins, L, depth + 1)
                if c1 >= min(split_cost, best_cost):
                    continue
                pins2 = dict(in_pins)
                pins2[cut_t.guid] = L
                g2, c2 = self.optimize(post, pins2, out_pin, depth + 1)
                if c1 + c2 < split_cost:
                    split_cost = c1 + c2
                    best_pair = (g1, g2)
            if best_pair is not None and split_cost < best_cost:
                best_cost = split_cost
                best_merged = _merge_split(best_pair[0], best_pair[1],
                                           graph,
                                           [t.guid for t in cut_tensors])
        if best_merged is None:
            raise RuntimeError(
                "sequence split produced no merged graph")
        res = (best_merged, best_cost)
        self._memo[key] = res
        self._store(skey, graph, order, res)
        return res


def _merge_split(pre: Graph, post: Graph, original: Graph,
                 cut_guids: Sequence[int]) -> Graph:
    """Stitch optimized halves back into one graph: reconnect post's
    external inputs that are pre's outputs. ``cut_guids[k]`` is the
    ORIGINAL tensor guid of pre's k-th output — after substitutions the
    producing node (and its output Tensor) may be new, so the mapping is
    positional, not by the optimized node's tensor guid."""
    g = Graph()
    for part in (pre, post):
        for n in part.in_edges:
            g.add_node(n)
        for edges in part.in_edges.values():
            for e in edges:
                g.add_edge(e.src, e.dst, e.src_idx, e.dst_idx)
    # pre's declared outputs by ORIGINAL crossing-tensor guid (positional)
    if len(cut_guids) != len(pre.outputs):
        raise RuntimeError(f"cut arity changed: {len(cut_guids)} vs "
                           f"{len(pre.outputs)}")
    pre_out: Dict[int, Tuple[PNode, int]] = {}
    for guid, (n, i) in zip(cut_guids, pre.outputs):
        pre_out[guid] = (n, i)
        pre_out.setdefault(n.layer.outputs[i].guid, (n, i))
    for n in post.in_edges:
        ext = post.external_inputs.get(n.guid, ())
        keep = []
        for slot, t in ext:
            if t.guid in pre_out:
                src, si = pre_out[t.guid]
                g.add_edge(src, n, si, slot)
            else:
                keep.append((slot, t))
        if keep:
            g.external_inputs[n.guid] = keep
    for n in pre.in_edges:
        if n.guid in pre.external_inputs:
            g.external_inputs[n.guid] = list(pre.external_inputs[n.guid])
    g.input_tensors = list(original.input_tensors)
    g.outputs = list(post.outputs)
    return g


# ---------------------------------------------------------------------------
# Memory-aware search (lambda binary search)
# ---------------------------------------------------------------------------
def graph_optimize_with_memory(graph: Graph, xfers: Sequence[GraphXfer],
                               cost_model: OpCostModel, dmesh: DeviceMesh,
                               mem_budget_bytes: float, budget: int = 32,
                               alpha: float = 1.05, iters: int = 6,
                               base_optimize_threshold: int = 12,
                               evaluator_cls=None
                               ) -> Tuple[Graph, GraphCost]:
    """Binary search on the memory weight lambda until the best strategy
    fits per-device HBM (reference ``graph_optimize_with_memory`` +
    ``try_one_lambda``, ``substitution.cc:1960``, ``graph.cc:1883``)."""
    if evaluator_cls is None:
        evaluator_cls = GraphCostEvaluator

    def run(lam: float) -> Tuple[Graph, GraphCost]:
        ev = evaluator_cls(cost_model, dmesh, mem_lambda=lam)
        search = UnitySearch(ev, xfers, budget=budget, alpha=alpha,
                             base_optimize_threshold=base_optimize_threshold)
        g, _ = search.optimize(graph)
        pure = evaluator_cls(cost_model, dmesh)
        return g, pure.graph_cost(g)

    g0, c0 = run(0.0)
    per_dev = c0.peak_memory / max(dmesh.num_devices, 1)
    if per_dev <= mem_budget_bytes:
        return g0, c0
    lo, hi = 0.0, 1e-6
    best_feasible: Optional[Tuple[Graph, GraphCost]] = None
    for _ in range(iters):
        g, c = run(hi)
        if c.peak_memory / max(dmesh.num_devices, 1) <= mem_budget_bytes:
            best_feasible = (g, c)
            break
        hi *= 10
    for _ in range(iters):
        mid = (lo + hi) / 2
        g, c = run(mid)
        if c.peak_memory / max(dmesh.num_devices, 1) <= mem_budget_bytes:
            best_feasible = (g, c)
            hi = mid
        else:
            lo = mid
    return best_feasible if best_feasible is not None else (g0, c0)


# ---------------------------------------------------------------------------
# Strategy extraction: optimized PCG -> executable program + shardings
# ---------------------------------------------------------------------------
def _group_tier_prefs(graph: Graph) -> Dict[str, str]:
    """Per-group axis-tier preference for placement-aware allocation:
    groups that shard weights or carry partial sums (tensor/reduce
    parallelism — per-op, per-layer collectives) belong on the fastest
    fabric (``"inner"``); pure output-sharding groups (data parallel —
    one gradient sync per step, lowered as a hierarchical tree) can
    afford the outermost tiers (``"outer"``)."""
    prefs: Dict[str, str] = {}
    for n in graph.in_edges:
        ann = n.ann
        for _w, _d, g in ann.weights:
            prefs[g] = "inner"
        if ann.reduce is not None:
            prefs[ann.reduce] = "inner"
        if ann.replicate is not None:
            prefs.setdefault(ann.replicate, "inner")
        for g, _d in ann.groups:
            prefs.setdefault(g, "outer")
    return prefs


def _allocate_group_axes(graph: Graph, dmesh: DeviceMesh,
                         placement_policy: Optional[str] = None
                         ) -> Dict[str, Tuple[str, ...]]:
    """Assign disjoint-where-needed atomic mesh axes to each annotation
    group, consistently across the whole graph (the analog of the
    reference's per-op MachineView assignment).

    With ``placement_policy="hier"`` the assignment is tier-aware: each
    group's axes are taken innermost- or outermost-first per
    :func:`_group_tier_prefs` — the axis→tier placement half of the
    arXiv 2110.10548 search space. ``None`` keeps the historical
    declaration-order greedy (the flat baseline)."""
    co: Dict[str, set] = {}
    degrees: Dict[str, int] = {}
    for n in graph.in_edges:
        gs = [g for g, _ in n.ann.groups]
        for g, d in n.ann.groups:
            degrees[g] = d
            co.setdefault(g, set()).update(x for x in gs if x != g)
    prefs = _group_tier_prefs(graph) if placement_policy == "hier" \
        else {}
    assign: Dict[str, Tuple[str, ...]] = {}
    # inner-preferring (tp/reduce) groups allocate FIRST so the fast
    # axes are still free when they ask; ties keep the legacy
    # biggest-degree-first order
    def alloc_rank(g: str) -> Tuple:
        return (0 if prefs.get(g) == "inner" else 1, -degrees[g], g)

    for g in sorted(degrees, key=alloc_rank):
        used: List[str] = []
        for other in co.get(g, ()):
            used.extend(assign.get(other, ()))
        prefer = prefs.get(g)
        axes = dmesh.allocate_axes(degrees[g], used, prefer=prefer)
        if axes is None:
            axes = dmesh.allocate_axes(degrees[g], [], prefer=prefer)
        assign[g] = axes or ()
    return assign


def extract_strategy(graph: Graph, info: GraphProgramInfo,
                     dmesh: DeviceMesh,
                     placement_policy: Optional[str] = None
                     ) -> ShardingStrategy:
    """Convert the optimized PCG into the executable ShardingStrategy.
    ``placement_policy="hier"`` makes the group→axis assignment
    tier-aware (see :func:`_allocate_group_axes`) and records the
    adopted axis→tier placement on the strategy."""
    from jax.sharding import PartitionSpec as P

    st = ShardingStrategy(dmesh)
    axes_of = _allocate_group_axes(graph, dmesh, placement_policy)
    lay = propagate_layouts(graph)
    if placement_policy == "hier":
        try:
            st.axis_tiers = dict(dmesh.axis_tiers)
        except Exception:  # noqa: BLE001 — annotation is best-effort
            pass

    # group axes by (dim -> axes) for a node's layout: we need group names,
    # so rebuild specs from annotations for compute nodes and from layouts
    # (with deterministic axis choice) for parallel ops.
    def spec_from_groups(placements: Dict[int, Tuple[str, ...]], rank: int
                         ) -> Optional[P]:
        if not placements:
            return None
        entries = []
        for d in range(rank):
            ax = placements.get(d)
            if not ax:
                entries.append(None)
            else:
                entries.append(ax[0] if len(ax) == 1 else tuple(ax))
        return P(*entries)

    def axes_for_layout(layout: Layout) -> Dict[int, Tuple[str, ...]]:
        used: List[str] = []
        placements: Dict[int, Tuple[str, ...]] = {}
        # under hierarchical placement, batch (dim 0) layouts take the
        # outer tiers and feature/interior layouts the inner — matching
        # the group allocation above
        for dim, deg in layout:
            prefer = None
            if placement_policy == "hier":
                prefer = "outer" if dim == 0 else "inner"
            ax = dmesh.allocate_axes(deg, used, prefer=prefer)
            if ax is None:
                continue
            used.extend(ax)
            placements[dim] = ax
        return placements

    for n in graph.topo_order():
        exec_layer = info.node_to_layer.get(n.guid)
        if exec_layer is None or n.op_type == OperatorType.OP_INPUT:
            continue
        rank = len(exec_layer.outputs[0].shape) if exec_layer.outputs else 0
        ann = n.ann
        if not ann.is_trivial() and n.op_type not in PARALLEL_OPS:
            placements: Dict[int, Tuple[str, ...]] = {}
            valid = True
            for oi, dim, g in ann.out:
                if oi != 0:
                    continue
                ax = axes_of.get(g, ())
                if not ax:
                    valid = False
                    continue
                placements[dim] = placements.get(dim, ()) + ax
            out_spec = spec_from_groups(placements, rank) if valid else None
            wspecs: Dict[str, P] = {}
            wplace: Dict[str, Dict[int, Tuple[str, ...]]] = {}
            for wname, wdim, g in ann.weights:
                ax = axes_of.get(g, ())
                if ax:
                    wplace.setdefault(wname, {})[wdim] = ax
            for wname, pl in wplace.items():
                wrank = max(pl.keys()) + 1
                for w in exec_layer.weights:
                    if w.name == wname:
                        wrank = len(w.shape)
                        break
                sp = spec_from_groups(pl, wrank)
                if sp is not None:
                    wspecs[wname] = sp
            outs = [out_spec] + [None] * (len(exec_layer.outputs) - 1)
            st.set_op(exec_layer.name, outs, wspecs)
        else:
            # parallel ops / unannotated ops: constrain to the propagated
            # layout so XLA materializes the intended collective
            layout = lay.get((n.guid, 0), ())
            pl = axes_for_layout(layout)
            sp = spec_from_groups(pl, rank)
            outs = [sp] + [None] * (max(len(exec_layer.outputs), 1) - 1)
            st.set_op(exec_layer.name, outs, {})

    # inputs: batch-shard when the first consumer's layout says so
    first_layouts: Dict[int, Layout] = {}
    for n in graph.topo_order():
        for s, t in graph.external_inputs.get(n.guid, ()):
            if t.guid not in first_layouts:
                lay_n = lay.get((n.guid, 0), ())
                first_layouts[t.guid] = lay_n
    for t in graph.input_tensors:
        L = first_layouts.get(t.guid, ())
        d0 = dict(L).get(0)
        if d0 and t.shape and t.shape[0] % d0 == 0:
            ax = dmesh.allocate_axes(
                d0, [], prefer="outer" if placement_policy == "hier"
                else None)
            if ax:
                st.inputs[t.name] = P(ax[0] if len(ax) == 1 else tuple(ax))
    errs = st.validate()
    if errs:
        for name in {e.split(":")[0] for e in errs}:
            st.ops.pop(name, None)
    return st


# ---------------------------------------------------------------------------
# Top-level entry
# ---------------------------------------------------------------------------
def data_parallel_graph(layers: Sequence[Layer],
                        input_tensors: Sequence[Tensor],
                        output_tensors: Sequence[Tensor],
                        dmesh: DeviceMesh) -> Graph:
    """The canonical data-parallel PCG: every op whose leading output dim
    divides the device count is batch-partitioned (the reference's
    ``--only-data-parallel`` view, ``graph.cc:1939``). Scoring this with
    the SAME evaluator as the search gives the search a floor: its
    answer is never predicted-worse than plain DP."""
    g = Graph.from_layers(layers, input_tensors, output_tensors)
    d = dmesh.num_devices
    for n in g.in_edges:
        if n.op_type in (OperatorType.OP_INPUT, OperatorType.OP_NOOP,
                         OperatorType.OP_WEIGHT) or d <= 1:
            continue
        outs = tuple((i, 0, "dp")
                     for i, t in enumerate(n.layer.outputs)
                     if t.shape and t.shape[0] % d == 0)
        if outs:
            n.ann = ParAnn(groups=(("dp", d),), out=outs)
    return g


def saturate_xfers(graph: Graph, xfers: Sequence[GraphXfer],
                   max_apply: int = 2048, max_num_ops: int = 4096) -> Graph:
    """Apply each xfer greedily (first match, repeat) until fixpoint."""
    applied = True
    while applied and max_apply > 0:
        applied = False
        for xf in xfers:
            while max_apply > 0:
                g2 = next(iter(xf.run(graph, max_num_ops)), None)
                if g2 is None:
                    break
                graph = g2
                applied = True
                max_apply -= 1
    return graph


def hybrid_template_graphs(layers: Sequence[Layer],
                           input_tensors: Sequence[Tensor],
                           output_tensors: Sequence[Tensor],
                           dmesh: DeviceMesh
                           ) -> List[Tuple[str, Graph]]:
    """Uniform composed-2D candidate strategies, one per (dp, tp)
    factorization of the machine: batch x column-parallel every Linear,
    batch x head-parallel every attention, batch-partition everything
    else by dp, then cancel adjacent combine/partition pairs.

    The reference's search starts FROM per-op data-parallel MachineViews
    (``graph.cc:1939``) so hybrid corners of the space are a few moves
    away; our rewrite search seeds from the serial graph, so these
    templates (like the DP floor) guarantee the well-known strategy
    families are always in the candidate set, whatever the budget."""
    from .substitution import (_ELEMENTWISE_PARTITIONABLE,
                               _NORM_PARTITIONABLE,
                               create_combine_partition_elimination,
                               create_partition_attention_combine_2d,
                               create_partition_ffn_2d,
                               create_partition_linear_combine_2d,
                               create_partition_op_combine)
    n = dmesh.num_devices
    degs = set(d for d in dmesh.valid_degrees() if d > 1)
    out: List[Tuple[str, Graph]] = []
    for dp in sorted(degs):
        tp = n // dp
        if dp >= n or n % dp or tp not in degs:
            continue
        base = Graph.from_layers(layers, input_tensors, output_tensors)
        # paired-FFN rule FIRST: it claims linear->linear chains before
        # the per-op column rule can split them apart
        xfers = [create_partition_ffn_2d(dp, tp),
                 create_partition_linear_combine_2d(dp, tp),
                 create_partition_attention_combine_2d(dp, tp)]
        for op_type, n_in in (_ELEMENTWISE_PARTITIONABLE
                              + _NORM_PARTITIONABLE
                              + ((OperatorType.OP_EMBEDDING, 1),)):
            xfers.append(create_partition_op_combine(op_type, n_in, 0, dp))
        xfers.append(create_combine_partition_elimination(0, dp))
        out.append((f"2d_dp{dp}xtp{tp}",
                    saturate_xfers(base, xfers)))
    return out


def unity_search(layers: Sequence[Layer], input_tensors: Sequence[Tensor],
                 output_tensors: Sequence[Tensor], dmesh: DeviceMesh,
                 cost_model: OpCostModel, budget: int = 32,
                 alpha: float = 1.05,
                 mem_budget_bytes: Optional[float] = None,
                 base_optimize_threshold: int = 12,
                 xfers: Optional[Sequence[GraphXfer]] = None,
                 evaluator_cls=None
                 ) -> Tuple[GraphProgramInfo, ShardingStrategy, GraphCost,
                            Graph]:
    """Full Unity pipeline: Layer graph -> PCG -> substitution/DP search ->
    executable program + ShardingStrategy (reference
    ``Graph::graph_optimize_task``, ``graph.cc:2046``).

    ``evaluator_cls`` selects the scoring backend: the additive
    GraphCostEvaluator (default; machine model v0) or the native task-graph
    simulator (``tasksim.TaskGraphEvaluator``; machine model v1)."""
    graph = Graph.from_layers(layers, input_tensors, output_tensors)
    degrees = [d for d in dmesh.valid_degrees() if d > 1]
    if xfers is None:
        xfers = generate_all_pcg_xfers(degrees)
    if evaluator_cls is None:
        evaluator_cls = GraphCostEvaluator
    dp_predicted_total = None
    final_ranker = "additive"
    if mem_budget_bytes is not None:
        with obs_events.span("unity.memory_search", budget=budget):
            g, gc = graph_optimize_with_memory(
                graph, xfers, cost_model, dmesh, mem_budget_bytes, budget,
                alpha, base_optimize_threshold=base_optimize_threshold,
                evaluator_cls=evaluator_cls)
    else:
        ev = evaluator_cls(cost_model, dmesh)
        search = UnitySearch(ev, xfers, budget=budget, alpha=alpha,
                             base_optimize_threshold=base_optimize_threshold)
        with obs_events.span("unity.dp", budget=budget):
            g, _ = search.optimize(graph)
        gc = ev.graph_cost(g)
        # DP floor: never return a strategy predicted worse than the
        # canonical data-parallel view (the reference search starts FROM
        # per-op data-parallel configs, so DP is always in its space; our
        # rewrite search seeds from the serial graph and can exhaust its
        # budget before reaching full batch partitioning on small models)
        dp_g = data_parallel_graph(layers, input_tensors, output_tensors,
                                   dmesh)
        dp_gc = ev.graph_cost(dp_g)
        dp_predicted_total = dp_gc.total
        finalists = [(g, gc), (dp_g, dp_gc)]
        # hybrid composed-2D template floor (see hybrid_template_graphs)
        for _name, tg in hybrid_template_graphs(layers, input_tensors,
                                                output_tensors, dmesh):
            finalists.append((tg, ev.graph_cost(tg)))
        # Final candidate ranking goes through the native event-driven
        # task simulator so overlap/contention shapes the adoption, not
        # just additive op costs (reference: the search trusts its
        # event-driven simulator end-to-end, simulator.cc:822-1200).
        # The additive evaluator remains the pruner inside the DP; only
        # the few finalists are re-simulated.
        # FF_FINAL_RANKER=additive keeps the additive evaluator's
        # ranking (fidelity A/Bs between the two rankers —
        # examples/osdi22ae/ranker_fidelity.py)
        if (evaluator_cls is GraphCostEvaluator and len(finalists) > 1
                and os.environ.get("FF_FINAL_RANKER",
                                   "tasksim") != "additive"):
            try:
                from .tasksim import TaskGraphEvaluator
                tev = TaskGraphEvaluator(cost_model, dmesh)
                with obs_events.span("unity.final_rank",
                                     ranker="tasksim",
                                     finalists=len(finalists)):
                    ranked = [(cg, tev.graph_cost(cg))
                              for cg, _ in finalists]
                g, gc = min(ranked, key=lambda p: p[1].total)
                dp_predicted_total = next(
                    tgc.total for cg, tgc in ranked if cg is dp_g)
                final_ranker = "tasksim"
            except Exception:  # noqa: BLE001 — fall back to additive
                g, gc = min(finalists, key=lambda p: p[1].total)
        else:
            g, gc = min(finalists, key=lambda p: p[1].total)
    info = g.to_program()
    info.final_ranker = final_ranker
    # predicted DP-baseline cost (already computed for the DP floor in
    # the non-memory branch) — consumed by optimizer reporting
    info.dp_predicted_total = dp_predicted_total
    strategy = extract_strategy(
        g, info, dmesh,
        placement_policy=getattr(cost_model, "placement_policy", None))
    return info, strategy, gc, g
