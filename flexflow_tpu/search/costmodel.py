"""Execution cost model: per-op compute costs + inter-op transfer costs
over the TPU machine model.

Analog of the reference's Simulator (``src/runtime/simulator.cc``):
  - ``measure_operator_cost`` ≙ ``OpCostModel.op_cost``: analytic roofline
    (FLOPs on the MXU vs bytes over HBM) refined by optional on-chip
    microbenchmarks (jit-compile the op at shard-local shape, warmup +
    repeat — the direct analog of ``inner_measure_operator_cost``,
    ``model.cu:38``), cached by (op params, degrees) like the reference's
    ``hash_to_operator_cost``.
  - ``estimate_xfer_cost`` ≙ resharding cost between PartitionSpecs:
    collective volume over ICI bandwidth + per-hop latency.
  - weight sync ≙ gradient all-reduce ring cost over the dp axes.
"""
from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core.layer import Layer
from ..dtypes import itemsize
from ..ffconst import OperatorType, PARALLEL_OPS
from ..ops import get_op_def
from ..parallel.machine import DeviceMesh, MachineSpec


@dataclasses.dataclass
class CostMetrics:
    """Reference ``CostMetrics`` (``simulator.h:54``) parity."""
    forward_time: float = 0.0     # seconds
    backward_time: float = 0.0
    sync_time: float = 0.0
    inputs_memory: int = 0
    outputs_memory: int = 0
    weights_memory: int = 0

    @property
    def total_memory(self) -> int:
        return self.inputs_memory + self.outputs_memory + self.weights_memory


class OpCostModel:
    """Analytic + measured operator costs on one chip."""

    # MXU efficiency defaults by op class (fraction of peak achieved);
    # refined by calibrate() microbenchmarks when a chip is available.
    _DEFAULT_EFF = 0.5

    def __init__(self, spec: MachineSpec):
        self.spec = spec
        self.cache: Dict[Tuple, CostMetrics] = {}
        self.mxu_eff = self._DEFAULT_EFF
        self.overhead_s = 2e-6  # per-op dispatch/fusion overhead inside XLA

    # ------------------------------------------------------------------
    def calibrate(self):
        """Measure real matmul throughput on the local device to set the
        efficiency factor (one-time, <1s). Synchronizes via a device-to-
        host value fetch — block_until_ready does not block on tunneled
        TPU backends."""
        try:
            import jax
            import jax.numpy as jnp
            n = 2048
            reps = 8
            a = jnp.ones((n, n), jnp.bfloat16)

            def chain(x):
                for _ in range(reps):
                    x = x @ x
                    x = x * jnp.bfloat16(1e-3)
                return jnp.sum(x.astype(jnp.float32))

            f = jax.jit(chain)
            float(np.asarray(f(a)))  # compile + sync
            t0 = time.perf_counter()
            float(np.asarray(f(a)))
            dt = (time.perf_counter() - t0) / reps
            achieved = 2.0 * n ** 3 / dt
            self.mxu_eff = min(1.0, max(0.05,
                                        achieved / self.spec.peak_flops))
        except Exception:
            pass

    # ------------------------------------------------------------------
    def op_cost(self, layer: Layer, shard_degrees: Dict[int, int],
                weight_shard_degree: int = 1) -> CostMetrics:
        """Cost of one op with its output dims partitioned by
        ``shard_degrees`` (dim -> degree). Compute scales ~1/prod(degrees);
        memory likewise."""
        key = (layer.param_key(), tuple(sorted(shard_degrees.items())),
               weight_shard_degree)
        hit = self.cache.get(key)
        if hit is not None:
            return hit
        op = get_op_def(layer.op_type)
        in_shapes = [t.shape for t in layer.inputs]
        out_shapes = [t.shape for t in layer.outputs]
        total_deg = 1
        for d in shard_degrees.values():
            total_deg *= max(d, 1)
        flops = op.flops(layer.params, in_shapes, out_shapes) / total_deg
        in_bytes = sum(int(np.prod(t.shape)) * itemsize(t.dtype)
                       for t in layer.inputs) // total_deg
        out_bytes = sum(int(np.prod(t.shape)) * itemsize(t.dtype)
                        for t in layer.outputs) // total_deg
        w_bytes = sum(int(np.prod(w.shape)) * itemsize(w.dtype)
                      for w in layer.weights) // max(weight_shard_degree, 1)
        bytes_moved = in_bytes + out_bytes + w_bytes
        t_compute = flops / (self.spec.peak_flops * self.mxu_eff)
        t_mem = bytes_moved / self.spec.hbm_bandwidth
        fwd = max(t_compute, t_mem) + self.overhead_s
        bwd = fwd * op.backward_flops_factor() \
            if layer.op_type != OperatorType.OP_INPUT else 0.0
        cm = CostMetrics(forward_time=fwd, backward_time=bwd,
                         inputs_memory=in_bytes, outputs_memory=out_bytes,
                         weights_memory=w_bytes)
        self.cache[key] = cm
        return cm

    # ------------------------------------------------------------------
    def xfer_cost(self, volume_bytes: float, collective: str,
                  degree: int) -> float:
        """Collective time over ICI (ring algorithms):
        all-gather/reduce-scatter move (d-1)/d of the volume; all-reduce
        2(d-1)/d; all-to-all (d-1)/d with per-hop latency."""
        if degree <= 1 or volume_bytes <= 0:
            return 0.0
        bw = self.spec.ici_bandwidth
        lat = self.spec.ici_latency_us * 1e-6
        frac = (degree - 1) / degree
        mult = {"all_reduce": 2.0, "all_gather": 1.0, "reduce_scatter": 1.0,
                "all_to_all": 1.0 / degree, "permute": 1.0 / degree}[collective]
        return mult * frac * volume_bytes / bw + (degree - 1) * lat

    def resharding_cost(self, tensor_bytes: float,
                        src_degrees: Dict[int, int],
                        dst_degrees: Dict[int, int]) -> float:
        """Cost of moving a tensor between two dim->degree layouts
        (reference ``estimate_xfer_cost`` / Repartition special case)."""
        if src_degrees == dst_degrees:
            return 0.0
        src_total = int(np.prod(list(src_degrees.values()))) \
            if src_degrees else 1
        dst_total = int(np.prod(list(dst_degrees.values()))) \
            if dst_degrees else 1
        if src_total == 1 and dst_total > 1:
            return 0.0  # slicing a replicated tensor is local
        if dst_total == 1:
            return self.xfer_cost(tensor_bytes, "all_gather", src_total)
        same_dims = set(src_degrees) == set(dst_degrees)
        if same_dims:
            return self.xfer_cost(tensor_bytes, "permute",
                                  max(src_total, dst_total))
        return self.xfer_cost(tensor_bytes, "all_to_all",
                              max(src_total, dst_total))

    def weight_sync_cost(self, weight_bytes: float, dp_degree: int) -> float:
        """Per-step gradient all-reduce (reference NCCL optimizer path)."""
        return self.xfer_cost(weight_bytes, "all_reduce", dp_degree)
