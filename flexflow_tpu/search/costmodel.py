"""Execution cost model: per-op compute costs + inter-op transfer costs
over the TPU machine model.

Analog of the reference's Simulator (``src/runtime/simulator.cc``):
  - ``measure_operator_cost`` (``simulator.cc:537``) ≙
    ``OpCostModel.measure``: jit-compile the op's own ``emit`` at the
    shard-local shape on the real device, warmup + repeat + median — the
    direct analog of ``inner_measure_operator_cost`` (``model.cu:38``) —
    cached in-memory AND on disk by (generation, op params, degrees) like
    the reference's ``hash_to_operator_cost``. ``op_cost`` consults the
    measurement when ``measure_on_device`` is set (search on a real chip)
    and falls back to the analytic roofline (FLOPs on the MXU vs bytes
    over HBM) otherwise — e.g. on the CPU simulation platform.
  - ``estimate_xfer_cost`` ≙ resharding cost between PartitionSpecs:
    collective volume over ICI bandwidth + per-hop latency.
  - weight sync ≙ gradient all-reduce ring cost over the dp axes.
"""
from __future__ import annotations

import dataclasses
import json
import math
import os
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core.layer import Layer
from ..dtypes import itemsize
from ..ffconst import OperatorType, PARALLEL_OPS
from ..obs import events as obs_events
from ..ops import get_op_def
from ..parallel.machine import DeviceMesh, MachineSpec
from ..parallel.topology import link_degradation_factor


@dataclasses.dataclass
class CostMetrics:
    """Reference ``CostMetrics`` (``simulator.h:54``) parity."""
    forward_time: float = 0.0     # seconds
    backward_time: float = 0.0
    sync_time: float = 0.0
    inputs_memory: int = 0
    outputs_memory: int = 0
    weights_memory: int = 0

    @property
    def total_memory(self) -> int:
        return self.inputs_memory + self.outputs_memory + self.weights_memory


class OpCostModel:
    """Analytic + measured operator costs on one chip."""

    # MXU efficiency defaults by op class (fraction of peak achieved);
    # refined by calibrate() microbenchmarks when a chip is available.
    _DEFAULT_EFF = 0.5

    # ops worth a per-op microbenchmark (compile time ~seconds each);
    # everything cheaper uses the analytic roofline, as fusion makes
    # standalone elementwise timings meaningless under XLA anyway
    _MEASURE_MIN_FLOPS = 1e7

    def __init__(self, spec: MachineSpec, cache_dir: Optional[str] = None):
        self.spec = spec
        self.cache: Dict[Tuple, CostMetrics] = {}
        self.mxu_eff = self._DEFAULT_EFF
        self.overhead_s = 2e-6  # per-op dispatch/fusion overhead inside XLA
        # measured collective constants (calibrate_collectives); None =
        # use the machine-model ICI numbers. On the CPU simulation
        # platform the model's v5e ICI bandwidths overstate one host's
        # memcpy fabric by orders of magnitude — the round-2 root cause
        # of searched strategies losing to DP on DLRM/XDL.
        self.coll_bw: Optional[float] = None
        self.coll_lat: Optional[float] = None
        # segmented-transfer settings for the task simulator (reference
        # EnhancedMachineModel, machine_model.cc: --simulator-segment-size
        # / --simulator-max-num-segments). max_segments 1 = whole-message
        # store-and-forward; >1 lets multi-hop transfers pipeline
        # segment-wise across their route in tasksim.py.
        self.segment_size: int = 16777216
        self.max_segments: int = 1
        # measurement-grounded calibration v2 (search/calibration.py):
        # host dispatch overhead, memory bandwidth, parallel efficiency
        # and per-collective tables measured on the live backend. None =
        # analytic terms only (unchanged legacy behavior).
        self.calib = None
        # hierarchical placement (parallel/placement.py, arXiv
        # 2110.10548): when attached, collectives are priced against
        # the (tier, degree) path their mesh axes span and the cheapest
        # reduction-tree shape is chosen per site. None = flat-mesh
        # pricing (bit-identical legacy behavior); policy "flat" keeps
        # the placement but scores every collective as a flat ring at
        # its bottleneck tier (the searched-vs-flat baseline).
        self.placement = None
        self.placement_policy: Optional[str] = None
        # per-site chosen trees, for the strategy audit record and the
        # adopted strategy's serialized tree shapes (bounded)
        self.algo_choices: Dict[Tuple, Dict[str, Any]] = {}
        self._tree_memo: Dict[Tuple, Any] = {}
        # quantized gradient collectives (ops/quantized_collectives.py,
        # arXiv 2506.17615): when a policy dict {"mode", "wire"} is
        # attached, grad-sync sites are additionally scored with their
        # slow legs narrowed to the wire dtype (int8/fp8, per-chunk
        # scales + error feedback) — per-tensor on flat syncs,
        # per-phase on the reduction trees — and the cheaper side wins
        # per the mode (auto) or the mode's mandate (dcn_only/all).
        # None (default) keeps every prediction bit-identical.
        self.quantization: Optional[Dict[str, str]] = None
        # wire dtype of the most recent weight_sync_cost answer (the
        # audit breakdown records it per grad-sync site — the drift
        # detector attributes quantized rows by it)
        self.last_sync_wire: str = "float32"
        # calibration-row provenance tap (obs/drift.py): when a list is
        # installed here, every pricing call appends WHICH calibration
        # row (or analytic term) produced its answer. Installed only by
        # the audit breakdown path (GraphCostEvaluator.
        # graph_cost_breakdown) — None keeps the search's hot loops at
        # one attribute read per call.
        self.provenance: Optional[List[Dict[str, Any]]] = None
        # overlap-aware scoring (runtime/overlap.py's model half): when
        # set, GraphCostEvaluator prices each gradient-sync site at its
        # EXPOSED cost — max(0, comm − hideable backward compute) under
        # a single-comm-channel queue model — instead of the serial
        # full cost, and records the hidden/exposed split per site.
        # Off (the default) keeps every prediction bit-identical to the
        # serial model. Set by search/optimizer.py from FFConfig.overlap
        # / FF_OVERLAP; the event-driven simulator (tasksim.py
        # overlap_estimate) is the authority this additive split is
        # checked against (bench comm_overlap leg, within 2x).
        self.overlap_mode = False
        # on-device measurement (reference measure_operator_cost analog)
        self.measure_on_device = False
        # searchable kernel tier (kernels/registry.py): per-(op, impl)
        # answers memoized like the op cache — kernel_impl_cost sits in
        # the planner's candidate loop
        self._impl_cache: Dict[Tuple, CostMetrics] = {}
        # attach_kernel_tier installs this: {"seq_degree", "backend",
        # "tier", "forced"}. With it set, op_cost_with_impl prices
        # attention at its cheapest AVAILABLE implementation — the impl
        # becomes a per-op dimension of the search; the argmin of the
        # most recent pricing is left in last_kernel_impl for the audit
        # breakdown and accumulated per layer name in kernel_choice for
        # FFModel._plan_kernels to adopt.
        self.kernel_tier: Optional[Dict[str, Any]] = None
        self.last_kernel_impl: Optional[str] = None
        self.kernel_choice: Dict[str, str] = {}
        self.measure_budget_s = 120.0   # total wall budget for microbenches
        self._measure_spent_s = 0.0
        self._unmeasurable: set = set()  # per-process, deliberately not on disk
        self._disk: Optional[Dict[str, Any]] = None
        self._cache_dir = cache_dir or os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))), ".ffcache")

    # ------------------------------------------------------------------
    # disk cache (reference hash_to_operator_cost persisted)
    # ------------------------------------------------------------------
    @property
    def _disk_path(self) -> str:
        return os.path.join(self._cache_dir,
                            f"opcost_{self.spec.generation}.json")

    def _disk_cache(self) -> Dict[str, Any]:
        if self._disk is None:
            try:
                with open(self._disk_path) as f:
                    self._disk = json.load(f)
            except Exception:
                self._disk = {}
        return self._disk

    def _disk_put(self, key: str, value) -> None:
        cache = self._disk_cache()
        cache[key] = value
        try:
            os.makedirs(self._cache_dir, exist_ok=True)
            tmp = self._disk_path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(cache, f)
            os.replace(tmp, self._disk_path)
        except Exception:
            pass

    # ------------------------------------------------------------------
    def attach_placement(self, placement, policy: str = "hier") -> None:
        """Attach an :class:`~flexflow_tpu.parallel.placement.
        AxisPlacement`: collective costs become (tier-path, algorithm)-
        aware. ``policy`` is the axis-consumption model — ``"hier"``
        (per-op collectives innermost-first, gradient sync on the
        complement, best tree per site) or ``"flat"`` (flat-ring
        scoring at the bottleneck tier — the baseline the search is
        compared against). Clears every cached cost priced under the
        previous placement."""
        if policy not in ("hier", "flat"):
            raise ValueError(f"unknown placement policy {policy!r}")
        self.placement = placement
        self.placement_policy = policy if placement is not None else None
        self.cache.clear()
        self._tree_memo.clear()
        self.algo_choices.clear()

    def attach_quantization(self, mode: Optional[str],
                            wire: str = "int8") -> None:
        """Attach (or detach, ``mode=None``/"off") the quantized-
        collectives scoring policy. Clears every cached cost priced
        under the previous policy."""
        if mode in (None, "off"):
            self.quantization = None
        else:
            from ..ops.quantized_collectives import QSYNC_MODES
            if mode not in QSYNC_MODES:
                raise ValueError(f"unknown quantization mode {mode!r}")
            self.quantization = {"mode": mode, "wire": wire}
        self.cache.clear()
        self._tree_memo.clear()

    def _quant_overhead_s(self, volume_bytes: float) -> float:
        """In-jit quantize+dequantize cost of one synced tensor: two
        streaming passes over the payload at measured (or datasheet)
        memory bandwidth."""
        mem_bw = self.spec.hbm_bandwidth
        if self.calib is not None and self.calib.mem_bw:
            mem_bw = self.calib.mem_bw
        return 2.0 * volume_bytes / max(mem_bw, 1.0)

    def _flat_wire_sync(self, volume_bytes: float, degree: int,
                        wire: str) -> float:
        """Flat quantized grad-sync candidate: the calibrated wire-
        dtype rows answer first (measured int8/fp8 collectives), else
        the float32 tables itemsize-scaled (the same curve queried at
        the narrow payload's byte volume), else the analytic ring at
        wire bytes — plus the quantize/dequantize overhead."""
        from ..parallel.placement import (bandwidth_multiplier,
                                          wire_byte_scale)
        wb = volume_bytes * wire_byte_scale(wire)
        t = None
        if self.calib is not None:
            t = self.calib.collective_marginal("all_reduce", degree, wb,
                                               dtype=wire)
            if t is None:
                t = self.calib.collective_marginal("all_reduce", degree,
                                                   wb)
        if t is None:
            ici_bw = (self.coll_bw or self.spec.ici_bandwidth) \
                / link_degradation_factor("ici")
            ici_lat = self.coll_lat if self.coll_lat is not None \
                else self.spec.ici_latency_us * 1e-6
            # two wire collectives (reduce leg + gather leg) pay twice
            # the latency rounds of the single fused ring — the
            # conservative side of the comparison
            t = (bandwidth_multiplier("all_reduce", degree)
                 * (degree - 1) / degree * wb / ici_bw
                 + 2 * (degree - 1) * ici_lat)
        return float(t) + self._quant_overhead_s(volume_bytes)

    def quantized_sync_quote(self, volume_bytes: float, degree: int,
                             skeleton: Sequence[Tuple[Tuple[str, ...],
                                                      str]],
                             mode: Optional[str] = None,
                             wire: Optional[str] = None
                             ) -> Optional[Tuple[float, float,
                                                 List[Optional[str]]]]:
        """Score one gradient tensor's sync at full precision vs with
        its legs quantized, over the tier-phase ``skeleton``
        (``[(axes, tier), ...]`` innermost first — what the runtime
        executes). Returns ``(baseline_s, quantized_s, phase_wires)``
        with ``phase_wires[i]`` the wire dtype of phase i (None =
        full-precision); all-None when the mode rejects quantization
        for this tensor. None when no policy applies."""
        q = self.quantization or {}
        mode = mode or q.get("mode")
        wire = wire or q.get("wire") or "int8"
        if not mode or mode == "off" or degree <= 1 or volume_bytes <= 0:
            return None
        saved = self.quantization
        try:
            self.quantization = None
            base = self.weight_sync_cost(volume_bytes, degree)
        finally:
            self.quantization = saved
        tiers = [t for _, t in skeleton] or ["ici"]
        if len(skeleton) <= 1 or self.placement is None:
            # flat sync: both sides answer from the same calibrated
            # curve (the wire side at the narrow payload's byte volume
            # — the itemsize-scaled fallback — or from measured
            # wire-dtype rows when they exist), so the auto comparison
            # is apples-to-apples
            if mode == "dcn_only":
                return None
            qc = self._flat_wire_sync(volume_bytes, degree, wire)
            if mode == "auto" and qc >= base:
                return base, base, [None] * len(tiers)
            return base, qc, [wire] * len(tiers)
        from ..parallel.placement import wire_byte_scale

        def phase_cost(volume, d, tier, w) -> float:
            pl = self.placement
            bw = None
            if pl is not None:
                try:
                    bw = pl.tier_graph.tier(tier).bandwidth
                except Exception:  # noqa: BLE001 — unknown tier
                    bw = None
            if bw is None:
                bw = self.spec.dcn_bandwidth if tier == "dcn" \
                    else (self.coll_bw or self.spec.ici_bandwidth)
            bw /= link_degradation_factor(tier)
            return 2.0 * (d - 1) / d * volume * wire_byte_scale(w) / bw

        def total_cost(phase_wires) -> float:
            # staged tree: inner legs reduce-scatter, so each outer leg
            # carries the tier-reduced volume (the runtime's shape).
            # Per-phase degrees resolve from the skeleton's real axes
            # through the placed axis sizes; a tierless (single-phase)
            # skeleton is the whole degree.
            sizes = dict(getattr(self.placement, "axis_sizes", None)
                         or {})
            resolved = []
            for (axes, _tier) in skeleton:
                d = 1
                for a in axes:
                    d *= int(sizes.get(a, 1)) or 1
                resolved.append(d)
            known = 1
            for d in resolved:
                known *= d
            if known != degree:
                if len(resolved) <= 1:
                    resolved = [degree]
                else:       # fold the unexplained remainder outermost
                    resolved[-1] = max(
                        1, degree * resolved[-1] // max(known, 1))
            cost, v = 0.0, volume_bytes
            for (_axes, tier), d, w in zip(skeleton, resolved,
                                           phase_wires):
                if d <= 1:
                    continue
                cost += phase_cost(v, d, tier, w)
                v = v / d          # staged: outer legs see reduced bytes
            if any(phase_wires):
                cost += self._quant_overhead_s(volume_bytes)
            return cost

        def wires(pred) -> List[Optional[str]]:
            return [wire if pred(t) else None for t in tiers]

        if mode == "dcn_only":
            cands = [wires(lambda t: t == "dcn")]
        elif mode == "all":
            cands = [wires(lambda t: True)]
        else:
            cands = [wires(lambda t: True)]
            if "dcn" in tiers and len(set(tiers)) > 1:
                cands.insert(0, wires(lambda t: t == "dcn"))
        best: Optional[Tuple[float, List[Optional[str]]]] = None
        for pw in cands:
            if not any(pw):
                continue
            c = total_cost(pw)
            if best is None or c < best[0]:
                best = (c, pw)
        if best is None:
            return None
        if mode == "auto" and best[0] >= base:
            return base, base, [None] * len(tiers)
        return base, best[0], best[1]

    def _placed_collective(self, volume_bytes: float, collective: str,
                           degree: int, axes: Optional[Tuple[str, ...]],
                           prefer: str, site: str) -> Optional[float]:
        """Tier-path pricing of one collective under the attached
        placement. Returns None when the path stays within one tier —
        the caller keeps its flat-mesh pricing, so single-tier machines
        are bit-identical to the historical model."""
        pl = self.placement
        if pl is None or degree <= 1 or volume_bytes <= 0:
            return None
        if self.placement_policy == "flat" and axes is None:
            # the legacy greedy allocator consumed axes in declaration
            # order — DCN first — so the flat baseline's per-op groups
            # land outermost and its sync group on what remains
            prefer = "outer" if prefer == "inner" else "inner"
        path = pl.path_for_axes(axes) if axes \
            else pl.path_for_degree(degree, prefer=prefer)
        if not path:
            return None
        if len(path) == 1 and \
                path[0][0].name == pl.tier_graph.innermost().name:
            # confined to the innermost fabric: the legacy (flat-mesh)
            # pricing IS that tier's pricing — keep it bit-identical,
            # calibrated fast paths included
            return None
        from ..parallel.placement import (_ring_tree, TreeChoice,
                                          choose_reduction_tree,
                                          tree_bandwidth_cost)
        # memo key carries the EXACT volume: a shape-class bucket here
        # made cost non-monotonic in volume (same-band payloads up to
        # ~2x apart returned the first-seen absolute cost)
        q = self.quantization
        memo_key = (site, collective, degree,
                    tuple((t.name, d) for t, d in path),
                    int(volume_bytes), self.placement_policy,
                    (q["mode"], q["wire"]) if q else None)
        choice = self._tree_memo.get(memo_key)
        if choice is None:
            if self.placement_policy == "flat":
                cost, phases = _ring_tree(collective, volume_bytes, path)
                choice = TreeChoice(algo="ring", phases=phases,
                                    cost_s=cost, flat_cost_s=cost)
            else:
                choice = choose_reduction_tree(self, collective,
                                               volume_bytes, path)
            if choice is None:
                return None
            if site == "grad_sync":
                # MARGINAL (bandwidth-only) pricing, the placed analog
                # of collective_marginal: XLA's all-reduce combiner
                # coalesces per-layer gradient reductions, so the
                # per-leg latency rounds are paid once per step, not
                # once per layer — charging them per op inverted the
                # searched-vs-DP ranking on dense tower models (see
                # weight_sync_cost). Applied to BOTH policies so the
                # searched-vs-flat audit ratio stays apples-to-apples.
                choice = TreeChoice(
                    algo=choice.algo, phases=choice.phases,
                    cost_s=tree_bandwidth_cost(choice.phases,
                                               pl.tier_graph),
                    flat_cost_s=choice.flat_cost_s)
                qchoice = self._quantize_tree(choice, pl.tier_graph,
                                              volume_bytes)
                if qchoice is not None:
                    choice = qchoice
            if len(self._tree_memo) > 4096:
                self._tree_memo.clear()
            self._tree_memo[memo_key] = choice
            self._record_choice(site, collective, degree, path, choice,
                                volume_bytes)
        if site == "grad_sync":
            self.last_sync_wire = next(
                (p.wire for p in choice.phases if p.wire), "float32")
        if self.provenance is not None:
            # tier-path pricing provenance (best effort): the
            # bottleneck (outermost) tier's row is the one a drift on
            # this entry should re-measure
            tier = path[-1][0].name
            key = self.calib.row_key(collective, degree, volume_bytes,
                                     tier=tier) \
                if self.calib is not None else None
            self._prov("sync" if site == "grad_sync" else "xfer",
                       f"coll_{collective}@{tier}", key, tier)
        return float(choice.cost_s)

    def _quantize_tree(self, choice, tier_graph, volume_bytes):
        """Per-PHASE precision choice on a grad-sync reduction tree
        (ops/quantized_collectives.py): re-price the chosen tree with
        some legs' wire dtype narrowed — the DCN legs only (dcn_only,
        and the auto candidate that keeps ICI full-precision) or every
        leg (all) — through the same bandwidth-marginal algebra
        (``tree_bandwidth_cost`` scales each leg by its wire's byte
        ratio), plus the quantize/dequantize overhead. Returns the
        quantized TreeChoice when the policy adopts it, else None."""
        q = self.quantization
        if q is None or not choice.phases:
            return None
        from ..parallel.placement import Phase, TreeChoice, \
            tree_bandwidth_cost
        wire, mode = q["wire"], q["mode"]

        def variant(pred):
            return [Phase(p.collective, p.tier, p.degree,
                          p.volume_bytes,
                          wire=wire if pred(p.tier) else None)
                    for p in choice.phases]

        cands = []
        if mode in ("dcn_only", "auto"):
            ph = variant(lambda t: t == "dcn")
            if any(p.wire for p in ph):
                cands.append(ph)
        if mode in ("all", "auto"):
            cands.append(variant(lambda t: True))
        best = None
        for ph in cands:
            if not any(p.wire for p in ph):
                continue
            cost = tree_bandwidth_cost(ph, tier_graph) \
                + self._quant_overhead_s(volume_bytes)
            if best is None or cost < best[0]:
                best = (cost, ph)
        if best is None:
            return None
        if mode == "auto" and best[0] >= choice.cost_s:
            return None
        return TreeChoice(algo=choice.algo, phases=best[1],
                          cost_s=best[0],
                          flat_cost_s=choice.flat_cost_s)

    def _record_choice(self, site, collective, degree, path, choice,
                       volume_bytes) -> None:
        if self.placement_policy == "hier":
            # only genuine selections count: the flat-policy baseline
            # re-pricing (searched-vs-flat audit) must not inflate the
            # algorithm counters with phantom ring "choices"
            from ..obs.metrics_registry import REGISTRY
            REGISTRY.counter(
                "ff_collective_algo_total",
                "Reduction-tree algorithms chosen by the "
                "placement-aware cost model").inc(algo=choice.algo)
            obs_events.counter(f"placement.algo_{choice.algo}")
        key = (site, collective, degree,
               tuple((t.name, d) for t, d in path))
        if len(self.algo_choices) > 512:
            self.algo_choices.clear()
        self.algo_choices[key] = {
            "site": site, "collective": collective, "degree": degree,
            "tier_path": [[t.name, d] for t, d in path],
            "volume_bytes": float(volume_bytes),
            **choice.to_json()}

    def _prov(self, term: str, table: Optional[str],
              key: Optional[str] = None, tier: Optional[str] = None
              ) -> None:
        """Record one provenance row when the tap is installed (audit
        breakdowns only): ``term`` is the audit-entry component the
        answer lands in ("compute" | "xfer" | "sync"), ``table`` the
        calibration table family, ``key`` the exact row."""
        p = self.provenance
        if p is not None:
            p.append({"term": term, "table": table, "key": key,
                      "tier": tier})

    # ------------------------------------------------------------------
    def attach_calibration(self, calib) -> None:
        """Attach a ``calibration.MeshCalibration``: measured host
        dispatch overhead + memory bandwidth + parallel efficiency enter
        ``op_cost`` and the persisted collective tables take precedence
        in ``xfer_cost``. Invalidates the in-memory op cache — costs
        priced under the old terms must not survive."""
        self.calib = calib
        self.cache.clear()
        self._impl_cache.clear()

    # ------------------------------------------------------------------
    def attach_kernel_tier(self, dmesh, forced: Optional[Dict[str, str]]
                           = None) -> None:
        """Turn on the kernel-impl dimension (kernels/registry.py):
        ``op_cost_with_impl`` prices attention at its cheapest available
        implementation on this mesh. ``forced`` pins op kinds to one
        impl (``--kernel-impl`` / FF_KERNEL_IMPL / the retired
        use_flash_attention shim)."""
        import jax
        tier = None
        seq_ax = getattr(dmesh, "seq_axis", None)
        if seq_ax:
            tier = getattr(dmesh, "axis_tiers", {}).get(seq_ax)
        self.kernel_tier = {
            "seq_degree": int(getattr(dmesh, "seq_degree", 0) or 0),
            "backend": jax.default_backend(),
            "tier": tier,
            "forced": dict(forced or {}),
        }
        self.kernel_choice = {}
        self.cache.clear()
        self._impl_cache.clear()

    def op_cost_with_impl(self, layer: Layer,
                          shard_degrees: Dict[int, int],
                          weight_shard_degree: int = 1) -> CostMetrics:
        """``op_cost`` with the kernel-impl dimension resolved: when a
        kernel tier is attached and the op has registered variants, every
        AVAILABLE impl is priced (``kernel_impl_cost``) and the cheapest
        answers; the argmin lands in ``last_kernel_impl`` (audit
        breakdowns) and ``kernel_choice[layer.name]``
        (``FFModel._plan_kernels``). Without a tier this IS ``op_cost``."""
        self.last_kernel_impl = None
        kt = self.kernel_tier
        if kt is None \
                or layer.op_type != OperatorType.OP_MULTIHEAD_ATTENTION:
            return self.op_cost(layer, shard_degrees, weight_shard_degree)
        from ..kernels import registry as kreg
        q_len = int(layer.inputs[0].shape[1]) if layer.inputs else 0
        kv_len = int(layer.inputs[1].shape[1]) \
            if len(layer.inputs) > 1 else q_len
        ctx = kreg.attention_ctx(layer.params, q_len, kv_len,
                                 backend=kt["backend"],
                                 seq_degree=kt["seq_degree"])
        forced = kt["forced"].get(kreg.ATTENTION)
        if forced is not None:
            names = [forced]  # pinned: availability enforced at adopt
        else:
            names = kreg.available_impls(kreg.ATTENTION, ctx)
        best_name, best = None, None
        for name in names:
            cm = self.kernel_impl_cost(
                layer, kreg.ATTENTION, name, shard_degrees,
                weight_shard_degree,
                seq_degree=kt["seq_degree"] if name == "ring" else 0,
                tier=kt["tier"])
            t = cm.forward_time + cm.backward_time
            if best is None or t < best.forward_time + best.backward_time:
                best_name, best = name, cm
        if best is None:  # no registered impl legal — reference path
            return self.op_cost(layer, shard_degrees, weight_shard_degree)
        self.last_kernel_impl = best_name
        self.kernel_choice[layer.name] = best_name
        return best

    # ------------------------------------------------------------------
    def calibrate(self):
        """Measure real matmul throughput on the local device to set the
        efficiency factor (one-time, <1s). Synchronizes via a device-to-
        host value fetch — block_until_ready does not block on tunneled
        TPU backends."""
        try:
            import jax
            import jax.numpy as jnp
            n = 2048
            reps = 8
            a = jnp.ones((n, n), jnp.bfloat16)

            def chain(x):
                for _ in range(reps):
                    x = x @ x
                    x = x * jnp.bfloat16(1e-3)
                return jnp.sum(x.astype(jnp.float32))

            f = jax.jit(chain)
            float(np.asarray(f(a)))  # compile + sync
            t0 = time.perf_counter()
            float(np.asarray(f(a)))
            dt = (time.perf_counter() - t0) / reps
            achieved = 2.0 * n ** 3 / dt
            self.mxu_eff = min(1.0, max(0.05,
                                        achieved / self.spec.peak_flops))
        except Exception:
            pass

    # ------------------------------------------------------------------
    def calibrate_collectives(self, dmesh: "DeviceMesh") -> None:
        """Fit effective all-reduce bandwidth + latency by timing a real
        ring all-reduce at two sizes on the live mesh (same pattern as
        ``calibrate()`` for matmuls; the reference trusts per-link
        constants from its machine model, ``machine_model.cc``). The fit
        t(s) = 2(n-1)/n * s/bw + (n-1)*lat replaces the machine-model
        ICI constants in ``xfer_cost`` — essential on the CPU simulation
        platform, where the v5e constants mispredict collectives badly.
        Disk-cached per (backend, mesh shape, slice structure): a fit
        from one mesh topology must not be reused for a differently
        shaped or multi-slice mesh of the same device count, where
        effective all-reduce bandwidth differs."""
        import jax
        n = dmesh.num_devices
        if n <= 1:
            return
        shape = "x".join(f"{a}{s}"
                         for a, s in dmesh.axis_sizes.items())
        slices = getattr(getattr(dmesh, "spec", None), "num_slices", 1)
        key = f"coll_{jax.default_backend()}_{n}_{shape}_s{slices}"
        cached = self._disk_cache().get(key)
        if cached:
            self.coll_bw, self.coll_lat = cached
            return
        try:
            import jax.numpy as jnp
            from jax.sharding import PartitionSpec as P

            from ..utils.jax_compat import shard_map
            mesh = dmesh.mesh
            axes = tuple(mesh.axis_names)

            def bench(nbytes: int) -> float:
                m = max(nbytes // 4, 1024)
                x = jnp.ones((m,), jnp.float32)

                @jax.jit
                def f(x):
                    return shard_map(
                        lambda xl: jax.lax.psum(xl, axes), mesh=mesh,
                        in_specs=P(None), out_specs=P(None))(x)

                float(np.asarray(f(x)[0]))  # compile + sync
                ts = []
                for _ in range(3):
                    t0 = time.perf_counter()
                    float(np.asarray(f(x)[0]))
                    ts.append(time.perf_counter() - t0)
                return float(np.median(ts))

            s1, s2 = 1 << 20, 16 << 20
            t1, t2 = bench(s1), bench(s2)
            a = 2.0 * (n - 1) / n
            if t2 > t1 > 0:
                bw = a * (s2 - s1) / (t2 - t1)
                lat = max((t1 - a * s1 / bw) / (n - 1), 1e-9)
            else:  # noisy fit: bandwidth-only estimate from the big size
                bw = a * s2 / max(t2, 1e-9)
                lat = 1e-9
            self.coll_bw = float(min(max(bw, 1e7), 1e13))
            self.coll_lat = float(min(lat, 1e-2))
            self._disk_put(key, [self.coll_bw, self.coll_lat])
        except Exception:  # noqa: BLE001 — calibration is best-effort
            pass

    # ------------------------------------------------------------------
    # on-device per-op measurement (simulator.cc:537 / model.cu:38 analog)
    # ------------------------------------------------------------------
    @staticmethod
    def _local_shape(shape: Sequence[int],
                     degrees: Dict[int, int]) -> Tuple[int, ...]:
        out = list(shape)
        for d, deg in degrees.items():
            if 0 <= d < len(out) and deg > 1 and out[d] % deg == 0:
                out[d] = out[d] // deg
        return tuple(out)

    def _make_arg(self, shape, dtype, rng: np.random.Generator,
                  int_high: int):
        import jax.numpy as jnp
        from ..dtypes import to_jnp
        jdt = to_jnp(dtype)
        if np.issubdtype(np.dtype(jdt if jdt != jnp.bfloat16 else np.float32),
                         np.integer):
            return jnp.asarray(
                rng.integers(0, max(int_high, 2), size=shape), jdt)
        return jnp.asarray(rng.standard_normal(shape) * 0.02, jdt)

    def measure(self, layer: Layer, shard_degrees: Dict[int, int],
                weight_shard_degree: int = 1, warmup: int = 2,
                repeats: int = 5) -> Optional[CostMetrics]:
        """Microbenchmark one op's fwd and fwd+bwd at shard-local shape on
        the local device (jit the op's own ``emit``; warmup + repeat +
        median; device-to-host fetch as the sync barrier). Returns None
        when the op cannot be measured standalone — caller falls back to
        the analytic roofline."""
        import jax
        import jax.numpy as jnp
        from ..dtypes import to_jnp
        from ..ops import EmitCtx

        op = get_op_def(layer.op_type)
        out_shape = layer.outputs[0].shape if layer.outputs else ()
        out_rank = len(out_shape)
        # A degree on the LAST output dim is feature/head sharding: it is
        # realized by sharding the weight's output dim, NOT by shrinking
        # the op input (column-parallel linear/attention). Degrees on
        # earlier dims (batch/spatial) shrink the activations.
        act_degrees = {d: g for d, g in shard_degrees.items()
                       if d < out_rank - 1}
        eff_wdeg = weight_shard_degree * shard_degrees.get(out_rank - 1, 1)
        rng = np.random.default_rng(0)
        int_high = int(layer.params.get(
            "num_entries", layer.params.get("vocab_size", 100)))
        ins = []
        for t in layer.inputs:
            ls = self._local_shape(t.shape, act_degrees) \
                if len(t.shape) == len(out_shape) else t.shape
            ins.append(self._make_arg(ls, t.dtype, rng, int_high))
        w: Dict[str, Any] = {}
        for spec in (layer.weights or op.weights(
                layer.params, [t.shape for t in layer.inputs],
                [t.dtype for t in layer.inputs])):
            ws = list(spec.shape)
            if eff_wdeg > 1 and ws and ws[-1] % eff_wdeg == 0:
                ws[-1] //= eff_wdeg
            w[spec.name] = self._make_arg(tuple(ws), spec.dtype, rng, 2)
        state = {}
        state_spec = getattr(op, "state_spec", None)
        if state_spec is not None:
            ss = state_spec(layer.params, [t.shape for t in layer.inputs],
                            [t.dtype for t in layer.inputs]) or {}
            for sname, (sshape, sdt) in ss.items():
                init = jnp.ones if sname == "var" else jnp.zeros
                state[sname] = init(sshape, to_jnp(sdt))

        def make_ctx():
            return EmitCtx(training=True,
                           rngs={layer.name: jax.random.key(0)},
                           state={layer.name: state})

        def fwd(ins_, w_):
            outs = op.emit(layer.params, list(ins_), w_, make_ctx(),
                           layer.name)
            return sum(jnp.sum(o.astype(jnp.float32)) for o in outs)

        float_ins = [i for i, a in enumerate(ins)
                     if jnp.issubdtype(a.dtype, jnp.floating)]

        def fwdbwd(ins_, w_):
            def loss(w__, fins):
                full = list(ins_)
                for i, a in zip(float_ins, fins):
                    full[i] = a
                return fwd(full, w__)
            args = (w_, [ins_[i] for i in float_ins])
            g = jax.grad(loss, argnums=(0, 1))(*args)
            return jax.tree_util.tree_reduce(
                lambda acc, x: acc + jnp.sum(x.astype(jnp.float32)), g, 0.0)

        def timed(fn):
            f = jax.jit(fn)
            for _ in range(warmup):
                float(np.asarray(f(ins, w)))  # fetch = sync barrier
            ts = []
            for _ in range(repeats):
                t0 = time.perf_counter()
                float(np.asarray(f(ins, w)))
                ts.append(time.perf_counter() - t0)
            return float(np.median(ts))

        t_all = time.perf_counter()
        try:
            fwd_t = timed(fwd)
            tot_t = timed(fwdbwd) if (float_ins or w) else fwd_t
            return CostMetrics(forward_time=fwd_t,
                               backward_time=max(tot_t - fwd_t, 0.0))
        except Exception:
            return None
        finally:
            # real elapsed time, success or failure: a 60s failed
            # compile must burn 60s of budget, not a token 1s
            self._measure_spent_s += time.perf_counter() - t_all

    def _measured_cost(self, layer: Layer, shard_degrees: Dict[int, int],
                       weight_shard_degree: int,
                       key: Tuple) -> Optional[CostMetrics]:
        """Disk-cached measurement; None = not measurable / over budget."""
        dkey = repr(key)
        cached = self._disk_cache().get(dkey)
        if cached is not None:
            obs_events.counter("costmodel.measure_cache_hits")
            return CostMetrics(forward_time=cached[0],
                               backward_time=cached[1])
        if key in self._unmeasurable:
            return None
        if self._measure_spent_s >= self.measure_budget_s:
            return None
        obs_events.counter("costmodel.measure_cache_misses")
        with obs_events.span("costmodel.measure", op=layer.name):
            cm = self.measure(layer, shard_degrees, weight_shard_degree)
        if cm is None:
            # in-memory only: a failure may be transient (device busy,
            # flaky compile) and must not poison future processes
            self._unmeasurable.add(key)
            return None
        self._disk_put(dkey, [cm.forward_time, cm.backward_time])
        return cm

    # ------------------------------------------------------------------
    def op_cost(self, layer: Layer, shard_degrees: Dict[int, int],
                weight_shard_degree: int = 1) -> CostMetrics:
        """Cost of one op with its output dims partitioned by
        ``shard_degrees`` (dim -> degree). Compute scales ~1/prod(degrees);
        memory likewise."""
        key = (layer.param_key(), tuple(sorted(shard_degrees.items())),
               weight_shard_degree)
        obs_events.counter("costmodel.queries")
        hit = self.cache.get(key)
        if hit is not None:
            obs_events.counter("costmodel.cache_hits")
            if self.provenance is not None:
                self._op_prov(key)
            return hit
        op = get_op_def(layer.op_type)
        in_shapes = [t.shape for t in layer.inputs]
        out_shapes = [t.shape for t in layer.outputs]
        total_deg = 1
        for d in shard_degrees.values():
            total_deg *= max(d, 1)
        flops = op.flops(layer.params, in_shapes, out_shapes) / total_deg
        in_bytes = sum(int(np.prod(t.shape)) * itemsize(t.dtype)
                       for t in layer.inputs) // total_deg
        out_bytes = sum(int(np.prod(t.shape)) * itemsize(t.dtype)
                        for t in layer.outputs) // total_deg
        w_bytes = sum(int(np.prod(w.shape)) * itemsize(w.dtype)
                      for w in layer.weights) // max(weight_shard_degree, 1)
        bytes_moved = in_bytes + out_bytes + w_bytes
        t_compute = flops / (self.spec.peak_flops * self.mxu_eff)
        # calibration v2: measured memory bandwidth replaces the
        # datasheet HBM constant; measured host dispatch overhead
        # replaces the fixed 2us guess; measured parallel efficiency
        # stretches per-shard time when concurrent shards oversubscribe
        # the host (N virtual devices on C < N cores) — the host terms
        # the r05 fidelity study showed the blind model lacks
        mem_bw = self.spec.hbm_bandwidth
        dispatch = self.overhead_s
        par_eff = 1.0
        if self.calib is not None:
            if self.calib.mem_bw:
                mem_bw = self.calib.mem_bw
            if self.calib.dispatch_s:
                dispatch = self.calib.dispatch_s
            # SPMD executes EVERY op on every device simultaneously —
            # replicated ops run N full copies, sharded ops N shards —
            # so the whole mesh's concurrency applies regardless of the
            # op's own shard degrees (a replicated op escaping the
            # stretch would under-price replication vs sharding)
            par_eff = self.calib.efficiency(max(self.spec.num_devices, 1))
        t_mem = bytes_moved / mem_bw
        fwd = max(t_compute, t_mem) / max(par_eff, 1e-6) + dispatch
        bwd = fwd * op.backward_flops_factor() \
            if layer.op_type != OperatorType.OP_INPUT else 0.0
        if (self.measure_on_device and flops >= self._MEASURE_MIN_FLOPS
                and layer.op_type not in PARALLEL_OPS
                and layer.op_type != OperatorType.OP_INPUT):
            mm = self._measured_cost(layer, shard_degrees,
                                     weight_shard_degree,
                                     (self.spec.generation,) + key)
            if mm is not None:
                fwd, bwd = mm.forward_time, mm.backward_time
        cm = CostMetrics(forward_time=fwd, backward_time=bwd,
                         inputs_memory=in_bytes, outputs_memory=out_bytes,
                         weights_memory=w_bytes)
        self.cache[key] = cm
        if self.provenance is not None:
            self._op_prov(key)
        return cm

    def _op_prov(self, key: Tuple) -> None:
        """Compute-term provenance for one ``op_cost`` answer: the
        on-device measured row when one exists, else the calibrated
        host terms (membw/dispatch/parallel-eff — re-measuring those
        three is what fixes a drifting compute prediction), else the
        bare analytic roofline."""
        from .calibration import CalibrationTable
        if self.measure_on_device:
            dkey = repr((self.spec.generation,) + key)
            if self._disk_cache().get(dkey) is not None:
                self._prov("compute", "opcost", dkey)
                return
        if self.calib is not None:
            b = self.calib.backend
            self._prov("compute", "host_membw",
                       CalibrationTable.key(b, "host_membw"))
            self._prov("compute", "host_dispatch",
                       CalibrationTable.key(b, "host_dispatch"))
            if self.calib.parallel_eff:
                n = max(self.spec.num_devices, 1)
                self._prov("compute", "parallel_eff",
                           CalibrationTable.key(b, "parallel_eff", "-",
                                                0, n))
        else:
            self._prov("compute", None)

    # ------------------------------------------------------------------
    # searchable kernel tier (kernels/registry.py)
    # ------------------------------------------------------------------
    def kernel_impl_cost(self, layer: Optional[Layer], op: str,
                         impl_name: str,
                         shard_degrees: Optional[Dict[int, int]] = None,
                         weight_shard_degree: int = 1, *,
                         seq_degree: int = 0,
                         tier: Optional[str] = None,
                         param_bytes: float = 0.0,
                         **_ignored) -> CostMetrics:
        """Price one (op, kernel-impl) pair — the registry's cost entry
        point (``kernels/registry.py KernelImpl.cost``).

        ``attention``: starts from :meth:`op_cost` (the XLA reference
        path) and swaps the attention CORE term for the chosen impl's.
        Measured ``op_attention@<impl>`` calibration rows answer first
        (both sides of the swap from the same table, so the delta is
        apples-to-apples); off-table impls use the analytic curves:
        flash = same matmul flops minus the (s, s) score-matrix HBM
        round trip; ring = core/deg + (deg-1) ``ppermute`` hops of the
        local K/V block, each hop priced from the ``coll_ppermute``
        rows (``tier``-scoped when given) and — under ``overlap_mode``
        — charged only for its EXPOSED remainder after the concurrent
        block compute (the PR-13 bucket model applied to ring slices).

        ``opt_update``: absolute update time over ``param_bytes`` —
        ``fused`` streams w/g/m/v through VMEM once (~7 HBM passes),
        ``unfused`` pays XLA's multi-kernel round trips (~2x).
        """
        sd = dict(shard_degrees or {})
        key = (layer.param_key() if layer is not None else None, op,
               impl_name, tuple(sorted(sd.items())), weight_shard_degree,
               seq_degree, tier, int(param_bytes))
        hit = self._impl_cache.get(key)
        if hit is not None:
            return hit
        mem_bw = self.spec.hbm_bandwidth
        if self.calib is not None and self.calib.mem_bw:
            mem_bw = self.calib.mem_bw

        if op == "opt_update":
            b = max(float(param_bytes), 0.0)
            passes = 7.0 if impl_name == "fused" else 14.0
            t = passes * b / max(mem_bw, 1.0)
            if self.calib is not None:
                m = self.calib.op_time(f"opt_update@{impl_name}", b)
                if m is not None:
                    t = m
            cm = CostMetrics(forward_time=t)
            self._impl_cache[key] = cm
            return cm

        if op != "attention" or layer is None:
            raise ValueError(f"unpriceable kernel op {op!r}")
        base = self.op_cost(layer, sd, weight_shard_degree)
        out = layer.outputs[0].shape
        bsz, s = int(out[0]), int(out[1])
        h = int(layer.params.get("num_heads", 1))
        # kdim/embed_dim of 0 are unset placeholders, not real dims
        e = int(layer.params.get("kdim") or
                layer.params.get("embed_dim") or out[-1])
        dh = e // max(h, 1)
        total_deg = 1
        for d in sd.values():
            total_deg *= max(d, 1)
        pe = 1.0
        if self.calib is not None:
            pe = max(self.calib.efficiency(
                max(self.spec.num_devices, 1)), 1e-6)
        # fwd core: the two (s, s) contractions (qk^T and p·v),
        # stretched by measured parallel efficiency exactly like the
        # base roofline so "base minus core" stays non-negative
        core_flops = 4.0 * bsz * s * s * h * dh / total_deg
        t_core = core_flops / (self.spec.peak_flops * self.mxu_eff) / pe
        # the XLA path's score-matrix HBM round trip (write + read of
        # the (b, h, s, s) logits) — the traffic flash/ring never pay,
        # and the base roofline (inputs+outputs+weights only) misses
        t_scores = 2.0 * 4.0 * bsz * h * s * s / total_deg \
            / max(mem_bw, 1.0) / pe
        q_bytes = 4.0 * bsz * s * h * dh / total_deg
        # everything in the op that is NOT the attention core
        # (projections, bias, softmax overhead) — shared by every impl
        rest_f = max(base.forward_time - t_core, 0.0)
        rest_b = max(base.backward_time - 2.0 * t_core, 0.0)

        def _measured(name: str, deg: int = 0):
            """Measured impl time, ONLY within the measured payload
            range (x2 margin): the bench grid spans s=128..1024 at its
            own geometry, and extrapolating the near-quadratic xla
            curve an order of magnitude out turns the impl comparison
            into noise larger than the base cost itself. Out-of-range
            queries fall back to the analytic curve."""
            if self.calib is None:
                return None
            key = (f"op:attention@{name}", deg, self.calib.dtype)
            pts = self.calib._pts.get(key)
            if pts is None:
                self.calib.op_time(f"attention@{name}", 1, degree=deg)
                pts = self.calib._pts.get(key) or []
            if not pts or not (pts[0][0] / 2 <= q_bytes
                               <= pts[-1][0] * 2):
                return None
            return self.calib.op_time(f"attention@{name}", q_bytes,
                                      degree=deg)

        if impl_name == "xla":
            m = _measured("xla")
            t_impl = m if m is not None else t_core + t_scores
            t_impl_b = 2.0 * t_impl
        elif impl_name == "flash":
            m = _measured("flash")
            t_impl = m if m is not None else t_core
            t_impl_b = 2.0 * t_impl
        elif impl_name == "ring":
            deg = max(int(seq_degree), 1)
            # per-device: deg blocks of (s/deg, s/deg) scores — core
            # compute drops by deg, per-chunk score traffic by deg^2
            # summed over deg chunks
            t_blocks = (t_core + t_scores / deg) / deg
            hop_bytes = 2.0 * 4.0 * bsz * h * (s / max(deg, 1)) * dh
            hop_t = None
            if self.calib is not None:
                hop_t = self.calib.collective_time(
                    "ppermute", deg, hop_bytes, tier=tier)
                if hop_t is None and tier is not None:
                    hop_t = self.calib.collective_time(
                        "ppermute", deg, hop_bytes)
            if hop_t is None:
                ici_bw = (self.coll_bw or self.spec.ici_bandwidth) \
                    / link_degradation_factor(tier or "ici")
                ici_lat = self.coll_lat if self.coll_lat is not None \
                    else self.spec.ici_latency_us * 1e-6
                hop_t = hop_bytes / max(ici_bw, 1.0) + ici_lat
            per_hop_block = t_blocks / max(deg, 1)
            if self.overlap_mode:
                # each hop's transfer overlaps the concurrent block's
                # compute — only the exposed remainder is charged
                # (PR-13's bucket split applied to ring slices)
                exposed = max(hop_t - per_hop_block, 0.0)
            else:
                exposed = hop_t
            comm_f = (deg - 1) * exposed
            m = _measured("ring", deg=deg)
            if m is not None:
                t_impl = m              # the bench times hops included
                t_impl_b = 2.0 * m + comm_f   # bwd rings 2x payload
            else:
                t_impl = t_blocks + comm_f
                # backward rotates (k, v, dk, dv) — double payload
                t_impl_b = 2.0 * t_blocks + 2.0 * comm_f
        else:
            raise ValueError(f"unknown attention impl {impl_name!r}")

        fwd = rest_f + t_impl
        bwd = rest_b + t_impl_b
        cm = CostMetrics(forward_time=fwd, backward_time=bwd,
                         inputs_memory=base.inputs_memory,
                         outputs_memory=base.outputs_memory,
                         weights_memory=base.weights_memory)
        self._impl_cache[key] = cm
        if self.provenance is not None:
            row = None
            if self.calib is not None:
                from .calibration import CalibrationTable, shape_class
                d = seq_degree if impl_name == "ring" else 0
                if self.calib.op_time(f"attention@{impl_name}", q_bytes,
                                      degree=d) is not None:
                    row = CalibrationTable.key(
                        self.calib.backend,
                        f"op_attention@{impl_name}", "float32",
                        shape_class(q_bytes), d)
            self._prov("compute", f"op_attention@{impl_name}", row,
                       tier)
        return cm

    # ------------------------------------------------------------------
    def xfer_cost(self, volume_bytes: float, collective: str,
                  degree: int,
                  axes: Optional[Tuple[str, ...]] = None) -> float:
        """Collective time (ring algorithms): all-gather/reduce-scatter
        move (d-1)/d of the volume; all-reduce 2(d-1)/d; all-to-all
        (d-1)/d with per-hop latency.

        Hierarchical placement (``attach_placement``): when the
        collective's mesh axes (``axes``, or the placement policy's
        axis consumption for a bare degree) span more than one hardware
        tier, the cost is the cheapest reduction-tree shape over that
        (tier, degree) path — ring vs recursive halving vs two/three-
        phase hierarchical trees (``parallel/placement.py``,
        arXiv 2110.10548) — and the choice is recorded for the audit
        record. Single-tier paths (and no placement) keep the exact
        historical pricing below.

        Multi-slice machines without a placement: a collective whose
        degree exceeds ``devices_per_slice`` necessarily crosses DCN;
        its cost is the standard hierarchical decomposition —
        intra-slice leg over ICI plus an inter-slice leg on the
        slice-reduced volume over DCN (reference analog: per-link-type
        simulation in ``src/runtime/network.cc`` /
        ``simulator.h:381-499``).

        Calibration v2: a persisted measured table for this
        (backend, collective, degree) answers first — real XLA
        collective timings at import-time shapes interpolated across
        shape classes; degrees never measured fall through to the
        fitted/analytic ring model."""
        obs_events.counter("costmodel.xfer_queries")
        placed = self._placed_collective(volume_bytes, collective,
                                         degree, axes, "inner",
                                         "op_collective")
        if placed is not None:
            floor = (self.calib.dispatch_s or 0.0) \
                if self.calib is not None else 0.0
            return max(floor, placed)
        floor = 0.0
        if self.calib is not None:
            kind = "all_to_all" if collective == "permute" else collective
            t = self.calib.collective_time(kind, degree, volume_bytes)
            if t is not None:
                if self.provenance is not None:
                    self._prov("xfer", f"coll_{kind}",
                               self.calib.row_key(kind, degree,
                                                  volume_bytes))
                return float(t)
            # even off-table, no collective is cheaper than one measured
            # host dispatch — the floor the host-blind model lacked
            floor = self.calib.dispatch_s or 0.0
        if self.provenance is not None and degree > 1 \
                and volume_bytes > 0:
            self._prov("xfer", None)     # analytic ring model
        ici_bw = (self.coll_bw or self.spec.ici_bandwidth) \
            / link_degradation_factor("ici")
        ici_lat = self.coll_lat if self.coll_lat is not None \
            else self.spec.ici_latency_us * 1e-6
        per_slice = self.spec.devices_per_slice
        if self.spec.num_slices > 1 and degree > per_slice:
            d_in = math.gcd(degree, per_slice) or 1
            d_out = degree // d_in
            t = (self._ring_cost(volume_bytes, collective, d_in,
                                 ici_bw, ici_lat)
                 + self._ring_cost(volume_bytes / max(d_in, 1),
                                   collective, d_out,
                                   self.spec.dcn_bandwidth
                                   / link_degradation_factor("dcn"),
                                   self.spec.dcn_latency_us * 1e-6))
        else:
            t = self._ring_cost(volume_bytes, collective, degree,
                                ici_bw, ici_lat)
        # zero-cost (elided) collectives stay free; everything real is
        # floored at one measured host dispatch
        return max(floor, t) if t > 0 else t

    @staticmethod
    def _ring_cost(volume_bytes: float, collective: str, degree: int,
                   bw: float, lat: float) -> float:
        if degree <= 1 or volume_bytes <= 0:
            return 0.0
        from ..parallel.placement import bandwidth_multiplier
        frac = (degree - 1) / degree
        mult = bandwidth_multiplier(collective, degree)
        return mult * frac * volume_bytes / bw + (degree - 1) * lat

    def reshard_step_cost(self, kind: str, degree: int,
                          volume_bytes: float,
                          axes: Optional[Tuple[str, ...]] = None
                          ) -> float:
        """Cost of ONE step of a reshard lowering plan
        (``parallel/reshard.py``): ``all_gather`` / ``all_to_all`` price
        through ``xfer_cost`` — the calibrated collective tables answer
        first, and with a placement attached the step's actual mesh
        ``axes`` select its tier path — while ``slice`` is a local block
        copy (no traffic), priced at measured memory bandwidth plus one
        dispatch."""
        if degree <= 1 or volume_bytes <= 0:
            return 0.0
        if kind == "slice":
            mem_bw = self.spec.hbm_bandwidth
            dispatch = self.overhead_s
            if self.calib is not None:
                if self.calib.mem_bw:
                    mem_bw = self.calib.mem_bw
                if self.calib.dispatch_s:
                    dispatch = self.calib.dispatch_s
            return volume_bytes / max(mem_bw, 1.0) + dispatch
        return self.xfer_cost(volume_bytes, kind, degree, axes=axes)

    def resharding_cost(self, tensor_bytes: float,
                        src_degrees: Dict[int, int],
                        dst_degrees: Dict[int, int]) -> float:
        """Cost of moving a tensor between two dim->degree layouts
        (reference ``estimate_xfer_cost`` / Repartition special case)."""
        if src_degrees == dst_degrees:
            return 0.0
        src_total = int(np.prod(list(src_degrees.values()))) \
            if src_degrees else 1
        dst_total = int(np.prod(list(dst_degrees.values()))) \
            if dst_degrees else 1
        if src_total == 1 and dst_total > 1:
            return 0.0  # slicing a replicated tensor is local
        if dst_total == 1:
            return self.xfer_cost(tensor_bytes, "all_gather", src_total)
        same_dims = set(src_degrees) == set(dst_degrees)
        if same_dims:
            return self.xfer_cost(tensor_bytes, "permute",
                                  max(src_total, dst_total))
        return self.xfer_cost(tensor_bytes, "all_to_all",
                              max(src_total, dst_total))

    def weight_sync_cost(self, weight_bytes: float, dp_degree: int,
                         axes: Optional[Tuple[str, ...]] = None) -> float:
        """Per-step gradient all-reduce (reference NCCL optimizer path).

        Hierarchical placement: the data-parallel group lives on the
        axes the per-op groups did NOT consume — outermost tiers
        included — so a tier-crossing sync is priced as the best
        reduction tree over its path (e.g. intra-slice reduce-scatter →
        inter-slice all-reduce over hosts → intra-slice all-gather)
        instead of one flat DCN-bottlenecked ring.

        Calibrated (single-tier): priced at the measured curve's
        MARGINAL (per-byte) cost — XLA's all-reduce combiner coalesces
        per-layer gradient reductions into a few large collectives, so
        the fixed dispatch floor is paid once per step, not once per op
        (calibration.MeshCalibration.collective_marginal)."""
        self.last_sync_wire = "float32"
        placed = self._placed_collective(weight_bytes, "all_reduce",
                                         dp_degree, axes, "outer",
                                         "grad_sync")
        if placed is not None:
            return placed
        t = None
        if self.calib is not None and dp_degree > 1 and weight_bytes > 0:
            t = self.calib.collective_marginal("all_reduce", dp_degree,
                                               weight_bytes)
            if t is not None:
                t = float(t)
        if t is None:
            n0 = len(self.provenance) if self.provenance is not None \
                else 0
            t = self.xfer_cost(weight_bytes, "all_reduce", dp_degree)
            if self.provenance is not None:
                # the fallthrough priced through xfer_cost, but this IS
                # the gradient sync — drift diffs it under "sync"
                for row in self.provenance[n0:]:
                    row["term"] = "sync"
        elif self.provenance is not None:
            self._prov("sync", "coll_all_reduce",
                       self.calib.row_key("all_reduce", dp_degree,
                                          weight_bytes))
        # quantized flat candidate (ops/quantized_collectives.py): the
        # per-TENSOR precision choice — int8/fp8 wire payload at 1/4 of
        # the bytes, error feedback carried as runtime state. "auto"
        # takes it only when the scaled curve predicts a win; "all"
        # mandates it. (dcn_only is a tree-leg policy — the flat path
        # has no DCN leg to narrow.)
        q = self.quantization
        if q is not None and q["mode"] in ("auto", "all") \
                and dp_degree > 1 and weight_bytes > 0:
            qc = self._flat_wire_sync(weight_bytes, dp_degree,
                                      q["wire"])
            if q["mode"] == "all" or qc < t:
                self.last_sync_wire = q["wire"]
                if self.provenance is not None:
                    from ..parallel.placement import wire_byte_scale
                    self._prov("sync", "coll_all_reduce",
                               self.calib.row_key(
                                   "all_reduce", dp_degree,
                                   weight_bytes
                                   * wire_byte_scale(q["wire"]))
                               if self.calib is not None else None,
                               None)
                    self.provenance[-1]["wire"] = q["wire"]
                return qc
        return t

    # ------------------------------------------------------------------
    # serving objective (search/serving_plan.py)
    # ------------------------------------------------------------------
    def decode_collective_cost(self, volume_bytes: float,
                               collective: str, degree: int,
                               axes: Optional[Tuple[str, ...]] = None
                               ) -> float:
        """Latency-side price of ONE decode-step collective.

        Decode-step payloads are tiny ((bucket × hidden) activations at
        seq-len 1) and fire once per generated token — XLA cannot
        coalesce them across tokens the way the gradient-sync combiner
        batches per-layer reductions, so the per-dispatch floor and
        per-hop latency terms dominate. Routes through ``xfer_cost``
        (calibrated small-message table rows, placement/tree selection,
        dispatch floor) — deliberately NOT the bandwidth-marginal
        ``weight_sync_cost``/``collective_marginal`` path, which prices
        exactly the coalescing decode does not get."""
        return self.xfer_cost(volume_bytes, collective, degree,
                              axes=axes)

    def kv_read_time(self, kv_bytes: float) -> float:
        """HBM time to stream a resident KV cache once — the per-step
        memory floor of autoregressive decode (every step reads the
        full local cache). Uses the calibrated memory bandwidth when a
        calibration is attached."""
        if kv_bytes <= 0:
            return 0.0
        mem_bw = self.spec.hbm_bandwidth
        if self.calib is not None and self.calib.mem_bw:
            mem_bw = self.calib.mem_bw
        return kv_bytes / max(mem_bw, 1.0)
