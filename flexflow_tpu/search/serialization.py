"""Strategy import/export (reference ``--export``/``--import``,
``src/runtime/strategy.cc``): JSON with per-layer output/weight
PartitionSpecs and the mesh axis sizes."""
from __future__ import annotations

import json
from typing import Dict, Optional, Tuple

from jax.sharding import PartitionSpec as P

from ..parallel.machine import DeviceMesh
from ..parallel.strategy import OpSharding, ShardingStrategy


def _spec_to_json(spec: Optional[P]):
    if spec is None:
        return None
    return [list(e) if isinstance(e, tuple) else e for e in spec]


def _spec_from_json(j) -> Optional[P]:
    if j is None:
        return None
    return P(*[tuple(e) if isinstance(e, list) else e for e in j])


def save_strategy(path: str, strategy: ShardingStrategy,
                  assignment: Optional[Dict] = None,
                  meta: Optional[Dict] = None):
    doc = {
        "mesh_axes": dict(strategy.dmesh.axis_sizes),
        "inputs": {k: _spec_to_json(v) for k, v in strategy.inputs.items()},
        "ops": {
            name: {
                "outputs": [_spec_to_json(s) for s in os.outputs],
                "weights": {w: _spec_to_json(s)
                            for w, s in os.weights.items()},
            } for name, os in strategy.ops.items()},
        "assignment": {k: list(v) for k, v in (assignment or {}).items()},
        "meta": meta or {},
    }
    with open(path, "w") as f:
        json.dump(doc, f, indent=1)


def load_strategy(path: str, layers, dmesh: DeviceMesh) -> ShardingStrategy:
    with open(path) as f:
        doc = json.load(f)
    saved_axes = doc.get("mesh_axes", {})
    if dict(dmesh.axis_sizes) != saved_axes:
        raise ValueError(
            f"strategy was searched for mesh {saved_axes}, current mesh is "
            f"{dict(dmesh.axis_sizes)}")
    st = ShardingStrategy(dmesh)
    for k, v in doc.get("inputs", {}).items():
        sp = _spec_from_json(v)
        if sp is not None:
            st.inputs[k] = sp
    for name, os in doc.get("ops", {}).items():
        st.ops[name] = OpSharding(
            [_spec_from_json(s) for s in os.get("outputs", [])],
            {w: _spec_from_json(s) for w, s in os.get("weights", {}).items()
             if s is not None})
    return st
