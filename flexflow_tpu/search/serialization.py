"""Strategy import/export (reference ``--export``/``--import``,
``src/runtime/strategy.cc``): JSON with per-layer output/weight
PartitionSpecs and the mesh axis sizes. Also serializes the searched
*program* (the rewritten PCG as an executable layer list) so that an
exported Unity strategy — whose graph contains inserted parallel ops —
round-trips through ``--import`` (the analog of the reference's
``GraphOptimalViewSerialized``, ``graph.cc:2162``)."""
from __future__ import annotations

import dataclasses
import enum
import json
from typing import Any, Dict, List, Optional, Tuple

from jax.sharding import PartitionSpec as P

from .. import ffconst
from ..core.layer import Layer
from ..core.tensor import Tensor
from ..parallel.machine import DeviceMesh
from ..parallel.strategy import OpSharding, ShardingStrategy


def _spec_to_json(spec: Optional[P]):
    if spec is None:
        return None
    return [list(e) if isinstance(e, tuple) else e for e in spec]


def _spec_from_json(j) -> Optional[P]:
    if j is None:
        return None
    return P(*[tuple(e) if isinstance(e, list) else e for e in j])


def save_strategy(path: str, strategy: ShardingStrategy,
                  assignment: Optional[Dict] = None,
                  meta: Optional[Dict] = None,
                  program: Optional[Dict] = None,
                  serving: Optional[Dict] = None):
    doc = {
        "program": program,
        "mesh_axes": dict(strategy.dmesh.axis_sizes),
        "inputs": {k: _spec_to_json(v) for k, v in strategy.inputs.items()},
        "ops": {
            name: {
                "outputs": [_spec_to_json(s) for s in os.outputs],
                "weights": {w: _spec_to_json(s)
                            for w, s in os.weights.items()},
            } for name, os in strategy.ops.items()},
        "assignment": {k: list(v) for k, v in (assignment or {}).items()},
        "meta": meta or {},
    }
    if getattr(strategy, "axis_tiers", None):
        doc["axis_tiers"] = dict(strategy.axis_tiers)
    if getattr(strategy, "collective_trees", None):
        doc["collective_trees"] = list(strategy.collective_trees)
    if getattr(strategy, "zero", None) is not None:
        doc["zero"] = strategy.zero.to_json()
    if getattr(strategy, "qsync", None) is not None:
        # per-tensor/per-phase quantized grad-sync plan
        # (ops/quantized_collectives.py): --import honors it verbatim
        # and ffcheck --verify-strategies runs the qsync check on it
        doc["qsync"] = strategy.qsync.to_json()
    if getattr(strategy, "overlap", None):
        # the bucketed grad-sync schedule (runtime/overlap.py): round-
        # trips so --import pins the audited schedule verbatim and
        # ffcheck --verify-strategies runs the overlapped-ordering
        # check on the exported artifact
        doc["overlap"] = dict(strategy.overlap)
    if getattr(strategy, "kernel_impls", None):
        # per-op kernel implementations (kernels/registry.py): layer
        # names -> attention impl, plus the graph-wide "opt_update"
        # kind; --import honors it verbatim and the plan verifier
        # re-checks every predicate on the importing mesh
        doc["kernel_impls"] = dict(strategy.kernel_impls)
    banks_doc = banks_to_json(strategy)
    if banks_doc:
        doc["banks"] = banks_doc
    pgs = getattr(strategy, "place_groups", None) or []
    if pgs:
        doc["place_groups"] = [
            {"members": list(g.members), "axis": g.axis,
             "machine_views": {
                 m: dataclasses.asdict(v)
                 for m, v in g.machine_views(strategy.dmesh).items()}}
            for g in pgs]
    if serving is None:
        serving = getattr(strategy, "serving", None)
    if serving:
        # per-(model, batch-class) serving plans (search/serving_plan.py):
        # one sub-strategy per bucket + the KV-cache geometry; --import
        # and ModelRepository.load_* adopt them, ffcheck
        # --verify-strategies runs the serving-block checks
        doc["serving"] = dict(serving)
    with open(path, "w") as f:
        json.dump(doc, f, indent=1)


def banks_to_json(strategy: ShardingStrategy) -> List[Dict]:
    """Serialize strategy.banks (shared by save_strategy and the
    post-search export rewrite in search/optimizer.py). Each member's
    device subset is recorded as a reference-parity machine view
    (machine_view.h: start/num/stride in flat device order)."""
    banks = getattr(strategy, "banks", None)
    if not banks:
        return []
    return [
        {"members": list(b.members), "axes": list(b.axes),
         "batch_axes": list(b.batch_axes),
         "param_name": b.param_name,
         "padded": bool(getattr(b, "padded", False)),
         "machine_views": {
             m: dataclasses.asdict(v)
             for m, v in b.machine_views(strategy.dmesh).items()}}
        for b in banks]


# ---------------------------------------------------------------------------
# Program (rewritten-graph) serialization
# ---------------------------------------------------------------------------
def _param_to_json(v: Any) -> Any:
    if isinstance(v, enum.Enum):
        return {"_enum": type(v).__name__, "v": int(v)}
    if isinstance(v, (tuple, list)):
        return {"_seq": [_param_to_json(x) for x in v]}
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    return {"_repr": repr(v)}   # non-serializable (e.g. initializer objects)


def _param_from_json(v: Any) -> Any:
    if isinstance(v, dict):
        if "_enum" in v:
            return getattr(ffconst, v["_enum"])(v["v"])
        if "_seq" in v:
            return tuple(_param_from_json(x) for x in v["_seq"])
        if "_repr" in v:
            return None
    return v


def program_to_json(layers: List[Layer], graph_inputs: List[Tensor],
                    output_tensor: Tensor) -> Dict:
    """Serialize an executable layer list: each layer's op type, params,
    and input references (graph input name or (producer layer, out idx))."""
    producer: Dict[int, Tuple[str, int]] = {}
    input_names = {t.guid: t.name for t in graph_inputs}
    ser = []
    for layer in layers:
        ins = []
        for t in layer.inputs:
            if t.guid in producer:
                ins.append({"op": producer[t.guid][0],
                            "idx": producer[t.guid][1]})
            elif t.guid in input_names:
                ins.append({"input": input_names[t.guid]})
            else:
                ins.append({"input": t.name})
        ser.append({
            "name": layer.name,
            "op_type": layer.op_type.name,
            "params": {k: _param_to_json(v) for k, v in layer.params.items()},
            "inputs": ins,
            "trainable": layer.trainable,
        })
        for i, o in enumerate(layer.outputs):
            producer[o.guid] = (layer.name, i)
    out_ref = producer.get(output_tensor.guid)
    return {"layers": ser, "output": {"op": out_ref[0], "idx": out_ref[1]}
            if out_ref else None}


def program_from_json(doc: Dict, graph_inputs: List[Tensor]):
    """Rebuild (layers, output_tensor) from ``program_to_json`` output.
    Output shapes/dtypes are re-inferred through the op registry."""
    from ..ops import get_op_def
    by_input_name = {t.name: t for t in graph_inputs}
    by_layer: Dict[str, Layer] = {}
    layers: List[Layer] = []
    for ls in doc["layers"]:
        ins: List[Tensor] = []
        for ref in ls["inputs"]:
            if "input" in ref:
                t = by_input_name.get(ref["input"])
                if t is None:
                    raise ValueError(
                        f"program references unknown input {ref['input']}")
                ins.append(t)
            else:
                ins.append(by_layer[ref["op"]].outputs[ref["idx"]])
        params = {k: _param_from_json(v) for k, v in ls["params"].items()}
        op_type = ffconst.OperatorType[ls["op_type"]]
        layer = Layer(op_type, None, ins, params)
        layer.name = ls["name"]
        layer.trainable = ls.get("trainable", True)
        op = get_op_def(op_type)
        for (shape, dtype) in op.infer(params, [t.shape for t in ins],
                                       [t.dtype for t in ins]):
            layer.outputs.append(Tensor(shape, dtype, owner_layer=layer,
                                        owner_idx=len(layer.outputs)))
        by_layer[layer.name] = layer
        layers.append(layer)
    out_ref = doc.get("output")
    out_t = by_layer[out_ref["op"]].outputs[out_ref["idx"]] if out_ref \
        else layers[-1].outputs[0]
    return layers, out_t


# ---------------------------------------------------------------------------
# Legacy text strategy format (reference save/load_strategies_to_file,
# src/runtime/strategy.cc:100-196): line-oriented —
#   <num_ops>
#   then per op: <name> / <device_type> / <nDims> / dim[0..n) /
#   <num_device_ids> / device_ids[0..n)
# The reference's DeviceType enum: 0 = GPU (accelerator), 1 = CPU; we
# write 0 (the TPU plays the accelerator role).
# ---------------------------------------------------------------------------
def _spec_degrees(spec: Optional[P], rank: int, axis_sizes: Dict[str, int],
                  ) -> List[int]:
    """Per-tensor-dim shard degree for one PartitionSpec."""
    degs = [1] * rank
    if spec is None:
        return degs
    for j, e in enumerate(spec):
        if j >= rank or e is None:
            continue
        names = e if isinstance(e, tuple) else (e,)
        d = 1
        for nm in names:
            d *= axis_sizes.get(nm, 1)
        degs[j] = d
    return degs


def _spec_flat_ids(spec, rank: int, dmesh, n: int) -> List[int]:
    """Flat device ids a PartitionSpec's shards actually occupy: one
    representative device per shard (coordinate 0 on unmapped axes),
    enumerated shard-major in tensor-dim order — so ops sharded over
    non-leading mesh axes export their real placement instead of a
    normalized 0..n-1 prefix (ADVICE r4). Specs a single tensor dim of
    which spans multiple mesh axes fall back to the prefix form."""
    import numpy as np
    names = list(dmesh.axis_sizes.keys())
    sizes = [dmesh.axis_sizes[a] for a in names]
    used: List[str] = []
    if spec is not None:
        for j, e in enumerate(spec):
            if j >= rank or e is None:
                continue
            ax = e if isinstance(e, tuple) else (e,)
            if len(ax) != 1 or ax[0] in used or ax[0] not in names:
                return list(range(n))   # composed/unknown: prefix form
            used.append(ax[0])
    if not used:
        return list(range(n))
    grid = np.arange(int(np.prod(sizes))).reshape(sizes)
    index = tuple(slice(None) if a in used else 0 for a in names)
    sub = grid[index]
    # sub's axes are the used axes in MESH order; reorder to the order
    # they appear across the tensor dims (shard-major enumeration)
    mesh_order = [a for a in names if a in used]
    sub = np.transpose(sub, [mesh_order.index(a) for a in used])
    ids = [int(i) for i in sub.ravel()]
    return ids if len(ids) == n else list(range(n))


def save_legacy_strategies(path: str, strategy: ShardingStrategy,
                           layers: List[Layer]) -> None:
    """Export the searched strategy in the reference's text wire format
    so its tooling (and ``load_strategies_from_file``-based flows) can
    consume strategies searched here. Device ids are the flat ids each
    shard actually occupies (see :func:`_spec_flat_ids`); ops with a
    bank placement write their bank members instead."""
    axis_sizes = dict(strategy.dmesh.axis_sizes)
    bank_of = {}
    for b in getattr(strategy, "banks", None) or []:
        for m in b.members:
            bank_of[m] = b
    by_name = {l.name: l for l in layers}
    rows = []
    for name, os in strategy.ops.items():
        if any(c.isspace() for c in name):
            raise ValueError(
                f"op name {name!r} contains whitespace, which the "
                f"line-oriented legacy format cannot represent — "
                f"rename the layer or use the JSON export")
        layer = by_name.get(name)
        out_spec = os.outputs[0] if os.outputs else None
        rank = len(layer.outputs[0].shape) if layer is not None \
            and layer.outputs else (len(out_spec) if out_spec else 1)
        degs = _spec_degrees(out_spec, rank, axis_sizes)
        n = 1
        for d in degs:
            n *= d
        bank = bank_of.get(name)
        if bank is not None:
            # banked op: its devices are the bank member's subset; the
            # reference loader asserts prod(dims) == len(device_ids), so
            # fold the subset's dp replication into the batch dim — and
            # refuse to write a file the reference cannot load when the
            # subset size is not a multiple of the sharded degree
            view = bank.machine_views(strategy.dmesh)[name]
            ids = list(view.device_ids)
            if not degs or n == 0 or len(ids) % n != 0:
                raise ValueError(
                    f"op {name}: bank subset of {len(ids)} devices is "
                    f"incompatible with shard degrees {degs} "
                    f"(prod(dims) must equal the device count)")
            degs[0] *= len(ids) // n
            n = len(ids)
        else:
            ids = _spec_flat_ids(out_spec, rank, strategy.dmesh, n)
        rows.append((name, degs, ids))
    with open(path, "w") as f:
        f.write(f"{len(rows)}\n")
        for name, degs, ids in rows:
            f.write(f"{name}\n0\n{len(degs)}\n")
            f.write("\t".join(str(d) for d in degs) + "\n")
            f.write(f"{len(ids)}\n")
            f.write("\t".join(str(i) for i in ids) + "\n")
    # sidecar naming the bank rows: their id lists are true device
    # subsets, byte-indistinguishable from the representative-per-shard
    # pattern in the flat format; our importer refuses them with a
    # pointer to the JSON format, reference tooling ignores the sidecar
    if bank_of:
        with open(path + ".banks.json", "w") as f:
            json.dump({"banked_ops": sorted(
                n for n, _, _ in rows if n in bank_of)}, f)


def _axes_from_flat_ids(degs: List[int], ids: List[int],
                        dmesh) -> Optional[List]:
    """Invert :func:`_spec_flat_ids`: find the per-dim single-axis
    assignment whose representative-device enumeration equals ``ids``.
    Returns PartitionSpec entries, or None if no assignment matches
    (a true subset placement). Sharded dims and mesh axes are both few,
    so permutation search is fine."""
    import itertools
    names = list(dmesh.axis_sizes.keys())
    sharded = [j for j, d in enumerate(degs) if d > 1]
    cand_axes = [[a for a in names if dmesh.axis_sizes[a] == degs[j]]
                 for j in sharded]
    for combo in itertools.product(*cand_axes):
        if len(set(combo)) != len(combo):
            continue
        entries: List = [None] * len(degs)
        for j, ax in zip(sharded, combo):
            entries[j] = ax
        rank = len(degs)
        got = _spec_flat_ids(P(*entries), rank, dmesh, len(ids))
        if got == ids:
            return entries
    return None


def load_legacy_strategies(path: str, layers, dmesh: DeviceMesh,
                           ) -> ShardingStrategy:
    """Import the reference's text strategy format. Per-dim degrees are
    mapped back onto mesh axes greedily (axes in mesh order, largest
    dims first); degrees that don't factor over the mesh raise."""
    with open(path) as f:
        toks = f.read().split()
    pos = 0
    banked_names = set()
    sidecar = path + ".banks.json"
    sidecar_present = True
    try:
        with open(sidecar) as f:
            banked_names = set(json.load(f).get("banked_ops", ()))
    except OSError:
        sidecar_present = False
    # rows whose flat ids are a device-id prefix are ambiguous without
    # the sidecar: a bank's true device subset and an axis assignment's
    # representative-per-shard pattern can be byte-identical (see
    # save_legacy_strategies); collected below to warn once per import
    ambiguous_rows = []

    def take() -> str:
        nonlocal pos
        t = toks[pos]
        pos += 1
        return t

    n_ops = int(take())
    st = ShardingStrategy(dmesh)
    axis_items = list(dict(dmesh.axis_sizes).items())
    for _ in range(n_ops):
        name = take()
        int(take())                       # device_type (accelerator)
        ndims = int(take())
        degs = [int(take()) for _ in range(ndims)]
        n_ids = int(take())
        ids = [int(take()) for _ in range(n_ids)]
        if name in banked_names:
            # flagged by the exporter's sidecar: these ids are a true
            # device-subset (bank) placement, which per-dim degrees
            # cannot express — refuse rather than silently import a
            # different strategy (the JSON format round-trips banks).
            # The flat format alone cannot distinguish a subset from
            # the representative-per-shard pattern below, hence the
            # sidecar (reference tooling ignores it).
            raise ValueError(
                f"op {name}: device ids {ids[:8]}... describe a "
                f"device-subset placement; the legacy text import "
                f"cannot represent it — use the JSON strategy format")
        if ids:
            # representative-per-shard ids (what save_legacy_strategies
            # writes): reconstruct the exact axis assignment from the
            # id pattern — including prefix-shaped ids, which on a
            # multi-axis mesh may correspond to a LAST (stride-1) axis,
            # not the greedy first one
            if not sidecar_present and ids == list(range(len(ids))) \
                    and 1 < len(ids) < dmesh.num_devices:
                # prefix-shaped ids on a proper device subset: exactly
                # what an exported bank row looks like once the sidecar
                # that would flag it is gone — checked BEFORE the axis
                # reconstruction below, because a prefix can ALSO match
                # a (stride-1) axis assignment and import cleanly
                ambiguous_rows.append(name)
            entries = _axes_from_flat_ids(degs, ids, dmesh)
            if entries is not None:
                st.ops[name] = OpSharding([P(*entries)], {})
                continue
            if ids != list(range(len(ids))):
                raise ValueError(
                    f"op {name}: device ids {ids[:8]}... match no axis "
                    f"assignment of this mesh — use the JSON strategy "
                    f"format")
        free = dict(axis_items)           # axis -> size, unconsumed
        entries = []
        for d in degs:
            if d == 1:
                entries.append(None)
                continue
            # exact subset-product match over the unconsumed axes
            # (greedy-in-mesh-order fails on e.g. {x0:2, x1:8} with
            # d=8: consuming x0 first strands rem=4); axis counts are
            # tiny so brute force is fine
            import itertools
            got: Optional[Tuple[str, ...]] = None
            names = list(free)
            for r in range(1, len(names) + 1):
                for combo in itertools.combinations(names, r):
                    p = 1
                    for ax in combo:
                        p *= free[ax]
                    if p == d:
                        got = combo
                        break
                if got:
                    break
            if got is None:
                raise ValueError(
                    f"op {name}: degree {d} does not factor over mesh "
                    f"axes {dict(axis_items)}")
            for ax in got:
                del free[ax]
            entries.append(got[0] if len(got) == 1 else tuple(got))
        st.ops[name] = OpSharding([P(*entries)], {})
    if ambiguous_rows:
        import logging
        logging.getLogger("flexflow_tpu").warning(
            "strategy file %s: %d op row(s) (%s%s) have device-subset-"
            "shaped ids but no %s sidecar was found; if this file was "
            "exported from a bank-capable strategy those rows are BANK "
            "placements being imported as regular axis shardings — "
            "restore the sidecar or use the JSON strategy format",
            path, len(ambiguous_rows), ", ".join(ambiguous_rows[:4]),
            "..." if len(ambiguous_rows) > 4 else "", sidecar)
    return st


def load_strategy(path: str, layers, dmesh: DeviceMesh) -> ShardingStrategy:
    with open(path) as f:
        doc = json.load(f)
    saved_axes = doc.get("mesh_axes", {})
    if dict(dmesh.axis_sizes) != saved_axes:
        raise ValueError(
            f"strategy was searched for mesh {saved_axes}, current mesh is "
            f"{dict(dmesh.axis_sizes)}")
    st = ShardingStrategy(dmesh)
    for k, v in doc.get("inputs", {}).items():
        sp = _spec_from_json(v)
        if sp is not None:
            st.inputs[k] = sp
    for name, os in doc.get("ops", {}).items():
        st.ops[name] = OpSharding(
            [_spec_from_json(s) for s in os.get("outputs", [])],
            {w: _spec_from_json(s) for w, s in os.get("weights", {}).items()
             if s is not None})
    if doc.get("axis_tiers"):
        st.axis_tiers = {str(k): str(v)
                         for k, v in doc["axis_tiers"].items()}
    if doc.get("collective_trees"):
        st.collective_trees = list(doc["collective_trees"])
    if doc.get("zero"):
        from ..runtime.zero import ZeroAssignment
        st.zero = ZeroAssignment.from_json(doc["zero"])
    if doc.get("qsync"):
        from ..ops.quantized_collectives import QsyncPlan
        st.qsync = QsyncPlan.from_json(doc["qsync"])
    if doc.get("overlap"):
        st.overlap = dict(doc["overlap"])
    if doc.get("kernel_impls"):
        st.kernel_impls = {str(k): str(v)
                           for k, v in doc["kernel_impls"].items()}
    if doc.get("banks"):
        from ..parallel.banks import BankSpec
        st.banks = [BankSpec(list(b["members"]), tuple(b["axes"]),
                             batch_axes=tuple(b.get("batch_axes", ())),
                             param_name=b.get("param_name", "__bank__"),
                             padded=bool(b.get("padded", False)))
                    for b in doc["banks"]]
    if doc.get("place_groups"):
        from ..parallel.banks import PlaceGroup
        st.place_groups = [PlaceGroup(list(g["members"]), g["axis"])
                           for g in doc["place_groups"]]
    if doc.get("serving"):
        # per-bucket serving plans ride the strategy object so the
        # plan verifier's serving checks (KV soundness + envelope at
        # the largest bucket) bind at compile time
        st.serving = dict(doc["serving"])
    return st
