"""Pipeline-candidate scoring for the strategy search (bubble model).

The reference reserves OP_PIPELINE without a cost model; here the search
can score "partition the repeated-block region into S GPipe stages" the
same way it scores sharding strategies, so pipeline parallelism competes
on measured/analytic cost rather than being a user-only knob.

Cost model (standard GPipe bubble algebra):
  per-microbatch stage time  t = (fwd+bwd of one stage's ops at batch
                                  B/dp/M)
  schedule length            T_region = (M + S - 1) * (t + t_handoff)
  handoff                    activation bytes / ICI bw + latency
  outside-region layers      costed at the dp sharding
  weight sync                all-reduce over dp only (stage weights live
                             on their pipeline rank; no pp sync)
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence

import numpy as np

from ..dtypes import itemsize
from ..ffconst import OperatorType
from ..parallel.machine import DeviceMesh, MachineSpec
from ..parallel.pipeline_lowering import PipelineRegion, \
    find_pipeline_region
from .costmodel import OpCostModel


@dataclasses.dataclass
class PipelineCandidate:
    n_stages: int
    n_microbatches: int
    dp_size: int
    cost: float                  # estimated step time, seconds
    region: PipelineRegion
    n_chunks: int = 1            # interleaved (circular) chunks per stage
    tp: int = 1                  # Megatron tp inside each stage


def score_pipeline(layers, spec: MachineSpec, cost_model: OpCostModel,
                   n_stages: int, n_devices: int,
                   n_microbatches: int = 0,
                   n_chunks: int = 1,
                   region: Optional[PipelineRegion] = None,
                   tp: int = 1
                   ) -> Optional[PipelineCandidate]:
    """Estimated train-step time for an S-stage GPipe split of the
    graph's repeated-block region on ``n_devices`` (dp = n/(S*tp)). None
    when the graph has no S-divisible region. ``n_chunks = v > 1``
    scores the interleaved (circular) schedule: T = (M*v + S - 1) chunk
    steps, so the bubble fraction drops from (S-1)/M to (S-1)/(M*v).
    ``tp > 1`` scores Megatron tp inside each stage: role-layer compute
    divides by tp, plus one all-reduce of the microbatch activation per
    psum point (one per attention, one per FFN pair).

    ``region`` (discovery depends only on (S, v), not M) lets sweeps
    reuse one O(n^2) ``find_pipeline_region`` across microbatch counts.
    """
    if region is None:
        region = find_pipeline_region(layers, n_stages, n_microbatches,
                                      n_chunks)
    elif n_microbatches > 0:
        if n_chunks > 1 and n_microbatches % n_stages:
            return None
        region = dataclasses.replace(region,
                                     n_microbatches=n_microbatches)
    if region is None:
        return None
    roles = {}
    if tp > 1:
        if n_devices % (n_stages * tp):
            return None
        from ..parallel.pipeline_lowering import assign_tp_roles
        roles = assign_tp_roles(region.template, tp)
        if not roles:
            return None
    S, M, v = n_stages, region.n_microbatches, region.n_chunks
    dp = max(n_devices // (S * tp), 1)
    batch_deg = {0: dp * M}
    ragged = getattr(region, "counts", None) is not None
    t_block = 0.0                # one template block's per-microbatch time
    for l in region.template:
        cm = cost_model.op_cost(l, batch_deg)
        t = cm.forward_time + cm.backward_time
        if l.name in roles:
            t /= tp              # heads/columns split over the tp axis
        t_block += t
    if ragged:
        # every scan step executes max(counts) blocks (short stages
        # mask) + the heavier of prologue/epilogue on the edge stages
        t_stage = max(region.counts) * t_block

        def _edge_t(ls):
            total = 0.0
            for l in ls:
                c = cost_model.op_cost(l, batch_deg)
                total += c.forward_time + c.backward_time
            return total

        t_stage += max(_edge_t(region.prologue), _edge_t(region.epilogue))
    else:
        t_stage = t_block        # one CHUNK = the whole template
    # handoff: the boundary activation (one microbatch, dp-sharded)
    by_guid = {t.guid: t for l in layers for t in l.outputs}
    entry_t = by_guid.get(region.entry_guid)
    if entry_t is not None and entry_t.shape \
            and entry_t.shape[0] % max(dp * M, 1):
        return None  # microbatches don't divide the global batch
    act_bytes = (int(np.prod(entry_t.shape)) * itemsize(entry_t.dtype)
                 / max(dp * M, 1)) if entry_t is not None else 0.0
    if roles:
        # one psum of the microbatch activation per reduction point
        # (fwd) and one in the backward transpose
        n_psums = sum(1 for r in roles.values() if r in ("attn", "row"))
        t_stage += 2 * n_psums * cost_model.xfer_cost(
            act_bytes, "all_reduce", tp)
    t_handoff = act_bytes / spec.ici_bandwidth + spec.ici_latency_us * 1e-6
    t_region = (M * v + S - 1) * (t_stage + t_handoff)
    # outside layers at plain dp (absorbed prologue/epilogue layers are
    # inside the region under the ragged schedule)
    region_idx = set(range(region.start, region.end))
    absorbed = set()
    if ragged:
        absorbed = {l.name for l in region.prologue} \
            | {l.name for l in region.epilogue}
    t_out, w_bytes_out = 0.0, 0.0
    for i, l in enumerate(layers):
        if i in region_idx or l.op_type == OperatorType.OP_INPUT \
                or l.name in absorbed:
            continue
        cm = cost_model.op_cost(l, {0: dp * S})
        t_out += cm.forward_time + cm.backward_time
        w_bytes_out += cm.weights_memory
    if ragged:
        # replicated prologue/epilogue weights sync over the whole mesh
        from ..ops import get_op_def as _g
        for l in list(region.prologue) + list(region.epilogue):
            specs = l.weights or _g(l.op_type).weights(
                l.params, [t.shape for t in l.inputs],
                [t.dtype for t in l.inputs])
            w_bytes_out += sum(int(np.prod(ws.shape)) * itemsize(ws.dtype)
                               for ws in specs)
    # gradient sync over dp. Stage weights all-reduce over their own dp
    # group (disjoint groups run concurrently), so the region contributes
    # ONE stage's weight bytes, not S stages' (tp-split layers hold 1/tp
    # of their weights per device).
    from ..ops import get_op_def
    w_bytes_stage = 0.0
    for l in region.template:
        specs = l.weights or get_op_def(l.op_type).weights(
            l.params, [t.shape for t in l.inputs],
            [t.dtype for t in l.inputs])
        wb = sum(int(np.prod(ws.shape)) * itemsize(ws.dtype)
                 for ws in specs)
        if l.name in roles:
            wb /= tp
        w_bytes_stage += wb
    # a stage holds v chunks' weights (uniform) or up to max(counts)
    # blocks' weights (ragged)
    w_bytes_stage *= max(region.counts) if ragged else v
    t_sync = cost_model.weight_sync_cost(w_bytes_stage + w_bytes_out, dp)
    return PipelineCandidate(S, M, dp, t_region + t_out + t_sync, region,
                             n_chunks=v, tp=tp)


def best_pipeline(layers, dmesh: DeviceMesh,
                  cost_model: OpCostModel,
                  microbatches: int = 0) -> Optional[PipelineCandidate]:
    """Best S over the stage counts realizable on this machine (S must
    divide the device count; the mesh is rebuilt (n/S, S) when chosen)."""
    n = dmesh.num_devices
    best: Optional[PipelineCandidate] = None
    for S in range(2, n + 1):
        if n % S:
            continue
        # sweep microbatch count (bubble (M+S-1)/M shrinks with M;
        # per-microbatch efficiency and handoff latency grow) and the
        # interleaved chunk count (bubble /v; weights stream per chunk).
        # Region discovery depends only on (S, v) — do it once per pair.
        ms = (microbatches,) if microbatches else (0, S, 4 * S, 8 * S)
        for v in (1, 2, 3, 4):
            region = find_pipeline_region(layers, S, 0, v)
            if region is None and v == 1:
                # ragged fallback: unequal stage depths + absorbed
                # embedding/head (no interleave/tp composition in v1);
                # sweep M like the uniform candidates
                from ..parallel.pipeline_lowering import \
                    find_ragged_pipeline_region
                region = find_ragged_pipeline_region(layers, S, 0)
                if region is not None:
                    for M in ms:
                        cand = score_pipeline(layers, dmesh.spec,
                                              cost_model, S, n, M, 1,
                                              region=region, tp=1)
                        if cand is not None and (best is None
                                                 or cand.cost < best.cost):
                            best = cand
                continue
            if region is None:
                continue
            for tp in (1, 2, 4, 8):
                if (n // S) % tp:
                    continue
                for M in ms:
                    cand = score_pipeline(layers, dmesh.spec, cost_model,
                                          S, n, M, v, region=region,
                                          tp=tp)
                    if cand is not None and (best is None
                                             or cand.cost < best.cost):
                        best = cand
    return best
