"""Pipeline-candidate scoring for the strategy search (bubble model).

The reference reserves OP_PIPELINE without a cost model; here the search
can score "partition the repeated-block region into S GPipe stages" the
same way it scores sharding strategies, so pipeline parallelism competes
on measured/analytic cost rather than being a user-only knob.

Cost model (standard GPipe bubble algebra):
  per-microbatch stage time  t = (fwd+bwd of one stage's ops at batch
                                  B/dp/M)
  schedule length            T_region = (M + S - 1) * (t + t_handoff)
  handoff                    activation bytes / ICI bw + latency
  outside-region layers      costed at the dp sharding
  weight sync                all-reduce over dp only (stage weights live
                             on their pipeline rank; no pp sync)
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence

import numpy as np

from ..dtypes import itemsize
from ..ffconst import OperatorType
from ..parallel.machine import DeviceMesh, MachineSpec
from ..parallel.pipeline_lowering import PipelineRegion, \
    find_pipeline_region
from .costmodel import OpCostModel


@dataclasses.dataclass
class PipelineCandidate:
    n_stages: int
    n_microbatches: int
    dp_size: int
    cost: float                  # estimated step time, seconds
    region: PipelineRegion


def score_pipeline(layers, spec: MachineSpec, cost_model: OpCostModel,
                   n_stages: int, n_devices: int,
                   n_microbatches: int = 0) -> Optional[PipelineCandidate]:
    """Estimated train-step time for an S-stage GPipe split of the
    graph's repeated-block region on ``n_devices`` (dp = n/S). None when
    the graph has no S-divisible region."""
    region = find_pipeline_region(layers, n_stages, n_microbatches)
    if region is None:
        return None
    S, M = n_stages, region.n_microbatches
    dp = max(n_devices // S, 1)
    batch_deg = {0: dp * M}
    t_stage = 0.0
    for l in region.template:
        cm = cost_model.op_cost(l, batch_deg)
        t_stage += cm.forward_time + cm.backward_time
    # handoff: the boundary activation (one microbatch, dp-sharded)
    by_guid = {t.guid: t for l in layers for t in l.outputs}
    entry_t = by_guid.get(region.entry_guid)
    act_bytes = (int(np.prod(entry_t.shape)) * itemsize(entry_t.dtype)
                 / max(dp * M, 1)) if entry_t is not None else 0.0
    t_handoff = act_bytes / spec.ici_bandwidth + spec.ici_latency_us * 1e-6
    t_region = (M + S - 1) * (t_stage + t_handoff)
    # outside layers at plain dp
    region_idx = set(range(region.start, region.end))
    t_out, w_bytes_out = 0.0, 0.0
    for i, l in enumerate(layers):
        if i in region_idx or l.op_type == OperatorType.OP_INPUT:
            continue
        cm = cost_model.op_cost(l, {0: dp * S})
        t_out += cm.forward_time + cm.backward_time
        w_bytes_out += cm.weights_memory
    # gradient sync over dp. Stage weights all-reduce over their own dp
    # group (disjoint groups run concurrently), so the region contributes
    # ONE stage's weight bytes, not S stages'.
    from ..ops import get_op_def
    w_bytes_stage = 0.0
    for l in region.template:
        specs = l.weights or get_op_def(l.op_type).weights(
            l.params, [t.shape for t in l.inputs],
            [t.dtype for t in l.inputs])
        w_bytes_stage += sum(int(np.prod(ws.shape)) * itemsize(ws.dtype)
                             for ws in specs)
    t_sync = cost_model.weight_sync_cost(w_bytes_stage + w_bytes_out, dp)
    return PipelineCandidate(S, M, dp, t_region + t_out + t_sync, region)


def best_pipeline(layers, dmesh: DeviceMesh,
                  cost_model: OpCostModel,
                  microbatches: int = 0) -> Optional[PipelineCandidate]:
    """Best S over the stage counts realizable on this machine (S must
    divide the device count; the mesh is rebuilt (n/S, S) when chosen)."""
    n = dmesh.num_devices
    best: Optional[PipelineCandidate] = None
    for S in range(2, n + 1):
        if n % S:
            continue
        cand = score_pipeline(layers, dmesh.spec, cost_model, S, n,
                              microbatches)
        if cand is not None and (best is None or cand.cost < best.cost):
            best = cand
    return best
