"""Lazy symbolic tensors — the frontend-facing compute graph level.

Analog of the reference's ``Tensor``/``TensorBase`` (``include/flexflow/tensor.h``):
a symbolic handle with shape/dtype, a producing layer, and (for parameters)
an initializer. No device data lives here; materialization happens when the
model is compiled into a jitted step.
"""
from __future__ import annotations

import itertools
from typing import Optional, Sequence, Tuple, TYPE_CHECKING

import numpy as np

from ..ffconst import DataType, InitializerType
from ..dtypes import to_jnp

if TYPE_CHECKING:
    from .layer import Layer

_uid = itertools.count()


class Tensor:
    """Symbolic tensor in the (serial) computation graph."""

    __slots__ = ("shape", "dtype", "owner_layer", "owner_idx", "name",
                 "initializer", "create_grad", "guid", "_np_value")

    def __init__(self, shape: Sequence[int], dtype: DataType = DataType.DT_FLOAT,
                 owner_layer: Optional["Layer"] = None, owner_idx: int = 0,
                 name: Optional[str] = None, initializer=None,
                 create_grad: bool = True):
        self.shape: Tuple[int, ...] = tuple(int(s) for s in shape)
        self.dtype = DataType(dtype)
        self.owner_layer = owner_layer
        self.owner_idx = owner_idx
        self.guid = next(_uid)
        self.name = name or f"tensor_{self.guid}"
        self.initializer = initializer
        self.create_grad = create_grad
        self._np_value: Optional[np.ndarray] = None  # for attached constants

    # reference API parity -------------------------------------------------
    @property
    def num_dims(self) -> int:
        return len(self.shape)

    @property
    def dims(self) -> Tuple[int, ...]:
        return self.shape

    def get_volume(self) -> int:
        v = 1
        for s in self.shape:
            v *= s
        return v

    def get_shape(self) -> Tuple[int, ...]:
        return self.shape

    @property
    def jnp_dtype(self):
        return to_jnp(self.dtype)

    def set_tensor(self, value: np.ndarray):
        """Attach a host value (reference: NumPy region attach)."""
        value = np.asarray(value)
        if value.shape != self.shape:
            raise ValueError(f"value shape {value.shape} does not "
                             f"match tensor shape {self.shape}")
        self._np_value = value

    def get_tensor(self):
        return self._np_value

    def __repr__(self):
        src = self.owner_layer.name if self.owner_layer else "input"
        return f"Tensor({self.name}, shape={self.shape}, dtype={self.dtype.name}, from={src})"


class WeightSpec:
    """Declarative parameter: shape/dtype/initializer, resolved at compile.

    Analog of the reference's weight ``Tensor`` created by each layer
    (e.g. Linear kernel/bias) with an attached ``Initializer``.
    """

    __slots__ = ("name", "shape", "dtype", "initializer", "init_args", "create_grad")

    def __init__(self, name: str, shape: Sequence[int],
                 dtype: DataType = DataType.DT_FLOAT,
                 initializer: InitializerType = InitializerType.GLOROT_UNIFORM,
                 init_args: Optional[dict] = None, create_grad: bool = True):
        self.name = name
        self.shape = tuple(int(s) for s in shape)
        self.dtype = DataType(dtype)
        self.initializer = initializer
        self.init_args = init_args or {}
        self.create_grad = create_grad

    def __repr__(self):
        return f"WeightSpec({self.name}, {self.shape}, {self.initializer.value})"
