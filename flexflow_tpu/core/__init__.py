from .tensor import Tensor, WeightSpec  # noqa: F401
from .layer import Layer  # noqa: F401
