"""Layer: a node in the lazy computation graph.

Analog of the reference's ``Layer`` (``include/flexflow/layer.h:20-61``): an
op-typed node holding key/value properties, input tensors, produced output
tensors, and weight specs. Lowering to the PCG (``Op`` level) happens in
``FFModel.compile`` — mirroring ``create_operators_from_layers``
(reference ``src/runtime/model.cc:2785``).
"""
from __future__ import annotations

import itertools
from typing import Any, Dict, List, Optional, Tuple

from ..ffconst import OperatorType
from .tensor import Tensor, WeightSpec

_layer_uid = itertools.count(100)  # LAYER_GUID_FIRST_VALID-style offset


def _hashable(v: Any) -> Any:
    if isinstance(v, list):
        return tuple(_hashable(x) for x in v)
    if isinstance(v, dict):
        return tuple(sorted((k, _hashable(x)) for k, x in v.items()))
    return v


class Layer:
    __slots__ = ("op_type", "name", "params", "inputs", "outputs", "weights",
                 "guid", "trainable")

    def __init__(self, op_type: OperatorType, name: Optional[str],
                 inputs: List[Tensor], params: Optional[Dict[str, Any]] = None):
        self.op_type = OperatorType(op_type)
        self.guid = next(_layer_uid)
        self.name = name or f"{self.op_type.name.lower()}_{self.guid}"
        self.params: Dict[str, Any] = dict(params or {})
        self.inputs: List[Tensor] = list(inputs)
        self.outputs: List[Tensor] = []
        self.weights: List[WeightSpec] = []
        self.trainable = True

    # key/value property API (reference Layer::add_int_property etc.)
    def add_property(self, key: str, value: Any):
        self.params[key] = value

    def get_property(self, key: str, default=None):
        return self.params.get(key, default)

    def add_weight(self, spec: WeightSpec):
        self.weights.append(spec)

    def param_key(self) -> Tuple:
        """Hashable identity used for node dedup / cost caching — analog of
        the reference's ``*Params`` structs (``src/ops/*_params.h``)."""
        return (self.op_type, _hashable(self.params),
                tuple(t.shape for t in self.inputs),
                tuple(t.dtype for t in self.inputs))

    def __repr__(self):
        return (f"Layer({self.name}, {self.op_type.name}, "
                f"in={[t.shape for t in self.inputs]}, "
                f"out={[t.shape for t in self.outputs]})")
