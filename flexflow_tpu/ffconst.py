"""Framework-wide enums and constants.

Parity with the reference's ``include/flexflow/ffconst.h`` (OperatorType,
ActiMode, DataType, LossType, MetricsType, ...). Values are kept numerically
compatible where the reference assigns explicit values, so serialized
artifacts / frontend glue can interoperate.
"""
from __future__ import annotations

import enum


class ActiMode(enum.IntEnum):
    AC_MODE_NONE = 10
    AC_MODE_RELU = 11
    AC_MODE_SIGMOID = 12
    AC_MODE_TANH = 13
    AC_MODE_GELU = 14

    @classmethod
    def _missing_(cls, value):
        if isinstance(value, str):
            try:
                return cls[f"AC_MODE_{value.upper()}"]
            except KeyError:
                pass
        return None


class RegularizerMode(enum.IntEnum):
    REG_MODE_NONE = 17
    REG_MODE_L1 = 18
    REG_MODE_L2 = 19


class AggrMode(enum.IntEnum):
    AGGR_MODE_NONE = 20
    AGGR_MODE_SUM = 21
    AGGR_MODE_AVG = 22


class PoolType(enum.IntEnum):
    POOL_MAX = 30
    POOL_AVG = 31


class DataType(enum.IntEnum):
    DT_BOOLEAN = 40
    DT_INT32 = 41
    DT_INT64 = 42
    DT_HALF = 43      # on TPU this maps to bfloat16 by default (see dtypes.py)
    DT_BFLOAT16 = 46  # TPU-native addition (not in reference)
    DT_FLOAT = 44
    DT_DOUBLE = 45
    # narrow wire dtypes (not in reference): quantized gradient
    # collectives (ops/quantized_collectives.py) move int8 / fp8
    # payloads over the slow fabric legs; values chosen past the
    # reference's enum range so serialized reference strategies never
    # collide
    DT_INT8 = 50
    DT_FLOAT8_E4M3 = 51
    DT_FLOAT8_E5M2 = 52
    DT_NONE = 49

    @classmethod
    def _missing_(cls, value):
        if isinstance(value, str):
            aliases = {"bool": "BOOLEAN", "int32": "INT32", "int64": "INT64",
                       "half": "HALF", "float16": "HALF",
                       "bfloat16": "BFLOAT16", "float": "FLOAT",
                       "float32": "FLOAT", "double": "DOUBLE",
                       "float64": "DOUBLE", "int8": "INT8",
                       "float8_e4m3": "FLOAT8_E4M3", "e4m3": "FLOAT8_E4M3",
                       "float8_e4m3fn": "FLOAT8_E4M3",
                       "float8_e5m2": "FLOAT8_E5M2", "e5m2": "FLOAT8_E5M2"}
            key = aliases.get(value.lower(), value.upper())
            try:
                return cls[f"DT_{key}" if not key.startswith("DT_") else key]
            except KeyError:
                return None
        return None


class LossType(enum.IntEnum):
    LOSS_CATEGORICAL_CROSSENTROPY = 50
    LOSS_SPARSE_CATEGORICAL_CROSSENTROPY = 51
    LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE = 52
    LOSS_MEAN_SQUARED_ERROR_SUM_REDUCE = 53
    LOSS_IDENTITY = 54


class CompMode(enum.IntEnum):
    COMP_MODE_TRAINING = 70
    COMP_MODE_INFERENCE = 71


class ParameterSyncType(enum.IntEnum):
    """Gradient sync mode.

    The reference distinguishes parameter-server vs NCCL allreduce
    (``ffconst.h:80-82``). On TPU both lower to XLA collectives inside the
    compiled step; PS is kept for API parity and maps to the same path.
    """
    NONE = 80
    PS = 81
    NCCL = 82  # = XLA all-reduce / reduce-scatter over mesh axes


class MetricsType(enum.IntFlag):
    METRICS_ACCURACY = 1001
    METRICS_CATEGORICAL_CROSSENTROPY = 1002
    METRICS_SPARSE_CATEGORICAL_CROSSENTROPY = 1004
    METRICS_MEAN_SQUARED_ERROR = 1008
    METRICS_ROOT_MEAN_SQUARED_ERROR = 1016
    METRICS_MEAN_ABSOLUTE_ERROR = 1032


class OperatorType(enum.IntEnum):
    """Full operator set (reference ``ffconst.h:69-161``)."""
    OP_INPUT = 0
    OP_WEIGHT = enum.auto()
    OP_NOOP = enum.auto()
    OP_CONV2D = enum.auto()
    OP_DROPOUT = enum.auto()
    OP_LINEAR = enum.auto()
    OP_BATCHMATMUL = enum.auto()
    OP_POOL2D = enum.auto()
    OP_SCALAR_MULTIPLY = enum.auto()
    OP_SCALAR_ADD = enum.auto()
    OP_SCALAR_FLOOR_DIV = enum.auto()
    OP_SCALAR_TRUE_DIV = enum.auto()
    OP_SCALAR_SUB = enum.auto()
    OP_RELU = enum.auto()
    OP_IDENTITY = enum.auto()
    OP_SIGMOID = enum.auto()
    OP_TANH = enum.auto()
    OP_ELU = enum.auto()
    OP_FLAT = enum.auto()
    OP_SOFTMAX = enum.auto()
    OP_BATCHNORM = enum.auto()
    OP_CONCAT = enum.auto()
    OP_SPLIT = enum.auto()
    OP_EMBEDDING = enum.auto()
    OP_GROUP_BY = enum.auto()
    OP_CACHE = enum.auto()
    OP_AGGREGATE = enum.auto()
    OP_AGG_SPEC = enum.auto()
    OP_RESHAPE = enum.auto()
    OP_REVERSE = enum.auto()
    OP_TRANSPOSE = enum.auto()
    OP_EW_ADD = enum.auto()
    OP_EW_MUL = enum.auto()
    OP_MATMUL = enum.auto()
    OP_MUL = enum.auto()
    OP_ENLARGE = enum.auto()
    OP_MERGE_GCONV = enum.auto()
    OP_CONSTANT_IMM = enum.auto()
    OP_CONSTANT_ICONV = enum.auto()
    OP_CONSTANT_ONE = enum.auto()
    OP_CONSTANT_POOL = enum.auto()
    OP_SQUEEZE = enum.auto()
    OP_UNSQUEEZE = enum.auto()
    OP_EW_SUB = enum.auto()
    OP_EW_DIV = enum.auto()
    OP_EW_EQUAL = enum.auto()
    OP_EW_GREATER = enum.auto()
    OP_EW_LESS = enum.auto()
    OP_EW_MAX = enum.auto()
    OP_EW_MIN = enum.auto()
    OP_REDUCE_ARGMAX = enum.auto()
    OP_REDUCE_ARGMIN = enum.auto()
    OP_REDUCE_MAX = enum.auto()
    OP_REDUCE_MEAN = enum.auto()
    OP_REDUCE_MIN = enum.auto()
    OP_REDUCE_PROD = enum.auto()
    OP_REDUCE_SUM = enum.auto()
    OP_PAD = enum.auto()
    OP_SHAPE = enum.auto()
    OP_SIZE = enum.auto()
    OP_TOPK = enum.auto()
    OP_WHERE = enum.auto()
    OP_CEIL = enum.auto()
    OP_CAST = enum.auto()
    OP_EXP = enum.auto()
    OP_ROUND = enum.auto()
    OP_LOG = enum.auto()
    OP_LOGICAL_NOT = enum.auto()
    OP_SQRT = enum.auto()
    OP_SIN = enum.auto()
    OP_COS = enum.auto()
    OP_LEAKYRELU = enum.auto()
    OP_SLICE = enum.auto()
    OP_RESIZE = enum.auto()
    OP_PRELU = enum.auto()
    OP_GELU = enum.auto()
    OP_MULTIHEAD_ATTENTION = enum.auto()
    OP_FUSED = enum.auto()
    OP_RSQRT = enum.auto()
    OP_POW = enum.auto()
    OP_MEAN = enum.auto()
    OP_LAYERNORM = enum.auto()
    OP_GATHER = enum.auto()
    # Parallel ops: communication reified as graph nodes (reference
    # src/parallel_ops/). On TPU these are sharding transitions that lower
    # to XLA collectives.
    OP_REPARTITION = enum.auto()
    OP_COMBINE = enum.auto()
    OP_REPLICATE = enum.auto()
    OP_REDUCTION = enum.auto()
    OP_PIPELINE = enum.auto()
    OP_FUSED_PARALLEL = enum.auto()
    # TPU-native additions beyond the reference
    OP_RMSNORM = enum.auto()
    OP_RING_ATTENTION = enum.auto()
    OP_ALLTOALL = enum.auto()
    # LSTM: the reference ships it only as the hand-rolled legacy NMT app
    # (nmt/lstm.cu) outside the op registry; here it is a first-class op
    OP_LSTM = enum.auto()
    OP_INVALID = enum.auto()


# Ops that are pure elementwise-unary (single input, same shape out).
ELEMENTWISE_UNARY_OPS = frozenset({
    OperatorType.OP_RELU, OperatorType.OP_SIGMOID, OperatorType.OP_TANH,
    OperatorType.OP_ELU, OperatorType.OP_GELU, OperatorType.OP_LEAKYRELU,
    OperatorType.OP_PRELU, OperatorType.OP_IDENTITY, OperatorType.OP_EXP,
    OperatorType.OP_LOG, OperatorType.OP_SQRT, OperatorType.OP_RSQRT,
    OperatorType.OP_SIN, OperatorType.OP_COS, OperatorType.OP_CEIL,
    OperatorType.OP_ROUND, OperatorType.OP_LOGICAL_NOT, OperatorType.OP_POW,
    OperatorType.OP_SCALAR_MULTIPLY, OperatorType.OP_SCALAR_ADD,
    OperatorType.OP_SCALAR_SUB, OperatorType.OP_SCALAR_TRUE_DIV,
    OperatorType.OP_SCALAR_FLOOR_DIV, OperatorType.OP_CAST,
})

# Ops that are elementwise-binary with numpy broadcasting semantics.
ELEMENTWISE_BINARY_OPS = frozenset({
    OperatorType.OP_EW_ADD, OperatorType.OP_EW_SUB, OperatorType.OP_EW_MUL,
    OperatorType.OP_EW_DIV, OperatorType.OP_EW_MAX, OperatorType.OP_EW_MIN,
    OperatorType.OP_EW_EQUAL, OperatorType.OP_EW_GREATER,
    OperatorType.OP_EW_LESS,
})

REDUCE_OPS = frozenset({
    OperatorType.OP_REDUCE_SUM, OperatorType.OP_REDUCE_MEAN,
    OperatorType.OP_REDUCE_MAX, OperatorType.OP_REDUCE_MIN,
    OperatorType.OP_REDUCE_PROD, OperatorType.OP_REDUCE_ARGMAX,
    OperatorType.OP_REDUCE_ARGMIN, OperatorType.OP_MEAN,
})

PARALLEL_OPS = frozenset({
    OperatorType.OP_REPARTITION, OperatorType.OP_COMBINE,
    OperatorType.OP_REPLICATE, OperatorType.OP_REDUCTION,
    OperatorType.OP_PIPELINE, OperatorType.OP_FUSED_PARALLEL,
    OperatorType.OP_ALLTOALL,
})


class InitializerType(enum.Enum):
    GLOROT_UNIFORM = "glorot_uniform"
    ZERO = "zero"
    ONE = "one"
    CONSTANT = "constant"
    UNIFORM = "uniform"
    NORMAL = "normal"


def op_type_name(t: OperatorType) -> str:
    return t.name


# Maximum tensor rank, reference CMake option FF_MAX_DIM=5
MAX_TENSOR_DIM = 5
