"""Asyncio HTTP front-end for the model repository.

Same KServe-style surface as ``http_server.py`` (the two share the
route functions), but connections are multiplexed on one event loop
instead of a thread per connection: the round-4 load test showed
client-observed p99 at ~4x the server-recorded latency purely from the
``ThreadingHTTPServer`` front under concurrency. Request BODIES are
parsed and executed in a bounded thread pool (the batching scheduler's
``infer`` blocks on its result event), so the loop never stalls on a
device step; keep-alive is supported so load generators reuse
connections. Header reads are bounded (count and total bytes) so a
client streaming endless header lines cannot grow memory without
bound.

Reference analog: Triton's event-driven HTTP/REST frontend
(``/root/reference/triton/README.md``) — stdlib-only here.

Usage::

    from flexflow_tpu.serving import serve_async
    serve_async(repo, port=8000)                     # blocks
    srv = serve_async(repo, port=8000, block=False)  # returns handle
    ...
    srv.drain()   # graceful: finish in-flight, reject new work, close
    srv.stop()    # immediate
"""
from __future__ import annotations

import asyncio
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Optional

from ..obs import events as obs_events
from ..obs import request_trace
from .http_server import (ServingState, drain_frontend, get_route,
                          post_route, render_body)

_MAX_BODY = 256 << 20   # sanity bound, matches big dense batches
_MAX_HEADERS = 256      # header-line count bound per request
_MAX_HEADER_BYTES = 64 << 10   # total header bytes bound per request


class AsyncServerHandle:
    """Running server + its loop thread; ``drain()`` shuts down
    gracefully (finish in-flight, shed new work), ``stop()``
    immediately."""

    def __init__(self, loop, server, thread, schedulers, pool, state):
        self._loop = loop
        self._server = server
        self._thread = thread
        self.schedulers = schedulers
        self._pool = pool
        self.state = state

    @property
    def port(self) -> int:
        return self._server.sockets[0].getsockname()[1]

    def drain(self, deadline_s: float = 10.0) -> bool:
        """Graceful drain: flip ``/v2/health/ready`` to 503, reject new
        inference work with 503 + ``Retry-After``, finish in-flight
        requests (responses written included) within ``deadline_s``,
        then stop. Returns True when nothing was abandoned."""
        clean = drain_frontend(self.schedulers, self.state, deadline_s)
        self.stop()
        return clean

    def stop(self):
        def _close():
            self._server.close()

        try:
            self._loop.call_soon_threadsafe(_close)
            self._loop.call_soon_threadsafe(self._loop.stop)
        except RuntimeError:
            pass    # loop already stopped and closed (double stop)
        self._thread.join(timeout=10)
        # snapshot: a concurrent unload request pops from the live dict
        for s in list(self.schedulers.values()):
            s.close()
        self._pool.shutdown(wait=False)
        # the loop thread itself closes the loop (releasing the
        # selector/self-pipe fds) right after run_forever returns — see
        # serve_async's runner — so a thread that misses the join
        # timeout above still cannot leak the fds once it does stop


async def _read_request(reader):
    """Parse one HTTP/1.1 request; returns (method, path, headers,
    body) or None on EOF. An unparseable request line — or a header
    section exceeding the count/byte bounds — yields the "bad" marker:
    the client gets a 400 response and the connection closes instead of
    the server buffering unbounded header bytes (same contract as the
    bad-Content-Length path)."""
    try:
        line = await reader.readline()
    except (ConnectionError, asyncio.IncompleteReadError):
        return None
    except ValueError:
        # ONE line at/over the stream limit (64 KiB): readline raises
        # before any bound of ours can trip — same contract as a
        # garbage request line: answer 400 and close
        return "bad", "", {}, b""
    if not line:
        return None
    try:
        method, path, _ = line.decode("latin1").split(" ", 2)
    except ValueError:
        # garbage request line: nothing after it is framable, so the
        # response must close the socket — but it IS a response
        return "bad", "", {}, b""
    headers = {}
    header_bytes = 0
    while True:
        try:
            h = await reader.readline()
        except ValueError:
            # one header LINE at/over the stream limit — the byte
            # bound below only catches many small lines
            return "bad", path, {}, b""
        if h in (b"\r\n", b"\n", b""):
            break
        header_bytes += len(h)
        if len(headers) >= _MAX_HEADERS or header_bytes > _MAX_HEADER_BYTES:
            # unread header tail on the socket: framing unrecoverable,
            # answer 400 and close rather than buffer without bound
            return "bad", path, {}, b""
        k, _, v = h.decode("latin1").partition(":")
        headers[k.strip().lower()] = v.strip()
    try:
        n = int(headers.get("content-length", 0))
    except ValueError:
        return "bad", path, headers, b""     # -> 400, not a dead socket
    if n < 0 or n > _MAX_BODY:
        return "bad", path, headers, b""
    body = await reader.readexactly(n) if n else b""
    return method, path, headers, body


def _response(code: int, obj, keep_alive: bool, extra=None) -> bytes:
    body, ctype = render_body(obj)
    reason = {200: "OK", 400: "Bad Request", 404: "Not Found",
              503: "Service Unavailable",
              504: "Gateway Timeout"}.get(code, "OK")
    conn = "keep-alive" if keep_alive else "close"
    head = (f"HTTP/1.1 {code} {reason}\r\n"
            f"Content-Type: {ctype}\r\n"
            f"Content-Length: {len(body)}\r\n")
    for k, v in (extra or {}).items():
        head += f"{k}: {v}\r\n"
    head += f"Connection: {conn}\r\n\r\n"
    return head.encode("latin1") + body


def _make_client_handler(repo, schedulers, pool, state):
    async def handle(reader, writer):
        loop = asyncio.get_running_loop()
        try:
            while True:
                req = await _read_request(reader)
                if req is None:
                    break
                method, path, headers, body = req
                keep = headers.get("connection", "keep-alive").lower() \
                    != "close"
                extra = {}
                # only POSTs are counted in flight (response write
                # included): drain() must not exit while an inference
                # response is unwritten, but counting health probes /
                # metrics scrapes would let monitoring traffic flake a
                # clean drain
                counted = method == "POST"
                if counted:
                    state.enter_request()
                try:
                    if method == "bad":
                        # the body was never read (unparseable request
                        # line, oversized header section, or
                        # unparseable/oversized Content-Length), so
                        # keep-alive framing on this socket is
                        # unrecoverable: respond and close
                        code, obj = 400, {"error": "malformed request"}
                        keep = False
                    elif method == "GET":
                        code, obj, extra = get_route(path, repo,
                                                     schedulers, state)
                    elif method == "POST":
                        # parse + (blocking) scheduler wait off-loop;
                        # the span is the LOOP-side view of the request
                        # (dispatch -> executor result), linked into
                        # the request's trace via the echoed id
                        with obs_events.span("serving.post",
                                             path=path) as sp:
                            code, obj, extra = \
                                await loop.run_in_executor(
                                    pool, post_route, path, body, repo,
                                    schedulers, headers, state)
                            tid = (extra or {}).get(
                                request_trace.TRACE_HEADER)
                            if tid:
                                sp.set(trace=tid)
                            sp.set(status=code)
                    else:
                        # unknown method/route: a framed 404 on a live
                        # connection (the body was consumed above),
                        # never a silent drop
                        code, obj = 404, {"error": f"method {method}"}
                    writer.write(_response(code, obj, keep, extra))
                    await writer.drain()
                finally:
                    if counted:
                        state.exit_request()
                if not keep:
                    break
        except (ConnectionError, asyncio.IncompleteReadError):
            pass
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except Exception:  # noqa: BLE001 — teardown only
                pass

    return handle


def serve_async(repo, host: str = "127.0.0.1", port: int = 8000,
                batching: bool = True, block: bool = True,
                max_batch: int = 64, max_delay_ms: float = 2.0,
                max_queue: int = 256, pool_workers: int = 32,
                default_deadline_ms: Optional[float] = None,
                breaker_threshold: int = 5,
                breaker_cooldown_s: float = 5.0
                ) -> Optional[AsyncServerHandle]:
    """Serve a :class:`ModelRepository` on an asyncio event loop.
    Mirrors :func:`http_server.serve_http` (same endpoints, batching
    schedulers, backpressure, deadlines, circuit breaker, drain);
    ``block=False`` runs the loop on a daemon thread and returns an
    :class:`AsyncServerHandle`."""
    from .scheduler import BatchScheduler
    schedulers = {}
    state = ServingState(default_deadline_ms=default_deadline_ms)
    if batching:
        for name in repo.names():
            schedulers[name] = BatchScheduler(
                repo.get_instances(name), max_batch=max_batch,
                max_delay_ms=max_delay_ms, max_queue=max_queue,
                name=name, default_deadline_ms=default_deadline_ms,
                breaker_threshold=breaker_threshold,
                breaker_cooldown_s=breaker_cooldown_s)
    pool = ThreadPoolExecutor(max_workers=pool_workers,
                              thread_name_prefix="ffserve")
    loop = asyncio.new_event_loop()
    handler = _make_client_handler(repo, schedulers, pool, state)
    server = loop.run_until_complete(
        asyncio.start_server(handler, host, port))

    if block:
        try:
            loop.run_forever()
        finally:
            server.close()
            for s in schedulers.values():
                s.close()
            pool.shutdown(wait=False)
            loop.close()
        return None

    def _run():
        # the loop thread owns the close: run_forever returning (via
        # stop()) always releases the selector/self-pipe fds, even when
        # the stopping thread's join times out — closing from OUTSIDE
        # conditioned on is_alive() leaked them in exactly that case
        try:
            loop.run_forever()
        finally:
            loop.close()

    t = threading.Thread(target=_run, daemon=True)
    t.start()
    return AsyncServerHandle(loop, server, t, schedulers, pool, state)
