"""Asyncio HTTP front-end for the model repository.

Same KServe-style surface as ``http_server.py`` (the two share the
route functions), but connections are multiplexed on one event loop
instead of a thread per connection: the round-4 load test showed
client-observed p99 at ~4x the server-recorded latency purely from the
``ThreadingHTTPServer`` front under concurrency. Request BODIES are
parsed and executed in a bounded thread pool (the batching scheduler's
``infer`` blocks on its result event), so the loop never stalls on a
device step; keep-alive is supported so load generators reuse
connections.

Reference analog: Triton's event-driven HTTP/REST frontend
(``/root/reference/triton/README.md``) — stdlib-only here.

Usage::

    from flexflow_tpu.serving import serve_async
    serve_async(repo, port=8000)                     # blocks
    srv = serve_async(repo, port=8000, block=False)  # returns handle
    ...
    srv.stop()
"""
from __future__ import annotations

import asyncio
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Optional

from .http_server import get_route, post_route, render_body

_MAX_BODY = 256 << 20   # sanity bound, matches big dense batches


class AsyncServerHandle:
    """Running server + its loop thread; ``stop()`` shuts both down."""

    def __init__(self, loop, server, thread, schedulers, pool):
        self._loop = loop
        self._server = server
        self._thread = thread
        self.schedulers = schedulers
        self._pool = pool

    @property
    def port(self) -> int:
        return self._server.sockets[0].getsockname()[1]

    def stop(self):
        def _close():
            self._server.close()

        self._loop.call_soon_threadsafe(_close)
        self._loop.call_soon_threadsafe(self._loop.stop)
        self._thread.join(timeout=10)
        for s in self.schedulers.values():
            s.close()
        self._pool.shutdown(wait=False)
        if not self._thread.is_alive():
            # release the loop's selector/self-pipe fds (the blocking
            # serve path closes in its finally; this mirrors it)
            self._loop.close()


async def _read_request(reader):
    """Parse one HTTP/1.1 request; returns (method, path, headers,
    body) or None on EOF. An unparseable request line yields the "bad"
    marker — the client gets a 400 response instead of a silent
    connection drop (same contract as the bad-Content-Length path)."""
    try:
        line = await reader.readline()
    except (ConnectionError, asyncio.IncompleteReadError):
        return None
    if not line:
        return None
    try:
        method, path, _ = line.decode("latin1").split(" ", 2)
    except ValueError:
        # garbage request line: nothing after it is framable, so the
        # response must close the socket — but it IS a response
        return "bad", "", {}, b""
    headers = {}
    while True:
        h = await reader.readline()
        if h in (b"\r\n", b"\n", b""):
            break
        k, _, v = h.decode("latin1").partition(":")
        headers[k.strip().lower()] = v.strip()
    try:
        n = int(headers.get("content-length", 0))
    except ValueError:
        return "bad", path, headers, b""     # -> 400, not a dead socket
    if n < 0 or n > _MAX_BODY:
        return "bad", path, headers, b""
    body = await reader.readexactly(n) if n else b""
    return method, path, headers, body


def _response(code: int, obj, keep_alive: bool) -> bytes:
    body, ctype = render_body(obj)
    reason = {200: "OK", 400: "Bad Request", 404: "Not Found",
              503: "Service Unavailable"}.get(code, "OK")
    conn = "keep-alive" if keep_alive else "close"
    head = (f"HTTP/1.1 {code} {reason}\r\n"
            f"Content-Type: {ctype}\r\n"
            f"Content-Length: {len(body)}\r\n"
            f"Connection: {conn}\r\n\r\n")
    return head.encode("latin1") + body


def _make_client_handler(repo, schedulers, pool):
    async def handle(reader, writer):
        loop = asyncio.get_running_loop()
        try:
            while True:
                req = await _read_request(reader)
                if req is None:
                    break
                method, path, headers, body = req
                keep = headers.get("connection", "keep-alive").lower() \
                    != "close"
                if method == "bad":
                    # the body was never read (unparseable request line
                    # or unparseable/oversized Content-Length), so
                    # keep-alive framing on this socket is
                    # unrecoverable: respond and close
                    code, obj = 400, {"error": "malformed request"}
                    keep = False
                elif method == "GET":
                    code, obj = get_route(path, repo, schedulers)
                elif method == "POST":
                    # parse + (blocking) scheduler wait off-loop
                    code, obj = await loop.run_in_executor(
                        pool, post_route, path, body, repo, schedulers)
                else:
                    # unknown method/route: a framed 404 on a live
                    # connection (the body was consumed above), never
                    # a silent drop
                    code, obj = 404, {"error": f"method {method}"}
                writer.write(_response(code, obj, keep))
                await writer.drain()
                if not keep:
                    break
        except (ConnectionError, asyncio.IncompleteReadError):
            pass
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except Exception:  # noqa: BLE001 — teardown only
                pass

    return handle


def serve_async(repo, host: str = "127.0.0.1", port: int = 8000,
                batching: bool = True, block: bool = True,
                max_batch: int = 64, max_delay_ms: float = 2.0,
                max_queue: int = 256, pool_workers: int = 32
                ) -> Optional[AsyncServerHandle]:
    """Serve a :class:`ModelRepository` on an asyncio event loop.
    Mirrors :func:`http_server.serve_http` (same endpoints, batching
    schedulers, backpressure); ``block=False`` runs the loop on a
    daemon thread and returns an :class:`AsyncServerHandle`."""
    from .scheduler import BatchScheduler
    schedulers = {}
    if batching:
        for name in repo.names():
            schedulers[name] = BatchScheduler(
                repo.get_instances(name), max_batch=max_batch,
                max_delay_ms=max_delay_ms, max_queue=max_queue,
                name=name)
    pool = ThreadPoolExecutor(max_workers=pool_workers,
                              thread_name_prefix="ffserve")
    loop = asyncio.new_event_loop()
    handler = _make_client_handler(repo, schedulers, pool)
    server = loop.run_until_complete(
        asyncio.start_server(handler, host, port))

    if block:
        try:
            loop.run_forever()
        finally:
            server.close()
            for s in schedulers.values():
                s.close()
            pool.shutdown(wait=False)
            loop.close()
        return None
    t = threading.Thread(target=loop.run_forever, daemon=True)
    t.start()
    return AsyncServerHandle(loop, server, t, schedulers, pool)
