"""Inference session: bucketed, cached, eval-mode jitted forwards.

The Triton backend's per-model execution context
(``/root/reference/triton/src/model_instance_state.cc`` equivalent)
reduced to what matters on TPU: a warm XLA executable per (batch-bucket,
input-shape) and zero-copy host->device batch assembly.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..obs import request_trace
from ..resilience import faults


def _next_bucket(n: int, buckets: Sequence[int]) -> int:
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


class InferenceSession:
    """Wraps a compiled FFModel for serving.

    Requests of any batch size are padded up to the nearest bucket so
    XLA compiles once per bucket (the recompile-avoidance trick Triton
    gets from its preferred_batch_size config).
    """

    def __init__(self, ff, batch_buckets: Sequence[int] = (1, 4, 16, 64),
                 decode_segment: int = 32):
        if ff.executor is None:
            raise ValueError("compile() the model first")
        self.ff = ff
        self.buckets = sorted(set(int(b) for b in batch_buckets))
        # greedy decodes longer than this run in decode_segment-token
        # chunks, RELEASING the instance lock between chunks — a
        # 512-token generate no longer starves every short infer()
        # queued on the same instance for its whole duration. 0
        # disables segmentation (one lock hold, the legacy behavior).
        self.decode_segment = int(decode_segment)
        self._fwd = ff.executor.make_forward()
        self._lock = threading.Lock()

    def clone(self) -> "InferenceSession":
        """A concurrent instance of the same model: shares the compiled
        forward and parameters, carries its OWN dispatch lock — jitted
        executions are thread-safe, so clones genuinely overlap
        (Triton's instance_group over one device)."""
        c = InferenceSession.__new__(InferenceSession)
        c.ff = self.ff
        c.buckets = self.buckets
        c.decode_segment = self.decode_segment
        c._fwd = self._fwd
        c._lock = threading.Lock()
        return c

    @property
    def input_names(self) -> List[str]:
        return [t.name for t in self.ff.graph_inputs]

    @property
    def input_signature(self) -> Dict[str, Tuple[Tuple[int, ...],
                                                 np.dtype]]:
        """name -> (compile-time shape, numpy dtype) for each graph
        input. ``shape[0]`` is the COMPILE-TIME batch size — requests
        may send any row count; the scheduler's admission validation
        compares only ``shape[1:]`` and the dtype."""
        return {t.name: (tuple(t.shape), np.dtype(t.jnp_dtype))
                for t in self.ff.graph_inputs}

    def infer(self, inputs: Dict[str, np.ndarray]) -> np.ndarray:
        """Run one batch; pads to the bucket and slices the result.
        Batches larger than the biggest bucket run in bucket-sized
        chunks (one executable, several dispatches). Client errors
        (missing inputs, ragged rows) raise :class:`ValueError` — not
        ``assert``, which vanishes under ``python -O`` and would turn
        them into shape crashes deep in XLA."""
        if faults.active():
            faults.raise_infer_fault()
        return self._infer_checked(inputs)

    def _infer_checked(self, inputs: Dict[str, np.ndarray]) -> np.ndarray:
        # chunk recursion goes through here, NOT infer(): the fault
        # hook must advance the infer_fail@N counter exactly once per
        # top-level call or clause indices stop matching request counts
        names = self.input_names
        missing = [n for n in names if n not in inputs]
        if missing:
            raise ValueError(f"missing inputs: {missing}")
        n = int(next(iter(inputs.values())).shape[0])
        cap = self.buckets[-1]
        if n > cap:
            return np.concatenate(
                [self._infer_checked(
                    {k: v[i:i + cap] for k, v in inputs.items()})
                 for i in range(0, n, cap)], axis=0)
        bucket = _next_bucket(n, self.buckets)
        padded = {}
        for name in names:
            arr = np.ascontiguousarray(inputs[name])
            if arr.shape[0] != n:
                raise ValueError(f"ragged batch: {name} has "
                                 f"{arr.shape[0]} rows, want {n}")
            if bucket != n:
                pad = np.zeros((bucket - n,) + arr.shape[1:], arr.dtype)
                arr = np.concatenate([arr, pad], axis=0)
            padded[name] = arr
        with self._lock:  # jax dispatch of ONE model's forward at a time
            out = self._fwd(self.ff.params, self.ff.state, padded)
        return np.asarray(out)[:n]

    def generate(self, input_ids: np.ndarray,
                 prompt_len: "int | np.ndarray",
                 max_new_tokens: int, temperature: float = 0.0,
                 seed: int = 0,
                 eos_token_id: "int | None" = None,
                 top_k: int = 0, top_p: float = 1.0,
                 num_beams: int = 1) -> np.ndarray:
        """Autoregressive decode for causal-LM sessions. ``prompt_len``
        may be a per-row (batch,) array (ragged prompts). Batch is
        padded to the bucket (decode programs cache per bucket inside
        ``FFModel.generate``); the padded rows' outputs are sliced off."""
        # same chaos hook as infer(): generate IS the serving path a
        # fleet chaos plan (infer_fail@N / infer_crash@N) must reach.
        # Each bucket-sized chunk of an oversized batch advances the
        # call counter once (chunks are separate device dispatches),
        # which keeps clause indices deterministic per workload.
        if faults.active():
            faults.raise_infer_fault()
        ids = np.ascontiguousarray(np.asarray(input_ids, np.int32))
        n = int(ids.shape[0])
        ragged = np.ndim(prompt_len) > 0
        if ragged:
            if num_beams > 1:
                raise ValueError("per-row prompt lengths are not "
                                 "supported with beam search; send "
                                 "uniform-length beams or one request "
                                 "per row")
            prompt_len = np.asarray(prompt_len, np.int32)
        cap = self.buckets[-1]
        if n > cap:
            # per-chunk seed: identical prompts in different chunks must
            # not draw identical sampling streams. Wide-stride fold so a
            # separate request using seed+1 does not collide with chunk 1
            # of this request (the streams only meet after ~2^31 seeds).
            return np.concatenate(
                [self.generate(ids[i:i + cap],
                               prompt_len[i:i + cap] if ragged
                               else prompt_len,
                               max_new_tokens, temperature,
                               (seed + (i // cap) * 0x9E3779B1)
                               & 0x7FFFFFFF, eos_token_id,
                               top_k=top_k, top_p=top_p,
                               num_beams=num_beams)
                 for i in range(0, n, cap)], axis=0)
        # ambient request trace (set by the HTTP front): generate runs
        # on the caller's thread, so its lifecycle stages — batch
        # padding here, the instance-lock wait below, the prefill/
        # decode spans inside FFModel.generate — link into the request
        trace = request_trace.current()
        t_pad = time.perf_counter()
        bucket = _next_bucket(n, self.buckets)
        if bucket != n:
            pad = np.zeros((bucket - n,) + ids.shape[1:], ids.dtype)
            ids = np.concatenate([ids, pad], axis=0)
            if ragged:
                # padded rows decode from a dummy 1-token prompt
                prompt_len = np.concatenate(
                    [prompt_len, np.ones(bucket - n, np.int32)])
        if trace is not None:
            trace.stage("batch", t_pad, bucket=str(bucket), rows=n)
        seg = int(getattr(self, "decode_segment", 0) or 0)
        if (num_beams == 1 and temperature == 0.0 and not top_k
                and top_p >= 1.0 and 0 < seg < max_new_tokens):
            # greedy decode is deterministic, so it can run in bounded
            # segments with the lock RELEASED between them — short
            # infer() calls on this instance interleave instead of
            # waiting out the whole generation. Sampling paths keep the
            # single hold: the RNG stream is keyed to one scan.
            out = self._generate_segmented(ids, prompt_len,
                                           max_new_tokens, seg,
                                           eos_token_id, ragged)
            return np.asarray(out)[:n]
        t_lock = time.perf_counter()
        with self._lock:
            if trace is not None:
                # instance-lock wait = this request's queue time on the
                # single-hold decode path
                trace.stage("queue", t_lock, bucket=str(bucket))
            if num_beams > 1:
                # beam search is deterministic: temperature/top-k/top-p
                # do not apply
                out = self.ff.generate_beam(ids, prompt_len,
                                            max_new_tokens,
                                            num_beams=num_beams,
                                            eos_token_id=eos_token_id)
            else:
                out = self.ff.generate(ids, prompt_len, max_new_tokens,
                                       temperature=temperature,
                                       seed=seed,
                                       eos_token_id=eos_token_id,
                                       top_k=top_k, top_p=top_p)
        return np.asarray(out)[:n]

    def _generate_segmented(self, ids: np.ndarray,
                            prompt_len, max_new_tokens: int, seg: int,
                            eos_token_id, ragged: bool) -> np.ndarray:
        """Greedy decode in bounded lock-hold segments, bit-exact with
        the single-hold path: each segment continues from the previous
        one's ids with the prompt length advanced. Rows that emitted
        ``eos`` in an earlier segment have their later columns forced
        back to ``eos`` on the host — exactly what the in-program
        done-mask does inside one segment — so early-stopped rows read
        identically however the generation was segmented (rows are
        batch-independent under causal attention, so a finished row's
        forced columns cannot perturb its neighbors)."""
        out = np.asarray(ids)
        b, L = out.shape
        plen = (np.asarray(prompt_len, np.int32) if ragged
                else int(prompt_len))
        done = np.zeros(b, bool)
        col = np.arange(L)[None, :]
        trace = request_trace.current()
        seg_idx = 0
        offset, remaining = 0, int(max_new_tokens)
        while remaining > 0:
            step = min(seg, remaining)
            cur = plen + offset
            t_wait = time.perf_counter()
            with self._lock:
                if trace is not None and seg_idx == 0:
                    # first lock acquisition = the request's queue time
                    # on this instance (later waits show up as gaps
                    # between decode_segment spans)
                    trace.stage("queue", t_wait, bucket=str(b))
                t_step = time.perf_counter()
                # np.array (copy): the device buffer view is read-only
                # and the eos forcing below writes in place
                out = np.array(self.ff.generate(
                    out, cur, step, temperature=0.0,
                    eos_token_id=eos_token_id))
            if trace is not None:
                trace.stage("decode_segment", t_step, segment=seg_idx,
                            tokens=step, bucket=str(b))
            seg_idx += 1
            if eos_token_id is not None:
                starts = np.asarray(cur, np.int64) if ragged \
                    else np.full(b, cur, np.int64)
                seg_cols = (col >= starts[:, None]) \
                    & (col < (starts + step)[:, None])
                if done.any():
                    out[done[:, None] & seg_cols] = eos_token_id
                done |= np.where(seg_cols, out == eos_token_id,
                                 False).any(axis=1)
            offset += step
            remaining -= step
        return out


class ServingPlanSession:
    """Bucket-routed instances of a searched serving plan
    (``search/serving_plan.optimize_serving_strategy``).

    One compiled model per batch bucket, each imported from the plan's
    per-bucket sub-strategy: a batch-1 request rides the latency-lean
    (typically tensor-parallel) plan, a batch-64 request the
    throughput (data-parallel) plan — per-batch-class parallelization
    instead of one compromise strategy. Duck-typed to
    :class:`InferenceSession` (``infer``/``generate``/``clone``/
    ``input_names``/``input_signature``/``buckets``/``ff``) so
    :class:`~flexflow_tpu.serving.scheduler.BatchScheduler` and both
    HTTP fronts serve it unchanged."""

    def __init__(self, sessions: Dict[int, InferenceSession]):
        if not sessions:
            raise ValueError("need at least one bucket session")
        self._by_bucket = {int(b): s for b, s in dict(sessions).items()}
        self.buckets = sorted(self._by_bucket)
        # adoption-time measured floor-guard decisions, when the guard
        # ran (build_serving_plan_session): bucket -> {searched_s,
        # baseline_s, adopted}
        self.floor_guard: Dict = {}

    @property
    def ff(self):
        """The largest bucket's model — the one the serving envelope
        gate was enforced at (KV-cache fallback/health introspection
        reads this instance)."""
        return self._by_bucket[self.buckets[-1]].ff

    def session_for(self, n: int) -> InferenceSession:
        """The per-bucket instance a batch of ``n`` rows routes to."""
        return self._by_bucket[_next_bucket(n, self.buckets)]

    @property
    def input_names(self) -> List[str]:
        return self._by_bucket[self.buckets[-1]].input_names

    @property
    def input_signature(self):
        return self._by_bucket[self.buckets[-1]].input_signature

    def infer(self, inputs: Dict[str, np.ndarray]) -> np.ndarray:
        n = int(next(iter(inputs.values())).shape[0])
        # oversized batches ride the largest bucket's own chunking
        return self.session_for(n).infer(inputs)

    def generate(self, input_ids: np.ndarray,
                 prompt_len: "int | np.ndarray",
                 max_new_tokens: int, temperature: float = 0.0,
                 seed: int = 0, eos_token_id: "int | None" = None,
                 top_k: int = 0, top_p: float = 1.0,
                 num_beams: int = 1) -> np.ndarray:
        n = int(np.asarray(input_ids).shape[0])
        return self.session_for(n).generate(
            input_ids, prompt_len, max_new_tokens,
            temperature=temperature, seed=seed,
            eos_token_id=eos_token_id, top_k=top_k, top_p=top_p,
            num_beams=num_beams)

    def clone(self) -> "ServingPlanSession":
        c = ServingPlanSession(
            {b: s.clone() for b, s in self._by_bucket.items()})
        c.floor_guard = self.floor_guard
        return c

    def measured_profile(self) -> Dict[str, Dict]:
        """Measured per-bucket decode reality, keyed 1:1 to the serving
        audit block's ``predicted`` entries: bucket label ->
        ``{prefill_s, decode_step_s, n}`` — the min-tracked sink
        ``FFModel._generate_kv`` maintains per batch size on each
        bucket's model.  Buckets that have served no generate traffic
        yet are absent (``obs.drift.serving_drift_report`` skips them
        rather than report drift on zero measurements).  Clones share
        the underlying ``ff``, so any instance's traffic lands here."""
        out: Dict[str, Dict] = {}
        for b, s in self._by_bucket.items():
            rec = getattr(s.ff, "_decode_measured", {}).get(int(b))
            if rec:
                out[str(b)] = dict(rec)
        return out


def _min_decode_latency(ff, bucket: int, hist, reps: int = 3) -> float:
    """Min measured per-token decode-step latency of ``ff`` at
    ``bucket`` rows (read from the ``ff_decode_step_seconds`` histogram
    the KV-decode path observes — decode phase only, prefill excluded).
    The first call warms/compiles and is not timed. Raises when the
    graph has no generate path (non-causal-LM) — callers treat that as
    'guard not applicable'."""
    t = next(t for t in ff.graph_inputs if t.name == "input_ids")
    seq = int(t.shape[1])
    plen = max(1, seq // 4)
    new_tokens = max(1, min(8, seq - plen))
    ids = np.zeros((bucket, seq), np.int32)
    np.asarray(ff.generate(ids, plen, new_tokens, temperature=0.0))
    best = float("inf")
    for _ in range(reps):
        before = hist.sum(bucket=str(bucket))
        np.asarray(ff.generate(ids, plen, new_tokens, temperature=0.0))
        best = min(best, hist.sum(bucket=str(bucket)) - before)
    return best


def build_serving_plan_session(serving_strategy_file: str, build,
                               floor_guard: str = "auto"
                               ) -> ServingPlanSession:
    """One compiled model per bucket of a serving-plan artifact: each
    bucket's sub-strategy is extracted into a standalone single-bucket
    strategy doc (``serving_plan.bucket_strategy_doc`` — so compile's
    plan verifier gates the KV envelope AT that bucket) and imported
    through the ordinary strategy-file path. ``build(sf, buckets=...)``
    compiles one session from a strategy file (``sf=None`` = the model
    as it would load WITHOUT a serving plan — the reused-training-plan
    baseline the floor guard compares against).

    ``floor_guard`` (``FFConfig.serving_floor_guard``): the measured
    decode floor on adoption. Like the training search's
    ``_apply_floor_guard``, the protection is direct measurement, not
    trust in the cost model: per bucket, a few greedy decodes of the
    imported plan AND the baseline run back to back, and the bucket
    keeps whichever measures faster (records in
    ``ServingPlanSession.floor_guard``). "auto" skips on bare-CPU
    backends (the extra baseline compile is expensive on the CPU sim);
    any failure to measure keeps the searched plan — the guard must
    never kill a load."""
    import json
    import os
    import tempfile
    import time

    from ..search.serving_plan import bucket_strategy_doc
    with open(serving_strategy_file) as f:
        doc = json.load(f)
    sblock = doc.get("serving") or {}
    bks = sorted(int(k) for k in (sblock.get("buckets") or {}))
    if not bks:
        raise ValueError(
            f"{serving_strategy_file} has no serving block — "
            f"search one with optimize_serving_strategy "
            f"(mode='serving') or pass it as strategy_file")
    per_bucket = {}
    for b in bks:
        sub = bucket_strategy_doc(doc, b)
        fd, p = tempfile.mkstemp(suffix=f".bucket{b}.json")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(sub, f)
            per_bucket[b] = build(p, buckets=[b])
        finally:
            try:
                os.unlink(p)
            except OSError:
                pass

    mode = str(floor_guard or "auto").lower()
    guard = mode not in ("false", "off", "0", "no")
    if guard and mode == "auto":
        import jax
        guard = jax.devices()[0].platform != "cpu"
    records = {}
    if guard:
        from ..obs import events as obs_events
        from ..obs.metrics_registry import DECODE_STEP_BUCKETS, REGISTRY
        hist = REGISTRY.histogram(
            "ff_decode_step_seconds",
            "Per-token decode-step latency by batch bucket",
            buckets=DECODE_STEP_BUCKETS)
        t0 = time.perf_counter()
        try:
            base = build(None, buckets=list(bks))
            for b in bks:
                t_s = _min_decode_latency(per_bucket[b].ff, b, hist)
                t_b = _min_decode_latency(base.ff, b, hist)
                adopted = "searched" if t_s <= t_b else "baseline"
                if adopted == "baseline":
                    per_bucket[b] = InferenceSession(
                        base.ff, [b],
                        decode_segment=per_bucket[b].decode_segment)
                records[b] = {"searched_s": t_s, "baseline_s": t_b,
                              "adopted": adopted}
        except Exception as e:  # noqa: BLE001 — guard never kills a load
            records = {"skipped": repr(e)[:200]}
        obs_events.record_span(
            "serving.floor_guard", t0, time.perf_counter() - t0,
            buckets=len(bks))
    session = ServingPlanSession(per_bucket)
    session.floor_guard = records
    return session


class ModelRepository:
    """Name -> session-instances registry (Triton model repository +
    instance groups, ``triton/src/backend.cc``/``instance.cc``).

    Each model may have N concurrent instances (session replicas); the
    HTTP layer gives all of them to one :class:`BatchScheduler`, whose
    per-instance workers drain a shared bounded queue. Models can be
    loaded/unloaded by name at runtime (Triton repository API)."""

    def __init__(self):
        self._models: Dict[str, List[InferenceSession]] = {}

    def register(self, name: str, session: InferenceSession,
                 instances: "int | None" = None):
        """Register a model. Pass a list of sessions OR ``instances=N``
        to clone one session N times — clones share the compiled
        forward and weights but have independent dispatch locks, so
        the N scheduler workers genuinely overlap (Triton instances
        sharing one device)."""
        if isinstance(session, (list, tuple)):
            self._models[name] = list(session)
        elif instances and instances > 1:
            self._models[name] = [session] + [
                session.clone() for _ in range(int(instances) - 1)]
        else:
            self._models[name] = [session]

    def unload(self, name: str):
        """Remove a model by name (Triton ``.../unload``)."""
        if name not in self._models:
            raise KeyError(f"model {name!r} not loaded")
        del self._models[name]

    def load_graph(self, name: str, path: str,
                   input_shapes: Sequence[Sequence[int]],
                   checkpoint_dir: Optional[str] = None,
                   batch_buckets: Sequence[int] = (1, 4, 16, 64),
                   config=None, strategy_file=None, instances: int = 1,
                   serving_strategy_file=None):
        """Serve a serialized graph (``PyTorchModel.torch_to_file`` /
        strategy-export output) without its source framework: rebuild
        through ``file_to_ff``, optionally restore trained weights from
        a checkpoint, and register an eval session.

        ``strategy_file`` imports a searched strategy instead of plain
        data parallelism; pass a LIST (one entry per instance, None =
        DP) to give each instance its own parallelization — the
        reference Triton backend's per-instance strategy files
        (``triton/src/instance.cc``). A single value with
        ``instances=N`` compiles once and clones (instances sharing one
        program); a list compiles each instance separately."""
        from ..frontends.torch_fx import PyTorchModel

        def graph_build(ff):
            ins = [ff.create_tensor(tuple(s), name=f"in{i}")
                   for i, s in enumerate(input_shapes)]
            outs = PyTorchModel.file_to_ff(path, ff, ins)
            return outs[0]

        return self._load_with_builder(
            name, graph_build, batch_buckets=batch_buckets, config=config,
            strategy_file=strategy_file, instances=instances,
            checkpoint_dir=checkpoint_dir,
            serving_strategy_file=serving_strategy_file)

    def load_onnx(self, name: str, path_or_model,
                  input_shapes: Optional[Dict[str, Sequence[int]]] = None,
                  checkpoint_dir: Optional[str] = None,
                  batch_buckets: Sequence[int] = (1, 4, 16, 64),
                  config=None, strategy_file=None, instances: int = 1,
                  serving_strategy_file=None):
        """Serve an ONNX model torch-free (the reference Triton
        backend's direct ONNX ingestion, ``triton/src/onnx_parser.cc``):
        rebuild the graph through ``frontends.onnx_frontend.ONNXModel``,
        transfer the initializer weights after compile, and register
        sessions. ``input_shapes`` overrides/maps graph-input name ->
        shape (required for inputs with symbolic batch dims);
        ``strategy_file``/``instances`` behave as in
        :meth:`load_graph`."""
        from ..frontends.onnx_frontend import ONNXModel
        model = ONNXModel(path_or_model)
        graph = model.model.graph
        fed = [vi for vi in graph.input
               if vi.name not in model.initializers]
        # elem_type -> framework dtype (TensorProto enum values)
        from ..ffconst import DataType
        dt_map = {1: DataType.DT_FLOAT, 6: DataType.DT_INT32,
                  7: DataType.DT_INT64, 9: DataType.DT_BOOLEAN,
                  10: DataType.DT_HALF, 16: DataType.DT_BFLOAT16}

        def shape_of(vi):
            if input_shapes and vi.name in input_shapes:
                return tuple(int(d) for d in input_shapes[vi.name])
            dims = []
            for d in vi.type.tensor_type.shape.dim:
                if d.dim_param or d.dim_value <= 0:
                    raise ValueError(
                        f"ONNX input {vi.name!r} has a symbolic dim "
                        f"{d.dim_param or '?'} — pass input_shapes")
                dims.append(int(d.dim_value))
            return tuple(dims)

        def onnx_build(ff):
            ins = {vi.name: ff.create_tensor(
                shape_of(vi), name=vi.name,
                dtype=dt_map.get(vi.type.tensor_type.elem_type,
                                 DataType.DT_FLOAT)) for vi in fed}
            outs = model.apply(ff, ins)
            return outs[0]

        return self._load_with_builder(
            name, onnx_build, batch_buckets=batch_buckets, config=config,
            strategy_file=strategy_file, instances=instances,
            checkpoint_dir=checkpoint_dir,
            post_compile=model.copy_weights,
            serving_strategy_file=serving_strategy_file)

    def _load_with_builder(self, name, graph_build, batch_buckets,
                           config, strategy_file, instances,
                           checkpoint_dir=None, post_compile=None,
                           serving_strategy_file=None):
        """Shared per-instance loading: one compiled session per
        strategy-file entry (None = plain DP), or one session cloned
        ``instances`` times (replicas sharing the compiled program) —
        the reference Triton backend's per-instance strategy files
        (``triton/src/instance.cc``).

        ``serving_strategy_file`` adopts a searched per-batch-class
        serving plan (a strategy export whose ``serving`` block carries
        one sub-strategy per bucket): one model is compiled per bucket
        and requests route by batch size through a
        :class:`ServingPlanSession`. Mutually exclusive with
        ``strategy_file``."""
        import copy

        from ..config import FFConfig
        from ..model import FFModel
        from ..runtime.optimizers import SGDOptimizer
        from ..utils.compilation_cache import enable_compilation_cache

        if serving_strategy_file and strategy_file:
            raise ValueError("pass strategy_file OR "
                             "serving_strategy_file, not both")
        per_instance = isinstance(strategy_file, (list, tuple))
        files = (list(strategy_file) if per_instance
                 else [strategy_file])
        if per_instance and instances != 1 and instances != len(files):
            raise ValueError(
                f"instances={instances} conflicts with "
                f"{len(files)} per-instance strategy files — the list "
                f"length alone sets the instance count")

        def build(sf, buckets=batch_buckets):
            cfg = copy.deepcopy(config) if config is not None \
                else FFConfig()
            if sf:
                cfg.import_strategy_file = sf
                cfg.only_data_parallel = False
            else:
                cfg.only_data_parallel = True
                # a None list entry means plain DP for THIS instance:
                # clear any import the caller's config carried, or the
                # instance would silently adopt that strategy instead
                cfg.import_strategy_file = ""
            # warm start: every repository load opts into the
            # persistent compilation cache, so a fresh serving process
            # re-loading the same model hits disk instead of re-paying
            # XLA (the helper's own guard skips bare-CPU backends,
            # where AOT reload risks SIGILL). Recompiles stay visible
            # through ff_model_compiles_total{model=...}.
            enable_compilation_cache(
                getattr(cfg, "compilation_cache_dir", "") or None)
            ff = FFModel(cfg)
            ff._model_name = name   # labels compile/fallback counters
            out = graph_build(ff)
            ff.compile(SGDOptimizer(0.0), "identity", [],
                       output_tensor=out)
            if post_compile is not None:
                post_compile(ff)
            if checkpoint_dir:
                from ..runtime.checkpoint import restore_model_checkpoint
                restore_model_checkpoint(ff, checkpoint_dir)
            return InferenceSession(ff, buckets)

        if serving_strategy_file:
            session = build_serving_plan_session(
                serving_strategy_file, build,
                floor_guard=getattr(config, "serving_floor_guard",
                                    "auto") if config is not None
                else "auto")
            self.register(name, session, instances=instances)
            return session

        sessions = [build(sf) for sf in files]
        if per_instance:
            self.register(name, sessions)
        else:
            # register's own clone path handles instances=N
            self.register(name, sessions[0], instances=instances)
        return sessions[0]

    # backward-compat alias: the per-bucket build + measured floor
    # guard live in the module-level build_serving_plan_session
    _build_serving_plan = staticmethod(build_serving_plan_session)

    def hot_swap(self, name: str, session, instances: "int | None" = None,
                 scheduler=None, deadline_s: float = 10.0):
        """Replace a loaded model's instances in place — the adoption
        point for a re-searched serving plan. With ``scheduler`` (the
        model's :class:`~flexflow_tpu.serving.scheduler.BatchScheduler`)
        the swap rides the graceful-drain path: admission pauses
        (503 + ``Retry-After``), the admitted backlog flushes on the
        OLD instances, then workers restart on the new ones — no
        admitted request is dropped. Without a scheduler it is a bare
        registry swap (single-session deployments)."""
        if name not in self._models:
            raise KeyError(f"model {name!r} not loaded "
                           f"(have {list(self._models)})")
        self.register(name, session, instances=instances)
        if scheduler is not None:
            scheduler.hot_swap(self.get_instances(name),
                               deadline_s=deadline_s)
        return self.get(name)

    def get(self, name: str) -> InferenceSession:
        """First (primary) instance — the single-session API."""
        return self.get_instances(name)[0]

    def get_instances(self, name: str) -> List[InferenceSession]:
        if name not in self._models:
            raise KeyError(
                f"model {name!r} not loaded (have {list(self._models)})")
        return self._models[name]

    def names(self) -> List[str]:
        return list(self._models)
