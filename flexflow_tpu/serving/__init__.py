"""TPU-native inference serving (the reference's Triton backend analog).

Reference parity: ``/root/reference/triton/`` (~16.7k LoC C++) serves
FlexFlow-compiled models behind Triton's HTTP/gRPC batching frontend.
TPU-native redesign: the expensive part of serving on TPU is (a) keeping
one warm jitted forward per bucketed shape (recompiles are seconds) and
(b) batching requests into those buckets; both live here in
``InferenceSession`` / ``BatchScheduler``, and a dependency-free HTTP
frontend (``serve_http``) exposes the Triton-style
``POST /v2/models/<name>/infer`` JSON API. Models arrive either as a
live ``FFModel`` or from the torch-frontend's serialization hand-off
(``ModelRepository.load_graph`` -> ``file_to_ff``).

Overload robustness (docs/serving.md): per-request deadlines
(``x-ff-timeout-ms``), admission control that sheds doomed work at the
queue door, a per-model circuit breaker, batch-poison isolation, and
graceful drain on both HTTP fronts.

Fleet serving (``serving/fleet``, docs/serving.md · Fleet): continuous
batching for autoregressive decode (``ContinuousBatcher``), a
multi-replica router driven by the per-replica admission-control EWMA
(``FleetRouter``/``serve_fleet``), and a signal-driven autoscaler
(``Autoscaler``) — imported lazily from ``flexflow_tpu.serving.fleet``
to keep the single-replica import path lean.
"""
from .session import InferenceSession, ModelRepository
from .scheduler import (BatchScheduler, CircuitBreaker, CircuitOpenError,
                        DeadlineExceededError, DeadlineRejectedError,
                        DrainingError, InvalidInputError, QueueFullError,
                        RequestRejected, SchedulerMetrics)
from .http_server import serve_http
from .async_server import serve_async

__all__ = ["InferenceSession", "ModelRepository", "BatchScheduler",
           "CircuitBreaker", "CircuitOpenError", "DeadlineExceededError",
           "DeadlineRejectedError", "DrainingError", "InvalidInputError",
           "QueueFullError", "RequestRejected", "SchedulerMetrics",
           "serve_http", "serve_async"]
