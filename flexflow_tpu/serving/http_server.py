"""Dependency-free HTTP frontend speaking the Triton KServe-style API.

Endpoints (JSON bodies, shapes row-major):
  - ``GET  /v2/health/ready``            -> 200 when serving
  - ``GET  /healthz``                    -> 200 {"status": "ok"} (probe
    alias — what k8s-style liveness checks expect)
  - ``GET  /v2/models``                  -> {"models": [names]}
  - ``GET  /v2/metrics``                 -> per-model scheduler counters
    (requests/completed/rejected, queue depth, mean batch rows,
    latency p50/p99 ms, instances)
  - ``GET  /metrics``                    -> Prometheus text exposition
    (request-latency histograms, queue-depth gauges, request counters —
    the ``obs/metrics_registry.py`` registry; scrape-ready)
  - ``POST /v2/models/<name>/infer``     -> {"outputs": [{"data", "shape"}]}
    body: {"inputs": [{"name": ..., "shape": [...], "data": [flat]}]};
    bounded-queue overflow -> 503
  - ``POST /v2/models/<name>/generate``  -> {"outputs": [{"name":
    "output_ids", ...}]} — causal-LM decode; body adds
    {"parameters": {"prompt_len", "max_new_tokens", "temperature", "top_k", "top_p",
    "seed", "eos_token_id"}}
  - ``POST /v2/repository/models/<name>/unload`` -> remove a model

Reference analog: the Triton backend's HTTP surface
(``/root/reference/triton/README.md``); stdlib-only so it runs anywhere
the framework does.
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np

from ..obs import events as obs_events
from ..obs.metrics_registry import REGISTRY
from .scheduler import QueueFullError


def render_body(obj):
    """Encode a route result body: dict -> JSON, str -> pre-rendered
    plain text (the Prometheus exposition). Returns ``(bytes, ctype)``;
    shared by the threading and asyncio front-ends so the content-type
    policy cannot drift between them."""
    if isinstance(obj, str):
        return obj.encode(), "text/plain; version=0.0.4; charset=utf-8"
    return json.dumps(obj).encode(), "application/json"


def render_prometheus(schedulers) -> str:
    """Prometheus text for ``GET /metrics``: the process-wide registry
    plus point-in-time gauges (queue depth, instances) sampled at
    scrape time from the live schedulers.

    The registry is process-wide by design (all fronts' request
    counters/histograms merge into one namespace); the point-in-time
    gauges reflect the schedulers of the server front that was scraped,
    so a process running MULTIPLE fronts should scrape one of them —
    the standard one-server-per-process deployment is unaffected."""
    live = list(schedulers.items())
    # atomic re-sample from live state: rows for models unloaded since
    # the last scrape disappear, and a concurrent scrape never observes
    # a half-populated row set
    REGISTRY.gauge("ff_queue_depth",
                   "Requests waiting in the bounded queue").set_all(
        ({"model": name}, sched._q.qsize()) for name, sched in live)
    REGISTRY.gauge("ff_scheduler_instances",
                   "Model instances draining the queue").set_all(
        ({"model": name}, sched.num_instances) for name, sched in live)
    return REGISTRY.render()


def get_route(path: str, repo, schedulers):
    """Route one GET; returns ``(status, obj)`` where ``obj`` is a JSON
    document (dict) or pre-rendered plain text (str — the Prometheus
    exposition). Shared by the threading and asyncio front-ends (the
    request counter lives here for the same reason: one counting
    policy, both fronts)."""
    obs_events.counter("serving.http_requests")
    if path in ("/v2/health/ready", "/healthz"):
        # resilience block (resilience/status.py): restart/fault/
        # checkpoint facts + checkpoint age, so a liveness probe can
        # alert on "restarting in a loop" or "checkpoints stale" — both
        # invisible to a bare 200
        from ..resilience import status as resilience_status
        return 200, {"status": "ok", "ready": True,
                     "resilience": resilience_status.health_fields()}
    if path == "/metrics":
        return 200, render_prometheus(schedulers)
    if path == "/v2/models":
        return 200, {"models": repo.names()}
    if path == "/v2/metrics":
        # per-model scheduler counters + latency percentiles
        # (Triton's /metrics endpoint, prometheus-lite as JSON)
        out = {}
        # snapshot: a concurrent unload may pop from schedulers
        for name, sched in list(schedulers.items()):
            out[name] = sched.metrics.snapshot(sched._q.qsize())
            out[name]["instances"] = sched.num_instances
        return 200, {"models": out}
    return 404, {"error": f"no route {path}"}


def post_route(path: str, body: bytes, repo, schedulers):
    """Route one POST (BLOCKING — the batching scheduler's ``infer``
    waits for the result; the asyncio front runs this in a thread
    pool). Returns ``(status, json_obj)``."""
    obs_events.counter("serving.http_requests")
    parts = path.strip("/").split("/")
    # v2/repository/models/<name>/unload (Triton repository API)
    if len(parts) == 5 and parts[:3] == ["v2", "repository", "models"] \
            and parts[4] == "unload":
        try:
            repo.unload(parts[3])
            sched = schedulers.pop(parts[3], None)
            if sched is not None:
                sched.close()
            return 200, {"unloaded": parts[3]}
        except KeyError as e:
            return 404, {"error": str(e)}
    # v2/models/<name>/{infer,generate}
    if len(parts) != 4 or parts[:2] != ["v2", "models"] \
            or parts[3] not in ("infer", "generate"):
        return 404, {"error": f"no route {path}"}
    name, verb = parts[2], parts[3]
    try:
        doc = json.loads(body)
        inputs = {}
        for rec in doc["inputs"]:
            arr = np.asarray(rec["data"], dtype=np.dtype(
                rec.get("datatype", "float32").lower()
                .replace("fp", "float")))
            inputs[rec["name"]] = arr.reshape(rec["shape"])
        if verb == "generate":
            sess = repo.get(name)      # unknown model -> 404
            p = doc.get("parameters", {})
            missing = [k for k in ("prompt_len",
                                   "max_new_tokens") if k not in p]
            if missing or "input_ids" not in inputs:
                return 400, {
                    "error": "generate needs inputs.input_ids "
                             f"and parameters {missing or ''}"}
            eos = p.get("eos_token_id")
            top_k = int(p.get("top_k", 0))
            top_p = float(p.get("top_p", 1.0))
            temp = float(p.get("temperature", 0.0))
            num_beams = int(p.get("num_beams", 1))
            if not (0.0 < top_p <= 1.0) or top_k < 0 \
                    or temp < 0.0 or num_beams < 1:
                return 400, {
                    "error": "need 0 < top_p <= 1, top_k >= 0, "
                             "temperature >= 0, num_beams >= 1"}
            pl = p["prompt_len"]
            out = sess.generate(
                inputs["input_ids"],
                prompt_len=(np.asarray(pl, np.int32)
                            if isinstance(pl, list) else int(pl)),
                max_new_tokens=int(p["max_new_tokens"]),
                temperature=temp,
                seed=int(p.get("seed", 0)),
                eos_token_id=None if eos is None else int(eos),
                top_k=top_k, top_p=top_p, num_beams=num_beams)
            return 200, {"outputs": [{
                "name": "output_ids", "shape": list(out.shape),
                "data": np.asarray(out, np.int32).ravel().tolist()}]}
        sched = schedulers.get(name)
        out = sched.infer(inputs) if sched is not None \
            else repo.get(name).infer(inputs)
        return 200, {"outputs": [{
            "name": "output0", "shape": list(out.shape),
            "data": np.asarray(out, np.float32).ravel().tolist()}]}
    except KeyError as e:
        return 404, {"error": str(e)}
    except QueueFullError as e:
        # bounded-queue backpressure: shed load explicitly
        return 503, {"error": str(e)}
    except Exception as e:  # noqa: BLE001 — report, don't die
        return 400, {"error": f"{type(e).__name__}: {e}"}


def _make_handler(repo, schedulers):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):  # quiet
            pass

        def _send(self, code: int, obj):
            body, ctype = render_body(obj)
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            self._send(*get_route(self.path, repo, schedulers))

        def do_POST(self):
            try:
                n = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(n)
            except (ValueError, OSError) as e:
                return self._send(400, {"error": f"bad request: {e}"})
            self._send(*post_route(self.path, body, repo, schedulers))

    return Handler


def serve_http(repo, host: str = "127.0.0.1", port: int = 8000,
               batching: bool = True, block: bool = True,
               max_batch: int = 64, max_delay_ms: float = 2.0,
               max_queue: int = 256):
    """Serve a :class:`ModelRepository`. ``block=False`` returns the
    (server, thread, schedulers) triple for in-process testing. Each
    model's scheduler drains a bounded queue (``max_queue``; overflow =
    HTTP 503) with one worker per registered instance."""
    from .scheduler import BatchScheduler
    schedulers = {}
    if batching:
        for name in repo.names():
            schedulers[name] = BatchScheduler(
                repo.get_instances(name), max_batch=max_batch,
                max_delay_ms=max_delay_ms, max_queue=max_queue,
                name=name)
    srv = ThreadingHTTPServer((host, port), _make_handler(repo, schedulers))
    if block:
        try:
            srv.serve_forever()
        finally:
            for s in schedulers.values():
                s.close()
        return None
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    return srv, t, schedulers
