"""Dependency-free HTTP frontend speaking the Triton KServe-style API.

Endpoints (JSON bodies, shapes row-major):
  - ``GET  /v2/health/ready``            -> 200 when serving, 503 while
    draining (k8s readiness semantics)
  - ``GET  /healthz``                    -> 200 {"status": "ok"} (probe
    alias — what k8s-style liveness checks expect); carries the
    resilience block AND a per-model serving block (circuit-breaker
    state, queue depth, draining)
  - ``GET  /v2/models``                  -> {"models": [names]}
  - ``GET  /v2/metrics``                 -> per-model scheduler counters
    (requests/completed/rejected/expired/deadline-rejected, queue
    depth, circuit state, mean batch rows, sketch latency quantiles
    p50/p90/p99/p99.9 ms overall and per batch bucket, SLO violations,
    instances)
  - ``GET  /metrics``                    -> Prometheus text exposition
    (request-latency histograms, queue-depth + circuit-state gauges,
    request counters — the ``obs/metrics_registry.py`` registry;
    scrape-ready)
  - ``POST /v2/models/<name>/infer``     -> {"outputs": [{"data", "shape"}]}
    body: {"inputs": [{"name": ..., "shape": [...], "data": [flat]}]};
    optional ``x-ff-timeout-ms`` header sets the request deadline.
    Load shedding (bounded queue, admission control, circuit open,
    draining) -> 503 + ``Retry-After``; a missed deadline -> 504;
    malformed inputs -> 400
  - ``POST /v2/models/<name>/generate``  -> {"outputs": [{"name":
    "output_ids", ...}]} — causal-LM decode; body adds
    {"parameters": {"prompt_len", "max_new_tokens", "temperature", "top_k", "top_p",
    "seed", "eos_token_id"}}
  - ``POST /v2/repository/models/<name>/unload`` -> remove a model

Reference analog: the Triton backend's HTTP surface
(``/root/reference/triton/README.md``); stdlib-only so it runs anywhere
the framework does. Deadline/admission/breaker/drain semantics:
docs/serving.md.

Request tracing: when ``obs.events`` is enabled every inference POST
carries a trace id — the client's ``x-ff-trace-id`` header or a
generated one, echoed back on the response — and its lifecycle
(admission -> queue -> batch -> prefill -> decode -> response) lands as
linked spans in the trace ring (docs/observability.md).
"""
from __future__ import annotations

import json
import math
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional

import numpy as np

from ..obs import events as obs_events
from ..obs import request_trace
from ..obs.metrics_registry import REGISTRY
from .scheduler import (CIRCUIT_STATE_NUM, InvalidInputError,
                        RequestRejected)


class ServingState:
    """Shared per-server lifecycle state (one per front): ``draining``
    flips readiness to 503 and rejects new inference work with 503 +
    ``Retry-After`` while in-flight requests finish. The in-flight
    counter tracks HTTP requests between parse and response-written so
    a drain can wait for the RESPONSES to flush, not just for the
    schedulers to go idle (the asyncio front's write happens after the
    scheduler completes — stopping the loop in that window would reset
    the client of an already-successful request)."""

    def __init__(self, default_deadline_ms: Optional[float] = None):
        self.draining = False
        # the front's configured default deadline: the batching
        # scheduler applies its own copy, but the uncancellable paths
        # (generate, batching=False) need it for the post-hoc 504
        self.default_deadline_ms = default_deadline_ms
        self._inflight = 0
        self._lock = threading.Lock()

    def enter_request(self):
        with self._lock:
            self._inflight += 1

    def exit_request(self):
        with self._lock:
            self._inflight -= 1

    def inflight(self) -> int:
        with self._lock:
            return self._inflight


def drain_frontend(schedulers, state: ServingState,
                   deadline_s: float) -> bool:
    """Shared drain policy for both fronts: stop admitting (readiness
    -> 503, new inference work -> 503 + ``Retry-After``), drain every
    scheduler, then wait for the in-flight RESPONSES to flush — the
    schedulers going idle is not the end of a request; killing the
    process before the handler writes the response would reset the
    client of already-successful work. Returns True when nothing was
    abandoned."""
    state.draining = True
    end = time.perf_counter() + max(0.0, deadline_s)
    clean = True
    # snapshot: a concurrent unload request pops from the live dict
    for s in list(schedulers.values()):
        clean &= s.drain(max(0.0, end - time.perf_counter()))
    # one observation of 0 is enough: admitted work has flushed, and
    # anything arriving after the draining flip is shed — re-reading
    # the counter at the end would let a late shed 503 (counted only
    # until its response is written) spuriously report work abandoned
    while time.perf_counter() < end:
        if state.inflight() == 0:
            return clean
        time.sleep(0.005)
    return clean and state.inflight() == 0


def render_body(obj):
    """Encode a route result body: dict -> JSON, str -> pre-rendered
    plain text (the Prometheus exposition). Returns ``(bytes, ctype)``;
    shared by the threading and asyncio front-ends so the content-type
    policy cannot drift between them."""
    if isinstance(obj, str):
        return obj.encode(), "text/plain; version=0.0.4; charset=utf-8"
    return json.dumps(obj).encode(), "application/json"


def _retry_after(e: RequestRejected) -> Dict[str, str]:
    """Retry-After header for a shedding rejection: integer seconds
    (HTTP spec), at least 1."""
    return {"Retry-After": str(max(1, math.ceil(e.retry_after_s)))}


def _past_deadline(t0: float, timeout_ms: Optional[float]):
    """Post-hoc deadline check for the two UNCANCELLABLE paths
    (generate, batching=False): the work already ran, but a completion
    past the declared deadline must be a 504, not a misleadingly-late
    200. Returns the 504 response tuple, or None within budget."""
    if timeout_ms is not None and \
            (time.perf_counter() - t0) * 1e3 > timeout_ms:
        return 504, {"error": "request deadline "
                              f"({timeout_ms:.0f} ms) exceeded"}, {}
    return None


def render_prometheus(schedulers) -> str:
    """Prometheus text for ``GET /metrics``: the process-wide registry
    plus point-in-time gauges (queue depth, instances, circuit state)
    sampled at scrape time from the live schedulers.

    The registry is process-wide by design (all fronts' request
    counters/histograms merge into one namespace); the point-in-time
    gauges reflect the schedulers of the server front that was scraped,
    so a process running MULTIPLE fronts should scrape one of them —
    the standard one-server-per-process deployment is unaffected."""
    live = list(schedulers.items())
    # atomic re-sample from live state: rows for models unloaded since
    # the last scrape disappear, and a concurrent scrape never observes
    # a half-populated row set
    REGISTRY.gauge("ff_queue_depth",
                   "Requests waiting in the bounded queue").set_all(
        ({"model": name}, sched._q.qsize()) for name, sched in live)
    REGISTRY.gauge("ff_scheduler_instances",
                   "Model instances draining the queue").set_all(
        ({"model": name}, sched.num_instances) for name, sched in live)
    REGISTRY.gauge("ff_circuit_state",
                   "Per-model circuit-breaker state: 0 closed, "
                   "1 half-open, 2 open").set_all(
        ({"model": name},
         CIRCUIT_STATE_NUM.get(sched.breaker.state, 0.0))
        for name, sched in live)
    qrows = []
    for name, sched in live:
        qrows.extend(sched.metrics.quantile_rows())
    REGISTRY.gauge(
        "ff_request_latency_quantile",
        "Streaming-sketch request latency quantiles (seconds) by "
        "model, batch bucket ('all' = every bucket), and quantile"
    ).set_all(qrows)
    return REGISTRY.render()


def get_route(path: str, repo, schedulers, state: Optional[ServingState]
              = None):
    """Route one GET; returns ``(status, obj, extra_headers)`` where
    ``obj`` is a JSON document (dict) or pre-rendered plain text (str —
    the Prometheus exposition). Shared by the threading and asyncio
    front-ends (the request counter lives here for the same reason: one
    counting policy, both fronts)."""
    obs_events.counter("serving.http_requests")
    if path in ("/v2/health/ready", "/healthz"):
        # resilience block (resilience/status.py): restart/fault/
        # checkpoint facts + checkpoint age, so a probe can alert on
        # "restarting in a loop" or "checkpoints stale" — both
        # invisible to a bare 200. The serving block adds per-model
        # circuit-breaker and drain state for the same reason.
        from ..resilience import status as resilience_status
        draining = bool(state is not None and state.draining)
        serving = {}
        # cheap fields only — probes fire every few seconds; the
        # latency block is a sketch walk (O(bins), no sort, bounded
        # bins), unlike the full stats() snapshot
        for name, sched in list(schedulers.items()):
            serving[name] = {"circuit": sched.breaker.state,
                             "queue_depth": sched._q.qsize(),
                             "draining": sched._draining,
                             # routing/scaling signals for a fleet
                             # front: the admission-control EWMA and
                             # the SLO counter it differentiates
                             "estimated_wait_s":
                                 sched.estimated_wait_s(),
                             "slo_violations":
                                 sched.metrics.slo_total(),
                             "latency_ms":
                                 sched.metrics.latency_quantiles()}
            # KV-decode fallback state (satellite of the serving-plan
            # work): a model quietly riding the O(L)-per-token
            # re-forward path is a live perf regression a probe should
            # see — count + the exact (batch, seq) shapes that failed
            try:
                ff = repo.get(name).ff
                ex = getattr(ff, "executor", None)
                shapes = sorted(getattr(ex, "_kv_failed_shapes", ())
                                or ())
                serving[name]["kv_fallback"] = {
                    "count": int(getattr(ff, "_kv_fallback_count", 0)),
                    "failed_shapes": [list(s) for s in shapes]}
            except Exception:  # noqa: BLE001 — non-FF session (tests)
                pass
        body = {"status": "draining" if draining else "ok",
                "ready": not draining,
                "resilience": resilience_status.health_fields(),
                "serving": serving,
                # trace-recorder health: silent ring overflow was
                # invisible before — a probe can now alert on a dropping
                # recorder; the flight-record pointer rides in the
                # resilience block (last_flight_record)
                "trace": {"enabled": obs_events.enabled(),
                          "events_dropped": obs_events.dropped()}}
        # READINESS flips to 503 while draining (stop routing here);
        # LIVENESS (/healthz) must stay 200 — the process is alive and
        # finishing work, and a k8s liveness kill would abort exactly
        # the in-flight requests the drain protects
        code = 503 if draining and path == "/v2/health/ready" else 200
        return code, body, {}
    if path == "/metrics":
        return 200, render_prometheus(schedulers), {}
    if path == "/v2/models":
        return 200, {"models": repo.names()}, {}
    if path == "/v2/metrics":
        # per-model scheduler counters + latency percentiles
        # (Triton's /metrics endpoint, prometheus-lite as JSON)
        out = {}
        # snapshot: a concurrent unload may pop from schedulers
        for name, sched in list(schedulers.items()):
            out[name] = sched.stats()
        return 200, {"models": out}, {}
    return 404, {"error": f"no route {path}"}, {}


#: HTTP status -> trace outcome, the COARSE fallback mapping for the
#: direct (non-scheduler) paths; the scheduler's precise outcome is
#: latched first and wins (RequestTrace.finish is idempotent)
_OUTCOME_BY_STATUS = {200: "ok", 400: "invalid", 404: "invalid",
                      503: "rejected", 504: "expired"}


def post_route(path: str, body: bytes, repo, schedulers,
               headers: Optional[Dict[str, str]] = None,
               state: Optional[ServingState] = None):
    """Route one POST (BLOCKING — the batching scheduler's ``infer``
    waits for the result; the asyncio front runs this in a thread
    pool). Returns ``(status, json_obj, extra_headers)``.

    Inference routes get a lifecycle trace (``obs.request_trace``) when
    tracing is enabled: the client's ``x-ff-trace-id`` is honored (and
    echoed on the response), the terminal outcome lands on the trace's
    response span, and the trace is the thread's ambient one for the
    duration so deep layers (generate's prefill/decode spans) link into
    it."""
    obs_events.counter("serving.http_requests")
    parts = path.strip("/").split("/")
    # v2/repository/models/<name>/unload (Triton repository API)
    if len(parts) == 5 and parts[:3] == ["v2", "repository", "models"] \
            and parts[4] == "unload":
        try:
            repo.unload(parts[3])
            sched = schedulers.pop(parts[3], None)
            if sched is not None:
                sched.close()
            return 200, {"unloaded": parts[3]}, {}
        except KeyError as e:
            return 404, {"error": str(e)}, {}
    # v2/models/<name>/{infer,generate}
    if len(parts) != 4 or parts[:2] != ["v2", "models"] \
            or parts[3] not in ("infer", "generate"):
        return 404, {"error": f"no route {path}"}, {}
    name, verb = parts[2], parts[3]
    hdrs = {str(k).lower(): v for k, v in (headers or {}).items()}
    trace = request_trace.from_headers(hdrs, model=name)
    status, obj, extra = _model_route(verb, name, body, repo,
                                      schedulers, hdrs, state, trace)
    if trace is not None:
        # fallback finish for paths that never reached the scheduler
        # (generate, direct infer, parse errors) — a no-op when the
        # scheduler already latched the precise outcome
        trace.finish(_OUTCOME_BY_STATUS.get(status, "failed"),
                     status=status)
        extra = dict(extra)
        extra[request_trace.TRACE_HEADER] = trace.trace_id
    return status, obj, extra


def _model_route(verb: str, name: str, body: bytes, repo, schedulers,
                 hdrs: Dict[str, str], state: Optional[ServingState],
                 trace):
    """The infer/generate route body behind :func:`post_route`'s trace
    bracketing."""
    if state is not None and state.draining:
        # graceful drain: readiness already flipped; in-flight work
        # finishes but nothing new is admitted
        return 503, {"error": "server draining; retry against another "
                              "replica"}, {"Retry-After": "5"}
    timeout_ms = None
    if "x-ff-timeout-ms" in hdrs:
        try:
            timeout_ms = float(hdrs["x-ff-timeout-ms"])
        except ValueError:
            return 400, {"error": "bad x-ff-timeout-ms header: "
                                  f"{hdrs['x-ff-timeout-ms']!r}"}, {}
        if not (timeout_ms > 0 and math.isfinite(timeout_ms)):
            # inf passes a bare '> 0' check and would overflow the
            # scheduler's Event.wait; nan fails every comparison
            return 400, {"error": "x-ff-timeout-ms must be a finite "
                                  f"positive number, got {timeout_ms}"}, {}
    # effective deadline + start reference for the direct
    # (non-scheduler) paths, where the work cannot be shed or
    # preempted — only 504'd after the fact; the front's configured
    # default applies to headerless requests there too
    eff_ms = timeout_ms
    if eff_ms is None and state is not None:
        eff_ms = state.default_deadline_ms
    t0 = time.perf_counter()
    # ambient-trace bracket around the whole verb body — manual
    # enter/exit so the long-standing try/except chain below keeps its
    # indentation; the finally below is the matching exit
    _ambient = request_trace.activate(trace)
    _ambient.__enter__()
    try:
        doc = json.loads(body)
        inputs = {}
        for rec in doc["inputs"]:
            arr = np.asarray(rec["data"], dtype=np.dtype(
                rec.get("datatype", "float32").lower()
                .replace("fp", "float")))
            inputs[rec["name"]] = arr.reshape(rec["shape"])
        if trace is not None:
            # admission span: JSON parse + tensor assembly + (for
            # generate) parameter validation happen between t0 and the
            # dispatch into the scheduler/session
            trace.stage("admission", t0, verb=verb,
                        rows=(int(next(iter(inputs.values())).shape[0])
                              if inputs else 0))
        if verb == "generate":
            sess = repo.get(name)      # unknown model -> 404
            p = doc.get("parameters", {})
            missing = [k for k in ("prompt_len",
                                   "max_new_tokens") if k not in p]
            if missing or "input_ids" not in inputs:
                return 400, {
                    "error": "generate needs inputs.input_ids "
                             f"and parameters {missing or ''}"}, {}
            eos = p.get("eos_token_id")
            top_k = int(p.get("top_k", 0))
            top_p = float(p.get("top_p", 1.0))
            temp = float(p.get("temperature", 0.0))
            num_beams = int(p.get("num_beams", 1))
            if not (0.0 < top_p <= 1.0) or top_k < 0 \
                    or temp < 0.0 or num_beams < 1:
                return 400, {
                    "error": "need 0 < top_p <= 1, top_k >= 0, "
                             "temperature >= 0, num_beams >= 1"}, {}
            pl = p["prompt_len"]
            out = sess.generate(
                inputs["input_ids"],
                prompt_len=(np.asarray(pl, np.int32)
                            if isinstance(pl, list) else int(pl)),
                max_new_tokens=int(p["max_new_tokens"]),
                temperature=temp,
                seed=int(p.get("seed", 0)),
                eos_token_id=None if eos is None else int(eos),
                top_k=top_k, top_p=top_p, num_beams=num_beams)
            late = _past_deadline(t0, eff_ms)
            if late is not None:
                # late completion on the uncancellable generate path:
                # count the SLO violation on THIS replica's counter —
                # a fleet router forwarding remaining deadlines relies
                # on the replica owning this count (it only accounts
                # requests no replica attempt ever carried)
                sched = schedulers.get(name)
                if sched is not None:
                    sched.metrics.record_slo()
                return late
            return 200, {"outputs": [{
                "name": "output_ids", "shape": list(out.shape),
                "data": np.asarray(out, np.int32).ravel().tolist()}]}, {}
        sched = schedulers.get(name)
        if sched is not None:
            # a deadline BEYOND the default 30 s blocking timeout —
            # header-declared or the scheduler's configured default —
            # must extend the wait, or a 60 s deadline 504s at 30 s
            # with half its budget left
            dl_ms = timeout_ms if timeout_ms is not None \
                else sched.default_deadline_ms
            wait_s = 30.0 if dl_ms is None else max(30.0, dl_ms / 1e3)
            out = sched.infer(inputs, timeout=wait_s,
                              deadline_ms=timeout_ms, trace=trace)
        else:
            out = repo.get(name).infer(inputs)
            late = _past_deadline(t0, eff_ms)
            if late is not None:
                return late
        return 200, {"outputs": [{
            "name": "output0", "shape": list(out.shape),
            "data": np.asarray(out, np.float32).ravel().tolist()}]}, {}
    except KeyError as e:
        return 404, {"error": str(e)}, {}
    except InvalidInputError as e:
        # malformed request (schema mismatch): a client error for THIS
        # request only — co-batched requests are unaffected
        return 400, {"error": str(e)}, {}
    except RequestRejected as e:
        # load shedding (queue full, admission control, circuit open,
        # draining): explicit 503 with a retry hint
        return 503, {"error": str(e)}, _retry_after(e)
    except TimeoutError as e:
        # deadline exceeded (queued too long or executed too late)
        return 504, {"error": f"{type(e).__name__}: {e}"}, {}
    except Exception as e:  # noqa: BLE001 — report, don't die
        return 400, {"error": f"{type(e).__name__}: {e}"}, {}
    finally:
        _ambient.__exit__(None, None, None)


def _make_handler(repo, schedulers, state):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):  # quiet
            pass

        def _send(self, code: int, obj, extra: Optional[Dict] = None):
            body, ctype = render_body(obj)
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            for k, v in (extra or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        # POSTs bracket the RESPONSE write in the in-flight counter:
        # handler threads are daemons the server never joins
        # (socketserver._Threads skips daemon threads), so drain()
        # must count them itself or a process exit right after
        # drain() kills a thread mid-write. GETs (health probes,
        # metrics scrapes) are NOT counted — losing one mid-write is
        # harmless, and counting them would let monitoring traffic
        # flake a clean drain

        def do_GET(self):
            self._send(*get_route(self.path, repo, schedulers, state))

        def do_POST(self):
            state.enter_request()
            try:
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    body = self.rfile.read(n)
                except (ValueError, OSError) as e:
                    return self._send(400,
                                      {"error": f"bad request: {e}"})
                self._send(*post_route(self.path, body, repo,
                                       schedulers,
                                       dict(self.headers.items()),
                                       state))
            finally:
                state.exit_request()

    return Handler


class HttpServerHandle(tuple):
    """The ``(server, thread, schedulers)`` triple ``serve_http`` has
    always returned (tuple unpacking keeps working), plus lifecycle
    methods: ``drain()`` for graceful shutdown, ``stop()`` for an
    immediate one."""

    def __new__(cls, srv, thread, schedulers, state):
        self = super().__new__(cls, (srv, thread, schedulers))
        self.state = state
        return self

    @property
    def server(self):
        return self[0]

    @property
    def thread(self):
        return self[1]

    @property
    def schedulers(self):
        return self[2]

    def drain(self, deadline_s: float = 10.0) -> bool:
        """Graceful drain: flip ``/v2/health/ready`` to 503, reject new
        inference work with 503 + ``Retry-After``, finish in-flight
        requests (responses written included) within ``deadline_s``,
        then close the schedulers and the listener. Returns True when
        nothing was abandoned."""
        clean = drain_frontend(self[2], self.state, deadline_s)
        self[0].shutdown()
        self[0].server_close()     # refuse (not hang) new connections
        return clean

    def stop(self):
        """Immediate shutdown: close the listener, fail queued work."""
        self[0].shutdown()
        self[0].server_close()
        for s in list(self[2].values()):
            s.close()


def serve_http(repo, host: str = "127.0.0.1", port: int = 8000,
               batching: bool = True, block: bool = True,
               max_batch: int = 64, max_delay_ms: float = 2.0,
               max_queue: int = 256,
               default_deadline_ms: Optional[float] = None,
               breaker_threshold: int = 5,
               breaker_cooldown_s: float = 5.0,
               admission_estimate: str = "wait"):
    """Serve a :class:`ModelRepository`. ``block=False`` returns an
    :class:`HttpServerHandle` (unpacks as the ``(server, thread,
    schedulers)`` triple for in-process testing; adds ``drain()``/
    ``stop()``). Each model's scheduler drains a bounded queue
    (``max_queue``; overflow = HTTP 503) with one worker per registered
    instance; ``default_deadline_ms`` applies to requests without an
    ``x-ff-timeout-ms`` header, and ``breaker_threshold``/
    ``breaker_cooldown_s`` configure the per-model circuit breaker.
    ``admission_estimate`` is forwarded to each
    :class:`~flexflow_tpu.serving.scheduler.BatchScheduler` — fleet
    replicas pass ``"completion"`` so deadline shedding predicts the
    full request latency, not just the queue wait."""
    from .scheduler import BatchScheduler
    schedulers = {}
    state = ServingState(default_deadline_ms=default_deadline_ms)
    if batching:
        for name in repo.names():
            schedulers[name] = BatchScheduler(
                repo.get_instances(name), max_batch=max_batch,
                max_delay_ms=max_delay_ms, max_queue=max_queue,
                name=name, default_deadline_ms=default_deadline_ms,
                breaker_threshold=breaker_threshold,
                breaker_cooldown_s=breaker_cooldown_s,
                admission_estimate=admission_estimate)
    srv = ThreadingHTTPServer((host, port),
                              _make_handler(repo, schedulers, state))
    if block:
        try:
            srv.serve_forever()
        finally:
            for s in schedulers.values():
                s.close()
        return None
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    return HttpServerHandle(srv, t, schedulers, state)
