"""Multi-replica routing front: least-estimated-wait, deadline-true.

One ``BatchScheduler`` process caps goodput at one device mesh and
makes every restart a full outage. The router puts N replica serving
processes (each its own scheduler + HTTP front, spawned by
:mod:`.replica` or adopted by URL) behind one endpoint:

* **Routing signal**: each replica's own admission-control EWMA —
  ``estimated_wait_s`` polled from ``/healthz`` — plus its circuit
  state. Requests go to the live, non-draining replica with the least
  estimated wait; breaker-open replicas are skipped entirely (their
  503s are *predictable*, so routing around them is free).
* **Failover**: a dead replica (transport error, hard crash) or a 503
  shed fails over to the next candidate while deadline budget remains.
* **Deadline truth**: the hop forwards the *remaining* budget
  (``x-ff-timeout-ms`` minus elapsed) — a router hop must never extend
  a request's deadline. SLO accounting stays deduplicated: a replica
  that received the remaining deadline counts its own violation
  (completed-late / expired / deadline-rejected), so the fleet layer
  counts ``ff_fleet_slo_violations_total`` ONLY for requests no
  replica attempt ever carried — expired in the router or dead on
  every transport. Fleet violations = Σ replica counters + the fleet
  counter, each violation counted exactly once.
* **Traces**: ``x-ff-trace-id`` propagates across the hop (minting one
  if the client sent none), so replica-side lifecycle traces link into
  the same fleet request in ``fftrace``.

Fleet ``/v2/metrics`` scrapes every replica and merges their latency
sketches with ``QuantileSketch.merge`` — fleet p99 is computed over
the union stream, never averaged across replicas.
"""
from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Sequence

from ...obs.metrics_registry import REGISTRY
from ...obs.request_trace import TRACE_HEADER
from ...obs.sketch import QuantileSketch

#: consecutive failed health polls after which a replica is routed
#: around (and eligible for autoscaler replacement)
DEAD_AFTER = 3

_ROUTED = REGISTRY.counter(
    "ff_fleet_requests_total", "requests routed, by replica")
_FAILOVERS = REGISTRY.counter(
    "ff_fleet_failovers_total",
    "requests re-dispatched after a replica transport failure or shed")
_FLEET_SLO = REGISTRY.counter(
    "ff_fleet_slo_violations_total",
    "deadline violations accounted at the FLEET layer: requests that "
    "expired before any replica attempt carried the remaining "
    "deadline, or whose every transport died. Disjoint from the "
    "replicas' own ff_slo_violations_total by construction")
_REPLICAS_G = REGISTRY.gauge(
    "ff_fleet_replicas", "replicas known to the router, by state")
_TTR = REGISTRY.gauge(
    "ff_replica_time_to_ready_seconds",
    "spawn -> first passing health poll, by replica (warm compile "
    "cache is what keeps this flat as the fleet scales)")


class NoReplicaAvailableError(RuntimeError):
    """No live, non-draining, breaker-closed replica to route to."""


def free_port() -> int:
    """An OS-assigned free TCP port (the standard bind-0 probe; the
    tiny race with another binder is acceptable for tests/smokes)."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return int(s.getsockname()[1])


class Replica:
    """One serving process behind the router: its URL, the child
    process when the router spawned it (adopted replicas have none),
    and the router's latest view of its health.

    Health fields are guarded by the owning router's lock — the
    poller writes them, ``pick``/``healthz`` read them."""

    def __init__(self, name: str, url: str,
                 proc: Optional[subprocess.Popen] = None):
        self.name = name
        self.url = url.rstrip("/")
        self.proc = proc
        self.spawned_at = time.monotonic()
        # guarded by FleetRouter._lock:
        self.health: Optional[Dict] = None
        self.consecutive_errors = 0
        self.ready_at: Optional[float] = None
        self.draining = False
        self.retired = False

    def alive_locked(self) -> bool:
        if self.proc is not None and self.proc.poll() is not None:
            return False
        return self.consecutive_errors < DEAD_AFTER \
            and self.health is not None


class FleetRouter:
    """Routes requests across replicas; owns the health-poll loop and
    (optionally) the replica child processes.

    ``spawn_argv`` is the replica launch template — a list of argv
    strings where the literal ``"{port}"`` and ``"{name}"`` are
    substituted per spawn (see :mod:`.replica` for the worker CLI).
    ``spawn_env`` overlays ``os.environ`` for every spawned child;
    ``spawn`` accepts a per-replica ``extra_env`` on top (the fault
    plan that kills exactly one replica in the chaos smoke)."""

    def __init__(self, spawn_argv: Optional[Sequence[str]] = None,
                 spawn_env: Optional[Dict[str, str]] = None,
                 poll_interval_s: float = 0.25,
                 connect_timeout_s: float = 3.0,
                 request_timeout_s: float = 120.0,
                 startup_grace_s: float = 180.0,
                 default_deadline_ms: Optional[float] = None):
        self.spawn_argv = list(spawn_argv) if spawn_argv else None
        self.spawn_env = dict(spawn_env or {})
        self.poll_interval_s = float(poll_interval_s)
        # connect_timeout_s bounds the cheap-by-contract control-plane
        # GETs (/healthz, /v2/metrics); request_timeout_s bounds a
        # forwarded request that carries NO deadline — generate can
        # legitimately run long (first-call compiles, long decodes)
        self.connect_timeout_s = float(connect_timeout_s)
        self.request_timeout_s = float(request_timeout_s)
        # how long a spawned replica may fail health polls before its
        # cold start is declared wedged (see retire_dead)
        self.startup_grace_s = float(startup_grace_s)
        self.default_deadline_ms = default_deadline_ms
        self._lock = threading.Lock()
        # guarded by _lock:
        self._replicas: List[Replica] = []
        self._seq = 0
        self._rr = 0  # round-robin cursor for tied-wait candidates
        self._stats = {"routed": 0, "failovers": 0,
                       "fleet_slo_violations": 0, "no_replica": 0}
        self._stop = threading.Event()
        self._poller = threading.Thread(
            target=self._poll_loop, name="ff-fleet-health", daemon=True)
        self._poller.start()

    # -- replica lifecycle -----------------------------------------

    def adopt(self, url: str, name: Optional[str] = None) -> Replica:
        """Route to an already-running serving process by URL (no
        child handle: the router cannot drain or replace it)."""
        with self._lock:
            self._seq += 1
            r = Replica(name or f"replica-{self._seq}", url)
            self._replicas.append(r)
        self.poll_once(r)
        return r

    def spawn(self, name: Optional[str] = None,
              extra_env: Optional[Dict[str, str]] = None,
              port: Optional[int] = None) -> Replica:
        """Launch one replica child from ``spawn_argv`` and start
        routing to it once its first health poll passes."""
        if not self.spawn_argv:
            raise NoReplicaAvailableError(
                "router has no spawn_argv template; adopt() replicas "
                "or construct with spawn_argv")
        with self._lock:
            self._seq += 1
            rname = name or f"replica-{self._seq}"
        rport = port if port is not None else free_port()
        argv = [a.replace("{port}", str(rport))
                 .replace("{name}", rname) for a in self.spawn_argv]
        env = dict(os.environ)
        env.update(self.spawn_env)
        env.update(extra_env or {})
        proc = subprocess.Popen(
            argv, env=env, stdin=subprocess.PIPE,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        r = Replica(rname, f"http://127.0.0.1:{rport}", proc)
        with self._lock:
            self._replicas.append(r)
        return r

    def drain(self, replica: Replica) -> None:
        """Graceful scale-down: stop routing to it, then ask the child
        to drain and exit (stdin protocol — see :mod:`.replica`)."""
        with self._lock:
            replica.draining = True
        if replica.proc is not None and replica.proc.stdin is not None:
            try:
                replica.proc.stdin.write(b"drain\n")
                replica.proc.stdin.flush()
            except (BrokenPipeError, OSError, ValueError):
                pass  # already dead — reap below

    def retire_dead(self) -> List[Replica]:
        """Drop replicas that are past ``DEAD_AFTER`` or whose process
        exited; returns them (the autoscaler's replacement signal).

        A spawned replica that has NEVER passed a health poll but
        whose process is still running is a cold start in progress,
        not a corpse — its connection-refused polls don't retire it
        until ``startup_grace_s`` has elapsed (a replacement compiling
        through the cache would otherwise be culled before its HTTP
        front even binds)."""
        dead: List[Replica] = []
        now = time.monotonic()
        with self._lock:
            keep = []
            for r in self._replicas:
                exited = r.proc is not None and r.proc.poll() is not None
                cold = (r.proc is not None and not exited
                        and r.ready_at is None)
                if cold and now - r.spawned_at <= self.startup_grace_s:
                    keep.append(r)
                    continue
                if exited or cold or (r.consecutive_errors >= DEAD_AFTER
                                      and r.health is None):
                    r.retired = True
                    dead.append(r)
                else:
                    keep.append(r)
            self._replicas = keep
        for r in dead:
            if r.proc is not None:
                if r.proc.poll() is None:
                    # wedged but running (grace expired / health-dead):
                    # reap it so retirement never leaks a process
                    r.proc.kill()
                try:
                    r.proc.wait(timeout=2.0)
                except subprocess.TimeoutExpired:
                    pass
        return dead

    def replicas(self) -> List[Replica]:
        with self._lock:
            return list(self._replicas)

    def close(self, drain_children: bool = True,
              timeout_s: float = 15.0) -> None:
        self._stop.set()
        self._poller.join(timeout=5.0)
        with self._lock:
            reps = list(self._replicas)
            self._replicas = []
        for r in reps:
            if r.proc is None:
                continue
            if drain_children:
                try:
                    if r.proc.stdin is not None:
                        r.proc.stdin.write(b"drain\n")
                        r.proc.stdin.flush()
                except (BrokenPipeError, OSError, ValueError):
                    pass
        deadline = time.monotonic() + timeout_s
        for r in reps:
            if r.proc is None:
                continue
            left = max(0.1, deadline - time.monotonic())
            try:
                r.proc.wait(timeout=left)
            except subprocess.TimeoutExpired:
                r.proc.kill()
                try:
                    r.proc.wait(timeout=5.0)
                except subprocess.TimeoutExpired:
                    pass

    # -- health ----------------------------------------------------

    def _http_json(self, url: str, timeout_s: float) -> Dict:
        with urllib.request.urlopen(url, timeout=timeout_s) as resp:
            return json.loads(resp.read().decode())

    def poll_once(self, replica: Replica) -> Optional[Dict]:
        """One health poll; updates the router's view. Returns the
        health document, or None on failure."""
        try:
            doc = self._http_json(replica.url + "/healthz",
                                  self.connect_timeout_s)
        except Exception:  # noqa: BLE001 — any transport/parse
            # failure counts one strike; DEAD_AFTER strikes = dead
            with self._lock:
                replica.consecutive_errors += 1
                if replica.consecutive_errors >= DEAD_AFTER:
                    replica.health = None
            return None
        first = False
        with self._lock:
            replica.consecutive_errors = 0
            replica.health = doc
            if replica.ready_at is None:
                replica.ready_at = time.monotonic()
                first = True
            if not doc.get("ready", True):
                replica.draining = True
        if first and replica.proc is not None:
            _TTR.set(replica.ready_at - replica.spawned_at,
                     replica=replica.name)
        return doc

    def _poll_loop(self) -> None:
        while not self._stop.is_set():
            for r in self.replicas():
                if self._stop.is_set():
                    break
                if r.proc is not None and r.proc.poll() is not None:
                    with self._lock:
                        r.health = None
                        r.consecutive_errors = DEAD_AFTER
                    continue
                self.poll_once(r)
            with self._lock:
                alive = sum(1 for r in self._replicas
                            if r.alive_locked())
                total = len(self._replicas)
            _REPLICAS_G.set(alive, state="alive")
            _REPLICAS_G.set(total - alive, state="down")
            self._stop.wait(timeout=self.poll_interval_s)

    # -- routing ---------------------------------------------------

    def candidates(self, model: str) -> List[Replica]:
        """Live, non-draining replicas that can serve ``model``,
        cheapest estimated wait first; breaker-open replicas excluded.
        Replicas whose waits tie (an idle fleet, or generate-only
        traffic that never moves the scheduler EWMA) rotate round-
        robin — a stable sort alone would convoy every request onto
        one replica."""
        scored = []
        with self._lock:
            for r in self._replicas:
                if r.draining or not r.alive_locked():
                    continue
                serving = (r.health or {}).get("serving", {})
                m = serving.get(model)
                if m is None:
                    continue
                if m.get("circuit") == "open" or m.get("draining"):
                    continue
                scored.append((float(m.get("estimated_wait_s", 0.0)),
                               r))
            self._rr += 1
            rr = self._rr
        scored.sort(key=lambda t: t[0])
        if len(scored) > 1:
            best = scored[0][0]
            ties = [r for w, r in scored if w - best < 1e-9]
            rest = [r for w, r in scored if w - best >= 1e-9]
            k = rr % len(ties)
            return ties[k:] + ties[:k] + rest
        return [r for _, r in scored]

    def forward(self, model: str, path: str, body: bytes,
                headers: Dict[str, str]):
        """Route one POST. Returns ``(status, body_bytes, headers)``.

        Deadline semantics: the origin deadline is fixed at ARRIVAL
        here; every replica attempt receives only the remaining
        budget. Failover (transport death, 503 shed) retries the next
        candidate while budget remains."""
        t0 = time.monotonic()
        hdrs = {k.lower(): v for k, v in headers.items()}
        deadline_ms: Optional[float] = None
        if "x-ff-timeout-ms" in hdrs:
            try:
                deadline_ms = float(hdrs["x-ff-timeout-ms"])
            except ValueError:
                return 400, json.dumps(
                    {"error": "bad x-ff-timeout-ms header: "
                              f"{hdrs['x-ff-timeout-ms']!r}"}
                ).encode(), {}
        elif self.default_deadline_ms is not None:
            deadline_ms = float(self.default_deadline_ms)
        trace_id = hdrs.get(TRACE_HEADER) or uuid.uuid4().hex[:16]

        tried: List[str] = []
        dispatched_with_deadline = False
        last_exc: Optional[str] = None
        while True:
            remaining_ms = None
            if deadline_ms is not None:
                remaining_ms = deadline_ms \
                    - (time.monotonic() - t0) * 1e3
                if remaining_ms <= 0.0:
                    # never *extend* the budget: expired at the fleet
                    # layer. SLO dedupe: count here ONLY if no replica
                    # attempt carried the remaining deadline (a replica
                    # that did will count its own late completion)
                    if not dispatched_with_deadline:
                        self._count_fleet_slo(model)
                    return 504, json.dumps(
                        {"error": "deadline exceeded in fleet router",
                         "tried": tried}).encode(), \
                        {TRACE_HEADER: trace_id}
            cands = [r for r in self.candidates(model)
                     if r.name not in tried]
            if not cands:
                with self._lock:
                    self._stats["no_replica"] += 1
                if deadline_ms is not None \
                        and not dispatched_with_deadline:
                    self._count_fleet_slo(model)
                detail = {"error": "no replica available for "
                                   f"model {model!r}",
                          "tried": tried}
                if last_exc:
                    detail["last_error"] = last_exc
                return 503, json.dumps(detail).encode(), \
                    {"Retry-After": "1", TRACE_HEADER: trace_id}
            replica = cands[0]
            tried.append(replica.name)
            fwd_headers = {"Content-Type": "application/json",
                           TRACE_HEADER: trace_id}
            if remaining_ms is not None:
                fwd_headers["x-ff-timeout-ms"] = \
                    f"{remaining_ms:.3f}"
            # socket timeout: the remaining budget plus slack for the
            # response bytes — a replica past the deadline answers 504
            # itself; the slack keeps US from abandoning a reply that
            # is already on the wire. Deadline-less requests get the
            # long request_timeout_s: a first generate may compile
            sock_t = self.request_timeout_s if remaining_ms is None \
                else max(0.05, remaining_ms / 1e3) + 2.0
            req = urllib.request.Request(
                replica.url + path, data=body, headers=fwd_headers,
                method="POST")
            try:
                if remaining_ms is not None:
                    dispatched_with_deadline = True
                with urllib.request.urlopen(req, timeout=sock_t) \
                        as resp:
                    out = resp.read()
                    with self._lock:
                        self._stats["routed"] += 1
                    _ROUTED.inc(replica=replica.name)
                    return resp.status, out, \
                        {TRACE_HEADER: trace_id}
            except urllib.error.HTTPError as e:
                out = e.read()
                if e.code == 503:
                    # shed (queue full / breaker / draining): another
                    # replica may have room — fail over
                    self._note_failover(replica)
                    last_exc = f"{replica.name}: 503 shed"
                    continue
                with self._lock:
                    self._stats["routed"] += 1
                _ROUTED.inc(replica=replica.name)
                return e.code, out, {TRACE_HEADER: trace_id}
            except (urllib.error.URLError, ConnectionError,
                    socket.timeout, TimeoutError) as e:
                reason = getattr(e, "reason", e)
                if not isinstance(reason,
                                  (socket.timeout, TimeoutError)):
                    # transport death — crashed replica; strike its
                    # health (the poller revives it if it recovers)
                    with self._lock:
                        replica.consecutive_errors = DEAD_AFTER
                        replica.health = None
                # a timed-out request means a SLOW replica, not a
                # dead one — death verdicts stay with the health
                # poller; either way, fail over to the next candidate
                self._note_failover(replica)
                last_exc = f"{replica.name}: {e}"
                continue

    def _note_failover(self, replica: Replica) -> None:
        with self._lock:
            self._stats["failovers"] += 1
        _FAILOVERS.inc()

    def _count_fleet_slo(self, model: str) -> None:
        with self._lock:
            self._stats["fleet_slo_violations"] += 1
        _FLEET_SLO.inc(model=model)

    # -- aggregation -----------------------------------------------

    def fleet_health(self) -> Dict:
        """The fleet ``/healthz`` document: per-replica state + a
        converged flag (every known replica polled healthy)."""
        reps = {}
        alive = 0
        with self._lock:
            for r in self._replicas:
                ok = r.alive_locked()
                alive += 1 if ok and not r.draining else 0
                reps[r.name] = {
                    "url": r.url,
                    "alive": ok,
                    "draining": r.draining,
                    "consecutive_errors": r.consecutive_errors,
                    "serving": (r.health or {}).get("serving", {}),
                }
            total = len(self._replicas)
            stats = dict(self._stats)
        converged = total > 0 and alive == total
        return {"status": "ok" if converged else "degraded",
                "ready": alive > 0,
                "converged": converged,
                "replicas": reps,
                "fleet": stats}

    def fleet_metrics(self) -> Dict:
        """The fleet ``/v2/metrics`` document: per-replica scheduler
        stats scraped live, plus per-model aggregates where counters
        sum and latency quantiles come from the MERGED sketches."""
        per_replica: Dict[str, Dict] = {}
        for r in self.replicas():
            with self._lock:
                ok = r.alive_locked() and not r.draining
            if not ok:
                continue
            try:
                doc = self._http_json(r.url + "/v2/metrics",
                                      self.connect_timeout_s)
            except Exception:  # noqa: BLE001 — a replica dying
                # mid-scrape degrades the view, never the endpoint
                continue
            per_replica[r.name] = doc.get("models", {})
        models = merge_replica_metrics(per_replica)
        with self._lock:
            stats = dict(self._stats)
        return {"models": models, "replicas": per_replica,
                "fleet": stats}


_SUM_FIELDS = ("requests", "completed", "failed", "rejected",
               "expired", "deadline_rejected", "breaker_opens",
               "slo_violations", "batches", "queue_depth")


def merge_replica_metrics(per_replica: Dict[str, Dict]) -> Dict:
    """Aggregate per-replica ``/v2/metrics`` model blocks: counters
    sum; latency quantiles are recomputed from the union of the
    replicas' serialized sketches (``QuantileSketch.merge`` — exact,
    not an average of percentiles). Pure so the merge path is unit-
    testable against single-stream ingestion."""
    models: Dict[str, Dict] = {}
    sketches: Dict[str, Dict[str, QuantileSketch]] = {}
    for rep_doc in per_replica.values():
        for model, stats in rep_doc.items():
            agg = models.setdefault(
                model, {f: 0 for f in _SUM_FIELDS})
            agg["replicas"] = agg.get("replicas", 0) + 1
            for f in _SUM_FIELDS:
                agg[f] += int(stats.get(f, 0))
            by_label = sketches.setdefault(model, {})
            for label, doc in (stats.get("sketches") or {}).items():
                sk = QuantileSketch.from_dict(doc)
                if label in by_label:
                    by_label[label].merge(sk)
                else:
                    by_label[label] = sk
    for model, by_label in sketches.items():
        q = {}
        for label, sk in sorted(by_label.items()):
            if not sk.count:
                continue
            q[label] = {"p50": round(sk.quantile(0.5) * 1e3, 3),
                        "p90": round(sk.quantile(0.9) * 1e3, 3),
                        "p99": round(sk.quantile(0.99) * 1e3, 3),
                        "p99.9": round(sk.quantile(0.999) * 1e3, 3)}
        models[model]["latency_ms"] = q
        models[model]["sketches"] = {
            label: sk.to_dict() for label, sk in by_label.items()}
    return models


# ---------------------------------------------------------------------------
# fleet HTTP front
# ---------------------------------------------------------------------------
def _make_fleet_handler(router: FleetRouter):
    class FleetHandler(BaseHTTPRequestHandler):
        # keep-alive: every response path goes through _send, which
        # always carries Content-Length, so clients under deadline
        # pressure can reuse connections instead of paying a TCP
        # setup (and a handler-thread spawn) per request. Nagle off:
        # a buffered small response must not wait out a delayed ACK
        protocol_version = "HTTP/1.1"
        disable_nagle_algorithm = True

        def log_message(self, *a):  # quiet
            pass

        def _send(self, code: int, payload: bytes,
                  extra: Optional[Dict[str, str]] = None):
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(payload)))
            for k, v in (extra or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(payload)

        def do_GET(self):
            if self.path in ("/healthz", "/v2/health/ready"):
                doc = router.fleet_health()
                code = 200
                if self.path == "/v2/health/ready" \
                        and not doc["ready"]:
                    code = 503
                self._send(code, json.dumps(doc).encode())
                return
            if self.path == "/v2/metrics":
                self._send(200,
                           json.dumps(router.fleet_metrics()).encode())
                return
            if self.path == "/v2/models":
                names = set()
                for r in router.replicas():
                    with router._lock:
                        serving = (r.health or {}).get("serving", {})
                    names.update(serving)
                self._send(200, json.dumps(
                    {"models": sorted(names)}).encode())
                return
            self._send(404, json.dumps(
                {"error": f"no route {self.path}"}).encode())

        def do_POST(self):
            parts = self.path.strip("/").split("/")
            # /v2/models/<name>/(infer|generate)
            if len(parts) == 4 and parts[:2] == ["v2", "models"] \
                    and parts[3] in ("infer", "generate"):
                n = int(self.headers.get("Content-Length", 0) or 0)
                body = self.rfile.read(n) if n else b""
                code, out, extra = router.forward(
                    parts[2], self.path, body, dict(self.headers))
                self._send(code, out, extra)
                return
            self._send(404, json.dumps(
                {"error": f"no route {self.path}"}).encode())

    return FleetHandler


class FleetHandle:
    """Running fleet front: the HTTP server, its thread, and the
    router (with its replica children)."""

    def __init__(self, server, thread, router: FleetRouter):
        self.server = server
        self.thread = thread
        self.router = router

    @property
    def port(self) -> int:
        return int(self.server.server_address[1])

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def stop(self, drain_children: bool = True) -> None:
        self.server.shutdown()
        self.server.server_close()
        self.thread.join(timeout=10.0)
        self.router.close(drain_children=drain_children)


def serve_fleet(router: FleetRouter, host: str = "127.0.0.1",
                port: int = 0) -> FleetHandle:
    """Start the fleet HTTP front (non-blocking); ``port=0`` picks a
    free port (read it back from ``handle.port``)."""
    srv = ThreadingHTTPServer((host, port),
                              _make_fleet_handler(router))
    t = threading.Thread(target=srv.serve_forever,
                         name="ff-fleet-http", daemon=True)
    t.start()
    return FleetHandle(srv, t, router)
