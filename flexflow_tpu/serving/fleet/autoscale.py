"""Signal-driven autoscaling over the replica fleet.

The control loop consumes exactly the signals the serving layer
already emits — the per-replica admission-control EWMA
(``estimated_wait_s``), queue depth, and ``ff_slo_violations_total``
deltas — and turns them into three actions:

* **repair**: a dead replica (crash, ``DEAD_AFTER`` failed polls) is
  replaced immediately up to ``min_replicas`` — the fleet's floor is
  an invariant, not a suggestion.
* **scale up**: predicted wait has exceeded the deadline band (or SLO
  violations are actively accruing) for ``sustain_polls`` consecutive
  ticks and the fleet is below ``max_replicas``. One spawn at a time:
  a pending (spawned, not yet ready) replica blocks further spawns so
  a slow cold start cannot stampede the fleet to max.
* **scale down**: the fleet has been idle (queue EWMA ~ 0, zero wait,
  zero SLO delta) for ``idle_polls`` ticks and sits above
  ``min_replicas`` — one spawned replica takes the graceful-drain
  path (finish in-flight, then exit).

New replicas come up warm through the persistent compile cache baked
into the spawn template; time-to-ready is recorded per replica by the
router's ``ff_replica_time_to_ready_seconds`` gauge, which is the
number that keeps autoscaling honest (a cold replica arriving after
the burst it was scaled for is capacity theater).

``decide`` is pure — (config, observed state) -> action — so the
policy is unit-testable without processes.
"""
from __future__ import annotations

import dataclasses
import threading
import time
from typing import Dict, List, Optional

from ...obs.metrics_registry import REGISTRY
from .router import FleetRouter, Replica

_ACTIONS = REGISTRY.counter(
    "ff_autoscaler_actions_total", "autoscaler actions, by kind")
_TARGET = REGISTRY.gauge(
    "ff_autoscaler_replicas", "autoscaler view of the fleet size")


@dataclasses.dataclass
class AutoscalerConfig:
    min_replicas: int = 1
    max_replicas: int = 4
    #: SLO band: scale up when the best replica's predicted wait
    #: exceeds ``wait_band_fraction`` of the deadline
    deadline_ms: float = 100.0
    wait_band_fraction: float = 0.8
    #: consecutive hot ticks before a scale-up
    sustain_polls: int = 2
    #: consecutive idle ticks before a scale-down
    idle_polls: int = 10
    poll_interval_s: float = 0.5
    #: queue-depth EWMA smoothing (weight of the newest sample)
    ewma_alpha: float = 0.3


def decide(cfg: AutoscalerConfig, alive: int, pending: int,
           hot_streak: int, idle_streak: int) -> str:
    """Pure scaling policy: ``repair`` | ``scale_up`` | ``scale_down``
    | ``hold``. ``alive`` counts ready routable replicas; ``pending``
    counts spawned-but-not-yet-ready ones (they block duplicate
    spawns but do not serve yet)."""
    if alive + pending < cfg.min_replicas:
        return "repair"
    if pending > 0:
        return "hold"  # one cold start in flight at a time
    if alive < cfg.max_replicas and hot_streak >= cfg.sustain_polls:
        return "scale_up"
    if alive > cfg.min_replicas and idle_streak >= cfg.idle_polls:
        return "scale_down"
    return "hold"


class Autoscaler:
    """Control loop bound to a :class:`FleetRouter` that owns spawn
    capability (``spawn_argv``). Call :meth:`start` after the initial
    fleet is up; :meth:`stop` before tearing the router down."""

    def __init__(self, router: FleetRouter,
                 cfg: Optional[AutoscalerConfig] = None):
        self.router = router
        self.cfg = cfg or AutoscalerConfig()
        self._lock = threading.Lock()
        # guarded by _lock:
        self._queue_ewma = 0.0
        self._hot_streak = 0
        self._idle_streak = 0
        self._last_slo_total: Optional[int] = None
        self._actions: List[Dict] = []
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="ff-autoscaler", daemon=True)

    def start(self) -> None:
        self._thread.start()

    def stop(self, timeout_s: float = 5.0) -> None:
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=timeout_s)

    def actions(self) -> List[Dict]:
        with self._lock:
            return list(self._actions)

    # -- signal gathering ------------------------------------------

    def _signals(self) -> Dict:
        """Read the router's cached health view (no extra HTTP): per
        model the MIN estimated wait across routable replicas (the
        wait the router can actually achieve), then the max over
        models (the binding constraint); plus summed queue depth and
        the fleet-wide SLO violation total."""
        alive = pending = 0
        queue_sum = 0
        slo_total = 0
        best_wait: Dict[str, float] = {}
        with self.router._lock:
            replicas = list(self.router._replicas)
            for r in replicas:
                ok = r.alive_locked()
                if r.draining:
                    continue
                if not ok:
                    if r.proc is not None and r.proc.poll() is None \
                            and r.ready_at is None:
                        pending += 1
                    continue
                alive += 1
                serving = (r.health or {}).get("serving", {})
                for model, m in serving.items():
                    w = float(m.get("estimated_wait_s", 0.0))
                    queue_sum += int(m.get("queue_depth", 0))
                    slo_total += int(m.get("slo_violations", 0))
                    if m.get("circuit") == "open":
                        # an open breaker is un-routable capacity:
                        # treat as infinite wait on this replica
                        continue
                    cur = best_wait.get(model)
                    if cur is None or w < cur:
                        best_wait[model] = w
        wait = max(best_wait.values()) if best_wait else 0.0
        return {"alive": alive, "pending": pending,
                "queue_sum": queue_sum, "wait_s": wait,
                "slo_total": slo_total}

    # -- loop ------------------------------------------------------

    def tick(self) -> str:
        """One control iteration (public so tests can step it
        deterministically without the thread)."""
        dead = self.router.retire_dead()
        sig = self._signals()
        cfg = self.cfg
        with self._lock:
            self._queue_ewma = ((1 - cfg.ewma_alpha) * self._queue_ewma
                                + cfg.ewma_alpha * sig["queue_sum"])
            prev = self._last_slo_total
            self._last_slo_total = sig["slo_total"]
            slo_delta = 0 if prev is None \
                else max(0, sig["slo_total"] - prev)
            band_s = cfg.deadline_ms / 1e3 * cfg.wait_band_fraction
            hot = sig["wait_s"] > band_s or slo_delta > 0
            idle = (self._queue_ewma < 0.5 and sig["wait_s"] == 0.0
                    and slo_delta == 0)
            self._hot_streak = self._hot_streak + 1 if hot else 0
            self._idle_streak = self._idle_streak + 1 if idle else 0
            hot_streak, idle_streak = \
                self._hot_streak, self._idle_streak
        action = decide(cfg, sig["alive"], sig["pending"],
                        hot_streak, idle_streak)
        if action in ("repair", "scale_up"):
            self.router.spawn()
            with self._lock:
                self._hot_streak = 0
                self._actions.append(
                    {"action": action, "t": time.monotonic(),
                     "dead": [r.name for r in dead],
                     "signals": sig})
            _ACTIONS.inc(kind=action)
        elif action == "scale_down":
            victim = self._pick_drain_victim()
            if victim is not None:
                self.router.drain(victim)
                with self._lock:
                    self._idle_streak = 0
                    self._actions.append(
                        {"action": action, "t": time.monotonic(),
                         "replica": victim.name, "signals": sig})
                _ACTIONS.inc(kind=action)
        _TARGET.set(sig["alive"] + sig["pending"])
        return action

    def _pick_drain_victim(self) -> Optional[Replica]:
        """Newest SPAWNED replica (adopted ones have no drain path)."""
        with self.router._lock:
            cands = [r for r in self.router._replicas
                     if r.proc is not None and not r.draining]
        if not cands:
            return None
        return max(cands, key=lambda r: r.spawned_at)

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — the control loop must
                # survive transient scrape/spawn errors; next tick
                # re-reads ground truth
                pass
            self._stop.wait(timeout=self.cfg.poll_interval_s)
