"""Iteration-level continuous batching for autoregressive decode.

The classic serving loss with static batching is convoy latency: a
4-token completion admitted next to a 512-token one waits out the whole
batch. Orca-style iteration-level scheduling fixes that by making the
decode loop — not the request — the batching unit: every
``decode_segment`` tokens (the lock-release boundary the segmented
greedy path already created) finished sequences leave the batch and
waiting sequences take their slots.

The engine keeps a fixed pool of KV-cache *slots* sized from the
serving plan's ``kv_cache_bytes`` envelope (``kv_slot_capacity``).
Fixed capacity is what keeps the XLA program set bounded: every
iteration decodes a full ``(capacity, L)`` batch with per-row ragged
prompt lengths, free slots running as 1-token dummy rows, so the only
compiled decode programs are the same per-(bucket, step) ones the
sequential path uses.

Bit-exactness contract: each admitted sequence's output row equals
``session.generate(row[None], plen, max_new_tokens, temperature=0.0,
eos_token_id=eos)[0]`` no matter which neighbors shared its
iterations. This rests on row-independence under causal attention —
the same invariant ``InferenceSession._generate_segmented`` relies on
for its host-side eos forcing, pinned by the bucket-boundary tests —
plus the engine replicating that exact forcing per row.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from ...obs.metrics_registry import REGISTRY

_REQS = REGISTRY.counter(
    "ff_cb_requests_total",
    "continuous-batching sequences accepted, by outcome")
_ADMITTED_MIDFLIGHT = REGISTRY.counter(
    "ff_cb_admitted_midflight_total",
    "sequences admitted while other sequences were already decoding")
_EVICTED_EARLY = REGISTRY.counter(
    "ff_cb_evicted_early_total",
    "sequences evicted at a segment boundary before max_new_tokens "
    "(eos emitted); their freed iterations went to other sequences")
_ACTIVE = REGISTRY.gauge(
    "ff_cb_active_slots", "decode slots occupied this iteration")


class EngineClosedError(RuntimeError):
    """Submitted to (or pending in) an engine that has shut down."""


class SequenceError(ValueError):
    """A sequence's ids/prompt_len/max_new_tokens cannot be served."""


def kv_slot_capacity(ff, kv_cache_bytes_budget: int,
                     max_seq: Optional[int] = None,
                     hard_cap: int = 64) -> int:
    """Decode slots that fit the serving plan's KV envelope: the
    per-sequence resident K+V bytes at full context length, divided
    into ``kv_cache_bytes_budget``. Clamped to [1, hard_cap] — one
    slot always exists (the envelope gate that would reject even one
    sequence lives in the plan verifier, not here)."""
    from ...search.serving_plan import kv_cache_bytes
    if max_seq is None:
        t = next(t for t in ff.graph_inputs if t.name == "input_ids")
        max_seq = int(t.shape[1])
    per_seq = sum(kv_cache_bytes(l, 1, int(max_seq)) for l in ff.layers)
    if per_seq <= 0:
        return int(hard_cap)
    return max(1, min(int(hard_cap),
                      int(kv_cache_bytes_budget) // per_seq))


class _Sequence:
    """One admitted decode request: its full-width ids row, progress,
    and the completion event its submitter blocks on."""

    __slots__ = ("ids", "plen", "max_new", "emitted", "done_eos",
                 "slot", "event", "result", "error", "t_submit",
                 "deadline", "admitted_midflight")

    def __init__(self, ids: np.ndarray, plen: int, max_new: int,
                 deadline: Optional[float]):
        self.ids = ids
        self.plen = plen
        self.max_new = max_new
        self.emitted = 0
        self.done_eos = False
        self.slot = -1
        self.event = threading.Event()
        self.result: Optional[np.ndarray] = None
        self.error: Optional[BaseException] = None
        self.t_submit = time.monotonic()
        self.deadline = deadline
        self.admitted_midflight = False

    def wait(self, timeout_s: Optional[float] = None) -> np.ndarray:
        """Block until the engine finishes (or fails) this sequence;
        returns the full-width output row."""
        if not self.event.wait(timeout=600.0 if timeout_s is None
                               else timeout_s):
            raise TimeoutError("sequence did not complete in time")
        if self.error is not None:
            raise self.error
        if self.result is None:  # unreachable except on engine bugs
            raise EngineClosedError(
                "sequence completed without a result")
        return self.result


class ContinuousBatcher:
    """Iteration-level decode engine over one serving session.

    ``session`` is an ``InferenceSession`` or ``ServingPlanSession``;
    for a plan session the engine pins the bucket instance that covers
    ``capacity`` (``session_for``) and shares its dispatch lock, so
    direct ``infer``/``generate`` callers on the same instance
    interleave with the engine at segment boundaries exactly as they
    do with the sequential segmented path.

    ``admission`` selects the scheduling policy:

    * ``"continuous"`` (default): waiting sequences join at every
      segment boundary; finished ones are evicted.
    * ``"static"``: new sequences are admitted only when the in-flight
      batch is EMPTY — the whole batch runs to completion of its
      slowest member. Same engine, same programs; the paired baseline
      the bench leg compares against, isolating the scheduling policy.

    Greedy-only (``temperature=0``): that is the regime where segment
    boundaries exist at all (sampling keys its RNG stream to one scan,
    so it keeps the single lock hold and cannot be re-batched).
    """

    def __init__(self, session, capacity: Optional[int] = None,
                 kv_cache_bytes_budget: Optional[int] = None,
                 eos_token_id: Optional[int] = None,
                 decode_segment: Optional[int] = None,
                 admission: str = "continuous"):
        if admission not in ("continuous", "static"):
            raise ValueError(f"admission must be 'continuous' or "
                             f"'static', got {admission!r}")
        self.admission = admission
        self.eos_token_id = (None if eos_token_id is None
                             else int(eos_token_id))
        if capacity is None:
            if kv_cache_bytes_budget is not None:
                capacity = kv_slot_capacity(session.ff,
                                            kv_cache_bytes_budget)
            else:
                capacity = session.buckets[-1]
        self.capacity = int(capacity)
        if self.capacity < 1:
            raise ValueError("capacity must be >= 1")
        # pin ONE bucket instance: the engine always dispatches full
        # (capacity, L) batches, so bucket routing is decided once
        sess = session.session_for(self.capacity) \
            if hasattr(session, "session_for") else session
        self._sess = sess
        t = next(t for t in sess.ff.graph_inputs
                 if t.name == "input_ids")
        self._seq_len = int(t.shape[1])
        seg = int(decode_segment if decode_segment is not None
                  else getattr(sess, "decode_segment", 0) or 0)
        if not 1 <= seg <= self._seq_len - 1:
            raise ValueError(
                f"decode_segment must be in [1, {self._seq_len - 1}] "
                f"(dummy slots decode the segment from position 1), "
                f"got {seg}")
        self.decode_segment = seg
        self._lock = threading.Lock()
        # guarded by _lock:
        self._waiting: List[_Sequence] = []
        self._slots: List[Optional[_Sequence]] = \
            [None] * self.capacity
        self._closed = False
        self._stats = {"completed": 0, "expired": 0,
                       "evicted_early": 0, "iterations": 0}
        self._arrival = threading.Event()
        self._stop = threading.Event()
        self._worker = threading.Thread(
            target=self._run, name="ff-continuous-batcher", daemon=True)
        self._worker.start()

    # -- client side -----------------------------------------------

    def submit(self, input_ids: np.ndarray, prompt_len: int,
               max_new_tokens: int,
               timeout_s: Optional[float] = None) -> "_Sequence":
        """Enqueue one sequence; returns a handle whose ``wait()``
        blocks for the full output row. ``input_ids`` is a 1-D prompt
        of length <= the model's sequence width (zero-padded to it)."""
        ids = np.asarray(input_ids, np.int32).reshape(-1)
        plen = int(prompt_len)
        max_new = int(max_new_tokens)
        if ids.shape[0] > self._seq_len:
            raise SequenceError(
                f"prompt row length {ids.shape[0]} exceeds model "
                f"sequence width {self._seq_len}")
        if not 1 <= plen <= ids.shape[0]:
            raise SequenceError(
                f"prompt_len {plen} out of range [1, {ids.shape[0]}]")
        if max_new < 1 or plen + max_new > self._seq_len:
            raise SequenceError(
                f"prompt_len {plen} + max_new_tokens {max_new} "
                f"exceeds sequence width {self._seq_len}")
        row = np.zeros(self._seq_len, np.int32)
        row[:ids.shape[0]] = ids
        deadline = (None if timeout_s is None
                    else time.monotonic() + float(timeout_s))
        seq = _Sequence(row, plen, max_new, deadline)
        with self._lock:
            if self._closed:
                raise EngineClosedError("engine is closed")
            self._waiting.append(seq)
        self._arrival.set()
        return seq

    def generate(self, input_ids: np.ndarray, prompt_len: int,
                 max_new_tokens: int,
                 timeout_s: Optional[float] = None) -> np.ndarray:
        """Blocking convenience: submit one sequence and wait."""
        return self.submit(input_ids, prompt_len, max_new_tokens,
                           timeout_s=timeout_s).wait(
                               None if timeout_s is None
                               else timeout_s + 120.0)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            out = dict(self._stats)
            out["waiting"] = len(self._waiting)
            out["active"] = sum(1 for s in self._slots
                                if s is not None)
            out["capacity"] = self.capacity
        return out

    def close(self, timeout_s: float = 30.0) -> None:
        """Stop admitting, finish nothing further: pending (waiting
        AND in-flight) sequences fail with ``EngineClosedError``.
        Graceful completion is the caller's job (stop submitting,
        wait on outstanding handles, then close)."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            pending = self._waiting + [s for s in self._slots
                                       if s is not None]
            self._waiting = []
            self._slots = [None] * self.capacity
        self._stop.set()
        self._arrival.set()
        for seq in pending:
            seq.error = EngineClosedError("engine closed")
            seq.event.set()
        self._worker.join(timeout=timeout_s)

    # -- engine side -----------------------------------------------

    def _run(self) -> None:
        while not self._stop.is_set():
            active = self._admit()
            if not active:
                # bounded nap between polls; submit() sets _arrival
                self._arrival.wait(timeout=0.05)
                self._arrival.clear()
                continue
            try:
                self._iterate(active)
            except BaseException as exc:  # noqa: BLE001 — the engine
                # thread must not die silently; fail the batch instead
                self._fail_active(active, exc)

    def _admit(self) -> List[_Sequence]:
        """Fill free slots from the waiting queue (continuous), or
        only when the batch is empty (static). Expired waiters fail
        here without ever touching the device."""
        now = time.monotonic()
        admitted: List[_Sequence] = []
        expired: List[_Sequence] = []
        with self._lock:
            if self._closed:
                return []
            active = [s for s in self._slots if s is not None]
            allow = self.capacity - len(active) \
                if self.admission == "continuous" \
                else (self.capacity if not active else 0)
            keep: List[_Sequence] = []
            for seq in self._waiting:
                if seq.deadline is not None and now > seq.deadline:
                    expired.append(seq)
                elif allow > 0:
                    admitted.append(seq)
                    allow -= 1
                else:
                    keep.append(seq)
            self._waiting = keep
            for seq in admitted:
                slot = self._slots.index(None)
                seq.slot = slot
                self._slots[slot] = seq
                seq.admitted_midflight = bool(active)
            self._stats["expired"] += len(expired)
            active = [s for s in self._slots if s is not None]
        for seq in expired:
            seq.error = TimeoutError(
                "sequence expired before admission")
            seq.event.set()
            _REQS.inc(outcome="expired")
        for seq in admitted:
            if seq.admitted_midflight:
                _ADMITTED_MIDFLIGHT.inc()
        return active

    def _iterate(self, active: List[_Sequence]) -> None:
        """One decode iteration: a full-capacity ragged batch advances
        every active sequence by one segment (bounded by the shortest
        remaining budget, so no row oversteps its max_new_tokens)."""
        cap, L = self.capacity, self._seq_len
        eos = self.eos_token_id
        ids = np.zeros((cap, L), np.int32)
        cur = np.ones(cap, np.int32)  # free slots: 1-token dummy rows
        for seq in active:
            ids[seq.slot] = seq.ids
            cur[seq.slot] = seq.plen + seq.emitted
        step = min(self.decode_segment,
                   min(s.max_new - s.emitted for s in active))
        _ACTIVE.set(len(active))
        with self._sess._lock:
            out = np.array(self._sess.ff.generate(
                ids, cur, step, temperature=0.0, eos_token_id=eos))
        finished: List[_Sequence] = []
        for seq in active:
            row = out[seq.slot]
            start = seq.plen + seq.emitted
            if eos is not None:
                # mirror _generate_segmented's host-side forcing: a row
                # that latched eos in an EARLIER segment reads eos for
                # this segment's columns too (the in-program done-mask
                # only covers one program invocation)
                if seq.done_eos:
                    row[start:start + step] = eos
                else:
                    seq.done_eos = bool(
                        (row[start:start + step] == eos).any())
            seq.ids = row
            seq.emitted += step
            if seq.emitted >= seq.max_new or seq.done_eos:
                if seq.done_eos and seq.emitted < seq.max_new:
                    # evict early; the columns the sequential oracle
                    # would spend real iterations forcing to eos are
                    # forced here for free — bit-identical output, and
                    # the slot goes to a waiting sequence instead
                    row[seq.plen + seq.emitted:
                        seq.plen + seq.max_new] = eos
                    self._note_early_eviction()
                seq.result = row
                finished.append(seq)
        with self._lock:
            self._stats["iterations"] += 1
            self._stats["completed"] += len(finished)
            for seq in finished:
                if self._slots[seq.slot] is seq:
                    self._slots[seq.slot] = None
        for seq in finished:
            seq.event.set()
            _REQS.inc(outcome="completed")

    def _note_early_eviction(self) -> None:
        with self._lock:
            self._stats["evicted_early"] += 1
        _EVICTED_EARLY.inc()

    def _fail_active(self, active: List[_Sequence], exc) -> None:
        with self._lock:
            for seq in active:
                if 0 <= seq.slot < self.capacity \
                        and self._slots[seq.slot] is seq:
                    self._slots[seq.slot] = None
        for seq in active:
            if not seq.event.is_set():
                seq.error = exc
                seq.event.set()
                _REQS.inc(outcome="failed")
