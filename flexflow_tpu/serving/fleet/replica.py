"""Replica worker: one serving process behind the fleet router.

Launched by :class:`.router.FleetRouter.spawn` (or by hand)::

    python -m flexflow_tpu.serving.fleet.replica --port 8101 \
        --model gpt2-tiny --compile-cache /tmp/ffcache

Builds a model repository, starts the threaded HTTP front
(``serve_http(block=False)``), and then watches **stdin** for the
drain protocol: a ``drain`` line (or EOF — the router closing the
pipe) triggers the graceful-drain path (readiness 503, finish
in-flight work, close schedulers) and exits 0. Hard faults injected
via ``FF_FAULT_PLAN=infer_crash@N`` kill the process mid-request with
no drain — the failure mode the router's failover must absorb.

Two model kinds:

* ``synthetic``: a fixed-latency session (``--synthetic-ms`` per
  device step) — scheduler/router policy measurement decoupled from
  XLA compile noise; the bench harness's replicas.
* ``gpt2-tiny``: a real tiny GPT-2 compiled through the persistent
  XLA compile cache when ``--compile-cache`` is set (``allow_cpu=True``:
  replicas share one host, where CPU cache reuse is safe), so a
  replacement replica comes up warm. ``ff_model_compiles_total`` stays
  the honest witness: a warm start still *counts* its program builds,
  but the cache turns each build into a disk hit — asserted by the
  fleet smoke via time-to-ready and cache-directory reuse.
"""
from __future__ import annotations

import argparse
import sys
import threading
import time

import numpy as np


def _build_repo(args):
    from ..session import InferenceSession, ModelRepository

    repo = ModelRepository()
    if args.model == "synthetic":
        step_s = args.synthetic_ms / 1e3

        class SyntheticSession:
            """Fixed-latency device-step stand-in: one batched step
            costs ``--synthetic-ms`` regardless of rows (up to the
            scheduler's max_batch) — the policy-measurement harness
            bench.py's overload stage established."""
            input_names = ["x"]

            def infer(self, inputs):
                time.sleep(step_s)
                return np.zeros((int(inputs["x"].shape[0]), 1),
                                np.float32)

            def clone(self):
                return self

        repo.register(args.model_name, SyntheticSession(),
                      instances=args.instances)
        return repo
    # gpt2-tiny: a real autoregressive model on the CPU sim mesh
    if args.compile_cache:
        from ...utils.compilation_cache import enable_compilation_cache
        enable_compilation_cache(args.compile_cache, allow_cpu=True)
    from ... import FFConfig, FFModel, SGDOptimizer
    from ...models.nlp import GPTConfig, build_gpt2
    cfg = FFConfig()
    cfg.batch_size = args.bucket
    cfg.only_data_parallel = True
    g = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                  num_heads=4, max_position=args.seq_len, dropout=0.0)
    ff = FFModel(cfg)
    ff._model_name = args.model_name  # before compile: labels the
    # ff_model_compiles_total increments the warm-start check reads
    out = build_gpt2(ff, args.bucket, args.seq_len, g)
    ff.compile(SGDOptimizer(0.0), "identity", [], output_tensor=out)
    sess = InferenceSession(ff, batch_buckets=(args.bucket,),
                            decode_segment=args.decode_segment)
    repo.register(args.model_name, sess, instances=args.instances)
    return repo


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--port", type=int, required=True)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--name", default=None,
                   help="replica display name (the router substitutes "
                        "{name} in its spawn template); logging only")
    p.add_argument("--model", default="gpt2-tiny",
                   choices=["gpt2-tiny", "synthetic"])
    p.add_argument("--model-name", default=None,
                   help="served model name (default: --model)")
    p.add_argument("--instances", type=int, default=1)
    p.add_argument("--synthetic-ms", type=float, default=40.0)
    p.add_argument("--bucket", type=int, default=4)
    p.add_argument("--seq-len", type=int, default=32)
    p.add_argument("--decode-segment", type=int, default=4)
    p.add_argument("--compile-cache", default=None,
                   help="persistent XLA compile-cache dir (shared "
                        "across replicas: replacements start warm)")
    p.add_argument("--max-batch", type=int, default=4)
    p.add_argument("--max-delay-ms", type=float, default=2.0)
    p.add_argument("--max-queue", type=int, default=256)
    p.add_argument("--default-deadline-ms", type=float, default=None)
    p.add_argument("--breaker-threshold", type=int, default=5)
    p.add_argument("--breaker-cooldown-s", type=float, default=5.0)
    p.add_argument("--admission-estimate", default="completion",
                   choices=["wait", "completion"],
                   help="deadline-shed predictor (default "
                        "'completion': replicas behind a deadline-"
                        "routing front shed on predicted request "
                        "latency, not just queue wait)")
    p.add_argument("--drain-deadline-s", type=float, default=10.0)
    args = p.parse_args(argv)
    if args.model_name is None:
        args.model_name = args.model

    from ..http_server import serve_http
    repo = _build_repo(args)
    handle = serve_http(repo, host=args.host, port=args.port,
                        block=False, max_batch=args.max_batch,
                        max_delay_ms=args.max_delay_ms,
                        max_queue=args.max_queue,
                        default_deadline_ms=args.default_deadline_ms,
                        breaker_threshold=args.breaker_threshold,
                        breaker_cooldown_s=args.breaker_cooldown_s,
                        admission_estimate=args.admission_estimate)
    print(f"READY name={args.name or '-'} port={args.port} "
          f"model={args.model_name}", flush=True)

    done = threading.Event()

    def _stdin_watch():
        # the router's drain protocol: a "drain" line or EOF (the
        # router closing our stdin / dying) -> graceful drain + exit
        try:
            for line in sys.stdin:
                if line.strip() in ("drain", "stop", "quit"):
                    break
        except (ValueError, OSError):
            pass
        done.set()

    t = threading.Thread(target=_stdin_watch, name="ff-replica-stdin",
                         daemon=True)
    t.start()
    while not done.wait(timeout=0.5):
        pass
    handle.drain(deadline_s=args.drain_deadline_s)
    handle.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
