"""Serving fleet: continuous batching, multi-replica routing, and
signal-driven autoscaling.

The three cooperating parts (see ``docs/serving.md`` · Fleet):

* :mod:`.continuous` — :class:`ContinuousBatcher`, the iteration-level
  decode engine: a fixed KV-slot pool, admission/eviction at
  ``decode_segment`` boundaries, bit-exact per sequence vs the
  sequential ``generate`` oracle.
* :mod:`.router` — :class:`FleetRouter` + ``serve_fleet``: least-
  estimated-wait routing over replica ``/healthz`` signals, failover,
  remaining-deadline propagation, sketch-merged fleet metrics.
* :mod:`.autoscale` — :class:`Autoscaler`: repair / scale-up /
  scale-down from queue-depth EWMA and SLO-violation deltas, warm
  starts through the persistent compile cache.
"""
from .autoscale import Autoscaler, AutoscalerConfig, decide
from .continuous import (ContinuousBatcher, EngineClosedError,
                         SequenceError, kv_slot_capacity)
from .router import (FleetHandle, FleetRouter, NoReplicaAvailableError,
                     Replica, free_port, merge_replica_metrics,
                     serve_fleet)

__all__ = [
    "Autoscaler", "AutoscalerConfig", "ContinuousBatcher",
    "EngineClosedError", "FleetHandle", "FleetRouter",
    "NoReplicaAvailableError", "Replica", "SequenceError", "decide",
    "free_port", "kv_slot_capacity", "merge_replica_metrics",
    "serve_fleet",
]
