"""Dynamic micro-batching for inference requests.

Triton's dynamic batcher (``preferred_batch_size`` +
``max_queue_delay_microseconds``) reimplemented in a few hundred lines:
requests queue up; per-instance workers drain up to ``max_batch`` of
them (or whatever arrived within ``max_delay_ms``), stack them into one
device batch, and fan the result back out per request. On TPU the win is
identical to the GPU case — one big MXU-shaped batch instead of many
tiny dispatches.

Triton-scope hardening (reference ``triton/src/instance.cc``,
``backend.cc``):
  - **bounded queue + backpressure**: the queue holds at most
    ``max_queue`` requests; beyond that ``infer`` raises
    :class:`QueueFullError` (HTTP 503) instead of growing without bound;
  - **request deadlines**: every request may carry a deadline
    (``x-ff-timeout-ms`` header or the scheduler default); a request
    whose deadline passes while queued — or whose client timed out and
    abandoned it — is failed at dequeue time and NEVER consumes a
    device step;
  - **admission control**: when the estimated queue wait (EWMA of
    recent batch latency x backlog) already exceeds a request's
    deadline, ``infer`` fast-fails with :class:`DeadlineRejectedError`
    (HTTP 503 + ``Retry-After``) instead of queueing doomed work;
  - **circuit breaker**: K consecutive session failures open the
    per-model circuit — requests fast-fail 503 until a cooldown
    elapses, then ONE half-open probe is admitted; its success closes
    the circuit, its failure re-opens it (Triton's model-health
    isolation);
  - **batch-poison isolation**: inputs are validated against the
    session signature at admission (:class:`InvalidInputError`, HTTP
    400, for the malformed request only); if a batch execution still
    fails, each member is retried individually once so good co-batched
    requests succeed anyway;
  - **graceful drain**: :meth:`BatchScheduler.drain` stops admitting
    (:class:`DrainingError`, HTTP 503 + ``Retry-After``), finishes
    everything in flight within a drain deadline, then closes;
  - **N concurrent instances**: one worker thread per model instance
    (Triton's ``instance_group { count: N }``), all draining the shared
    queue;
  - **metrics**: per-model counters + streaming quantile sketches
    (``obs.sketch``) feeding the ``/v2/metrics`` endpoint
    (p50/p90/p99/p99.9 overall and per batch bucket, queue depth, batch
    sizes), plus expired / deadline-rejected / breaker-open / SLO-
    violation counters and the circuit state in the Prometheus registry;
  - **request lifecycle tracing**: when ``obs.events`` is enabled each
    request carries a :class:`~..obs.request_trace.RequestTrace` through
    admission -> queue -> batch -> response, every stage a linked span
    tagged with the trace id, batch bucket, and terminal outcome.
"""
from __future__ import annotations

import queue
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..obs import request_trace
from ..obs.metrics_registry import DEFAULT_BUCKETS, REGISTRY
from ..obs.sketch import QuantileSketch

#: request-latency histogram buckets (seconds): the registry default
#: extended upward for slow generate calls
LATENCY_BUCKETS = DEFAULT_BUCKETS + (30.0,)

#: numeric encoding of circuit states for the ``ff_circuit_state`` gauge
CIRCUIT_STATE_NUM = {"closed": 0.0, "half_open": 1.0, "open": 2.0}


class RequestRejected(RuntimeError):
    """Base of all load-shedding rejections (HTTP 503).

    ``retry_after_s`` is the server's estimate of when retrying could
    succeed — surfaced to HTTP clients as the ``Retry-After`` header."""

    def __init__(self, msg: str, retry_after_s: float = 1.0):
        super().__init__(msg)
        self.retry_after_s = float(retry_after_s)


class QueueFullError(RequestRejected):
    """Raised by ``infer`` when the bounded request queue is full —
    callers should shed load (HTTP 503)."""


class DeadlineRejectedError(RequestRejected):
    """Admission control: the estimated queue wait already exceeds the
    request's deadline, so queueing it would only waste a device step
    (HTTP 503 + ``Retry-After``)."""


class CircuitOpenError(RequestRejected):
    """The per-model circuit breaker is open after repeated session
    failures; requests fast-fail until the cooldown's half-open probe
    succeeds (HTTP 503 + ``Retry-After``)."""


class DrainingError(RequestRejected):
    """The scheduler is draining for shutdown and admits no new work
    (HTTP 503 + ``Retry-After``)."""


class DeadlineExceededError(TimeoutError):
    """The request's deadline passed before a result was produced —
    either while queued (the request never reached a device step) or
    while the client was waiting (HTTP 504)."""


class InvalidInputError(ValueError):
    """Request inputs do not match the session signature (missing or
    unknown names, wrong feature shape/dtype, ragged rows) — a client
    error for THIS request only (HTTP 400), caught at admission so it
    can never poison a co-batched device step."""


class CircuitBreaker:
    """Per-model circuit breaker (Triton model-health isolation analog).

    closed --(K consecutive session failures)--> open --(cooldown
    elapses)--> half_open: ONE probe request is admitted; its success
    closes the circuit, its failure re-opens it. ``allow()`` is the
    admission gate; request outcomes feed back via
    ``on_success``/``on_failure``. Thread-safe."""

    def __init__(self, threshold: int = 5, cooldown_s: float = 5.0,
                 on_open=None):
        self.threshold = max(1, int(threshold))
        self.cooldown_s = float(cooldown_s)
        self._on_open = on_open
        self._lock = threading.Lock()
        self.state = "closed"
        self.opens = 0
        self._failures = 0
        self._opened_at = 0.0
        self._probe_inflight = False

    def allow(self) -> Tuple[bool, float, bool]:
        """Admission gate: ``(admitted, retry_after_s, is_probe)``.
        ``is_probe`` marks the single half-open probe admission; its
        holder MUST end in on_success/on_failure — or release_probe if
        it dies before reaching the session — or the slot would wedge
        the model in half-open forever."""
        with self._lock:
            if self.state == "closed":
                return True, 0.0, False
            if self.state == "open":
                remaining = (self._opened_at + self.cooldown_s
                             - time.perf_counter())
                if remaining > 0:
                    return False, remaining, False
                self.state = "half_open"
                self._probe_inflight = False
            # half_open: admit exactly one probe at a time
            if self._probe_inflight:
                return False, self.cooldown_s, False
            self._probe_inflight = True
            return True, 0.0, True

    def release_probe(self) -> None:
        """Give the half-open probe slot back: the admitted probe was
        shed before execution (queue full, admission rejection) or
        expired in the queue, so its outcome says nothing about model
        health — let the next request probe instead."""
        with self._lock:
            if self.state == "half_open":
                self._probe_inflight = False

    def on_success(self) -> None:
        with self._lock:
            self._failures = 0
            if self.state == "half_open":
                self.state = "closed"
                self._probe_inflight = False

    def on_failure(self) -> None:
        opened = False
        with self._lock:
            self._failures += 1
            if self.state == "half_open" or (
                    self.state == "closed"
                    and self._failures >= self.threshold):
                self.state = "open"
                self._opened_at = time.perf_counter()
                self._failures = 0
                self._probe_inflight = False
                self.opens += 1
                opened = True
        if opened and self._on_open is not None:
            self._on_open()


class SchedulerMetrics:
    """Thread-safe counters + streaming latency quantiles for one
    scheduler.

    Latency lands in mergeable :class:`~..obs.sketch.QuantileSketch`
    instances — one overall, one per batch bucket — instead of the old
    bounded reservoir: memory stays fixed no matter how many requests
    flow through, quantile error is a bounded *relative* 1%, and
    per-bucket sketches merge exactly into fleet aggregates.

    Doubles as the bridge into the process-wide Prometheus registry
    (``obs/metrics_registry.py``): every completion lands in the
    ``ff_request_latency_seconds`` histogram and the per-model request
    counters, labeled by model name — what ``GET /metrics`` serves.
    Deadline violations additionally feed the SLO burn-rate counter
    ``ff_slo_violations_total{model,bucket}`` (completed-late, expired
    with a deadline, and deadline-rejected requests all count; failures
    without a deadline breach do not — they burn the error budget via
    ``ff_requests_failed_total`` instead).

    Counter semantics (disjoint: every admitted-or-rejected request
    lands in exactly one of completed/failed/expired/rejected/
    deadline_rejected):
      - ``rejected``: shed at admission (queue full, circuit open,
        draining);
      - ``deadline_rejected``: shed at admission because the estimated
        queue wait exceeded the request deadline;
      - ``expired``: admitted but the client never got a result and no
        device step was spent ON ITS BEHALF (deadline passed or client
        abandoned at dequeue time, swept at close/unload, or dropped
        from a failed batch's individual-retry pass because the client
        was already gone — that last case rode a failed batch attempt,
        but got no step of its own);
      - ``failed``: executed (or retried) and errored;
      - ``completed``: executed successfully."""

    #: quantiles exposed on /healthz, /v2/metrics, and the
    #: ``ff_request_latency_quantile`` gauge
    QUANTILES = ((0.5, "p50"), (0.9, "p90"), (0.99, "p99"),
                 (0.999, "p99.9"))

    def __init__(self, window: int = 2048, name: str = ""):
        # ``window`` is legacy (the old reservoir size) — kept in the
        # signature for callers; the sketches are memory-bounded by
        # construction
        del window
        self._lock = threading.Lock()
        self.name = name or "default"
        self.requests = 0
        self.completed = 0
        self.failed = 0
        self.rejected = 0
        self.expired = 0
        self.deadline_rejected = 0
        self.breaker_opens = 0
        self.slo_violations = 0
        self.batches = 0
        self.batched_rows = 0
        self._sketch = QuantileSketch()
        self._sketch_by_bucket: Dict[str, QuantileSketch] = {}
        # registry handles resolved ONCE — the hot path below must not
        # take the registry lock for a name lookup per request
        self._m_requests = REGISTRY.counter(
            "ff_requests_total",
            "Inference requests accepted into the queue")
        self._m_rejected = REGISTRY.counter(
            "ff_requests_rejected_total",
            "Requests shed at admission (queue full, circuit open, "
            "draining)")
        self._m_failed = REGISTRY.counter(
            "ff_requests_failed_total",
            "Requests completed with an error")
        self._m_expired = REGISTRY.counter(
            "ff_requests_expired_total",
            "Requests whose deadline passed (or whose client abandoned "
            "them) before producing a result — failed at dequeue, swept "
            "at close/unload, or skipped in a failed batch's retry pass; "
            "no device step was spent on their behalf alone")
        self._m_deadline_rejected = REGISTRY.counter(
            "ff_requests_deadline_rejected_total",
            "Requests shed at admission: estimated queue wait exceeded "
            "the request deadline")
        self._m_breaker_opens = REGISTRY.counter(
            "ff_breaker_opens_total",
            "Circuit-breaker open transitions (consecutive session "
            "failures reached the threshold)")
        self._m_latency = REGISTRY.histogram(
            "ff_request_latency_seconds",
            "End-to-end request latency (queue + batch assembly + "
            "device step)", buckets=LATENCY_BUCKETS)
        self._m_slo = REGISTRY.counter(
            "ff_slo_violations_total",
            "Requests that violated their deadline SLO, by model and "
            "batch bucket: completed past the deadline, expired in the "
            "queue with a deadline set, or deadline-rejected at "
            "admission")

    def record_submitted(self):
        with self._lock:
            self.requests += 1
        self._m_requests.inc(model=self.name)

    def record_rejected(self):
        with self._lock:
            self.rejected += 1
        self._m_rejected.inc(model=self.name)

    def record_deadline_rejected(self, bucket: Optional[str] = None):
        # always an SLO violation: the request carried a deadline the
        # server declined to attempt
        with self._lock:
            self.deadline_rejected += 1
            self.slo_violations += 1
        self._m_deadline_rejected.inc(model=self.name)
        self._m_slo.inc(model=self.name, bucket=bucket or "all")

    def record_expired(self, bucket: Optional[str] = None,
                       deadline_missed: bool = False):
        with self._lock:
            self.expired += 1
            if deadline_missed:
                self.slo_violations += 1
        self._m_expired.inc(model=self.name)
        if deadline_missed:
            self._m_slo.inc(model=self.name, bucket=bucket or "all")

    def record_slo(self, bucket: Optional[str] = None):
        """SLO accounting for the direct (non-scheduler) verb paths:
        a deadline-carrying ``generate`` that completed past its
        deadline is a violation even though the scheduler never saw
        it. Counting it HERE is what lets a fleet router dedupe — the
        replica that carried the remaining deadline owns the count,
        the fleet layer only counts requests no replica attempted."""
        with self._lock:
            self.slo_violations += 1
        self._m_slo.inc(model=self.name, bucket=bucket or "all")

    def record_breaker_open(self):
        with self._lock:
            self.breaker_opens += 1
        self._m_breaker_opens.inc(model=self.name)

    def record_done(self, latency_s: float, ok: bool,
                    bucket: Optional[str] = None,
                    deadline_missed: bool = False):
        with self._lock:
            self.completed += ok
            self.failed += (not ok)
            self._sketch.add(latency_s)
            if bucket is not None:
                sk = self._sketch_by_bucket.get(bucket)
                if sk is None:
                    sk = self._sketch_by_bucket[bucket] = QuantileSketch()
                sk.add(latency_s)
            if deadline_missed:
                self.slo_violations += 1
        self._m_latency.observe(latency_s, model=self.name)
        if deadline_missed:
            self._m_slo.inc(model=self.name, bucket=bucket or "all")
        if not ok:
            self._m_failed.inc(model=self.name)

    @classmethod
    def _quantiles_ms(cls, sk: QuantileSketch) -> Dict:
        """One sketch's quantile row (ms, rounded) for JSON surfaces."""
        if not sk.count:
            return {"count": 0}
        out: Dict = {"count": sk.count}
        for q, label in cls.QUANTILES:
            out[label] = round(sk.quantile(q) * 1e3, 3)
        return out

    def latency_quantiles(self) -> Dict:
        """p50/p90/p99/p99.9 (ms) overall and per batch bucket — the
        ``/healthz`` latency block and the ``/v2/metrics`` detail."""
        with self._lock:
            out = {"all": self._quantiles_ms(self._sketch)}
            for b in sorted(self._sketch_by_bucket):
                out[b] = self._quantiles_ms(self._sketch_by_bucket[b])
        return out

    def quantile_rows(self) -> List[Tuple[Dict, float]]:
        """``(labels, seconds)`` rows for the
        ``ff_request_latency_quantile`` gauge — sampled at scrape time
        by ``render_prometheus`` (set_all semantics: rows for unloaded
        models disappear with their scheduler)."""
        rows: List[Tuple[Dict, float]] = []
        with self._lock:
            sketches = [("all", self._sketch)] \
                + sorted(self._sketch_by_bucket.items())
            for b, sk in sketches:
                if not sk.count:
                    continue
                for q, _ in self.QUANTILES:
                    rows.append(({"model": self.name, "bucket": b,
                                  "quantile": str(q)}, sk.quantile(q)))
        return rows

    def slo_total(self) -> int:
        """Cheap read of the SLO-violation count — the ``/healthz``
        field the fleet autoscaler differentiates per poll."""
        with self._lock:
            return self.slo_violations

    def sketch_docs(self) -> Dict[str, Dict]:
        """Serialized latency sketches (``QuantileSketch.to_dict``),
        overall + per batch bucket — the ``/v2/metrics`` field a fleet
        front scrapes and ``merge``s so fleet quantiles are computed
        over the union stream, not averaged per replica (averaging
        percentiles is the classic observability bug)."""
        with self._lock:
            out = {"all": self._sketch.to_dict()}
            for b, sk in sorted(self._sketch_by_bucket.items()):
                out[b] = sk.to_dict()
        return out

    def snapshot(self, queue_depth: int) -> Dict:
        with self._lock:
            sk = self._sketch
            # empty sketch reports 0.0 (NaN would poison JSON surfaces
            # and the pre-traffic /healthz probe)
            q = {label: (sk.quantile(p) if sk.count else 0.0)
                 for p, label in self.QUANTILES}
            by_bucket = {
                b: self._quantiles_ms(s)
                for b, s in sorted(self._sketch_by_bucket.items())}
            return {
                "requests": self.requests,
                "completed": self.completed,
                "failed": self.failed,
                "rejected": self.rejected,
                "expired": self.expired,
                "deadline_rejected": self.deadline_rejected,
                "breaker_opens": self.breaker_opens,
                "slo_violations": self.slo_violations,
                "batches": self.batches,
                "mean_batch_rows": (self.batched_rows
                                    / max(self.batches, 1)),
                "queue_depth": queue_depth,
                "latency_p50_ms": round(q["p50"] * 1e3, 3),
                "latency_p90_ms": round(q["p90"] * 1e3, 3),
                "latency_p99_ms": round(q["p99"] * 1e3, 3),
                "latency_p999_ms": round(q["p99.9"] * 1e3, 3),
                "latency_by_bucket_ms": by_bucket,
            }


class _Request:
    __slots__ = ("inputs", "rows", "deadline", "abandoned", "probe",
                 "event", "result", "error", "t0", "trace", "bucket")

    def __init__(self, inputs, rows: int = 0,
                 deadline: Optional[float] = None, probe: bool = False,
                 trace=None, bucket: Optional[str] = None):
        self.inputs = inputs
        self.rows = rows or int(next(iter(inputs.values())).shape[0])
        self.deadline = deadline      # absolute perf_counter time
        self.abandoned = False        # client gave up waiting
        self.probe = probe            # holds the half-open probe slot
        self.trace = trace            # RequestTrace or None (disabled)
        self.bucket = bucket          # batch-bucket label for metrics
        self.event = threading.Event()
        self.result = None
        self.error: Optional[Exception] = None
        self.t0 = time.perf_counter()


class BatchScheduler:
    """Bounded queue + N instance workers around
    :class:`InferenceSession` replicas.

    ``sessions`` may be one session or a list (one per concurrent
    instance — Triton's instance group); each gets its own worker
    thread draining the shared queue.

    ``default_deadline_ms`` applies to requests that carry no explicit
    deadline; ``breaker_threshold``/``breaker_cooldown_s`` configure
    the per-model circuit breaker; ``est_batch_latency_s`` seeds the
    admission-control EWMA before the first measured batch (cold-start
    estimates and tests).

    ``admission_estimate`` picks what the deadline gate compares:
    ``"wait"`` (default) sheds when the estimated QUEUE wait exceeds
    the deadline; ``"completion"`` adds one batch's service EWMA on
    top — a request whose queue wait just fits but whose own service
    time predictably lands past the deadline is shed at the door
    instead of burning a device step on a guaranteed SLO violation.
    Replicas under a deadline-routing fleet front run ``"completion"``
    (see ``fleet/replica.py``)."""

    def __init__(self, sessions, max_batch: int = 64,
                 max_delay_ms: float = 2.0, max_queue: int = 256,
                 name: str = "", default_deadline_ms: Optional[float] = None,
                 breaker_threshold: int = 5,
                 breaker_cooldown_s: float = 5.0,
                 est_batch_latency_s: Optional[float] = None,
                 admission_estimate: str = "wait"):
        if admission_estimate not in ("wait", "completion"):
            raise ValueError(
                f"admission_estimate must be 'wait' or 'completion', "
                f"got {admission_estimate!r}")
        self.admission_estimate = admission_estimate
        if not isinstance(sessions, (list, tuple)):
            sessions = [sessions]
        if not sessions:
            raise ValueError("need at least one session instance")
        self.sessions: List = list(sessions)
        self.session = self.sessions[0]    # back-compat alias
        self.max_batch = max_batch
        self.max_delay_s = max_delay_ms / 1e3
        self.default_deadline_ms = default_deadline_ms
        self.metrics = SchedulerMetrics(name=name)
        self.breaker = CircuitBreaker(
            breaker_threshold, breaker_cooldown_s,
            on_open=self.metrics.record_breaker_open)
        self._q: "queue.Queue[_Request]" = queue.Queue(maxsize=max_queue)
        self._stop = threading.Event()
        self._draining = False
        # admission-control state: EWMA of measured batch latency plus
        # the current backlog (queued + executing rows), under one lock
        self._stat_lock = threading.Lock()
        self._ewma_batch_s = (float(est_batch_latency_s)
                              if est_batch_latency_s is not None
                              else None)
        self._queued_rows = 0
        self._active_rows = 0
        self._active = 0              # requests popped but not finished
        # admitted but not yet resolved (queued, in a worker's hand
        # between pop and the _active bump, or executing): drain()'s
        # idle check — _active alone has a pop-vs-bump TOCTOU window
        # in which a mid-execution request looks idle
        self._pending = 0
        self._workers = [
            threading.Thread(target=self._run, args=(s,), daemon=True)
            for s in self.sessions]
        for w in self._workers:
            w.start()

    @property
    def num_instances(self) -> int:
        return len(self.sessions)

    # ------------------------------------------------------------------
    # admission
    # ------------------------------------------------------------------
    def _validate(self, inputs) -> Tuple[Dict[str, np.ndarray], int]:
        """Admission-time schema check against the session signature:
        missing names, ragged row counts, wrong feature shapes/dtypes
        raise :class:`InvalidInputError` (HTTP 400) for THIS request
        only, before it can poison a co-batched device step."""
        names = self.session.input_names
        missing = [n for n in names if n not in inputs]
        if missing:
            raise InvalidInputError(
                f"missing inputs: {missing} (expected {names})")
        unknown = [k for k in inputs if k not in names]
        if unknown:
            # a typo'd optional tensor silently dropped would return a
            # 200 computed without data the client thought it sent
            raise InvalidInputError(
                f"unknown inputs: {unknown} (expected {names})")
        sig = getattr(self.session, "input_signature", None) or {}
        arrs: Dict[str, np.ndarray] = {}
        rows = None
        for n in names:
            arr = np.asarray(inputs[n])
            if arr.ndim < 1:
                raise InvalidInputError(
                    f"input {n!r} must have a leading batch dimension")
            if rows is None:
                rows = int(arr.shape[0])
            elif int(arr.shape[0]) != rows:
                raise InvalidInputError(
                    f"ragged batch: {n!r} has {arr.shape[0]} rows, "
                    f"other inputs have {rows}")
            if n in sig:
                shape, dtype = sig[n]
                if tuple(arr.shape[1:]) != tuple(shape[1:]):
                    raise InvalidInputError(
                        f"input {n!r} feature shape {tuple(arr.shape[1:])}"
                        f" does not match the model's {tuple(shape[1:])}")
                if not np.can_cast(arr.dtype, dtype, casting="same_kind"):
                    raise InvalidInputError(
                        f"input {n!r} dtype {arr.dtype} is not "
                        f"compatible with the model's {dtype}")
                if arr.dtype != dtype:
                    # normalize compatible dtypes HERE so one request
                    # sending f64 cannot force a per-dtype recompile of
                    # the warm executable (and cannot poison a batch
                    # concat with a surprise promotion)
                    arr = arr.astype(dtype, copy=False)
            arrs[n] = arr
        if not rows:
            raise InvalidInputError("empty batch (0 rows)")
        return arrs, rows

    def estimated_wait_s(self) -> float:
        """Admission-control estimate: EWMA of recent batch latency x
        the backlog in batches, split across instances. 0.0 until a
        first batch has been measured (or a seed was given)."""
        with self._stat_lock:
            ewma = self._ewma_batch_s
            backlog = self._queued_rows + self._active_rows
        if ewma is None or backlog <= 0:
            return 0.0
        batches = backlog / float(max(1, self.max_batch))
        return ewma * batches / max(1, self.num_instances)

    def _bucket_label(self, rows: int) -> str:
        """Batch-bucket label for metrics/traces: the smallest serving
        bucket that fits ``rows`` (the padding target the session will
        actually run), or the raw row count for bucketless sessions
        (bounded: rows <= max_batch)."""
        session = self.session
        buckets = getattr(session, "buckets", None) \
            or getattr(session, "batch_buckets", None)
        if buckets:
            for b in sorted(buckets):
                if rows <= b:
                    return str(b)
            return str(sorted(buckets)[-1])
        return str(rows)

    def infer(self, inputs: Dict[str, np.ndarray],
              timeout: float = 30.0,
              deadline_ms: Optional[float] = None,
              trace=None) -> np.ndarray:
        """Blocking single-request API (each row batch is one request).

        ``deadline_ms`` (or the scheduler's ``default_deadline_ms``)
        bounds the request end-to-end: admission control fast-fails
        when the admission estimate (queue wait, plus one batch's
        service EWMA under ``admission_estimate="completion"``)
        already exceeds it
        (:class:`DeadlineRejectedError`), a queued request whose
        deadline passes is failed without a device step, and a timed-out
        wait marks the request abandoned so it cannot be batched later.
        Raises :class:`QueueFullError` / :class:`CircuitOpenError` /
        :class:`DrainingError` for the shedding cases (HTTP 503) and
        :class:`InvalidInputError` for malformed inputs (HTTP 400).

        ``trace`` is the request's lifecycle
        :class:`~..obs.request_trace.RequestTrace` (the HTTP fronts
        pass one carrying the client's ``x-ff-trace-id``); when tracing
        is enabled and none is given the scheduler starts its own, so
        direct API callers get linked spans too. Every terminal path
        records the outcome on the trace's response span."""
        if trace is None:
            trace = request_trace.start(model=self.metrics.name)
        with self._stat_lock:
            draining = self._draining
        if draining:
            self.metrics.record_rejected()
            if trace is not None:
                trace.finish("rejected", reason="draining")
            raise DrainingError(
                f"model {self.metrics.name!r} is draining for shutdown",
                retry_after_s=5.0)
        try:
            arrs, rows = self._validate(inputs)
        except InvalidInputError:
            if trace is not None:
                trace.finish("invalid")
            raise
        bucket = self._bucket_label(rows)
        admitted, retry_after, probe = self.breaker.allow()
        if not admitted:
            self.metrics.record_rejected()
            if trace is not None:
                trace.finish("breaker", bucket=bucket)
            raise CircuitOpenError(
                f"circuit open for model {self.metrics.name!r} after "
                f"repeated session failures; retry in {retry_after:.1f}s",
                retry_after_s=max(retry_after, 0.05))
        dl_ms = deadline_ms if deadline_ms is not None \
            else self.default_deadline_ms
        deadline = None
        if dl_ms is not None and dl_ms > 0:
            deadline = time.perf_counter() + dl_ms / 1e3
            est = self.estimated_wait_s()
            if self.admission_estimate == "completion":
                # shed on predicted COMPLETION, not queue entry: a
                # request admitted with the queue wait just under its
                # deadline still pays its own batch's service time and
                # would predictably complete late (burning a device
                # step the deadline turns into a 504/SLO violation)
                with self._stat_lock:
                    svc = self._ewma_batch_s or 0.0
                est += svc / max(1, self.num_instances)
            if est > dl_ms / 1e3:
                if probe:
                    # the probe dies before execution: its outcome says
                    # nothing about model health, so the slot must not
                    # stay held or half-open would wedge forever
                    self.breaker.release_probe()
                self.metrics.record_deadline_rejected(bucket=bucket)
                if trace is not None:
                    trace.finish("deadline-rejected", bucket=bucket,
                                 estimated_wait_ms=round(est * 1e3, 3))
                what = ("estimated completion"
                        if self.admission_estimate == "completion"
                        else "estimated queue wait")
                raise DeadlineRejectedError(
                    f"{what} {est * 1e3:.0f} ms exceeds "
                    f"the request deadline {dl_ms:.0f} ms",
                    retry_after_s=max(est - dl_ms / 1e3, 0.1))
        r = _Request(arrs, rows, deadline, probe=probe, trace=trace,
                     bucket=bucket)
        # count the rows BEFORE the put: a worker popping the request
        # immediately would otherwise decrement first and drive the
        # admission backlog transiently negative under load
        with self._stat_lock:
            self._queued_rows += rows
            self._pending += 1
        try:
            self._q.put_nowait(r)
        except queue.Full:
            with self._stat_lock:
                self._queued_rows -= rows
                self._pending -= 1
            if probe:
                self.breaker.release_probe()
            self.metrics.record_rejected()
            if trace is not None:
                trace.finish("rejected", reason="queue-full",
                             bucket=bucket)
            raise QueueFullError(
                f"request queue full ({self._q.maxsize}); retry later")
        self.metrics.record_submitted()
        if self._stop.is_set():
            # raced close(): its sweep may already have passed this
            # request, leaving it on a queue no worker reads — re-run
            # the sweep so the client fails promptly, not at timeout
            self._fail_queued()
        wait_s = timeout
        if deadline is not None:
            wait_s = min(timeout,
                         max(deadline - time.perf_counter(), 0.0))
        # a huge or inf timeout/deadline (API callers) must not
        # OverflowError out of Event.wait with the request enqueued —
        # the orphan would still consume a device step
        wait_s = min(wait_s, threading.TIMEOUT_MAX)
        if not r.event.wait(wait_s):
            # mark abandoned so the workers skip it at dequeue time —
            # a timed-out client must never consume a device step
            r.abandoned = True
            if deadline is not None \
                    and time.perf_counter() >= deadline:
                if trace is not None:
                    trace.finish("expired", r.t0, bucket=bucket,
                                 reason="deadline")
                raise DeadlineExceededError(
                    f"request deadline ({dl_ms:.0f} ms) exceeded")
            if trace is not None:
                trace.finish("expired", r.t0, bucket=bucket,
                             reason="client-timeout")
            raise TimeoutError("inference request timed out")
        if r.error is not None:
            raise r.error
        return r.result

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    def stats(self) -> Dict:
        """Scheduler snapshot + circuit/drain state (the per-model row
        of ``GET /v2/metrics`` and the ``/healthz`` serving block)."""
        s = self.metrics.snapshot(self._q.qsize())
        s["instances"] = self.num_instances
        # routing signal + mergeable sketches for a fleet front: wait
        # BEFORE _stat_lock below (estimated_wait_s acquires it; the
        # queue lock is not reentrant)
        s["estimated_wait_s"] = self.estimated_wait_s()
        s["sketches"] = self.metrics.sketch_docs()
        # benign: atomic read of the state string for a health probe —
        # /healthz must stay cheap (PR 5) and a probe racing a breaker
        # transition just reports the old state for one scrape
        s["circuit"] = self.breaker.state  # ffcheck: ok(guarded-field)
        with self._stat_lock:
            s["draining"] = self._draining
        return s

    def drain(self, deadline_s: float = 10.0) -> bool:
        """Graceful drain: stop admitting (``infer`` raises
        :class:`DrainingError` -> HTTP 503 + ``Retry-After``), finish
        everything queued or executing within ``deadline_s``, then
        close. Returns True when nothing was left behind."""
        # under the stat lock: the admission read in infer() must see
        # either pre-drain or drain, never a torn intermediate with the
        # backlog counters (the drain-vs-unload snapshot race PR 5's
        # review found by hand is exactly this class)
        with self._stat_lock:
            self._draining = True
        end = time.perf_counter() + max(0.0, deadline_s)
        while time.perf_counter() < end:
            with self._stat_lock:
                idle = self._pending == 0
            if idle:
                break
            time.sleep(0.005)
        with self._stat_lock:
            clean = self._pending == 0
        self.close()
        return clean

    def hot_swap(self, sessions, deadline_s: float = 10.0) -> bool:
        """Replace the serving instances in place under the graceful-
        drain protocol: admission pauses (``infer`` sheds with 503 +
        ``Retry-After``), the admitted backlog flushes on the OLD
        instances within ``deadline_s``, the workers restart on the new
        ones, and admission resumes. The adoption point for a
        re-searched serving plan (``ModelRepository.hot_swap``): no
        admitted request is dropped, late arrivals are shed exactly as
        during a drain. Returns True when the backlog flushed clean."""
        if not isinstance(sessions, (list, tuple)):
            sessions = [sessions]
        if not sessions:
            raise ValueError("need at least one session instance")
        with self._stat_lock:
            self._draining = True
        end = time.perf_counter() + max(0.0, deadline_s)
        while time.perf_counter() < end:
            with self._stat_lock:
                if self._pending == 0:
                    break
            time.sleep(0.005)
        with self._stat_lock:
            clean = self._pending == 0
        # stop the old workers (the queue is empty or past-deadline:
        # anything still queued re-queues onto the new workers' event)
        self._stop.set()
        for w in self._workers:
            w.join(timeout=5)
        self._stop = threading.Event()
        self.sessions = list(sessions)
        self.session = self.sessions[0]
        self._workers = [
            threading.Thread(target=self._run, args=(s,), daemon=True)
            for s in self.sessions]
        for w in self._workers:
            w.start()
        with self._stat_lock:
            self._draining = False
        return clean

    def close(self):
        """Stop the workers and promptly fail anything still queued —
        an unload must not leave clients blocked until their timeout."""
        self._stop.set()
        for w in self._workers:
            w.join(timeout=5)
        self._fail_queued()

    def _fail_queued(self):
        """Fail everything still queued (no worker will ever pop it):
        close()'s sweep, re-run by any ``infer`` whose enqueue raced
        past it."""
        while True:
            try:
                r = self._q.get_nowait()
            except queue.Empty:
                return
            with self._stat_lock:
                self._queued_rows -= r.rows
                self._pending -= 1
            if r.probe:
                self.breaker.release_probe()
            # a shed, not a client error: RequestRejected maps to 503 +
            # Retry-After so retry-aware clients try another replica.
            # Counted as expired (never consumed a device step), NOT
            # failed — ff_requests_failed_total is a model-health
            # signal and must not fire on routine unload/shutdown
            r.error = RequestRejected(
                "scheduler closed (model unloaded or shut down); "
                "retry against another replica", retry_after_s=5.0)
            self.metrics.record_expired(bucket=r.bucket)
            if r.trace is not None:
                r.trace.finish("expired", r.t0, bucket=r.bucket,
                               reason="closed")
            r.event.set()

    # ------------------------------------------------------------------
    # worker side
    # ------------------------------------------------------------------
    def _expire(self, r: _Request, active: bool = False):
        """Fail a request without running it (deadline passed or client
        abandoned) — it never consumes a device step. ``active`` marks
        requests already counted in flight (the individual-retry path);
        an expired probe gives its half-open slot back."""
        with self._stat_lock:
            self._pending -= 1
            if active:
                self._active -= 1
                self._active_rows -= r.rows
        if r.probe:
            self.breaker.release_probe()
        r.error = DeadlineExceededError(
            "request expired in queue before reaching a device step")
        # an expired request with a deadline missed its SLO; a merely
        # abandoned one (client timeout shorter than any deadline) did
        # not breach a deadline the server agreed to
        missed = (r.deadline is not None
                  and time.perf_counter() >= r.deadline)
        self.metrics.record_expired(bucket=r.bucket,
                                    deadline_missed=missed)
        if r.trace is not None:
            r.trace.finish("expired", r.t0, bucket=r.bucket,
                           reason="queue-expired")
        r.event.set()

    def _take(self, timeout: float) -> Optional[_Request]:
        """Pop the next LIVE request; expired/abandoned ones are failed
        on the spot and skipped. None on timeout."""
        end = time.perf_counter() + timeout
        while True:
            remaining = end - time.perf_counter()
            if remaining <= 0:
                return None
            try:
                r = self._q.get(timeout=remaining)
            except queue.Empty:
                return None
            with self._stat_lock:
                self._queued_rows -= r.rows
            if r.trace is not None:
                # queue-wait span for live AND expired requests: the
                # expired trace must still show where the time went
                r.trace.stage("queue", r.t0, bucket=r.bucket,
                              rows=r.rows)
            if r.abandoned or (r.deadline is not None
                               and time.perf_counter() >= r.deadline):
                self._expire(r)
                continue
            with self._stat_lock:
                self._active += 1
                self._active_rows += r.rows
            return r

    def _drain(self) -> List[_Request]:
        """Block for one live request, then batch whatever arrives
        within the delay window (up to max_batch rows)."""
        first = self._take(0.1)
        if first is None:
            return []
        batch = [first]
        rows = first.rows
        deadline = self.max_delay_s
        t0 = time.perf_counter()
        while rows < self.max_batch:
            remaining = deadline - (time.perf_counter() - t0)
            if remaining <= 0:
                break
            r = self._take(remaining)
            if r is None:
                break
            batch.append(r)
            rows += r.rows
        return batch

    def _finish_ok(self, r: _Request, now: float):
        with self._stat_lock:
            self._pending -= 1
            self._active -= 1
            self._active_rows -= r.rows
        missed = r.deadline is not None and now > r.deadline
        self.metrics.record_done(now - r.t0, ok=True, bucket=r.bucket,
                                 deadline_missed=missed)
        if r.trace is not None:
            # finish BEFORE event.set: the waiter (or the HTTP layer
            # above it) sees the latch already taken and cannot record
            # a second, less precise outcome
            if missed:
                r.trace.finish("ok", r.t0, bucket=r.bucket,
                               deadline_missed=True)
            else:
                r.trace.finish("ok", r.t0, bucket=r.bucket)
        r.event.set()

    def _finish_error(self, r: _Request, e: Exception):
        with self._stat_lock:
            self._pending -= 1
            self._active -= 1
            self._active_rows -= r.rows
        r.error = e
        self.metrics.record_done(time.perf_counter() - r.t0, ok=False,
                                 bucket=r.bucket)
        if r.trace is not None:
            r.trace.finish("failed", r.t0, bucket=r.bucket,
                           error=type(e).__name__)
        r.event.set()

    def _observe_batch_latency(self, dt: float):
        with self._stat_lock:
            if self._ewma_batch_s is None:
                self._ewma_batch_s = dt
            else:
                self._ewma_batch_s = 0.7 * self._ewma_batch_s + 0.3 * dt

    def _retry_individually(self, session, batch: List[_Request]):
        """A failed batch may contain ONE poisoned member: retry each
        request alone once so good co-batched requests still succeed
        (request-level fault isolation); only the bad member fails.
        Members whose client is gone (deadline passed or abandoned
        during the failed batch attempt) are expired instead of
        retried — no device step for work nobody is waiting on, and no
        spurious breaker feedback from it."""
        for r in batch:
            if r.abandoned or (r.deadline is not None
                               and time.perf_counter() >= r.deadline):
                self._expire(r, active=True)
                continue
            try:
                out = session.infer(r.inputs)
            except Exception as e:  # noqa: BLE001 — isolate per request
                # breaker BEFORE the event: a client retrying the
                # instant the K-th failure surfaces must hit the open
                # circuit, not race past the threshold
                self.breaker.on_failure()
                self._finish_error(r, e)
            else:
                r.result = out
                self.breaker.on_success()
                self._finish_ok(r, time.perf_counter())

    def _run(self, session):
        while not self._stop.is_set():
            batch = self._drain()
            if not batch:
                continue
            brows = sum(r.rows for r in batch)
            with self.metrics._lock:
                self.metrics.batches += 1
                self.metrics.batched_rows += brows
            t_exec = time.perf_counter()
            try:
                names = session.input_names
                stacked = {
                    n: np.concatenate([r.inputs[n] for r in batch], axis=0)
                    for n in names}
                out = session.infer(stacked)
            except Exception as e:  # noqa: BLE001 — fan the error out
                if len(batch) > 1:
                    self._retry_individually(session, batch)
                else:
                    # breaker BEFORE the event (see _retry_individually)
                    self.breaker.on_failure()
                    self._finish_error(batch[0], e)
                continue
            self._observe_batch_latency(time.perf_counter() - t_exec)
            self.breaker.on_success()
            off = 0
            now = time.perf_counter()
            for r in batch:
                r.result = out[off:off + r.rows]
                off += r.rows
                if r.trace is not None:
                    # batch-assembly + device-step span, one per member
                    # so each request's trace shows the batch it rode
                    r.trace.stage("batch", t_exec, now - t_exec,
                                  bucket=r.bucket, batch_rows=brows,
                                  batch_requests=len(batch))
                self._finish_ok(r, now)
