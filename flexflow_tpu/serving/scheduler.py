"""Dynamic micro-batching for inference requests.

Triton's dynamic batcher (``preferred_batch_size`` +
``max_queue_delay_microseconds``) reimplemented in a few hundred lines:
requests queue up; per-instance workers drain up to ``max_batch`` of
them (or whatever arrived within ``max_delay_ms``), stack them into one
device batch, and fan the result back out per request. On TPU the win is
identical to the GPU case — one big MXU-shaped batch instead of many
tiny dispatches.

Triton-scope hardening (reference ``triton/src/instance.cc``,
``backend.cc``):
  - **bounded queue + backpressure**: the queue holds at most
    ``max_queue`` requests; beyond that ``infer`` raises
    :class:`QueueFullError` (HTTP 503) instead of growing without bound;
  - **N concurrent instances**: one worker thread per model instance
    (Triton's ``instance_group { count: N }``), all draining the shared
    queue;
  - **metrics**: per-model counters + latency reservoir feeding the
    ``/v2/metrics`` endpoint (p50/p99, queue depth, batch sizes).
"""
from __future__ import annotations

import collections
import queue
import threading
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..obs.metrics_registry import DEFAULT_BUCKETS, REGISTRY

#: request-latency histogram buckets (seconds): the registry default
#: extended upward for slow generate calls
LATENCY_BUCKETS = DEFAULT_BUCKETS + (30.0,)


class QueueFullError(RuntimeError):
    """Raised by ``infer`` when the bounded request queue is full —
    callers should shed load (HTTP 503)."""


class SchedulerMetrics:
    """Thread-safe counters + latency reservoir for one scheduler.

    Doubles as the bridge into the process-wide Prometheus registry
    (``obs/metrics_registry.py``): every completion lands in the
    ``ff_request_latency_seconds`` histogram and the per-model request
    counters, labeled by model name — what ``GET /metrics`` serves."""

    def __init__(self, window: int = 2048, name: str = ""):
        self._lock = threading.Lock()
        self.name = name or "default"
        self.requests = 0
        self.completed = 0
        self.failed = 0
        self.rejected = 0
        self.batches = 0
        self.batched_rows = 0
        self._lat = collections.deque(maxlen=window)
        # registry handles resolved ONCE — the hot path below must not
        # take the registry lock for a name lookup per request
        self._m_requests = REGISTRY.counter(
            "ff_requests_total",
            "Inference requests accepted into the queue")
        self._m_rejected = REGISTRY.counter(
            "ff_requests_rejected_total",
            "Requests shed by bounded-queue backpressure")
        self._m_failed = REGISTRY.counter(
            "ff_requests_failed_total",
            "Requests completed with an error")
        self._m_latency = REGISTRY.histogram(
            "ff_request_latency_seconds",
            "End-to-end request latency (queue + batch assembly + "
            "device step)", buckets=LATENCY_BUCKETS)

    def record_submitted(self):
        with self._lock:
            self.requests += 1
        self._m_requests.inc(model=self.name)

    def record_rejected(self):
        with self._lock:
            self.rejected += 1
        self._m_rejected.inc(model=self.name)

    def record_done(self, latency_s: float, ok: bool):
        with self._lock:
            self.completed += ok
            self.failed += (not ok)
            self._lat.append(latency_s)
        self._m_latency.observe(latency_s, model=self.name)
        if not ok:
            self._m_failed.inc(model=self.name)

    def snapshot(self, queue_depth: int) -> Dict:
        with self._lock:
            lat = sorted(self._lat)
            pct = (lambda p: lat[min(len(lat) - 1,
                                     int(p * len(lat)))] if lat else 0.0)
            return {
                "requests": self.requests,
                "completed": self.completed,
                "failed": self.failed,
                "rejected": self.rejected,
                "batches": self.batches,
                "mean_batch_rows": (self.batched_rows
                                    / max(self.batches, 1)),
                "queue_depth": queue_depth,
                "latency_p50_ms": round(pct(0.50) * 1e3, 3),
                "latency_p99_ms": round(pct(0.99) * 1e3, 3),
            }


class _Request:
    __slots__ = ("inputs", "event", "result", "error", "t0")

    def __init__(self, inputs):
        self.inputs = inputs
        self.event = threading.Event()
        self.result = None
        self.error: Optional[Exception] = None
        self.t0 = time.perf_counter()


class BatchScheduler:
    """Bounded queue + N instance workers around
    :class:`InferenceSession` replicas.

    ``sessions`` may be one session or a list (one per concurrent
    instance — Triton's instance group); each gets its own worker
    thread draining the shared queue.
    """

    def __init__(self, sessions, max_batch: int = 64,
                 max_delay_ms: float = 2.0, max_queue: int = 256,
                 name: str = ""):
        if not isinstance(sessions, (list, tuple)):
            sessions = [sessions]
        assert sessions, "need at least one session instance"
        self.sessions: List = list(sessions)
        self.session = self.sessions[0]    # back-compat alias
        self.max_batch = max_batch
        self.max_delay_s = max_delay_ms / 1e3
        self.metrics = SchedulerMetrics(name=name)
        self._q: "queue.Queue[_Request]" = queue.Queue(maxsize=max_queue)
        self._stop = threading.Event()
        self._workers = [
            threading.Thread(target=self._run, args=(s,), daemon=True)
            for s in self.sessions]
        for w in self._workers:
            w.start()

    @property
    def num_instances(self) -> int:
        return len(self.sessions)

    # ------------------------------------------------------------------
    def infer(self, inputs: Dict[str, np.ndarray],
              timeout: float = 30.0) -> np.ndarray:
        """Blocking single-request API (each row batch is one request).
        Raises :class:`QueueFullError` when the bounded queue is full."""
        r = _Request(inputs)
        try:
            self._q.put_nowait(r)
        except queue.Full:
            self.metrics.record_rejected()
            raise QueueFullError(
                f"request queue full ({self._q.maxsize}); retry later")
        self.metrics.record_submitted()
        if not r.event.wait(timeout):
            raise TimeoutError("inference request timed out")
        if r.error is not None:
            raise r.error
        return r.result

    def close(self):
        """Stop the workers and promptly fail anything still queued —
        an unload must not leave clients blocked until their timeout."""
        self._stop.set()
        for w in self._workers:
            w.join(timeout=5)
        while True:
            try:
                r = self._q.get_nowait()
            except queue.Empty:
                break
            r.error = RuntimeError("scheduler closed (model unloaded)")
            self.metrics.record_done(time.perf_counter() - r.t0,
                                     ok=False)
            r.event.set()

    # ------------------------------------------------------------------
    def _drain(self) -> List[_Request]:
        """Block for one request, then batch whatever arrives within the
        delay window (up to max_batch rows)."""
        try:
            first = self._q.get(timeout=0.1)
        except queue.Empty:
            return []
        batch = [first]
        rows = int(next(iter(first.inputs.values())).shape[0])
        deadline = self.max_delay_s
        t0 = time.perf_counter()
        while rows < self.max_batch:
            remaining = deadline - (time.perf_counter() - t0)
            if remaining <= 0:
                break
            try:
                r = self._q.get(timeout=remaining)
            except queue.Empty:
                break
            batch.append(r)
            rows += int(next(iter(r.inputs.values())).shape[0])
        return batch

    def _run(self, session):
        while not self._stop.is_set():
            batch = self._drain()
            if not batch:
                continue
            with self.metrics._lock:
                self.metrics.batches += 1
                self.metrics.batched_rows += sum(
                    int(next(iter(r.inputs.values())).shape[0])
                    for r in batch)
            try:
                names = session.input_names
                stacked = {
                    n: np.concatenate([r.inputs[n] for r in batch], axis=0)
                    for n in names}
                out = session.infer(stacked)
            except Exception as e:  # noqa: BLE001 — fan the error out
                now = time.perf_counter()
                for r in batch:
                    r.error = e
                    self.metrics.record_done(now - r.t0, ok=False)
                    r.event.set()
                continue
            off = 0
            now = time.perf_counter()
            for r in batch:
                n = int(next(iter(r.inputs.values())).shape[0])
                r.result = out[off:off + n]
                off += n
                self.metrics.record_done(now - r.t0, ok=True)
                r.event.set()
