"""Dynamic micro-batching for inference requests.

Triton's dynamic batcher (``preferred_batch_size`` +
``max_queue_delay_microseconds``) reimplemented in ~100 lines: requests
queue up; a worker drains up to ``max_batch`` of them (or whatever
arrived within ``max_delay_ms``), stacks them into one device batch, and
fans the result back out per request. On TPU the win is identical to the
GPU case — one big MXU-shaped batch instead of many tiny dispatches.
"""
from __future__ import annotations

import queue
import threading
from typing import Dict, List, Optional

import numpy as np


class _Request:
    __slots__ = ("inputs", "event", "result", "error")

    def __init__(self, inputs):
        self.inputs = inputs
        self.event = threading.Event()
        self.result = None
        self.error: Optional[Exception] = None


class BatchScheduler:
    """Queue + worker thread around an :class:`InferenceSession`."""

    def __init__(self, session, max_batch: int = 64,
                 max_delay_ms: float = 2.0):
        self.session = session
        self.max_batch = max_batch
        self.max_delay_s = max_delay_ms / 1e3
        self._q: "queue.Queue[_Request]" = queue.Queue()
        self._stop = threading.Event()
        self._worker = threading.Thread(target=self._run, daemon=True)
        self._worker.start()

    # ------------------------------------------------------------------
    def infer(self, inputs: Dict[str, np.ndarray],
              timeout: float = 30.0) -> np.ndarray:
        """Blocking single-request API (each row batch is one request)."""
        r = _Request(inputs)
        self._q.put(r)
        if not r.event.wait(timeout):
            raise TimeoutError("inference request timed out")
        if r.error is not None:
            raise r.error
        return r.result

    def close(self):
        self._stop.set()
        self._worker.join(timeout=5)

    # ------------------------------------------------------------------
    def _drain(self) -> List[_Request]:
        """Block for one request, then batch whatever arrives within the
        delay window (up to max_batch rows)."""
        try:
            first = self._q.get(timeout=0.1)
        except queue.Empty:
            return []
        batch = [first]
        rows = int(next(iter(first.inputs.values())).shape[0])
        deadline = self.max_delay_s
        import time
        t0 = time.perf_counter()
        while rows < self.max_batch:
            remaining = deadline - (time.perf_counter() - t0)
            if remaining <= 0:
                break
            try:
                r = self._q.get(timeout=remaining)
            except queue.Empty:
                break
            batch.append(r)
            rows += int(next(iter(r.inputs.values())).shape[0])
        return batch

    def _run(self):
        while not self._stop.is_set():
            batch = self._drain()
            if not batch:
                continue
            try:
                names = self.session.input_names
                stacked = {
                    n: np.concatenate([r.inputs[n] for r in batch], axis=0)
                    for n in names}
                out = self.session.infer(stacked)
            except Exception as e:  # noqa: BLE001 — fan the error out
                for r in batch:
                    r.error = e
                    r.event.set()
                continue
            off = 0
            for r in batch:
                n = int(next(iter(r.inputs.values())).shape[0])
                r.result = out[off:off + n]
                off += n
                r.event.set()
