"""PCG graph structure: nodes with parallel annotations, explicit edges,
hashing, dominators, dot export, and conversion to/from the Layer level.

Reference analogs: ``PCG::Graph``/``Edge``/``Node`` (``include/flexflow/
graph.h:293-377``, ``include/flexflow/node.h``, ``src/runtime/graph.cc``);
``create_operators_from_layers`` (``src/runtime/model.cc:2785``) ≙
``Graph.from_layers``; ``convert_graph_to_operators`` (``model.cc:2834``) ≙
``Graph.to_program``. Parallel annotations replace the reference's
``ParallelDim{degree, parallel_idx}`` records (``parallel_tensor.h:36-70``):
an annotation names *axis groups* (degree-sized slices of the global mesh)
and places them on output dims / weight dims, with an optional partial-sum
group that a downstream Reduction resolves.
"""
from __future__ import annotations

import dataclasses
import itertools
from typing import Any, Dict, Iterable, List, Optional, Sequence, Set, Tuple

from ..core.layer import Layer
from ..core.tensor import Tensor
from ..ffconst import OperatorType, PARALLEL_OPS

_node_uid = itertools.count()


# ---------------------------------------------------------------------------
# Parallel annotation
# ---------------------------------------------------------------------------
@dataclasses.dataclass(frozen=True)
class ParAnn:
    """Parallel annotation of one PCG node.

    ``groups``: (group_name, degree) pairs — each group is realized as a
    disjoint set of atomic mesh axes whose sizes multiply to ``degree``.
    ``out``: (out_idx, dim, group) placements on output tensors.
    ``weights``: (weight_name, dim, group) placements on weight tensors.
    ``reduce``: group over which outputs are partial sums awaiting a
    Reduction parallel op (row-parallel linear etc.).
    ``replicate``: group over which the op's *inputs* are replicated
    (pure fan-out; affects cost, not output layout).
    """
    groups: Tuple[Tuple[str, int], ...] = ()
    out: Tuple[Tuple[int, int, str], ...] = ()
    weights: Tuple[Tuple[str, int, str], ...] = ()
    reduce: Optional[str] = None
    replicate: Optional[str] = None

    def degree_of(self, group: str) -> int:
        for g, d in self.groups:
            if g == group:
                return d
        return 1

    def out_degrees(self, out_idx: int = 0) -> Dict[int, int]:
        """dim -> degree for one output tensor."""
        degs: Dict[int, int] = {}
        for oi, dim, g in self.out:
            if oi == out_idx:
                degs[dim] = degs.get(dim, 1) * self.degree_of(g)
        return degs

    def weight_degree(self) -> int:
        """Total shard degree over all weight placements (per unique group)."""
        used = {g for (_, _, g) in self.weights}
        d = 1
        for g in used:
            d *= self.degree_of(g)
        return d

    def total_degree(self) -> int:
        d = 1
        for _, deg in self.groups:
            d *= deg
        return d

    def is_trivial(self) -> bool:
        return not self.groups

    @staticmethod
    def trivial() -> "ParAnn":
        return _TRIVIAL


_TRIVIAL = ParAnn()


# ---------------------------------------------------------------------------
# Nodes and edges
# ---------------------------------------------------------------------------
class PNode:
    """PCG node: a Layer plus its parallel annotation.

    Layers are shared (read-only) across candidate graphs during search;
    only the annotation differs — the analog of the reference's
    (``Op``, ``MachineView``) pair.
    """
    __slots__ = ("layer", "ann", "guid")

    def __init__(self, layer: Layer, ann: ParAnn = _TRIVIAL):
        self.layer = layer
        self.ann = ann
        self.guid = next(_node_uid)

    @property
    def op_type(self) -> OperatorType:
        return self.layer.op_type

    def with_ann(self, ann: ParAnn) -> "PNode":
        return PNode(self.layer, ann)

    def key(self) -> Tuple:
        """Structural identity (for graph hashing): op params + annotation,
        NOT the guid — two nodes with the same layer+ann are equivalent."""
        return (self.layer.guid, self.ann)

    def __repr__(self):
        a = "" if self.ann.is_trivial() else f" ann={self.ann.groups}"
        return f"PNode({self.layer.name}{a})"


@dataclasses.dataclass(frozen=True)
class Edge:
    """src output ``src_idx`` feeds dst input slot ``dst_idx``."""
    src: PNode
    dst: PNode
    src_idx: int = 0
    dst_idx: int = 0


class GraphProgramInfo:
    """Result of ``Graph.to_program``: executable layer list (topo order,
    with freshly-plumbed tensors for inserted parallel ops) plus the
    node -> executable-layer mapping for strategy extraction."""

    def __init__(self, layers: List[Layer], node_to_layer: Dict[int, Layer],
                 output_tensors: List[Tensor]):
        self.layers = layers
        self.node_to_layer = node_to_layer  # PNode.guid -> executable Layer
        self.output_tensors = output_tensors


# ---------------------------------------------------------------------------
# Graph
# ---------------------------------------------------------------------------
class Graph:
    """Op-level DAG with in/out edge maps (reference ``PCG::Graph``)."""

    def __init__(self):
        self.in_edges: Dict[PNode, List[Edge]] = {}
        self.out_edges: Dict[PNode, List[Edge]] = {}
        # input tensors feeding source nodes: node guid -> list of
        # (in_slot, Tensor) for graph-external inputs
        self.external_inputs: Dict[int, List[Tuple[int, Tensor]]] = {}
        self.input_tensors: List[Tensor] = []
        # (node, out_idx) pairs that are graph outputs
        self.outputs: List[Tuple[PNode, int]] = []

    # -- construction ------------------------------------------------------
    def add_node(self, node: PNode):
        self.in_edges.setdefault(node, [])
        self.out_edges.setdefault(node, [])

    def add_edge(self, src: PNode, dst: PNode, src_idx: int = 0,
                 dst_idx: int = 0):
        self.add_node(src)
        self.add_node(dst)
        e = Edge(src, dst, src_idx, dst_idx)
        self.in_edges[dst].append(e)
        self.out_edges[src].append(e)

    def remove_node(self, node: PNode):
        for e in list(self.in_edges.get(node, ())):
            self.out_edges[e.src].remove(e)
        for e in list(self.out_edges.get(node, ())):
            self.in_edges[e.dst].remove(e)
        self.in_edges.pop(node, None)
        self.out_edges.pop(node, None)
        self.external_inputs.pop(node.guid, None)

    def remove_edge(self, e: Edge):
        self.in_edges[e.dst].remove(e)
        self.out_edges[e.src].remove(e)

    @property
    def nodes(self) -> List[PNode]:
        return list(self.in_edges.keys())

    def num_nodes(self) -> int:
        return len(self.in_edges)

    def producer(self, node: PNode, in_slot: int) -> Optional[Edge]:
        for e in self.in_edges.get(node, ()):
            if e.dst_idx == in_slot:
                return e
        return None

    # -- queries -----------------------------------------------------------
    def topo_order(self) -> List[PNode]:
        indeg = {n: len(self.in_edges[n]) for n in self.in_edges}
        # Deterministic order: seed queue sorted by guid.
        ready = sorted((n for n, d in indeg.items() if d == 0),
                       key=lambda n: n.guid)
        order: List[PNode] = []
        while ready:
            n = ready.pop(0)
            order.append(n)
            fresh = []
            for e in self.out_edges[n]:
                indeg[e.dst] -= 1
                if indeg[e.dst] == 0:
                    fresh.append(e.dst)
            if fresh:
                ready = sorted(ready + fresh, key=lambda x: x.guid)
        if len(order) != self.num_nodes():
            raise RuntimeError("cycle in PCG")
        return order

    def hash(self) -> int:
        """Structural hash: order-independent over (node key, edge keys).
        Analog of the reference's graph hash used for search memoization."""
        h = 17
        items = []
        for n in self.in_edges:
            items.append(("n",) + n.key())
        for edges in self.in_edges.values():
            for e in edges:
                items.append(("e", e.src.key(), e.dst.key(),
                              e.src_idx, e.dst_idx))
        for v in sorted(hash(i) for i in items):
            h = (h * 1000000007 + v) & ((1 << 64) - 1)
        return h

    def check_consistency(self) -> List[str]:
        """Structural validation (reference ``check_correctness``)."""
        errs = []
        for n, edges in self.in_edges.items():
            slots = [e.dst_idx for e in edges]
            slots += [s for s, _ in self.external_inputs.get(n.guid, ())]
            if len(slots) != len(set(slots)):
                errs.append(f"{n}: duplicate input slots {slots}")
            arity = len(n.layer.inputs)
            if n.op_type not in (OperatorType.OP_INPUT,) and \
                    len(slots) != arity:
                errs.append(f"{n}: {len(slots)} inputs wired, arity {arity}")
        for n, edges in self.out_edges.items():
            for e in edges:
                if e not in self.in_edges[e.dst]:
                    errs.append(f"dangling edge {e}")
        # acyclicity via the native reachability closure when built
        # (bitset transitive closure, flexflow_tpu/native/src/ffruntime.cc)
        try:
            from .. import native
            nodes = self.nodes
            index = {n.guid: i for i, n in enumerate(nodes)}
            edges = [(index[e.src.guid], index[e.dst.guid])
                     for es in self.out_edges.values() for e in es]
            native.transitive_closure(len(nodes), edges)
        except ValueError:
            errs.append("graph contains a cycle")
        return errs

    # -- dominators (for Unity sequence splits) ----------------------------
    def post_dominators(self) -> Dict[PNode, Set[PNode]]:
        """node -> set of nodes on EVERY path from node to the sink(s).
        Single-cut "bottleneck" nodes for sequence splitting are nodes that
        post-dominate all source nodes. Reference: ``src/runtime/graph.cc``
        dominator machinery (tested by ``tests/unit/test_dominators.cc``)."""
        order = self.topo_order()
        sinks = [n for n in order if not self.out_edges[n]]
        pdom: Dict[PNode, Set[PNode]] = {}
        allset = set(order)
        for n in reversed(order):
            succs = [e.dst for e in self.out_edges[n]]
            if not succs:
                pdom[n] = {n}
                continue
            inter: Optional[Set[PNode]] = None
            for s in succs:
                inter = set(pdom[s]) if inter is None else inter & pdom[s]
            # Multiple sinks: a node reaching several sinks is post-dominated
            # only by common post-dominators of all of them.
            pdom[n] = (inter or set()) | {n}
        # Nodes reaching different sinks: intersect via the virtual sink =
        # already handled since pdom(sink)={sink}; intersection across sinks
        # empties unless common.
        del allset, sinks
        return pdom

    def bottlenecks(self) -> List[PNode]:
        """Nodes through which every source→sink path passes, in topo order
        (excluding sources and sinks themselves is left to the caller).
        These are the sequence-split points of the Unity DP
        (``substitution.cc:2572``)."""
        order = self.topo_order()
        sources = [n for n in order if not self.in_edges[n]]
        if not sources:
            return []
        pdom = self.post_dominators()
        common: Optional[Set[PNode]] = None
        for s in sources:
            common = set(pdom[s]) if common is None else common & pdom[s]
        common = common or set()
        return [n for n in order if n in common]

    # -- split (Unity sequence decomposition) ------------------------------
    def split_at(self, node: PNode) -> Tuple["Graph", "Graph"]:
        """Sequence-split into (prefix incl. node, suffix) at a bottleneck.
        The suffix consumes the bottleneck's outputs as external inputs."""
        order = self.topo_order()
        idx = order.index(node)
        pre_nodes = set(order[: idx + 1])
        first, second = Graph(), Graph()
        for n in order:
            g = first if n in pre_nodes else second
            g.add_node(n)
            for s, t in self.external_inputs.get(n.guid, ()):
                g.external_inputs.setdefault(n.guid, []).append((s, t))
        for edges in self.in_edges.values():
            for e in edges:
                if e.src in pre_nodes and e.dst in pre_nodes:
                    first.add_edge(e.src, e.dst, e.src_idx, e.dst_idx)
                elif e.src not in pre_nodes and e.dst not in pre_nodes:
                    second.add_edge(e.src, e.dst, e.src_idx, e.dst_idx)
                else:
                    # crossing edge: becomes an output of `first` and an
                    # external input of `second`
                    t = e.src.layer.outputs[e.src_idx]
                    if (e.src, e.src_idx) not in first.outputs:
                        first.outputs.append((e.src, e.src_idx))
                    second.external_inputs.setdefault(
                        e.dst.guid, []).append((e.dst_idx, t))
        first.input_tensors = list(self.input_tensors)
        if not first.outputs:
            first.outputs = [(node, 0)]
        second.outputs = list(self.outputs)
        return first, second

    # -- copy --------------------------------------------------------------
    def copy(self) -> "Graph":
        g = Graph()
        for n in self.in_edges:
            g.add_node(n)
        for edges in self.in_edges.values():
            for e in edges:
                g.add_edge(e.src, e.dst, e.src_idx, e.dst_idx)
        g.external_inputs = {k: list(v)
                             for k, v in self.external_inputs.items()}
        g.input_tensors = list(self.input_tensors)
        g.outputs = list(self.outputs)
        return g

    def replace_node(self, old: PNode, new: PNode):
        """Swap a node keeping all edges (e.g. re-annotate in place)."""
        self.add_node(new)
        for e in list(self.in_edges[old]):
            self.add_edge(e.src, new, e.src_idx, e.dst_idx)
        for e in list(self.out_edges[old]):
            self.add_edge(new, e.dst, e.src_idx, e.dst_idx)
        if old.guid in self.external_inputs:
            self.external_inputs[new.guid] = self.external_inputs.pop(
                old.guid)
        self.outputs = [(new, i) if n is old else (n, i)
                        for n, i in self.outputs]
        self.remove_node(old)

    # -- build from the Layer level ---------------------------------------
    @classmethod
    def from_layers(cls, layers: Sequence[Layer],
                    input_tensors: Sequence[Tensor],
                    output_tensors: Optional[Sequence[Tensor]] = None
                    ) -> "Graph":
        g = cls()
        g.input_tensors = list(input_tensors)
        producer: Dict[int, Tuple[PNode, int]] = {}
        nodes: Dict[int, PNode] = {}
        for layer in layers:
            n = PNode(layer)
            nodes[layer.guid] = n
            g.add_node(n)
            for o_idx, o in enumerate(layer.outputs):
                producer[o.guid] = (n, o_idx)
        input_guids = {t.guid: t for t in input_tensors}
        for layer in layers:
            n = nodes[layer.guid]
            for slot, t in enumerate(layer.inputs):
                if t.guid in producer:
                    src, src_idx = producer[t.guid]
                    g.add_edge(src, n, src_idx, slot)
                else:
                    # graph-external input (dataloader-fed or constant)
                    g.external_inputs.setdefault(n.guid, []).append(
                        (slot, t))
        if output_tensors:
            for t in output_tensors:
                if t.guid not in producer:
                    raise ValueError(
                        f"output {t.name} has no producer")
                g.outputs.append(producer[t.guid])
        else:
            for n in g.topo_order():
                if not g.out_edges[n]:
                    g.outputs.append((n, 0))
        del input_guids
        return g

    # -- convert back to an executable Layer program -----------------------
    def to_program(self) -> GraphProgramInfo:
        """Rebuild an executable layer list in topo order, re-plumbing
        tensors through inserted parallel-op nodes. Reference:
        ``convert_graph_to_operators`` (``model.cc:2834-2838``)."""
        order = self.topo_order()
        # (node guid, out idx) -> live Tensor
        live: Dict[Tuple[int, int], Tensor] = {}
        out_layers: List[Layer] = []
        node_to_layer: Dict[int, Layer] = {}
        used_names: Dict[str, int] = {}
        for n in order:
            orig = n.layer
            # Resolve this node's input tensors.
            ins: List[Optional[Tensor]] = [None] * max(
                len(orig.inputs),
                1 if (self.in_edges[n] or
                      self.external_inputs.get(n.guid)) else 0)
            for e in self.in_edges[n]:
                ins[e.dst_idx] = live[(e.src.guid, e.src_idx)]
            for slot, t in self.external_inputs.get(n.guid, ()):
                ins[slot] = t
            if any(i is None for i in ins):
                raise RuntimeError(f"{n}: unwired input slot")
            same_inputs = len(ins) == len(orig.inputs) and all(
                a is b for a, b in zip(ins, orig.inputs))
            if same_inputs:
                new_layer = orig
            else:
                new_layer = Layer(orig.op_type, None, list(ins),
                                  dict(orig.params))
                # Unique but stable name; strategy keys on it.
                base = orig.name
                k = used_names.get(base, 0)
                used_names[base] = k + 1
                new_layer.name = base if k == 0 else f"{base}__{k}"
                new_layer.trainable = orig.trainable
                new_layer.weights = list(orig.weights)
                for o in orig.outputs:
                    nt = Tensor(o.shape, o.dtype, owner_layer=new_layer,
                                owner_idx=o.owner_idx)
                    new_layer.outputs.append(nt)
            if used_names.get(new_layer.name) is None:
                used_names[new_layer.name] = 1
            out_layers.append(new_layer)
            node_to_layer[n.guid] = new_layer
            for i, o in enumerate(new_layer.outputs):
                live[(n.guid, i)] = o
        outs = [live[(n.guid, i)] for n, i in self.outputs]
        return GraphProgramInfo(out_layers, node_to_layer, outs)

    # -- observability -----------------------------------------------------
    def to_dot(self, costs: Optional[Dict[int, float]] = None) -> str:
        """Graphviz export (reference ``--compgraph``/``--taskgraph``,
        ``graph.h:337-344``)."""
        lines = ["digraph PCG {"]
        ids = {n: f"n{idx}" for idx, n in enumerate(self.topo_order())}
        for n, nid in ids.items():
            label = n.layer.name
            if not n.ann.is_trivial():
                label += "\\n" + ",".join(
                    f"{g}={d}" for g, d in n.ann.groups)
            if costs and n.guid in costs:
                label += f"\\n{costs[n.guid] * 1e6:.1f}us"
            shape = "ellipse" if n.op_type in PARALLEL_OPS else "box"
            lines.append(f'  {ids[n]} [label="{label}", shape={shape}];')
        for edges in self.in_edges.values():
            for e in edges:
                lines.append(f"  {ids[e.src]} -> {ids[e.dst]};")
        lines.append("}")
        return "\n".join(lines)

    def __repr__(self):
        return f"Graph({self.num_nodes()} nodes, {len(self.outputs)} outputs)"
