"""Parallel Computation Graph (PCG) — the searched graph level.

Reference analog: ``PCG::Graph`` (``include/flexflow/graph.h:293-377``,
``src/runtime/graph.cc``). Users build a lazy Layer graph; ``FFModel.compile``
lowers it to a PCG whose nodes carry *parallel annotations* (which dims are
partitioned over which mesh-axis groups, which weights co-shard, which
outputs hold partial sums) and whose communication is reified as parallel-op
nodes (Repartition / Combine / Replicate / Reduction). The auto-parallelization
search rewrites this graph; the chosen PCG converts back to an executable
program + ShardingStrategy.
"""
from .graph import Edge, Graph, PNode, ParAnn, GraphProgramInfo

__all__ = ["Edge", "Graph", "PNode", "ParAnn", "GraphProgramInfo"]
