"""Runtime configuration & flag system.

TPU-native analog of the reference's ``FFConfig`` (``include/flexflow/config.h:92-160``,
parsed in ``src/runtime/model.cc:3566-3730``). Instead of querying Legion/Realm for
nodes/GPUs, we query ``jax.devices()``; ``-ll:gpu`` becomes ``--tpus-per-node`` /
the ambient device count. All reference flags are accepted (same spellings) so
reference launch scripts port over directly.
"""
from __future__ import annotations

import dataclasses
import sys
from typing import List, Optional, Sequence


@dataclasses.dataclass
class FFConfig:
    # -------- training (reference: -e/-b/--lr/--wd/-p/-d) --------
    epochs: int = 1
    batch_size: int = 64
    learning_rate: float = 0.01
    weight_decay: float = 1e-4
    print_freq: int = 10
    dataset_path: str = ""
    # -------- machine --------
    num_nodes: int = 1
    workers_per_node: int = 0     # 0 = use all local devices
    cpus_per_node: int = 1
    # multi-host rendezvous (reference: GASNet/mpirun launch, MULTI-NODE.md;
    # here: jax.distributed — see parallel/distributed.py). Empty = also
    # honor FF_COORDINATOR_ADDRESS / FF_NUM_PROCESSES / FF_PROCESS_ID env.
    coordinator_address: str = ""
    process_id: int = -1
    # multi-process failure detection (resilience/coord.py): per-rank
    # heartbeat cadence, how long a silent peer is tolerated, and the
    # bound on every cross-rank rendezvous (checkpoint commit barriers,
    # recovery re-rendezvous). 0 = keep the coordinator defaults; the
    # FF_HB_INTERVAL_S / FF_HB_TIMEOUT_S / FF_BARRIER_TIMEOUT_S env vars
    # override both. Every wait is bounded — a timeout raises
    # RankFailure with the suspected rank attributed.
    heartbeat_interval_s: float = 0.0
    heartbeat_timeout_s: float = 0.0
    barrier_timeout_s: float = 0.0
    # memory per device in MB (reference -ll:fsize); used by memory-aware search
    device_mem_mb: int = 0        # 0 = query from device / default model
    # -------- search (reference --budget/--alpha/...) --------
    search_budget: int = -1
    search_alpha: float = 1.2
    only_data_parallel: bool = False
    enable_parameter_parallel: bool = False
    enable_attribute_parallel: bool = False
    enable_sample_parallel: bool = False
    enable_propagation: bool = False
    enable_inplace_optimizations: bool = False
    search_overlap_backward_update: bool = False
    search_num_nodes: int = -1
    search_num_workers: int = -1
    base_optimize_threshold: int = 10
    enable_memory_search: bool = False
    search_algo: str = "unity"    # "unity" (substitution DP) | "mcmc" | "dp"
    substitution_json_path: Optional[str] = None
    # -------- simulator --------
    simulator_workspace_mb: int = 2048
    machine_model_version: int = 0
    machine_model_file: str = ""
    simulator_segment_size: int = 16777216
    simulator_max_num_segments: int = 1
    # measurement-grounded cost-model calibration v2 (host dispatch/
    # memory-bandwidth/parallel-efficiency terms + persisted collective
    # tables, search/calibration.py). "auto" honors FF_CALIBRATION_V2.
    calibration_v2: str = "auto"  # "auto" | "true" | "false"
    # hierarchical topology-aware placement (parallel/placement.py,
    # arXiv 2110.10548): the search assigns mesh axes to hardware tiers
    # (ici/host/dcn) and picks a reduction-tree shape per collective.
    # "auto" enables it whenever the machine has more than one tier
    # (multi-slice / multi-host); single-tier machines are unaffected
    # either way. FF_HIER_PLACEMENT=0 is the env override.
    hier_placement: str = "auto"  # "auto" | "true" | "false"
    # -------- observability (obs/) --------
    # span/counter tracing (obs/events.py): "true"/"false" force the
    # PROCESS-WIDE recorder on/off at compile (one recorder per
    # process — "false" also stops tracing of other models/servers in
    # it); "auto" (default) honors the FF_TRACE env var so recorded
    # benchmarks are unchanged unless asked. Near-zero-cost when
    # disabled (bench's obs-overhead leg pins it at <= 3%).
    trace: str = "auto"           # "auto" | "true" | "false"
    # write a Chrome trace-event JSON (Perfetto/TensorBoard-viewable)
    # of the recorded spans here when fit() completes; "" = off
    trace_export_file: str = ""
    # step-time attribution (obs/attribution.py): profile a few
    # steady-state steps of the compiled plan when training completes
    # and write a MEASURED per-op/per-collective cost side into the
    # strategy audit record next to the predicted ones, then run the
    # cost-model drift detector (obs/drift.py) over the pair. "auto"
    # honors FF_ATTRIB; enabling implies tracing (the audit record the
    # measured side lands in only exists when tracing is on). Adds no
    # per-step work — the harness runs once, after the last epoch.
    attribution: str = "auto"     # "auto" | "true" | "false"
    # steady-state steps the attribution harness profiles
    # (FF_ATTRIB_STEPS overrides)
    attribution_steps: int = 3
    # -------- execution --------
    perform_fusion: bool = False
    allow_tensor_op_math_conversion: bool = True   # = allow bf16 matmul accum
    computation_mode: str = "training"
    profiling: bool = False
    # static plan verification (analysis/plan_verifier.py): compile
    # proves the adopted strategy executable — mesh-axis soundness,
    # shard divisibility, legal reshard lowerings at every layout seam,
    # a static peak-memory envelope, and SPMD collective-ordering
    # consistency — BEFORE params materialize; failures raise a typed
    # PlanVerificationError with op/seam attribution. FF_PLAN_VERIFY=0
    # (or this flag) disables the gate; findings land in the strategy
    # audit record and the ff_plan_verify_* counters either way.
    plan_verify: bool = True
    # -------- strategy import/export --------
    export_strategy_file: str = ""
    import_strategy_file: str = ""
    export_strategy_task_graph_file: str = ""
    export_strategy_computation_graph_file: str = ""
    include_costs_dot_graph: bool = False
    # -------- TPU-native --------
    mesh_shape: Optional[Sequence[int]] = None     # explicit ICI mesh, else auto
    # pipeline parallelism through the product path (reference reserves
    # OP_PIPELINE, ffconst.h:159, with no implementation): partition the
    # maximal repeated-block region into this many GPipe stages
    pipeline_stages: int = 1
    pipeline_microbatches: int = 0                 # 0 = 2 * stages
    # interleaved (circular) schedule: chunks per stage (1 = plain GPipe;
    # v > 1 cuts the pipeline bubble to (S-1)/(M*v))
    pipeline_chunks: int = 1
    # Megatron-style tensor parallelism INSIDE each pipeline stage
    # (dp x pp x tp composition; the reference composes per-op machine
    # views the same way, substitution.cc:1898)
    pipeline_tp: int = 1
    # direct dp x tp (x sp) preset WITHOUT a pipeline or a search:
    # --tp N applies transformer_strategy (Megatron column/row sharding
    # over a size-N mesh axis); --sp additionally shards the sequence
    # dim (ring/Ulysses-style context parallelism via GSPMD)
    tensor_parallel: int = 1
    sequence_parallel: bool = False
    # ZeRO-1: shard optimizer moments over the replicated mesh axes
    # (runtime/zero.py); the reference keeps full state per replica.
    # This is the legacy UNIFORM flag (every shardable leaf, no
    # scoring) — pinned bit-identical across releases.
    shard_optimizer_states: bool = False
    # per-parameter ZeRO in the search space (search/zero_plan.py,
    # arXiv 2004.13336): the cost model scores each parameter's update
    # path (replicated all-reduce vs reduce-scatter + sharded update +
    # all-gather over the placed tier path) and the stack honors the
    # per-parameter assignment end to end (strategy serialization,
    # plan verifier, executor state pins, checkpoint meta).
    #   "off"    — never plan (default);
    #   "auto"   — shard the predicted-free parameters, plus whatever
    #              the device-memory envelope needs;
    #   "memory" — shard only what the envelope needs to fit;
    #   "all"    — shard everything shardable (the uniform assignment,
    #              scored and audited).
    zero_policy: str = "off"
    # "auto" slack: a parameter shards when its predicted marginal
    # collective overhead is within this fraction of its replicated
    # update cost
    zero_overhead_frac: float = 0.05
    # communication–computation overlap (runtime/overlap.py): lower
    # gradient sync as size-bucketed groups whose optimizer updates
    # launch as each bucket's backward slice completes (barrier-chained
    # dependency cuts — bit-exact with the serial path by construction),
    # prefetch ZeRO param gathers one bucket ahead, and pipeline
    # tier-staged reshard legs. Also flips the cost model into
    # overlap-aware scoring (exposed-vs-hidden sync). "auto" honors the
    # FF_OVERLAP env var and resolves OFF when unset — the serial path
    # stays the bit-exact default. See docs/performance.md.
    overlap: str = "auto"         # "auto" | "on" | "off"
    # gradient-bucket size for the overlap schedule (MiB, fractional
    # allowed): consecutive reverse-order layers coalesce until this
    # many gradient bytes accumulate; a single larger parameter gets
    # its own bucket
    overlap_bucket_mb: float = 4.0
    # ZeRO all-gather prefetch depth under overlap: >= 1 chains each
    # bucket's updated (re-gathered) params into the next bucket's
    # launch token so the gather is scheduled one bucket ahead of use;
    # 0 chains raw grads only (gathers may sink to the step end)
    zero_prefetch: int = 1
    # quantized gradient collectives (ops/quantized_collectives.py,
    # arXiv 2506.17615): int8/fp8 wire payloads with per-chunk scaling
    # and error feedback, planned per-tensor (flat grad sync) and
    # per-phase (PR 9's reduction trees — quantize the DCN leg, keep
    # ICI legs full-precision), scored by the calibrated cost model.
    #   "off"      — plan nothing (default; the bit-exact path — but a
    #                strategy IMPORTED with a qsync plan is still
    #                honored verbatim, like zero/overlap);
    #   "auto"     — quantize where the model predicts a win;
    #   "dcn_only" — quantize only inter-slice (DCN) legs;
    #   "all"      — quantize every eligible leg;
    #   "disable"  — force full precision even for an imported plan
    #                (what --no-quantized-collectives parses to).
    # FF_QUANTIZED_COLLECTIVES overrides when set (an explicit off
    # value there also strips imported plans). Replicated-math seams
    # (sharded weights, per-op collectives) always stay full-precision
    # — the structural accuracy-risk gate.
    quantized_collectives: str = "off"
    # wire dtype for quantized legs: "int8" (default) |
    # "float8_e4m3" | "float8_e5m2" (FF_QSYNC_WIRE overrides; fp8
    # falls back to int8 when the installed jax lacks the dtype)
    qsync_wire: str = "int8"
    # rematerialization: "none" | "blocks" (jax.checkpoint around each
    # repeated block — HBM-for-FLOPs; executor._emit_remat)
    remat: str = "none"
    # micro-batch gradient accumulation (one optimizer update per
    # `gradient_accumulation_steps` micro-batches; batch_size must divide)
    gradient_accumulation_steps: int = 1
    # let the search score a pipeline candidate (bubble model) against the
    # searched sharding strategy and pick the winner
    enable_pipeline_search: bool = False
    # ragged pipeline schedule (parallel/pipeline_lowering.py): "auto"
    # falls back to unequal per-stage block counts with embedding/head
    # absorbed into the edge stages when the uniform region finder
    # fails; "force" always uses the ragged finder; "off" disables.
    pipeline_ragged: str = "auto"
    # per-op concurrent device-subset placement (parallel/banks.py): the
    # search may place groups of independent same-signature ops (DLRM
    # embedding banks) on disjoint device subsets when the cost model
    # predicts a win (reference MachineView placement). "auto" proposes
    # when profitable; "off" disables; "force" banks every eligible group.
    banked_placement: str = "auto"
    use_bf16_compute: bool = True                  # matmuls in bf16, fp32 accum
    # end-to-end bf16 ACTIVATIONS: inter-op tensors are stored bf16
    # (halves HBM traffic on the memory-bound segments); weights stay
    # fp32 masters, losses/norms still reduce in fp32 internally.
    # Off by default — enable for MFU on bandwidth-bound models.
    bf16_activations: bool = False
    # async-dispatch training loop (runtime/metrics_buffer.py): how many
    # train steps the host may keep in flight before blocking on the
    # step leaving the window; per-step metrics stay device-resident
    # and are fetched in one device_get at print_freq/epoch boundaries.
    # <= 0 forces the sync-every-step fallback (also FF_SYNC_EVERY_STEP=1
    # / --sync-every-step) — fetch and NaN-screen every step, for
    # debugging. See docs/performance.md.
    async_dispatch_steps: int = 8
    # dataloader prefetch depth (runtime/dataloader.py): device batches
    # dispatched ahead of consumption; 0 disables, 1 is the old
    # single-slot double-buffer
    prefetch_batches: int = 2
    # persistent XLA compilation cache dir; "" = off unless
    # JAX_COMPILATION_CACHE_DIR is set (see utils/compilation_cache.py)
    compilation_cache_dir: str = ""
    # DEPRECATED tri-state (kept as a shim over the kernel tier): "true"
    # forces attention:flash, "false" forces attention:xla, "auto" defers
    # to the searched kernel_impls dimension (kernels/registry.py emits a
    # DeprecationWarning for the non-auto values). See docs/kernels.md.
    use_flash_attention: str = "auto"
    # searched per-op kernel-implementation tier (kernels/registry.py):
    # "auto" lets FFModel._plan_kernels pick each op's impl from the
    # calibrated (op, impl) costs; "<op>:<impl>[,...]" forces choices
    # (e.g. "attention:ring,opt_update:fused"). FF_KERNEL_IMPL env and
    # --kernel-impl override. Forced-but-unavailable impls are rejected
    # by the plan verifier's `kernel` check with op attribution.
    kernel_impls: str = "auto"
    # sequence-parallel (context) mesh axis degree: N >= 2 carves a
    # dedicated "seq" axis out of the device factorization; attention
    # ops assigned the `ring` impl shard the context dimension over it
    # (kernels/ring_attention.py lowered as one shard_map with ppermute
    # ring hops). 0/1 = no seq axis. Unlike --sp (the GSPMD tp preset),
    # this axis is reserved for ring attention — the general search
    # never shards batch/params over it.
    seq_parallel_degree: int = 0
    # measured DP-floor guard on search adoption: after the search picks a
    # strategy, compile+time a few real steps of it AND of plain data
    # parallel, and keep DP when the searched program measures slower (the
    # reference trusts its calibrated simulator, simulator.cc:537; we
    # enforce the floor by measurement). "auto" = on when running on a
    # real accelerator, off on the CPU simulator (double-compile is
    # expensive there and tests exercise the guard explicitly).
    search_floor_guard: str = "auto"   # "auto" | "true" | "false"
    floor_guard_steps: int = 3
    # -------- serving plans (search/serving_plan.py) --------
    # batch classes the serving search targets, csv ("1,4,16,64");
    # "" = the InferenceSession defaults. One plan is searched per
    # bucket (mode="serving" of optimize_strategy).
    serving_buckets: str = ""
    # KV-cache sequence envelope the serving plans budget for;
    # 0 = the graph's compile-time sequence length
    serving_max_seq: int = 0
    # decode weight of the serving objective (prefill +
    # decode_tokens x decode-step latency); 0 = serving_max_seq
    serving_decode_tokens: int = 0
    # serving-plan artifact for ModelRepository load paths (a strategy
    # JSON with a "serving" block; see docs/serving.md)
    serving_strategy_file: str = ""
    # measured decode floor on serving-plan adoption (the serving
    # analog of search_floor_guard): per bucket, the imported
    # sub-strategy is kept only if its measured decode-step latency
    # beats the no-serving-plan baseline's — a mispredicting serving
    # cost model can never ship a per-bucket plan that decodes slower
    # than the plan it replaces. "auto" = on off-CPU backends only.
    serving_floor_guard: str = "auto"  # "auto" | "true" | "false"
    seed: int = 0

    def __post_init__(self):
        self._devices = None

    def serving_buckets_list(self) -> List[int]:
        """Parsed ``serving_buckets`` ([] = caller defaults)."""
        if not self.serving_buckets:
            return []
        return sorted({int(b) for b in
                       str(self.serving_buckets).split(",") if b})

    # ---- machine queries (lazy; avoids importing jax at flag-parse time) ----
    @property
    def devices(self):
        if self._devices is None:
            import jax
            self._devices = jax.devices()
        return self._devices

    @property
    def num_devices(self) -> int:
        if self.workers_per_node:
            return self.workers_per_node * self.num_nodes
        return len(self.devices)

    @property
    def seq_length(self) -> int:  # reference FFIterationConfig::seq_length
        return getattr(self, "_seq_length", -1)

    # ------------------------------------------------------------------
    @classmethod
    def parse_args(cls, argv: Optional[List[str]] = None) -> "FFConfig":
        """Parse reference-compatible command-line flags.

        Mirrors ``FFConfig::parse_args`` (reference ``model.cc:3566-3730``).
        Unknown flags are ignored (the reference forwards them to Legion).
        """
        cfg = cls()
        args = list(sys.argv[1:] if argv is None else argv)
        i = 0

        def take() -> str:
            nonlocal i
            i += 1
            return args[i]

        while i < len(args):
            a = args[i]
            if a in ("-e", "--epochs"):
                cfg.epochs = int(take())
            elif a in ("-b", "--batch-size"):
                cfg.batch_size = int(take())
            elif a == "--lr" or a == "--learning-rate":
                cfg.learning_rate = float(take())
            elif a == "--wd" or a == "--weight-decay":
                cfg.weight_decay = float(take())
            elif a in ("-p", "--print-freq"):
                cfg.print_freq = int(take())
            elif a in ("-d", "--dataset"):
                cfg.dataset_path = take()
            elif a == "--budget" or a == "--search-budget":
                cfg.search_budget = int(take())
            elif a == "--alpha" or a == "--search-alpha":
                cfg.search_alpha = float(take())
            elif a == "--only-data-parallel":
                cfg.only_data_parallel = True
            elif a == "--no-plan-verify":
                cfg.plan_verify = False
            elif a == "--enable-parameter-parallel":
                cfg.enable_parameter_parallel = True
            elif a == "--enable-attribute-parallel":
                cfg.enable_attribute_parallel = True
            elif a == "--enable-sample-parallel":
                cfg.enable_sample_parallel = True
            elif a == "--enable-propagation":
                cfg.enable_propagation = True
            elif a == "--enable-inplace-optimizations":
                cfg.enable_inplace_optimizations = True
            elif a == "--overlap":
                cfg.search_overlap_backward_update = True
            elif a == "--search-num-nodes":
                cfg.search_num_nodes = int(take())
            elif a == "--search-num-workers":
                cfg.search_num_workers = int(take())
            elif a == "--base-optimize-threshold":
                cfg.base_optimize_threshold = int(take())
            elif a == "--memory-search":
                cfg.enable_memory_search = True
            elif a == "--search-algo":
                cfg.search_algo = take()
            elif a == "--substitution-json":
                cfg.substitution_json_path = take()
            elif a == "--floor-guard":
                cfg.search_floor_guard = take().lower()
            elif a == "--no-floor-guard":
                cfg.search_floor_guard = "false"
            elif a == "--simulator-workspace-size":
                cfg.simulator_workspace_mb = int(take())
            elif a == "--machine-model-version":
                cfg.machine_model_version = int(take())
            elif a == "--machine-model-file":
                cfg.machine_model_file = take()
            elif a == "--simulator-segment-size":
                cfg.simulator_segment_size = int(take())
            elif a == "--simulator-max-num-segments":
                cfg.simulator_max_num_segments = int(take())
            elif a == "--calibration-v2":
                cfg.calibration_v2 = take().lower()
            elif a == "--hier-placement":
                cfg.hier_placement = take().lower()
            elif a == "--no-hier-placement":
                cfg.hier_placement = "false"
            elif a == "--trace":
                cfg.trace = "true"
            elif a == "--no-trace":
                cfg.trace = "false"
            elif a == "--trace-export":
                cfg.trace_export_file = take()
                cfg.trace = "true"
            elif a == "--attribution":
                cfg.attribution = "true"
            elif a == "--no-attribution":
                cfg.attribution = "false"
            elif a == "--attribution-steps":
                cfg.attribution_steps = int(take())
            elif a == "--fusion":
                cfg.perform_fusion = True
            elif a == "--profiling":
                cfg.profiling = True
            elif a == "--allow-tensor-op-math-conversion":
                cfg.allow_tensor_op_math_conversion = True
                cfg.use_bf16_compute = True   # symmetric with --f32-compute
            elif a in ("--no-tensor-op-math-conversion", "--f32-compute"):
                # TPU-native default is bf16 matmul compute (the MXU's
                # native dtype) — unlike the reference, which defaults its
                # TF32/FP16 conversion OFF (model.cc:3491). This flag
                # restores full-f32 math for numerics debugging.
                cfg.allow_tensor_op_math_conversion = False
                cfg.use_bf16_compute = False
            elif a == "--export" or a == "--export-strategy":
                cfg.export_strategy_file = take()
            elif a == "--import" or a == "--import-strategy":
                cfg.import_strategy_file = take()
            elif a == "--taskgraph":
                cfg.export_strategy_task_graph_file = take()
            elif a == "--compgraph":
                cfg.export_strategy_computation_graph_file = take()
            elif a == "--include-costs-dot-graph":
                cfg.include_costs_dot_graph = True
            elif a == "-ll:tpu" or a == "-ll:gpu":
                cfg.workers_per_node = int(take())
            elif a == "-ll:cpu":
                cfg.cpus_per_node = int(take())
            elif a == "-ll:fsize":
                cfg.device_mem_mb = int(take())
            elif a == "--nodes":
                cfg.num_nodes = int(take())
            elif a == "--coordinator-address":
                cfg.coordinator_address = take()
            elif a == "--process-id":
                cfg.process_id = int(take())
            elif a == "--mesh-shape":
                cfg.mesh_shape = tuple(int(x) for x in take().split("x"))
            elif a in ("--pp", "--pipeline-stages"):
                cfg.pipeline_stages = int(take())
            elif a in ("--num-microbatches", "--pipeline-microbatches"):
                cfg.pipeline_microbatches = int(take())
            elif a in ("--pipeline-chunks", "--interleave"):
                cfg.pipeline_chunks = int(take())
            elif a in ("--pp-tp", "--pipeline-tp"):
                cfg.pipeline_tp = int(take())
            elif a in ("--tp", "--tensor-parallel"):
                cfg.tensor_parallel = int(take())
            elif a in ("--sp", "--sequence-parallel"):
                cfg.sequence_parallel = True
            elif a == "--seq-parallel":
                cfg.seq_parallel_degree = int(take())
            elif a == "--kernel-impl":
                # repeated flags accumulate: --kernel-impl attention:ring
                # --kernel-impl opt_update:fused
                v = take()
                cfg.kernel_impls = v if cfg.kernel_impls == "auto" \
                    else f"{cfg.kernel_impls},{v}"
            elif a == "--bf16-activations":
                cfg.bf16_activations = True
            elif a in ("--zero", "--shard-optimizer-states"):
                cfg.shard_optimizer_states = True
            elif a == "--zero-policy":
                cfg.zero_policy = take().lower()
            elif a == "--zero-search":
                cfg.zero_policy = "auto"
            elif a == "--zero-overhead-frac":
                cfg.zero_overhead_frac = float(take())
            elif a == "--overlap-schedule":
                cfg.overlap = take().lower()
            elif a == "--no-overlap-schedule":
                cfg.overlap = "off"
            elif a == "--overlap-bucket-mb":
                cfg.overlap_bucket_mb = float(take())
            elif a == "--zero-prefetch":
                cfg.zero_prefetch = int(take())
            elif a == "--quantized-collectives":
                cfg.quantized_collectives = take().lower()
            elif a == "--no-quantized-collectives":
                # "disable", not "off": strips an imported strategy's
                # qsync plan too (the explicit full-precision A/B knob)
                cfg.quantized_collectives = "disable"
            elif a == "--qsync-wire":
                cfg.qsync_wire = take().lower()
            elif a == "--remat":
                cfg.remat = "blocks"
            elif a in ("--gradient-accumulation-steps", "--accum"):
                cfg.gradient_accumulation_steps = int(take())
            elif a == "--enable-pipeline-search":
                cfg.enable_pipeline_search = True
            elif a == "--banked-placement":
                cfg.banked_placement = take()
            elif a == "--pipeline-ragged":
                cfg.pipeline_ragged = take()
            elif a == "--async-dispatch-steps":
                cfg.async_dispatch_steps = int(take())
            elif a == "--sync-every-step":
                cfg.async_dispatch_steps = 0
            elif a == "--prefetch-batches":
                cfg.prefetch_batches = int(take())
            elif a == "--serving-buckets":
                cfg.serving_buckets = take()
            elif a == "--serving-max-seq":
                cfg.serving_max_seq = int(take())
            elif a == "--serving-decode-tokens":
                cfg.serving_decode_tokens = int(take())
            elif a == "--serving-strategy":
                cfg.serving_strategy_file = take()
            elif a == "--serving-floor-guard":
                cfg.serving_floor_guard = take()
            elif a == "--compilation-cache-dir":
                cfg.compilation_cache_dir = take()
            elif a == "--seed":
                cfg.seed = int(take())
            # unknown flags: skip (reference forwards to Legion)
            i += 1
        return cfg


@dataclasses.dataclass
class FFIterationConfig:
    """Per-iteration config (reference ``config.h:162-167``)."""
    seq_length: int = -1

    def reset(self):
        self.seq_length = -1
