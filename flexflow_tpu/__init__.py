"""flexflow_tpu: a TPU-native distributed DNN training framework with
automatic parallelization search (FlexFlow/Unity capabilities, JAX/XLA/
Pallas implementation).

Quick start::

    from flexflow_tpu import FFModel, FFConfig, SGDOptimizer
    ff = FFModel(FFConfig())
    x = ff.create_tensor((64, 784))
    t = ff.dense(x, 512, activation=ActiMode.AC_MODE_RELU)
    t = ff.dense(t, 10)
    out = ff.softmax(t)
    ff.compile(SGDOptimizer(0.01), "sparse_categorical_crossentropy",
               ["accuracy"])
    ff.fit(x=images, y=labels, epochs=2)
"""
from .utils.jax_compat import enable_partitionable_rng

# sharding-invariant random bits BEFORE any model code traces an rng
# consumer: with the flag off, GSPMD generates different dropout masks
# for different shardings of the same op (the tp-vs-dp numerics split
# pinned by tests/test_tp_flag.py::test_tp_flag_matches_dp_numerics)
enable_partitionable_rng()

from .ffconst import (ActiMode, AggrMode, CompMode, DataType, InitializerType,
                      LossType, MetricsType, OperatorType, ParameterSyncType,
                      PoolType, RegularizerMode)
from .config import FFConfig, FFIterationConfig
from .core.tensor import Tensor, WeightSpec
from .core.layer import Layer
from .model import FFModel
from .parallel.machine import DeviceMesh, MachineSpec
from .parallel.ptensor import ParallelDim, ParallelTensorShape
from .parallel.strategy import OpSharding, ShardingStrategy
from .runtime.optimizers import AdamOptimizer, Optimizer, SGDOptimizer
from .runtime.dataloader import SingleDataLoader
from .runtime.metrics import PerfMetrics

__version__ = "0.1.0"
