"""MLP (MLP_Unify), CANDLE-Uno, and the MoE encoder example.

Reference parity: ``examples/cpp/{MLP_Unify,candle_uno,mixture_of_experts}``.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Sequence

from ..ffconst import ActiMode
from ..model import FFModel


def build_mlp(ff: FFModel, batch_size: int, in_dim: int = 1024,
              hidden: Sequence[int] = (4096, 4096, 4096, 1024),
              num_classes: int = 10):
    """MLP benchmark (reference ``examples/cpp/MLP_Unify/mlp.cc``)."""
    x = ff.create_tensor((batch_size, in_dim), name="input")
    t = x
    for h in hidden:
        t = ff.dense(t, h, ActiMode.AC_MODE_RELU)
    t = ff.dense(t, num_classes)
    return ff.softmax(t)


@dataclasses.dataclass
class CandleConfig:
    """Reference defaults (``candle_uno.cc:26-47``)."""
    dense_layers: Sequence[int] = (4192,) * 2
    dense_feature_layers: Sequence[int] = (4192,) * 2
    feature_shapes: Dict[str, int] = dataclasses.field(default_factory=lambda: {
        "dose": 1, "cell.rnaseq": 942, "drug.descriptors": 5270,
        "drug.fingerprints": 2048})
    input_features: Dict[str, str] = dataclasses.field(default_factory=lambda: {
        "dose1": "dose", "dose2": "dose", "cell.rnaseq": "cell.rnaseq",
        "drug1.descriptors": "drug.descriptors",
        "drug1.fingerprints": "drug.fingerprints",
        "drug2.descriptors": "drug.descriptors",
        "drug2.fingerprints": "drug.fingerprints"})


def build_candle_uno(ff: FFModel, batch_size: int,
                     cfg: CandleConfig | None = None):
    """CANDLE-Uno (reference ``candle_uno.cc:49-130``): per-feature dense
    towers (shared per feature model), concat, deep dense stack, dense(1)."""
    cfg = cfg or CandleConfig()

    def feature_model(t, layers):
        for s in layers:
            t = ff.dense(t, s, ActiMode.AC_MODE_RELU, use_bias=False)
        return t

    encoded = []
    for name, feat in cfg.input_features.items():
        shape = cfg.feature_shapes[feat]
        inp = ff.create_tensor((batch_size, shape), name=name)
        if feat == "dose":
            encoded.append(inp)
        else:
            encoded.append(feature_model(inp, cfg.dense_feature_layers))
    t = ff.concat(encoded, axis=-1)
    for s in cfg.dense_layers:
        t = ff.dense(t, s, ActiMode.AC_MODE_RELU, use_bias=False)
    return ff.dense(t, 1)


@dataclasses.dataclass
class MoeConfig:
    """Reference ``examples/cpp/mixture_of_experts/moe.h`` defaults
    (scaled-down-able)."""
    hidden_size: int = 64
    num_encoder_layers: int = 1
    num_attention_heads: int = 16
    num_exp: int = 32
    num_select: int = 2
    alpha: float = 2.0
    lambda_bal: float = 0.04
    in_dim: int = 784
    num_classes: int = 10

    @classmethod
    def tiny(cls):
        return cls(hidden_size=32, num_attention_heads=4, num_exp=4,
                   in_dim=64)


def build_moe_mnist(ff: FFModel, batch_size: int,
                    cfg: MoeConfig | None = None):
    """MoE classifier (reference ``moe.cc:100-140``): the FFModel::moe
    composite (gate → top-k → group_by → experts → aggregate) on flat
    input, then classification head."""
    cfg = cfg or MoeConfig()
    x = ff.create_tensor((batch_size, cfg.in_dim), name="input")
    t = ff.moe(x, cfg.num_exp, cfg.num_select, cfg.hidden_size,
               cfg.alpha, cfg.lambda_bal)
    t = ff.dense(t, cfg.num_classes)
    return ff.softmax(t)
