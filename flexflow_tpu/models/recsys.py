"""Recommender model zoo: DLRM and XDL.

Reference parity: ``examples/cpp/DLRM/dlrm.cc`` and ``examples/cpp/XDL/
xdl.cc`` — embedding tables (the attribute-parallel workhorses of the
reference's DLRM strategies) + bottom/top MLPs + feature interaction.
"""
from __future__ import annotations

import dataclasses
from typing import List, Sequence

from ..ffconst import ActiMode, AggrMode, DataType
from ..model import FFModel


@dataclasses.dataclass
class DLRMConfig:
    """Reference defaults (``dlrm.cc:26-42``)."""
    embedding_size: Sequence[int] = (1000000,) * 4
    sparse_feature_size: int = 64
    embedding_bag_size: int = 1
    mlp_bot: Sequence[int] = (4, 64, 64)
    mlp_top: Sequence[int] = (64, 64, 2)
    arch_interaction_op: str = "cat"


def _mlp(ff: FFModel, t, sizes: Sequence[int], sigmoid_last: bool = False):
    for i, s in enumerate(sizes[1:]):
        last = i == len(sizes) - 2
        act = (ActiMode.AC_MODE_SIGMOID if (last and sigmoid_last)
               else ActiMode.AC_MODE_RELU)
        t = ff.dense(t, s, act)
    return t


def build_dlrm(ff: FFModel, batch_size: int, cfg: DLRMConfig | None = None):
    """DLRM (reference ``dlrm.cc:103-190``): per-table embedding-bag sum,
    dense-feature bottom MLP, concat interaction, top MLP → 2-way softmax."""
    cfg = cfg or DLRMConfig()
    sparse_inputs = [
        ff.create_tensor((batch_size, cfg.embedding_bag_size),
                         DataType.DT_INT32, name=f"sparse_{i}")
        for i in range(len(cfg.embedding_size))]
    dense_input = ff.create_tensor((batch_size, cfg.mlp_bot[0]),
                                   name="dense_input")
    ly = [ff.embedding(s, n, cfg.sparse_feature_size,
                       AggrMode.AGGR_MODE_SUM, name=f"emb_{i}")
          for i, (s, n) in enumerate(zip(sparse_inputs, cfg.embedding_size))]
    x = _mlp(ff, dense_input, list(cfg.mlp_bot))
    if cfg.arch_interaction_op != "cat":
        raise ValueError(f"unsupported arch_interaction_op "
                         f"{cfg.arch_interaction_op!r} (only 'cat')")
    z = ff.concat([x] + ly, axis=-1)
    # last top-MLP layer uses sigmoid (reference dlrm.cc:165:
    # sigmoid_layer = mlp_top.size() - 2)
    t = _mlp(ff, z, [z.shape[-1]] + list(cfg.mlp_top)[1:],
             sigmoid_last=True)
    return ff.softmax(t)


@dataclasses.dataclass
class XDLConfig:
    """Reference defaults (``xdl.cc:26-32``)."""
    embedding_size: Sequence[int] = (1000000,) * 4
    sparse_feature_size: int = 64
    embedding_bag_size: int = 1
    mlp: Sequence[int] = (256, 128, 2)


def build_xdl(ff: FFModel, batch_size: int, cfg: XDLConfig | None = None):
    """XDL (reference ``xdl.cc``): embeddings concat → MLP → softmax."""
    cfg = cfg or XDLConfig()
    sparse_inputs = [
        ff.create_tensor((batch_size, cfg.embedding_bag_size),
                         DataType.DT_INT32, name=f"sparse_{i}")
        for i in range(len(cfg.embedding_size))]
    ly = [ff.embedding(s, n, cfg.sparse_feature_size,
                       AggrMode.AGGR_MODE_SUM, name=f"emb_{i}")
          for i, (s, n) in enumerate(zip(sparse_inputs, cfg.embedding_size))]
    z = ff.concat(ly, axis=-1)
    t = z
    for i, s in enumerate(cfg.mlp):
        act = (ActiMode.AC_MODE_NONE if i == len(cfg.mlp) - 1
               else ActiMode.AC_MODE_RELU)
        t = ff.dense(t, s, act)
    return ff.softmax(t)
