"""Vision model zoo: AlexNet, ResNet, ResNeXt, Inception-v3.

Reference parity: ``examples/cpp/{AlexNet,ResNet,resnext50,InceptionV3}`` —
the same layer sequences expressed through the FFModel builder API
(these double as op integration drivers, as in the reference).
"""
from __future__ import annotations

from ..ffconst import ActiMode, PoolType
from ..model import FFModel


def build_alexnet(ff: FFModel, batch_size: int, num_classes: int = 10,
                  image_hw: int = 229):
    """AlexNet (reference ``examples/cpp/AlexNet/alexnet.cc:70-84``)."""
    x = ff.create_tensor((batch_size, 3, image_hw, image_hw), name="input")
    t = ff.conv2d(x, 64, 11, 11, 4, 4, 2, 2, ActiMode.AC_MODE_RELU)
    t = ff.pool2d(t, 3, 3, 2, 2, 0, 0)
    t = ff.conv2d(t, 192, 5, 5, 1, 1, 2, 2, ActiMode.AC_MODE_RELU)
    t = ff.pool2d(t, 3, 3, 2, 2, 0, 0)
    t = ff.conv2d(t, 384, 3, 3, 1, 1, 1, 1, ActiMode.AC_MODE_RELU)
    t = ff.conv2d(t, 256, 3, 3, 1, 1, 1, 1, ActiMode.AC_MODE_RELU)
    t = ff.conv2d(t, 256, 3, 3, 1, 1, 1, 1, ActiMode.AC_MODE_RELU)
    t = ff.pool2d(t, 3, 3, 2, 2, 0, 0)
    t = ff.flat(t)
    t = ff.dense(t, 4096, ActiMode.AC_MODE_RELU)
    t = ff.dense(t, 4096, ActiMode.AC_MODE_RELU)
    t = ff.dense(t, num_classes)
    return ff.softmax(t)


def build_alexnet_cifar10(ff: FFModel, batch_size: int):
    """CIFAR-sized AlexNet (reference ``bootcamp_demo/ff_alexnet_cifar10.py``
    — BASELINE config 1). Smaller strides for 32x32 inputs."""
    x = ff.create_tensor((batch_size, 3, 32, 32), name="input")
    t = ff.conv2d(x, 64, 5, 5, 1, 1, 2, 2, ActiMode.AC_MODE_RELU)
    t = ff.pool2d(t, 3, 3, 2, 2, 0, 0)
    t = ff.conv2d(t, 192, 5, 5, 1, 1, 2, 2, ActiMode.AC_MODE_RELU)
    t = ff.pool2d(t, 3, 3, 2, 2, 0, 0)
    t = ff.conv2d(t, 384, 3, 3, 1, 1, 1, 1, ActiMode.AC_MODE_RELU)
    t = ff.conv2d(t, 256, 3, 3, 1, 1, 1, 1, ActiMode.AC_MODE_RELU)
    t = ff.conv2d(t, 256, 3, 3, 1, 1, 1, 1, ActiMode.AC_MODE_RELU)
    t = ff.pool2d(t, 3, 3, 2, 2, 0, 0)
    t = ff.flat(t)
    t = ff.dense(t, 2048, ActiMode.AC_MODE_RELU)
    t = ff.dense(t, 2048, ActiMode.AC_MODE_RELU)
    t = ff.dense(t, 10)
    return ff.softmax(t)


def _bottleneck(ff: FFModel, input, out_channels: int, stride: int,
                groups: int = 1, width_factor: int = 1):
    """ResNet bottleneck block (reference ``resnet.cc:33-58``); with
    groups>1 / width_factor=2 it is the ResNeXt block
    (``resnext50/resnext.cc``)."""
    width = out_channels * width_factor
    t = ff.conv2d(input, width, 1, 1, 1, 1, 0, 0, ActiMode.AC_MODE_NONE)
    t = ff.batch_norm(t)
    t = ff.conv2d(t, width, 3, 3, stride, stride, 1, 1,
                  ActiMode.AC_MODE_NONE, groups=groups)
    t = ff.batch_norm(t)
    t = ff.conv2d(t, 4 * out_channels, 1, 1, 1, 1, 0, 0)
    t = ff.batch_norm(t, relu=False)
    in_c = input.shape[1]
    if in_c != 4 * out_channels or stride > 1:
        input = ff.conv2d(input, 4 * out_channels, 1, 1, stride, stride, 0, 0)
        input = ff.batch_norm(input, relu=False)
    t = ff.add(input, t)
    return ff.relu(t)


def build_resnet50(ff: FFModel, batch_size: int, num_classes: int = 10,
                   image_hw: int = 224, groups: int = 1,
                   width_factor: int = 1):
    """ResNet-50 (reference ``examples/cpp/ResNet/resnet.cc:85-113``).
    groups=32, width_factor=2 gives ResNeXt-50 32x4d."""
    x = ff.create_tensor((batch_size, 3, image_hw, image_hw), name="input")
    t = ff.conv2d(x, 64, 7, 7, 2, 2, 3, 3)
    t = ff.batch_norm(t)
    t = ff.pool2d(t, 3, 3, 2, 2, 1, 1)
    for (n, c, s) in [(3, 64, 1), (4, 128, 2), (6, 256, 2), (3, 512, 2)]:
        for i in range(n):
            t = _bottleneck(ff, t, c, s if i == 0 else 1, groups,
                            width_factor)
    t = ff.pool2d(t, t.shape[2], t.shape[3], 1, 1, 0, 0, PoolType.POOL_AVG)
    t = ff.flat(t)
    t = ff.dense(t, num_classes)
    return ff.softmax(t)


def build_resnext50(ff: FFModel, batch_size: int, num_classes: int = 10,
                    image_hw: int = 224):
    """ResNeXt-50 32x4d (reference ``examples/cpp/resnext50``)."""
    return build_resnet50(ff, batch_size, num_classes, image_hw,
                          groups=32, width_factor=2)


def _inception_a(ff, x, pool_features):
    b1 = ff.batch_norm(ff.conv2d(x, 64, 1, 1, 1, 1, 0, 0))
    b2 = ff.batch_norm(ff.conv2d(x, 48, 1, 1, 1, 1, 0, 0))
    b2 = ff.batch_norm(ff.conv2d(b2, 64, 5, 5, 1, 1, 2, 2))
    b3 = ff.batch_norm(ff.conv2d(x, 64, 1, 1, 1, 1, 0, 0))
    b3 = ff.batch_norm(ff.conv2d(b3, 96, 3, 3, 1, 1, 1, 1))
    b3 = ff.batch_norm(ff.conv2d(b3, 96, 3, 3, 1, 1, 1, 1))
    b4 = ff.pool2d(x, 3, 3, 1, 1, 1, 1, PoolType.POOL_AVG)
    b4 = ff.batch_norm(ff.conv2d(b4, pool_features, 1, 1, 1, 1, 0, 0))
    return ff.concat([b1, b2, b3, b4], axis=1)


def _inception_b(ff, x):
    b1 = ff.batch_norm(ff.conv2d(x, 384, 3, 3, 2, 2, 0, 0))
    b2 = ff.batch_norm(ff.conv2d(x, 64, 1, 1, 1, 1, 0, 0))
    b2 = ff.batch_norm(ff.conv2d(b2, 96, 3, 3, 1, 1, 1, 1))
    b2 = ff.batch_norm(ff.conv2d(b2, 96, 3, 3, 2, 2, 0, 0))
    b3 = ff.pool2d(x, 3, 3, 2, 2, 0, 0)
    return ff.concat([b1, b2, b3], axis=1)


def _inception_c(ff, x, c7):
    b1 = ff.batch_norm(ff.conv2d(x, 192, 1, 1, 1, 1, 0, 0))
    b2 = ff.batch_norm(ff.conv2d(x, c7, 1, 1, 1, 1, 0, 0))
    b2 = ff.batch_norm(ff.conv2d(b2, c7, 1, 7, 1, 1, 0, 3))
    b2 = ff.batch_norm(ff.conv2d(b2, 192, 7, 1, 1, 1, 3, 0))
    b3 = ff.batch_norm(ff.conv2d(x, c7, 1, 1, 1, 1, 0, 0))
    b3 = ff.batch_norm(ff.conv2d(b3, c7, 7, 1, 1, 1, 3, 0))
    b3 = ff.batch_norm(ff.conv2d(b3, c7, 1, 7, 1, 1, 0, 3))
    b3 = ff.batch_norm(ff.conv2d(b3, c7, 7, 1, 1, 1, 3, 0))
    b3 = ff.batch_norm(ff.conv2d(b3, 192, 1, 7, 1, 1, 0, 3))
    b4 = ff.pool2d(x, 3, 3, 1, 1, 1, 1, PoolType.POOL_AVG)
    b4 = ff.batch_norm(ff.conv2d(b4, 192, 1, 1, 1, 1, 0, 0))
    return ff.concat([b1, b2, b3, b4], axis=1)


def _inception_d(ff, x):
    b1 = ff.batch_norm(ff.conv2d(x, 192, 1, 1, 1, 1, 0, 0))
    b1 = ff.batch_norm(ff.conv2d(b1, 320, 3, 3, 2, 2, 0, 0))
    b2 = ff.batch_norm(ff.conv2d(x, 192, 1, 1, 1, 1, 0, 0))
    b2 = ff.batch_norm(ff.conv2d(b2, 192, 1, 7, 1, 1, 0, 3))
    b2 = ff.batch_norm(ff.conv2d(b2, 192, 7, 1, 1, 1, 3, 0))
    b2 = ff.batch_norm(ff.conv2d(b2, 192, 3, 3, 2, 2, 0, 0))
    b3 = ff.pool2d(x, 3, 3, 2, 2, 0, 0)
    return ff.concat([b1, b2, b3], axis=1)


def _inception_e(ff, x):
    b1 = ff.batch_norm(ff.conv2d(x, 320, 1, 1, 1, 1, 0, 0))
    b2 = ff.batch_norm(ff.conv2d(x, 384, 1, 1, 1, 1, 0, 0))
    b2a = ff.batch_norm(ff.conv2d(b2, 384, 1, 3, 1, 1, 0, 1))
    b2b = ff.batch_norm(ff.conv2d(b2, 384, 3, 1, 1, 1, 1, 0))
    b2 = ff.concat([b2a, b2b], axis=1)
    b3 = ff.batch_norm(ff.conv2d(x, 448, 1, 1, 1, 1, 0, 0))
    b3 = ff.batch_norm(ff.conv2d(b3, 384, 3, 3, 1, 1, 1, 1))
    b3a = ff.batch_norm(ff.conv2d(b3, 384, 1, 3, 1, 1, 0, 1))
    b3b = ff.batch_norm(ff.conv2d(b3, 384, 3, 1, 1, 1, 1, 0))
    b3 = ff.concat([b3a, b3b], axis=1)
    b4 = ff.pool2d(x, 3, 3, 1, 1, 1, 1, PoolType.POOL_AVG)
    b4 = ff.batch_norm(ff.conv2d(b4, 192, 1, 1, 1, 1, 0, 0))
    return ff.concat([b1, b2, b3, b4], axis=1)


def build_inception_v3(ff: FFModel, batch_size: int, num_classes: int = 10,
                       image_hw: int = 299):
    """Inception-v3 (reference ``examples/cpp/InceptionV3/inception.cc``)."""
    x = ff.create_tensor((batch_size, 3, image_hw, image_hw), name="input")
    t = ff.batch_norm(ff.conv2d(x, 32, 3, 3, 2, 2, 0, 0))
    t = ff.batch_norm(ff.conv2d(t, 32, 3, 3, 1, 1, 0, 0))
    t = ff.batch_norm(ff.conv2d(t, 64, 3, 3, 1, 1, 1, 1))
    t = ff.pool2d(t, 3, 3, 2, 2, 0, 0)
    t = ff.batch_norm(ff.conv2d(t, 80, 1, 1, 1, 1, 0, 0))
    t = ff.batch_norm(ff.conv2d(t, 192, 3, 3, 1, 1, 1, 1))
    t = ff.pool2d(t, 3, 3, 2, 2, 0, 0)
    t = _inception_a(ff, t, 32)
    t = _inception_a(ff, t, 64)
    t = _inception_a(ff, t, 64)
    t = _inception_b(ff, t)
    t = _inception_c(ff, t, 128)
    t = _inception_c(ff, t, 160)
    t = _inception_c(ff, t, 160)
    t = _inception_c(ff, t, 192)
    t = _inception_d(ff, t)
    t = _inception_e(ff, t)
    t = _inception_e(ff, t)
    t = ff.pool2d(t, t.shape[2], t.shape[3], 1, 1, 0, 0, PoolType.POOL_AVG)
    t = ff.flat(t)
    t = ff.dense(t, num_classes)
    return ff.softmax(t)
