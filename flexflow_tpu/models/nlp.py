"""NLP model zoo: Transformer encoder, BERT, GPT-2.

Reference parity: ``examples/cpp/Transformer/transformer.cc`` (encoder
stack); BERT/GPT come through the torch.fx frontend in the reference —
here they're also available natively, configured to the standard published
sizes (BERT-large: 24 layers, hidden 1024, heads 16; GPT-2 sizes per
https://openai.com 124M/355M/774M/1.5B).
"""
from __future__ import annotations

import dataclasses

from ..ffconst import ActiMode, AggrMode, DataType
from ..model import FFModel


@dataclasses.dataclass
class TransformerConfig:
    """Reference ``transformer.cc`` TransformerConfig defaults."""
    hidden_size: int = 512
    embedding_size: int = 512
    num_heads: int = 8
    num_layers: int = 6
    sequence_length: int = 512


def create_attention_encoder(ff: FFModel, input, hidden_dim: int,
                             num_heads: int, kdim: int, vdim: int):
    """One encoder layer exactly as reference ``transformer.cc:33-45``:
    MHA followed by two dense layers, no residual/LN (the reference
    example omits them)."""
    t = ff.multihead_attention(input, input, input, hidden_dim, num_heads,
                               kdim, vdim)
    return ff.dense(ff.dense(t, hidden_dim, ActiMode.AC_MODE_RELU,
                             use_bias=False),
                    hidden_dim, ActiMode.AC_MODE_NONE, use_bias=False)


def build_transformer(ff: FFModel, batch_size: int,
                      cfg: TransformerConfig | None = None):
    """Reference Transformer benchmark model (``transformer.cc:135-158``):
    encoder stack on (B, L, H) input, final dense(1), MSE loss."""
    cfg = cfg or TransformerConfig()
    x = ff.create_tensor((batch_size, cfg.sequence_length, cfg.hidden_size),
                         name="input")
    t = x
    for _ in range(cfg.num_layers):
        t = create_attention_encoder(ff, t, cfg.hidden_size, cfg.num_heads,
                                     cfg.hidden_size // cfg.num_heads,
                                     cfg.hidden_size // cfg.num_heads)
    return ff.dense(t, 1, ActiMode.AC_MODE_NONE, use_bias=False)


@dataclasses.dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 1024        # BERT-large
    num_layers: int = 24
    num_heads: int = 16
    intermediate_size: int = 4096
    max_position: int = 512
    type_vocab_size: int = 2
    dropout: float = 0.1
    num_labels: int = 2

    @classmethod
    def base(cls):
        return cls(hidden_size=768, num_layers=12, num_heads=12,
                   intermediate_size=3072)

    @classmethod
    def tiny(cls):
        """For tests/compile checks."""
        return cls(vocab_size=1024, hidden_size=64, num_layers=2,
                   num_heads=4, intermediate_size=128, max_position=64)


def _bert_layer(ff: FFModel, t, cfg: BertConfig, causal: bool = False):
    attn = ff.multihead_attention(t, t, t, cfg.hidden_size, cfg.num_heads,
                                  dropout=cfg.dropout, causal=causal)
    t = ff.layer_norm(ff.add(t, ff.dropout(attn, cfg.dropout)),
                      [-1])
    ffn = ff.dense(t, cfg.intermediate_size, ActiMode.AC_MODE_GELU)
    ffn = ff.dense(ffn, cfg.hidden_size)
    return ff.layer_norm(ff.add(t, ff.dropout(ffn, cfg.dropout)), [-1])


def build_bert(ff: FFModel, batch_size: int, seq_len: int,
               cfg: BertConfig | None = None, classifier: bool = True):
    """BERT encoder (token ids → pooled classification logits).

    Post-LN encoder per the original architecture; embeddings = word +
    position (+ segment omitted when ids not given).
    """
    cfg = cfg or BertConfig()
    ids = ff.create_tensor((batch_size, seq_len), DataType.DT_INT32,
                           name="input_ids")
    pos = ff.create_tensor((batch_size, seq_len), DataType.DT_INT32,
                           name="position_ids")
    tok = ff.embedding(ids, cfg.vocab_size, cfg.hidden_size,
                       AggrMode.AGGR_MODE_NONE, name="word_embeddings")
    pe = ff.embedding(pos, cfg.max_position, cfg.hidden_size,
                      AggrMode.AGGR_MODE_NONE, name="position_embeddings")
    t = ff.layer_norm(ff.add(tok, pe), [-1])
    t = ff.dropout(t, cfg.dropout)
    for _ in range(cfg.num_layers):
        t = _bert_layer(ff, t, cfg)
    if not classifier:
        return t
    # pooler: first-token representation → dense tanh → classifier
    cls_tok = ff.reshape(ff.slice_tensor(t, starts=[0], ends=[1], axes=[1]),
                         (batch_size, cfg.hidden_size))
    pooled = ff.dense(cls_tok, cfg.hidden_size, ActiMode.AC_MODE_TANH)
    logits = ff.dense(pooled, cfg.num_labels)
    return ff.softmax(logits)


@dataclasses.dataclass
class GPTConfig:
    vocab_size: int = 50257
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    max_position: int = 1024
    dropout: float = 0.0

    @classmethod
    def gpt2_xl(cls):
        return cls(hidden_size=1600, num_layers=48, num_heads=25)

    @classmethod
    def gpt2_medium(cls):
        return cls(hidden_size=1024, num_layers=24, num_heads=16)

    @classmethod
    def tiny(cls):
        return cls(vocab_size=512, hidden_size=64, num_layers=2,
                   num_heads=4, max_position=128)


def build_gpt2(ff: FFModel, batch_size: int, seq_len: int,
               cfg: GPTConfig | None = None):
    """GPT-2 decoder-only LM: pre-LN blocks, causal attention, tied-untied
    LM head (untied dense here), softmax over vocab."""
    cfg = cfg or GPTConfig()
    ids = ff.create_tensor((batch_size, seq_len), DataType.DT_INT32,
                           name="input_ids")
    pos = ff.create_tensor((batch_size, seq_len), DataType.DT_INT32,
                           name="position_ids")
    tok = ff.embedding(ids, cfg.vocab_size, cfg.hidden_size,
                       name="wte")
    pe = ff.embedding(pos, cfg.max_position, cfg.hidden_size, name="wpe")
    t = ff.dropout(ff.add(tok, pe), cfg.dropout)
    for _ in range(cfg.num_layers):
        h = ff.layer_norm(t, [-1])
        attn = ff.multihead_attention(h, h, h, cfg.hidden_size,
                                      cfg.num_heads, dropout=cfg.dropout,
                                      causal=True)
        t = ff.add(t, attn)
        h = ff.layer_norm(t, [-1])
        ffn = ff.dense(h, 4 * cfg.hidden_size, ActiMode.AC_MODE_GELU)
        ffn = ff.dense(ffn, cfg.hidden_size)
        t = ff.add(t, ffn)
    t = ff.layer_norm(t, [-1])
    logits = ff.dense(t, cfg.vocab_size, use_bias=False, name="lm_head")
    return ff.softmax(logits)


@dataclasses.dataclass
class NMTConfig:
    """LSTM seq2seq with attention (reference legacy ``nmt/`` app:
    embed -> stacked LSTM encoder/decoder -> attention -> softmax,
    ``nmt/nmt.cc``/``lstm.cu``)."""
    src_vocab: int = 32000
    tgt_vocab: int = 32000
    embed_dim: int = 512
    hidden_size: int = 512
    num_layers: int = 2
    num_heads: int = 1           # attention over encoder states


def build_nmt(ff: FFModel, batch_size: int, src_len: int, tgt_len: int,
              cfg: NMTConfig | None = None):
    """Teacher-forcing NMT: encoder LSTM over the source, decoder LSTM
    over the (shifted) target, decoder attends to encoder states, dense
    projects to the target vocabulary. Returns (b, tgt_len, tgt_vocab)
    logits; train with sparse CE against the gold target."""
    cfg = cfg or NMTConfig()
    src = ff.create_tensor((batch_size, src_len), dtype=DataType.DT_INT32,
                           name="src_ids")
    tgt = ff.create_tensor((batch_size, tgt_len), dtype=DataType.DT_INT32,
                           name="tgt_ids")
    enc = ff.embedding(src, cfg.src_vocab, cfg.embed_dim,
                       AggrMode.AGGR_MODE_NONE, name="src_embed")
    enc = ff.lstm(enc, cfg.hidden_size, cfg.num_layers, name="encoder")
    dec = ff.embedding(tgt, cfg.tgt_vocab, cfg.embed_dim,
                       AggrMode.AGGR_MODE_NONE, name="tgt_embed")
    dec = ff.lstm(dec, cfg.hidden_size, cfg.num_layers, name="decoder")
    # attention readout over encoder states (the nmt app's per-step
    # attention, batched over all decoder positions)
    ctx = ff.multihead_attention(dec, enc, enc, cfg.hidden_size,
                                 cfg.num_heads, name="attention")
    h = ff.add(dec, ctx, name="attn_residual")
    return ff.dense(h, cfg.tgt_vocab, ActiMode.AC_MODE_NONE,
                    name="vocab_proj")


@dataclasses.dataclass
class LlamaConfig:
    """LLaMA-family decoder (RMSNorm, SwiGLU, rotary embeddings) — built
    from framework primitives (rms_norm / dense / batch_matmul / rotate
    via slice+concat), no special attention op. TPU-native addition:
    the reference predates this family."""
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_layers: int = 32
    num_heads: int = 32
    # grouped-query attention (LLaMA-2-70B/LLaMA-3 family); 0 = MHA.
    # Only the fused_attention build consumes this (the primitive form
    # predates GQA, like the reference).
    num_kv_heads: int = 0
    # Mistral-family sliding-window attention; 0 = full causal.
    # fused_attention only.
    sliding_window: int = 0
    # Qwen2-family q/k/v projection biases (o_proj stays bias-free in
    # those checkpoints; the fused op's bo is simply zero).
    # fused_attention only.
    attention_bias: bool = False
    max_position: int = 2048
    rope_theta: float = 10000.0
    rms_eps: float = 1e-6

    @classmethod
    def tiny(cls):
        return cls(vocab_size=96, hidden_size=32, intermediate_size=64,
                   num_layers=2, num_heads=4, max_position=64)


def _rope_tables(seq_len: int, head_dim: int, theta: float):
    import numpy as np
    inv = 1.0 / theta ** (np.arange(0, head_dim, 2) / head_dim)
    freqs = np.outer(np.arange(seq_len), inv)          # (s, d/2)
    emb = np.concatenate([freqs, freqs], axis=-1)      # (s, d) half-split
    shape = (1, 1, seq_len, head_dim)
    return (np.cos(emb).reshape(shape).astype(np.float32),
            np.sin(emb).reshape(shape).astype(np.float32))


def build_llama(ff: FFModel, batch_size: int, seq_len: int,
                cfg: LlamaConfig | None = None, lm_head: bool = True,
                fused_attention: bool = False):
    """Causal LM: (b, s) token ids -> (b, s, vocab) logits (or final
    hidden states when ``lm_head=False``). HF weight layout compatible
    (q/k/v/o + gate/up/down per layer, half-split rotate RoPE).

    ``fused_attention=True`` builds each attention block as ONE
    OP_MULTIHEAD_ATTENTION with in-op RoPE instead of the primitive
    dense/batch_matmul/softmax form — same math, but eligible for the
    Pallas flash kernel and KV-cache incremental decode (the primitive
    form carries seq-length-baked mask/rope constants a length-1 decode
    trace cannot satisfy). Convert primitive-layout weights with
    ``llama_fuse_params``."""
    import math
    import numpy as np
    cfg = cfg or LlamaConfig()
    b, s = batch_size, seq_len
    nh = cfg.num_heads
    hd = cfg.hidden_size // nh

    ids = ff.create_tensor((b, s), DataType.DT_INT32, name="input_ids")
    h = ff.embedding(ids, cfg.vocab_size, cfg.hidden_size,
                     AggrMode.AGGR_MODE_NONE, name="embed_tokens")

    def mlp_block(h, i):
        """SwiGLU MLP + residual — shared by both attention forms (the
        layer names are llama_fuse_params' pass-through contract)."""
        x2 = ff.rms_norm(h, eps=cfg.rms_eps, name=f"post_norm_{i}")
        gate = ff.dense(x2, cfg.intermediate_size, use_bias=False,
                        name=f"gate_proj_{i}")
        up = ff.dense(x2, cfg.intermediate_size, use_bias=False,
                      name=f"up_proj_{i}")
        silu = ff.multiply(gate, ff.sigmoid(gate), name=f"silu_{i}")
        down = ff.dense(ff.multiply(silu, up), cfg.hidden_size,
                        use_bias=False, name=f"down_proj_{i}")
        return ff.add(h, down, name=f"mlp_res_{i}")

    def head(h):
        h = ff.rms_norm(h, eps=cfg.rms_eps, name="final_norm")
        if not lm_head:
            return h
        # final softmax so the executor fuses CE-on-logits (the stable
        # loss path engages on OP_SOFTMAX outputs, executor.py; same
        # convention as build_gpt2/build_bert)
        return ff.softmax(ff.dense(h, cfg.vocab_size, use_bias=False,
                                   name="lm_head"))

    if fused_attention:
        for i in range(cfg.num_layers):
            x = ff.rms_norm(h, eps=cfg.rms_eps, name=f"input_norm_{i}")
            attn_out = ff.multihead_attention(
                x, x, x, cfg.hidden_size, nh,
                bias=cfg.attention_bias, causal=True,
                rope=True, rope_theta=cfg.rope_theta,
                num_kv_heads=cfg.num_kv_heads,
                sliding_window=cfg.sliding_window, name=f"attn_{i}")
            h = ff.add(h, attn_out, name=f"attn_res_{i}")
            h = mlp_block(h, i)
        return head(h)

    if cfg.sliding_window or cfg.attention_bias \
            or cfg.num_kv_heads not in (0, nh):
        raise ValueError(
            "sliding_window/GQA/attention_bias need "
            "fused_attention=True — the primitive build predates them "
            "and would silently compute plain full MHA")
    cos_np, sin_np = _rope_tables(s, hd, cfg.rope_theta)
    cos_t = ff.create_tensor(cos_np.shape, create_grad=False,
                             name="rope_cos")
    cos_t.set_tensor(cos_np)
    sin_t = ff.create_tensor(sin_np.shape, create_grad=False,
                             name="rope_sin")
    sin_t.set_tensor(sin_np)
    mask_np = np.triu(np.full((1, 1, s, s), -1e9, np.float32), 1)
    mask_t = ff.create_tensor(mask_np.shape, create_grad=False,
                              name="causal_mask")
    mask_t.set_tensor(mask_np)

    def heads(x, name):
        # (b, s, H) -> (b, nh, s, hd)
        return ff.transpose(ff.reshape(x, (b, s, nh, hd),
                                       name=f"{name}_split"),
                            (0, 2, 1, 3), name=f"{name}_t")

    def rope(x, name):
        x1 = ff.slice_tensor(x, [0], [hd // 2], [3], name=f"{name}_lo")
        x2 = ff.slice_tensor(x, [hd // 2], [hd], [3], name=f"{name}_hi")
        rot = ff.concat([ff.scalar_multiply(x2, -1.0), x1], axis=-1,
                        name=f"{name}_rot")
        return ff.add(ff.multiply(x, cos_t), ff.multiply(rot, sin_t),
                      name=f"{name}_rope")

    for i in range(cfg.num_layers):
        x = ff.rms_norm(h, eps=cfg.rms_eps, name=f"input_norm_{i}")
        q = rope(heads(ff.dense(x, cfg.hidden_size, use_bias=False,
                                name=f"q_proj_{i}"), f"q{i}"), f"q{i}")
        k = rope(heads(ff.dense(x, cfg.hidden_size, use_bias=False,
                                name=f"k_proj_{i}"), f"k{i}"), f"k{i}")
        v = heads(ff.dense(x, cfg.hidden_size, use_bias=False,
                           name=f"v_proj_{i}"), f"v{i}")
        kt = ff.transpose(k, (0, 1, 3, 2), name=f"kT_{i}")
        scores = ff.scalar_multiply(
            ff.batch_matmul(q, kt, name=f"qk_{i}"), 1.0 / math.sqrt(hd))
        probs = ff.softmax(ff.add(scores, mask_t), axis=-1,
                           name=f"probs_{i}")
        ctx = ff.batch_matmul(probs, v, name=f"ctx_{i}")
        merged = ff.reshape(ff.transpose(ctx, (0, 2, 1, 3)),
                            (b, s, cfg.hidden_size), name=f"merge_{i}")
        attn_out = ff.dense(merged, cfg.hidden_size, use_bias=False,
                            name=f"o_proj_{i}")
        h = ff.add(h, attn_out, name=f"attn_res_{i}")
        h = mlp_block(h, i)

    return head(h)


def _fuse_qkvo(q, k, v, o, e, nh, kvh):
    """Shared (in, out)-kernel -> fused-attention reshapes: wq/wk/wv
    (e, heads, hd), wo (nh, hd, e). The single reshape convention for
    both llama_fuse_params and the HF state-dict loader."""
    hd = e // nh
    return {"wq": q.reshape(e, nh, hd),
            "wk": k.reshape(e, kvh, hd),
            "wv": v.reshape(e, kvh, hd),
            "wo": o.reshape(nh, hd, e)}


def llama_fuse_params(params, cfg: LlamaConfig):
    """Convert primitive-layout LLaMA params (``build_llama`` default:
    ``q_proj_{i}``/``k_proj_{i}``/``v_proj_{i}``/``o_proj_{i}`` dense
    kernels, the HF import layout) into the fused-attention layout
    (``attn_{i}``: wq/wk/wv (e, h, d), wo (h, d, e)). Non-attention
    entries (norms, FFN, embeddings, lm_head) share names and pass
    through unchanged — so HF-imported weights can serve through the
    flash/KV-decode path."""
    import numpy as np
    if cfg.num_kv_heads not in (0, cfg.num_heads):
        raise ValueError(
            "llama_fuse_params converts the MHA primitive layout; a "
            "GQA target (num_kv_heads < num_heads) has no primitive "
            "source — load GQA checkpoints into the fused layout "
            "directly")
    nh = cfg.num_heads
    e = cfg.hidden_size
    hd = e // nh
    out = {}
    fused = {}
    for i in range(cfg.num_layers):
        fused[f"attn_{i}"] = _fuse_qkvo(
            np.asarray(params[f"q_proj_{i}"]["kernel"]),
            np.asarray(params[f"k_proj_{i}"]["kernel"]),
            np.asarray(params[f"v_proj_{i}"]["kernel"]),
            np.asarray(params[f"o_proj_{i}"]["kernel"]), e, nh, nh)
    skip = {f"{p}_proj_{i}" for i in range(cfg.num_layers)
            for p in ("q", "k", "v", "o")}
    for name, leaf in params.items():
        if name not in skip:
            out[name] = leaf
    out.update(fused)
    return out


def llama_load_hf_state_dict(state_dict, cfg: LlamaConfig,
                             fused: bool = False):
    """Map a HuggingFace ``LlamaForCausalLM`` state dict onto
    ``build_llama``'s parameter layout (primitive by default; ``fused``
    produces the fused-attention layout, required for GQA checkpoints
    where num_kv_heads < num_heads). HF stores Linear weights as
    (out, in); dense kernels here are (in, out). RoPE carries no
    weights in either convention, so the mapping is purely structural.

    Values may be torch tensors (CPU) or arrays. Returns the params
    dict for ``FFModel.params`` (numpy leaves; device placement happens
    on first use)."""
    import numpy as np

    def _np(v):
        try:
            return np.asarray(v)
        except Exception:
            # bf16 torch tensors have no numpy dtype — upcast (params
            # here are fp32 masters anyway)
            return v.detach().cpu().float().numpy()

    nh = cfg.num_heads
    e = cfg.hidden_size
    hd = e // nh
    kvh = cfg.num_kv_heads or nh
    if (kvh != nh or cfg.attention_bias) and not fused:
        raise ValueError("GQA / attention-bias checkpoints need "
                         "fused=True (the primitive build is plain "
                         "bias-free MHA)")
    sd = {k: _np(v) for k, v in state_dict.items()}
    consumed = set()

    def take(key):
        consumed.add(key)
        return sd[key]

    # tie_word_embeddings checkpoints (Llama-3.2-1B/3B class) omit
    # lm_head.weight — the head shares the embedding matrix
    if "lm_head.weight" in sd:
        lm_w = take("lm_head.weight")
    else:
        lm_w = sd["model.embed_tokens.weight"]
    params = {
        "embed_tokens": {"kernel": take("model.embed_tokens.weight")},
        "final_norm": {"scale": take("model.norm.weight")},
        "lm_head": {"kernel": lm_w.T},
    }
    if params["embed_tokens"]["kernel"].shape[1] != e:
        raise ValueError(
            f"embed_tokens kernel shape "
            f"{params['embed_tokens']['kernel'].shape} does not match "
            f"hidden_size {e}")
    for i in range(cfg.num_layers):
        p = f"model.layers.{i}."
        params[f"input_norm_{i}"] = {
            "scale": take(p + "input_layernorm.weight")}
        params[f"post_norm_{i}"] = {
            "scale": take(p + "post_attention_layernorm.weight")}
        for proj in ("gate", "up", "down"):
            params[f"{proj}_proj_{i}"] = {
                "kernel": take(p + f"mlp.{proj}_proj.weight").T}
        q = take(p + "self_attn.q_proj.weight").T      # (e, nh*hd)
        k = take(p + "self_attn.k_proj.weight").T      # (e, kvh*hd)
        v = take(p + "self_attn.v_proj.weight").T
        o = take(p + "self_attn.o_proj.weight").T      # (nh*hd, e)
        if q.shape != (e, nh * hd) or k.shape != (e, kvh * hd):
            raise ValueError(
                f"checkpoint/config head mismatch: q {q.shape} "
                f"k {k.shape} vs (e={e}, nh={nh}, kvh={kvh}, hd={hd})")
        if fused:
            attn = _fuse_qkvo(q, k, v, o, e, nh, kvh)
            if cfg.attention_bias:
                # Qwen2 family: q/k/v carry biases, o_proj does not —
                # the fused op's bo is present but zero
                attn["bq"] = take(
                    p + "self_attn.q_proj.bias").reshape(nh, hd)
                attn["bk"] = take(
                    p + "self_attn.k_proj.bias").reshape(kvh, hd)
                attn["bv"] = take(
                    p + "self_attn.v_proj.bias").reshape(kvh, hd)
                attn["bo"] = np.zeros((e,), attn["wq"].dtype)
            params[f"attn_{i}"] = attn
        else:
            params[f"q_proj_{i}"] = {"kernel": q}
            params[f"k_proj_{i}"] = {"kernel": k}
            params[f"v_proj_{i}"] = {"kernel": v}
            params[f"o_proj_{i}"] = {"kernel": o}
    # every checkpoint tensor must have been mapped (buffers like the
    # legacy rotary inv_freq are recomputed in-op and safely skipped);
    # silently dropping weights (attention biases, extra layers) would
    # produce wrong numerics with no signal
    leftover = [k_ for k_ in sd
                if k_ not in consumed and "rotary_emb" not in k_]
    if leftover:
        raise ValueError(
            f"unmapped checkpoint tensors {sorted(leftover)[:8]}"
            f"{'...' if len(leftover) > 8 else ''} — config/architecture "
            f"mismatch (attention_bias / num_layers / tied embeddings?)")
    return params


@dataclasses.dataclass
class MixtralConfig:
    """Mixtral-family sparse-MoE decoder (Mistral backbone: fused
    attention + GQA + RoPE, FFN replaced by a top-k mixture of SwiGLU
    experts). Beyond-reference: the reference's MoE (moe.cc) is the
    2017 classification MoE, not an LM block."""
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 14336
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 8
    num_experts: int = 8
    experts_per_tok: int = 2
    max_position: int = 2048
    rope_theta: float = 10000.0
    rms_eps: float = 1e-6
    # Mistral-backbone sliding window (HF MixtralConfig defaults 4096);
    # 0 = full causal
    sliding_window: int = 0

    @classmethod
    def tiny(cls):
        return cls(vocab_size=96, hidden_size=32, intermediate_size=64,
                   num_layers=2, num_heads=4, num_kv_heads=2,
                   num_experts=4, experts_per_tok=2, max_position=64)


def build_mixtral(ff: FFModel, batch_size: int, seq_len: int,
                  cfg: MixtralConfig | None = None,
                  lm_head: bool = True):
    """Mixtral decoder as a dense mixture: every expert computes, each
    token weights the top-k experts by its renormalized router probs
    (HF MixtralSparseMoeBlock semantics exactly — parity-tested against
    transformers). For sparse dispatch at scale use the MoE op family
    (group_by/aggregate) with expert_parallel_strategy; the dense form
    is exact, serving-friendly, and KV-decode eligible."""
    cfg = cfg or MixtralConfig()
    b, s = batch_size, seq_len
    E, k = cfg.num_experts, cfg.experts_per_tok

    ids = ff.create_tensor((b, s), DataType.DT_INT32, name="input_ids")
    h = ff.embedding(ids, cfg.vocab_size, cfg.hidden_size,
                     AggrMode.AGGR_MODE_NONE, name="embed_tokens")
    # one constant id per expert, shared by every layer's routing mask
    expert_sel = [ff.create_constant((1,), float(e_i), DataType.DT_INT32)
                  for e_i in range(E)]

    for i in range(cfg.num_layers):
        x = ff.rms_norm(h, eps=cfg.rms_eps, name=f"input_norm_{i}")
        attn_out = ff.multihead_attention(
            x, x, x, cfg.hidden_size, cfg.num_heads, bias=False,
            causal=True, rope=True, rope_theta=cfg.rope_theta,
            num_kv_heads=cfg.num_kv_heads,
            sliding_window=cfg.sliding_window, name=f"attn_{i}")
        h = ff.add(h, attn_out, name=f"attn_res_{i}")

        x2 = ff.rms_norm(h, eps=cfg.rms_eps, name=f"post_norm_{i}")
        router = ff.dense(x2, E, use_bias=False, name=f"moe_gate_{i}")
        probs = ff.softmax(router, axis=-1, name=f"moe_probs_{i}")
        vals, idx = ff.top_k(probs, k, True, name=f"moe_topk_{i}")
        denom = ff.reduce_sum(vals, [-1], keepdims=True,
                              name=f"moe_denom_{i}")
        moe_out = None
        for e_i in range(E):
            m = ff.cast(ff.equal(idx, expert_sel[e_i],
                                 name=f"moe_eq_{i}_{e_i}"),
                        DataType.DT_FLOAT, name=f"moe_m_{i}_{e_i}")
            w = ff.divide(
                ff.reduce_sum(ff.multiply(vals, m), [-1], keepdims=True,
                              name=f"moe_w_{i}_{e_i}"),
                denom, name=f"moe_wn_{i}_{e_i}")
            gate = ff.dense(x2, cfg.intermediate_size, use_bias=False,
                            name=f"e{e_i}_w1_{i}")
            up = ff.dense(x2, cfg.intermediate_size, use_bias=False,
                          name=f"e{e_i}_w3_{i}")
            act = ff.multiply(ff.multiply(gate, ff.sigmoid(gate)), up,
                              name=f"moe_act_{i}_{e_i}")
            down = ff.dense(act, cfg.hidden_size, use_bias=False,
                            name=f"e{e_i}_w2_{i}")
            contrib = ff.multiply(down, w, name=f"moe_c_{i}_{e_i}")
            moe_out = contrib if moe_out is None else \
                ff.add(moe_out, contrib, name=f"moe_sum_{i}_{e_i}")
        h = ff.add(h, moe_out, name=f"mlp_res_{i}")

    h = ff.rms_norm(h, eps=cfg.rms_eps, name="final_norm")
    if not lm_head:
        return h
    return ff.softmax(ff.dense(h, cfg.vocab_size, use_bias=False,
                               name="lm_head"))


def mixtral_load_hf_state_dict(state_dict, cfg: MixtralConfig):
    """Map a HuggingFace ``MixtralForCausalLM`` state dict onto
    ``build_mixtral``'s layout (attention via the shared fused
    reshapes; experts w1/w2/w3 -> e{e}_w1/w2/w3 kernels)."""
    import numpy as np

    def _np(v):
        try:
            return np.asarray(v)
        except Exception:
            return v.detach().cpu().float().numpy()

    nh, e = cfg.num_heads, cfg.hidden_size
    hd = e // nh
    kvh = cfg.num_kv_heads or nh
    sd = {k_: _np(v) for k_, v in state_dict.items()}
    consumed = set()

    def take(key):
        consumed.add(key)
        return sd[key]

    if "lm_head.weight" in sd:
        lm_w = take("lm_head.weight")
    else:
        lm_w = sd["model.embed_tokens.weight"]
    params = {
        "embed_tokens": {"kernel": take("model.embed_tokens.weight")},
        "final_norm": {"scale": take("model.norm.weight")},
        "lm_head": {"kernel": lm_w.T},
    }
    for i in range(cfg.num_layers):
        p = f"model.layers.{i}."
        params[f"input_norm_{i}"] = {
            "scale": take(p + "input_layernorm.weight")}
        params[f"post_norm_{i}"] = {
            "scale": take(p + "post_attention_layernorm.weight")}
        q = take(p + "self_attn.q_proj.weight").T
        k = take(p + "self_attn.k_proj.weight").T
        if q.shape != (e, nh * hd) or k.shape != (e, kvh * hd):
            raise ValueError(
                f"checkpoint/config head mismatch: q {q.shape} "
                f"k {k.shape} vs (e={e}, nh={nh}, kvh={kvh}, hd={hd})")
        params[f"attn_{i}"] = _fuse_qkvo(
            q, k,
            take(p + "self_attn.v_proj.weight").T,
            take(p + "self_attn.o_proj.weight").T, e, nh, kvh)
        params[f"moe_gate_{i}"] = {
            "kernel": take(p + "block_sparse_moe.gate.weight").T}
        for x in range(cfg.num_experts):
            ep = p + f"block_sparse_moe.experts.{x}."
            params[f"e{x}_w1_{i}"] = {"kernel": take(ep + "w1.weight").T}
            params[f"e{x}_w2_{i}"] = {"kernel": take(ep + "w2.weight").T}
            params[f"e{x}_w3_{i}"] = {"kernel": take(ep + "w3.weight").T}
    leftover = [k_ for k_ in sd
                if k_ not in consumed and "rotary_emb" not in k_]
    if leftover:
        raise ValueError(f"unmapped checkpoint tensors "
                         f"{sorted(leftover)[:8]} — config mismatch")
    return params
