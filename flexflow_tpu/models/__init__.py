from .vision import (build_alexnet, build_alexnet_cifar10, build_resnet50,
                     build_resnext50, build_inception_v3)  # noqa: F401
from .nlp import (TransformerConfig, BertConfig, GPTConfig, NMTConfig,
                  LlamaConfig, MixtralConfig, build_transformer,
                  build_bert, build_gpt2, build_nmt, build_llama,
                  build_mixtral)  # noqa: F401
from .recsys import DLRMConfig, XDLConfig, build_dlrm, build_xdl  # noqa: F401
from .misc import (CandleConfig, MoeConfig, build_mlp, build_candle_uno,
                   build_moe_mnist)  # noqa: F401
