"""ctypes bindings for the native (C++) runtime library.

The reference implements its runtime core in C++ (simulator, dataloader,
graph machinery — SURVEY.md §2.1/§2.3); this package is the TPU rebuild's
native layer: ``flexflow_tpu/native/src/ffruntime.cc`` compiled to ``libffruntime.so``.

``ensure_built()`` compiles the library on first use (g++, no external
deps); every entry point has a pure-Python fallback so the framework works
even without a toolchain, and the tests assert C++ == Python semantics.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Sequence, Tuple

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_HERE, "libffruntime.so")
# the C++ source ships INSIDE the package (package-data), so a
# pip-installed copy can rebuild the library on any host with g++
_SRC = os.path.join(_HERE, "src", "ffruntime.cc")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False


def ensure_built(force: bool = False) -> bool:
    """Compile libffruntime.so if missing. Returns True if available."""
    global _build_failed
    if os.path.exists(_SO) and not force:
        return True
    if _build_failed and not force:
        return False
    if not os.path.exists(_SRC):
        _build_failed = True
        return False
    try:
        subprocess.run(
            ["g++", "-O3", "-std=c++17", "-fPIC", "-Wall", "-pthread",
             "-shared", "-o", _SO, _SRC],
            check=True, capture_output=True, timeout=120)
        return True
    except Exception:
        _build_failed = True
        return False


def get_lib() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library; None if unavailable."""
    global _lib
    # benign: double-checked locking — the unlocked read is an atomic
    # reference load; _lock orders the one-time build+publish below
    if _lib is not None:  # ffcheck: ok(guarded-field)
        return _lib  # ffcheck: ok(guarded-field)
    with _lock:
        if _lib is not None:
            return _lib
        if not ensure_built():
            return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            return None
        i32p = ctypes.POINTER(ctypes.c_int32)
        i64p = ctypes.POINTER(ctypes.c_int64)
        f64p = ctypes.POINTER(ctypes.c_double)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        u64p = ctypes.POINTER(ctypes.c_uint64)
        lib.ffsim_simulate.restype = ctypes.c_double
        lib.ffsim_simulate.argtypes = [
            ctypes.c_int32, i32p, f64p, ctypes.c_int64, i32p, i32p,
            ctypes.c_int32, f64p]
        lib.ffsim_critical_path.restype = ctypes.c_double
        lib.ffsim_critical_path.argtypes = [
            ctypes.c_int32, f64p, ctypes.c_int64, i32p, i32p]
        lib.ffdl_gather.restype = None
        lib.ffdl_gather.argtypes = [u8p, u8p, i64p, ctypes.c_int64,
                                    ctypes.c_int64, ctypes.c_int32]
        lib.ffgraph_closure.restype = ctypes.c_int32
        lib.ffgraph_closure.argtypes = [ctypes.c_int32, ctypes.c_int64,
                                        i32p, i32p, u64p]
        lib.ffb_new.restype = ctypes.c_void_p
        lib.ffb_free.argtypes = [ctypes.c_void_p]
        lib.ffb_n_tasks.restype = ctypes.c_int64
        lib.ffb_n_tasks.argtypes = [ctypes.c_void_p]
        lib.ffb_n_edges.restype = ctypes.c_int64
        lib.ffb_n_edges.argtypes = [ctypes.c_void_p]
        lib.ffb_add_tasks.restype = ctypes.c_int32
        lib.ffb_add_tasks.argtypes = [ctypes.c_void_p, ctypes.c_int32,
                                      i32p, f64p]
        lib.ffb_cross_deps.restype = None
        lib.ffb_cross_deps.argtypes = [ctypes.c_void_p, ctypes.c_int32,
                                       i32p, ctypes.c_int32, i32p]
        lib.ffb_collective.restype = ctypes.c_int32
        lib.ffb_collective.argtypes = [
            ctypes.c_void_p, ctypes.c_int32, i32p, i32p, f64p,
            ctypes.c_int32, ctypes.c_double, ctypes.c_int32,
            ctypes.c_int32, i32p, i32p]
        lib.ffb_simulate.restype = ctypes.c_double
        lib.ffb_simulate.argtypes = [ctypes.c_void_p, ctypes.c_int32]
        lib.ffb_get.restype = None
        lib.ffb_get.argtypes = [ctypes.c_void_p, i32p, f64p, i32p, i32p]
        _lib = lib
        return _lib


def available() -> bool:
    return get_lib() is not None


def _as(arr, dtype):
    return np.ascontiguousarray(np.asarray(arr, dtype=dtype))


# ---------------------------------------------------------------------------
# task-graph simulation
# ---------------------------------------------------------------------------
def simulate(proc: Sequence[int], duration: Sequence[float],
             edges: Sequence[Tuple[int, int]], n_procs: int,
             want_starts: bool = False):
    """Event-driven task-graph simulation (reference
    ``Simulator::simulate_runtime``). Returns makespan, or (makespan,
    starts). Uses the C++ engine when available, else the Python fallback."""
    lib = get_lib()
    if lib is None:
        return simulate_py(proc, duration, edges, n_procs, want_starts)
    proc_a = _as(proc, np.int32)
    dur_a = _as(duration, np.float64)
    n = len(proc_a)
    e = np.asarray(edges, dtype=np.int32).reshape(-1, 2)
    esrc = _as(e[:, 0], np.int32)
    edst = _as(e[:, 1], np.int32)
    starts = np.zeros(n, np.float64) if want_starts else None
    i32p = ctypes.POINTER(ctypes.c_int32)
    f64p = ctypes.POINTER(ctypes.c_double)
    ms = lib.ffsim_simulate(
        n, proc_a.ctypes.data_as(i32p), dur_a.ctypes.data_as(f64p),
        len(e), esrc.ctypes.data_as(i32p), edst.ctypes.data_as(i32p),
        int(n_procs),
        starts.ctypes.data_as(f64p) if starts is not None else None)
    if ms < 0:
        raise ValueError("task graph contains a cycle or bad ids")
    return (ms, starts) if want_starts else ms


def simulate_py(proc, duration, edges, n_procs, want_starts: bool = False):
    """Pure-Python reference implementation (same scheduling semantics)."""
    import heapq
    n = len(proc)
    succ = [[] for _ in range(n)]
    indeg = [0] * n
    for s, d in edges:
        succ[s].append(d)
        indeg[d] += 1
    ready = [0.0] * n
    start = [0.0] * n
    avail = [0.0] * int(n_procs)
    pq = [(0.0, i) for i in range(n) if indeg[i] == 0]
    heapq.heapify(pq)
    done = 0
    makespan = 0.0
    while pq:
        rt, t = heapq.heappop(pq)
        st = max(rt, avail[proc[t]])
        ft = st + duration[t]
        start[t] = st
        avail[proc[t]] = ft
        makespan = max(makespan, ft)
        done += 1
        for s in succ[t]:
            ready[s] = max(ready[s], ft)
            indeg[s] -= 1
            if indeg[s] == 0:
                heapq.heappush(pq, (ready[s], s))
    if done != n:
        raise ValueError("task graph contains a cycle")
    if want_starts:
        return makespan, np.asarray(start)
    return makespan


def critical_path(duration, edges) -> float:
    """Longest path ignoring processor contention (overlap lower bound)."""
    lib = get_lib()
    dur_a = _as(duration, np.float64)
    n = len(dur_a)
    e = np.asarray(edges, dtype=np.int32).reshape(-1, 2)
    if lib is not None:
        i32p = ctypes.POINTER(ctypes.c_int32)
        f64p = ctypes.POINTER(ctypes.c_double)
        esrc = _as(e[:, 0], np.int32)
        edst = _as(e[:, 1], np.int32)
        cp = lib.ffsim_critical_path(
            n, dur_a.ctypes.data_as(f64p), len(e),
            esrc.ctypes.data_as(i32p), edst.ctypes.data_as(i32p))
        if cp < 0:
            raise ValueError("cycle")
        return cp
    # python fallback
    succ = [[] for _ in range(n)]
    indeg = [0] * n
    for s, d in e:
        succ[s].append(int(d))
        indeg[d] += 1
    order = [i for i in range(n) if indeg[i] == 0]
    fin = [0.0] * n
    best = 0.0
    for t in order:
        ft = fin[t] + float(dur_a[t])
        best = max(best, ft)
        for s in succ[t]:
            fin[s] = max(fin[s], ft)
            indeg[s] -= 1
            if indeg[s] == 0:
                order.append(s)
    if len(order) != n:
        raise ValueError("cycle")
    return best


# ---------------------------------------------------------------------------
# task-graph builder (search hot loop)
# ---------------------------------------------------------------------------
_I32P = ctypes.POINTER(ctypes.c_int32)
_F64P = ctypes.POINTER(ctypes.c_double)


class TaskBuffer:
    """Task-graph accumulation buffer for the strategy search.

    Native-backed when libffruntime.so is available (the ring-collective
    expansion of one search is ~20M dependency edges — the round-4
    profile's hottest Python loop); the pure-Python branch implements
    IDENTICAL semantics (tests assert parity). One logical collective is
    one call either way."""

    def __init__(self):
        self._lib = get_lib()
        if self._lib is not None:
            self._h = self._lib.ffb_new()
        else:
            self.proc: list = []
            self.dur: list = []
            self.edges: list = []

    def __del__(self):
        lib = getattr(self, "_lib", None)
        if lib is not None and getattr(self, "_h", None):
            lib.ffb_free(self._h)
            self._h = None

    @property
    def n_tasks(self) -> int:
        if self._lib is not None:
            return int(self._lib.ffb_n_tasks(self._h))
        return len(self.proc)

    def add_tasks(self, procs, durs) -> int:
        """Append len(procs) tasks; returns the first id (consecutive)."""
        if self._lib is not None:
            p = _as(procs, np.int32)
            d = _as(durs, np.float64)
            return int(self._lib.ffb_add_tasks(
                self._h, len(p), p.ctypes.data_as(_I32P),
                d.ctypes.data_as(_F64P)))
        first = len(self.proc)
        self.proc.extend(int(x) for x in procs)
        self.dur.extend(float(x) for x in durs)
        return first

    def cross_deps(self, a, b) -> None:
        """All-pairs dependencies: every a[i] -> every b[j]."""
        if not len(a) or not len(b):
            return
        if self._lib is not None:
            aa = _as(a, np.int32)
            bb = _as(b, np.int32)
            self._lib.ffb_cross_deps(
                self._h, len(aa), aa.ctypes.data_as(_I32P),
                len(bb), bb.ctypes.data_as(_I32P))
            return
        for x in a:
            for y in b:
                self.edges.append((int(x), int(y)))

    def collective(self, route_off, route_procs, route_fac, rounds: int,
                   per_round_secs: float, n_seg: int, deps) -> list:
        """Ring-collective expansion (see ffb_collective in
        src/ffruntime.cc for the dependency structure). Returns
        the final task id of each participant that produced tasks."""
        n_routes = len(route_off) - 1
        if n_routes <= 0 or rounds <= 0:
            return []
        if self._lib is not None:
            off = _as(route_off, np.int32)
            procs = _as(route_procs, np.int32)
            fac = None if route_fac is None else _as(route_fac, np.float64)
            dep = _as(deps, np.int32)
            out = np.zeros(n_routes, np.int32)
            n = self._lib.ffb_collective(
                self._h, n_routes, off.ctypes.data_as(_I32P),
                procs.ctypes.data_as(_I32P),
                fac.ctypes.data_as(_F64P) if fac is not None else None,
                int(rounds), float(per_round_secs), max(1, int(n_seg)),
                len(dep), dep.ctypes.data_as(_I32P),
                out.ctypes.data_as(_I32P))
            return [int(x) for x in out[:n]]
        # python mirror of ffb_collective
        n_seg = max(1, int(n_seg))
        prev_last = [-1] * n_routes
        for r in range(rounds):
            cur = [-1] * n_routes
            for i in range(n_routes):
                h0, h1 = route_off[i], route_off[i + 1]
                if h0 >= h1:
                    cur[i] = prev_last[i]
                    continue
                last = -1
                for _s in range(n_seg):
                    prev = -1
                    for h in range(h0, h1):
                        d = (per_round_secs / n_seg) * (
                            route_fac[h] if route_fac is not None else 1.0)
                        t = len(self.proc)
                        self.proc.append(int(route_procs[h]))
                        self.dur.append(d)
                        if prev < 0:
                            if r == 0:
                                for k in deps:
                                    self.edges.append((int(k), t))
                            else:
                                pp = prev_last[(i - 1) % n_routes]
                                if pp >= 0:
                                    self.edges.append((pp, t))
                                if prev_last[i] >= 0:
                                    self.edges.append((prev_last[i], t))
                        else:
                            self.edges.append((prev, t))
                        prev = t
                    if prev >= 0:
                        last = prev
                cur[i] = last if last >= 0 else prev_last[i]
            prev_last = cur
        return [t for t in prev_last if t >= 0]

    def arrays(self):
        """(proc, dur, edges Nx2) copies — tests/introspection only."""
        if self._lib is None:
            return (list(self.proc), list(self.dur),
                    [tuple(e) for e in self.edges])
        n = int(self._lib.ffb_n_tasks(self._h))
        m = int(self._lib.ffb_n_edges(self._h))
        proc = np.zeros(n, np.int32)
        dur = np.zeros(n, np.float64)
        esrc = np.zeros(m, np.int32)
        edst = np.zeros(m, np.int32)
        self._lib.ffb_get(self._h, proc.ctypes.data_as(_I32P),
                          dur.ctypes.data_as(_F64P),
                          esrc.ctypes.data_as(_I32P),
                          edst.ctypes.data_as(_I32P))
        return proc, dur, np.stack([esrc, edst], axis=1)

    def simulate(self, n_procs: int) -> float:
        """Play the accumulated DAG through the event simulator."""
        if self._lib is not None:
            ms = self._lib.ffb_simulate(self._h, int(n_procs))
            if ms < 0:
                raise ValueError("task graph contains a cycle or bad ids")
            return float(ms)
        return simulate_py(self.proc, self.dur, self.edges, n_procs)


# ---------------------------------------------------------------------------
# dataloader gather
# ---------------------------------------------------------------------------
def gather_batch(src: np.ndarray, indices: np.ndarray,
                 out: Optional[np.ndarray] = None,
                 n_threads: int = 4) -> np.ndarray:
    """out[b] = src[indices[b]] — threaded C++ row gather when available
    (reference dataloader batch-copy tasks)."""
    src = np.ascontiguousarray(src)
    idx = _as(indices, np.int64)
    # normalize negative indices + bounds-check: the C++ path must match
    # np.take semantics exactly (no silent OOB reads)
    n_rows = src.shape[0]
    idx = np.where(idx < 0, idx + n_rows, idx)
    if len(idx) and (idx.min() < 0 or idx.max() >= n_rows):
        raise IndexError("gather_batch index out of range")
    batch = len(idx)
    row_shape = src.shape[1:]
    if out is None:
        out = np.empty((batch,) + row_shape, dtype=src.dtype)
    elif (out.shape != (batch,) + row_shape or out.dtype != src.dtype
          or not out.flags.c_contiguous):
        raise ValueError(
            f"out must be C-contiguous {(batch,) + row_shape} {src.dtype}")
    lib = get_lib()
    if lib is None:
        np.take(src, idx, axis=0, out=out)
        return out
    sample_bytes = int(np.prod(row_shape, dtype=np.int64)) * src.itemsize
    u8p = ctypes.POINTER(ctypes.c_uint8)
    i64p = ctypes.POINTER(ctypes.c_int64)
    lib.ffdl_gather(
        src.ctypes.data_as(u8p), out.ctypes.data_as(u8p),
        idx.ctypes.data_as(i64p), batch, sample_bytes, int(n_threads))
    return out


# ---------------------------------------------------------------------------
# reachability closure
# ---------------------------------------------------------------------------
def transitive_closure(n: int, edges) -> np.ndarray:
    """Packed-bitset transitive closure: bool matrix reach[i, j]."""
    words = (n + 63) // 64
    e = np.asarray(edges, dtype=np.int32).reshape(-1, 2)
    lib = get_lib()
    if lib is not None:
        out = np.zeros(n * words, np.uint64)
        i32p = ctypes.POINTER(ctypes.c_int32)
        u64p = ctypes.POINTER(ctypes.c_uint64)
        esrc = _as(e[:, 0], np.int32)
        edst = _as(e[:, 1], np.int32)
        rc = lib.ffgraph_closure(n, len(e), esrc.ctypes.data_as(i32p),
                                 edst.ctypes.data_as(i32p),
                                 out.ctypes.data_as(u64p))
        if rc != 0:
            raise ValueError("cycle")
        bits = np.unpackbits(out.reshape(n, words).view(np.uint8),
                             axis=1, bitorder="little")
        return bits[:, :n].astype(bool)
    # python fallback
    reach = np.zeros((n, n), bool)
    indeg = [0] * n
    succ = [[] for _ in range(n)]
    pred = [[] for _ in range(n)]
    for s, d in e:
        succ[s].append(int(d))
        pred[d].append(int(s))
        indeg[d] += 1
    order = [i for i in range(n) if indeg[i] == 0]
    for t in order:
        for p in pred[t]:
            reach[t] |= reach[p]
            reach[t, p] = True
        for s in succ[t]:
            indeg[s] -= 1
            if indeg[s] == 0:
                order.append(s)
    if len(order) != n:
        raise ValueError("cycle")
    return reach
