// flexflow_tpu native runtime library (C++, ctypes ABI).
//
// TPU-native re-implementation of the reference's native runtime pieces:
//
//  1. Event-driven task-graph simulator — the analog of
//     Simulator::simulate_runtime (reference src/runtime/simulator.cc:822-1200):
//     tasks carry a processor id (compute shard OR communication link — the
//     reference models links as devices too) and a duration; dependencies form
//     a DAG; the simulator plays the DAG against per-processor FIFO queues and
//     returns the makespan plus per-task start times. Used by the
//     auto-parallelization search to score candidate strategies with
//     queueing/overlap fidelity the additive cost model lacks.
//
//  2. Parallel batch gather — the analog of the reference's dataloader
//     index-launch batch copies (src/dataloader/dataloader.cc:324,382):
//     gathers shuffled sample rows into a contiguous batch buffer with a
//     thread pool (wired into SingleDataLoader._host_batch).
//
//  3. Graph reachability helpers (transitive closure bitsets) backing the
//     PCG's structural validation / cycle detection
//     (Graph.check_consistency; reference Graph::check_correctness,
//     src/runtime/graph.cc).
//
// Exposed as a flat C ABI for ctypes (no pybind11 in this image).

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstring>
#include <queue>
#include <thread>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// 1. Event-driven task-graph simulation
// ---------------------------------------------------------------------------
// tasks i in [0, n_tasks): proc[i] (processor id, compute or link),
// duration[i] seconds. edges j: esrc[j] -> edst[j].
// Returns makespan (seconds); if start_out != nullptr it receives per-task
// start times. Returns -1.0 on malformed input (cycle / bad ids).
double ffsim_simulate(int32_t n_tasks, const int32_t* proc,
                      const double* duration, int64_t n_edges,
                      const int32_t* esrc, const int32_t* edst,
                      int32_t n_procs, double* start_out) {
  if (n_tasks <= 0) return 0.0;
  std::vector<std::vector<int32_t>> succ(n_tasks);
  std::vector<int32_t> indeg(n_tasks, 0);
  for (int64_t j = 0; j < n_edges; ++j) {
    int32_t s = esrc[j], d = edst[j];
    if (s < 0 || s >= n_tasks || d < 0 || d >= n_tasks) return -1.0;
    succ[s].push_back(d);
    indeg[d]++;
  }
  for (int32_t i = 0; i < n_tasks; ++i)
    if (proc[i] < 0 || proc[i] >= n_procs) return -1.0;

  std::vector<double> ready(n_tasks, 0.0);   // max finish over preds
  std::vector<double> start(n_tasks, 0.0);
  std::vector<double> proc_avail(n_procs, 0.0);

  // min-heap of ready tasks keyed by (ready_time, id): FIFO-by-readiness per
  // processor, matching the reference's simulate_runtime scheduling order
  using Entry = std::pair<double, int32_t>;
  std::priority_queue<Entry, std::vector<Entry>, std::greater<Entry>> pq;
  for (int32_t i = 0; i < n_tasks; ++i)
    if (indeg[i] == 0) pq.emplace(0.0, i);

  int32_t done = 0;
  double makespan = 0.0;
  while (!pq.empty()) {
    auto [rt, t] = pq.top();
    pq.pop();
    int32_t p = proc[t];
    double st = std::max(rt, proc_avail[p]);
    double ft = st + duration[t];
    start[t] = st;
    proc_avail[p] = ft;
    makespan = std::max(makespan, ft);
    ++done;
    for (int32_t s : succ[t]) {
      ready[s] = std::max(ready[s], ft);
      if (--indeg[s] == 0) pq.emplace(ready[s], s);
    }
  }
  if (done != n_tasks) return -1.0;  // cycle
  if (start_out) std::memcpy(start_out, start.data(), n_tasks * sizeof(double));
  return makespan;
}

// Longest path through the DAG ignoring processor contention (lower bound;
// the reference compares this against the simulated makespan when
// estimating overlap headroom).
double ffsim_critical_path(int32_t n_tasks, const double* duration,
                           int64_t n_edges, const int32_t* esrc,
                           const int32_t* edst) {
  if (n_tasks <= 0) return 0.0;
  std::vector<std::vector<int32_t>> succ(n_tasks);
  std::vector<int32_t> indeg(n_tasks, 0);
  for (int64_t j = 0; j < n_edges; ++j) {
    int32_t s = esrc[j], d = edst[j];
    if (s < 0 || s >= n_tasks || d < 0 || d >= n_tasks) return -1.0;
    succ[s].push_back(d);
    indeg[d]++;
  }
  std::vector<int32_t> order;
  order.reserve(n_tasks);
  for (int32_t i = 0; i < n_tasks; ++i)
    if (indeg[i] == 0) order.push_back(i);
  std::vector<double> fin(n_tasks, 0.0);
  double best = 0.0;
  for (size_t h = 0; h < order.size(); ++h) {
    int32_t t = order[h];
    double ft = fin[t] + duration[t];
    best = std::max(best, ft);
    for (int32_t s : succ[t]) {
      fin[s] = std::max(fin[s], ft);
      if (--indeg[s] == 0) order.push_back(s);
    }
  }
  return order.size() == static_cast<size_t>(n_tasks) ? best : -1.0;
}

// ---------------------------------------------------------------------------
// 2. Parallel batch gather (dataloader hot path)
// ---------------------------------------------------------------------------
// dst[b] = src[indices[b]] for b in [0, batch); rows are sample_bytes wide.
void ffdl_gather(const uint8_t* src, uint8_t* dst, const int64_t* indices,
                 int64_t batch, int64_t sample_bytes, int32_t n_threads) {
  if (batch <= 0) return;
  if (n_threads <= 1 || batch < 64) {
    for (int64_t b = 0; b < batch; ++b)
      std::memcpy(dst + b * sample_bytes, src + indices[b] * sample_bytes,
                  sample_bytes);
    return;
  }
  n_threads = std::min<int64_t>(n_threads, batch);
  std::vector<std::thread> pool;
  pool.reserve(n_threads);
  int64_t chunk = (batch + n_threads - 1) / n_threads;
  for (int32_t w = 0; w < n_threads; ++w) {
    int64_t lo = w * chunk, hi = std::min(batch, lo + chunk);
    if (lo >= hi) break;
    pool.emplace_back([=]() {
      for (int64_t b = lo; b < hi; ++b)
        std::memcpy(dst + b * sample_bytes, src + indices[b] * sample_bytes,
                    sample_bytes);
    });
  }
  for (auto& t : pool) t.join();
}

// ---------------------------------------------------------------------------
// 3. Reachability bitset (substitution-engine cycle checks)
// ---------------------------------------------------------------------------
// Computes ancestor sets over an n-node DAG into a packed bitset:
// out[i * words + (j >> 6)] bit (j & 63) set iff i is reachable FROM j
// (j is an ancestor of i). words = ceil(n / 64).
// Returns 0 on success, -1 on cycle or out-of-range edge ids.
int32_t ffgraph_closure(int32_t n, int64_t n_edges, const int32_t* esrc,
                        const int32_t* edst, uint64_t* out) {
  int64_t words = (n + 63) / 64;
  std::memset(out, 0, sizeof(uint64_t) * words * n);
  std::vector<std::vector<int32_t>> pred(n);
  std::vector<int32_t> indeg(n, 0);
  std::vector<std::vector<int32_t>> succ(n);
  for (int64_t j = 0; j < n_edges; ++j) {
    int32_t s = esrc[j], d = edst[j];
    if (s < 0 || s >= n || d < 0 || d >= n) return -1;
    succ[s].push_back(d);
    pred[d].push_back(s);
    indeg[d]++;
  }
  std::vector<int32_t> order;
  order.reserve(n);
  for (int32_t i = 0; i < n; ++i)
    if (indeg[i] == 0) order.push_back(i);
  for (size_t h = 0; h < order.size(); ++h) {
    int32_t t = order[h];
    uint64_t* row = out + static_cast<int64_t>(t) * words;
    for (int32_t p : pred[t]) {
      const uint64_t* prow = out + static_cast<int64_t>(p) * words;
      for (int64_t w = 0; w < words; ++w) row[w] |= prow[w];
      row[p >> 6] |= (1ull << (p & 63));
    }
    for (int32_t s : succ[t])
      if (--indeg[s] == 0) order.push_back(s);
  }
  return order.size() == static_cast<size_t>(n) ? 0 : -1;
}

// ---------------------------------------------------------------------------
// 4. Task-graph builder (search hot loop)
// ---------------------------------------------------------------------------
// The auto-parallelization search expands each candidate PCG into a task DAG
// (search/tasksim.py). The expansion of one logical collective into physical
// ring rounds x segments x route hops is the hot loop: a BERT-large budget-8
// search makes ~8.6k collective expansions totalling ~20M dependency edges,
// which cost ~60 s in Python (round-4 profile). The builder keeps the
// proc/duration/edge arrays in C++ and exposes batched task/dep insertion
// plus the full ring expansion, so Python makes one call per logical
// collective — the same division of labor as the reference, whose whole
// simulator lives in C++ (src/runtime/simulator.cc:822-1200).

struct FFBuilder {
  std::vector<int32_t> proc;
  std::vector<double> dur;
  std::vector<int32_t> esrc, edst;
};

FFBuilder* ffb_new() { return new FFBuilder(); }
void ffb_free(FFBuilder* b) { delete b; }
int64_t ffb_n_tasks(FFBuilder* b) { return static_cast<int64_t>(b->proc.size()); }
int64_t ffb_n_edges(FFBuilder* b) { return static_cast<int64_t>(b->esrc.size()); }

// Append n tasks; returns the id of the first (ids are consecutive).
int32_t ffb_add_tasks(FFBuilder* b, int32_t n, const int32_t* procs,
                      const double* durs) {
  int32_t first = static_cast<int32_t>(b->proc.size());
  b->proc.insert(b->proc.end(), procs, procs + n);
  b->dur.insert(b->dur.end(), durs, durs + n);
  return first;
}

// All-pairs dependencies a[i] -> t for every t in b[]; used for the
// per-shard compute tasks (preds x shards).
void ffb_cross_deps(FFBuilder* b, int32_t na, const int32_t* a, int32_t nb,
                    const int32_t* bs) {
  for (int32_t i = 0; i < na; ++i)
    for (int32_t j = 0; j < nb; ++j) {
      b->esrc.push_back(a[i]);
      b->edst.push_back(bs[j]);
    }
}

// Ring-collective expansion (TaskGraphBuilder.collective_tasks semantics):
// `rounds` rounds over `n_routes` participants; participant i's route to its
// ring successor is the hop list route_procs[route_off[i] : route_off[i+1]]
// (processor ids, already offset past the compute cores), with per-hop
// duration multipliers route_fac (or null = 1.0). Each round costs
// per_round_secs split over n_seg store-and-forward segments that pipeline
// across the route. Round r of participant i depends on round r-1 of i and
// of its ring predecessor (the chunk being forwarded); round 0 depends on
// deps[]. Writes <= n_routes final task ids to out_ids; returns the count.
int32_t ffb_collective(FFBuilder* b, int32_t n_routes,
                       const int32_t* route_off, const int32_t* route_procs,
                       const double* route_fac, int32_t rounds,
                       double per_round_secs, int32_t n_seg,
                       int32_t n_deps, const int32_t* deps,
                       int32_t* out_ids) {
  if (n_routes <= 0 || rounds <= 0) return 0;
  if (n_seg < 1) n_seg = 1;
  std::vector<int32_t> prev_last(n_routes, -1);
  std::vector<int32_t> cur(n_routes, -1);
  for (int32_t r = 0; r < rounds; ++r) {
    for (int32_t i = 0; i < n_routes; ++i) {
      int32_t h0 = route_off[i], h1 = route_off[i + 1];
      if (h0 >= h1) {  // empty route: carry the previous round's task
        cur[i] = prev_last[i];
        continue;
      }
      int32_t last = -1;
      for (int32_t s = 0; s < n_seg; ++s) {
        int32_t prev = -1;
        for (int32_t h = h0; h < h1; ++h) {
          double d = (per_round_secs / n_seg) *
                     (route_fac ? route_fac[h] : 1.0);
          int32_t t = static_cast<int32_t>(b->proc.size());
          b->proc.push_back(route_procs[h]);
          b->dur.push_back(d);
          if (prev < 0) {
            if (r == 0) {
              for (int32_t k = 0; k < n_deps; ++k) {
                b->esrc.push_back(deps[k]);
                b->edst.push_back(t);
              }
            } else {
              int32_t pp = prev_last[(i - 1 + n_routes) % n_routes];
              if (pp >= 0) { b->esrc.push_back(pp); b->edst.push_back(t); }
              if (prev_last[i] >= 0) {
                b->esrc.push_back(prev_last[i]);
                b->edst.push_back(t);
              }
            }
          } else {
            b->esrc.push_back(prev);
            b->edst.push_back(t);
          }
          prev = t;
        }
        if (prev >= 0) last = prev;
      }
      cur[i] = (last >= 0) ? last : prev_last[i];
    }
    std::swap(prev_last, cur);
  }
  int32_t n_out = 0;
  for (int32_t i = 0; i < n_routes; ++i)
    if (prev_last[i] >= 0) out_ids[n_out++] = prev_last[i];
  return n_out;
}

// Copy out the accumulated arrays (sizes from ffb_n_tasks/ffb_n_edges);
// any pointer may be null to skip that array. For tests/introspection.
void ffb_get(FFBuilder* b, int32_t* proc, double* dur, int32_t* esrc,
             int32_t* edst) {
  if (proc) std::memcpy(proc, b->proc.data(), b->proc.size() * sizeof(int32_t));
  if (dur) std::memcpy(dur, b->dur.data(), b->dur.size() * sizeof(double));
  if (esrc) std::memcpy(esrc, b->esrc.data(), b->esrc.size() * sizeof(int32_t));
  if (edst) std::memcpy(edst, b->edst.data(), b->edst.size() * sizeof(int32_t));
}

// Play the accumulated DAG through the event simulator.
double ffb_simulate(FFBuilder* b, int32_t n_procs) {
  return ffsim_simulate(static_cast<int32_t>(b->proc.size()), b->proc.data(),
                        b->dur.data(),
                        static_cast<int64_t>(b->esrc.size()), b->esrc.data(),
                        b->edst.data(), n_procs, nullptr);
}

}  // extern "C"
