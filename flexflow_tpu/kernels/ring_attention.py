"""Sequence/context-parallel attention: ring attention and Ulysses.

The reference has NO sequence parallelism (SURVEY.md §5: "no ring
attention, no blockwise, no Ulysses") — this module is the beyond-reference
capability the rebuild makes first-class. Two schemes:

- :func:`ring_attention` — blockwise attention with K/V chunks rotating
  around the mesh axis via ``lax.ppermute`` (ICI neighbor exchange), log-
  sum-exp merging of per-chunk partial results, and a custom VJP that runs
  a second ring pass rotating (k, v, dk, dv) together so every device
  accumulates gradient contributions for every chunk. Peak memory per
  device stays O(seq/N · seq/N) and communication rides the ICI ring.
- :func:`ulysses_attention` — all-to-all the (seq-sharded) q/k/v into
  head-sharded layout, run local flash attention over the full sequence,
  all-to-all back. One all-to-all pair instead of N ring steps; requires
  heads % axis_size == 0.

Both are written to be used inside ``shard_map`` over a mesh axis that
shards the sequence dimension; per-chunk compute uses the Pallas flash
kernel (:mod:`flash_attention`) when block structure allows.
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp

from .flash_attention import NEG_INF, flash_attention


def _chunk_attn(q, k, v, sm_scale, mode):
    """Partial attention of local q against one k/v chunk.

    mode: 0 = full (all keys visible), 1 = causal diagonal, 2 = skip.
    Returns (o_unnormalized? no — normalized o, lse) in f32.
    q: (b, h, sq, d); k/v: (b, h, sc, d).
    """
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * sm_scale
    sq, sc = s.shape[-2], s.shape[-1]
    if mode == 1:
        i = jax.lax.broadcasted_iota(jnp.int32, (sq, sc), 0)
        j = jax.lax.broadcasted_iota(jnp.int32, (sq, sc), 1)
        s = jnp.where(j <= i, s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    m = jnp.maximum(m, NEG_INF)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    l_safe = jnp.where(l == 0.0, 1.0, l)
    lse = m[..., 0] + jnp.log(l_safe[..., 0])       # (b, h, sq)
    return o / l_safe, lse


def _merge(o_acc, lse_acc, o_new, lse_new):
    """Log-sum-exp merge of two normalized partial attention results."""
    lse_max = jnp.maximum(lse_acc, lse_new)
    a = jnp.exp(lse_acc - lse_max)
    b = jnp.exp(lse_new - lse_max)
    denom = a + b
    lse_out = lse_max + jnp.log(denom)
    w_a = (a / denom)[..., None]
    w_b = (b / denom)[..., None]
    return o_acc * w_a + o_new * w_b, lse_out


def _ring_fwd_pass(q, k, v, axis_name, causal, sm_scale):
    """One full ring rotation computing (o, lse); everything f32 inside."""
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, h, sq, d = q.shape
    o = jnp.zeros((b, h, sq, d), jnp.float32)
    lse = jnp.full((b, h, sq), NEG_INF, jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]

    kc, vc = k, v
    for r in range(n):
        src = (idx - r) % n               # whose chunk we hold this step
        if causal:
            # src < idx: fully visible; src == idx: diagonal; src > idx: skip
            def full_case(args):
                qq, kk, vv = args
                return _chunk_attn(qq, kk, vv, sm_scale, 0)

            def diag_case(args):
                qq, kk, vv = args
                return _chunk_attn(qq, kk, vv, sm_scale, 1)

            def skip_case(args):
                # zeros derived from the inputs so the branch output's
                # device-varying type matches the compute branches
                qq, _, _ = args
                z = (qq * 0).astype(jnp.float32)
                return z, jnp.sum(z, axis=-1) + NEG_INF

            branch = jnp.where(src < idx, 0, jnp.where(src == idx, 1, 2))
            o_c, lse_c = jax.lax.switch(
                branch, [full_case, diag_case, skip_case], (q, kc, vc))
        else:
            o_c, lse_c = _chunk_attn(q, kc, vc, sm_scale, 0)
        o, lse = _merge(o, lse, o_c, lse_c)
        if r != n - 1:
            kc = jax.lax.ppermute(kc, axis_name, perm)
            vc = jax.lax.ppermute(vc, axis_name, perm)
    return o, lse


def _chunk_grads(q, k, v, do, lse, delta, sm_scale, mode):
    """Per-chunk flash-style backward math (recompute p from lse).

    Returns (dq, dk, dv) in f32. mode as in _chunk_attn."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * sm_scale
    sq, sc = s.shape[-2], s.shape[-1]
    if mode == 1:
        i = jax.lax.broadcasted_iota(jnp.int32, (sq, sc), 0)
        j = jax.lax.broadcasted_iota(jnp.int32, (sq, sc), 1)
        s = jnp.where(j <= i, s, NEG_INF)
    p = jnp.exp(s - lse[..., None])                   # (b,h,sq,sc)
    dv = jnp.einsum("bhqk,bhqd->bhkd", p, do)
    dp = jnp.einsum("bhqd,bhkd->bhqk", do, v.astype(jnp.float32))
    ds = p * (dp - delta[..., None]) * sm_scale
    dq = jnp.einsum("bhqk,bhkd->bhqd", ds, k.astype(jnp.float32))
    dk = jnp.einsum("bhqk,bhqd->bhkd", ds, q.astype(jnp.float32))
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _ring_core(q, k, v, axis_name, causal, sm_scale):
    o, _ = _ring_fwd_pass(q, k, v, axis_name, causal, sm_scale)
    return o.astype(q.dtype)


def _ring_core_fwd(q, k, v, axis_name, causal, sm_scale):
    o, lse = _ring_fwd_pass(q, k, v, axis_name, causal, sm_scale)
    return o.astype(q.dtype), (q, k, v, o, lse)


def _ring_core_bwd(axis_name, causal, sm_scale, res, do):
    q, k, v, o, lse = res
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    do32 = do.astype(jnp.float32)
    delta = jnp.sum(do32 * o, axis=-1)                # (b, h, sq)
    dq = jnp.zeros(q.shape, jnp.float32)
    dk = jnp.zeros(k.shape, jnp.float32)
    dv = jnp.zeros(v.shape, jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]

    kc, vc, dkc, dvc = k, v, dk, dv
    for r in range(n):
        src = (idx - r) % n
        if causal:
            def full_case(args):
                return _chunk_grads(*args, sm_scale, 0)

            def diag_case(args):
                return _chunk_grads(*args, sm_scale, 1)

            def skip_case(args):
                qq, kk, vv, *_ = args
                return ((qq * 0).astype(jnp.float32),
                        (kk * 0).astype(jnp.float32),
                        (vv * 0).astype(jnp.float32))

            branch = jnp.where(src < idx, 0, jnp.where(src == idx, 1, 2))
            dq_c, dk_c, dv_c = jax.lax.switch(
                branch, [full_case, diag_case, skip_case],
                (q, kc, vc, do32, lse, delta))
        else:
            dq_c, dk_c, dv_c = _chunk_grads(q, kc, vc, do32, lse, delta,
                                            sm_scale, 0)
        dq = dq + dq_c
        dkc = dkc + dk_c
        dvc = dvc + dv_c
        # rotate k/v AND their gradient accumulators together; after n
        # rotations every accumulator is back on its home device having
        # collected every device's contribution
        kc = jax.lax.ppermute(kc, axis_name, perm)
        vc = jax.lax.ppermute(vc, axis_name, perm)
        dkc = jax.lax.ppermute(dkc, axis_name, perm)
        dvc = jax.lax.ppermute(dvc, axis_name, perm)
    return dq.astype(q.dtype), dkc.astype(k.dtype), dvc.astype(v.dtype)


_ring_core.defvjp(_ring_core_fwd, _ring_core_bwd)


def ring_attention(q, k, v, axis_name: str, *, causal: bool = False,
                   sm_scale: Optional[float] = None):
    """Ring attention over a sequence-sharded mesh axis.

    Call inside ``shard_map``: q/k/v are the LOCAL sequence chunks
    (b, h, seq/N, d) and ``axis_name`` the mesh axis sharding the sequence.
    Differentiable; causal masking respects global positions (chunks are
    contiguous slices in axis order)."""
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    return _ring_core(q, k, v, axis_name, causal, sm_scale)


def ulysses_attention(q, k, v, axis_name: str, *, causal: bool = False,
                      sm_scale: Optional[float] = None,
                      interpret: Optional[bool] = None):
    """DeepSpeed-Ulysses-style sequence parallelism.

    Inside ``shard_map`` with q/k/v sequence-sharded (b, h, seq/N, d):
    all-to-all seq-shards ↔ head-shards, local flash attention over the
    full sequence with heads/N local heads, then all-to-all back.
    Requires h % axis_size == 0."""
    n = jax.lax.psum(1, axis_name)
    # (b, h, s/N, d) -> (b, h/N, s, d)
    qh = jax.lax.all_to_all(q, axis_name, split_axis=1, concat_axis=2,
                            tiled=True)
    kh = jax.lax.all_to_all(k, axis_name, split_axis=1, concat_axis=2,
                            tiled=True)
    vh = jax.lax.all_to_all(v, axis_name, split_axis=1, concat_axis=2,
                            tiled=True)
    o = flash_attention(qh, kh, vh, causal=causal, sm_scale=sm_scale,
                        interpret=interpret)
    # (b, h/N, s, d) -> (b, h, s/N, d)
    return jax.lax.all_to_all(o, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)
