"""Pallas TPU kernels — the hand-written hot-op layer.

The reference backs its hot ops with cuDNN/cuBLAS kernels (e.g. attention
via ``cudnnMultiHeadAttnForward``, ``src/ops/attention.cu:35``). Here XLA
covers most of that ground; this package holds the kernels XLA needs help
with:

  - ``flash_attention``: fused, tiled, online-softmax attention (fwd+bwd)
    that never materializes the (seq, seq) score matrix in HBM.
  - ``ring_attention``: sequence/context-parallel attention over a sharded
    sequence axis (a capability the reference LACKS — SURVEY.md §5
    "Long-context / sequence parallelism: not present").
  - ``ulysses_attention``: all-to-all (DeepSpeed-Ulysses style) sequence
    parallelism: swap seq-sharding for head-sharding around local flash
    attention.

All kernels run compiled on TPU and in Pallas interpret mode on CPU, so the
test suite exercises them without hardware.
"""
from .flash_attention import (dropout_keep_mask, flash_attention,
                              mha_reference)
from .ring_attention import ring_attention, ulysses_attention

__all__ = [
    "dropout_keep_mask",
    "flash_attention",
    "mha_reference",
    "ring_attention",
    "ulysses_attention",
]
