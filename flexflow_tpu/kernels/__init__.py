"""Pallas TPU kernels — the hand-written hot-op layer.

The reference backs its hot ops with cuDNN/cuBLAS kernels (e.g. attention
via ``cudnnMultiHeadAttnForward``, ``src/ops/attention.cu:35``). Here XLA
covers most of that ground; this package holds the kernels XLA needs help
with:

  - ``flash_attention``: fused, tiled, online-softmax attention (fwd+bwd)
    that never materializes the (seq, seq) score matrix in HBM.
  - ``ring_attention``: sequence/context-parallel attention over a sharded
    sequence axis (a capability the reference LACKS — SURVEY.md §5
    "Long-context / sequence parallelism: not present").
  - ``ulysses_attention``: all-to-all (DeepSpeed-Ulysses style) sequence
    parallelism: swap seq-sharding for head-sharding around local flash
    attention.
  - ``opt_update``: fused one-HBM-pass Adam update for the ZeRO-sharded
    optimizer path (the ``opt_update:fused`` kernel tier).

``registry`` makes the implementation choice a searched dimension: per-op
variants with availability predicates and calibrated cost entry points
(docs/kernels.md).

All kernels run compiled on TPU and in Pallas interpret mode on CPU, so the
test suite exercises them without hardware.
"""
from .flash_attention import (dropout_keep_mask, flash_attention,
                              mha_reference)
from .opt_update import fused_adam_update
from .registry import (DEFAULT_IMPLS, KernelImpl, REGISTRY, attention_ctx,
                       available_impls, get_impl, parse_forced,
                       resolve_forced)
from .ring_attention import ring_attention, ulysses_attention

__all__ = [
    "DEFAULT_IMPLS",
    "KernelImpl",
    "REGISTRY",
    "attention_ctx",
    "available_impls",
    "dropout_keep_mask",
    "flash_attention",
    "fused_adam_update",
    "get_impl",
    "mha_reference",
    "parse_forced",
    "resolve_forced",
    "ring_attention",
    "ulysses_attention",
]
