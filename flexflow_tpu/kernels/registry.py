"""Searchable kernel tier — per-op implementation variants.

The reference owns every per-op execution decision inside its search and
prices each candidate with ``measure_operator_cost`` microbenchmarks
(simulator.cc). Here the same idea lands as a small registry: each op kind
that has more than one implementation (attention, the optimizer update for
the ZeRO-sharded path) declares its variants, an availability predicate
(backend, shape divisibility, mesh-axis requirements) and a cost entry
point. The search treats the implementation as a per-op assignment
dimension (``FFModel._plan_kernels``), the adopted choice serializes with
the strategy (``kernel_impls`` block) and the plan verifier re-checks every
predicate on the adopted mesh/shapes (``plan_verifier._check_kernel``).

Forcing: ``FFConfig.kernel_impls`` / ``--kernel-impl`` / the
``FF_KERNEL_IMPL`` env var take ``<op>:<impl>`` pairs (comma-separated),
e.g. ``attention:flash`` or ``attention:ring,opt_update:fused``. The
retired ``use_flash_attention`` tri-state keeps working through
:func:`resolve_forced`'s deprecation shim.

See docs/kernels.md.
"""
from __future__ import annotations

import dataclasses
import os
import warnings
from typing import Any, Callable, Dict, List, Optional

# op kinds with a searchable implementation dimension
ATTENTION = "attention"
OPT_UPDATE = "opt_update"

# the impl the pre-kernel-tier code paths execute when no plan exists;
# also the forced baseline the strategy audit compares the searched
# choice against ("searched-vs-forced-XLA")
DEFAULT_IMPLS: Dict[str, str] = {ATTENTION: "xla", OPT_UPDATE: "unfused"}


def _attn_xla(ctx: Dict[str, Any]) -> Optional[str]:
    return None  # the reference path is always legal


def _attn_flash(ctx: Dict[str, Any]) -> Optional[str]:
    """Pallas flash kernel: tiled online-softmax attention.

    Structural legality only — the kernel runs compiled on TPU and in
    interpret mode on CPU (slow, priced accordingly), so the backend is
    a cost question, not an availability one.
    """
    if ctx.get("sliding_window", 0):
        return "flash kernel has no sliding-window mask support"
    if ctx.get("causal", False) and \
            ctx.get("q_len", 0) != ctx.get("kv_len", 0):
        return "flash kernel does not mask causal cross-attention " \
               "(q_len != kv_len)"
    return None


def _attn_ring(ctx: Dict[str, Any]) -> Optional[str]:
    """Ring attention over the mesh's sequence axis (``seq``)."""
    deg = int(ctx.get("seq_degree", 0) or 0)
    if deg < 2:
        return "ring attention requires a mesh sequence axis " \
               "(seq degree >= 2); this mesh has none"
    q_len = int(ctx.get("q_len", 0) or 0)
    kv_len = int(ctx.get("kv_len", 0) or 0)
    if q_len != kv_len:
        return "ring attention requires self-attention (q_len == kv_len)"
    if q_len % deg != 0:
        return f"sequence length {q_len} is not divisible by the " \
               f"seq-axis degree {deg}"
    if ctx.get("sliding_window", 0):
        return "ring attention has no sliding-window mask support"
    if ctx.get("dropout", 0.0):
        return "ring attention has no in-kernel dropout"
    if ctx.get("kv_mode"):
        return "ring attention does not run under the KV-cache " \
               "prefill/decode paths"
    return None


def _opt_unfused(ctx: Dict[str, Any]) -> Optional[str]:
    return None  # the tree-mapped jnp update is always legal


def _opt_fused(ctx: Dict[str, Any]) -> Optional[str]:
    """Fused Pallas optimizer update: one HBM pass over (w, g, m, v)."""
    if ctx.get("backend") != "tpu":
        return "fused optimizer update compiles on TPU only " \
               "(interpret mode is test-only)"
    if ctx.get("optimizer", "adam") != "adam":
        return "fused update kernel covers Adam only"
    return None


@dataclasses.dataclass(frozen=True)
class KernelImpl:
    """One implementation variant of a multi-impl op kind."""
    op: str                                     # ATTENTION | OPT_UPDATE
    name: str                                   # e.g. "flash"
    predicate: Callable[[Dict[str, Any]], Optional[str]]
    # calibration kind whose measured rows price this impl
    # (``op_attention@flash`` rows in the v2 table); the analytic curve
    # is the fallback when no row was measured
    calib_kind: str = ""

    def available(self, ctx: Dict[str, Any]) -> Optional[str]:
        """None when legal on ``ctx``, else a human-readable reason."""
        return self.predicate(ctx)

    def cost(self, cost_model, layer, shard_degrees,
             weight_shard_degree, **ctx) -> float:
        """Predicted seconds for this (op, impl) pair — measured
        calibration rows first, analytic fallback (OpCostModel owns the
        numbers; this is the registry's cost entry point)."""
        return cost_model.kernel_impl_cost(
            layer, self.op, self.name, shard_degrees,
            weight_shard_degree, **ctx)


REGISTRY: Dict[str, Dict[str, KernelImpl]] = {
    ATTENTION: {
        "xla": KernelImpl(ATTENTION, "xla", _attn_xla,
                          "op_attention@xla"),
        "flash": KernelImpl(ATTENTION, "flash", _attn_flash,
                            "op_attention@flash"),
        "ring": KernelImpl(ATTENTION, "ring", _attn_ring,
                           "op_attention@ring"),
    },
    OPT_UPDATE: {
        "unfused": KernelImpl(OPT_UPDATE, "unfused", _opt_unfused,
                              "op_opt_update@unfused"),
        "fused": KernelImpl(OPT_UPDATE, "fused", _opt_fused,
                            "op_opt_update@fused"),
    },
}


def impl_names(op: str) -> List[str]:
    return list(REGISTRY[op])


def get_impl(op: str, name: str) -> KernelImpl:
    try:
        return REGISTRY[op][name]
    except KeyError:
        known = {k: sorted(v) for k, v in REGISTRY.items()}
        raise KeyError(
            f"unknown kernel impl {op}:{name} (known: {known})") from None


def available_impls(op: str, ctx: Dict[str, Any]) -> List[str]:
    """Impl names whose predicate holds on ``ctx`` (default first)."""
    out = [n for n, im in REGISTRY[op].items() if im.available(ctx) is None]
    d = DEFAULT_IMPLS[op]
    if d in out:
        out.remove(d)
        out.insert(0, d)
    return out


def attention_ctx(params: Dict[str, Any], q_len: int, kv_len: int,
                  *, backend: str = "", seq_degree: int = 0,
                  dropout: float = None, kv_mode: Optional[str] = None
                  ) -> Dict[str, Any]:
    """Predicate context for an attention layer's params + shapes."""
    h = int(params.get("num_heads", 1) or 1)
    e = int(params.get("embed_dim", 0) or 0)
    kdim = int(params.get("kdim", 0) or e)
    return {
        "backend": backend,
        "q_len": int(q_len),
        "kv_len": int(kv_len),
        "head_dim": kdim // max(h, 1),
        "num_heads": h,
        "num_kv_heads": int(params.get("num_kv_heads", 0) or h),
        "causal": bool(params.get("causal", False)),
        "sliding_window": int(params.get("sliding_window", 0) or 0),
        "dropout": float(params.get("dropout", 0.0) or 0.0)
        if dropout is None else float(dropout),
        "seq_degree": int(seq_degree),
        "kv_mode": kv_mode,
    }


# ----------------------------------------------------------------------
# forcing: config flag / env var / use_flash_attention deprecation shim
# ----------------------------------------------------------------------
def parse_forced(spec: str) -> Dict[str, str]:
    """Parse ``"attention:ring,opt_update:fused"`` into an op->impl map.

    Unknown ops/impls raise ValueError — a typo'd force must fail loudly,
    never silently fall back to the default impl.
    """
    out: Dict[str, str] = {}
    for part in str(spec or "").split(","):
        part = part.strip()
        if not part or part == "auto":
            continue
        if ":" not in part:
            raise ValueError(
                f"--kernel-impl takes <op>:<impl> pairs, got {part!r}")
        op, impl = (p.strip() for p in part.split(":", 1))
        if op not in REGISTRY:
            raise ValueError(
                f"unknown kernel op {op!r} (known: {sorted(REGISTRY)})")
        if impl not in REGISTRY[op]:
            raise ValueError(
                f"unknown impl {impl!r} for op {op!r} "
                f"(known: {sorted(REGISTRY[op])})")
        out[op] = impl
    return out


def resolve_forced(cfg) -> Dict[str, str]:
    """Forced op->impl choices from config/env, deprecation shim included.

    Precedence (later wins): ``use_flash_attention`` shim <
    ``cfg.kernel_impls`` < ``FF_KERNEL_IMPL``. The shim maps the retired
    tri-state's "true"/"false" to a forced attention impl and warns;
    "auto" forces nothing (the searched dimension subsumes it).
    """
    forced: Dict[str, str] = {}
    legacy = getattr(cfg, "use_flash_attention", "auto") \
        if cfg is not None else "auto"
    if legacy in ("true", "false"):
        warnings.warn(
            "FFConfig.use_flash_attention is deprecated; use "
            "kernel_impls / --kernel-impl attention:<xla|flash|ring> "
            "(FF_KERNEL_IMPL works too)", DeprecationWarning,
            stacklevel=2)
        forced[ATTENTION] = "flash" if legacy == "true" else "xla"
    forced.update(parse_forced(getattr(cfg, "kernel_impls", "auto")
                               if cfg is not None else "auto"))
    forced.update(parse_forced(os.environ.get("FF_KERNEL_IMPL", "")))
    return forced
