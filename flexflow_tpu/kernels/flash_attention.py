"""Flash attention as a Pallas TPU kernel (forward + backward).

Replaces the reference's cuDNN multi-head attention kernels
(``src/ops/attention.cu:35,105,128``) with a TPU-native tiled kernel:
online-softmax accumulation in VMEM scratch so the (seq_q, seq_k) score
matrix never hits HBM, bf16/f32 matmuls on the MXU with f32 accumulation,
and a custom VJP whose dq and dk/dv passes are separate Pallas kernels
(the standard split so each pass has a sequential accumulation grid).

Attention-probability dropout (the reference's cuDNN attnDropout) runs
in-kernel and counter-based: keep[i, j] is a pure hash of (seed, bh,
absolute q/k positions), so the differently-blocked backward kernels
regenerate the identical keep mask without storing it, and the same
hash lowers in interpret mode for CPU CI.

Layout: (batch, heads, seq, head_dim), batch*heads collapsed into one grid
axis. Sequence/head dims are padded to block/lane multiples; the padded-key
mask is baked in statically (shapes are static under jit). TPU grids
execute sequentially over the last grid axis, which is what makes the VMEM
scratch accumulators correct; interpret mode preserves that, so the same
kernel is unit-testable on CPU.
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def _pad_to(x, mult, axis):
    rem = x.shape[axis] % mult
    if rem == 0:
        return x
    pad = [(0, 0)] * x.ndim
    pad[axis] = (0, mult - rem)
    return jnp.pad(x, pad)


def _key_mask(iq, ik, block_q, block_k, kv_len, causal):
    """Validity mask for one (q block, k block) tile; kv_len is static."""
    k_pos = ik * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    mask = k_pos < kv_len
    if causal:
        q_pos = iq * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        mask = jnp.logical_and(mask, k_pos <= q_pos)
    return mask


def _tile_keep_mask(seed_ref, b, iq, ik, block_q, block_k, rate):
    """Counter-based dropout keep-mask (rate is static).

    keep[i, j] is a pure hash of (seed, batch-head, ABSOLUTE query
    position, ABSOLUTE key position) — independent of the tiling — so
    the forward (512x512 blocks) and backward (128x128 blocks) kernels
    regenerate bit-identical masks. Found compiling on a real v5e: a
    pltpu-PRNG mask seeded per (b, iq, ik) tile cannot be reproduced by
    a differently-blocked backward pass, which silently corrupted dq
    (and Mosaic's prng_set_seed_32 takes at most two seed words anyway).
    A position hash also lowers in interpret mode, so CPU CI now covers
    the dropout path. Mix: odd-constant multiplies folded by xor, then
    the murmur3 fmix32 finalizer in uint32."""
    q_pos = iq * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    k_pos = ik * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    return _position_keep(seed_ref[0, 0], jnp.asarray(b, jnp.int32),
                          q_pos, k_pos, rate)


def _position_keep(seed, bh, q_pos, k_pos, rate):
    """keep = hash(seed, bh, q_pos, k_pos) >= rate-threshold, in ops that
    lower identically inside Pallas and in plain XLA — the single source
    of truth for the dropout mask shared by the kernels (via
    :func:`_tile_keep_mask`) and the explicit-mask golden (via
    :func:`dropout_keep_mask`)."""
    h = (seed * jnp.int32(-1640531527)                 # 0x9E3779B1
         ^ bh * jnp.int32(840146601)                   # 0x3243F6A9
         ^ q_pos * jnp.int32(-2048144789)              # 0x85EBCA6B
         ^ k_pos * jnp.int32(-1028477387))             # 0xC2B2AE35
    u = jax.lax.bitcast_convert_type(h, jnp.uint32)
    u = u ^ (u >> jnp.uint32(16))
    u = u * jnp.uint32(0x85EBCA6B)
    u = u ^ (u >> jnp.uint32(13))
    u = u * jnp.uint32(0xC2B2AE35)
    u = u ^ (u >> jnp.uint32(16))
    thresh = min(int(rate * 4294967296.0), 4294967295)
    return u >= jnp.uint32(thresh)


# ---------------------------------------------------------------------------
# forward kernel: grid (bh, nq, nk), accumulate over the nk axis in scratch
# ---------------------------------------------------------------------------
def _fwd_kernel(seed_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                acc_sc, m_sc, l_sc, *, sm_scale, causal,
                kv_len, block_q, block_k, dropout_rate):
    iq = pl.program_id(1)
    ik = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ik == 0)
    def _init():
        m_sc[:] = jnp.full_like(m_sc, NEG_INF)
        l_sc[:] = jnp.zeros_like(l_sc)
        acc_sc[:] = jnp.zeros_like(acc_sc)

    # causal: the kv block is live iff its first key is visible to the last
    # query of this q block
    live = (ik * block_k <= (iq + 1) * block_q - 1) if causal else True

    @pl.when(live)
    def _compute():
        q = q_ref[0]                      # (block_q, d)
        k = k_ref[0]                      # (block_k, d)
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        s = jnp.where(_key_mask(iq, ik, block_q, block_k, kv_len, causal),
                      s, NEG_INF)
        m_prev = m_sc[:, :1]                            # (block_q, 1)
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)                          # (block_q, block_k)
        alpha = jnp.exp(m_prev - m_new)
        # softmax denominator uses UNdropped p; dropout only scales the
        # numerator (matches dropout-on-probs semantics)
        l_new = l_sc[:, :1] * alpha + jnp.sum(p, axis=1, keepdims=True)
        if dropout_rate > 0.0:
            keep = _tile_keep_mask(seed_ref, pl.program_id(0), iq, ik,
                                   block_q, block_k, dropout_rate)
            p_eff = jnp.where(keep, p / (1.0 - dropout_rate), 0.0)
        else:
            p_eff = p
        pv = jax.lax.dot_general(
            p_eff.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        acc_sc[:] = acc_sc[:] * alpha + pv
        m_sc[:] = jnp.broadcast_to(m_new, m_sc.shape)
        l_sc[:] = jnp.broadcast_to(l_new, l_sc.shape)

    @pl.when(ik == nk - 1)
    def _finish():
        l = l_sc[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_sc[:] / l_safe).astype(o_ref.dtype)
        lse = m_sc[:, :1] + jnp.log(l_safe)
        lse_ref[0] = jnp.broadcast_to(lse, lse_ref.shape[1:])


# ---------------------------------------------------------------------------
# backward kernels
# ---------------------------------------------------------------------------
def _bwd_dq_kernel(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, dq_sc, *, sm_scale, causal, kv_len, block_q,
                   block_k, dropout_rate):
    iq = pl.program_id(1)
    ik = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ik == 0)
    def _init():
        dq_sc[:] = jnp.zeros_like(dq_sc)

    live = (ik * block_k <= (iq + 1) * block_q - 1) if causal else True

    @pl.when(live)
    def _compute():
        q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
        lse = lse_ref[0][:, :1]              # (block_q, 1)
        delta = delta_ref[0][:, :1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        s = jnp.where(_key_mask(iq, ik, block_q, block_k, kv_len, causal),
                      s, NEG_INF)
        p = jnp.exp(s - lse)                 # (block_q, block_k)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if dropout_rate > 0.0:
            keep = _tile_keep_mask(seed_ref, pl.program_id(0), iq, ik,
                                   block_q, block_k, dropout_rate)
            dp = jnp.where(keep, dp / (1.0 - dropout_rate), 0.0)
        ds = p * (dp - delta) * sm_scale
        dq_sc[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ik == nk - 1)
    def _finish():
        dq_ref[0] = dq_sc[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                    delta_ref, dk_ref, dv_ref, dk_sc, dv_sc, *, sm_scale,
                    causal, kv_len, block_q, block_k, dropout_rate):
    ik = pl.program_id(1)
    iq = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(iq == 0)
    def _init():
        dk_sc[:] = jnp.zeros_like(dk_sc)
        dv_sc[:] = jnp.zeros_like(dv_sc)

    # causal: the q block is live iff its last query can see the first key
    live = ((iq + 1) * block_q - 1 >= ik * block_k) if causal else True

    @pl.when(live)
    def _compute():
        q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
        lse = lse_ref[0][:, :1]
        delta = delta_ref[0][:, :1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        s = jnp.where(_key_mask(iq, ik, block_q, block_k, kv_len, causal),
                      s, NEG_INF)
        p = jnp.exp(s - lse)                             # (bq, bk)
        if dropout_rate > 0.0:
            # same (seed, b, iq, ik) tuple as forward → identical mask
            keep = _tile_keep_mask(seed_ref, pl.program_id(0), iq, ik,
                                   block_q, block_k, dropout_rate)
            p_eff = jnp.where(keep, p / (1.0 - dropout_rate), 0.0)
        else:
            keep = None
            p_eff = p
        dv_sc[:] += jax.lax.dot_general(
            p_eff.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if keep is not None:
            dp = jnp.where(keep, dp / (1.0 - dropout_rate), 0.0)
        ds = p * (dp - delta) * sm_scale                 # (bq, bk)
        dk_sc[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(iq == nq - 1)
    def _finish():
        dk_ref[0] = dk_sc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_sc[:].astype(dv_ref.dtype)


# ---------------------------------------------------------------------------
# flat (BH, S, D) custom-vjp core
# ---------------------------------------------------------------------------
_SEED_SPEC = pl.BlockSpec((1, 1), lambda b, i, j: (0, 0),
                          memory_space=pltpu.SMEM)


def _q_spec(block_q, d):
    return pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0))


def _k_spec(block_k, d):
    return pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0))


def _row_spec(block_q):
    return pl.BlockSpec((1, block_q, 128), lambda b, i, j: (b, i, 0))


def _fwd_call(q, k, v, seed, kv_len, sm_scale, causal, block_q, block_k,
              dropout_rate, interpret):
    bh, sq, d = q.shape
    sk = k.shape[1]
    kernel = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, causal=causal,
        kv_len=kv_len, block_q=block_q, block_k=block_k,
        dropout_rate=dropout_rate)
    o, lse = pl.pallas_call(
        kernel,
        grid=(bh, sq // block_q, sk // block_k),
        in_specs=[_SEED_SPEC, _q_spec(block_q, d), _k_spec(block_k, d),
                  _k_spec(block_k, d)],
        out_specs=[_q_spec(block_q, d), _row_spec(block_q)],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, sq, 128), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        interpret=interpret,
    )(seed, q, k, v)
    return o, lse[:, :, 0]


@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(4, 5, 6, 7, 8, 9, 10, 11, 12))
def _flash(q, k, v, seed, kv_len, sm_scale, causal, block_q, block_k,
           bwd_block_q, bwd_block_k, dropout_rate, interpret):
    o, _ = _fwd_call(q, k, v, seed, kv_len, sm_scale, causal, block_q,
                     block_k, dropout_rate, interpret)
    return o


def _flash_fwd_rule(q, k, v, seed, kv_len, sm_scale, causal, block_q,
                    block_k, bwd_block_q, bwd_block_k, dropout_rate,
                    interpret):
    o, lse = _fwd_call(q, k, v, seed, kv_len, sm_scale, causal, block_q,
                       block_k, dropout_rate, interpret)
    return o, (q, k, v, seed, o, lse)


def _flash_bwd_rule(kv_len, sm_scale, causal, fwd_block_q, fwd_block_k,
                    block_q, block_k, dropout_rate, interpret, res, do):
    q, k, v, seed, o, lse = res
    bh, sq, d = q.shape
    sk = k.shape[1]
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    lse_b = jnp.broadcast_to(lse[:, :, None], (bh, sq, 128))
    delta_b = jnp.broadcast_to(delta[:, :, None], (bh, sq, 128))
    row = _row_spec(block_q)
    qs, ks = _q_spec(block_q, d), _k_spec(block_k, d)

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, sm_scale=sm_scale, causal=causal,
                          kv_len=kv_len, block_q=block_q, block_k=block_k,
                          dropout_rate=dropout_rate),
        grid=(bh, sq // block_q, sk // block_k),
        in_specs=[_SEED_SPEC, qs, ks, ks, qs, row, row],
        out_specs=qs,
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(seed, q, k, v, do, lse_b, delta_b)

    # dkv grid: (bh, nk, nq) — index maps swap the roles of grid axes 1/2
    seed2 = pl.BlockSpec((1, 1), lambda b, j, i: (0, 0),
                         memory_space=pltpu.SMEM)
    qs2 = pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0))
    ks2 = pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0))
    row2 = pl.BlockSpec((1, block_q, 128), lambda b, j, i: (b, i, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, sm_scale=sm_scale, causal=causal,
                          kv_len=kv_len, block_q=block_q, block_k=block_k,
                          dropout_rate=dropout_rate),
        grid=(bh, sk // block_k, sq // block_q),
        in_specs=[seed2, qs2, ks2, ks2, qs2, row2, row2],
        out_specs=[ks2, ks2],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, sk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(seed, q, k, v, do, lse_b, delta_b)
    return dq, dk, dv, np.zeros(seed.shape, dtype=jax.dtypes.float0)


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


# ---------------------------------------------------------------------------
# public entry
# ---------------------------------------------------------------------------
def flash_attention(q, k, v, *, causal: bool = False,
                    sm_scale: Optional[float] = None,
                    dropout_rate: float = 0.0,
                    dropout_seed=None,
                    block_q: int = 512, block_k: int = 512,
                    bwd_block_q: int = 128, bwd_block_k: int = 128,
                    interpret: Optional[bool] = None):
    """Tiled flash attention. q: (b, h, sq, d); k, v: (b, h, sk, d).

    Pads seq dims to block multiples and head_dim to a multiple of 64
    (padded keys masked, padded head dims sliced off), runs the Pallas
    kernels, and is differentiable via the custom VJP. ``dropout_rate`` > 0
    applies in-kernel counter-based dropout to the attention
    probabilities (requires ``dropout_seed``, an int32 scalar).

    Block defaults are measured on v5e (head_dim 64): the forward wants
    large tiles (512x512 — k/v are re-streamed once per q block, so
    bigger q blocks cut HBM traffic); the backward wants small ones
    (128x128 — its dq/dkv scratch accumulators serialize the grid)."""
    if interpret is None:
        interpret = not _on_tpu()
    if dropout_rate > 0.0 and dropout_seed is None:
        raise ValueError("dropout_rate > 0 requires dropout_seed")
    b, h, sq, d = q.shape
    sk = k.shape[2]
    if causal and sq != sk:
        raise NotImplementedError("causal flash requires sq == sk")
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    # clamp blocks to (hardware-aligned) sequence sizes: sublane mult of 8,
    # lane mult of 128
    block_q = min(block_q, -(-sq // 8) * 8)
    block_k = min(block_k, -(-sk // 128) * 128)
    # bwd blocks must tile the (block_q/block_k-padded) seq dims exactly
    bwd_block_q = min(bwd_block_q, block_q)
    bwd_block_k = min(bwd_block_k, block_k)
    if block_q % bwd_block_q:
        bwd_block_q = block_q
    if block_k % bwd_block_k:
        bwd_block_k = block_k

    # head_dim: pad only to a multiple of 64. d=64 (BERT/GPT-class) stays
    # unpadded — padding to the full 128 lane width doubled k/v HBM
    # traffic and the PV-matmul passes (measured: flash lost to XLA
    # attention below seq 1024 because of it). The MXU handles 64-lane
    # tiles natively.
    qp = _pad_to(_pad_to(q, block_q, 2), 64, 3)
    kp = _pad_to(_pad_to(k, block_k, 2), 64, 3)
    vp = _pad_to(_pad_to(v, block_k, 2), 64, 3)
    sq_p, d_p = qp.shape[2], qp.shape[3]
    sk_p = kp.shape[2]

    if dropout_seed is None:
        seed = jnp.zeros((1, 1), jnp.int32)
    else:
        seed = jnp.asarray(dropout_seed, jnp.int32).reshape(1, 1)
    o = _flash(qp.reshape(b * h, sq_p, d_p),
               kp.reshape(b * h, sk_p, d_p),
               vp.reshape(b * h, sk_p, d_p),
               seed, sk, sm_scale, causal, block_q, block_k,
               bwd_block_q, bwd_block_k, float(dropout_rate), interpret)
    return o.reshape(b, h, sq_p, d_p)[:, :, :sq, :d]


def mha_reference(q, k, v, *, causal: bool = False,
                  sm_scale: Optional[float] = None, precision=None):
    """Plain-XLA attention used as the numerics golden for the kernels.
    Same layout as :func:`flash_attention`. ``precision`` feeds the
    einsums (pass ``jax.lax.Precision.HIGHEST`` to force multi-pass fp32
    on the MXU, whose default is a single bf16 pass)."""
    d = q.shape[-1]
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    s = (jnp.einsum("bhqd,bhkd->bhqk", q, k, precision=precision)
         .astype(jnp.float32) * sm_scale)
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        mask = np.tril(np.ones((sq, sk), dtype=bool), sk - sq)
        s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(q.dtype), v,
                      precision=precision).astype(q.dtype)


def dropout_keep_mask(b, h, sq, sk, rate, seed):
    """The kernel's counter-based keep mask, computed in plain XLA.

    Bit-identical to what :func:`_tile_keep_mask` generates inside the
    Pallas kernels under ANY block decomposition (same hash of the same
    absolute coordinates), so an explicit-mask golden —
    ``where(keep, softmax(s)/(1-rate), 0) @ v`` — reproduces the
    kernel's dropout semantics exactly. Used by the on-chip validator
    to check the compiled vjp without finite differences (MXU bf16
    rounding swamps an eps-sized central difference)."""
    bh = jnp.arange(b * h, dtype=jnp.int32)[:, None, None]
    qp = jnp.arange(sq, dtype=jnp.int32)[None, :, None]
    kp = jnp.arange(sk, dtype=jnp.int32)[None, None, :]
    keep = _position_keep(jnp.int32(seed), bh, qp, kp, rate)
    return keep.reshape(b, h, sq, sk)
