"""Fused Pallas optimizer update — the ``opt_update:fused`` kernel tier.

The reference runs its Adam update as one CUDA kernel per parameter view
(``optimizer_kernel.cu:196``). XLA usually fuses the tree-mapped jnp
update well, but on the ZeRO-sharded path the per-shard update is small
and bandwidth-bound: this kernel does the whole Adam step — weight-decay
fold, both moment updates, bias-corrected step — in ONE HBM pass over
(w, g, m, v), writing (w', m', v') without intermediate materialization.

Semantics exactly mirror ``runtime.optimizers.AdamOptimizer.update`` (the
bit-parity oracle in tests/test_kernels.py): registry predicate gates it
to TPU + Adam; interpret mode exists for CPU numerics tests only.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANES = 128
_SUBLANES = 8  # float32 min tile height


def _adam_kernel(beta1, beta2, eps, wd, scal_ref, w_ref, g_ref, m_ref,
                 v_ref, ow_ref, om_ref, ov_ref):
    alpha_t = scal_ref[0, 0]
    w32 = w_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32) + wd * w32
    m = beta1 * m_ref[:] + (1.0 - beta1) * g
    v = beta2 * v_ref[:] + (1.0 - beta2) * g * g
    step = alpha_t * m / (jnp.sqrt(v) + eps)
    ow_ref[:] = (w32 - step.astype(ow_ref.dtype)
                 .astype(jnp.float32)).astype(ow_ref.dtype)
    om_ref[:] = m
    ov_ref[:] = v


def _pad2d(x, rows):
    flat = x.reshape(-1)
    pad = rows * _LANES - flat.size
    if pad:
        flat = jnp.pad(flat, (0, pad))
    return flat.reshape(rows, _LANES)


def fused_adam_update(w, g, m, v, alpha_t, *, beta1: float = 0.9,
                      beta2: float = 0.999, eps: float = 1e-8,
                      wd: float = 0.0, interpret=None):
    """One-pass Adam update for a single parameter leaf.

    ``alpha_t`` is the bias-corrected step size (traced — it depends on
    the step counter), fed through SMEM. Returns ``(w', m', v')`` with
    the exact update math of ``AdamOptimizer.update``.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    n = w.size
    rows = pl.cdiv(n, _LANES)
    rows = pl.cdiv(rows, _SUBLANES) * _SUBLANES
    shape = w.shape
    w2, g2 = _pad2d(w, rows), _pad2d(g, rows)
    m2 = _pad2d(m.astype(jnp.float32), rows)
    v2 = _pad2d(v.astype(jnp.float32), rows)
    scal = jnp.asarray(alpha_t, jnp.float32).reshape(1, 1)
    kern = functools.partial(_adam_kernel, float(beta1), float(beta2),
                             float(eps), float(wd))
    ow, om, ov = pl.pallas_call(
        kern,
        out_shape=(
            jax.ShapeDtypeStruct((rows, _LANES), w.dtype),
            jax.ShapeDtypeStruct((rows, _LANES), jnp.float32),
            jax.ShapeDtypeStruct((rows, _LANES), jnp.float32),
        ),
        in_specs=[
            pl.BlockSpec((1, 1), memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ),
        interpret=bool(interpret),
    )(scal, w2, g2, m2, v2)
    unflat = lambda a: a.reshape(-1)[:n].reshape(shape)  # noqa: E731
    return unflat(ow), unflat(om), unflat(ov)
