"""Parallel operators: communication reified as graph nodes.

Reference parity: ``src/parallel_ops/{partition,combine,replicate,reduction,
fused_parallel_op}.cc``. In the reference each is a Legion index launch with
a custom CUDA copy kernel; here each is a *sharding transition*: the emitted
value is (numerically) identity / reduction, and the executor attaches a
``jax.lax.with_sharding_constraint`` for the target sharding so XLA inserts
the matching ICI collective (all-to-all / all-gather / collective-permute /
reduce-scatter). See parallel/strategy.py for the sharding attachment.
"""
from __future__ import annotations

from typing import Any, Dict, List

import jax
import jax.numpy as jnp

from ..ffconst import OperatorType
from .registry import EmitCtx, OpDef, register


class _ShardingTransitionBase(OpDef):
    """Identity at the value level; sharding change at the mesh level."""

    def infer(self, params, in_shapes, in_dtypes):
        return [(in_shapes[0], in_dtypes[0])]

    def emit(self, params, inputs, weights, ctx, name):
        return [inputs[0]]


@register
class RepartitionOp(_ShardingTransitionBase):
    """Re-shard along dim `dim` with degree `degree` (scatter)."""
    op_type = OperatorType.OP_REPARTITION


@register
class CombineOp(_ShardingTransitionBase):
    """Inverse of repartition (gather along a dim)."""
    op_type = OperatorType.OP_COMBINE


@register
class ReplicateOp(_ShardingTransitionBase):
    """Replicate across `degree` devices (broadcast)."""
    op_type = OperatorType.OP_REPLICATE


@register
class ReductionOp(_ShardingTransitionBase):
    """Sum-combine `degree` replicas (all-reduce / reduce-scatter).

    Value-level: with GSPMD the partial sums live in an unreduced sharding
    only inside shard_map-style code; under pjit the producing op already
    yields the full sum, so this is an identity plus a sharding constraint.
    """
    op_type = OperatorType.OP_REDUCTION


@register
class AllToAllOp(_ShardingTransitionBase):
    """Resharding between two partitioned dims (sequence<->head parallax for
    Ulysses-style sequence parallelism). TPU-native addition."""
    op_type = OperatorType.OP_ALLTOALL


@register
class PipelineOp(_ShardingTransitionBase):
    """Pipeline stage boundary marker (reference has only the enum,
    ``ffconst.h:159`` — no implementation). As a graph node it is an
    identity; actual pipelining happens when ``FFConfig.pipeline_stages``
    lowers a repeated-block region onto the GPipe engine
    (``parallel/pipeline_lowering.py`` + executor), not through this op."""
    op_type = OperatorType.OP_PIPELINE


@register
class FusedParallelOp(_ShardingTransitionBase):
    """A chain of parallel ops collapsed into one transition
    (reference ``fused_parallel_op.cc``): the net effect is just the final
    sharding, which is exactly what one with_sharding_constraint expresses."""
    op_type = OperatorType.OP_FUSED_PARALLEL
