"""Operator registry: import all op modules to populate OPS."""
from .registry import OPS, EmitCtx, OpDef, get_op_def, matmul  # noqa: F401
from . import nn_ops        # noqa: F401
from . import element_ops   # noqa: F401
from . import tensor_ops    # noqa: F401
from . import moe_ops       # noqa: F401
from . import rnn_ops       # noqa: F401
from . import parallel_ops  # noqa: F401
