"""Operator registry: import all op modules to populate OPS."""
from .registry import OPS, EmitCtx, OpDef, get_op_def, matmul  # noqa: F401


def ensure_weight_specs(layer):
    """Materialize (and memoize on the layer) a layer's WeightSpec list
    — THE shared wiring for every consumer that sizes or initializes
    weights (executor init, the overlap schedule builder): a future
    change to how specs derive happens here once, or per-consumer
    copies drift."""
    specs = layer.weights or get_op_def(layer.op_type).weights(
        layer.params, [t.shape for t in layer.inputs],
        [t.dtype for t in layer.inputs])
    layer.weights = specs
    return specs
from . import nn_ops        # noqa: F401
from . import element_ops   # noqa: F401
from . import tensor_ops    # noqa: F401
from . import moe_ops       # noqa: F401
from . import rnn_ops       # noqa: F401
from . import parallel_ops  # noqa: F401
