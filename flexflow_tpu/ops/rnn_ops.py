"""Recurrent ops: multi-layer LSTM as a ``lax.scan`` recurrence.

Reference parity: the legacy NMT app's hand-rolled cuDNN LSTM
(``/root/reference/nmt/lstm.cu``, ``rnn.h`` — per-timestep kernel
launches outside the op registry). TPU-native redesign: the whole
recurrence is ONE ``lax.scan`` inside the jitted step — XLA unrolls
nothing, the (x @ W_ih) input projection for ALL timesteps is hoisted
into a single big MXU matmul before the scan, and only the (h @ W_hh)
recurrent matmul rides the sequential carry. Backward is jax.grad
through the scan (no hand-written BPTT).
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..core.tensor import WeightSpec
from ..ffconst import DataType, InitializerType, OperatorType
from .registry import OpDef, compute_dtype, register


@register
class LSTMOp(OpDef):
    """Multi-layer unidirectional LSTM.

    input  (b, s, d) -> output (b, s, h); zero initial state. Weights per
    layer l: ``w{l}`` ((in_l + h), 4h) with gate order [i, f, g, o] and
    ``b{l}`` (4h,); forget-gate bias +1 at init (standard practice; the
    reference's nmt app initializes uniformly).
    """
    op_type = OperatorType.OP_LSTM

    def infer(self, params, in_shapes, in_dtypes):
        b, s, _ = in_shapes[0]
        return [((b, s, params["hidden_size"]), in_dtypes[0])]

    def weights(self, params, in_shapes, in_dtypes):
        h = params["hidden_size"]
        layers = params.get("num_layers", 1)
        d = in_shapes[0][2]
        dt = in_dtypes[0]
        out = []
        for l in range(layers):
            in_l = d if l == 0 else h
            out.append(WeightSpec(f"w{l}", (in_l + h, 4 * h), dt,
                                  InitializerType.GLOROT_UNIFORM))
            out.append(WeightSpec(f"b{l}", (4 * h,), dt,
                                  InitializerType.ZERO))
        return out

    def emit(self, params, inputs, weights, ctx, name):
        (x,) = inputs
        h = params["hidden_size"]
        layers = params.get("num_layers", 1)
        b = x.shape[0]
        mdt = compute_dtype(ctx, x.dtype)

        y = x
        for l in range(layers):
            w = weights[f"w{l}"]
            bias = weights[f"b{l}"].astype(jnp.float32)
            d_in = y.shape[-1]
            w_ih, w_hh = w[:d_in], w[d_in:]
            # hoist the input projection out of the scan: one big matmul
            # over (b*s, d) instead of s small ones
            zx = jnp.einsum("bsd,dk->bsk", y.astype(mdt), w_ih.astype(mdt),
                            preferred_element_type=jnp.float32)
            zx = jnp.swapaxes(zx + bias, 0, 1)          # (s, b, 4h)

            def step(carry, zx_t, w_hh=w_hh):
                h_prev, c_prev = carry
                z = zx_t + jnp.einsum(
                    "bh,hk->bk", h_prev.astype(mdt), w_hh.astype(mdt),
                    preferred_element_type=jnp.float32)
                i, f, g, o = jnp.split(z, 4, axis=-1)
                # +1 forget bias applied here so the ZERO-initialized
                # bias weight still starts the gate open
                c = jax.nn.sigmoid(f + 1.0) * c_prev \
                    + jax.nn.sigmoid(i) * jnp.tanh(g)
                hh = jax.nn.sigmoid(o) * jnp.tanh(c)
                return (hh, c), hh

            init = (jnp.zeros((b, h), jnp.float32),
                    jnp.zeros((b, h), jnp.float32))
            _, hs = jax.lax.scan(step, init, zx)
            y = jnp.swapaxes(hs, 0, 1).astype(x.dtype)  # (b, s, h)
        return [y]

    def flops(self, params, in_shapes, out_shapes):
        b, s, d = in_shapes[0]
        h = params["hidden_size"]
        layers = params.get("num_layers", 1)
        total = 0.0
        for l in range(layers):
            in_l = d if l == 0 else h
            total += 2.0 * b * s * (in_l + h) * 4 * h
        return total

    def backward_flops_factor(self):
        return 2.0
