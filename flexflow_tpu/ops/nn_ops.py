"""Neural-net operators: dense, conv, pool, norms, attention, embedding, ...

Reference parity: ``src/ops/{linear,conv_2d,pool_2d,batch_norm,layer_norm,
softmax,dropout,embedding,attention,batch_matmul,flat}.cc`` — rebuilt as JAX
emission (XLA handles kernel selection/fusion; bf16 matmuls target the MXU).
Shape conventions follow the reference's Python API: images are NCHW,
sequences are (batch, seq, hidden).
"""
from __future__ import annotations

import math
from typing import Any, Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ffconst import (ActiMode, AggrMode, DataType, InitializerType,
                       OperatorType, PoolType)
from ..core.tensor import WeightSpec
from ..dtypes import to_jnp
from .registry import (EmitCtx, OpDef, bf16_enabled, compute_dtype,
                       matmul, register)


def apply_activation(x, acti: ActiMode):
    acti = ActiMode(acti)
    if acti == ActiMode.AC_MODE_NONE:
        return x
    if acti == ActiMode.AC_MODE_RELU:
        return jax.nn.relu(x)
    if acti == ActiMode.AC_MODE_SIGMOID:
        return jax.nn.sigmoid(x)
    if acti == ActiMode.AC_MODE_TANH:
        return jnp.tanh(x)
    if acti == ActiMode.AC_MODE_GELU:
        return jax.nn.gelu(x)
    raise ValueError(acti)


# ---------------------------------------------------------------------------
@register
class LinearOp(OpDef):
    """Dense / fully-connected (reference ``src/ops/linear.cc``).

    y = act(x @ kernel + bias); kernel (in_dim, out_dim). The reference's
    cuBLAS GEMM + activation epilogue becomes one bf16 MXU matmul that XLA
    fuses with the epilogue.
    """
    op_type = OperatorType.OP_LINEAR

    def infer(self, params, in_shapes, in_dtypes):
        (ish,) = in_shapes
        out_dim = params["out_dim"]
        out_dtype = params.get("dtype", in_dtypes[0])
        return [(tuple(ish[:-1]) + (out_dim,), out_dtype)]

    def weights(self, params, in_shapes, in_dtypes):
        in_dim = in_shapes[0][-1]
        out_dim = params["out_dim"]
        dt = params.get("dtype", in_dtypes[0])
        ws = [WeightSpec("kernel", (in_dim, out_dim), dt,
                         params.get("kernel_initializer",
                                    InitializerType.GLOROT_UNIFORM))]
        if params.get("use_bias", True):
            ws.append(WeightSpec("bias", (out_dim,), dt, InitializerType.ZERO))
        return ws

    def emit(self, params, inputs, weights, ctx, name):
        (x,) = inputs
        y = matmul(x, weights["kernel"], ctx=ctx)
        if "bias" in weights:
            y = y + weights["bias"]
        y = apply_activation(y, params.get("activation",
                                           ActiMode.AC_MODE_NONE))
        if "dtype" in params:
            y = y.astype(to_jnp(params["dtype"]))
        return [y]

    def flops(self, params, in_shapes, out_shapes):
        batch = int(np.prod(in_shapes[0][:-1]))
        return 2.0 * batch * in_shapes[0][-1] * params["out_dim"]

    def backward_flops_factor(self):
        return 2.0


# ---------------------------------------------------------------------------
@register
class Conv2DOp(OpDef):
    """2-D convolution, NCHW (reference ``src/ops/conv_2d.cc``)."""
    op_type = OperatorType.OP_CONV2D

    def infer(self, params, in_shapes, in_dtypes):
        n, c, h, w = in_shapes[0]
        kh, kw = params["kernel_h"], params["kernel_w"]
        sh, sw = params["stride_h"], params["stride_w"]
        ph, pw = params["padding_h"], params["padding_w"]
        oh = (h + 2 * ph - kh) // sh + 1
        ow = (w + 2 * pw - kw) // sw + 1
        return [((n, params["out_channels"], oh, ow), in_dtypes[0])]

    def weights(self, params, in_shapes, in_dtypes):
        c = in_shapes[0][1]
        groups = params.get("groups", 1)
        dt = in_dtypes[0]
        ws = [WeightSpec("kernel",
                         (params["out_channels"], c // groups,
                          params["kernel_h"], params["kernel_w"]), dt,
                         params.get("kernel_initializer",
                                    InitializerType.GLOROT_UNIFORM))]
        if params.get("use_bias", True):
            ws.append(WeightSpec("bias", (params["out_channels"],), dt,
                                 InitializerType.ZERO))
        return ws

    def emit(self, params, inputs, weights, ctx, name):
        (x,) = inputs
        k = weights["kernel"]
        cdt = x.dtype
        if cdt == jnp.float32 and bf16_enabled(ctx):
            x16, k16 = x.astype(jnp.bfloat16), k.astype(jnp.bfloat16)
        else:
            x16, k16 = x, k
        # No preferred_element_type here: its conv VJP emits a transposed
        # conv with mismatched (f32 cotangent, bf16 kernel) dtypes. bf16
        # in/out is fine — the MXU accumulates in f32 internally.
        y = jax.lax.conv_general_dilated(
            x16, k16,
            window_strides=(params["stride_h"], params["stride_w"]),
            padding=[(params["padding_h"], params["padding_h"]),
                     (params["padding_w"], params["padding_w"])],
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
            feature_group_count=params.get("groups", 1))
        y = y.astype(cdt)
        if "bias" in weights:
            y = y + weights["bias"][None, :, None, None]
        return [apply_activation(y, params.get("activation",
                                               ActiMode.AC_MODE_NONE))]

    def flops(self, params, in_shapes, out_shapes):
        n, co, oh, ow = out_shapes[0]
        ci = in_shapes[0][1] // params.get("groups", 1)
        return 2.0 * n * co * oh * ow * ci * params["kernel_h"] * params["kernel_w"]

    def backward_flops_factor(self):
        return 2.0


# ---------------------------------------------------------------------------
@register
class Pool2DOp(OpDef):
    """Max/avg pooling, NCHW (reference ``src/ops/pool_2d.cc``)."""
    op_type = OperatorType.OP_POOL2D

    def infer(self, params, in_shapes, in_dtypes):
        n, c, h, w = in_shapes[0]
        kh, kw = params["kernel_h"], params["kernel_w"]
        sh, sw = params["stride_h"], params["stride_w"]
        ph, pw = params["padding_h"], params["padding_w"]
        oh = (h + 2 * ph - kh) // sh + 1
        ow = (w + 2 * pw - kw) // sw + 1
        return [((n, c, oh, ow), in_dtypes[0])]

    def emit(self, params, inputs, weights, ctx, name):
        (x,) = inputs
        kh, kw = params["kernel_h"], params["kernel_w"]
        sh, sw = params["stride_h"], params["stride_w"]
        ph, pw = params["padding_h"], params["padding_w"]
        dims = (1, 1, kh, kw)
        strides = (1, 1, sh, sw)
        pads = ((0, 0), (0, 0), (ph, ph), (pw, pw))
        if PoolType(params.get("pool_type", PoolType.POOL_MAX)) == PoolType.POOL_MAX:
            init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
            y = jax.lax.reduce_window(x, init, jax.lax.max, dims, strides, pads)
        else:
            s = jax.lax.reduce_window(x, 0.0, jax.lax.add, dims, strides, pads)
            # count_include_pad=True matches cuDNN's default used by the reference
            y = s / float(kh * kw)
        return [apply_activation(y, params.get("activation",
                                               ActiMode.AC_MODE_NONE))]


# ---------------------------------------------------------------------------
@register
class FlatOp(OpDef):
    """NCHW → (N, C*H*W) (reference ``src/ops/flat.cc``)."""
    op_type = OperatorType.OP_FLAT

    def infer(self, params, in_shapes, in_dtypes):
        s = in_shapes[0]
        return [((s[0], int(np.prod(s[1:]))), in_dtypes[0])]

    def emit(self, params, inputs, weights, ctx, name):
        (x,) = inputs
        return [x.reshape(x.shape[0], -1)]


# ---------------------------------------------------------------------------
@register
class SoftmaxOp(OpDef):
    op_type = OperatorType.OP_SOFTMAX

    def infer(self, params, in_shapes, in_dtypes):
        return [(in_shapes[0], in_dtypes[0])]

    def emit(self, params, inputs, weights, ctx, name):
        (x,) = inputs
        return [jax.nn.softmax(x, axis=params.get("axis", -1))]


# ---------------------------------------------------------------------------
@register
class DropoutOp(OpDef):
    op_type = OperatorType.OP_DROPOUT

    def infer(self, params, in_shapes, in_dtypes):
        return [(in_shapes[0], in_dtypes[0])]

    def emit(self, params, inputs, weights, ctx, name):
        (x,) = inputs
        rate = params.get("rate", 0.5)
        if not ctx.training or rate <= 0.0:
            return [x]
        rng = ctx.rng_for(name)
        if rng is None:
            raise RuntimeError(f"dropout layer {name} needs an rng")
        keep = 1.0 - rate
        mask = jax.random.bernoulli(rng, keep, x.shape)
        return [jnp.where(mask, x / keep, jnp.zeros_like(x))]


# ---------------------------------------------------------------------------
@register
class BatchNormOp(OpDef):
    """Batch norm over NCHW, with running stats in the state collection
    (reference ``src/ops/batch_norm.cc``; cuDNN BN → jnp + state threading)."""
    op_type = OperatorType.OP_BATCHNORM

    def infer(self, params, in_shapes, in_dtypes):
        return [(in_shapes[0], in_dtypes[0])]

    def weights(self, params, in_shapes, in_dtypes):
        c = in_shapes[0][1]
        dt = in_dtypes[0]
        return [WeightSpec("scale", (c,), dt, InitializerType.ONE),
                WeightSpec("bias", (c,), dt, InitializerType.ZERO)]

    def state_spec(self, params, in_shapes, in_dtypes):
        c = in_shapes[0][1]
        return {"mean": ((c,), DataType.DT_FLOAT),
                "var": ((c,), DataType.DT_FLOAT)}

    def emit(self, params, inputs, weights, ctx, name):
        (x,) = inputs
        eps = params.get("eps", 1e-5)
        momentum = params.get("momentum", 0.1)
        axes = (0, 2, 3) if x.ndim == 4 else (0,)
        bshape = (1, -1) + (1,) * (x.ndim - 2)
        st = ctx.state.get(name, {})
        if ctx.training or not st:
            mean = jnp.mean(x.astype(jnp.float32), axis=axes)
            var = jnp.var(x.astype(jnp.float32), axis=axes)
            if st:
                ctx.new_state[name] = {
                    "mean": (1 - momentum) * st["mean"] + momentum * mean,
                    "var": (1 - momentum) * st["var"] + momentum * var,
                }
        else:
            mean, var = st["mean"], st["var"]
        inv = jax.lax.rsqrt(var + eps) * weights["scale"].astype(jnp.float32)
        y = (x.astype(jnp.float32) - mean.reshape(bshape)) * inv.reshape(bshape) \
            + weights["bias"].astype(jnp.float32).reshape(bshape)
        y = y.astype(x.dtype)
        if params.get("relu", True):
            y = jax.nn.relu(y)
        return [y]


# ---------------------------------------------------------------------------
@register
class LayerNormOp(OpDef):
    """Layer norm (reference ``src/ops/layer_norm.cc`` — Welford kernels →
    jnp mean/var which XLA fuses into one pass)."""
    op_type = OperatorType.OP_LAYERNORM

    def infer(self, params, in_shapes, in_dtypes):
        return [(in_shapes[0], in_dtypes[0])]

    def weights(self, params, in_shapes, in_dtypes):
        if not params.get("elementwise_affine", True):
            return []
        axes = params.get("axes", [len(in_shapes[0]) - 1])
        shape = tuple(in_shapes[0][a] for a in axes)
        dt = in_dtypes[0]
        return [WeightSpec("scale", shape, dt, InitializerType.ONE),
                WeightSpec("bias", shape, dt, InitializerType.ZERO)]

    def emit(self, params, inputs, weights, ctx, name):
        (x,) = inputs
        ndim = x.ndim
        axes = tuple(a % ndim for a in params.get("axes", [ndim - 1]))
        eps = params.get("eps", 1e-5)
        xf = x.astype(jnp.float32)
        mean = jnp.mean(xf, axis=axes, keepdims=True)
        var = jnp.var(xf, axis=axes, keepdims=True)
        y = (xf - mean) * jax.lax.rsqrt(var + eps)
        if "scale" in weights:
            bshape = [x.shape[a] if a in axes else 1 for a in range(ndim)]
            y = y * weights["scale"].astype(jnp.float32).reshape(bshape) \
                + weights["bias"].astype(jnp.float32).reshape(bshape)
        return [y.astype(x.dtype)]


# ---------------------------------------------------------------------------
@register
class RMSNormOp(OpDef):
    """RMSNorm — TPU-native addition (used by T5/LLaMA-style models; the
    reference fuses T5LayerNorm patterns in its fx frontend)."""
    op_type = OperatorType.OP_RMSNORM

    def infer(self, params, in_shapes, in_dtypes):
        return [(in_shapes[0], in_dtypes[0])]

    def weights(self, params, in_shapes, in_dtypes):
        return [WeightSpec("scale", (in_shapes[0][-1],), in_dtypes[0],
                           InitializerType.ONE)]

    def emit(self, params, inputs, weights, ctx, name):
        (x,) = inputs
        eps = params.get("eps", 1e-6)
        xf = x.astype(jnp.float32)
        ms = jnp.mean(xf * xf, axis=-1, keepdims=True)
        y = xf * jax.lax.rsqrt(ms + eps) * weights["scale"].astype(jnp.float32)
        return [y.astype(x.dtype)]


# ---------------------------------------------------------------------------
@register
class EmbeddingOp(OpDef):
    """Embedding lookup with none/sum/avg aggregation
    (reference ``src/ops/embedding.cc``: gather/scatter-add kernels →
    jnp.take, which XLA lowers to TPU gather)."""
    op_type = OperatorType.OP_EMBEDDING

    def infer(self, params, in_shapes, in_dtypes):
        ish = in_shapes[0]
        out_dim = params["out_dim"]
        dt = params.get("dtype", DataType.DT_FLOAT)
        aggr = AggrMode(params.get("aggr", AggrMode.AGGR_MODE_NONE))
        if aggr == AggrMode.AGGR_MODE_NONE:
            return [(tuple(ish) + (out_dim,), dt)]
        # sum/avg aggregate over the trailing (bag) dim
        return [(tuple(ish[:-1]) + (out_dim,), dt)]

    def weights(self, params, in_shapes, in_dtypes):
        dt = params.get("dtype", DataType.DT_FLOAT)
        return [WeightSpec("kernel", (params["num_entries"], params["out_dim"]),
                           dt, params.get("kernel_initializer",
                                          InitializerType.GLOROT_UNIFORM))]

    def emit(self, params, inputs, weights, ctx, name):
        (ids,) = inputs
        table = weights["kernel"]
        aggr = AggrMode(params.get("aggr", AggrMode.AGGR_MODE_NONE))
        out = jnp.take(table, ids.astype(jnp.int32), axis=0)
        if aggr == AggrMode.AGGR_MODE_SUM:
            out = jnp.sum(out, axis=-2)
        elif aggr == AggrMode.AGGR_MODE_AVG:
            out = jnp.mean(out, axis=-2)
        return [out]


# ---------------------------------------------------------------------------
def _apply_rope(x, pos, theta: float):
    """Rotary position embedding, LLaMA half-split-rotate convention.
    ``x``: (B, L, h, d) with d even; ``pos``: (L,) absolute indices
    shared by the batch, or (B, L) per-row (ragged-prompt decode)."""
    d = x.shape[-1]
    inv = 1.0 / theta ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d)
    pf = pos.astype(jnp.float32)
    if pf.ndim == 1:
        pf = pf[None, :]                                # (1, L)
    freqs = pf[:, :, None] * inv[None, None, :]         # (B|1, L, d/2)
    emb = jnp.concatenate([freqs, freqs], axis=-1)      # (B|1, L, d)
    cos = jnp.cos(emb)[:, :, None, :]
    sin = jnp.sin(emb)[:, :, None, :]
    x1, x2 = x[..., : d // 2], x[..., d // 2:]
    rot = jnp.concatenate([-x2, x1], axis=-1)
    xf = x.astype(jnp.float32)
    return (xf * cos + rot.astype(jnp.float32) * sin).astype(x.dtype)


@register
class MultiHeadAttentionOp(OpDef):
    """Multi-head attention (reference ``src/ops/attention.cc`` wraps cuDNN
    MHA; here: einsum attention, bf16 on the MXU, fp32 softmax).

    Inputs: query (B, Lq, E), key (B, Lk, Ek), value (B, Lv, Ev).
    Output: (B, Lq, E) after the output projection — matching
    ``FFModel::multihead_attention`` (reference ``model.h``).
    """
    op_type = OperatorType.OP_MULTIHEAD_ATTENTION

    def infer(self, params, in_shapes, in_dtypes):
        q = in_shapes[0]
        return [((q[0], q[1], params["embed_dim"]), in_dtypes[0])]

    def weights(self, params, in_shapes, in_dtypes):
        e = params["embed_dim"]
        h = params["num_heads"]
        kvh = params.get("num_kv_heads", 0) or h   # GQA: kv-head groups
        kdim = params.get("kdim", 0) or e
        vdim = params.get("vdim", 0) or e
        # qProjSize == kProjSize == kdim (reference attention.cc:182)
        dt = in_dtypes[0]
        qe, ke, ve = in_shapes[0][-1], in_shapes[1][-1], in_shapes[2][-1]
        ws = [WeightSpec("wq", (qe, h, kdim // h), dt),
              WeightSpec("wk", (ke, kvh, kdim // h), dt),
              WeightSpec("wv", (ve, kvh, vdim // h), dt),
              WeightSpec("wo", (h, vdim // h, e), dt)]
        if params.get("bias", True):
            ws += [WeightSpec("bq", (h, kdim // h), dt, InitializerType.ZERO),
                   WeightSpec("bk", (kvh, kdim // h), dt,
                              InitializerType.ZERO),
                   WeightSpec("bv", (kvh, vdim // h), dt,
                              InitializerType.ZERO),
                   WeightSpec("bo", (e,), dt, InitializerType.ZERO)]
        return ws

    @staticmethod
    def _flash_mode(ctx) -> str:
        """Resolved flash-attention mode: "true" | "false" | "auto"."""
        return getattr(getattr(ctx, "config", None), "use_flash_attention",
                       "auto")

    @staticmethod
    def _impl_for(ctx, name: str):
        """This op's kernel impl from the adopted plan (the executor
        threads ``strategy.kernel_impls`` through EmitCtx): the
        layer-name key wins over the "attention" kind key; None = no
        plan, keep the legacy ``use_flash_attention`` resolution."""
        plan = getattr(ctx, "kernel_impls", None)
        if not plan:
            return None
        return plan.get(name, plan.get("attention"))

    # Measured on v5e (BERT-base, head_dim=64, tuned 512x512-fwd /
    # 128x128-bwd blocks, unpadded d=64): XLA's fused attention still
    # wins the train step below ~1024 tokens; at 1024 the Pallas kernel
    # pulls ahead (f+b 124 vs 130 ms) and at 2048 it wins decisively
    # (166 vs 226 ms) while never materializing the s^2 logits.
    FLASH_AUTO_MIN_SEQ = 1024

    @classmethod
    def _flash_enabled(cls, ctx, seq_len: int = 0, mode: str = None) -> bool:
        mode = mode or cls._flash_mode(ctx)
        if mode == "false":
            return False
        if mode == "true":
            return True
        import jax as _jax
        return _jax.default_backend() == "tpu" \
            and seq_len >= cls.FLASH_AUTO_MIN_SEQ

    def emit(self, params, inputs, weights, ctx, name):
        q, k, v = inputs
        cdt = q.dtype
        h = params["num_heads"]

        mdt = compute_dtype(ctx, cdt)

        def proj(x, w, b):
            y = jnp.einsum("ble,ehd->blhd", x.astype(mdt),
                           w.astype(mdt),
                           preferred_element_type=jnp.float32)
            if b is not None:
                y = y + b.astype(jnp.float32)
            return y

        qh = proj(q, weights["wq"], weights.get("bq"))
        kh = proj(k, weights["wk"], weights.get("bk"))
        vh = proj(v, weights["wv"], weights.get("bv"))
        rate = params.get("dropout", 0.0) if ctx.training else 0.0

        causal = params.get("causal", False)
        kv_mode = getattr(ctx, "kv_mode", None)
        if params.get("rope", False):
            # rotary embeddings applied in-op (LLaMA convention,
            # half-split rotate) — positions are absolute indices, so
            # the single decode token rotates at kv_index and the cache
            # stores already-rotated keys
            if not causal:
                raise ValueError(
                    "rope is only supported for causal attention")
            if qh.shape[1] != kh.shape[1]:
                raise ValueError(
                    "rope=True requires self-attention (Lq == Lk); "
                    "cross-attention has no single absolute position "
                    "stream")
            theta = float(params.get("rope_theta", 10000.0))
            if kv_mode == "decode":
                kvi = jnp.asarray(ctx.kv_index)
                # scalar index -> (1,); per-row (ragged prompts) -> (B,1)
                pos = kvi[:, None] if kvi.ndim else kvi[None]
            else:
                pos = jnp.arange(qh.shape[1], dtype=jnp.int32)
            qh = _apply_rope(qh, pos, theta)
            kh = _apply_rope(kh, pos, theta)
        if kv_mode == "prefill":
            # record per-position K/V for incremental decode; padded
            # positions hold garbage but every one is rewritten by the
            # decode step that first unmasks it. GQA caches the kv-head
            # count (the cache-size win is the point of GQA).
            W = params.get("sliding_window", 0)
            plen = getattr(ctx, "kv_prefill_len", None)
            if W and plen is not None and W < kh.shape[1]:
                # sliding window: ring-buffer cache of W slots (position
                # p lives at slot p % W) + a position track for masking —
                # O(window) HBM instead of O(max_seq). Slot s seeds with
                # the largest prompt position ≡ s (mod W); slots no
                # prompt position reached carry pos -inf (masked).
                L = kh.shape[1]
                s_idx = jnp.arange(W)
                pstar = plen - 1 - jnp.mod(plen - 1 - s_idx, W)
                valid = pstar >= 0
                gather = jnp.clip(pstar, 0, L - 1)
                pos = jnp.where(valid, pstar, -(10 ** 9))
                ctx.new_kv[name] = {
                    "k": jnp.take(kh, gather, axis=1),
                    "v": jnp.take(vh, gather, axis=1),
                    "pos": jnp.broadcast_to(pos[None, :],
                                            (kh.shape[0], W)),
                }
            else:
                ctx.new_kv[name] = {"k": kh, "v": vh}
        elif kv_mode == "decode":
            return self._emit_decode(params, weights, ctx, name, qh, kh,
                                     vh, mdt, cdt)
        # GQA: expand kv-head groups to the query head count for the
        # attention contraction (cache/weights stay at kvh heads).
        # qh.shape[2], not params["num_heads"]: under the tp attn role
        # this code runs inside shard_map with LOCAL head counts
        kh = self._expand_kv(kh, qh.shape[2])
        vh = self._expand_kv(vh, qh.shape[2])
        impl = self._impl_for(ctx, name)
        if impl == "ring" and kv_mode is None:
            if rate > 0.0:
                raise ValueError(
                    f"{name}: kernel impl 'ring' has no in-kernel "
                    f"dropout (the registry predicate rejects it; a "
                    f"forced plan must not bypass the verifier)")
            return self._emit_ring(weights, ctx, name, qh, kh, vh, mdt,
                                   cdt, causal)
        # a planned impl overrides the legacy tri-state: "flash" forces
        # the kernel path (in-kernel dropout included), "xla" forces the
        # reference path
        flash_mode = {"flash": "true", "xla": "false"}.get(
            impl, self._flash_mode(ctx))
        if self._flash_enabled(ctx, seq_len=max(qh.shape[1], kh.shape[1]),
                               mode=flash_mode) \
                and not (causal and qh.shape[1] != kh.shape[1]) \
                and not params.get("sliding_window", 0):
            # (sliding-window masking stays on the XLA path — the Pallas
            # kernel has no window support)
            # Pallas flash kernel ((b,h,s,d) layout); counter-based
            # in-kernel prob dropout runs compiled on TPU and in
            # interpret mode alike.
            # (causal cross-attention with sq != sk stays on the XLA path.)
            # In "auto" mode the dropout>0 case stays on XLA (the in-kernel
            # dropout path is opt-in via use_flash_attention="true").
            from ..kernels import flash_attention
            on_tpu = jax.default_backend() == "tpu"
            if rate > 0.0 and flash_mode != "true":
                pass  # fall through to the XLA path below
            else:
                seed = None
                if rate > 0.0:
                    seed = jax.random.randint(ctx.rng_for(name), (),
                                              0, 2 ** 31 - 1, jnp.int32)
                o = flash_attention(
                    jnp.swapaxes(qh, 1, 2).astype(mdt),
                    jnp.swapaxes(kh, 1, 2).astype(mdt),
                    jnp.swapaxes(vh, 1, 2).astype(mdt),
                    causal=causal,
                    dropout_rate=rate, dropout_seed=seed,
                    interpret=None if on_tpu else True)
                ctxv = jnp.swapaxes(o, 1, 2).astype(jnp.float32)
                out = jnp.einsum("bqhd,hde->bqe", ctxv.astype(mdt),
                                 weights["wo"].astype(mdt),
                                 preferred_element_type=jnp.float32)
                if "bo" in weights:
                    out = out + weights["bo"].astype(jnp.float32)
                return [out.astype(cdt)]

        scale = 1.0 / math.sqrt(qh.shape[-1])
        logits = jnp.einsum("bqhd,bkhd->bhqk", qh.astype(mdt),
                            kh.astype(mdt),
                            preferred_element_type=jnp.float32) * scale
        if params.get("causal", False):
            lq, lk = logits.shape[-2], logits.shape[-1]
            qpos = jnp.arange(lq)[:, None] + (lk - lq)
            kpos = jnp.arange(lk)[None, :]
            mask = kpos <= qpos
            window = params.get("sliding_window", 0)
            if window:
                # Mistral-family sliding window: each query attends the
                # last `window` positions only
                mask = jnp.logical_and(mask, kpos > qpos - window)
            logits = jnp.where(mask, logits, jnp.float32(-1e9))
        probs = jax.nn.softmax(logits, axis=-1)
        rate = params.get("dropout", 0.0)
        if ctx.training and rate > 0.0:
            rng = ctx.rng_for(name)
            keep = 1.0 - rate
            probs = jnp.where(jax.random.bernoulli(rng, keep, probs.shape),
                              probs / keep, 0.0)
        ctxv = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(mdt),
                          vh.astype(mdt),
                          preferred_element_type=jnp.float32)
        out = jnp.einsum("bqhd,hde->bqe", ctxv.astype(mdt),
                         weights["wo"].astype(mdt),
                         preferred_element_type=jnp.float32)
        if "bo" in weights:
            out = out + weights["bo"].astype(jnp.float32)
        return [out.astype(cdt)]

    @staticmethod
    def _expand_kv(x, h):
        """GQA: repeat kv-head groups up to ``h`` query heads
        ((B, L, kvh, d) -> (B, L, h, d)); identity when kvh == h."""
        kvh = x.shape[2]
        if kvh == h:
            return x
        return jnp.repeat(x, h // kvh, axis=2)

    def _emit_ring(self, weights, ctx, name, qh, kh, vh, mdt, cdt,
                   causal):
        """Ring-attention lowering: ONE shard_map over the mesh's
        dedicated ``seq`` axis. Each device holds a (B, L/deg, H, D)
        context chunk; the K/V blocks rotate ring-wise with explicit
        ``ppermute`` hops (kernels/ring_attention.py) while block
        compute hides the next block's KV transfer. The (seq, seq)
        score matrix never materializes and per-device activation
        residency drops by the seq degree — the 1/deg envelope the
        plan verifier accounts (docs/kernels.md)."""
        from ..kernels import ring_attention
        from ..utils.jax_compat import shard_map
        mesh = getattr(ctx, "mesh", None)
        ax = getattr(ctx, "seq_axis", None)
        if mesh is None or ax is None:
            raise ValueError(
                f"{name}: kernel impl 'ring' requires a mesh sequence "
                f"axis (--seq-parallel N >= 2); this compile has none")
        deg = dict(zip(mesh.axis_names, mesh.devices.shape))[ax]
        if qh.shape[1] % deg != 0:
            raise ValueError(
                f"{name}: sequence length {qh.shape[1]} is not "
                f"divisible by the seq-axis degree {deg}")

        from jax.sharding import PartitionSpec as P

        def _ring(qc, kc, vc):
            o = ring_attention(
                jnp.swapaxes(qc, 1, 2).astype(mdt),
                jnp.swapaxes(kc, 1, 2).astype(mdt),
                jnp.swapaxes(vc, 1, 2).astype(mdt),
                ax, causal=causal)
            return jnp.swapaxes(o, 1, 2)

        spec = P(None, ax, None, None)
        o = shard_map(_ring, mesh=mesh, in_specs=(spec,) * 3,
                      out_specs=spec, check_vma=False)(qh, kh, vh)
        ctxv = o.astype(jnp.float32)
        out = jnp.einsum("bqhd,hde->bqe", ctxv.astype(mdt),
                         weights["wo"].astype(mdt),
                         preferred_element_type=jnp.float32)
        if "bo" in weights:
            out = out + weights["bo"].astype(jnp.float32)
        return [out.astype(cdt)]

    def _emit_decode(self, params, weights, ctx, name, qh, kh, vh, mdt,
                     cdt):
        """Single-token decode against the KV cache: write this
        position's K/V into the cache, attend the length-1 query over
        positions <= kv_index. Exactly matches the full re-forward's row
        at kv_index (same mask, same softmax domain) — the re-forward
        path is the numerics oracle in tests/test_generate_kv.py."""
        if not params.get("causal", False):
            raise ValueError(
                "KV-cache decode requires causal self-attention")
        cache = ctx.kv_cache[name]
        idx = jnp.asarray(ctx.kv_index)
        ragged = idx.ndim == 1            # per-row positions (B,)
        ring = "pos" in cache
        if ring and ragged:
            raise ValueError(
                "ragged prompts use the full cache (generate passes "
                "prefill_len=None for vector prompt lengths)")
        if ring:
            # sliding-window ring buffer: write slot idx % W, track the
            # stored position for the validity mask
            W = cache["k"].shape[1]
            slot = jnp.mod(idx, W)
            b_ = kh.shape[0]
            pos = jax.lax.dynamic_update_slice_in_dim(
                cache["pos"], jnp.full((b_, 1), idx, cache["pos"].dtype),
                slot, axis=1)
        else:
            slot = idx
        if ragged:
            # one-hot write at each row's own position
            sel = (jnp.arange(cache["k"].shape[1])[None, :]
                   == idx[:, None])[:, :, None, None]
            k_full = jnp.where(sel, kh.astype(cache["k"].dtype),
                               cache["k"])
            v_full = jnp.where(sel, vh.astype(cache["v"].dtype),
                               cache["v"])
        else:
            k_full = jax.lax.dynamic_update_slice_in_dim(cache["k"], kh,
                                                         slot, axis=1)
            v_full = jax.lax.dynamic_update_slice_in_dim(cache["v"], vh,
                                                         slot, axis=1)
        ctx.new_kv[name] = {"k": k_full, "v": v_full}
        if ring:
            ctx.new_kv[name]["pos"] = pos
        # GQA: contract the length-1 query against the cache AT kvh
        # heads (grouped einsum) — materializing an expanded copy of
        # the whole cache every step would undo GQA's decode-bandwidth
        # win. g == 1 reduces to plain MHA.
        b_, lq_, hq, d_ = qh.shape
        kvh = k_full.shape[2]
        g = hq // kvh
        qg = qh.reshape(b_, lq_, kvh, g, d_)
        scale = 1.0 / math.sqrt(d_)
        logits = jnp.einsum("bqkgd,bmkd->bkgqm", qg.astype(mdt),
                            k_full.astype(mdt),
                            preferred_element_type=jnp.float32) * scale
        window = params.get("sliding_window", 0)
        if ring:
            # slot positions carry the mask (invalid slots hold -1e9)
            p = pos[:, None, None, None, :]
            mask = jnp.logical_and(p <= idx, p > idx - window)
        else:
            lk = k_full.shape[1]
            kpos = jnp.arange(lk)[None, None, None, None, :]
            # scalar idx broadcasts; ragged (B,) idx masks per row
            iq = idx[:, None, None, None, None] if ragged else idx
            mask = kpos <= iq
            if window:
                mask = jnp.logical_and(mask, kpos > iq - window)
        logits = jnp.where(mask, logits, jnp.float32(-1e9))
        probs = jax.nn.softmax(logits, axis=-1)
        ctxv = jnp.einsum("bkgqm,bmkd->bqkgd", probs.astype(mdt),
                          v_full.astype(mdt),
                          preferred_element_type=jnp.float32)
        ctxv = ctxv.reshape(b_, lq_, hq, d_)
        out = jnp.einsum("bqhd,hde->bqe", ctxv.astype(mdt),
                         weights["wo"].astype(mdt),
                         preferred_element_type=jnp.float32)
        if "bo" in weights:
            out = out + weights["bo"].astype(jnp.float32)
        return [out.astype(cdt)]

    def flops(self, params, in_shapes, out_shapes):
        b, lq, _ = in_shapes[0]
        lk = in_shapes[1][1]
        e = params["embed_dim"]
        h = params["num_heads"]
        kv_frac = (params.get("num_kv_heads", 0) or h) / h
        proj = (2.0 * b * lq * e * e                      # q proj
                + 2.0 * b * 2 * lk * e * e * kv_frac     # k+v (GQA)
                + 2.0 * b * lq * e * e)                  # out proj
        attn = 2.0 * b * lq * lk * e * 2
        return proj + attn

    def backward_flops_factor(self):
        return 2.0


# ---------------------------------------------------------------------------
@register
class BatchMatmulOp(OpDef):
    """Batched matmul with optional seq-length masking
    (reference ``src/ops/batch_matmul.cc``)."""
    op_type = OperatorType.OP_BATCHMATMUL

    def infer(self, params, in_shapes, in_dtypes):
        a, b = in_shapes
        if a[:-2] != b[:-2]:
            raise ValueError(
                f"batch_matmul batch dims differ: {a} vs {b}")
        if a[-1] != b[-2]:
            raise ValueError(
                f"batch_matmul contraction dims differ: {a} vs {b}")
        return [(tuple(a[:-1]) + (b[-1],), in_dtypes[0])]

    def emit(self, params, inputs, weights, ctx, name):
        a, b = inputs
        return [matmul(a, b, ctx=ctx)]

    def flops(self, params, in_shapes, out_shapes):
        a, b = in_shapes
        return 2.0 * float(np.prod(a)) * b[-1]

    def backward_flops_factor(self):
        return 2.0


@register
class MatmulOp(BatchMatmulOp):
    op_type = OperatorType.OP_MATMUL
