"""Elementwise unary/binary/scalar and reduction operators.

Reference parity: ``src/ops/element_unary.cc``, ``element_binary.cc``,
``reduce.cc``, ``mean.cc``, ``cast.cc`` — all pure jnp; XLA fuses these into
neighboring ops (the reference needed cuDNN OpTensor + custom kernels).
"""
from __future__ import annotations

from typing import Any, Dict, List

import jax
import jax.numpy as jnp
import numpy as np

from ..ffconst import DataType, OperatorType
from ..dtypes import to_jnp
from .registry import EmitCtx, OpDef, register

_UNARY_FNS = {
    OperatorType.OP_RELU: jax.nn.relu,
    OperatorType.OP_SIGMOID: jax.nn.sigmoid,
    OperatorType.OP_TANH: jnp.tanh,
    OperatorType.OP_ELU: jax.nn.elu,
    OperatorType.OP_GELU: jax.nn.gelu,
    OperatorType.OP_IDENTITY: lambda x: x,
    OperatorType.OP_EXP: jnp.exp,
    OperatorType.OP_LOG: jnp.log,
    OperatorType.OP_SQRT: jnp.sqrt,
    OperatorType.OP_RSQRT: jax.lax.rsqrt,
    OperatorType.OP_SIN: jnp.sin,
    OperatorType.OP_COS: jnp.cos,
    OperatorType.OP_CEIL: jnp.ceil,
    OperatorType.OP_ROUND: jnp.round,
    OperatorType.OP_LOGICAL_NOT: jnp.logical_not,
}

_SCALAR_FNS = {
    OperatorType.OP_SCALAR_MULTIPLY: lambda x, s: x * s,
    OperatorType.OP_SCALAR_ADD: lambda x, s: x + s,
    OperatorType.OP_SCALAR_SUB: lambda x, s: x - s,
    OperatorType.OP_SCALAR_TRUE_DIV: lambda x, s: x / s,
    OperatorType.OP_SCALAR_FLOOR_DIV: lambda x, s: jnp.floor_divide(x, s),
    OperatorType.OP_POW: lambda x, s: jnp.power(x, s),
}

_BINARY_FNS = {
    OperatorType.OP_EW_ADD: jnp.add,
    OperatorType.OP_EW_SUB: jnp.subtract,
    OperatorType.OP_EW_MUL: jnp.multiply,
    OperatorType.OP_EW_DIV: jnp.divide,
    OperatorType.OP_EW_MAX: jnp.maximum,
    OperatorType.OP_EW_MIN: jnp.minimum,
    OperatorType.OP_EW_EQUAL: jnp.equal,
    OperatorType.OP_EW_GREATER: jnp.greater,
    OperatorType.OP_EW_LESS: jnp.less,
}

_CMP_OPS = {OperatorType.OP_EW_EQUAL, OperatorType.OP_EW_GREATER,
            OperatorType.OP_EW_LESS}


class _UnaryBase(OpDef):
    def infer(self, params, in_shapes, in_dtypes):
        return [(in_shapes[0], in_dtypes[0])]

    def emit(self, params, inputs, weights, ctx, name):
        fn = _UNARY_FNS.get(self.op_type)
        if fn is not None:
            return [fn(inputs[0])]
        if self.op_type in _SCALAR_FNS:
            return [_SCALAR_FNS[self.op_type](inputs[0],
                                              params.get("scalar", 1.0))]
        raise NotImplementedError(self.op_type)


def _make_unary(op_t):
    cls = type(f"Unary_{op_t.name}", (_UnaryBase,), {"op_type": op_t})
    register(cls)


for _t in list(_UNARY_FNS) + list(_SCALAR_FNS):
    _make_unary(_t)


@register
class LeakyReluOp(_UnaryBase):
    op_type = OperatorType.OP_LEAKYRELU

    def emit(self, params, inputs, weights, ctx, name):
        return [jax.nn.leaky_relu(inputs[0],
                                  params.get("negative_slope", 0.01))]


@register
class PReluOp(OpDef):
    op_type = OperatorType.OP_PRELU

    def infer(self, params, in_shapes, in_dtypes):
        return [(in_shapes[0], in_dtypes[0])]

    def weights(self, params, in_shapes, in_dtypes):
        from ..core.tensor import WeightSpec
        from ..ffconst import InitializerType
        return [WeightSpec("alpha", (in_shapes[0][-1],), in_dtypes[0],
                           InitializerType.CONSTANT, {"value": 0.25})]

    def emit(self, params, inputs, weights, ctx, name):
        x = inputs[0]
        return [jnp.where(x >= 0, x, weights["alpha"] * x)]


class _BinaryBase(OpDef):
    def infer(self, params, in_shapes, in_dtypes):
        out = tuple(np.broadcast_shapes(in_shapes[0], in_shapes[1]))
        dt = DataType.DT_BOOLEAN if self.op_type in _CMP_OPS else in_dtypes[0]
        return [(out, dt)]

    def emit(self, params, inputs, weights, ctx, name):
        return [_BINARY_FNS[self.op_type](inputs[0], inputs[1])]


for _t in _BINARY_FNS:
    register(type(f"Binary_{_t.name}", (_BinaryBase,), {"op_type": _t}))

# OP_MUL is TASO's alias for elementwise multiply
register(type("Binary_OP_MUL", (_BinaryBase,),
              {"op_type": OperatorType.OP_MUL,
               "emit": lambda self, params, inputs, weights, ctx, name:
                   [jnp.multiply(inputs[0], inputs[1])]}))


@register
class CastOp(OpDef):
    op_type = OperatorType.OP_CAST

    def infer(self, params, in_shapes, in_dtypes):
        return [(in_shapes[0], DataType(params["dtype"]))]

    def emit(self, params, inputs, weights, ctx, name):
        return [inputs[0].astype(to_jnp(params["dtype"]))]


@register
class WhereOp(OpDef):
    op_type = OperatorType.OP_WHERE

    def infer(self, params, in_shapes, in_dtypes):
        out = tuple(np.broadcast_shapes(*in_shapes))
        return [(out, in_dtypes[1])]

    def emit(self, params, inputs, weights, ctx, name):
        return [jnp.where(*inputs)]


# ---------------------------------------------------------------------------
class _ReduceBase(OpDef):
    fn = None
    arg = False

    def infer(self, params, in_shapes, in_dtypes):
        ish = in_shapes[0]
        ndim = len(ish)
        axes = sorted(a % ndim for a in params.get("axes", range(ndim)))
        keep = params.get("keepdims", False)
        if keep:
            out = tuple(1 if i in axes else s for i, s in enumerate(ish))
        else:
            out = tuple(s for i, s in enumerate(ish) if i not in axes)
        dt = DataType.DT_INT32 if self.arg else in_dtypes[0]
        return [(out, dt)]

    def emit(self, params, inputs, weights, ctx, name):
        x = inputs[0]
        axes = tuple(a % x.ndim for a in params.get("axes", range(x.ndim)))
        keep = params.get("keepdims", False)
        if self.arg:
            if len(axes) != 1:
                raise ValueError(
                    f"arg-reduce takes exactly one axis, got {axes}")
            return [type(self).fn(x, axis=axes[0], keepdims=keep)
                    .astype(jnp.int32)]
        return [type(self).fn(x, axis=axes, keepdims=keep)]


for _t, _fn, _arg in [
    (OperatorType.OP_REDUCE_SUM, jnp.sum, False),
    (OperatorType.OP_REDUCE_MEAN, jnp.mean, False),
    (OperatorType.OP_MEAN, jnp.mean, False),
    (OperatorType.OP_REDUCE_MAX, jnp.max, False),
    (OperatorType.OP_REDUCE_MIN, jnp.min, False),
    (OperatorType.OP_REDUCE_PROD, jnp.prod, False),
    (OperatorType.OP_REDUCE_ARGMAX, jnp.argmax, True),
    (OperatorType.OP_REDUCE_ARGMIN, jnp.argmin, True),
]:
    register(type(f"Reduce_{_t.name}", (_ReduceBase,),
                  {"op_type": _t, "fn": staticmethod(_fn), "arg": _arg}))
