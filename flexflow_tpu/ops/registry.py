"""Operator registry.

Analog of the reference's ``Op`` contract (``include/flexflow/operator.h:51``):
each operator type registers an ``OpDef`` implementing

  - ``infer``   : shape/dtype inference (compute-graph level)
  - ``weights`` : declarative parameter specs (kernel/bias/...)
  - ``emit``    : JAX emission — the forward computation. Backward comes from
                  ``jax.grad`` over the whole graph (XLA fuses + schedules),
                  replacing the reference's per-op ``backward_task`` bodies.
  - ``flops`` / ``bytes`` : analytic cost hooks for the execution simulator
                  (analog of ``measure_operator_cost``; real on-chip
                  microbenchmarks refine these, see search/simulator.py).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..ffconst import DataType, OperatorType
from ..core.tensor import WeightSpec


class EmitCtx:
    """Per-trace emission context threaded through op emission."""

    def __init__(self, training: bool, rngs: Optional[Dict[str, Any]] = None,
                 state: Optional[Dict[str, Any]] = None, config=None,
                 seq_length: int = -1):
        self.training = training
        self.rngs = rngs or {}
        self.state = state or {}          # read-only collection (e.g. BN stats)
        self.new_state: Dict[str, Any] = {}  # updated state written by ops
        self.config = config
        self.seq_length = seq_length
        self.aux_losses: List[Any] = []  # e.g. MoE load-balancing terms
        # KV-cache decode plumbing (serving; the reference has no
        # generation path at all). kv_mode: None = normal forward,
        # "prefill" = full-sequence forward that also records each
        # attention layer's per-position K/V into new_kv, "decode" =
        # single-token forward reading kv_cache and writing the updated
        # buffers to new_kv. kv_index = the (traced) query position.
        # kv_prefill_len = (traced) count of real prompt positions in
        # the prefill batch — sliding-window layers seed their
        # O(window) ring-buffer cache from it.
        self.kv_mode: Optional[str] = None
        self.kv_cache: Optional[Dict[str, Any]] = None
        self.kv_index: Any = None
        self.kv_prefill_len: Any = None
        self.new_kv: Dict[str, Any] = {}
        # local-shape execution (the quantized-sync shard_map runs the
        # graph on per-device batch SHARDS): ops whose params bake
        # absolute batch-sized shapes (Reshape) rescale their batch dim
        # by the shard factor ONLY when this is set — global emission
        # keeps the exact historical error behavior
        self.local_shape: bool = False
        # searched kernel tier (kernels/registry.py): the adopted
        # strategy's per-op impl map plus the mesh context ring
        # attention lowers its shard_map against. None/empty = default
        # impls (the legacy use_flash_attention resolution).
        self.kernel_impls: Optional[Dict[str, str]] = None
        self.mesh = None                  # jax.sharding.Mesh
        self.seq_axis: Optional[str] = None

    def rng_for(self, name: str):
        return self.rngs.get(name)


class OpDef:
    op_type: OperatorType = OperatorType.OP_INVALID

    # ---- graph level ----
    def infer(self, params: Dict[str, Any],
              in_shapes: Sequence[Tuple[int, ...]],
              in_dtypes: Sequence[DataType]) -> List[Tuple[Tuple[int, ...], DataType]]:
        raise NotImplementedError

    def weights(self, params: Dict[str, Any],
                in_shapes: Sequence[Tuple[int, ...]],
                in_dtypes: Sequence[DataType]) -> List[WeightSpec]:
        return []

    # ---- execution level ----
    def emit(self, params: Dict[str, Any], inputs: List[Any],
             weights: Dict[str, Any], ctx: EmitCtx, name: str) -> List[Any]:
        raise NotImplementedError

    # ---- cost level (simulator) ----
    def flops(self, params, in_shapes, out_shapes) -> float:
        """Forward FLOPs estimate. Default: one op per output element."""
        return float(sum(int(np.prod(s)) for s in out_shapes))

    def backward_flops_factor(self) -> float:
        """bwd/fwd FLOP ratio. 2.0 for matmul-like ops (dgrad+wgrad)."""
        return 1.0


OPS: Dict[OperatorType, OpDef] = {}


def register(cls):
    inst = cls()
    if inst.op_type == OperatorType.OP_INVALID:
        raise ValueError(f"{cls.__name__} does not declare an op_type")
    OPS[inst.op_type] = inst
    return cls


def get_op_def(op_type: OperatorType) -> OpDef:
    return OPS[OperatorType(op_type)]


def bf16_enabled(ctx) -> bool:
    """Whether emission may cast f32 matmul operands to bf16 (MXU path)."""
    cfg = getattr(ctx, "config", None) if ctx is not None else None
    if cfg is None:
        return True
    return getattr(cfg, "use_bf16_compute", True) and \
        getattr(cfg, "allow_tensor_op_math_conversion", True)


def compute_dtype(ctx, ref_dtype=None):
    """bf16 when enabled and the reference dtype is f32/bf16, else f32."""
    import jax.numpy as jnp
    if bf16_enabled(ctx) and ref_dtype in (None, jnp.float32, jnp.bfloat16):
        return jnp.bfloat16
    return jnp.float32


def matmul(a, b, *, prefer_bf16: bool = True, precision=None, ctx=None):
    """MXU-friendly matmul: bf16 inputs, fp32 accumulation.

    ``ctx`` (EmitCtx) gates the bf16 cast on
    ``config.use_bf16_compute`` / ``allow_tensor_op_math_conversion``.
    Unlike the reference (math conversion OFF by default, model.cc:3491),
    the TPU-native default is ON — bf16 is the MXU's native input dtype;
    ``--f32-compute`` / ``--no-tensor-op-math-conversion`` disables it."""
    import jax.numpy as jnp
    if ctx is not None:
        prefer_bf16 = prefer_bf16 and bf16_enabled(ctx)
    if prefer_bf16 and a.dtype in (jnp.float32, jnp.bfloat16):
        a16 = a.astype(jnp.bfloat16)
        b16 = b.astype(jnp.bfloat16)
        out = jnp.matmul(a16, b16, preferred_element_type=jnp.float32)
        return out.astype(a.dtype) if a.dtype != jnp.float32 else out
    # f32-compute path: still accumulate in f32 for low-precision operands
    if a.dtype == jnp.bfloat16:
        return jnp.matmul(a, b, preferred_element_type=jnp.float32) \
            .astype(a.dtype)
    return jnp.matmul(a, b)
