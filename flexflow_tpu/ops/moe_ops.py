"""Mixture-of-Experts operator family: TopK routing, Group_by dispatch,
Aggregate combine, Cache.

Reference parity: ``src/ops/{group_by,aggregate,aggregate_spec,cache}.cc``
(custom expert-routing CUDA kernels, alpha capacity factor, lambda_bal
load balancing). TPU-native design: GShard-style dense dispatch/combine
einsums over a static capacity — one-hot matmuls ride the MXU, shapes stay
static for XLA, and the expert dimension shards cleanly over a mesh axis
(expert parallelism).

Shapes (numpy order):
  group_by:  input (B, D), assign (B, K) int  ->  n tensors (C, D),
             C = ceil(alpha * K * B / n)
  aggregate: [gate_preds (B,K), gate_assign (B,K), true_assign (B,K),
              full_gate_preds (B,n), exp_pred_0 (C,Do), ... exp_pred_{n-1}]
             -> (B, Do)
"""
from __future__ import annotations

import math
from typing import Any, Dict, List

import jax
import jax.numpy as jnp
import numpy as np

from ..ffconst import DataType, OperatorType
from .registry import EmitCtx, OpDef, register, compute_dtype


def _capacity(params, batch: int, k: int) -> int:
    n = params["n"]
    alpha = params.get("alpha", 1.0)
    return int(math.ceil(alpha * k * batch / n))


def _dispatch_mask(assign, n: int, capacity: int):
    """(B, K) int assignments -> (T=B*K, n, C) one-hot dispatch tensor.

    Position of each (token, choice) within its expert's buffer is its
    running count in flattened token order; overflow tokens are dropped —
    matching the reference kernels' first-come capacity policy
    (``group_by.cu`` expert_rows bound).
    """
    b, k = assign.shape
    flat = assign.reshape(-1).astype(jnp.int32)          # (T,)
    onehot = jax.nn.one_hot(flat, n, dtype=jnp.int32)    # (T, n)
    pos = jnp.cumsum(onehot, axis=0) * onehot - 1        # (T, n): slot per tok
    in_cap = (pos < capacity) & (pos >= 0)
    poscap = jnp.where(in_cap, pos, 0)
    poshot = jax.nn.one_hot(poscap.sum(-1), capacity, dtype=jnp.float32)
    mask = (onehot.astype(jnp.float32) * in_cap.astype(jnp.float32))
    return mask[:, :, None] * poshot[:, None, :]          # (T, n, C)


@register
class GroupByOp(OpDef):
    op_type = OperatorType.OP_GROUP_BY

    def infer(self, params, in_shapes, in_dtypes):
        (b, d), (b2, k) = in_shapes[0], in_shapes[1]
        if b != b2:
            raise ValueError(
                f"group_by input/assign batch dims differ: {in_shapes}")
        c = _capacity(params, b, k)
        return [((c, d), in_dtypes[0])] * params["n"]

    def emit(self, params, inputs, weights, ctx, name):
        x, assign = inputs
        b, k = assign.shape
        n = params["n"]
        c = _capacity(params, b, k)
        disp = _dispatch_mask(assign, n, c)               # (T, n, C)
        xr = jnp.repeat(x, k, axis=0)                     # (T, D) token per slot
        mdt = compute_dtype(ctx, x.dtype)
        buf = jnp.einsum("tec,td->ecd", disp.astype(mdt),
                         xr.astype(mdt),
                         preferred_element_type=jnp.float32)
        buf = buf.astype(x.dtype)
        return [buf[e] for e in range(n)]


@register
class AggregateOp(OpDef):
    """Combine expert outputs weighted by gate probabilities; adds the
    lambda_bal load-balancing auxiliary loss (the reference injects an
    equivalent term directly into gate gradients in ``aggregate.cu``)."""
    op_type = OperatorType.OP_AGGREGATE

    def infer(self, params, in_shapes, in_dtypes):
        b = in_shapes[0][0]
        out_dim = in_shapes[4][-1]
        return [((b, out_dim), in_dtypes[4])]

    def emit(self, params, inputs, weights, ctx, name):
        gate_preds, gate_assign = inputs[0], inputs[1]
        full_gate_preds = inputs[3]
        exp_preds = inputs[4:]
        n = params["n"]
        b, k = gate_assign.shape
        c = exp_preds[0].shape[0]
        disp = _dispatch_mask(gate_assign, n, c)          # (T, n, C)
        w = gate_preds.reshape(-1)                        # (T,)
        combine = disp * w[:, None, None]
        stacked = jnp.stack(exp_preds, axis=0)            # (n, C, Do)
        mdt = compute_dtype(ctx, exp_preds[0].dtype)
        out = jnp.einsum("tec,ecd->td", combine.astype(mdt),
                         stacked.astype(mdt),
                         preferred_element_type=jnp.float32)
        out = out.reshape(b, k, -1).sum(axis=1).astype(exp_preds[0].dtype)
        # GShard-style load-balance aux loss: n * sum_e(frac_tokens_e * mean_gate_e)
        lam = params.get("lambda_bal", 0.0)
        if lam > 0.0 and full_gate_preds is not None:
            frac = jnp.mean(
                jax.nn.one_hot(gate_assign[:, 0], n, dtype=jnp.float32), axis=0)
            mean_gate = jnp.mean(jax.nn.softmax(full_gate_preds, -1), axis=0)
            ctx.aux_losses.append(lam * n * jnp.sum(frac * mean_gate))
        return [out]


@register
class AggregateSpecOp(AggregateOp):
    """Aggregate variant that ignores gate weighting for the expert pass-
    through (reference ``aggregate_spec.cc`` — used with Cache for MoE
    speculation). Same output shape as Aggregate."""
    op_type = OperatorType.OP_AGG_SPEC

    def emit(self, params, inputs, weights, ctx, name):
        inputs = list(inputs)
        inputs[0] = jnp.ones_like(inputs[0]) / inputs[0].shape[-1]
        return super().emit(params, inputs, weights, ctx, name)


@register
class CacheOp(OpDef):
    """Rolling tensor cache (reference ``src/ops/cache.cc``): stores the
    input in the state collection; with a score trigger the runtime's
    recompile hook can switch to serving the cached value."""
    op_type = OperatorType.OP_CACHE

    def infer(self, params, in_shapes, in_dtypes):
        return [(in_shapes[0], in_dtypes[0])]

    def state_spec(self, params, in_shapes, in_dtypes):
        return {"cached": (in_shapes[0], in_dtypes[0])}

    def emit(self, params, inputs, weights, ctx, name):
        (x,) = inputs
        st = ctx.state.get(name)
        use_cached = params.get("use_cached", False)
        if st is not None:
            ctx.new_state[name] = {"cached": x}
            if use_cached and not ctx.training:
                return [st["cached"]]
        return [x]
