"""Tensor-layout operators: reshape/transpose/concat/split/pad/slice/topk/...

Reference parity: ``src/ops/{reshape,transpose,reverse,concat,split,pad,
topk,gather,noop}.cc`` — the reference needed custom copy/permute CUDA
kernels (cuTT-style); on TPU these are XLA ops the compiler lays out.
"""
from __future__ import annotations

from typing import Any, Dict, List

import jax
import jax.numpy as jnp
import numpy as np

from ..ffconst import DataType, OperatorType
from .registry import EmitCtx, OpDef, register


@register
class NoOp(OpDef):
    op_type = OperatorType.OP_NOOP

    def infer(self, params, in_shapes, in_dtypes):
        return [(in_shapes[0], in_dtypes[0])]

    def emit(self, params, inputs, weights, ctx, name):
        return [inputs[0]]


@register
class InputOp(NoOp):
    op_type = OperatorType.OP_INPUT


@register
class WeightOp(NoOp):
    op_type = OperatorType.OP_WEIGHT


@register
class ReshapeOp(OpDef):
    op_type = OperatorType.OP_RESHAPE

    def infer(self, params, in_shapes, in_dtypes):
        shape = tuple(params["shape"])
        vol_in = int(np.prod(in_shapes[0]))
        if -1 in shape:
            known = -int(np.prod(shape))
            shape = tuple(vol_in // known if s == -1 else s for s in shape)
        if int(np.prod(shape)) != vol_in:
            raise ValueError(
                f"reshape to {shape} does not preserve the element "
                f"count of {in_shapes[0]}")
        return [(shape, in_dtypes[0])]

    def emit(self, params, inputs, weights, ctx, name):
        shape = tuple(params["shape"])
        x = inputs[0]
        vol = int(np.prod(shape))
        if getattr(ctx, "local_shape", False) and -1 not in shape \
                and shape and vol != x.size:
            # local-shape execution (ctx.local_shape — the quantized-
            # sync shard_map runs the graph on batch SHARDS): the
            # recorded target shape is global, so rescale its batch dim
            # by the shard factor. Scoped to that context only: global
            # emission keeps the exact historical error on any
            # volume-mismatched reshape.
            rest = vol // shape[0] if shape[0] > 0 else 0
            if rest > 0 and x.size % rest == 0:
                shape = (x.size // rest,) + shape[1:]
        return [x.reshape(shape)]


@register
class TransposeOp(OpDef):
    op_type = OperatorType.OP_TRANSPOSE

    def infer(self, params, in_shapes, in_dtypes):
        perm = params["perm"]
        return [(tuple(in_shapes[0][p] for p in perm), in_dtypes[0])]

    def emit(self, params, inputs, weights, ctx, name):
        return [jnp.transpose(inputs[0], params["perm"])]


@register
class ReverseOp(OpDef):
    op_type = OperatorType.OP_REVERSE

    def infer(self, params, in_shapes, in_dtypes):
        return [(in_shapes[0], in_dtypes[0])]

    def emit(self, params, inputs, weights, ctx, name):
        return [jnp.flip(inputs[0], axis=params["axis"])]


@register
class ConcatOp(OpDef):
    op_type = OperatorType.OP_CONCAT

    def infer(self, params, in_shapes, in_dtypes):
        axis = params["axis"] % len(in_shapes[0])
        out = list(in_shapes[0])
        out[axis] = sum(s[axis] for s in in_shapes)
        return [(tuple(out), in_dtypes[0])]

    def emit(self, params, inputs, weights, ctx, name):
        return [jnp.concatenate(inputs, axis=params["axis"])]


@register
class SplitOp(OpDef):
    op_type = OperatorType.OP_SPLIT

    def infer(self, params, in_shapes, in_dtypes):
        ish = in_shapes[0]
        axis = params["axis"] % len(ish)
        sizes = params["sizes"]
        if sum(sizes) != ish[axis]:
            raise ValueError(
                f"split sizes {sizes} do not sum to dim {axis} of "
                f"{ish}")
        outs = []
        for sz in sizes:
            o = list(ish)
            o[axis] = sz
            outs.append((tuple(o), in_dtypes[0]))
        return outs

    def emit(self, params, inputs, weights, ctx, name):
        x = inputs[0]
        axis = params["axis"] % x.ndim
        idx = np.cumsum(params["sizes"])[:-1].tolist()
        return list(jnp.split(x, idx, axis=axis))


@register
class SqueezeOp(OpDef):
    op_type = OperatorType.OP_SQUEEZE

    def infer(self, params, in_shapes, in_dtypes):
        ish = in_shapes[0]
        axes = [a % len(ish) for a in params["axes"]]
        out = tuple(s for i, s in enumerate(ish) if i not in axes)
        return [(out, in_dtypes[0])]

    def emit(self, params, inputs, weights, ctx, name):
        x = inputs[0]
        return [jnp.squeeze(x, axis=tuple(a % x.ndim for a in params["axes"]))]


@register
class UnsqueezeOp(OpDef):
    op_type = OperatorType.OP_UNSQUEEZE

    def infer(self, params, in_shapes, in_dtypes):
        out = list(in_shapes[0])
        for a in sorted(params["axes"]):
            out.insert(a, 1)
        return [(tuple(out), in_dtypes[0])]

    def emit(self, params, inputs, weights, ctx, name):
        return [jnp.expand_dims(inputs[0], tuple(params["axes"]))]


@register
class PadOp(OpDef):
    op_type = OperatorType.OP_PAD

    def infer(self, params, in_shapes, in_dtypes):
        pads = params["pads"]  # [(lo, hi)] * ndim
        out = tuple(s + lo + hi for s, (lo, hi) in zip(in_shapes[0], pads))
        return [(out, in_dtypes[0])]

    def emit(self, params, inputs, weights, ctx, name):
        return [jnp.pad(inputs[0], params["pads"],
                        constant_values=params.get("value", 0.0))]


@register
class SliceOp(OpDef):
    op_type = OperatorType.OP_SLICE

    def infer(self, params, in_shapes, in_dtypes):
        ish = in_shapes[0]
        starts, ends = params["starts"], params["ends"]
        axes = params.get("axes", list(range(len(starts))))
        out = list(ish)
        for s, e, a in zip(starts, ends, axes):
            n = ish[a % len(ish)]
            s = min(s if s >= 0 else s + n, n)
            e = min(e if e >= 0 else e + n, n)
            out[a % len(ish)] = max(0, e - s)
        return [(tuple(out), in_dtypes[0])]

    def emit(self, params, inputs, weights, ctx, name):
        x = inputs[0]
        starts, ends = params["starts"], params["ends"]
        axes = params.get("axes", list(range(len(starts))))
        idx = [slice(None)] * x.ndim
        for s, e, a in zip(starts, ends, axes):
            idx[a % x.ndim] = slice(s, e)
        return [x[tuple(idx)]]


@register
class TopKOp(OpDef):
    """TopK (reference ``src/ops/topk.cc`` heap kernels → jax.lax.top_k)."""
    op_type = OperatorType.OP_TOPK

    def infer(self, params, in_shapes, in_dtypes):
        k = params["k"]
        out = tuple(in_shapes[0][:-1]) + (k,)
        return [(out, in_dtypes[0]), (out, DataType.DT_INT32)]

    def emit(self, params, inputs, weights, ctx, name):
        vals, idx = jax.lax.top_k(inputs[0], params["k"])
        return [vals, idx.astype(jnp.int32)]


@register
class GatherOp(OpDef):
    """torch.gather semantics (reference ``src/ops/gather.cc``)."""
    op_type = OperatorType.OP_GATHER

    def infer(self, params, in_shapes, in_dtypes):
        return [(in_shapes[1], in_dtypes[0])]

    def emit(self, params, inputs, weights, ctx, name):
        x, index = inputs
        dim = params.get("dim", 0) % x.ndim
        return [jnp.take_along_axis(x, index.astype(jnp.int32), axis=dim)]


@register
class ShapeOp(OpDef):
    op_type = OperatorType.OP_SHAPE

    def infer(self, params, in_shapes, in_dtypes):
        return [((len(in_shapes[0]),), DataType.DT_INT32)]

    def emit(self, params, inputs, weights, ctx, name):
        return [jnp.asarray(inputs[0].shape, dtype=jnp.int32)]


@register
class SizeOp(OpDef):
    op_type = OperatorType.OP_SIZE

    def infer(self, params, in_shapes, in_dtypes):
        return [((), DataType.DT_INT32)]

    def emit(self, params, inputs, weights, ctx, name):
        return [jnp.asarray(inputs[0].size, dtype=jnp.int32)]


@register
class ResizeOp(OpDef):
    """Nearest/linear image resize (ONNX Resize)."""
    op_type = OperatorType.OP_RESIZE

    def infer(self, params, in_shapes, in_dtypes):
        return [(tuple(params["size"]), in_dtypes[0])]

    def emit(self, params, inputs, weights, ctx, name):
        return [jax.image.resize(inputs[0], tuple(params["size"]),
                                 method=params.get("method", "nearest"))]
