"""Quantized gradient collectives: int8/fp8 sync as a searched choice.

EQuARX (PAPERS.md, arXiv 2506.17615) shows that an all-reduce whose
wire payload is int8/fp8 with per-chunk scaling and error-feedback
recovers most of the slow-fabric bandwidth at negligible accuracy cost.
This module makes that a first-class, *searched* decision:

  - **kernels** — in-jit quantize → collective → dequantize, built from
    portable collectives (``all_to_all`` reduce-scatter leg +
    ``all_gather``) so the wire bytes really shrink: per-chunk absolute-
    max scaling (:data:`QSYNC_CHUNK` elements per scale), round-to-
    nearest int8 or a direct fp8 cast, and **error feedback** — each
    device carries the quantization error it withheld as a residual and
    re-injects it next step, so the bias never accumulates;
  - **plan** — :class:`QsyncPlan` records, per gradient tensor, the
    wire dtype of each *phase* of its sync (PR 9's reduction trees make
    the DCN leg an explicit phase: quantize it, keep the ICI legs
    full-precision). Planned by :func:`plan_qsync` from the same
    calibrated cost model that prices the rest of the search, gated by
    ``FFConfig.quantized_collectives`` (off/auto/dcn_only/all),
    serialized with the strategy (``--import`` honors it verbatim) and
    statically checked by ``analysis/plan_verifier``;
  - **runtime state** — the error-feedback residual is sharding-aware
    runtime state: one leaf of shape ``(degree,) + grad.shape`` per
    quantized tensor, dim 0 sharded over the sync axes so each device
    holds exactly its own residual. It rides in the optimizer-state
    tree under :data:`RESIDUAL_SLOT` (stripped before the optimizer
    update), checkpoints with it, and survives elastic world changes by
    **sum-folding** (:func:`refit_residual`) — merging devices sums
    their withheld gradient mass, so no error is lost or double-counted.

The runtime path executes only on plans it can honor exactly
(:func:`runtime_schedule`): pure data-parallel programs whose weights
are replicated. Everything else keeps the implicit GSPMD sync — and
with the flag off (the default) nothing here runs at all, pinned
bit-exact by ``tools/quantized_sync_smoke.py``.
"""
from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..obs import events as obs_events
from ..parallel.placement import (QSYNC_CHUNK, WIRE_ITEMSIZE,
                                  wire_byte_scale)

__all__ = ["RESIDUAL_SLOT", "QsyncPlan", "resolve_qsync_mode",
           "wire_available", "quantize_chunked", "dequantize_chunked",
           "quantized_all_reduce", "phased_sync", "plan_qsync",
           "runtime_schedule", "init_residuals", "refit_residual",
           "sharded_grads"]

#: reserved optimizer-state slot carrying the error-feedback residuals —
#: stripped before ``optimizer.update`` (executor), checkpointed with
#: the rest of the state, special-cased by restore for world changes
RESIDUAL_SLOT = "qsync_residual"

QSYNC_MODES = ("off", "auto", "dcn_only", "all")

_QMAX = {"int8": 127.0}


def _wire_jnp(wire: str):
    import jax.numpy as jnp
    return {"int8": jnp.int8,
            "float8_e4m3": jnp.float8_e4m3fn,
            "float8_e5m2": jnp.float8_e5m2}[wire]


def wire_available(wire: str) -> bool:
    """Whether this wire dtype exists in the installed jax/ml_dtypes."""
    try:
        _wire_jnp(wire)
        return True
    except Exception:  # noqa: BLE001 — absent dtype = unavailable
        return False


def resolve_qsync_mode(cfg=None) -> str:
    """Resolve the quantized-collectives opt-in: the
    ``FF_QUANTIZED_COLLECTIVES`` env var wins when set (how the smokes
    and bench drive subprocesses), else ``FFConfig.
    quantized_collectives``; default ``"off"`` — the bit-exact path.
    ``"disable"`` (the ``--no-quantized-collectives`` spelling) also
    resolves off — see :func:`qsync_disabled` for its stronger
    meaning."""
    env = os.environ.get("FF_QUANTIZED_COLLECTIVES", "").strip().lower()
    mode = env or str(getattr(cfg, "quantized_collectives", "off")
                      or "off").lower()
    if mode in ("", "false", "no", "0", "disable", "disabled"):
        mode = "off"
    if mode in ("true", "yes", "1", "on"):
        mode = "auto"
    if mode not in QSYNC_MODES:
        raise ValueError(f"unknown quantized_collectives mode {mode!r} "
                         f"(expected one of {QSYNC_MODES})")
    return mode


def qsync_disabled(cfg=None) -> bool:
    """True when quantization is EXPLICITLY disabled — the env var set
    to an off value, or ``quantized_collectives="disable"`` (what
    ``--no-quantized-collectives`` parses to). Distinct from the plain
    default ``"off"``: an imported strategy's qsync plan is honored
    verbatim under the default, but an explicit disable STRIPS it
    (``FFModel._plan_qsync``) so a user can A/B an exported quantized
    strategy against full precision."""
    env = os.environ.get("FF_QUANTIZED_COLLECTIVES", "").strip().lower()
    if env in ("off", "false", "no", "0", "disable", "disabled"):
        return True
    return str(getattr(cfg, "quantized_collectives", "") or "").lower() \
        in ("disable", "disabled")


def resolve_qsync_wire(cfg=None) -> str:
    """Wire dtype for quantized legs: ``FF_QSYNC_WIRE`` / ``FFConfig.
    qsync_wire``, default int8 (fp8 variants fall back to int8 when the
    installed jax lacks the dtype)."""
    wire = os.environ.get("FF_QSYNC_WIRE", "").strip().lower() \
        or str(getattr(cfg, "qsync_wire", "int8") or "int8").lower()
    if wire not in WIRE_ITEMSIZE:
        raise ValueError(f"unknown qsync wire dtype {wire!r} "
                         f"(expected one of {sorted(WIRE_ITEMSIZE)})")
    if not wire_available(wire):
        return "int8"
    return wire


# ---------------------------------------------------------------------------
# kernels (in-jit; shard_map-body helpers)
# ---------------------------------------------------------------------------

def quantize_chunked(x, wire: str):
    """Per-chunk absolute-max quantization of a float array whose last
    dim is the chunk dim: returns ``(q, scale)`` with ``q`` in the wire
    dtype and ``scale`` float32 broadcastable over the chunk. int8
    rounds to nearest (±127 range); fp8 is a direct cast after
    scaling to the format's finite max."""
    import jax.numpy as jnp
    x = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
    if wire == "int8":
        qmax = _QMAX["int8"]
    else:
        qmax = float(jnp.finfo(_wire_jnp(wire)).max)
    scale = jnp.where(amax > 0, amax / qmax, 1.0).astype(jnp.float32)
    y = x / scale
    if wire == "int8":
        q = jnp.clip(jnp.round(y), -qmax, qmax).astype(jnp.int8)
    else:
        q = y.astype(_wire_jnp(wire))
    return q, scale


def dequantize_chunked(q, scale):
    import jax.numpy as jnp
    return q.astype(jnp.float32) * scale


def _group_index(axes: Sequence[str], sizes: Dict[str, int]):
    """Flat index of this device within the ``axes`` product group, in
    the same (first-axis-major) order jax's tuple-axis collectives
    enumerate the group."""
    import jax
    idx = None
    for a in axes:
        k = jax.lax.axis_index(a)
        idx = k if idx is None else idx * sizes[a] + k
    return idx


def quantized_all_reduce(x, axes: Tuple[str, ...], wire: str,
                         degree: int, sizes: Dict[str, int],
                         residual=None):
    """Error-feedback quantized all-reduce (SUM) over ``axes`` — call
    inside a shard_map body.

    Structure (EQuARX): quantize the full local vector per chunk →
    ``all_to_all`` the wire payload (the reduce-scatter leg: device i
    receives every device's chunks of segment i) → dequantize +
    accumulate in fp32 → requantize the reduced segment →
    ``all_gather`` the wire payload → dequantize. Only quantized bytes
    (plus one fp32 scale per :data:`QSYNC_CHUNK` elements) ever cross
    the fabric.

    Error feedback: ``residual`` (this device's withheld error from the
    previous step, same shape as ``x``) is added before quantization;
    the returned residual is the new local quantization error, with the
    owner's requantization error of the gather leg folded into its own
    segment. Returns ``(sum_over_group, new_residual)``.
    """
    import jax
    import jax.numpy as jnp
    shape = x.shape
    flat = x.astype(jnp.float32).ravel()
    if residual is not None:
        flat = flat + residual.astype(jnp.float32).ravel()
    n = flat.size
    unit = degree * QSYNC_CHUNK
    pad = (-n) % unit
    if pad:
        flat_p = jnp.concatenate([flat, jnp.zeros((pad,), jnp.float32)])
    else:
        flat_p = flat
    seg = flat_p.reshape(degree, -1, QSYNC_CHUNK)      # (d, k, C)
    k = seg.shape[1]
    q, s = quantize_chunked(seg, wire)
    r_new = flat_p - dequantize_chunked(q, s).ravel()
    # reduce-scatter leg: after all_to_all, this device holds every
    # group member's chunks of ITS segment
    q2 = jax.lax.all_to_all(q, axes, 0, 0)
    s2 = jax.lax.all_to_all(s, axes, 0, 0)
    red = jnp.sum(dequantize_chunked(q2, s2), axis=0)  # (k, C)
    qr, sr = quantize_chunked(red, wire)
    # the gather leg's requantization error belongs to the segment
    # OWNER (this device) — fold it into the residual at its own range
    gerr = (red - dequantize_chunked(qr, sr)).ravel()
    start = _group_index(axes, sizes) * (k * QSYNC_CHUNK)
    cur = jax.lax.dynamic_slice(r_new, (start,), (k * QSYNC_CHUNK,))
    r_new = jax.lax.dynamic_update_slice(r_new, cur + gerr, (start,))
    ag_q = jax.lax.all_gather(qr, axes, tiled=True)    # (d*k, C)
    ag_s = jax.lax.all_gather(sr, axes, tiled=True)
    out = dequantize_chunked(ag_q, ag_s).ravel()[:n].reshape(shape)
    return out, r_new[:n].reshape(shape)


def _add_at(buf, delta, start):
    """buf[start:start+len(delta)] += delta with a traced offset."""
    import jax
    cur = jax.lax.dynamic_slice(buf, (start,), (delta.shape[0],))
    return jax.lax.dynamic_update_slice(buf, cur + delta, (start,))


def phased_sync(x, phases: Sequence[Tuple[Tuple[str, ...],
                                          Optional[str]]],
                sizes: Dict[str, int], residual=None):
    """Gradient MEAN over the ordered inner→outer ``phases`` — call
    inside a shard_map body. Each phase is ``(axes, wire)``:
    ``wire=None`` is full-precision, a wire name a quantized leg.

    Multi-phase syncs execute as the real hierarchical tree — inner
    legs reduce-scatter (so the outer fabric only ever carries the
    tier-reduced volume, PR 9's two-phase shape), the outermost leg
    all-reduces, then the inner legs all-gather back — with each leg's
    payload in its phase's wire dtype. Error feedback: ``residual``
    (this device's withheld error, pre-sync gradient space) is added up
    front; every quantized leg's local error is accumulated back at the
    offset of the window this device owned at that depth, so next
    step's staged reduction re-injects each error exactly where (and
    exactly once) it was withheld. Returns ``(mean, new_residual)`` —
    ``new_residual`` is None when no phase quantizes."""
    import jax
    import jax.numpy as jnp
    shape = x.shape
    active: List[Tuple[Tuple[str, ...], Optional[str], int]] = []
    total = 1
    for axes, wire in phases:
        d = 1
        for a in axes:
            d *= int(sizes.get(a, 1))
        if d <= 1:
            continue
        active.append((tuple(axes), wire, d))
        total *= d
    if not active:
        return x, residual
    any_q = any(w for _, w, _ in active)
    if not any_q:
        out = x.astype(jnp.float32)
        for axes, _w, _d in active:
            out = jax.lax.psum(out, axes)
        return (out / total).astype(x.dtype), residual
    if len(active) == 1:
        axes, wire, d = active[0]
        if wire is None:
            return jax.lax.psum(x.astype(jnp.float32), axes) / total, \
                residual
        out, r_new = quantized_all_reduce(
            x, axes, wire, d, sizes, residual=residual)
        return out / total, r_new
    # staged hierarchical sync
    flat = x.astype(jnp.float32).ravel()
    n = flat.size
    if residual is not None:
        flat = flat + residual.astype(jnp.float32).ravel()
    unit = total * QSYNC_CHUNK
    pad = (-n) % unit
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), jnp.float32)])
    err = jnp.zeros_like(flat)
    cur = flat
    start = jnp.int32(0)      # offset of this device's window in flat
    starts: List[Any] = []    # window offset stack, one per down-leg
    down, (last_axes, last_wire, last_d) = active[:-1], active[-1]
    for axes, wire, d in down:
        seglen = cur.shape[0] // d
        gi = _group_index(axes, sizes)
        if wire is None:
            cur = jax.lax.psum_scatter(cur, axes, scatter_dimension=0,
                                       tiled=True)
        else:
            q, s = quantize_chunked(
                cur.reshape(d, -1, QSYNC_CHUNK), wire)
            e = cur - dequantize_chunked(q, s).ravel()
            err = _add_at(err, e, start)
            q2 = jax.lax.all_to_all(q, axes, 0, 0)
            s2 = jax.lax.all_to_all(s, axes, 0, 0)
            cur = jnp.sum(dequantize_chunked(q2, s2), axis=0).ravel()
        start = start + gi * seglen
        starts.append(start)
    if last_wire is None:
        cur = jax.lax.psum(cur, last_axes)
    else:
        cur, e = quantized_all_reduce(cur, last_axes, last_wire,
                                      last_d, sizes, residual=None)
        err = _add_at(err, e.ravel(), start)
    deeper = []               # degree product of the phases after leg k
    p = last_d
    for _axes, _wire, d in reversed(down):
        deeper.insert(0, p)
        p *= d
    for (axes, wire, d), st, dp in zip(reversed(down), reversed(starts),
                                       reversed(deeper)):
        if wire is None:
            cur = jax.lax.all_gather(cur, axes, tiled=True)
        else:
            # the requantization error of the gather payload belongs at
            # the window held going INTO this leg — and at this point
            # the ``dp`` devices sharing that window hold IDENTICAL
            # reduced values, so the identical error is scaled by 1/dp:
            # next step's staged reduction sums the copies back to
            # exactly one error mass
            q, s = quantize_chunked(
                cur.reshape(-1, QSYNC_CHUNK), wire)
            e = cur - dequantize_chunked(q, s).ravel()
            err = _add_at(err, e / dp, st)
            qg = jax.lax.all_gather(q, axes, tiled=True)
            sg = jax.lax.all_gather(s, axes, tiled=True)
            cur = dequantize_chunked(qg, sg).ravel()
    out = (cur[:n] / total).reshape(shape)
    return out, err[:n].reshape(shape)


# ---------------------------------------------------------------------------
# the per-tensor / per-phase plan
# ---------------------------------------------------------------------------

class QsyncPlan:
    """Per-tensor, per-phase wire-dtype plan for gradient sync.

    ``decisions`` maps layer name -> weight name -> a record dict::

        {"wire": "int8" | "float8_e4m3" | "float8_e5m2" | None,
         "phases": [{"axes": [..], "tier": str, "wire": str | None}],
         "baseline_s": float,     # predicted full-precision sync cost
         "quantized_s": float}    # predicted cost of this plan

    ``wire=None`` (or no quantized phase) keeps that tensor full-
    precision. Serializes with the strategy (``search/serialization``)
    so ``--import`` honors the decision verbatim, and is statically
    checked by ``analysis/plan_verifier``'s qsync pass.
    """

    def __init__(self, decisions: Optional[Dict[str, Dict[str, Dict]]]
                 = None, mode: str = "auto", wire: str = "int8"):
        self.decisions: Dict[str, Dict[str, Dict]] = decisions or {}
        self.mode = mode
        self.wire = wire

    def record_for(self, layer: str, wname: str) -> Optional[Dict]:
        return self.decisions.get(layer, {}).get(wname)

    def phases_for(self, layer: str, wname: str
                   ) -> Optional[List[Tuple[Tuple[str, ...],
                                            Optional[str]]]]:
        rec = self.record_for(layer, wname)
        if rec is None:
            return None
        return [(tuple(p.get("axes") or ()), p.get("wire"))
                for p in rec.get("phases", ())]

    def quantized_params(self) -> List[Tuple[str, str]]:
        out = []
        for lname, ws in self.decisions.items():
            for wname, rec in ws.items():
                if any(p.get("wire") for p in rec.get("phases", ())):
                    out.append((lname, wname))
        return out

    def __len__(self) -> int:
        return sum(len(ws) for ws in self.decisions.values())

    def __bool__(self) -> bool:
        return len(self.quantized_params()) > 0

    def summary(self) -> Dict[str, Any]:
        q = self.quantized_params()
        return {
            "mode": self.mode, "wire": self.wire,
            "n_params": len(self), "n_quantized": len(q),
            "baseline_s_total": sum(
                rec.get("baseline_s", 0.0)
                for ws in self.decisions.values()
                for rec in ws.values()),
            "quantized_s_total": sum(
                rec.get("quantized_s", 0.0)
                for ws in self.decisions.values()
                for rec in ws.values()),
        }

    def to_json(self) -> Dict[str, Any]:
        return {"mode": self.mode, "wire": self.wire,
                "decisions": self.decisions}

    @classmethod
    def from_json(cls, doc: Optional[Dict[str, Any]]
                  ) -> Optional["QsyncPlan"]:
        if not doc:
            return None
        return cls(dict(doc.get("decisions", {})),
                   mode=str(doc.get("mode", "auto")),
                   wire=str(doc.get("wire", "int8")))


def _tier_phases(dmesh, strategy) -> List[Tuple[Tuple[str, ...], str]]:
    """Mesh axes grouped by hardware tier, innermost tier first — the
    phase skeleton both the planner and the runtime share. The adopted
    strategy's ``axis_tiers`` is the ground truth when present (it is
    what the verifier checks against); a tierless machine is one "ici"
    phase spanning every axis."""
    from ..parallel.topology import TIER_RANK
    sizes = dict(dmesh.axis_sizes)
    tiers = dict(getattr(strategy, "axis_tiers", None) or {})
    if not tiers:
        try:
            tiers = dict(dmesh.axis_tiers)
        except Exception:  # noqa: BLE001 — tierless machine
            tiers = {}
    by_tier: Dict[str, List[str]] = {}
    for a in sizes:
        by_tier.setdefault(tiers.get(a, "ici"), []).append(a)
    return [(tuple(by_tier[t]), t)
            for t in sorted(by_tier, key=lambda t: TIER_RANK.get(t, 99))]


def plan_qsync(strategy, layers: Sequence, dmesh, cost_model, *,
               mode: str = "auto", wire: str = "int8"
               ) -> Optional["QsyncPlan"]:
    """Plan per-tensor, per-phase gradient-sync precision for an
    adopted strategy.

    Scores every trainable replicated-weight parameter's sync at full
    precision vs with its slow legs quantized, through
    ``OpCostModel.quantized_sync_quote`` (the calibrated wire-dtype
    rows / itemsize-scaled fallback — the same pricing the search used
    with the policy attached). The accuracy-risk gate is structural:
    only the *gradient all-reduce of replicated weights* may quantize —
    sharded weights' per-op collectives (replicated-math seams) and
    bank / place-group / pipeline state always stay full-precision.
    Returns None when nothing quantizes."""
    import time
    t0 = time.perf_counter()
    if mode == "off":
        return None
    if getattr(strategy, "pipeline", None) is not None:
        return None
    axis_sizes = dict(dmesh.axis_sizes)
    n_dev = 1
    for s in axis_sizes.values():
        n_dev *= s
    if n_dev <= 1:
        return None
    from ..dtypes import itemsize
    from ..ops import ensure_weight_specs
    from ..runtime.zero import spec_degree
    grouped: set = set()
    for bk in getattr(strategy, "banks", None) or ():
        grouped.update(bk.members)
    for pg in getattr(strategy, "place_groups", None) or ():
        grouped.update(pg.members)
    skeleton = _tier_phases(dmesh, strategy)
    has_dcn = any(t == "dcn" for _, t in skeleton)
    if mode == "dcn_only" and not has_dcn:
        return None
    ops = getattr(strategy, "ops", {})
    plan = QsyncPlan({}, mode=mode, wire=wire)
    for layer in layers:
        if layer.name in grouped or not getattr(layer, "trainable", True):
            continue
        if not ensure_weight_specs(layer):
            continue
        os_ = ops.get(layer.name)
        for w in layer.weights or ():
            wspec = os_.weights.get(w.name) if os_ is not None else None
            if spec_degree(wspec, axis_sizes) > 1:
                continue   # replicated-math seam: stays full-precision
            wbytes = float(int(np.prod(w.shape)) or 1) * itemsize(w.dtype)
            quote = cost_model.quantized_sync_quote(
                wbytes, n_dev, skeleton, mode=mode, wire=wire)
            if quote is None:
                continue
            base_s, quant_s, phase_wires = quote
            if not any(phase_wires):
                continue
            plan.decisions.setdefault(layer.name, {})[w.name] = {
                "wire": wire,
                "phases": [{"axes": list(axes), "tier": tier, "wire": pw}
                           for (axes, tier), pw in zip(skeleton,
                                                       phase_wires)],
                "baseline_s": float(base_s),
                "quantized_s": float(quant_s),
            }
    if not plan:
        return None
    from ..obs.metrics_registry import REGISTRY
    s = plan.summary()
    REGISTRY.counter(
        "ff_qsync_plans_total",
        "Quantized-collective plans adopted by mode").inc(mode=mode)
    REGISTRY.gauge(
        "ff_qsync_quantized_params",
        "Gradient tensors whose sync the last adopted plan "
        "quantized").set(s["n_quantized"])
    obs_events.record_span("qsync.plan", t0, time.perf_counter() - t0,
                           mode=mode, n_quantized=s["n_quantized"])
    return plan


def audit_record(plan: QsyncPlan) -> Dict[str, Any]:
    """The strategy-audit ``"quantized_sync"`` section: summary plus
    every tensor's per-phase wire choice with both predicted costs."""
    per_param = []
    for lname, ws in plan.decisions.items():
        for wname, rec in ws.items():
            per_param.append({
                "param": f"{lname}/{wname}",
                "wire": rec.get("wire"),
                "phases": [
                    {"tier": p.get("tier"),
                     "wire": p.get("wire") or "float32"}
                    for p in rec.get("phases", ())],
                "baseline_s": rec.get("baseline_s", 0.0),
                "quantized_s": rec.get("quantized_s", 0.0),
            })
    return {**plan.summary(), "per_param": per_param}


# ---------------------------------------------------------------------------
# runtime: the explicit-sync training path
# ---------------------------------------------------------------------------

class QsyncSchedule:
    """Resolved executable schedule: the plan plus the mesh facts the
    shard_map body needs (axis sizes, total degree)."""

    def __init__(self, plan: QsyncPlan, dmesh):
        self.plan = plan
        self.axes: Tuple[str, ...] = tuple(dmesh.axis_sizes.keys())
        self.sizes: Dict[str, int] = dict(dmesh.axis_sizes)
        self.degree = 1
        for s in self.sizes.values():
            self.degree *= s

    def phases_for(self, layer: str, wname: str
                   ) -> List[Tuple[Tuple[str, ...], Optional[str]]]:
        phases = self.plan.phases_for(layer, wname)
        if phases is None:
            return [(self.axes, None)]
        return phases


def runtime_schedule(program, strategy, config, dmesh
                     ) -> Optional[QsyncSchedule]:
    """Build the executable quantized-sync schedule, or None when the
    configuration cannot honor the plan exactly — the caller keeps the
    implicit (GSPMD) sync. The explicit path requires a pure data-
    parallel program: gradient sync is the ONLY cross-device collective
    it owns, so weights must be replicated, no pipeline / bank /
    place-group subsets, no stateful ops (their per-device state would
    silently diverge), and no gradient accumulation."""
    plan = getattr(strategy, "qsync", None)
    if plan is None or not plan.quantized_params():
        return None

    def fallback(why: str) -> None:
        import logging
        obs_events.counter("qsync.runtime_fallbacks")
        logging.getLogger("flexflow_tpu").warning(
            "quantized-collectives plan present but the runtime path "
            "is ineligible (%s); keeping the implicit full-precision "
            "sync", why)

    if getattr(strategy, "pipeline", None) is not None:
        fallback("pipelined region")
        return None
    if (getattr(strategy, "banks", None)
            or getattr(strategy, "place_groups", None)):
        fallback("bank/place-group subsets")
        return None
    if max(getattr(config, "gradient_accumulation_steps", 1), 1) > 1:
        fallback("gradient accumulation")
        return None
    n = 1
    for s in dmesh.axis_sizes.values():
        n *= s
    if n <= 1:
        return None
    from ..ops import get_op_def
    from ..runtime.zero import spec_degree
    axis_sizes = dict(dmesh.axis_sizes)
    ops = getattr(strategy, "ops", {})
    for layer in program.layers:
        os_ = ops.get(layer.name)
        for w in layer.weights or ():
            sp = os_.weights.get(w.name) if os_ is not None else None
            if spec_degree(sp, axis_sizes) > 1:
                fallback(f"sharded weight {layer.name}/{w.name}")
                return None
        state_spec = getattr(get_op_def(layer.op_type), "state_spec",
                             None)
        if state_spec is not None and state_spec(
                layer.params, [t.shape for t in layer.inputs],
                [t.dtype for t in layer.inputs]):
            fallback(f"stateful op {layer.name}")
            return None
    for t in program.input_tensors:
        if t.get_tensor() is not None:
            continue       # baked constant, not a per-batch input
        if not t.shape or t.shape[0] % n != 0:
            fallback(f"input {t.name} batch dim not divisible by {n}")
            return None
    return QsyncSchedule(plan, dmesh)


def init_residuals(schedule: QsyncSchedule, program, dmesh
                   ) -> Dict[str, Dict[str, Any]]:
    """Zero error-feedback residuals for every quantized tensor: shape
    ``(degree,) + weight.shape`` float32, dim 0 sharded over the sync
    axes via ``reshard.place_host`` so each device materializes only
    its own row. Keyed like the params tree, stored under
    :data:`RESIDUAL_SLOT` in the optimizer state."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from ..parallel import reshard as reshard_mod
    by_name = {l.name: l for l in program.layers}
    quantized = set(schedule.plan.quantized_params())
    out: Dict[str, Dict[str, Any]] = {}
    spec0 = schedule.axes[0] if len(schedule.axes) == 1 \
        else tuple(schedule.axes)
    for (lname, wname) in sorted(quantized):
        layer = by_name.get(lname)
        if layer is None:
            continue
        wshape = None
        for w in layer.weights or ():
            if w.name == wname:
                wshape = tuple(w.shape)
        if wshape is None:
            continue
        arr = np.zeros((schedule.degree,) + wshape, np.float32)
        sh = NamedSharding(dmesh.mesh,
                           P(spec0, *([None] * len(wshape))))
        out.setdefault(lname, {})[wname] = \
            reshard_mod.place_host(arr, sh)
    return out


def refit_residual(arr: np.ndarray, new_degree: int) -> np.ndarray:
    """Re-fit a saved residual ``(d_old,) + shape`` to a world of
    ``new_degree`` sync participants. Residuals are per-device withheld
    gradient mass whose SUM is what error feedback re-injects, so:
    merging devices sum-folds their rows, growing worlds keep the old
    rows and zero-fill the new ones, and a non-divisible change folds
    everything into row 0 — in every case total withheld mass is
    preserved exactly."""
    arr = np.asarray(arr, np.float32)
    d_old = arr.shape[0]
    if d_old == new_degree:
        return arr
    rest = arr.shape[1:]
    if d_old % new_degree == 0:
        return arr.reshape((new_degree, d_old // new_degree) + rest
                           ).sum(axis=1)
    out = np.zeros((new_degree,) + rest, np.float32)
    if new_degree % d_old == 0:
        out[:d_old] = arr
    else:
        out[0] = arr.sum(axis=0)
    return out


def strip_residual(opt_state):
    """(residual_tree_or_None, opt_state_without_slot) — the executor
    separates the residuals before the optimizer update (optimizers
    rebuild their slot dict and would silently drop a foreign slot)."""
    if not isinstance(opt_state, dict) or RESIDUAL_SLOT not in opt_state:
        return None, opt_state
    return (opt_state[RESIDUAL_SLOT],
            {k: v for k, v in opt_state.items() if k != RESIDUAL_SLOT})


def sharded_grads(executor, params, state, batch, step, residual):
    """The explicit-sync replacement for ``jax.grad`` + implicit GSPMD
    gradient sync: one shard_map over the whole mesh computes each
    device's LOCAL gradients (full fwd+bwd on its batch shard, weights
    replicated), then syncs every gradient tensor explicitly — plain
    ``psum`` legs at full precision, quantized all-reduce legs on the
    wire dtype the plan chose, error-feedback residuals carried in and
    out. Metrics sync with their proper reductions (means average,
    counts sum, RMS combines in the square domain). Returns
    ``(grads, metrics, new_residuals)`` — grads/metrics replicated,
    residuals sharded over the sync axes.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from ..runtime import metrics as metrics_mod
    from ..utils.jax_compat import shard_map
    sched: QsyncSchedule = executor._qsync
    axes = sched.axes
    sizes = sched.sizes
    n = sched.degree
    spec0 = axes[0] if len(axes) == 1 else tuple(axes)
    residual = residual or {}

    def body(params_l, state_l, batch_l, res_l):
        shard_index = _group_index(axes, sizes)

        def loss_fn(p):
            # shard_index marks shard-local emission: absolute-batch-
            # shape ops rescale and per-device dropout streams
            # decorrelate (matching the global path's independent
            # per-row masks in distribution)
            outs, _, aux, capture = executor._forward(
                p, state_l, batch_l, True, step, strategy=None,
                shard_index=shard_index)
            loss, bm = executor._loss_and_metrics(
                outs, capture, batch_l["label"], aux)
            return loss, bm
        g, bm = jax.grad(loss_fn, has_aux=True)(params_l)
        new_res: Dict[str, Dict[str, Any]] = {}
        synced: Dict[str, Dict[str, Any]] = {}
        for lname, ws in g.items():
            sl: Dict[str, Any] = {}
            for wname, leaf in ws.items():
                phases = sched.phases_for(lname, wname)
                r = res_l.get(lname, {}).get(wname)
                out, r_new = phased_sync(
                    leaf, phases, sizes,
                    residual=None if r is None else r[0])
                sl[wname] = out.astype(leaf.dtype)
                if r is not None:
                    # keep the slot even when the plan left this leaf
                    # full-precision (structure must round-trip)
                    new_res.setdefault(lname, {})[wname] = \
                        (r[0] if r_new is None else r_new)[None]
            synced[lname] = sl

        def sync_metric(k, v):
            if k in metrics_mod.COUNT_KEYS:
                return jax.lax.psum(v, axes)
            if k in metrics_mod.RMS_KEYS:
                return jnp.sqrt(jax.lax.psum(v * v, axes) / n)
            return jax.lax.psum(v, axes) / n

        bm = {k: sync_metric(k, v) for k, v in bm.items()}
        return synced, bm, new_res

    rep = P()
    batch_specs = jax.tree.map(
        lambda a: P(spec0, *([None] * (a.ndim - 1))), batch)
    res_specs = jax.tree.map(
        lambda a: P(spec0, *([None] * (a.ndim - 1))), residual)
    # prefix pytrees: replicated params/state in, replicated synced
    # grads + metrics out, residuals sharded over the sync axes both
    # ways (each device sees exactly its own (1, ...) row)
    fn = shard_map(
        body, mesh=executor.dmesh.mesh,
        in_specs=(rep, rep, batch_specs, res_specs),
        out_specs=(rep, rep, res_specs),
        check_vma=False)
    return fn(params, state, batch, residual)
