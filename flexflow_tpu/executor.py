"""Executor: lowers a (graph, strategy) pair to jitted SPMD train/eval steps.

This replaces the reference's entire Legion execution stack — per-op
IndexLaunchers, FFMapper routing, NCCL cliques, Legion tracing
(``src/runtime/model.cc:2415-2469``, ``src/mapper/mapper.cc``) — with ONE
pjit-compiled function per step kind:

  - the op graph is interpreted once at trace time (topological emission);
  - the searched strategy is applied as ``with_sharding_constraint`` on op
    outputs and ``NamedSharding`` placement of parameters;
  - XLA GSPMD inserts the ICI collectives the strategy implies, fuses
    elementwise chains (the reference's FusedOp pass), and overlaps
    compute/comm (the reference's Legion async task graph);
  - jit caching plays the role of Legion tracing: iteration 2+ replays the
    compiled executable.

Backward is jax.grad over the traced graph — the analog of the reference's
per-op backward tasks driven in reverse topo order (``model.cc:2438``).
"""
from __future__ import annotations

import functools
import itertools
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .ffconst import (CompMode, DataType, LossType, MetricsType, OperatorType)
from .core.layer import Layer
from .core.tensor import Tensor
from .dtypes import to_jnp
from .obs import events as obs_events
from .ops import EmitCtx, ensure_weight_specs, get_op_def
from .parallel import reshard as reshard_mod
from .parallel.machine import DeviceMesh
from .parallel.strategy import ShardingStrategy
from .runtime import losses as losses_mod
from .runtime import metrics as metrics_mod
from .runtime.initializers import initialize, initialize_host  # noqa: F401
from .runtime.optimizers import Optimizer
from .utils.jax_compat import shard_map


def _npdt(dtype) -> "np.dtype":
    """numpy dtype for a framework DataType (bfloat16 via ml_dtypes)."""
    return np.dtype(to_jnp(dtype))


def _trace_sync_on() -> bool:
    """``FF_TRACE_SYNC=1``: block on the step's outputs inside the
    instrumentation span so it measures TRUE step latency instead of
    dispatch time (the async-dispatch loop otherwise returns as soon as
    XLA enqueues the step). Read per call — only on the traced path —
    so a debug session can toggle it without rebuilding the step."""
    from .obs.events import _env_on
    return _env_on(os.environ.get("FF_TRACE_SYNC"))


def _instrument_step(fn, name: str):
    """Wrap a jitted step with per-step telemetry: a span per call with
    the compile-vs-steady split (the FIRST call of a fresh jit traces +
    compiles; later calls replay the executable) and a step counter.
    With ``FF_TRACE_SYNC=1`` the span additionally blocks on the step's
    outputs, so it records device latency, not dispatch latency.

    Disabled-mode cost is one flag check plus an int increment — the
    bench's obs-overhead leg pins this at <= 3% of a train step, and the
    raw jitted callable stays reachable as ``wrapped.__wrapped__`` so
    the leg can time both sides of exactly this wrapper. The jit
    inspection surface callers rely on (``lower`` for HLO dumps —
    utils/debug.py — plus ``trace``/``eval_shape``) is re-exposed on the
    wrapper."""
    # itertools.count: serving instance clones share one compiled
    # forward across N scheduler workers, and next() is atomic under
    # the GIL — a read-modify-write int would double-label "compile"
    calls = itertools.count()

    def wrapped(*args, **kwargs):
        n = next(calls)
        if not obs_events.enabled():
            return fn(*args, **kwargs)
        obs_events.counter(f"executor.{name}_steps")
        with obs_events.span(f"executor.{name}_step",
                             phase="compile" if n == 0 else "steady",
                             step=n):
            out = fn(*args, **kwargs)
            if _trace_sync_on():
                jax.block_until_ready(out)
            return out

    wrapped.__wrapped__ = fn
    for attr in ("lower", "trace", "eval_shape", "clear_cache"):
        if hasattr(fn, attr):
            setattr(wrapped, attr, getattr(fn, attr))
    return wrapped


def _needs_rng(layer: Layer) -> bool:
    if layer.op_type == OperatorType.OP_DROPOUT:
        return True
    if layer.op_type == OperatorType.OP_MULTIHEAD_ATTENTION:
        return layer.params.get("dropout", 0.0) > 0.0
    return False


class GraphProgram:
    """Topologically-ordered emission plan for a layer graph."""

    def __init__(self, layers: Sequence[Layer], input_tensors: Sequence[Tensor],
                 output_tensors: Sequence[Tensor]):
        self.layers = list(layers)
        self.input_tensors = list(input_tensors)
        self.output_tensors = list(output_tensors)

    def init_env(self, inputs: Dict[str, Any]) -> Dict[int, Any]:
        env: Dict[int, Any] = {}
        for t in self.input_tensors:
            if t.name in inputs:
                env[t.guid] = inputs[t.name]
            elif t.get_tensor() is not None:
                # constant input (create_constant / frontend const folding):
                # baked into the jitted program at trace time
                env[t.guid] = jnp.asarray(t.get_tensor(), to_jnp(t.dtype))
            else:
                raise KeyError(f"missing input {t.name}")
        return env

    def emit_layers(self, layers: Sequence[Layer],
                    env: Dict[int, Any],
                    params: Dict[str, Dict[str, Any]], ctx: EmitCtx,
                    strategy: Optional[ShardingStrategy] = None,
                    capture: Optional[Dict[int, Any]] = None) -> None:
        bf16_act = bool(getattr(ctx.config, "bf16_activations", False)) \
            if ctx.config is not None else False
        # per-op device-subset placement (parallel/banks.py): member
        # layers of a bank are emitted together as one vmap whose mapped
        # dim is sharded over the bank axes — each device subset computes
        # only its own members, concurrently (reference MachineView
        # placement, machine_view.h:14-62)
        bank_out: Dict[str, Any] = {}
        # name -> (group, emit_fn) for BOTH subset-placement kinds
        # (stacked banks and heterogeneous place groups): member layers
        # are emitted together at the first member's position
        grouped: Dict[str, Tuple[Any, Any]] = {}
        if strategy is not None:
            present = {l.name for l in layers}
            for bk in getattr(strategy, "banks", None) or ():
                if set(bk.members) <= present:
                    for m in bk.members:
                        grouped[m] = (bk, self._emit_bank)
            for pg in getattr(strategy, "place_groups", None) or ():
                if set(pg.members) <= present:
                    for m in pg.members:
                        grouped[m] = (pg, self._emit_place_group)
        for layer in layers:
            if layer.name in grouped:
                if layer.name not in bank_out:
                    grp, emit_fn = grouped[layer.name]
                    emit_fn(grp, layers, env, params, ctx, strategy,
                            bank_out)
                o = bank_out[layer.name]
                if bf16_act and hasattr(o, "dtype") \
                        and o.dtype == jnp.float32:
                    o = o.astype(jnp.bfloat16)
                env[layer.outputs[0].guid] = o
                if capture is not None:
                    capture[layer.outputs[0].guid] = bank_out[layer.name]
                continue
            op = get_op_def(layer.op_type)
            ins = [env[t.guid] for t in layer.inputs]
            w = params.get(layer.name, {})
            outs = op.emit(layer.params, ins, w, ctx, layer.name)
            if len(outs) != len(layer.outputs):
                raise RuntimeError(
                    f"op {layer.name} emitted {len(outs)} outputs, "
                    f"expected {len(layer.outputs)}")
            for i, (o, t) in enumerate(zip(outs, layer.outputs)):
                cast = (bf16_act and hasattr(o, "dtype")
                        and o.dtype == jnp.float32)
                pre_cast = o
                if cast:
                    # end-to-end bf16 activations: inter-op tensors live
                    # in bf16 (weights stay fp32 masters; losses/norms
                    # upcast internally)
                    o = o.astype(jnp.bfloat16)
                if strategy is not None:
                    sh = strategy.output_sharding(layer.name, i)
                    if sh is not None:
                        # layout-op outputs take the PLANNED transition
                        # (explicit collectives under shard_map) — a bare
                        # constraint lets GSPMD propagate it backward
                        # through reshape/concat, the documented CPU
                        # miscompile (parallel/reshard.py)
                        o = reshard_mod.constrain_output(
                            o, sh, strategy, layer)
                        if cast:
                            pre_cast = reshard_mod.constrain_output(
                                pre_cast, sh, strategy, layer)
                env[t.guid] = o
                if capture is not None:
                    # capture keeps the pre-bf16-cast (but still
                    # sharding-constrained) value: the CE-on-logits
                    # fusion reads logits from here, and the loss must
                    # consume full-precision logits even when
                    # --bf16-activations quantizes the live graph
                    capture[t.guid] = pre_cast if cast else o

    def _emit_bank(self, bk, layers, env, params, ctx,
                   strategy: ShardingStrategy,
                   bank_out: Dict[str, Any]) -> None:
        """Emit one bank group: stack member inputs along a leading bank
        dim, vmap the member op over it, shard the mapped dim over the
        bank axes. Each device subset computes only its slice of the
        vmap — its own members — so the group runs concurrently across
        subsets; the downstream per-member reads (``out[k]``) are where
        GSPMD inserts the one rejoin all-gather."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        by_name = {l.name: l for l in layers}
        members = [by_name[n] for n in bk.members]
        op = get_op_def(members[0].op_type)
        mesh = strategy.dmesh.mesh
        bank_spec = bk.axes[0] if len(bk.axes) == 1 else tuple(bk.axes)
        # data parallelism inside each subset over the leftover axes
        batch_spec = None
        ish = members[0].inputs[0].shape
        if bk.batch_axes and ish:
            bdeg = 1
            for a in bk.batch_axes:
                bdeg *= strategy.dmesh.axis_sizes[a]
            if ish[0] % bdeg == 0:
                batch_spec = (bk.batch_axes[0] if len(bk.batch_axes) == 1
                              else tuple(bk.batch_axes))
        from .parallel.banks import rejoin_stack, shard_stack
        xs = jnp.stack([env[m.inputs[0].guid] for m in members])
        in_sp = P(bank_spec, batch_spec, *([None] * (xs.ndim - 2)))
        xs = shard_stack(xs, members[0].inputs[0], in_sp, strategy)
        w = params.get(bk.param_name, {})
        emit_params = members[0].params
        if getattr(bk, "padded", False):
            # heterogeneous members: emit with weight-sizing params
            # (e.g. num_entries) maxed to match the padded stack
            from .parallel.banks import _PAD_FREE_PARAMS
            emit_params = dict(members[0].params)
            for key in _PAD_FREE_PARAMS.get(members[0].op_type, ()):
                emit_params[key] = max(m.params[key] for m in members)

        def one(x_k, w_k):
            return op.emit(emit_params, [x_k], w_k, ctx,
                           members[0].name)[0]

        out = jax.vmap(one)(xs, w)
        out_sp = P(bank_spec, batch_spec, *([None] * (out.ndim - 2)))
        out = jax.lax.with_sharding_constraint(
            out, NamedSharding(mesh, out_sp))
        out = rejoin_stack(out, bank_spec, batch_spec, strategy)
        for k, m in enumerate(members):
            bank_out[m.name] = out[k]

    def _emit_place_group(self, pg, layers, env, params, ctx,
                          strategy: ShardingStrategy,
                          bank_out: Dict[str, Any]) -> None:
        """Emit one heterogeneous placement region (PlaceGroup): a
        shard_map over the place axis whose body ``lax.switch``es on
        the member block coordinate — each device EXECUTES only its
        member's op (MPMD-inside-SPMD), so mixed-type independent ops
        run concurrently on disjoint subsets; outputs rejoin by an
        exact masked psum (only the first coordinate of each owning
        block contributes). Weights stay replicated — for distributed
        weights use a (padded) bank; this region is the
        compute-placement half of the reference's arbitrary MachineView
        (machine_view.h:14-62)."""
        from jax.sharding import PartitionSpec as P
        by_name = {l.name: l for l in layers}
        members = [by_name[n] for n in pg.members]
        mesh = strategy.dmesh.mesh
        axis = pg.axis
        P_ = strategy.dmesh.axis_sizes[axis]
        K = len(members)
        if P_ % K != 0:
            raise ValueError(f"place axis {axis} size {P_} must divide "
                             f"into {K} members")
        per = P_ // K
        for m in members:
            if len(m.inputs) != 1 or len(m.outputs) != 1:
                raise ValueError(f"place-group member {m.name} must be "
                                 f"1-in/1-out")
            if _needs_rng(m):
                raise ValueError(f"place-group member {m.name} uses "
                                 f"rng (not supported)")
        ops = [get_op_def(m.op_type) for m in members]
        for m, op in zip(members, ops):
            ss = getattr(op, "state_spec", None)
            if ss is not None and ss(
                    m.params, [t.shape for t in m.inputs],
                    [t.dtype for t in m.inputs]):
                raise ValueError(
                    f"stateful op {m.name} cannot join a place group")
        xs = [env[m.inputs[0].guid] for m in members]
        ws = [params.get(m.name, {}) for m in members]
        out_sds = [jax.eval_shape(
            lambda x, w, i=i: ops[i].emit(members[i].params, [x], w,
                                          ctx, members[i].name)[0],
            xs[i], ws[i]) for i in range(K)]

        def body(xs_l, ws_l):
            k = jax.lax.axis_index(axis)
            owner = k // per
            first = (k % per) == 0

            def branch(i):
                def go(_):
                    out = ops[i].emit(members[i].params, [xs_l[i]],
                                      ws_l[i], ctx, members[i].name)[0]
                    outs = [jnp.zeros(s.shape, s.dtype)
                            for s in out_sds]
                    # zeros_like keeps integer/bool outputs in their
                    # own dtype (a weak-float 0.0 would promote and
                    # desync the branch signatures)
                    outs[i] = jnp.where(first, out, jnp.zeros_like(out))
                    return tuple(outs)
                return go

            outs = jax.lax.switch(owner, [branch(i) for i in range(K)],
                                  None)
            return tuple(jax.lax.psum(o, axis) for o in outs)

        # replicated in/out specs: shard_map's transpose of replicated
        # operands yields EXACT gradients even on meshes with extra
        # (non-place) axes — pinned by
        # tests/test_place_groups.py::test_place_group_grads_exact
        region = shard_map(
            body, mesh=mesh,
            in_specs=(tuple(P() for _ in xs),
                      tuple(jax.tree.map(lambda _: P(), w)
                            for w in ws)),
            out_specs=tuple(P() for _ in range(K)),
            check_vma=False)
        outs = region(tuple(xs), tuple(ws))
        for m, o in zip(members, outs):
            bank_out[m.name] = o

    def emit(self, params: Dict[str, Dict[str, Any]], inputs: Dict[str, Any],
             ctx: EmitCtx, strategy: Optional[ShardingStrategy] = None,
             capture: Optional[Dict[int, Any]] = None) -> List[Any]:
        """Interpret the graph. `capture[tensor.guid]` collects intermediate
        values (used for logits extraction by the loss)."""
        env = self.init_env(inputs)
        self.emit_layers(self.layers, env, params, ctx, strategy, capture)
        return [env[t.guid] for t in self.output_tensors]


def _find_remat_blocks(layers):
    """Block boundaries for ``--remat``: the maximal repeated-block run,
    each block single-input/single-output, containing no stateful or
    aux-loss-emitting ops (their side-channel writes cannot cross a
    ``jax.checkpoint`` boundary). Returns
    ``(start, unit, reps, entry_guids, exit_guids)`` or None."""
    from .parallel.pipeline_lowering import (_has_state, chunk_boundaries,
                                             find_repeated_run)
    run = find_repeated_run(list(layers), 1)
    if run is None:
        return None
    total, start, unit = run
    reps = total // unit
    layers = list(layers)
    region = layers[start:start + total]
    # ops whose emit writes ctx side-channels (aux losses / state) cannot
    # sit inside a jax.checkpoint boundary; AggregateSpec inherits
    # Aggregate's aux-loss emit
    aux_ops = {OperatorType.OP_AGGREGATE, OperatorType.OP_AGG_SPEC}
    if any(_has_state(l) or l.op_type in aux_ops for l in region):
        return None
    entries = chunk_boundaries(layers, start, unit, reps)
    if entries is None:
        return None
    exits = entries[1:] + [region[-1].outputs[0].guid]
    return start, unit, reps, entries, exits


# Megatron tp split of stacked stage weights: role -> weight name ->
# dim index (within the weight's own shape) sharded over tp_axis.
# None = replicated (biases applied once, after the psum).
_TP_WEIGHT_DIMS = {
    "attn": {"wq": 1, "wk": 1, "wv": 1, "bq": 0, "bk": 0, "bv": 0,
             "wo": 0, "bo": None},
    "col": {"kernel": 1, "bias": 0},
    "row": {"kernel": 0, "bias": None},
}


class Executor:
    def __init__(self, program: GraphProgram, config, dmesh: DeviceMesh,
                 strategy: ShardingStrategy, optimizer: Optimizer,
                 loss_type: LossType, metrics: Sequence[MetricsType],
                 seed: int = 0):
        self.program = program
        self.config = config
        self.dmesh = dmesh
        self.strategy = strategy
        self.optimizer = optimizer
        self.loss_type = LossType(loss_type)
        self.metrics = list(metrics)
        self.seed = seed
        self._train_step = None
        self._eval_step = None
        # ZeRO-1 (runtime/zero.py): NamedSharding pytree for the updated
        # optimizer state, set by FFModel.compile when enabled
        self.opt_state_constraints = None
        # communication–computation overlap (runtime/overlap.py): the
        # bucketed grad-sync schedule, or None = the serial path
        # (bit-exact default). Built statically here so the plan
        # verifier (which runs before the first step is traced) sees
        # the schedule on strategy.overlap.
        from .runtime import overlap as overlap_mod
        self._overlap_schedule = overlap_mod.build_overlap_schedule(
            program, strategy, config)
        if self._overlap_schedule is not None:
            strategy.overlap = self._overlap_schedule.record()
            obs_events.counter("overlap.schedules_built")
        # quantized gradient collectives (ops/quantized_collectives.py):
        # when the strategy carries a QsyncPlan the runtime can honor
        # (pure-DP program, replicated weights), gradients are computed
        # and synced explicitly — quantized legs on the wire dtype,
        # error-feedback residuals as runtime state. None = the
        # implicit GSPMD sync, bit-exact legacy behavior. An imported
        # plan resolves here; a plan adopted post-build (FFModel.
        # _plan_qsync) re-resolves via attach_qsync().
        self._qsync = None
        self.attach_qsync()
        # searched kernel tier (kernels/registry.py): the adopted
        # strategy's per-op impl map, threaded through EmitCtx so
        # attention emission resolves its impl (ring lowers one
        # shard_map over the mesh's seq axis) and the optimizer update
        # dispatches fused/unfused. Empty = default impls everywhere.
        self._kernel_impls: Dict[str, str] = dict(
            getattr(strategy, "kernel_impls", None) or {})
        # pipeline region (parallel/pipeline_lowering): pre/post layer
        # split + GPipe lowering of the repeated-block region
        self.pipe = getattr(strategy, "pipeline", None)
        # --remat: per-block jax.checkpoint over the repeated-block run
        # (HBM-for-FLOPs trade; the pipelined region already recomputes
        # via its scan, so remat applies to the non-pipelined path only)
        self._remat = None
        if getattr(config, "remat", "none") == "blocks" \
                and self.pipe is not None:
            import logging
            logging.getLogger("flexflow_tpu").warning(
                "--remat is skipped when a pipeline region is active: "
                "the GPipe scan already recomputes stage activations "
                "per microbatch (pre/post-region layers are never "
                "rematerialized)")
        if getattr(config, "remat", "none") == "blocks" \
                and self.pipe is None:
            self._remat = _find_remat_blocks(program.layers)
            if self._remat is None:
                import logging
                logging.getLogger("flexflow_tpu").warning(
                    "--remat requested but the graph has no eligible "
                    "repeated-block region (needs >= 2 identical "
                    "single-crossing blocks without stateful/aux-loss "
                    "ops); running without rematerialization")
        if self.pipe is not None:
            if getattr(self.pipe, "prologue", None):
                # absorbed into stage 0 (ragged schedule): the prologue
                # IS layers[:start] by construction
                self._pre_layers = []
            else:
                self._pre_layers = program.layers[:self.pipe.start]
            n_epi = len(getattr(self.pipe, "epilogue", None) or [])
            self._post_layers = program.layers[self.pipe.end + n_epi:]
        # CE-on-logits fusion: if the final op is Softmax, take its input as
        # logits (grad identical to the reference's (probs-labels)/B kernel).
        self._logits_tensor: Optional[Tensor] = None
        if (losses_mod.wants_logits(self.loss_type)
                and self.program.layers
                and self.program.output_tensors):
            final_t = self.program.output_tensors[0]
            prod = final_t.owner_layer
            if prod is not None and prod.op_type == OperatorType.OP_SOFTMAX:
                self._logits_tensor = prod.inputs[0]

    # ------------------------------------------------------------------
    def attach_qsync(self) -> None:
        """(Re)resolve the strategy's quantized-sync plan into an
        executable schedule. FFModel.compile calls this again after
        ``_plan_qsync`` adopts a plan (the executor may predate it —
        the floor guard builds executors mid-search), invalidating the
        cached train step when the schedule changes."""
        from .ops import quantized_collectives as qsync_mod
        sched = qsync_mod.runtime_schedule(
            self.program, self.strategy, self.config, self.dmesh)
        if (sched is None) != (self._qsync is None):
            self._train_step = None
        self._qsync = sched
        if sched is not None:
            obs_events.counter("qsync.schedules_built")

    # ------------------------------------------------------------------
    def init_params_and_state(self, rng: Optional[jax.Array] = None):
        """Materialize parameters per WeightSpec with strategy shardings
        (reference: per-op init tasks + initializer GPU kernels).

        Arrays are built HOST-SIDE (numpy Philox keyed by the weight's
        integer path — see ``initializers.initialize_host``) and placed
        with one tree-level ``device_put`` against the recorded target
        shardings. The round-4 north-star profile showed 230 s of its
        301 s compile in eager per-weight jax init dispatch; jitting the
        whole init instead takes minutes to SPMD-compile on a many-
        device mesh. Host init + bulk placement is seconds either way
        and deterministic across platforms."""
        if rng is not None:
            # API compat: derive the integer seed from a caller key
            words = jax.random.key_data(rng).ravel()
            seed = int(words[-1]) | (int(words[0]) << 32)
        else:
            seed = self.seed
        psh: Dict[str, Dict[str, Any]] = {}
        ssh: Dict[str, Dict[str, Any]] = {}
        params, state = self._build_params_and_state(seed, psh, ssh)
        # placement via the reshard planner's host→device step: sharded
        # leaves hand each device only its own slice instead of staging
        # a full per-device replica (parallel/reshard.place_host)
        params = jax.tree.map(reshard_mod.place_host, params, psh)
        state = jax.tree.map(reshard_mod.place_host, state, ssh)
        return params, state

    def _build_params_and_state(self, seed, psh, ssh):
        """Host-side body of :meth:`init_params_and_state`: returns raw
        numpy (params, state) trees and records each leaf's target
        sharding into ``psh``/``ssh`` (congruent pytrees)."""
        params: Dict[str, Dict[str, Any]] = {}
        state: Dict[str, Dict[str, Any]] = {}
        region_names = set()
        if self.pipe is not None:
            region_names = {l.name for l in self.program.layers[
                self.pipe.start:self.pipe.end]}
            if getattr(self.pipe, "counts", None) is not None:
                params.update(self._init_ragged_pipeline_params(seed, psh))
            else:
                params.update(self._init_pipeline_params(seed, psh))
        # banked members (parallel/banks.py): weights are stacked along
        # a leading bank dim sharded over the bank axes, so each device
        # subset HOLDS only its members' weights (the reference's
        # per-view weight placement). Member k is initialized with the
        # exact keys the unbanked path would use — banked and unbanked
        # runs are numerically identical.
        banks = getattr(self.strategy, "banks", None) or []
        if banks:
            # prune banks whose members don't all exist in this program
            # (e.g. a stale --import against a renamed model): emitting
            # such a bank would KeyError deep inside compile. Pruning on
            # the shared strategy keeps init and emit consistent.
            names = {l.name for l in self.program.layers}
            kept = [bk for bk in banks if set(bk.members) <= names]
            if len(kept) != len(banks):
                import logging
                logging.getLogger("flexflow_tpu").warning(
                    "dropping %d bank placement(s) whose members are "
                    "not in this program", len(banks) - len(kept))
                self.strategy.banks = kept
            banks = kept
        bank_member_arrs: Dict[str, Dict[str, Any]] = {}
        bank_names = {n for bk in banks for n in bk.members}
        for li, layer in enumerate(self.program.layers):
            if layer.name in region_names:
                continue  # initialized stacked, above
            op = get_op_def(layer.op_type)
            specs = ensure_weight_specs(layer)
            if specs and layer.name in bank_names:
                arrs = {}
                for wi, spec in enumerate(specs):
                    # same key path as the unbanked branch below: banked
                    # and unbanked runs are numerically identical
                    arrs[spec.name] = initialize_host(
                        spec, (seed, 1, li, wi), _npdt(spec.dtype))
                bank_member_arrs[layer.name] = arrs
            elif specs:
                lp = {}
                for wi, spec in enumerate(specs):
                    lp[spec.name] = initialize_host(
                        spec, (seed, 1, li, wi), _npdt(spec.dtype))
                    psh.setdefault(layer.name, {})[spec.name] = \
                        self.strategy.weight_sharding(layer.name, spec.name)
                params[layer.name] = lp
            state_spec = getattr(op, "state_spec", None)
            if state_spec is not None:
                ss = state_spec(layer.params, [t.shape for t in layer.inputs],
                                [t.dtype for t in layer.inputs])
                if ss:
                    if layer.name in bank_names:
                        raise ValueError(
                            f"stateful op {layer.name} cannot be "
                            f"banked")
                    st = {}
                    for sname, (sshape, sdt) in ss.items():
                        if sname == "var":
                            st[sname] = np.ones(sshape, _npdt(sdt))
                        else:
                            st[sname] = np.zeros(sshape, _npdt(sdt))
                        ssh.setdefault(layer.name, {})[sname] = \
                            self.strategy.replicated()
                    state[layer.name] = st
        for bk in banks:
            from jax.sharding import NamedSharding, PartitionSpec as P
            if any(m not in bank_member_arrs for m in bk.members):
                # member without weight specs: nothing to stack (the
                # emit path still banks the compute)
                continue
            bank_spec = bk.axes[0] if len(bk.axes) == 1 else tuple(bk.axes)
            lp = {}
            wnames = list(bank_member_arrs[bk.members[0]].keys())
            for wname in wnames:
                arrs = [bank_member_arrs[m][wname] for m in bk.members]
                if getattr(bk, "padded", False):
                    # heterogeneous members (e.g. different vocab
                    # sizes): zero-pad each weight to the group max —
                    # lookups are bounded by each member's true vocab,
                    # so the padding is never read
                    tgt = tuple(max(a.shape[d] for a in arrs)
                                for d in range(arrs[0].ndim))
                    arrs = [np.pad(a, [(0, t - s) for s, t in
                                       zip(a.shape, tgt)])
                            if tuple(a.shape) != tgt else a
                            for a in arrs]
                stacked = np.stack(arrs)
                psh.setdefault(bk.param_name, {})[wname] = NamedSharding(
                    self.dmesh.mesh,
                    P(bank_spec, *([None] * (stacked.ndim - 1))))
                lp[wname] = stacked
            params[bk.param_name] = lp
        return params, state

    # ------------------------------------------------------------------
    # pipeline lowering (parallel/pipeline_lowering.PipelineRegion)
    # ------------------------------------------------------------------
    def _init_pipeline_params(self, seed, psh):
        """Stacked region params: for each template layer, one leaf of
        shape (S,) + spec.shape — stage s initialized independently —
        sharded P(pp_axis, ...) so each pipeline rank holds its stage.
        Interleaved schedule (n_chunks = v > 1): (v, S) + spec.shape,
        sharded P(None, pp_axis, ...) — [k, s] is global chunk s + k*S.
        Returns raw host arrays; shardings recorded into ``psh``."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        pipe = self.pipe
        S, v = pipe.n_stages, pipe.n_chunks
        out: Dict[str, Dict[str, Any]] = {}
        for lj, layer in enumerate(pipe.template):
            specs = ensure_weight_specs(layer)
            if not specs:
                continue
            role = pipe.tp_roles.get(layer.name) \
                if pipe.tp_axis is not None else None
            lp = {}
            for wi, spec in enumerate(specs):
                slices = []
                for c in range(S * v):
                    slices.append(initialize_host(
                        spec, (seed, 2, 7000 + (lj << 12) + wi, c),
                        _npdt(spec.dtype)))
                stacked = np.stack(slices)
                wdims = [None] * len(spec.shape)
                if role is not None:
                    d = _TP_WEIGHT_DIMS[role].get(spec.name)
                    if d is not None:
                        wdims[d] = pipe.tp_axis
                if v > 1:
                    # [k, s] = chunk s + k*S: stack order is chunk-major,
                    # so the (v, S) reshape lands chunk c at [c//S, c%S]
                    stacked = stacked.reshape((v, S) + tuple(spec.shape))
                    sh = NamedSharding(self.dmesh.mesh,
                                       P(None, pipe.pp_axis, *wdims))
                else:
                    sh = NamedSharding(self.dmesh.mesh,
                                       P(pipe.pp_axis, *wdims))
                psh.setdefault(pipe.param_name(layer), {})[spec.name] = sh
                lp[spec.name] = stacked
            out[pipe.param_name(layer)] = lp
        return out

    # ------------------------------------------------------------------
    # ragged pipeline lowering (gpipe_ragged; pipeline_lowering.counts)
    # ------------------------------------------------------------------
    def _ragged_slot_of(self):
        """block index b -> (stage, slot) under the contiguous ragged
        assignment (stage s owns counts[s] consecutive blocks)."""
        out = []
        for s, c in enumerate(self.pipe.counts):
            out.extend((s, k) for k in range(c))
        return out

    def _init_ragged_pipeline_params(self, seed, psh):
        """Block params stacked (S, cmax) + spec.shape, stage dim over
        the pp axis, slot dim scanned by the engine; slots past a
        stage's count are zero (masked pass-through in the engine).
        Returns raw host arrays; shardings recorded into ``psh``."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        pipe = self.pipe
        S = pipe.n_stages
        cmax = max(pipe.counts)
        slot_of = self._ragged_slot_of()
        out: Dict[str, Dict[str, Any]] = {}
        for lj, layer in enumerate(pipe.template):
            specs = ensure_weight_specs(layer)
            if not specs:
                continue
            lp = {}
            for wi, spec in enumerate(specs):
                dt = _npdt(spec.dtype)
                rows = [[np.zeros(tuple(spec.shape), dt)
                         for _ in range(cmax)] for _ in range(S)]
                for b, (s, k) in enumerate(slot_of):
                    rows[s][k] = initialize_host(
                        spec, (seed, 3, 7000 + (lj << 12) + wi, b), dt)
                stacked = np.stack([np.stack(r) for r in rows])
                psh.setdefault(pipe.param_name(layer), {})[spec.name] = \
                    NamedSharding(
                        self.dmesh.mesh,
                        P(pipe.pp_axis, *([None] * (stacked.ndim - 1))))
                lp[spec.name] = stacked
            out[pipe.param_name(layer)] = lp
        return out

    def _make_block_fn(self, training: bool):
        """block_fn(p_k, x, t) emitting ONE template block; ``p_k`` is
        the per-slot param subtree handed over by gpipe_ragged's scan."""
        pipe = self.pipe
        template = pipe.template
        bf16_act = bool(getattr(self.config, "bf16_activations", False))

        def block_fn(p, x, t):
            rng_key = p.get("__rng__")
            env = {pipe.template_entry_guid: x}
            ctx = EmitCtx(training=training, rngs={}, state={},
                          config=self.config)
            for j, layer in enumerate(template):
                if training and rng_key is not None and _needs_rng(layer):
                    ctx.rngs[layer.name] = jax.random.fold_in(
                        jax.random.fold_in(rng_key, t), j)
                op = get_op_def(layer.op_type)
                ins = [env[tt.guid] for tt in layer.inputs]
                w = p.get(pipe.param_name(layer), {})
                outs = op.emit(layer.params, ins, w, ctx, layer.name)
                for o, tt in zip(outs, layer.outputs):
                    if bf16_act and hasattr(o, "dtype") \
                            and o.dtype == jnp.float32:
                        o = o.astype(jnp.bfloat16)
                    env[tt.guid] = o
            return env[pipe.template_exit_guid]

        return block_fn

    def _make_edge_fn(self, layers, out_guid, training: bool):
        """Interpret a prologue/epilogue layer list inside the pipelined
        shard_map; ``env_seed`` maps tensor guids to incoming values."""
        bf16_act = bool(getattr(self.config, "bf16_activations", False))

        def fn(p, env_seed, t):
            rng_key = p.get("__rng__")
            env = dict(env_seed)
            ctx = EmitCtx(training=training, rngs={}, state={},
                          config=self.config)
            for j, layer in enumerate(layers):
                if training and rng_key is not None and _needs_rng(layer):
                    ctx.rngs[layer.name] = jax.random.fold_in(
                        jax.random.fold_in(rng_key, t), j)
                op = get_op_def(layer.op_type)
                ins = [env[tt.guid] for tt in layer.inputs]
                w = p.get(layer.name, {})
                outs = op.emit(layer.params, ins, w, ctx, layer.name)
                for o, tt in zip(outs, layer.outputs):
                    if bf16_act and hasattr(o, "dtype") \
                            and o.dtype == jnp.float32:
                        o = o.astype(jnp.bfloat16)
                    env[tt.guid] = o
            return env[out_guid]

        return fn

    def _tensor_by_guid(self, guid: int):
        for l in self.program.layers:
            for t in list(l.outputs) + list(l.inputs):
                if t.guid == guid:
                    return t
        for t in self.program.input_tensors:
            if t.guid == guid:
                return t
        raise KeyError(guid)

    def _pipe_apply_ragged(self, params, env, batch, step,
                           training: bool):
        """Run a ragged pipeline region (unequal stage depths, optional
        prologue/epilogue inside stage 0 / S-1)."""
        from jax.sharding import PartitionSpec as P
        from .parallel.pipeline import gpipe_ragged
        pipe = self.pipe
        S, M = pipe.n_stages, pipe.n_microbatches
        cmax = max(pipe.counts)
        stacked = {pipe.param_name(l): params[pipe.param_name(l)]
                   for l in pipe.template
                   if pipe.param_name(l) in params}
        if training:
            base = jax.random.fold_in(jax.random.key(self.seed + 2), step)
            keys = jax.vmap(lambda i: jax.random.fold_in(base, i))(
                jnp.arange(S * cmax)).reshape(S, cmax)
            stacked = dict(stacked, __rng__=keys)

        pro_params = {l.name: params[l.name] for l in pipe.prologue
                      if l.name in params}
        epi_params = {l.name: params[l.name] for l in pipe.epilogue
                      if l.name in params}
        if training:
            pro_params = dict(pro_params, __rng__=jax.random.fold_in(
                jax.random.key(self.seed + 3), step))
            epi_params = dict(epi_params, __rng__=jax.random.fold_in(
                jax.random.key(self.seed + 4), step))

        entry_t = self._tensor_by_guid(pipe.entry_guid)
        mb = entry_t.shape[0] // M
        hidden_example = jnp.zeros((mb,) + tuple(entry_t.shape[1:]),
                                   to_jnp(entry_t.dtype))
        if pipe.epilogue:
            out_t = self._tensor_by_guid(pipe.epilogue_exit_guid)
            out_example = jnp.zeros((mb,) + tuple(out_t.shape[1:]),
                                    to_jnp(out_t.dtype))
        else:
            out_example = hidden_example

        prologue_fn = None
        if pipe.prologue:
            edge = self._make_edge_fn(pipe.prologue, pipe.entry_guid,
                                      training)

            def prologue_fn(p, raw_mb, t):  # noqa: F811
                seed = {t_.guid: raw_mb[t_.name]
                        for t_ in pipe.prologue_inputs}
                return edge(p, seed, t)

            raw_xs = {}
            for t_ in pipe.prologue_inputs:
                a = batch[t_.name]
                raw_xs[t_.name] = a.reshape((M, a.shape[0] // M)
                                            + a.shape[1:])
        else:
            from .parallel.pipeline_lowering import region_entry_transition
            x = region_entry_transition(
                env[pipe.entry_guid], self.strategy,
                self._tensor_by_guid(pipe.entry_guid))
            raw_xs = x.reshape((M, x.shape[0] // M) + x.shape[1:])

        epilogue_fn = None
        if pipe.epilogue:
            eedge = self._make_edge_fn(pipe.epilogue,
                                       pipe.epilogue_exit_guid, training)

            def epilogue_fn(p, y, t):  # noqa: F811
                return eedge(p, {pipe.exit_guid: y}, t)

        engine = gpipe_ragged(self._make_block_fn(training), pipe.pp_axis,
                              M, pipe.counts, prologue_fn=prologue_fn,
                              epilogue_fn=epilogue_fn)

        pp = pipe.pp_axis
        param_specs = jax.tree.map(
            lambda a: P(pp, *([None] * (a.ndim - 1))), stacked)
        pro_specs = jax.tree.map(lambda a: P(), pro_params)
        epi_specs = jax.tree.map(lambda a: P(), epi_params)
        dp = pipe.dp_axes if pipe.dp_axes else None
        dp = dp[0] if dp is not None and len(dp) == 1 else dp
        raw_specs = jax.tree.map(
            lambda a: P(None, dp, *([None] * (a.ndim - 2))), raw_xs)
        hid_spec = P(dp, *([None] * (hidden_example.ndim - 1)))
        out_spec = P(dp, *([None] * (out_example.ndim - 1)))
        ys_spec = P(None, dp, *([None] * (out_example.ndim - 1)))
        fn = shard_map(
            engine, mesh=self.dmesh.mesh,
            in_specs=(param_specs, pro_specs, epi_specs, raw_specs,
                      hid_spec, out_spec),
            out_specs=ys_spec, check_vma=False)
        ys = fn(stacked, pro_params, epi_params, raw_xs,
                hidden_example, out_example)
        from .parallel.pipeline_lowering import region_exit_transition
        ys = region_exit_transition(ys, self.strategy, ys_spec)
        return ys.reshape((-1,) + ys.shape[2:])

    def _make_stage_fn(self, training: bool):
        """stage_fn(params, x, t) interpreting the template chunk; params
        is the squeezed (per-stage) subtree handed over by gpipe."""
        pipe = self.pipe
        template = pipe.template

        tp_ax = pipe.tp_axis
        bf16_act = bool(getattr(self.config, "bf16_activations", False))

        def stage_fn(p, x, t):
            rng_base = p.get("__rng__")
            env = {pipe.template_entry_guid: x}
            ctx = EmitCtx(training=training, rngs={}, state={},
                          config=self.config)
            for j, layer in enumerate(template):
                if training and rng_base is not None and _needs_rng(layer):
                    key = jax.random.fold_in(
                        jax.random.fold_in(rng_base, t), j)
                    if tp_ax is not None and \
                            pipe.tp_roles.get(layer.name) == "attn":
                        # attention-prob dropout acts on tp-SHARDED
                        # heads: each shard must draw an independent
                        # mask. Role-less layers (residual dropout) see
                        # tp-REPLICATED activations and must keep the
                        # same key on every shard, or the replication
                        # invariant between psum points breaks.
                        key = jax.random.fold_in(
                            key, jax.lax.axis_index(tp_ax))
                    ctx.rngs[layer.name] = key
                op = get_op_def(layer.op_type)
                ins = [env[tt.guid] for tt in layer.inputs]
                w = p.get(pipe.param_name(layer), {})
                role = pipe.tp_roles.get(layer.name) \
                    if tp_ax is not None else None
                if role in ("attn", "row"):
                    # Megatron reduction point: emit with the bias held
                    # back (the local matmul yields a PARTIAL sum over
                    # the tp-split contraction dim), one psum over tp,
                    # then the bias applied exactly once
                    w = dict(w)
                    bias = w.pop("bo" if role == "attn" else "bias", None)
                    outs = op.emit(layer.params, ins, w, ctx, layer.name)
                    y = jax.lax.psum(outs[0], tp_ax)
                    if bias is not None:
                        y = (y + bias).astype(outs[0].dtype)
                    outs = [y]
                else:
                    outs = op.emit(layer.params, ins, w, ctx, layer.name)
                for o, tt in zip(outs, layer.outputs):
                    if bf16_act and hasattr(o, "dtype") \
                            and o.dtype == jnp.float32:
                        o = o.astype(jnp.bfloat16)
                    env[tt.guid] = o
            return env[pipe.template_exit_guid]

        return stage_fn

    def _pipe_apply(self, params, x, step, training: bool):
        """Run the pipeline region: microbatch x, shard_map the GPipe
        schedule over (dp, pp), return the region output (full batch)."""
        from jax.sharding import PartitionSpec as P
        from .parallel.pipeline import gpipe
        pipe = self.pipe
        S, M, v = pipe.n_stages, pipe.n_microbatches, pipe.n_chunks
        stacked = {pipe.param_name(l): params[pipe.param_name(l)]
                   for l in pipe.template
                   if pipe.param_name(l) in params}
        if training:
            base = jax.random.fold_in(jax.random.key(self.seed + 2), step)
            chunk_keys = jax.vmap(
                lambda i: jax.random.fold_in(base, i))(jnp.arange(S * v))
            if v > 1:
                chunk_keys = chunk_keys.reshape(v, S)
            stacked = dict(stacked, __rng__=chunk_keys)
        if x.shape[0] % M != 0:
            raise ValueError(f"batch {x.shape[0]} not divisible into "
                             f"{M} microbatches")
        from .parallel.pipeline_lowering import (region_entry_transition,
                                                 region_exit_transition)
        x = region_entry_transition(x, self.strategy,
                                    self._tensor_by_guid(pipe.entry_guid))
        xs = x.reshape((M, x.shape[0] // M) + x.shape[1:])
        engine = gpipe(self._make_stage_fn(training), pipe.pp_axis, M,
                       with_step_arg=True, n_chunks=v)
        pp_lead = (pipe.pp_axis,) if v == 1 else (None, pipe.pp_axis)

        def weight_spec(lname, wname, arr):
            dims = [None] * (arr.ndim - len(pp_lead))
            role = pipe.tp_roles.get(lname) \
                if pipe.tp_axis is not None else None
            if role is not None:
                d = _TP_WEIGHT_DIMS[role].get(wname)
                if d is not None:
                    dims[d] = pipe.tp_axis
            return P(*pp_lead, *dims)

        param_specs = {
            pipe.param_name(l): {
                wname: weight_spec(l.name, wname, arr)
                for wname, arr in stacked[pipe.param_name(l)].items()}
            for l in pipe.template if pipe.param_name(l) in stacked}
        if "__rng__" in stacked:
            param_specs["__rng__"] = P(*pp_lead)
        dp = pipe.dp_axes if pipe.dp_axes else None
        dp = dp[0] if dp is not None and len(dp) == 1 else dp
        xs_spec = P(None, dp, *([None] * (xs.ndim - 2)))
        fn = shard_map(engine, mesh=self.dmesh.mesh,
                           in_specs=(param_specs, xs_spec),
                           out_specs=xs_spec, check_vma=False)
        ys = fn(stacked, xs)
        ys = region_exit_transition(ys, self.strategy, xs_spec)
        return ys.reshape((-1,) + ys.shape[2:])

    # ------------------------------------------------------------------
    def _rngs_for_step(self, step, shard_index=None):
        base = jax.random.key(self.seed + 1)
        base = jax.random.fold_in(base, step)
        if shard_index is not None:
            # shard-local emission (quantized sync): each device draws
            # INDEPENDENT dropout masks for its batch shard — the
            # distributional match for the global path's one mask
            # partitioned across shards (a shared key would correlate
            # masks across devices)
            base = jax.random.fold_in(base, shard_index)
        rngs = {}
        for li, layer in enumerate(self.program.layers):
            if _needs_rng(layer):
                rngs[layer.name] = jax.random.fold_in(base, li)
        return rngs

    def _attach_kernel_ctx(self, ctx):
        """Thread the adopted kernel tier (kernels/registry.py) plus the
        seq-axis mesh context into an EmitCtx — ring attention lowers
        its shard_map against ctx.mesh/ctx.seq_axis."""
        if self._kernel_impls:
            ctx.kernel_impls = self._kernel_impls
        ctx.mesh = self.dmesh.mesh
        ctx.seq_axis = self.dmesh.seq_axis

    def _forward(self, params, state, batch, training: bool, step,
                 strategy="__use_own__", shard_index=None):
        """``strategy`` overrides the emission strategy — the quantized-
        sync path runs the forward INSIDE a shard_map on local batch
        shards and passes None (sharding constraints are meaningless in
        a manual shard region; weights arrive replicated).
        ``shard_index`` (a traced device index) marks that shard-local
        execution: absolute-batch-shape ops rescale (ctx.local_shape)
        and per-device rng streams decorrelate."""
        st = self.strategy if strategy == "__use_own__" else strategy
        rngs = self._rngs_for_step(step, shard_index) if training else {}
        ctx = EmitCtx(training=training, rngs=rngs, state=state,
                      config=self.config)
        self._attach_kernel_ctx(ctx)
        if shard_index is not None:
            ctx.local_shape = True
        capture: Dict[int, Any] = {}
        # checkpointing only matters under differentiation: eval/serving
        # forwards skip the remat path (prevent_cse barriers would only
        # inhibit XLA fusion there)
        if self.pipe is None and self._remat is not None and training:
            outs = self._emit_remat(params, batch, ctx, capture,
                                    strategy=st)
        elif self.pipe is None:
            outs = self.program.emit(params, batch, ctx, st, capture)
        else:
            env = self.program.init_env(batch)
            self.program.emit_layers(self._pre_layers, env, params, ctx,
                                     self.strategy, capture)
            if getattr(self.pipe, "counts", None) is not None:
                y = self._pipe_apply_ragged(params, env, batch, step,
                                            training)
                g = self.pipe.region_out_guid
            else:
                y = self._pipe_apply(params, env[self.pipe.entry_guid],
                                     step, training)
                g = self.pipe.exit_guid
            env[g] = y
            capture[g] = y
            self.program.emit_layers(self._post_layers, env, params, ctx,
                                     self.strategy, capture)
            outs = [env[t.guid] for t in self.program.output_tensors]
        new_state = dict(state)
        for k, v in ctx.new_state.items():
            new_state[k] = v
        return outs, new_state, ctx.aux_losses, capture

    def _emit_remat(self, params, batch, ctx, capture,
                    strategy="__use_own__"):
        """Forward with each repeated block wrapped in ``jax.checkpoint``:
        block-internal activations are recomputed in the backward pass
        instead of living in HBM for the whole step."""
        st = self.strategy if strategy == "__use_own__" else strategy
        start, unit, reps, entries, exits = self._remat
        layers = self.program.layers
        env = self.program.init_env(batch)
        self.program.emit_layers(layers[:start], env, params, ctx,
                                 st, capture)
        x = env[entries[0]]
        for b in range(reps):
            block = layers[start + b * unit:start + (b + 1) * unit]
            entry_g, exit_g = entries[b], exits[b]

            def block_fn(x_, p_, _block=block, _entry=entry_g,
                         _exit=exit_g):
                benv = {_entry: x_}
                bctx = EmitCtx(training=ctx.training, rngs=ctx.rngs,
                               state=ctx.state, config=self.config,
                               seq_length=ctx.seq_length)
                bctx.local_shape = getattr(ctx, "local_shape", False)
                self._attach_kernel_ctx(bctx)
                self.program.emit_layers(_block, benv, p_, bctx,
                                         st, None)
                if bctx.new_state or bctx.aux_losses:
                    raise RuntimeError(
                        "stateful/aux op inside a rematted block")
                return benv[_exit]

            bp = {l.name: params[l.name] for l in block
                  if l.name in params}
            x = jax.checkpoint(block_fn)(x, bp)
            env[exit_g] = x
            capture[exit_g] = x
        self.program.emit_layers(layers[start + reps * unit:], env,
                                 params, ctx, st, capture)
        return [env[t.guid] for t in self.program.output_tensors]

    def _loss_and_metrics(self, outs, capture, label, aux_losses):
        pred = outs[0]
        if self._logits_tensor is not None:
            logits = capture[self._logits_tensor.guid]
            loss = losses_mod.compute_loss(self.loss_type, logits, label,
                                           logits=True)
        else:
            loss = losses_mod.compute_loss(self.loss_type, pred, label)
        for al in aux_losses:
            loss = loss + al
        bm = metrics_mod.compute_batch_metrics(self.metrics, pred, label,
                                               self.loss_type)
        bm["loss"] = loss
        return loss, bm

    # ------------------------------------------------------------------
    def make_train_step(self):
        """Build the donated, jitted train step (fwd+bwd+update fused into
        one XLA program — the reference needed forward / zero_gradients /
        backward / update as separate task launch phases)."""
        if self._train_step is not None:
            return self._train_step

        accum = max(getattr(self.config, "gradient_accumulation_steps", 1),
                    1)
        if self.config.batch_size % accum != 0:
            raise ValueError(
                f"--gradient-accumulation-steps {accum} must divide "
                f"the batch size {self.config.batch_size}")

        def loss_fn(p, st, mb, sub_step):
            outs, new_state, aux, capture = self._forward(
                p, st, mb, True, sub_step)
            loss, bm = self._loss_and_metrics(outs, capture, mb["label"],
                                              aux)
            return loss, (new_state, bm)

        def step_fn(params, opt_state, state, step, batch):
            new_residual = None
            if self._qsync is not None:
                # explicit quantized gradient sync (ops/
                # quantized_collectives.py): one shard_map computes the
                # per-device local gradients and syncs every tensor on
                # the plan's wire dtypes, error-feedback residuals
                # riding the optimizer-state tree under a reserved slot
                # (stripped before the update below)
                from .ops import quantized_collectives as qsync_mod
                residual, opt_state = qsync_mod.strip_residual(opt_state)
                grads, bm, new_residual = qsync_mod.sharded_grads(
                    self, params, state, batch, step, residual)
                if residual is None and not new_residual:
                    new_residual = None   # keep the opt-state structure
                new_state = state   # stateful ops are qsync-ineligible
            elif accum <= 1:
                grads, (new_state, bm) = jax.grad(
                    loss_fn, has_aux=True)(params, state, batch, step)
            else:
                # gradient accumulation: scan over A micro-batches,
                # summing grads (mean losses => mean of micro grads ==
                # full-batch grad); one optimizer update per step.
                # Activations live one micro-batch at a time — an HBM
                # lever composing with --remat.
                def micro(carry, xs):
                    g_acc, st = carry
                    mb, i = xs
                    g, (st2, bm_i) = jax.grad(loss_fn, has_aux=True)(
                        params, st, mb, step * accum + i)
                    g_acc = jax.tree.map(jnp.add, g_acc, g)
                    return (g_acc, st2), bm_i

                def to_micro(v):
                    # the RUNTIME batch (fit(batch_size=...) may differ
                    # from config.batch_size) must also divide
                    if v.shape[0] % accum != 0:
                        raise ValueError(
                            f"batch dim {v.shape[0]} not divisible "
                            f"into {accum} accumulation micro-batches")
                    return v.reshape((accum, v.shape[0] // accum)
                                     + v.shape[1:])

                mbs = jax.tree.map(to_micro, batch)
                g0 = jax.tree.map(jnp.zeros_like, params)
                (g_sum, new_state), bms = jax.lax.scan(
                    micro, (g0, state), (mbs, jnp.arange(accum)))
                grads = jax.tree.map(lambda g: g / accum, g_sum)
                # mean-valued metrics average across micro-batches;
                # count-valued ones must SUM; sqrt-of-mean ones (RMSE)
                # average the squares and sqrt once (ownership of the
                # distinction lives with the metrics module)
                def reduce_metric(k, v):
                    if k in metrics_mod.COUNT_KEYS:
                        return jnp.sum(v, axis=0)
                    if k in metrics_mod.RMS_KEYS:
                        return jnp.sqrt(jnp.mean(v * v, axis=0))
                    return jnp.mean(v, axis=0)

                bm = {k: reduce_metric(k, v) for k, v in bms.items()}
            # fused NaN screen for the deferred-metrics loop
            # (runtime/metrics_buffer.py): the host checks this flag at
            # flush points instead of fetching the loss every step.
            # LOSS-only on purpose — the old per-step screen checked
            # only the loss, and an auxiliary metric overflowing float32
            # on its own must not trigger a supervisor rollback
            bm["all_finite"] = jnp.all(jnp.isfinite(bm["loss"]))
            if self._overlap_schedule is not None:
                # overlap path (runtime/overlap.py): per-bucket updates
                # chained in backward-completion order — identity math
                # (bit-exact with the serial branch below), but the
                # barrier chain hands XLA dependency cuts so bucket k's
                # grad sync + update (+ ZeRO gather) interleave with
                # the backward of buckets k+1..
                from .runtime import overlap as overlap_mod
                new_params, new_opt_state = overlap_mod.overlapped_update(
                    self.optimizer, params, grads, opt_state, step + 1,
                    self._overlap_schedule, self.opt_state_constraints)
            elif self._kernel_impls.get("opt_update") == "fused":
                # searched kernel tier: one-HBM-pass Pallas Adam update
                # (kernels/opt_update.py) — bit-equal math to
                # AdamOptimizer.update, adopted only when the registry
                # predicate held (TPU backend, adam) at plan time
                from .runtime.optimizers import fused_adam_tree_update
                new_params, new_opt_state = fused_adam_tree_update(
                    self.optimizer, params, grads, opt_state, step + 1)
                if self.opt_state_constraints is not None:
                    new_opt_state = jax.tree.map(
                        jax.lax.with_sharding_constraint,
                        new_opt_state, self.opt_state_constraints)
            else:
                new_params, new_opt_state = self.optimizer.update(
                    params, grads, opt_state, step + 1)
                if self.opt_state_constraints is not None:
                    # ZeRO-1 pin: keep the updated moments on their
                    # sharded placement (GSPMD lowers the update to
                    # reduce-scatter + sharded math instead of
                    # replicating the state back)
                    new_opt_state = jax.tree.map(
                        jax.lax.with_sharding_constraint,
                        new_opt_state, self.opt_state_constraints)
            if new_residual is not None:
                from .ops.quantized_collectives import RESIDUAL_SLOT
                new_opt_state = dict(new_opt_state)
                new_opt_state[RESIDUAL_SLOT] = new_residual
            return new_params, new_opt_state, new_state, bm

        self._train_step = _instrument_step(
            jax.jit(step_fn, donate_argnums=(0, 1, 2)), "train")
        return self._train_step

    def make_eval_step(self):
        if self._eval_step is not None:
            return self._eval_step

        def step_fn(params, state, batch):
            outs, _, aux, capture = self._forward(
                params, state, batch, False, jnp.int32(0))
            loss, bm = self._loss_and_metrics(outs, capture, batch["label"],
                                              aux)
            return outs[0], bm

        self._eval_step = _instrument_step(jax.jit(step_fn), "eval")
        return self._eval_step

    def make_forward(self):
        """Inference-only forward (no label), jitted (cached on self)."""
        if getattr(self, "_forward_fn", None) is not None:
            return self._forward_fn

        def fwd(params, state, batch):
            outs, _, _, _ = self._forward(params, state, batch, False,
                                          jnp.int32(0))
            return outs[0] if len(outs) == 1 else outs

        self._forward_fn = _instrument_step(jax.jit(fwd), "forward")
        return self._forward_fn

    # ------------------------------------------------------------------
    # generation support (serving; the reference has no generate path)
    # ------------------------------------------------------------------
    def scored_forward(self, params, state, batch):
        """Forward returning log-domain next-token scores (B, L, V):
        the pre-softmax logits when the graph ends in Softmax (numerically
        exact), else log of the clipped output probabilities. NOT jitted —
        call inside a jitted decode loop."""
        outs, _, _, capture = self._forward(params, state, batch, False,
                                            jnp.int32(0))
        if self._logits_tensor is not None \
                and self._logits_tensor.guid in capture:
            return capture[self._logits_tensor.guid]
        return jnp.log(jnp.clip(outs[0], 1e-20))

    def kv_prefill(self, params, state, batch, prefill_len=None):
        """Full-sequence forward that also returns every causal
        attention layer's K/V buffers (the decode cache seed) plus the
        scores. ``prefill_len`` (traced) marks how many leading
        positions are real prompt — sliding-window layers use it to
        seed their O(window) ring-buffer cache. NOT jitted."""
        ctx = EmitCtx(training=False, rngs={}, state=state,
                      config=self.config)
        self._attach_kernel_ctx(ctx)
        ctx.kv_mode = "prefill"
        ctx.kv_prefill_len = prefill_len
        capture: Dict[int, Any] = {}
        outs = self.program.emit(params, batch, ctx, self.strategy,
                                 capture)
        if not ctx.new_kv:
            raise ValueError("graph has no multihead-attention layers to "
                             "cache (KV decode unsupported)")
        return outs, ctx.new_kv

    def kv_decode_step(self, params, state, batch, cache, index):
        """Single-token forward (inputs (B, 1)) against the KV cache at
        query position ``index``. Returns (scores_row (B, V), new_cache).
        NOT jitted — called inside the generate scan."""
        ctx = EmitCtx(training=False, rngs={}, state=state,
                      config=self.config)
        self._attach_kernel_ctx(ctx)
        ctx.kv_mode = "decode"
        ctx.kv_cache = cache
        ctx.kv_index = index
        capture: Dict[int, Any] = {}
        outs = self.program.emit(params, batch, ctx, self.strategy,
                                 capture)
        if self._logits_tensor is not None \
                and self._logits_tensor.guid in capture:
            scores = capture[self._logits_tensor.guid]
        else:
            scores = jnp.log(jnp.clip(outs[0], 1e-20))
        # cache layers that did not run in decode keep their buffers
        new_cache = dict(cache)
        new_cache.update(ctx.new_kv)
        return scores[:, 0, :], new_cache
